"""The glint driver: file discovery, pass execution, suppression and
baseline application, reporting, CLI.

Default scan roots are the data-plane surfaces the invariants govern:
the package, the bench drivers, ``bench.py``, and ``examples/``.
Tests (``tests/``) are deliberately out of scope — they exercise
ad-hoc event kinds and throwaway RNG on private objects by design.

Exit code contract: 0 when every finding is inline-suppressed or
baselined, 1 otherwise, 2 on usage errors.  This is the single entry
point the bench/dev docs reference::

    python -m tools.glint --baseline tools/glint/baseline.json
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .context import FileContext
from .findings import Finding
from .registry import all_passes

REPO = Path(__file__).resolve().parent.parent.parent
DEFAULT_ROOTS = ('graphlearn_tpu', 'benchmarks', 'bench.py', 'examples')
DEFAULT_BASELINE = Path(__file__).resolve().parent / 'baseline.json'


@dataclasses.dataclass
class Run:
  """Run-level configuration handed to every pass (``begin``/
  ``finish``).  Tests override the resource paths to point passes at
  fixture registries instead of the live repo."""

  repo: Path = REPO
  #: knob table the env-knob-drift pass checks against
  readme_path: Path = REPO / 'benchmarks' / 'README.md'
  #: telemetry schema registry the event-schema pass checks against
  schema_path: Path = REPO / 'graphlearn_tpu' / 'telemetry' / 'schema.py'
  #: repo-relative prefix of "the package" for package-only passes
  pkg_prefix: str = 'graphlearn_tpu'


def discover(paths: Sequence, repo: Path) -> List[Path]:
  files: List[Path] = []
  for p in paths:
    p = Path(p)
    if not p.is_absolute():
      p = repo / p
    if p.is_file() and p.suffix == '.py':
      files.append(p)
    elif p.is_dir():
      files.extend(sorted(p.rglob('*.py')))
  return files


def run_glint(paths: Optional[Sequence] = None,
              rules: Optional[Sequence[str]] = None,
              run: Optional[Run] = None,
              baseline: Optional[Path] = None) -> List[Finding]:
  """Run the selected passes over ``paths`` (default roots when None)
  and return EVERY finding — suppressed and baselined ones included,
  flagged as such (callers filter on ``Finding.live``)."""
  run = run or Run()
  table = all_passes()
  if rules is not None:
    unknown = set(rules) - set(table)
    if unknown:
      raise ValueError(f'unknown glint rule(s): {sorted(unknown)} — '
                       f'registered: {sorted(table)}')
    table = {k: v for k, v in table.items() if k in rules}
  files = discover(paths if paths is not None else DEFAULT_ROOTS, run.repo)

  contexts: List[FileContext] = []
  findings: List[Finding] = []
  for f in files:
    ctx = FileContext.from_path(f, run.repo)
    if ctx.parse_error is not None:
      findings.append(Finding(
          rule='parse', path=ctx.rel, line=ctx.parse_error.lineno or 0,
          message=f'syntax error: {ctx.parse_error.msg}'))
      continue
    contexts.append(ctx)

  passes = [cls() for cls in table.values()]
  for p in passes:
    p.begin(run)
  for ctx in contexts:
    for p in passes:
      findings.extend(p.check_file(ctx))
  for p in passes:
    findings.extend(p.finish(run))

  by_rel: Dict[str, FileContext] = {c.rel: c for c in contexts}
  for f in findings:
    ctx = by_rel.get(f.path)
    if ctx is None:
      continue
    if not f.snippet:
      f.snippet = ctx.line_text(f.line)
    if ctx.rule_disabled(f.rule, f.line):
      f.suppressed = True
  if baseline is not None:
    apply_baseline(findings, load_baseline(baseline))
  findings.sort(key=lambda f: (f.path, f.line, f.rule))
  return findings


def check_source(source: str, rule: str, rel: str = 'fixture.py',
                 run: Optional[Run] = None) -> List[Finding]:
  """Test helper: run ONE pass over in-memory source.  Repo-level
  passes still honor ``run`` resource overrides."""
  run = run or Run()
  cls = all_passes()[rule]
  ctx = FileContext(source, rel)
  if ctx.parse_error is not None:
    raise ctx.parse_error
  p = cls()
  p.begin(run)
  findings = list(p.check_file(ctx))
  findings.extend(p.finish(run))
  for f in findings:
    if not f.snippet and f.path == rel:
      f.snippet = ctx.line_text(f.line)
    if f.path == rel and ctx.rule_disabled(f.rule, f.line):
      f.suppressed = True
  return findings


# -- baseline ----------------------------------------------------------------
def load_baseline(path: Path) -> List[str]:
  if not Path(path).exists():
    return []
  data = json.loads(Path(path).read_text())
  return list(data.get('findings', []))


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
  """Grandfather every unsuppressed finding.  Sorted for stable
  diffs; the workflow is: shrink this file over time, never grow it
  silently (new code must come in clean)."""
  fps = sorted(f.fingerprint for f in findings if not f.suppressed)
  Path(path).write_text(json.dumps(
      {'version': 1, 'findings': fps}, indent=2) + '\n')


def apply_baseline(findings: Sequence[Finding], fps: Sequence[str]) -> None:
  """Multiset match: each baseline entry absolves at most one
  finding, so a second instance of a grandfathered pattern still
  fails the run."""
  pool: Dict[str, int] = {}
  for fp in fps:
    pool[fp] = pool.get(fp, 0) + 1
  for f in findings:
    if f.suppressed:
      continue
    n = pool.get(f.fingerprint, 0)
    if n > 0:
      pool[f.fingerprint] = n - 1
      f.baselined = True


# -- CLI ---------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
  ap = argparse.ArgumentParser(
      prog='python -m tools.glint',
      description='repo-native static analysis for data-plane '
                  'invariants (host-sync, RNG discipline, guarded-by '
                  'locks, knob/schema drift)')
  ap.add_argument('paths', nargs='*',
                  help=f'files/dirs to scan (default: {DEFAULT_ROOTS})')
  ap.add_argument('--rules', help='comma-separated subset of passes')
  ap.add_argument('--baseline', type=Path, default=DEFAULT_BASELINE,
                  help='baseline JSON (default: tools/glint/baseline.json)')
  ap.add_argument('--no-baseline', action='store_true',
                  help='ignore the baseline (report grandfathered '
                       'findings as live)')
  ap.add_argument('--write-baseline', action='store_true',
                  help='rewrite the baseline from the current findings '
                       'and exit 0')
  ap.add_argument('--list-passes', action='store_true')
  ap.add_argument('-q', '--quiet', action='store_true',
                  help='summary line only')
  args = ap.parse_args(argv)

  if args.list_passes:
    for name, cls in sorted(all_passes().items()):
      print(f'{name:20s} {cls.description}')
    return 0

  rules = ([r.strip() for r in args.rules.split(',') if r.strip()]
           if args.rules else None)
  if args.write_baseline and (rules or args.paths):
    # a filtered run sees a SUBSET of findings; writing it out would
    # silently drop every grandfathered entry outside the filter
    print('glint: --write-baseline rewrites the whole baseline file — '
          'run it without --rules or explicit paths', file=sys.stderr)
    return 2
  try:
    findings = run_glint(
        paths=args.paths or None, rules=rules,
        baseline=None if (args.no_baseline or args.write_baseline)
        else args.baseline)
  except ValueError as e:
    print(f'glint: {e}', file=sys.stderr)
    return 2

  if args.write_baseline:
    write_baseline(args.baseline, findings)
    n = sum(1 for f in findings if not f.suppressed)
    print(f'glint: wrote {n} finding(s) to {args.baseline}')
    return 0

  live = [f for f in findings if f.live]
  if not args.quiet:
    for f in findings:
      print(f.render())
  n_sup = sum(1 for f in findings if f.suppressed)
  n_base = sum(1 for f in findings if f.baselined)
  print(f'glint: {len(findings)} finding(s) — {len(live)} live, '
        f'{n_sup} suppressed, {n_base} baselined '
        f'({len(all_passes() if rules is None else rules)} pass(es))')
  return 1 if live else 0


if __name__ == '__main__':              # pragma: no cover
  sys.exit(main())
