"""Importing this package registers every built-in pass.

To add a pass: create a module here with a ``@register``-decorated
``GlintPass`` subclass and import it below.  Give the rule a
kebab-case name — it becomes the suppression key
(``# glint: disable=<name>``), the ``--rules`` selector, and the
baseline fingerprint prefix.  Add a positive + negative fixture to
``tests/test_glint.py`` and a row to the rule table in
``benchmarks/README.md``.
"""
from . import (env_knobs, event_schema, guarded_by,  # noqa: F401
               host_sync, metric_label, metric_name, monotonic, rng)
