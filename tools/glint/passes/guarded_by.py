"""guarded-by: annotated shared attributes are only touched under
their lock.

Thread-shared state in this codebase is documented by a trailing
comment at the defining assignment::

    self.in_flight = 0          # guarded-by: self._lock

(or a standalone ``# guarded-by: self._lock`` comment on the line
directly above).  This pass enforces the annotation: every other
``self.<attr>`` load/store in the class must sit lexically inside
``with <lock>:`` — the serving frontend's executor counters, the
producer's supervision ledger and the RPC server's `_ReplayCache`
all carry the contract (an unguarded touch is a data race that only
fires under load, the worst kind of serving bug).

Escape hatches, both conventions the code already uses:
  * methods named ``*_locked`` are called with the lock held;
  * a method containing ``# glint: holds=<lock>`` declares the same
    for names the suffix convention doesn't fit.

Scope: accesses through ``self`` within the annotating class —
cross-object accesses (``other._attr``) are out of reach of a
lexical checker and stay review territory.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..context import comment_annotations
from ..findings import Finding
from ..registry import GlintPass, register

_GUARD_RE = re.compile(r'#\s*guarded-by:\s*([^\s#]+)')
_HOLDS_RE = re.compile(r'#\s*glint:\s*holds=([^\s#]+)')
_ATTR_RE = re.compile(r'self\.(\w+)\s*[:=]')


def _norm(expr: str) -> str:
  return expr.replace(' ', '')


@register
class GuardedByPass(GlintPass):
  name = 'guarded-by'
  description = ('attributes annotated "# guarded-by: <lock>" are '
                 'only accessed under "with <lock>:" (or in *_locked '
                 '/ "# glint: holds=<lock>" methods)')

  def check_file(self, ctx):
    # line -> lock for every guarded-by comment (trailing annotates
    # its own line, standalone the next — the shared convention in
    # context.comment_annotations)
    guard_lines: Dict[int, str] = {
        target: _norm(matches[-1].group(1))
        for target, matches in comment_annotations(
            ctx.lines, _GUARD_RE).items()}
    if not guard_lines:
      return

    for cls in ast.walk(ctx.tree):
      if isinstance(cls, ast.ClassDef):
        yield from self._check_class(ctx, cls, guard_lines)

  def _check_class(self, ctx, cls: ast.ClassDef,
                   guard_lines: Dict[int, str]):
    # guarded attrs declared in THIS class: the annotated line must
    # contain a `self.<attr> =` / `self.<attr>:` assignment
    guarded: Dict[str, str] = {}
    decl_methods: Dict[str, ast.AST] = {}
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for meth in methods:
      for node in ast.walk(meth):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
            and node.lineno in guard_lines:
          m = _ATTR_RE.search(ctx.lines[node.lineno - 1])
          if m:
            guarded[m.group(1)] = guard_lines[node.lineno]
            decl_methods[m.group(1)] = meth
    if not guarded:
      return

    for meth in methods:
      span = (meth.lineno, meth.end_lineno or meth.lineno)
      holds = self._holds(ctx, span)
      exempt_all = meth.name.endswith('_locked') or meth.name == '__init__'
      for node in ast.walk(meth):
        if not (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == 'self'
                and node.attr in guarded):
          continue
        lock = guarded[node.attr]
        if exempt_all or meth is decl_methods.get(node.attr):
          continue
        if lock in holds:
          continue
        if self._under_lock(ctx, node, lock):
          continue
        yield Finding(
            rule=self.name, path=ctx.rel, line=node.lineno,
            message=f'self.{node.attr} is guarded-by {lock} but '
                    f'accessed in {cls.name}.{meth.name} outside '
                    f'"with {lock}:" — data race; take the lock, or '
                    f'mark the method *_locked / "# glint: '
                    f'holds={lock}" if callers hold it')

  @staticmethod
  def _holds(ctx, span: Tuple[int, int]) -> List[str]:
    out = []
    for i in range(span[0], span[1] + 1):
      m = _HOLDS_RE.search(ctx.lines[i - 1] if i <= len(ctx.lines) else '')
      if m:
        out.append(_norm(m.group(1)))
    return out

  @staticmethod
  def _under_lock(ctx, node: ast.AST, lock: str) -> bool:
    for anc in ctx.ancestors(node):
      if isinstance(anc, (ast.With, ast.AsyncWith)):
        for item in anc.items:
          if _norm(ast.unparse(item.context_expr)) == lock:
            return True
      if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
        # don't credit a `with` in an OUTER function to a nested def
        # that may run later on another thread
        return False
    return False
