"""metric-name: live-registry metrics use declared ``snake.dot`` names.

The metric twin of the event-schema pass (ISSUE 12): the live ops
plane (`telemetry/live.py`) serves every registered metric on the
``/metrics`` scrape, and dashboards/alerts key off the names — an
undeclared metric is a panel nobody can discover, a stale declaration
is a panel that can never fill, and a name outside the ``snake.dot``
convention breaks the dotted-vocabulary merge with the offline
artifact.

Call sites are any ``counter('<name>', ...)`` / ``gauge('<name>',
...)`` / ``histogram('<name>', ...)`` call (terminal callee name)
whose first argument is a string literal, scoped to the package —
the registration surface of `LiveRegistry` however the registry
object is spelled.  Checks, all against the ``METRIC_NAMES`` dict
literal in ``telemetry/schema.py`` (parsed, not imported — jax-free):

  * every registered name is declared, matches
    ``snake.dot`` (lowercase segments joined by dots), and is
    registered with the kind its declaration states (the table value
    is ``'<type>: <doc>'``);
  * every declared name still has a registration call site (no rot);
  * every declaration documents type + meaning (>10 chars after the
    type prefix).

Dynamic parts (per-bucket capacities, shed reasons, scopes) belong in
``labels={...}``, never in the name — that is what keeps the
vocabulary enumerable.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..context import terminal_name as _callee_name
from ..findings import Finding
from ..registry import GlintPass, register
from .event_schema import registry_tables

#: registration callee -> declared-type prefix it must match
_REGISTRARS = ('counter', 'gauge', 'histogram')

_NAME_RE = re.compile(r'^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$')


@register
class MetricNamePass(GlintPass):
  name = 'metric-name'
  description = ('every live-registry counter/gauge/histogram uses a '
                 'declared snake.dot name from telemetry/schema.py::'
                 'METRIC_NAMES, with the declared type')

  def begin(self, run):
    self._schema = run.schema_path
    self._pkg = run.pkg_prefix.rstrip('/') + '/'
    #: name -> [(kind, rel, line), ...]
    self._sites: Dict[str, List[Tuple[str, str, int]]] = {}

  def check_file(self, ctx):
    if not ctx.rel.startswith(self._pkg):
      return ()
    for node in ast.walk(ctx.tree):
      if (isinstance(node, ast.Call)
          and _callee_name(node.func) in _REGISTRARS and node.args
          and isinstance(node.args[0], ast.Constant)
          and isinstance(node.args[0].value, str)):
        self._sites.setdefault(node.args[0].value, []).append(
            (_callee_name(node.func), ctx.rel, node.lineno))
    return ()

  def finish(self, run):
    try:
      table = registry_tables(
          self._schema, table_names=('METRIC_NAMES',)
      ).get('METRIC_NAMES', {})
    except (OSError, SyntaxError) as e:
      yield Finding(
          rule=self.name, path=str(self._schema), line=0,
          message=f'schema registry unreadable ({e}) — nothing to '
                  'enforce against')
      return
    schema_rel = self._schema_rel(run)
    for name, sites in sorted(self._sites.items()):
      kind, rel, line = sites[0]
      if not _NAME_RE.match(name):
        yield Finding(
            rule=self.name, path=rel, line=line,
            message=f'{kind}({name!r}) is not a snake.dot metric '
                    'name (lowercase segments joined by dots; '
                    'dynamic parts go in labels={...})')
      if name not in table:
        yield Finding(
            rule=self.name, path=rel, line=line,
            message=f'{kind}({name!r}) is not declared in '
                    'telemetry/schema.py::METRIC_NAMES — add it '
                    "with a '<type>: <doc>' value so the scrape "
                    'vocabulary stays enumerable')
        continue
      doc = table[name][1]
      declared = (doc.split(':', 1)[0].strip()
                  if isinstance(doc, str) and ':' in doc else None)
      for k, r, ln in sites:
        if declared is not None and k != declared:
          yield Finding(
              rule=self.name, path=r, line=ln,
              message=f'{k}({name!r}) registered as {k!r} but '
                      f'METRIC_NAMES declares it {declared!r}')
    for name, (line, doc) in sorted(table.items()):
      if name not in self._sites:
        yield Finding(
            rule=self.name, path=schema_rel, line=line,
            message=f'METRIC_NAMES[{name!r}] has no remaining '
                    'registration call site — remove the stale '
                    'declaration')
      body = (doc.split(':', 1)[1] if isinstance(doc, str)
              and ':' in doc else '')
      if not (isinstance(doc, str)
              and doc.split(':', 1)[0].strip() in _REGISTRARS
              and len(body.strip()) > 10):
        yield Finding(
            rule=self.name, path=schema_rel, line=line,
            message=f'METRIC_NAMES[{name!r}] must be '
                    "'<counter|gauge|histogram>: <doc>' (>10 char "
                    'doc) — the value IS the scrape contract')

  def _schema_rel(self, run) -> str:
    try:
      return self._schema.resolve().relative_to(
          run.repo.resolve()).as_posix()
    except ValueError:
      return str(self._schema)
