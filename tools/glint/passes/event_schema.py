"""event-schema: telemetry kinds/spans and the registry stay in sync.

Migrated from the AST scan that lived in ``tests/test_event_schema.py``
(the test is now a one-line driver invocation, so every new subsystem
gets schema checking for free).  Exporters, the report CLI and
external dashboards key off event ``kind`` / span ``name`` strings;
an unregistered kind is a consumer that silently sees nothing, and a
stale registry entry is a dashboard panel that can never fill.

Four sub-checks, all against the dict literals in
``telemetry/schema.py`` (parsed, not imported — the pass stays
jax-free):

  * every ``recorder.emit('<kind>', ...)`` call site in the package
    is registered in ``EVENT_KINDS``;
  * every registered kind still has a call site (no rot);
  * the same pair for ``span('<name>', ...)`` vs ``SPAN_NAMES``;
  * every registry value documents emitter + fields (>10 chars).

Scope is the package (``pkg_prefix``): tests exercise ad-hoc kinds on
private recorders by design, and bench drivers consume rather than
emit.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Tuple

from ..context import terminal_name as _callee_name
from ..findings import Finding
from ..registry import GlintPass, register


def registry_tables(schema_path: Path,
                    table_names=('EVENT_KINDS', 'SPAN_NAMES')
                    ) -> Dict[str, Dict[str, Tuple[int, object]]]:
  """``{'EVENT_KINDS': {kind: (line, doc)}, 'SPAN_NAMES': ...}``
  parsed from the schema module's dict literals (``table_names``
  selects which — the metric-name pass reuses this for
  ``METRIC_NAMES``)."""
  tree = ast.parse(Path(schema_path).read_text())
  out: Dict[str, Dict[str, Tuple[int, object]]] = {}
  for node in tree.body:
    targets = []
    if isinstance(node, ast.Assign):
      targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
      value = node.value
    elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
      targets = [node.target.id]
      value = node.value
    else:
      continue
    for name in targets:
      if name in table_names \
          and isinstance(value, ast.Dict):
        table: Dict[str, Tuple[int, object]] = {}
        for k, v in zip(value.keys, value.values):
          if isinstance(k, ast.Constant) and isinstance(k.value, str):
            doc = v.value if isinstance(v, ast.Constant) else None
            table[k.value] = (k.lineno, doc)
        out[name] = table
  return out


@register
class EventSchemaPass(GlintPass):
  name = 'event-schema'
  description = ('every recorder.emit(kind)/span(name) call site in '
                 'the package is registered in telemetry/schema.py, '
                 'and the registry holds no stale entries')

  def begin(self, run):
    self._schema = run.schema_path
    self._pkg = run.pkg_prefix.rstrip('/') + '/'
    #: callee -> {first_string_arg: [(rel, line), ...]}
    self._sites: Dict[str, Dict[str, List[Tuple[str, int]]]] = {
        'emit': {}, 'span': {}}

  def check_file(self, ctx):
    if not ctx.rel.startswith(self._pkg):
      return ()
    for node in ast.walk(ctx.tree):
      if (isinstance(node, ast.Call)
          and _callee_name(node.func) in self._sites and node.args
          and isinstance(node.args[0], ast.Constant)
          and isinstance(node.args[0].value, str)):
        self._sites[_callee_name(node.func)].setdefault(
            node.args[0].value, []).append((ctx.rel, node.lineno))
    return ()

  def finish(self, run):
    try:
      tables = registry_tables(self._schema)
    except (OSError, SyntaxError) as e:
      yield Finding(
          rule=self.name, path=str(self._schema), line=0,
          message=f'schema registry unreadable ({e}) — nothing to '
                  'enforce against')
      return
    schema_rel = self._schema_rel(run)
    for callee, table_name in (('emit', 'EVENT_KINDS'),
                               ('span', 'SPAN_NAMES')):
      table = tables.get(table_name, {})
      sites = self._sites[callee]
      for kind, where in sorted(sites.items()):
        if kind not in table:
          rel, line = where[0]
          yield Finding(
              rule=self.name, path=rel, line=line,
              message=f'{callee}({kind!r}) is not registered in '
                      f'telemetry/schema.py::{table_name} — add it '
                      'with a field summary so exporters and '
                      'dashboards do not go stale')
      for kind, (line, doc) in sorted(table.items()):
        if kind not in sites:
          yield Finding(
              rule=self.name, path=schema_rel, line=line,
              message=f'{table_name}[{kind!r}] has no remaining '
                      f'{callee}() call site — remove the stale '
                      'registry entry')
        if not (isinstance(doc, str) and len(doc) > 10):
          yield Finding(
              rule=self.name, path=schema_rel, line=line,
              message=f'{table_name}[{kind!r}] must document emitter '
                      '+ fields (a >10 char string) — the value IS '
                      'the consumer contract')

  def _schema_rel(self, run) -> str:
    try:
      return self._schema.resolve().relative_to(
          run.repo.resolve()).as_posix()
    except ValueError:
      return str(self._schema)
