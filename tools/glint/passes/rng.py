"""rng-discipline: all randomness is seeded and scoped.

Two hazards, both fatal to the byte-identity contracts (PR 6 resume,
PR 9 per-seed serving determinism, PR 10 GNS exactness):

* **Module-level numpy RNG** — ``np.random.randint(...)`` & friends
  draw from the shared global ``RandomState``; any library call that
  touches it perturbs every other consumer's stream, and a restart
  replays nothing.  Library code constructs a ``Generator``
  (``np.random.default_rng(seed)``) and threads it.

* **Constant ``PRNGKey`` in a loop** — ``jax.random.PRNGKey(0)`` /
  ``jax.random.key(0)`` inside a ``for``/``while`` body re-derives
  the SAME key every iteration, so every "random" draw repeats.
  Loops must ``fold_in`` / ``split`` from a key created outside.
"""
from __future__ import annotations

import ast

from ..findings import Finding
from ..registry import GlintPass, register

#: the sanctioned ``np.random.*`` surface: constructors of seeded,
#: threadable state (classes are CamelCase; ``default_rng`` is the
#: one lowercase entry point)
_ALLOWED_NP_RANDOM = {'default_rng'}

_KEY_CTORS = {'jax.random.PRNGKey', 'jax.random.key'}


@register
class RngDisciplinePass(GlintPass):
  name = 'rng-discipline'
  description = ('no module-level np.random.* (Generator-less global '
                 'state) and no constant PRNGKey/key construction '
                 'inside loop bodies (fold_in/split instead)')

  def check_file(self, ctx):
    for node in ast.walk(ctx.tree):
      if not isinstance(node, ast.Call):
        continue
      qn = ctx.qualname(node.func)
      if qn.startswith('numpy.random.'):
        attr = qn.rsplit('.', 1)[1]
        if attr[:1].islower() and attr not in _ALLOWED_NP_RANDOM:
          yield Finding(
              rule=self.name, path=ctx.rel, line=node.lineno,
              message=f'np.random.{attr}() draws from the shared '
                      'module-level RandomState — unseeded, '
                      'cross-contaminating, unresumable; construct '
                      'np.random.default_rng(seed) and thread it')
      elif qn in _KEY_CTORS and node.args \
          and isinstance(node.args[0], ast.Constant):
        loop = ctx.enclosing(node, (ast.For, ast.While, ast.AsyncFor))
        if loop is not None:
          yield Finding(
              rule=self.name, path=ctx.rel, line=node.lineno,
              message=f'{qn}({node.args[0].value!r}) inside a loop '
                      'body re-derives the SAME key every iteration '
                      '— create the key outside and fold_in/split '
                      'the loop index')
