"""metric-label-cardinality: metric labels draw KEYS from a closed set.

The label twin of the metric-name pass (ISSUE 16): a metric NAME
outside the vocabulary is one undiscoverable panel, but a label KEY
outside the vocabulary is worse — every distinct value mints a new
time series forever, so an unbounded label is a scrape-cardinality
leak that grows until the fleet scraper (`telemetry/federation.py`)
chokes on it.  The ``METRIC_LABELS`` dict literal in
``telemetry/schema.py`` is the closed key set, and each entry
documents why the VALUE domain is bounded.

Call sites are the same registration surface the metric-name pass
scans — ``counter(...)`` / ``gauge(...)`` / ``histogram(...)``
(terminal callee name) inside the package — restricted to those that
pass labels at all.  The labels value resolves statically:

  * ``labels={...}`` keyword or a positional dict literal (the
    `SloTracker` helper convention) — checked directly;
  * a bare name bound by a UNIQUE dict-literal assignment somewhere
    in the same file (the `cold_cache` shared-labels convention) —
    checked through the assignment;
  * a bare name that is a parameter of an enclosing function (a
    forwarding helper like `SloTracker._register_gauges.gauge`) —
    skipped: the helper's own call sites are scanned instead;
  * ``None`` (no labels) — skipped.

Anything else is flagged as statically unresolvable — pass a dict
literal.  Every resolved key must be a string constant declared in
``METRIC_LABELS``; `finish` also flags stale declarations (no
remaining use site) and entries whose doc does not state the bounded
value domain (>10 chars).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..context import terminal_name as _callee_name
from ..findings import Finding
from ..registry import GlintPass, register
from .event_schema import registry_tables

_REGISTRARS = ('counter', 'gauge', 'histogram')

#: per-request identifiers may NEVER be label keys — every request
#: would mint a fresh time series (the worst possible cardinality
#: leak).  Traces attach to metrics via exemplars (ISSUE 17), which
#: annotate a bucket sample without widening the series space.
_FORBIDDEN_KEYS = frozenset({'trace_id', 'span_id'})


def _labels_value(call: ast.Call) -> Optional[ast.AST]:
  """The AST node carrying the call's labels, or None when the call
  passes none: the ``labels=`` keyword wins; otherwise the first
  positional arg past the name that is a dict literal or an explicit
  ``None`` (the positional-labels helper convention)."""
  for kw in call.keywords:
    if kw.arg == 'labels':
      return kw.value
  for arg in call.args[1:]:
    if isinstance(arg, ast.Dict):
      return arg
    if isinstance(arg, ast.Constant) and arg.value is None:
      return arg
  return None


def _is_param(ctx, call: ast.Call, name: str) -> bool:
  """True when ``name`` is a parameter of a function enclosing the
  call — a forwarding helper whose OWN call sites carry the dict."""
  fn = ctx.enclosing_function(call)
  while fn is not None:
    a = fn.args
    params = [p.arg for p in
              (a.posonlyargs + a.args + a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
      if extra is not None:
        params.append(extra.arg)
    if name in params:
      return True
    fn = ctx.enclosing_function(fn)
  return False


def _unique_dict_assign(ctx, name: str) -> Optional[ast.Dict]:
  """The dict literal a bare labels name resolves to, when the file
  binds it by EXACTLY one simple ``<name> = {...}`` assignment."""
  hits: List[ast.Dict] = []
  for node in ast.walk(ctx.tree):
    if isinstance(node, ast.Assign) and len(node.targets) == 1 \
        and isinstance(node.targets[0], ast.Name) \
        and node.targets[0].id == name:
      if not isinstance(node.value, ast.Dict):
        return None                 # rebound to something opaque
      hits.append(node.value)
  return hits[0] if len(hits) == 1 else None


@register
class MetricLabelPass(GlintPass):
  name = 'metric-label-cardinality'
  description = ('every labels={...} at a metric registration site '
                 'draws its keys from telemetry/schema.py::'
                 'METRIC_LABELS (the closed, cardinality-bounded '
                 'label vocabulary)')

  def begin(self, run):
    self._schema = run.schema_path
    self._pkg = run.pkg_prefix.rstrip('/') + '/'
    #: label key -> first (rel, line) use site
    self._used: Dict[str, Tuple[str, int]] = {}

  def check_file(self, ctx):
    if not ctx.rel.startswith(self._pkg):
      return
    for node in ast.walk(ctx.tree):
      if not (isinstance(node, ast.Call)
              and _callee_name(node.func) in _REGISTRARS):
        continue
      val = _labels_value(node)
      if val is None or (isinstance(val, ast.Constant)
                         and val.value is None):
        continue
      kind = _callee_name(node.func)
      if isinstance(val, ast.Name):
        if _is_param(ctx, node, val.id):
          continue                  # forwarding helper — see its
        d = _unique_dict_assign(ctx, val.id)   # callers instead
        if d is None:
          yield Finding(
              rule=self.name, path=ctx.rel, line=node.lineno,
              message=f'{kind}(...) labels={val.id!r} does not '
                      'resolve to a unique dict literal in this '
                      'file — pass a dict literal so the label '
                      'keys are statically checkable')
          continue
        val = d
      if not isinstance(val, ast.Dict):
        yield Finding(
            rule=self.name, path=ctx.rel, line=node.lineno,
            message=f'{kind}(...) labels value is not a dict '
                    'literal (or a name bound to one) — label keys '
                    'must be statically enumerable')
        continue
      for k in val.keys:
        if not (isinstance(k, ast.Constant)
                and isinstance(k.value, str)):
          yield Finding(
              rule=self.name, path=ctx.rel, line=node.lineno,
              message=f'{kind}(...) has a non-string-constant '
                      'label KEY — keys are the closed vocabulary; '
                      'only values may be dynamic')
          continue
        if k.value in _FORBIDDEN_KEYS:
          yield Finding(
              rule=self.name, path=ctx.rel, line=node.lineno,
              message=f'{kind}(...) uses forbidden label key '
                      f'{k.value!r} — a per-request id as a label '
                      'mints one time series per request; attach '
                      'traces to metrics via exemplars instead')
          continue
        self._used.setdefault(k.value, (ctx.rel, node.lineno))

  def finish(self, run):
    try:
      table = registry_tables(
          self._schema, table_names=('METRIC_LABELS',)
      ).get('METRIC_LABELS', {})
    except (OSError, SyntaxError) as e:
      yield Finding(
          rule=self.name, path=str(self._schema), line=0,
          message=f'schema registry unreadable ({e}) — nothing to '
                  'enforce against')
      return
    schema_rel = self._schema_rel(run)
    for key, (rel, line) in sorted(self._used.items()):
      if key not in table:
        yield Finding(
            rule=self.name, path=rel, line=line,
            message=f'label key {key!r} is not declared in '
                    'telemetry/schema.py::METRIC_LABELS — declare '
                    'it with a doc stating its BOUNDED value set, '
                    'or fold the dimension into the metric name')
    for key, (line, doc) in sorted(table.items()):
      if key not in self._used:
        yield Finding(
            rule=self.name, path=schema_rel, line=line,
            message=f'METRIC_LABELS[{key!r}] has no remaining '
                    'labeled registration site — remove the stale '
                    'declaration')
      if not (isinstance(doc, str) and len(doc.strip()) > 10):
        yield Finding(
            rule=self.name, path=schema_rel, line=line,
            message=f'METRIC_LABELS[{key!r}] needs a doc (>10 '
                    'chars) stating why the value domain is '
                    'bounded — that statement IS the cardinality '
                    'contract')

  def _schema_rel(self, run) -> str:
    try:
      return self._schema.resolve().relative_to(
          run.repo.resolve()).as_posix()
    except ValueError:
      return str(self._schema)
