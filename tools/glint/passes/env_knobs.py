"""env-knob-drift: every ``GLT_*`` knob is documented.

Migrated from the standalone ``tools/check_env_knobs.py`` (ISSUE 6
satellite), which stays as a thin shim over the helpers here so its
documented CLI keeps working.  The contract is unchanged: every
``GLT_*`` string constant in the scanned surfaces — the knob
vocabulary: env reads go through ``os.environ.get('GLT_X')``,
``os.environ['GLT_X']`` or a ``FOO_ENV = 'GLT_X'`` constant, all of
which surface as a string literal — must appear in the
``benchmarks/README.md`` knob tables.  An undocumented knob is a
feature only its author can use.

As a glint pass the scan covers every file the driver scans (the
shim keeps its original three roots), so e.g. ``examples/`` knobs
get drift-checked for free.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Tuple

from ..findings import Finding
from ..registry import GlintPass, register

KNOB_RE = re.compile(r'^GLT_[A-Z0-9_]+$')


def knob_constants(tree: ast.AST) -> List[Tuple[str, int]]:
  """``(knob, lineno)`` for every GLT_* string constant in a tree."""
  out = []
  for node in ast.walk(tree):
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
        and KNOB_RE.match(node.value)):
      out.append((node.value, node.lineno))
  return out


def documented_knobs(readme_path: Path) -> set:
  return set(re.findall(r'GLT_[A-Z0-9_]+', Path(readme_path).read_text()))


@register
class EnvKnobDriftPass(GlintPass):
  name = 'env-knob-drift'
  description = ('every GLT_* knob referenced in code appears in the '
                 'benchmarks/README.md knob tables')

  def begin(self, run):
    self._readme = run.readme_path
    #: knob -> [(rel, line), ...]
    self._refs: Dict[str, List[Tuple[str, int]]] = {}

  def check_file(self, ctx):
    for knob, line in knob_constants(ctx.tree):
      self._refs.setdefault(knob, []).append((ctx.rel, line))
    return ()

  def finish(self, run):
    try:
      documented = documented_knobs(self._readme)
    except OSError:
      yield Finding(
          rule=self.name, path=str(self._readme), line=0,
          message=f'knob table {self._readme} is unreadable — the '
                  'drift check has nothing to check against')
      return
    for knob, refs in sorted(self._refs.items()):
      if knob in documented:
        continue
      rel, line = refs[0]
      others = ', '.join(sorted({r for r, _ in refs} - {rel}))
      yield Finding(
          rule=self.name, path=rel, line=line,
          message=f'{knob} is read in code but missing from the '
                  f'{self._readme.name} knob tables'
                  + (f' (also referenced in {others})' if others else '')
                  + ' — add a row (an undocumented knob is a feature '
                    'only its author can use)')
