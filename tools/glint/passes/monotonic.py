"""monotonic-clock: durations and deadlines never read the wall clock.

``time.time()`` steps under NTP adjustment and DST/clock-set events;
a duration computed from it can be negative or hours long, and a
deadline can fire immediately or never.  The span layer is monotonic
by contract (`telemetry/spans.py` records ``mono``), and the
resilience deadlines (PR 4/6) are built on ``time.monotonic()``.

The pass flags a ``time.time()`` call only where its value flows into
*arithmetic or comparison* — i.e. where a duration/deadline is being
computed:

* the call sits directly inside a ``BinOp`` / ``Compare`` /
  ``AugAssign``; or
* the call's result is bound to a plain name that is later used
  inside a ``BinOp`` / ``Compare`` in the same function.

Pure timestamps (``{'ts': time.time()}``, ``round(time.time(), 3)``)
are wall-clock by design — heartbeats and flight-recorder events
WANT human-correlatable time — and are not flagged.
"""
from __future__ import annotations

import ast
from typing import Optional

from ..findings import Finding
from ..registry import GlintPass, register

_WALL = {'time.time'}


@register
class MonotonicClockPass(GlintPass):
  name = 'monotonic-clock'
  description = ('time.time() must not feed duration/deadline '
                 'arithmetic — use time.monotonic(); pure wall-clock '
                 'timestamps are fine')

  def check_file(self, ctx):
    for node in ast.walk(ctx.tree):
      if not (isinstance(node, ast.Call)
              and ctx.qualname(node.func) in _WALL):
        continue
      hit = self._arithmetic_ancestor(ctx, node)
      if hit is None:
        hit = self._name_flows_to_arithmetic(ctx, node)
      if hit is not None:
        yield Finding(
            rule=self.name, path=ctx.rel, line=node.lineno,
            message='time.time() feeds a duration/deadline '
                    f'computation ({hit}) — wall clock steps under '
                    'NTP; use time.monotonic()')

  @staticmethod
  def _arithmetic_ancestor(ctx, node: ast.Call) -> Optional[str]:
    for anc in ctx.ancestors(node):
      if isinstance(anc, (ast.BinOp, ast.Compare, ast.AugAssign)):
        return 'in-expression arithmetic'
      if isinstance(anc, (ast.stmt, ast.Lambda)):
        return None
    return None

  @staticmethod
  def _name_flows_to_arithmetic(ctx, node: ast.Call) -> Optional[str]:
    parent = ctx.parent(node)
    if not (isinstance(parent, ast.Assign)
            and len(parent.targets) == 1
            and isinstance(parent.targets[0], ast.Name)):
      return None
    name = parent.targets[0].id
    scope = ctx.enclosing_function(node) or ctx.tree
    for n in ast.walk(scope):
      if isinstance(n, (ast.BinOp, ast.Compare)):
        for leaf in ast.walk(n):
          if isinstance(leaf, ast.Name) and leaf.id == name \
              and isinstance(leaf.ctx, ast.Load):
            return f'via {name!r} at line {n.lineno}'
    return None
