"""host-sync: no host synchronization inside the hot dispatch path.

The fused epoch drivers win by enqueuing the next compiled program
*before* the previous one finishes (dispatch-ahead, `loader/fused.py`
/ `parallel/fused.py`); the serving tier's warm executables and the
mesh epoch drivers share the property.  One `jax.device_get`,
`.item()`, `.block_until_ready()`, `np.asarray`-on-a-device-value or
tracer-`bool` inside that path stalls the pipeline silently — the
code stays correct, throughput dies, and nothing fails (the
PyTorch-Direct lineage in PAPERS.md depends on the same never-sync
contract in its overlapped window).

Hot scope = the transitive closure, within one file, of:
  * functions handed to ``_uncached_jit(...)`` / ``jax.jit(...)``
    (by local name or ``self.<method>`` reference);
  * ``jax.lax.scan`` body callables (named or lambda);
  * functions decorated ``@jax.jit`` (bare or ``partial(jax.jit,..)``);
  * same-file functions *called* from a hot function by simple name.

Banned inside a hot scope: ``jax.device_get``, ``.item()``,
``.block_until_ready()``, ``np.asarray`` / ``np.array`` /
``np.copy``, ``bool(...)`` on a traced value, and host clocks
(``time.time`` / ``time.monotonic`` / ``time.perf_counter`` — traced
ONCE at compile time, so the recorded "duration" is a compile-time
constant, a silent telemetry lie).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..context import terminal_name as _terminal
from ..findings import Finding
from ..registry import GlintPass, register

#: calls that make a jitted/scanned function hot, keyed by the
#: terminal segment of the callee's qualname
_JIT_WRAPPERS = {'_uncached_jit', 'jit'}
#: control-flow primitives -> positional indices of their traced
#: callables (scan(body, ...); while_loop(cond, body, ...);
#: fori_loop(lo, hi, body, ...))
_SCAN_CALLEES = {
    'jax.lax.scan': (0,), 'lax.scan': (0,),
    'jax.lax.while_loop': (0, 1), 'lax.while_loop': (0, 1),
    'jax.lax.fori_loop': (2,), 'lax.fori_loop': (2,),
}

_BANNED_QUAL = {
    'jax.device_get': 'forces a device→host transfer + sync',
    'numpy.asarray': 'materializes a device value on host (sync)',
    'numpy.array': 'materializes a device value on host (sync)',
    'numpy.copy': 'materializes a device value on host (sync)',
    'time.time': 'host clock is traced ONCE at compile time — the '
                 'value is a compile-time constant, not a timestamp',
    'time.monotonic': 'host clock is traced ONCE at compile time',
    'time.perf_counter': 'host clock is traced ONCE at compile time',
}
_BANNED_METHODS = {
    'item': '.item() blocks on the device value',
    'block_until_ready': 'explicit device sync',
    'tolist': '.tolist() blocks on the device value',
}


@register
class HostSyncPass(GlintPass):
  name = 'host-sync'
  description = ('no device_get/.item()/block_until_ready/np.asarray/'
                 'host clocks inside jitted or scanned (hot-path) '
                 'functions')

  def check_file(self, ctx):
    tree = ctx.tree
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
      if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        defs.setdefault(node.name, []).append(node)

    hot: Set[ast.AST] = set()

    def mark(name: str) -> None:
      for d in defs.get(name, ()):
        hot.add(d)

    for node in ast.walk(tree):
      if isinstance(node, ast.Call):
        qn = ctx.qualname(node.func)
        term = _terminal(node.func)
        if (term in _JIT_WRAPPERS
            and (term == '_uncached_jit' or qn in ('jax.jit', 'jit'))
            and node.args):
          arg = node.args[0]
          if isinstance(arg, ast.Lambda):
            hot.add(arg)
          else:
            mark(_terminal(arg))
        elif qn in _SCAN_CALLEES:
          for idx in _SCAN_CALLEES[qn]:
            if idx >= len(node.args):
              continue
            arg = node.args[idx]
            if isinstance(arg, ast.Lambda):
              hot.add(arg)
            else:
              mark(_terminal(arg))
      elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        for dec in node.decorator_list:
          target = dec.func if isinstance(dec, ast.Call) else dec
          qn = ctx.qualname(target)
          if qn in ('jax.jit', 'jit'):
            hot.add(node)
          elif qn in ('functools.partial', 'partial') \
              and isinstance(dec, ast.Call) and dec.args \
              and ctx.qualname(dec.args[0]) in ('jax.jit', 'jit'):
            hot.add(node)

    # transitive closure: same-file functions called from a hot scope
    # are traced into the same program
    changed = True
    while changed:
      changed = False
      for fn in list(hot):
        for node in ast.walk(fn):
          if isinstance(node, ast.Call):
            callee = _terminal(node.func)
            for d in defs.get(callee, ()):
              if d not in hot:
                hot.add(d)
                changed = True

    # report banned operations inside any hot scope (dedup nodes that
    # sit inside several nested hot functions)
    seen: Set[ast.AST] = set()
    for fn in hot:
      for node in ast.walk(fn):
        if not isinstance(node, ast.Call) or node in seen:
          continue
        seen.add(node)
        qn = ctx.qualname(node.func)
        why = _BANNED_QUAL.get(qn)
        if why is None and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _BANNED_METHODS and not node.args:
          why = _BANNED_METHODS[node.func.attr]
          qn = f'.{node.func.attr}()'
        if why is None and qn == 'bool' and node.args:
          why = ('bool() on a traced value concretizes (sync or '
                 'TracerBoolConversionError)')
        if why is None:
          continue
        host = fn.name if hasattr(fn, 'name') else '<lambda>'
        yield Finding(
            rule=self.name, path=ctx.rel, line=node.lineno,
            message=f'{qn} inside hot-path function {host!r} — {why}; '
                    'hoist it out of the jitted/scanned scope (or '
                    'return the value through scan outputs)')
