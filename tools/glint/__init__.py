"""glint — repo-native static analysis for data-plane invariants.

The codebase encodes a set of unwritten contracts that tests only
catch after the fact: no host synchronization inside the fused
dispatch-ahead path, per-seed deterministic sampling, byte-identical
resume, monotonic-clock durations, lock-guarded shared state,
documented env knobs, registered telemetry schema.  Two of those
already grew one-off AST checkers (``tools/check_env_knobs.py``, the
event-schema scan that used to live in ``tests/test_event_schema.py``)
because drift kept recurring — glint is the framework both migrated
into, plus four new passes grounded in the same class of hazard.

Usage::

    python -m tools.glint                       # scan default roots
    python -m tools.glint --list-passes
    python -m tools.glint --rules monotonic-clock graphlearn_tpu
    python -m tools.glint --write-baseline      # grandfather findings

Nonzero exit on any finding that is neither inline-suppressed
(``# glint: disable=<rule>``) nor recorded in the checked-in baseline
(``tools/glint/baseline.json``).  The same run is wired into tier-1 as
``tests/test_glint.py::test_whole_tree_clean``.
"""
from .driver import check_source, run_glint  # noqa: F401
from .findings import Finding  # noqa: F401
from .registry import GlintPass, all_passes, register  # noqa: F401
