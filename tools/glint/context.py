"""Per-file analysis context: one parse, shared by every pass.

Provides the AST plus the cross-cutting machinery passes need:
parent links, enclosing-scope lookup, import-alias resolution
(``import numpy as np`` makes ``np.random.rand`` resolve to
``numpy.random.rand``), source-line access, and the inline suppression
table (``# glint: disable=<rule>[,<rule>...]`` — trailing on the
flagged line, or a standalone comment line suppressing the next line).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(r'#\s*glint:\s*disable=([A-Za-z0-9_\-, ]+)')


def terminal_name(node: ast.AST) -> str:
  """Identifier a callable/attribute reference bottoms out in:
  ``f`` for a Name, ``m`` for ``self.m`` / ``obj.a.m``."""
  if isinstance(node, ast.Attribute):
    return node.attr
  if isinstance(node, ast.Name):
    return node.id
  return ''


def comment_annotations(lines, pattern: 're.Pattern') -> Dict[int, list]:
  """``{target_line: [matches]}`` for a comment-borne annotation:
  a trailing comment annotates its own line, a standalone comment
  line annotates the next line.  The single convention shared by
  suppressions, ``# guarded-by:`` and ``# glint: holds=``."""
  out: Dict[int, list] = {}
  for i, raw in enumerate(lines, start=1):
    m = pattern.search(raw)
    if m:
      target = i + 1 if raw.lstrip().startswith('#') else i
      out.setdefault(target, []).append(m)
  return out


class FileContext:
  """Parsed view of one source file."""

  def __init__(self, source: str, rel: str, path: Optional[Path] = None):
    self.path = path
    self.rel = rel                      #: repo-relative posix path
    self.source = source
    self.lines: List[str] = source.splitlines()
    self.tree: Optional[ast.AST] = None
    self.parse_error: Optional[SyntaxError] = None
    try:
      self.tree = ast.parse(source)
    except SyntaxError as e:            # surfaced by the driver
      self.parse_error = e
      return
    self._parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(self.tree):
      for child in ast.iter_child_nodes(node):
        self._parents[child] = node
    self.aliases = self._import_aliases()
    self._suppress = self._suppressions()

  @classmethod
  def from_path(cls, path: Path, repo: Path) -> 'FileContext':
    try:
      rel = path.resolve().relative_to(repo.resolve()).as_posix()
    except ValueError:                  # explicit path outside the repo
      rel = path.as_posix()
    return cls(path.read_text(), rel, path)

  # -- source helpers --------------------------------------------------------
  def line_text(self, lineno: int) -> str:
    if 1 <= lineno <= len(self.lines):
      return self.lines[lineno - 1].strip()
    return ''

  # -- tree helpers ----------------------------------------------------------
  def parent(self, node: ast.AST) -> Optional[ast.AST]:
    return self._parents.get(node)

  def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
    cur = self._parents.get(node)
    while cur is not None:
      yield cur
      cur = self._parents.get(cur)

  def enclosing(self, node: ast.AST, kinds: Tuple[type, ...]):
    for anc in self.ancestors(node):
      if isinstance(anc, kinds):
        return anc
    return None

  def enclosing_function(self, node: ast.AST):
    return self.enclosing(
        node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))

  def qualname(self, node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain with the ROOT segment
    expanded through the file's import aliases; '' when the chain
    bottoms out in anything else (a call result, a subscript, ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
      parts.append(node.attr)
      node = node.value
    if isinstance(node, ast.Name):
      parts.append(self.aliases.get(node.id, node.id))
    else:
      return ''
    return '.'.join(reversed(parts))

  def _import_aliases(self) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(self.tree):
      if isinstance(node, ast.Import):
        for a in node.names:
          out[a.asname or a.name.split('.')[0]] = (
              a.name if a.asname else a.name.split('.')[0])
      elif isinstance(node, ast.ImportFrom) and node.module \
          and not node.level:
        for a in node.names:
          out[a.asname or a.name] = f'{node.module}.{a.name}'
    return out

  # -- suppressions ----------------------------------------------------------
  def _suppressions(self) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for target, matches in comment_annotations(
        self.lines, _SUPPRESS_RE).items():
      for m in matches:
        out.setdefault(target, set()).update(
            r.strip() for r in m.group(1).split(',') if r.strip())
    return out

  def rule_disabled(self, rule: str, lineno: int) -> bool:
    rules = self._suppress.get(lineno, ())
    return rule in rules or 'all' in rules
