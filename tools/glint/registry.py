"""Pass registry: a pass is a class with a unique ``name``; the
``@register`` decorator adds it to the table the driver instantiates
per run.  Passes live in ``tools/glint/passes/`` — importing that
package populates the registry (``all_passes`` does it lazily so the
framework core stays import-light).
"""
from __future__ import annotations

from typing import Dict, Iterable, Type


class GlintPass:
  """Base pass.  Lifecycle per run::

      p = PassCls()
      p.begin(run)                  # run-level config (README paths, ...)
      for ctx in files: p.check_file(ctx)   # yield per-file findings
      p.finish(run)                 # yield repo-level findings

  Per-file passes implement only ``check_file``; repo-level passes
  (cross-file aggregation like knob drift) accumulate in
  ``check_file`` and report from ``finish``.
  """

  #: unique rule name — the suppression / --rules / baseline key
  name: str = ''
  #: one-line description for --list-passes and the docs table
  description: str = ''

  def begin(self, run) -> None:
    del run

  def check_file(self, ctx) -> Iterable:
    del ctx
    return ()

  def finish(self, run) -> Iterable:
    del run
    return ()


_REGISTRY: Dict[str, Type[GlintPass]] = {}


def register(cls: Type[GlintPass]) -> Type[GlintPass]:
  if not cls.name:
    raise ValueError(f'{cls.__name__} has no rule name')
  if cls.name in _REGISTRY and _REGISTRY[cls.name] is not cls:
    raise ValueError(f'duplicate glint pass name {cls.name!r}')
  _REGISTRY[cls.name] = cls
  return cls


def all_passes() -> Dict[str, Type[GlintPass]]:
  """Name -> pass class, loading the passes package on first use."""
  from . import passes  # noqa: F401 — import side effect registers
  return dict(_REGISTRY)
