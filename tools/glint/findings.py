"""The finding model: one diagnostic, anchored to a file:line.

Findings carry a line-content-based *fingerprint* so the baseline
survives unrelated edits shifting line numbers — the classic reason
line-keyed baselines rot within a week.
"""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class Finding:
  """One diagnostic from one pass.

  ``suppressed`` / ``baselined`` are stamped by the driver after the
  pass yields the finding; passes never set them.
  """

  rule: str               #: registered pass name, e.g. 'monotonic-clock'
  path: str               #: repo-relative posix path
  line: int               #: 1-based line the finding anchors to
  message: str
  snippet: str = ''       #: source line text (stripped) at the anchor
  suppressed: bool = False
  baselined: bool = False

  @property
  def fingerprint(self) -> str:
    """Stable identity for baseline matching: rule + file + the
    *content* of the anchored line (not its number)."""
    body = ' '.join((self.snippet or self.message).split())
    return f'{self.rule}|{self.path}|{body}'

  @property
  def live(self) -> bool:
    """True when this finding should fail the run."""
    return not (self.suppressed or self.baselined)

  def render(self) -> str:
    tag = ''
    if self.suppressed:
      tag = '  [suppressed]'
    elif self.baselined:
      tag = '  [baselined]'
    out = f'{self.path}:{self.line}: [{self.rule}] {self.message}{tag}'
    if self.snippet:
      out += f'\n    {self.snippet}'
    return out
