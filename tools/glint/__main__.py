import sys

from .driver import main

sys.exit(main())
