"""Env-knob documentation drift check (ISSUE 6 satellite) — now a
thin shim over glint's ``env-knob-drift`` pass (ISSUE 11).

The implementation lives in ``tools/glint/passes/env_knobs.py``; this
module keeps the original standalone CLI and the helper API
(`knob_references` / `documented_knobs` / `undocumented`) that
``tests/test_env_knobs.py`` and the docs reference::

    python tools/check_env_knobs.py          # exit 1 on drift

The full framework run (this pass plus five more) is::

    python -m tools.glint --baseline tools/glint/baseline.json
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:               # standalone-script import
  sys.path.insert(0, str(REPO))

from tools.glint.passes.env_knobs import (documented_knobs as  # noqa: E402
                                          _documented, knob_constants)

#: scanned roots: the package plus the bench drivers (their knobs are
#: user-facing too).  The glint pass scans the driver's wider root set
#: (``examples/`` included); this shim keeps its historical contract.
SCAN_ROOTS = ('graphlearn_tpu', 'benchmarks', 'bench.py')
README = REPO / 'benchmarks' / 'README.md'


def knob_references() -> dict:
  """``{knob: [relative file, ...]}`` for every GLT_* string constant
  in the scanned roots."""
  out: dict = {}
  files = []
  for root in SCAN_ROOTS:
    p = REPO / root
    if p.is_file():
      files.append(p)
    elif p.is_dir():
      files.extend(sorted(p.rglob('*.py')))
  for py in files:
    try:
      tree = ast.parse(py.read_text())
    except SyntaxError:             # pragma: no cover — broken file
      continue
    for knob, _line in knob_constants(tree):
      out.setdefault(knob, []).append(str(py.relative_to(REPO)))
  return out


def documented_knobs(readme_path: Path = README) -> set:
  return _documented(readme_path)


def undocumented(readme_path: Path = README) -> dict:
  """Knobs referenced in code but absent from the README's tables."""
  doc = documented_knobs(readme_path)
  return {k: sorted(set(files)) for k, files in knob_references().items()
          if k not in doc}


def main() -> int:
  missing = undocumented()
  if not missing:
    print(f'env knobs: OK ({len(knob_references())} GLT_* knobs, all '
          f'documented in {README.relative_to(REPO)})')
    return 0
  print('env knobs: DRIFT — knobs read in code but missing from '
        f'{README.relative_to(REPO)}:')
  for k, files in sorted(missing.items()):
    print(f'  {k}  ({", ".join(files)})')
  return 1


if __name__ == '__main__':
  sys.exit(main())
