"""Env-knob documentation drift check (ISSUE 6 satellite).

PR 4 and PR 5 each added ``GLT_*`` knobs that drifted from the
``benchmarks/README.md`` knob tables — an undocumented knob is a
feature only its author can use.  This tool AST-scans the package (and
the bench drivers) for every ``GLT_*`` string constant — the knob
vocabulary: env reads go through ``os.environ.get('GLT_X')``,
``os.environ['GLT_X']`` or a ``FOO_ENV = 'GLT_X'`` constant, and all
of them surface as a string literal — and fails if any knob is
missing from the README.

Wired into the test suite like ``tests/test_event_schema.py``
(``tests/test_env_knobs.py``), and runnable standalone::

    python tools/check_env_knobs.py          # exit 1 on drift
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
#: scanned roots: the package plus the bench drivers (their knobs are
#: user-facing too)
SCAN_ROOTS = ('graphlearn_tpu', 'benchmarks', 'bench.py')
README = REPO / 'benchmarks' / 'README.md'

_KNOB_RE = re.compile(r'^GLT_[A-Z0-9_]+$')


def knob_references() -> dict:
  """``{knob: [relative file, ...]}`` for every GLT_* string constant
  in the scanned roots."""
  out: dict = {}
  files = []
  for root in SCAN_ROOTS:
    p = REPO / root
    if p.is_file():
      files.append(p)
    elif p.is_dir():
      files.extend(sorted(p.rglob('*.py')))
  for py in files:
    try:
      tree = ast.parse(py.read_text())
    except SyntaxError:             # pragma: no cover — broken file
      continue
    for node in ast.walk(tree):
      if (isinstance(node, ast.Constant) and isinstance(node.value, str)
          and _KNOB_RE.match(node.value)):
        out.setdefault(node.value, []).append(
            str(py.relative_to(REPO)))
  return out


def documented_knobs(readme_path: Path = README) -> set:
  return set(re.findall(r'GLT_[A-Z0-9_]+', readme_path.read_text()))


def undocumented(readme_path: Path = README) -> dict:
  """Knobs referenced in code but absent from the README's tables."""
  doc = documented_knobs(readme_path)
  return {k: sorted(set(files)) for k, files in knob_references().items()
          if k not in doc}


def main() -> int:
  missing = undocumented()
  if not missing:
    print(f'env knobs: OK ({len(knob_references())} GLT_* knobs, all '
          f'documented in {README.relative_to(REPO)})')
    return 0
  print('env knobs: DRIFT — knobs read in code but missing from '
        f'{README.relative_to(REPO)}:')
  for k, files in sorted(missing.items()):
    print(f'  {k}  ({", ".join(files)})')
  return 1


if __name__ == '__main__':
  sys.exit(main())
