"""ctypes bindings for the native host runtime (``csrc/``).

The reference exposes its C++/CUDA layer through a pybind11 module
(`python/py_export.cc:46-216`); this build uses a plain C ABI + ctypes
(no pybind11 in the image).  The library is auto-built with ``make`` on
first import if missing or stale — the moral equivalent of the
reference's build-on-install `setup.py` extension.

Everything here is *host* runtime: cross-process shm queues and
serialization for the producer pipeline, and CPU twins of the sampling
ops.  The device plane lives in `graphlearn_tpu/ops` (XLA/Pallas).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
from typing import Dict, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), 'csrc')
_SO = os.path.join(_HERE, 'libglt_native.so')

_lib = None
_lock = threading.Lock()

# numpy dtype <-> wire code (keep stable: messages cross processes).
_DTYPE_CODES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4, np.dtype(np.bool_): 5,
    np.dtype(np.int16): 6, np.dtype(np.uint16): 7,
    np.dtype(np.float16): 8,
}
try:  # bfloat16 ships with jax via ml_dtypes
  import ml_dtypes as _ml
  _DTYPE_CODES[np.dtype(_ml.bfloat16)] = 9
except ImportError:
  pass
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def _build(force: bool = False):
  srcs = [os.path.join(_CSRC, f) for f in
          ('shm_queue.cc', 'tensor_map.cc', 'cpu_ops.cc', 'inducer.cc',
           'common.h')]
  if not force and os.path.exists(_SO):
    so_mtime = os.path.getmtime(_SO)
    if all(os.path.getmtime(s) <= so_mtime for s in srcs if
           os.path.exists(s)):
      return
  if force and os.path.exists(_SO):
    # make's mtime check would skip the rebuild; the stale artifact
    # must go first
    os.unlink(_SO)
  subprocess.run(['make', '-s', f'OUT={_SO}'], cwd=_CSRC, check=True)


def lib() -> ctypes.CDLL:
  """The loaded native library (built on first use).  A binary that
  fails to *load* — typically an artifact carried over from a host
  with a different libstdc++/glibc — is rebuilt in place from source
  and retried once, instead of poisoning every native-dependent path
  on this machine."""
  global _lib
  if _lib is None:
    with _lock:
      if _lib is None:
        _build()
        try:
          l = ctypes.CDLL(_SO)
        except OSError:
          _build(force=True)
          l = ctypes.CDLL(_SO)
        _declare(l)
        _lib = l
  return _lib


def available() -> bool:
  try:
    lib()
    return True
  except Exception:
    return False


def _declare(l):
  u64, i64, i32 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_int32
  p = ctypes.c_void_p
  l.glt_queue_create.restype = p
  l.glt_queue_create.argtypes = [u64, u64]
  l.glt_queue_attach.restype = p
  l.glt_queue_attach.argtypes = [ctypes.c_int]
  l.glt_queue_shmid.restype = ctypes.c_int
  l.glt_queue_shmid.argtypes = [p]
  l.glt_queue_slot_bytes.restype = u64
  l.glt_queue_slot_bytes.argtypes = [p]
  l.glt_queue_num_slots.restype = u64
  l.glt_queue_num_slots.argtypes = [p]
  l.glt_queue_size.restype = u64
  l.glt_queue_size.argtypes = [p]
  l.glt_queue_put.restype = ctypes.c_int
  l.glt_queue_put.argtypes = [p, ctypes.c_char_p, u64]
  l.glt_queue_get.restype = i64
  l.glt_queue_get.argtypes = [p, p, u64]
  l.glt_queue_get_timed.restype = i64
  l.glt_queue_get_timed.argtypes = [p, p, u64, i64]
  l.glt_queue_empty.restype = ctypes.c_int
  l.glt_queue_empty.argtypes = [p]
  l.glt_queue_detach.argtypes = [p]
  l.glt_queue_detach.restype = None

  u16p = np.ctypeslib.ndpointer(np.uint16, flags='C')
  u8p = np.ctypeslib.ndpointer(np.uint8, flags='C')
  u64p = np.ctypeslib.ndpointer(np.uint64, flags='C')
  i64p = np.ctypeslib.ndpointer(np.int64, flags='C')
  i32p = np.ctypeslib.ndpointer(np.int32, flags='C')
  f32p = np.ctypeslib.ndpointer(np.float32, flags='C')

  l.glt_tmap_size.restype = u64
  l.glt_tmap_size.argtypes = [ctypes.c_uint32, u16p, u8p, u64p]
  l.glt_tmap_write.restype = u64
  l.glt_tmap_write.argtypes = [
      ctypes.c_uint32, u16p, ctypes.c_char_p, u8p, u8p, u64p, u64p,
      ctypes.POINTER(ctypes.c_void_p), p]
  l.glt_tmap_count.restype = ctypes.c_uint32
  l.glt_tmap_count.argtypes = [p, u64]
  l.glt_tmap_parse.restype = ctypes.c_int
  l.glt_tmap_parse.argtypes = [p, u64, u16p, p, u8p, u8p, u64p, u64p, u64p]

  l.glt_coo_to_csr.restype = None
  l.glt_coo_to_csr.argtypes = [i64p, i64p, i64, i64, i64p, i64p, i64p]
  l.glt_sample_one_hop.restype = None
  l.glt_sample_one_hop.argtypes = [i64p, i64p, p, i64p, i64, i64, i64,
                                   u64, i64p, u8p, p]
  l.glt_cal_nbr_prob.restype = None
  l.glt_cal_nbr_prob.argtypes = [i64p, i64p, f32p, i64, i64, f32p]
  l.glt_negative_sample.restype = i64
  l.glt_negative_sample.argtypes = [i64p, i64p, i64, i64, i64,
                                    ctypes.c_int, ctypes.c_int, u64,
                                    i64p, i64p]

  l.glt_inducer_create.restype = p
  l.glt_inducer_create.argtypes = [i64]
  l.glt_inducer_destroy.argtypes = [p]
  l.glt_inducer_destroy.restype = None
  l.glt_inducer_clear.argtypes = [p]
  l.glt_inducer_clear.restype = None
  l.glt_inducer_num_nodes.restype = i64
  l.glt_inducer_num_nodes.argtypes = [p]
  l.glt_inducer_init.restype = None
  l.glt_inducer_init.argtypes = [p, i64p, i64, i32p]
  l.glt_inducer_induce.restype = i64
  l.glt_inducer_induce.argtypes = [p, i64p, i64p, u8p, i64, i64, i32p, i32p]
  l.glt_inducer_nodes_since.restype = None
  l.glt_inducer_nodes_since.argtypes = [p, i64, i64, i64p]
  l.glt_inducer_induce_pair.restype = i64
  l.glt_inducer_induce_pair.argtypes = [p, i32p, i64p, u8p, i64, i64,
                                        i32p, i32p]


# ---------------------------------------------------------------------------
# Serialization: Dict[str, np.ndarray] <-> bytes
# ---------------------------------------------------------------------------
def serialize_tensor_map(msg: Dict[str, np.ndarray]) -> bytes:
  """Flat-binary serialize (reference `csrc/tensor_map.cc:28-85` twin)."""
  l = lib()
  def _contig(v):
    v = np.asarray(v)
    # NB: np.ascontiguousarray would promote 0-d to 1-d; preserve rank.
    return v if v.flags['C_CONTIGUOUS'] else np.ascontiguousarray(v)
  items = [(k, _contig(v)) for k, v in msg.items()]
  n = len(items)
  key_bytes = b''.join(k.encode() for k, _ in items)
  key_lens = np.array([len(k.encode()) for k, _ in items], np.uint16)
  dtypes = np.array([_DTYPE_CODES[v.dtype] for _, v in items], np.uint8)
  ndims = np.array([v.ndim for _, v in items], np.uint8)
  shapes = np.array([d for _, v in items for d in v.shape], np.uint64)
  if shapes.size == 0:
    shapes = np.zeros(1, np.uint64)
  nbytes = np.array([v.nbytes for _, v in items], np.uint64)
  datas = (ctypes.c_void_p * n)(
      *[v.ctypes.data_as(ctypes.c_void_p).value for _, v in items])
  size = l.glt_tmap_size(n, key_lens, ndims, nbytes)
  out = ctypes.create_string_buffer(int(size))
  written = l.glt_tmap_write(n, key_lens, key_bytes, dtypes, ndims,
                             shapes, nbytes, datas, out)
  assert written == size, (written, size)
  return out.raw


def parse_tensor_map(buf: bytes) -> Dict[str, np.ndarray]:
  """Inverse of :func:`serialize_tensor_map` (copies out of ``buf``)."""
  l = lib()
  raw = ctypes.create_string_buffer(buf, len(buf))
  base = ctypes.cast(raw, ctypes.c_void_p)
  n = l.glt_tmap_count(base, len(buf))
  if n == 0 and len(buf) >= 12:
    raise ValueError('bad tensor-map buffer')
  key_lens = np.zeros(max(n, 1), np.uint16)
  dtypes = np.zeros(max(n, 1), np.uint8)
  ndims = np.zeros(max(n, 1), np.uint8)
  # Generous caps: keys and shapes are tiny.
  keys_buf = ctypes.create_string_buffer(len(buf))
  shapes = np.zeros(max(len(buf) // 8, 8), np.uint64)
  nbytes = np.zeros(max(n, 1), np.uint64)
  offs = np.zeros(max(n, 1), np.uint64)
  rc = l.glt_tmap_parse(base, len(buf), key_lens, keys_buf, dtypes,
                        ndims, shapes, nbytes, offs)
  if rc != 0:
    raise ValueError('malformed tensor-map buffer')
  out: Dict[str, np.ndarray] = {}
  kpos = 0
  spos = 0
  arr = np.frombuffer(buf, np.uint8)
  for i in range(n):
    key = keys_buf.raw[kpos:kpos + key_lens[i]].decode()
    kpos += key_lens[i]
    shape = tuple(int(s) for s in shapes[spos:spos + ndims[i]])
    spos += ndims[i]
    dt = _CODE_DTYPES[int(dtypes[i])]
    start = int(offs[i])
    data = arr[start:start + int(nbytes[i])].tobytes()
    out[key] = np.frombuffer(data, dt).reshape(shape)
  return out


# ---------------------------------------------------------------------------
# ShmQueue: cross-process bounded message queue
# ---------------------------------------------------------------------------
class ShmQueue:
  """Fixed-slot MPMC ring in SysV shm (see `csrc/shm_queue.cc`).

  Picklable: pickling captures the shmid; unpickling re-attaches —
  the reference's `SampleQueue` pickling contract
  (`py_export.cc:132-140`).
  """

  def __init__(self, num_slots: int, slot_bytes: int,
               _shmid: Optional[int] = None):
    self._l = lib()
    if _shmid is None:
      self._h = self._l.glt_queue_create(num_slots, slot_bytes)
      if not self._h:
        raise OSError('shmget failed (check kernel.shmmax)')
    else:
      self._h = self._l.glt_queue_attach(_shmid)
      if not self._h:
        raise OSError(f'shmat({_shmid}) failed')

  @property
  def shmid(self) -> int:
    return self._l.glt_queue_shmid(self._h)

  @property
  def slot_bytes(self) -> int:
    return self._l.glt_queue_slot_bytes(self._h)

  def qsize(self) -> int:
    return self._l.glt_queue_size(self._h)

  def empty(self) -> bool:
    return bool(self._l.glt_queue_empty(self._h))

  def put_bytes(self, data: bytes):
    rc = self._l.glt_queue_put(self._h, data, len(data))
    if rc != 0:
      raise ValueError(
          f'message of {len(data)} bytes exceeds slot size '
          f'{self.slot_bytes}')

  def get_bytes(self) -> bytes:
    cap = self.slot_bytes
    buf = ctypes.create_string_buffer(int(cap))
    n = self._l.glt_queue_get(self._h, buf, cap)
    if n < 0:
      raise ValueError('message exceeded receive buffer')
    return buf.raw[:n]

  def get_bytes_timed(self, timeout: float):
    """Dequeue with a timeout (seconds); ``None`` when nothing arrived
    — consumers run liveness watchdogs between waits."""
    cap = self.slot_bytes
    buf = ctypes.create_string_buffer(cap)
    n = self._l.glt_queue_get_timed(self._h, buf, cap,
                                    int(timeout * 1000))
    if n == -2:
      return None
    if n < 0:
      raise ValueError('message exceeded receive buffer')
    return buf.raw[:n]

  def get_timed(self, timeout: float):
    b = self.get_bytes_timed(timeout)
    return None if b is None else parse_tensor_map(b)

  def put(self, msg: Dict[str, np.ndarray]):
    self.put_bytes(serialize_tensor_map(msg))

  def get(self) -> Dict[str, np.ndarray]:
    return parse_tensor_map(self.get_bytes())

  def close(self):
    if getattr(self, '_h', None):
      self._l.glt_queue_detach(self._h)
      self._h = None

  def __del__(self):
    try:
      self.close()
    except Exception:
      pass

  def __reduce__(self):
    return (ShmQueue, (0, 0, self.shmid))


# ---------------------------------------------------------------------------
# CPU op wrappers
# ---------------------------------------------------------------------------
def coo_to_csr(rows: np.ndarray, cols: np.ndarray, num_nodes: int
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Counting-sort COO->CSR; returns (indptr, indices, edge_perm)."""
  l = lib()
  rows = np.ascontiguousarray(rows, np.int64)
  cols = np.ascontiguousarray(cols, np.int64)
  e = len(rows)
  indptr = np.zeros(num_nodes + 1, np.int64)
  indices = np.zeros(e, np.int64)
  perm = np.zeros(e, np.int64)
  l.glt_coo_to_csr(rows, cols, e, num_nodes, indptr, indices, perm)
  return indptr, indices, perm


def sample_one_hop(indptr: np.ndarray, indices: np.ndarray,
                   seeds: np.ndarray, k: int, seed: int = 0,
                   edge_ids: Optional[np.ndarray] = None,
                   with_edge_ids: bool = False):
  """Dense uniform one-hop sample — host twin of
  `graphlearn_tpu.ops.sample_one_hop` (same [B,k]+mask contract)."""
  if k > 256:
    raise ValueError('fanout must be <= 256')
  l = lib()
  indptr = np.ascontiguousarray(indptr, np.int64)
  indices = np.ascontiguousarray(indices, np.int64)
  seeds = np.ascontiguousarray(seeds, np.int64)
  b = len(seeds)
  nbrs = np.empty((b, k), np.int64)
  mask = np.empty((b, k), np.uint8)
  eids = np.empty((b, k), np.int64) if with_edge_ids else None
  eid_ptr = (eids.ctypes.data_as(ctypes.c_void_p) if with_edge_ids
             else None)
  src_eids = (np.ascontiguousarray(edge_ids, np.int64)
              .ctypes.data_as(ctypes.c_void_p)
              if edge_ids is not None else None)
  l.glt_sample_one_hop(indptr, indices, src_eids, seeds, b,
                       len(indptr) - 1, k, seed, nbrs, mask, eid_ptr)
  return nbrs, mask.astype(bool), eids


def cal_nbr_prob(indptr, indices, prob_in, k: int) -> np.ndarray:
  l = lib()
  indptr = np.ascontiguousarray(indptr, np.int64)
  indices = np.ascontiguousarray(indices, np.int64)
  prob_in = np.ascontiguousarray(prob_in, np.float32)
  n = len(indptr) - 1
  out = np.zeros(n, np.float32)
  l.glt_cal_nbr_prob(indptr, indices, prob_in, n, k, out)
  return out


def negative_sample(indptr, indices, req_num: int, trials: int = 5,
                    strict: bool = True, padding: bool = False,
                    seed: int = 0):
  l = lib()
  indptr = np.ascontiguousarray(indptr, np.int64)
  indices = np.ascontiguousarray(indices, np.int64)
  n = len(indptr) - 1
  rows = np.empty(req_num, np.int64)
  cols = np.empty(req_num, np.int64)
  cnt = l.glt_negative_sample(indptr, indices, n, req_num, trials,
                              int(strict), int(padding), seed, rows, cols)
  return rows[:cnt], cols[:cnt]


class CpuInducer:
  """Stateful dedup/relabel — host twin of the device inducer
  (`graphlearn_tpu/ops/unique.py`); see `csrc/inducer.cc`."""

  def __init__(self, capacity_hint: int = 1024):
    self._l = lib()
    self._h = self._l.glt_inducer_create(capacity_hint)

  def __del__(self):
    try:
      if getattr(self, '_h', None):
        self._l.glt_inducer_destroy(self._h)
        self._h = None
    except Exception:
      pass

  def clear(self):
    self._l.glt_inducer_clear(self._h)

  @property
  def num_nodes(self) -> int:
    return self._l.glt_inducer_num_nodes(self._h)

  def init_nodes(self, seeds: np.ndarray) -> np.ndarray:
    seeds = np.ascontiguousarray(seeds, np.int64)
    out = np.empty(len(seeds), np.int32)
    self._l.glt_inducer_init(self._h, seeds, len(seeds), out)
    return out

  def induce_next(self, srcs: np.ndarray, nbrs: np.ndarray,
                  mask: np.ndarray):
    """Returns (new_nodes, row_local, col_local); edges are
    neighbor->seed (message-passing direction)."""
    srcs = np.ascontiguousarray(srcs, np.int64)
    nbrs = np.ascontiguousarray(nbrs, np.int64)
    mask = np.ascontiguousarray(mask, np.uint8)
    b, k = nbrs.shape
    rows = np.empty((b, k), np.int32)
    cols = np.empty((b, k), np.int32)
    before = self.num_nodes
    n_new = self._l.glt_inducer_induce(self._h, srcs, nbrs, mask, b, k,
                                       rows, cols)
    new_nodes = np.empty(n_new, np.int64)
    if n_new:
      self._l.glt_inducer_nodes_since(self._h, before, n_new, new_nodes)
    return new_nodes, rows, cols

  def all_nodes(self) -> np.ndarray:
    return self.nodes_since(0)

  def nodes_since(self, start: int) -> np.ndarray:
    """Global ids of table slots ``[start, num_nodes)`` in local-id
    order — the nodes first discovered after a hop snapshot."""
    n = self.num_nodes - int(start)
    out = np.empty(max(n, 0), np.int64)
    if n > 0:
      self._l.glt_inducer_nodes_since(self._h, start, n, out)
    return out

  def induce_from(self, src_local: np.ndarray, nbrs: np.ndarray,
                  mask: np.ndarray):
    """Hetero hop: the frontier's local ids come from a *different*
    (source-type) inducer; neighbors insert into THIS table.  Returns
    (new_nodes, row_local, col_local), edges neighbor->seed like
    `induce_next`."""
    src_local = np.ascontiguousarray(src_local, np.int32)
    nbrs = np.ascontiguousarray(nbrs, np.int64)
    mask = np.ascontiguousarray(mask, np.uint8)
    b, k = nbrs.shape
    rows = np.empty((b, k), np.int32)
    cols = np.empty((b, k), np.int32)
    before = self.num_nodes
    n_new = self._l.glt_inducer_induce_pair(self._h, src_local, nbrs, mask,
                                            b, k, rows, cols)
    new_nodes = np.empty(n_new, np.int64)
    if n_new:
      self._l.glt_inducer_nodes_since(self._h, before, n_new, new_nodes)
    return new_nodes, rows, cols
