"""Per-stage latency report CLI over flight-recorder traces.

``python -m graphlearn_tpu.telemetry.report TRACE.jsonl`` prints a
per-stage (span-kind) latency table — count, total, mean, p50/p90/p99
from the log2 histograms, max — answering "where did the step time
go" without leaving the terminal:

    stage              count   total_s    mean_ms      p50      p90 ...
    batch                 16     0.842     52.6ms   64.0ms  128.0ms
    sample.exchange       16     0.512     32.0ms   32.0ms   65.5ms

Modes:
  * ``--diff OTHER.jsonl``: second trace as baseline; the table gains
    a ``Δmean%`` column per stage (positive = this trace is slower) —
    the two-trace regression hunt.  When BOTH files are ``/varz``
    JSON snapshots (``{'ts', 'metrics': {...}}``) the diff is a
    counter/gauge delta table instead — changed keys with Δ and
    per-second rate over the snapshots' wall-clock gap.
  * ``--attribution FILE``: render per-partition traffic attribution
    (`DistNeighborSampler.attribution_stats` JSON, a bench envelope
    row carrying an ``attribution`` block, or a records JSONL holding
    one): the P×P src-device → dst-range byte matrix, the locality
    summary, padding-waste-by-layout when the envelope's ``layouts``
    comparison rides along, and the top-K hot-range table.
  * ``--chrome OUT.json``: also write the Perfetto-loadable Chrome
    trace (`telemetry.export`).
  * ``--metrics-json FILE``: instead of a JSONL trace, read a
    `gather_metrics` aggregate dump (``{'aggregate': {...}}`` or the
    flat dict itself) and print the MERGED cross-host histograms —
    the ≥2-process mesh view.
  * ``--postmortem BUNDLE``: render a post-mortem bundle
    (`telemetry.postmortem`, ``GLT_POSTMORTEM_DIR``): spans still in
    flight at dump time, final-window event deltas, the resilience
    and serving tables over the captured ring, supervision state and
    the SLO gauges — the after-the-incident view of a process that
    can no longer be scraped.

Quantiles from ``--metrics-json`` are log2-bucket upper edges (a 2x
envelope); from a JSONL trace the same bucketing is applied to the raw
durations so the two views stay comparable.
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Dict, List, Optional

from .export import load_events, span_durations, write_chrome_trace
from .histogram import Histogram, from_snapshot


def histograms_from_events(events: List[Dict]) -> Dict[str, Histogram]:
  """Per-kind histograms rebuilt from already-loaded trace events'
  span.end durations."""
  out: Dict[str, Histogram] = {}
  for kind, durs in span_durations(events).items():
    h = out.setdefault(kind, Histogram(kind))
    for d in durs:
      h.add(d)
  return out


def histograms_from_trace(path: str) -> Dict[str, Histogram]:
  """Per-kind histograms rebuilt from a JSONL trace's span.end
  durations."""
  return histograms_from_events(load_events(path))


def _fmt_secs(s: float) -> str:
  if s >= 1.0:
    return f'{s:.3f}s'
  if s >= 1e-3:
    return f'{s * 1e3:.1f}ms'
  return f'{s * 1e6:.0f}us'


def format_table(hists: Dict[str, Histogram],
                 baseline: Optional[Dict[str, Histogram]] = None
                 ) -> str:
  """Render the per-stage latency table (largest total time first).
  With ``baseline``, adds the Δmean% column (positive = slower)."""
  header = ['stage', 'count', 'total_s', 'mean', 'p50', 'p90', 'p99']
  if baseline is not None:
    header.append('Δmean%')
  rows: List[List[str]] = []
  for kind in sorted(hists, key=lambda k: -hists[k].secs):
    h = hists[kind]
    row = [kind, f'{int(h.count)}', f'{h.secs:.3f}',
           _fmt_secs(h.mean), _fmt_secs(h.quantile(0.5)),
           _fmt_secs(h.quantile(0.9)), _fmt_secs(h.quantile(0.99))]
    if baseline is not None:
      b = baseline.get(kind)
      if b is not None and b.count and b.mean > 0:
        row.append(f'{100.0 * (h.mean / b.mean - 1.0):+.1f}')
      else:
        row.append('new')
    rows.append(row)
  if baseline is not None:
    for kind in sorted(set(baseline) - set(hists)):
      rows.append([kind, '0', '0.000', '-', '-', '-', '-', 'gone'])
  widths = [max(len(header[i]), *(len(r[i]) for r in rows))
            if rows else len(header[i]) for i in range(len(header))]
  lines = ['  '.join(h.ljust(w) if i == 0 else h.rjust(w)
                     for i, (h, w) in enumerate(zip(header, widths)))]
  for r in rows:
    lines.append('  '.join(c.ljust(w) if i == 0 else c.rjust(w)
                           for i, (c, w) in enumerate(zip(r, widths))))
  return '\n'.join(lines)


#: resilience/durability event kinds the report CLI counts next to the
#: latency table (ISSUE 6 satellite: until now these were only visible
#: by grepping the raw JSONL).  kind -> the field used for the
#: per-bucket breakdown column ('' = none).
RESILIENCE_KINDS = (
    ('rpc.retry', 'op'),
    ('peer.lost', 'peer_kind'),
    ('fault.injected', 'site'),
    ('producer.restart', 'worker'),
    ('snapshot.save', 'ok'),
    ('snapshot.restore', 'dir'),
    ('mesh.stall', 'scope'),
    ('slo.burn', 'window_secs'),
    ('recorder.overflow', ''),
    ('postmortem.dump', 'reason'),
    # streaming ingestion (ISSUE 14): WAL replays, torn-tail
    # truncations, apply/compact faults and compactions read out of
    # the same table as the retries and restarts around them
    ('ingest.replay', 'restored'),
    ('ingest.wal_truncate', ''),
    ('ingest.fault', 'site'),
    ('ingest.compact', 'ok'),
)


def resilience_counts(events) -> List[List[str]]:
  """``[kind, count, breakdown]`` rows for every resilience kind
  present in the trace (absent kinds are omitted — a clean run prints
  no table at all)."""
  rows: List[List[str]] = []
  for kind, field in RESILIENCE_KINDS:
    evs = [e for e in events if e.get('kind') == kind]
    if not evs:
      continue
    breakdown = ''
    if field:
      by: Dict[str, int] = {}
      for e in evs:
        key = str(e.get(field))
        by[key] = by.get(key, 0) + 1
      breakdown = ', '.join(f'{k}={v}' for k, v in sorted(by.items()))
    rows.append([kind, str(len(evs)), breakdown])
  return rows


def format_resilience_table(events) -> str:
  """Render the resilience-event count table ('' when the trace holds
  none)."""
  rows = resilience_counts(events)
  if not rows:
    return ''
  header = ['event', 'count', 'breakdown']
  widths = [max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(3)]
  lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
  for r in rows:
    lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
  return '\n'.join(lines)


def nearest_rank(sorted_vals, p: float):
  """Nearest-rank quantile over PRE-SORTED values (``None`` on
  empty).  ONE definition shared by this report CLI and
  `benchmarks/bench_serving.py`, so the bench's regression-guarded
  p99 and the trace report's p99 can never silently diverge."""
  if not sorted_vals:
    return None
  i = min(int(p * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
  return sorted_vals[i]


def serving_percentiles(events) -> Dict[str, Dict]:
  """Per-bucket serving latency percentiles from ``serving.request``
  events (EXACT quantiles over the raw ``latency_ms`` values — the
  serving SLO numbers deserve better than the 2x log2 envelope), plus
  an ``all`` row and the shed counts by reason.  ``{}`` when the
  trace holds no serving traffic."""
  lat: Dict[str, List[float]] = {}
  for e in events:
    if e.get('kind') != 'serving.request' or not e.get('ok', True):
      continue
    v = e.get('latency_ms')
    if v is None:
      continue
    lat.setdefault(str(e.get('bucket', '?')), []).append(float(v))
    lat.setdefault('all', []).append(float(v))
  if not lat:
    return {}
  out: Dict[str, Dict] = {}
  for bucket, vals in lat.items():
    vals = sorted(vals)
    out[bucket] = {'count': len(vals),
                   'p50_ms': nearest_rank(vals, 0.5),
                   'p95_ms': nearest_rank(vals, 0.95),
                   'p99_ms': nearest_rank(vals, 0.99),
                   'max_ms': vals[-1]}
  shed: Dict[str, int] = {}
  for e in events:
    if e.get('kind') == 'serving.shed':
      r = str(e.get('reason'))
      shed[r] = shed.get(r, 0) + 1
  if shed:
    out['shed'] = shed
  return out


def format_serving_table(events) -> str:
  """Render the serving percentile table ('' when the trace holds no
  serving.request events)."""
  pct = serving_percentiles(events)
  if not pct:
    return ''
  shed = pct.pop('shed', {})
  header = ['bucket', 'count', 'p50_ms', 'p95_ms', 'p99_ms', 'max_ms']
  rows = []
  # 'all' first, then buckets in NUMERIC ladder order (keys are
  # stringified capacities — a lexicographic sort puts 16 before 2)
  for bucket in sorted(pct, key=lambda b: (b != 'all',
                                           int(b) if b.isdigit() else 0,
                                           b)):
    r = pct[bucket]
    rows.append([bucket, str(r['count']),
                 f"{r['p50_ms']:.2f}", f"{r['p95_ms']:.2f}",
                 f"{r['p99_ms']:.2f}", f"{r['max_ms']:.2f}"])
  widths = [max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(len(header))]
  lines = ['  '.join(h.ljust(w) if i == 0 else h.rjust(w)
                     for i, (h, w) in enumerate(zip(header, widths)))]
  for r in rows:
    lines.append('  '.join(c.ljust(w) if i == 0 else c.rjust(w)
                           for i, (c, w) in enumerate(zip(r, widths))))
  if shed:
    lines.append('shed: ' + ', '.join(f'{k}={v}'
                                      for k, v in sorted(shed.items())))
  return '\n'.join(lines)


def spans_in_flight(events: List[Dict],
                    at_mono: Optional[float] = None) -> List[Dict]:
  """Spans whose ``span.begin`` has no matching ``span.end`` in the
  event window — at a post-mortem dump, the operations still in
  flight when the process died (the first thing an operator asks).
  Returns ``[{name, span_id, pid, age_s}]`` oldest-first; ``age_s``
  needs ``at_mono`` (the bundle's dump-time monotonic clock)."""
  open_spans: Dict[tuple, Dict] = {}
  for e in events:
    sid = (e.get('pid'), e.get('span_id'))
    if e.get('kind') == 'span.begin':
      open_spans[sid] = e
    elif e.get('kind') == 'span.end':
      open_spans.pop(sid, None)
  out = []
  for (pid, sid), e in open_spans.items():
    row = {'name': e.get('name'), 'span_id': sid, 'pid': pid}
    if at_mono is not None and e.get('mono') is not None:
      row['age_s'] = round(at_mono - float(e['mono']), 3)
    out.append(row)
  out.sort(key=lambda r: -(r.get('age_s') or 0))
  return out


def final_window_counts(events: List[Dict], at_mono: float,
                        window_s: float = 60.0) -> List[List[str]]:
  """``[kind, last_window, total]`` rows — what ACCELERATED into the
  crash vs the whole ring (a kind whose count concentrates in the
  final window is the trajectory of the incident)."""
  total: Dict[str, int] = {}
  recent: Dict[str, int] = {}
  horizon = at_mono - window_s
  for e in events:
    k = str(e.get('kind'))
    total[k] = total.get(k, 0) + 1
    if float(e.get('mono') or 0.0) >= horizon:
      recent[k] = recent.get(k, 0) + 1
  return [[k, str(recent.get(k, 0)), str(total[k])]
          for k in sorted(total, key=lambda k: -recent.get(k, 0))]


def _kv_table(rows: List[List[str]], header: List[str]) -> str:
  if not rows:
    return ''
  widths = [max(len(header[i]), *(len(r[i]) for r in rows))
            for i in range(len(header))]
  lines = ['  '.join(h.ljust(w) if i == 0 else h.rjust(w)
                     for i, (h, w) in enumerate(zip(header, widths)))]
  for r in rows:
    lines.append('  '.join(c.ljust(w) if i == 0 else c.rjust(w)
                           for i, (c, w) in enumerate(zip(r, widths))))
  return '\n'.join(lines)


def format_serving_health(block: Dict) -> str:
  """Render a heartbeat/healthz serving block (queue, executor,
  per-bucket compile status, SLO windows) as indented lines."""
  lines = []
  for key in ('healthy', 'executor_alive', 'queue_depth', 'max_queue',
              'in_flight', 'admitted', 'served_requests',
              'dispatches', 'failed', 'max_wait_ms'):
    if key in block:
      lines.append(f'  {key}: {block[key]}')
  shed = block.get('shed')
  if isinstance(shed, dict):
    lines.append('  shed: ' + ', '.join(
        f'{k}={v}' for k, v in sorted(shed.items())))
  cs = block.get('compile_status') or {}
  if cs.get('buckets'):
    lines.append('  buckets: ' + ', '.join(
        f'{c}={"warm" if w else "COLD"}'
        for c, w in sorted(cs['buckets'].items(),
                           key=lambda kv: int(kv[0]))))
  slo = block.get('slo') or {}
  for w in slo.get('windows', []):
    lines.append(
        f"  slo[{int(w['window_secs'])}s]: count={w['count']} "
        f"p50={w['p50_ms']}ms p99={w['p99_ms']}ms qps={w['qps']} "
        f"burn={w['burn_rate']}"
        + (f" (target p99 {slo['p99_target_ms']}ms)"
           if slo.get('p99_target_ms') else ''))
  return '\n'.join(lines)


def load_varz_snapshot(path: str) -> Optional[Dict]:
  """Load ``path`` if it is a ``/varz`` JSON snapshot (a single JSON
  object with a ``metrics`` dict); None when it is anything else
  (e.g. a recorder JSONL trace)."""
  try:
    with open(path) as f:
      obj = json.load(f)
  except (OSError, ValueError):
    return None
  if isinstance(obj, dict) and isinstance(obj.get('metrics'), dict):
    return obj
  return None


def format_varz_diff(cur: Dict, base: Dict) -> str:
  """Two-``/varz``-snapshot delta table: every key whose value
  changed (plus appeared/removed keys), with Δ and Δ/s over the
  snapshots' wall-clock gap.  Flat-encoded histogram bucket keys are
  rolled up to their ``count``/``secs`` totals to keep the table
  readable."""
  from . import histogram as _hist
  cm, bm = dict(cur['metrics']), dict(base['metrics'])
  dt = float(cur.get('ts', 0)) - float(base.get('ts', 0))
  for snap in (cm, bm):
    for k in [k for k in snap if _hist.HIST_SEP in k]:
      tail = k.rsplit(_hist.HIST_SEP, 1)[1]
      if tail.startswith('b'):
        snap.pop(k)
  rows = []
  for key in sorted(set(cm) | set(bm)):
    b, c = bm.get(key), cm.get(key)
    if b == c:
      continue
    d = (float(c) - float(b)) if (b is not None and c is not None) \
        else None
    rows.append([key,
                 '-' if b is None else f'{float(b):g}',
                 '-' if c is None else f'{float(c):g}',
                 '-' if d is None else f'{d:+g}',
                 '-' if d is None or dt <= 0 else f'{d / dt:.3g}'])
  head = (f"# /varz diff: pid {base.get('pid')} @ {base.get('ts')} -> "
          f"pid {cur.get('pid')} @ {cur.get('ts')} "
          f"({dt:.1f}s apart)")
  if not rows:
    return head + '\n(no changed keys)'
  return head + '\n' + _kv_table(
      rows, ['key', 'baseline', 'current', 'Δ', 'Δ/s'])


def _fmt_bytes(n: float) -> str:
  for unit in ('B', 'KB', 'MB', 'GB'):
    if abs(n) < 1024 or unit == 'GB':
      return f'{n:.0f}{unit}' if unit == 'B' else f'{n:.1f}{unit}'
    n /= 1024.0
  return f'{n:.1f}GB'


def find_attribution(path: str):
  """Locate an attribution block in ``path``: the
  `attribution_stats` dict itself, an envelope row carrying
  ``attribution``, or a records JSONL holding such rows (the
  highest-P row wins).  Returns ``(stats, layouts_or_None)``."""
  def from_obj(obj):
    if not isinstance(obj, dict):
      return None
    if 'bytes_matrix' in obj:
      return obj, None
    att = obj.get('attribution')
    if isinstance(att, dict) and 'bytes_matrix' in att:
      return att, obj.get('layouts')
    return None
  try:
    with open(path) as f:
      found = from_obj(json.load(f))
    if found:
      return found
  except ValueError:
    pass
  best, best_p = None, -1
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        hit = from_obj(json.loads(line))
      except ValueError:
        continue
      if hit and int(hit[0].get('num_parts', 0)) > best_p:
        best, best_p = hit, int(hit[0].get('num_parts', 0))
  if best is None:
    raise SystemExit(f'no attribution block found in {path!r} — '
                     'expected attribution_stats JSON, an envelope '
                     'row with "attribution", or a records JSONL')
  return best


def format_attribution(stats: Dict,
                       layouts: Optional[Dict] = None) -> str:
  """Render one attribution block: locality summary, the P×P
  src-device → dst-range byte matrix, the layout padding-waste
  comparison (when present), and the hot-range table."""
  p = int(stats.get('num_parts', 0))
  out = [f"# traffic attribution (P={p}, "
         f"feature_row_bytes={stats.get('feature_row_bytes')})"]
  out.append(
      f"  ids: local={stats.get('local_ids')} "
      f"cross={stats.get('cross_ids')} "
      f"cross_frac={stats.get('cross_partition_ids_frac')}")
  out.append(
      f"  bytes: total={_fmt_bytes(float(stats.get('total_bytes', 0)))} "
      f"cross={_fmt_bytes(float(stats.get('cross_partition_bytes', 0)))} "
      f"cross_frac={stats.get('cross_partition_bytes_frac')}")
  mat = stats.get('bytes_matrix') or []
  if mat:
    out.append('# bytes by (src device -> dst range); '
               'diagonal = partition-local')
    rows = [[f'src{i}'] + [_fmt_bytes(float(v)) for v in r]
            for i, r in enumerate(mat)]
    out.append(_kv_table(rows, ['', *(f'r{j}' for j in
                                      range(len(mat[0])))]))
  if layouts:
    out.append('# padding waste by exchange layout (same static '
               'slack, one epoch each)')
    lrows = [[name,
              f"{blk.get('padding_waste_pct', '-')}",
              f"{blk.get('drop_rate_pct', '-')}",
              f"{blk.get('frontier_slots', '-')}",
              f"{blk.get('frontier_offered', '-')}"]
             for name, blk in sorted(layouts.items())]
    out.append(_kv_table(lrows, ['layout', 'waste_pct', 'drop_pct',
                                 'slots', 'offered']))
  hot = stats.get('hot_ranges') or []
  if hot:
    out.append(f"# hot ranges (top-{stats.get('top_k')}, "
               f"source={stats.get('hotness_source')}, "
               f"coverage={stats.get('hot_range_coverage')})")
    hrows = [[f"r{h['partition']}", f"{100.0 * h['share']:.1f}%"]
             for h in hot]
    out.append(_kv_table(hrows, ['range', 'share']))
  return '\n'.join(out)


_SPARK = ' ._-=+*#%@'


def _sparkline(vals: List[float], width: int = 48) -> str:
  """Coarse ASCII sparkline (min-max normalized, downsampled to
  ``width`` columns) — enough to see a burn-rate ramp or a queue
  flood in a terminal post-mortem."""
  if not vals:
    return ''
  if len(vals) > width:
    step = len(vals) / width
    vals = [vals[int(i * step)] for i in range(width)]
  lo, hi = min(vals), max(vals)
  if hi <= lo:
    return _SPARK[1] * len(vals)
  scale = (len(_SPARK) - 1) / (hi - lo)
  return ''.join(_SPARK[int((v - lo) * scale)] for v in vals)


def format_timeseries(block: Dict) -> str:
  """Render a `TimeSeriesStore.query` block (as attached to
  post-mortem bundles): per-series span, last/min/max and a
  sparkline — the "what was trending when it died" view."""
  series = block.get('series') or {}
  if not series:
    return ''
  out = [f"# time-series rings ({block.get('cadence_ms')}ms cadence, "
         f"{block.get('retention_s')}s retention)"]
  for key in sorted(series):
    s = series[key]
    pts = s.get('points') or []
    if not pts:
      continue
    vals = [float(v) for _, v in pts]
    span = float(pts[-1][0]) - float(pts[0][0])
    out.append(f"  {key} [{s.get('kind')}] n={len(pts)} "
               f"span={span:.0f}s last={vals[-1]:g} "
               f"min={min(vals):g} max={max(vals):g}")
    out.append(f'    |{_sparkline(vals)}|')
  return '\n'.join(out)


def render_postmortem(bundle: Dict) -> str:
  """The ``--postmortem`` view of one bundle: what died, what was in
  flight, what accelerated into the final window, the resilience /
  serving tables over the captured ring, supervision state, and the
  SLO gauge values at dump time."""
  import datetime
  events = bundle.get('events', [])
  out: List[str] = []
  when = datetime.datetime.fromtimestamp(
      bundle.get('ts', 0)).isoformat(timespec='seconds')
  out.append(f"# post-mortem: {bundle.get('reason')} @ {when} "
             f"(pid {bundle.get('pid')}, {len(events)} ring events)")
  err = bundle.get('error')
  if err:
    detail = ', '.join(f'{k}={v}' for k, v in sorted(err.items())
                       if k not in ('type', 'message'))
    out.append(f"error: {err.get('type')}: {err.get('message')}"
               + (f'  [{detail}]' if detail else ''))
  if bundle.get('extra'):
    out.append('context: ' + ', '.join(
        f'{k}={v}' for k, v in sorted(bundle['extra'].items())))
  inflight = spans_in_flight(events, at_mono=bundle.get('mono'))
  out.append('# spans in flight at dump'
             + (' (none)' if not inflight else ''))
  for row in inflight[:20]:
    age = f" open {row['age_s']}s" if row.get('age_s') is not None \
        else ''
    out.append(f"  {row['name']}  pid={row['pid']}{age}")
  if bundle.get('mono') is not None and events:
    out.append('# event counts, final 60s window vs whole ring')
    out.append(_kv_table(
        final_window_counts(events, float(bundle['mono'])),
        ['kind', 'last_60s', 'total']))
  res = format_resilience_table(events)
  if res:
    out.append('# resilience events')
    out.append(res)
  srv = format_serving_table(events)
  if srv:
    out.append('# serving request latency percentiles')
    out.append(srv)
  health = bundle.get('health') or {}
  comps = health.get('components') or {}
  if comps:
    out.append(f"# health at dump (ok={health.get('ok')})")
    for name, block in sorted(comps.items()):
      out.append(f'{name}:')
      if name == 'serving':
        out.append(format_serving_health(block))
      else:
        for k, v in sorted(block.items()):
          if k == 'producers' and isinstance(v, dict):
            for pid, p in sorted(v.items()):
              out.append(f'  producer {pid}: ' + ', '.join(
                  f'{kk}={vv}' for kk, vv in sorted(p.items())))
          else:
            out.append(f'  {k}: {v}')
  metrics_snap = bundle.get('metrics') or {}
  slo_keys = sorted(k for k in metrics_snap
                    if k.startswith('serving.slo.'))
  if slo_keys:
    out.append('# SLO gauges at dump')
    for k in slo_keys:
      out.append(f'  {k}: {metrics_snap[k]}')
  # streaming ingestion block (ISSUE 14): the WAL/apply/version state
  # of a process that died mid-ingest — the first thing the operator
  # asks after an ingestion fault bundle
  ingest_keys = sorted(k for k in metrics_snap
                       if k.startswith('ingest.')
                       or k.startswith('graph.version'))
  if ingest_keys:
    out.append('# ingestion at dump')
    for k in ingest_keys:
      out.append(f'  {k}: {metrics_snap[k]}')
  ts_block = bundle.get('timeseries')
  if isinstance(ts_block, dict):
    ts = format_timeseries(ts_block)
    if ts:
      out.append(ts)
  elif bundle.get('timeseries_error'):
    out.append('note: time-series rings unavailable: '
               + str(bundle['timeseries_error']))
  hists = histograms_from_events(events)
  if hists:
    out.append('# per-stage span latencies (captured ring)')
    out.append(format_table(hists))
  rec = bundle.get('recorder') or {}
  if rec.get('ring_dropped'):
    out.append(f"note: the ring dropped {rec['ring_dropped']} "
               'event(s) before the dump — this window is partial '
               '(raise GLT_TELEMETRY_EVENTS)')
  return '\n'.join(out)


_BUCKET_RE = re.compile(
    r'^(?P<name>[A-Za-z_:][\w:]*)_bucket\{(?P<labels>[^}]*)\}\s')


def format_exemplars(text: str) -> str:
  """The p99→trace jump (ISSUE 17): for each histogram family in a
  saved ``/metrics`` exposition, the HIGHEST bucket carrying an
  OpenMetrics exemplar — its trace id is a retained trace of a
  request that LANDED in that bucket, fetchable at
  ``/trace?trace_id=<id>`` (``&format=chrome`` for Perfetto)."""
  from .live import split_exemplar
  best: Dict[str, tuple] = {}
  for line in text.splitlines():
    sample, ex = split_exemplar(line)
    if ex is None:
      continue
    m = _BUCKET_RE.match(sample.strip())
    if m is None:
      continue
    labels = m.group('labels')
    le_m = re.search(r'le="([^"]+)"', labels)
    le = le_m.group(1) if le_m else '+Inf'
    le_v = float('inf') if le == '+Inf' else float(le)
    tid_m = re.search(r'trace_id="([^"]+)"', ex)
    if tid_m is None:
      continue
    rest = ','.join(kv for kv in labels.split(',')
                    if not kv.startswith('le=') and kv)
    key = m.group('name') + (f'{{{rest}}}' if rest else '')
    if key not in best or le_v > best[key][0]:
      best[key] = (le_v, le, tid_m.group(1))
  if not best:
    return ''
  rows = [[key, le, tid, f'/trace?trace_id={tid}']
          for key, (_, le, tid) in sorted(best.items())]
  return _kv_table(rows, ['histogram', 'top bucket le',
                          'exemplar trace', 'fetch'])


def histograms_from_metrics_json(path: str) -> Dict[str, Histogram]:
  """Decode a `gather_metrics` dump (the ``aggregate`` dict, or the
  whole result object) into merged histograms."""
  with open(path) as f:
    obj = json.load(f)
  if isinstance(obj, dict) and isinstance(obj.get('aggregate'), dict):
    obj = obj['aggregate']
  return from_snapshot(obj)


def main(argv: Optional[List[str]] = None) -> int:
  ap = argparse.ArgumentParser(
      prog='python -m graphlearn_tpu.telemetry.report',
      description='Per-stage latency report over a flight-recorder '
                  'trace (and optional trace diff / Chrome export).')
  ap.add_argument('trace', nargs='?',
                  help='recorder JSONL (GLT_TELEMETRY_JSONL output)')
  ap.add_argument('--diff', metavar='BASELINE_JSONL',
                  help='second trace to diff against (Δmean%% column)')
  ap.add_argument('--chrome', metavar='OUT_JSON',
                  help='also write a Perfetto-loadable Chrome trace')
  ap.add_argument('--metrics-json', metavar='FILE',
                  help='print merged histograms from a gather_metrics '
                       'aggregate dump instead of a JSONL trace')
  ap.add_argument('--postmortem', metavar='BUNDLE',
                  help='render a post-mortem bundle '
                       '(GLT_POSTMORTEM_DIR output): spans in flight '
                       'at dump, final-window event deltas, '
                       'resilience/serving tables, supervision state')
  ap.add_argument('--attribution', metavar='FILE',
                  help='render per-partition traffic attribution '
                       '(attribution_stats JSON, a bench envelope '
                       'row, or a records JSONL): P×P byte matrix, '
                       'padding-waste-by-layout, hot-range table')
  ap.add_argument('--exemplars', metavar='METRICS_TXT',
                  help='render the p99→trace jump table from a '
                       'saved /metrics exposition: per histogram, '
                       'the top exemplar-carrying bucket and its '
                       '/trace?trace_id= fetch')
  args = ap.parse_args(argv)
  if args.exemplars:
    with open(args.exemplars) as f:
      table = format_exemplars(f.read())
    print('# exemplar → trace jumps '
          f'({args.exemplars})')
    print(table if table else
          '(no exemplars in the exposition — tracing off, or no '
          'traced request has landed in any bucket yet)')
    return 0
  if args.postmortem:
    from .postmortem import load_bundle
    print(render_postmortem(load_bundle(args.postmortem)))
    return 0
  if args.attribution:
    stats, layouts = find_attribution(args.attribution)
    print(format_attribution(stats, layouts))
    return 0
  if not args.trace and not args.metrics_json:
    ap.error('need a TRACE.jsonl, --metrics-json FILE, '
             '--attribution FILE, or --postmortem BUNDLE')
  if args.metrics_json:
    hists = histograms_from_metrics_json(args.metrics_json)
    print(f'# merged cross-host histograms ({args.metrics_json})')
    print(format_table(hists))
    if not args.trace:
      if args.chrome or args.diff:
        ap.error('--chrome/--diff need a TRACE.jsonl positional '
                 'argument (a metrics aggregate has no events to '
                 'export or diff)')
      return 0
  if args.trace and args.diff:
    cur_varz = load_varz_snapshot(args.trace)
    base_varz = load_varz_snapshot(args.diff)
    if cur_varz is not None and base_varz is not None:
      print(format_varz_diff(cur_varz, base_varz))
      return 0
    if (cur_varz is None) != (base_varz is None):
      ap.error('--diff mixes a /varz JSON snapshot with a JSONL '
               'trace — both sides must be the same kind')
  events = load_events(args.trace)
  hists = histograms_from_events(events)
  base = histograms_from_trace(args.diff) if args.diff else None
  print(f'# per-stage span latencies ({args.trace})'
        + (f' vs {args.diff}' if args.diff else ''))
  if not hists:
    print('(no span.end events in trace — was the recorder on and '
          'the pipeline span-instrumented?)')
  else:
    print(format_table(hists, baseline=base))
  res = format_resilience_table(events)
  if res:
    print('# resilience events (retries, faults, snapshots, stalls)')
    print(res)
  srv = format_serving_table(events)
  if srv:
    print('# serving request latency percentiles (serving.request '
          'events; exact quantiles, not log2 buckets)')
    print(srv)
  if args.chrome:
    n = write_chrome_trace(args.trace, args.chrome)
    print(f'# wrote {n} trace events -> {args.chrome} '
          '(open in https://ui.perfetto.dev)')
  return 0


if __name__ == '__main__':
  sys.exit(main())
