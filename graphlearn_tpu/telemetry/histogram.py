"""Fixed-bucket log2 latency histograms, mergeable across hosts.

One histogram per span kind: bucket ``b`` counts latencies in
``[2^(b-1), 2^b)`` microseconds (bucket 0 is the sub-microsecond
underflow, the last bucket absorbs overflow).  The bucket count is
FIXED (`NUM_BUCKETS`) so two histograms always align, and the encoding
is a flat ``{key: count}`` dict in the global `Metrics` registry
(``span.<kind>.hist.b<ii>`` + ``.count`` / ``.secs``) — which makes the
cross-host merge free: :func:`~graphlearn_tpu.telemetry.aggregate.
gather_metrics` already sums snapshots per key, so
``gather_metrics(prefix='span.')['aggregate']`` IS the mesh-wide
histogram set; :func:`from_snapshot` decodes it back into `Histogram`
objects for the report CLI.

Recording costs two dict increments and a bit_length — cheap enough
for the per-batch host path (`spans.span` only records when the flight
recorder is on).
"""
from __future__ import annotations

from typing import Dict, List, Optional

#: fixed bucket count: bucket 27's upper edge is 2^27 us ~ 134 s,
#: beyond any per-batch stage this layer times; longer spans land in
#: the overflow bucket (quantiles then report its upper edge, ~134 s).
NUM_BUCKETS = 28

#: metric-key layout (the wire format of the cross-host merge)
KEY_PREFIX = 'span.'
HIST_SEP = '.hist.'


def bucket_index(secs: float) -> int:
  """Log2 bucket of a latency: 0 for < 1 us, else
  ``floor(log2(us)) + 1``, clamped to the fixed bucket range."""
  us = int(secs * 1e6)
  if us <= 0:
    return 0
  return min(us.bit_length(), NUM_BUCKETS - 1)


def bucket_upper_edge_secs(idx: int) -> float:
  """Upper edge of bucket ``idx`` in seconds (2^idx microseconds)."""
  return (1 << idx) / 1e6


def record(kind: str, secs: float, registry=None) -> None:
  """Tick one latency into ``kind``'s histogram in the metrics
  registry (the global one by default).  The three keys of one
  observation go through ``inc_many`` (one lock acquisition) so a
  concurrent snapshot — the live ops scrape — can never see a torn
  histogram (``count != sum(buckets)``)."""
  if registry is None:
    from ..utils.profiling import metrics
    registry = metrics
  base = f'{KEY_PREFIX}{kind}{HIST_SEP}'
  pairs = ((f'{base}b{bucket_index(secs):02d}', 1.0),
           (f'{base}count', 1.0), (f'{base}secs', secs))
  inc_many = getattr(registry, 'inc_many', None)
  if inc_many is not None:
    inc_many(pairs)
  else:                           # bare-Metrics lookalikes in tests
    for k, v in pairs:
      registry.inc(k, v)


class Histogram:
  """Decoded per-kind latency histogram (counts + total seconds)."""

  def __init__(self, kind: str,
               buckets: Optional[List[float]] = None,
               count: float = 0.0, secs: float = 0.0):
    self.kind = kind
    self.buckets = list(buckets) if buckets else [0.0] * NUM_BUCKETS
    if len(self.buckets) != NUM_BUCKETS:
      self.buckets += [0.0] * (NUM_BUCKETS - len(self.buckets))
    self.count = count
    self.secs = secs

  def add(self, secs: float) -> None:
    self.buckets[bucket_index(secs)] += 1
    self.count += 1
    self.secs += secs

  def merge(self, other: 'Histogram') -> 'Histogram':
    """Element-wise sum (the same op `gather_metrics` performs on the
    flat encoding) — histograms merge exactly, unlike quantiles."""
    for i, c in enumerate(other.buckets):
      self.buckets[i] += c
    self.count += other.count
    self.secs += other.secs
    return self

  def quantile(self, q: float) -> float:
    """Approximate quantile in seconds: the upper edge of the bucket
    where the cumulative count crosses ``q * count`` (log2-bounded
    error — a 2x envelope, which is what stage attribution needs)."""
    if self.count <= 0:
      return 0.0
    target = q * self.count
    run = 0.0
    for i, c in enumerate(self.buckets):
      run += c
      if run >= target:
        return bucket_upper_edge_secs(i)
    return bucket_upper_edge_secs(NUM_BUCKETS - 1)

  @property
  def mean(self) -> float:
    return self.secs / self.count if self.count else 0.0

  def to_flat(self) -> Dict[str, float]:
    """Flat ``{metric_key: value}`` encoding (inverse of
    :func:`from_snapshot`)."""
    base = f'{KEY_PREFIX}{self.kind}{HIST_SEP}'
    out = {f'{base}b{i:02d}': c
           for i, c in enumerate(self.buckets) if c}
    out[f'{base}count'] = self.count
    out[f'{base}secs'] = self.secs
    return out


def from_snapshot(snap: Dict[str, float]) -> Dict[str, Histogram]:
  """Decode a metrics snapshot (or a `gather_metrics` ``aggregate``
  dict) into ``{kind: Histogram}``.  Keys not matching the
  ``span.<kind>.hist.*`` layout are ignored, so the full registry
  snapshot can be passed as-is."""
  out: Dict[str, Histogram] = {}
  for key, val in snap.items():
    if not key.startswith(KEY_PREFIX) or HIST_SEP not in key:
      continue
    head, leaf = key.rsplit(HIST_SEP, 1)
    kind = head[len(KEY_PREFIX):]
    h = out.setdefault(kind, Histogram(kind))
    if leaf == 'count':
      h.count = val
    elif leaf == 'secs':
      h.secs = val
    elif leaf.startswith('b'):
      try:
        idx = int(leaf[1:])
      except ValueError:
        continue
      if 0 <= idx < NUM_BUCKETS:
        h.buckets[idx] = val
  return out
