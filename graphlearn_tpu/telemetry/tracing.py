"""Request-scoped tail-sampled tracing — the serve plane's causality.

The span layer (`telemetry.spans`) answers "what did this PROCESS do"
— its context rides channel frames but dies at the serve RPC boundary,
and the frontend opens one ``serving.infer`` span per COALESCED run,
not per request.  This module adds the request axis (Dapper-style):

  * `Tracer.mint` creates a trace context at the FleetRouter — a tiny
    dict ``{'t': trace_id, 's': parent_span_id, 'k': sampled}`` that
    rides the serve RPC as a plain keyword argument (the same
    discipline as the channel ``'#SPAN'`` header), so every process a
    request crosses attributes its work to the same trace.
  * `Tracer.span` records one COMPLETED span (explicit start/duration
    — no context-vars, no clock mixing: callers time with
    ``time.monotonic()`` and hand over ``t0``/``dur``).  Spans buffer
    per trace until the request resolves.
  * `Tracer.resolve` applies TAIL-BASED retention: the finished
    request's spans are kept only when the request was slow
    (``GLT_TRACE_SLOW_MS``, default = the serving SLO p99), failed or
    shed, or head-sampled 1-in-N (``GLT_TRACE_SAMPLE``; the sampled
    bit is minted once and rides the context, so every process keeps
    the same traces).  Retained trees live in a bounded ring
    (``GLT_TRACE_BUFFER``) served at ``/traces`` + ``/trace?trace_id=``
    by the ops endpoint; `FleetScraper.fetch_trace` reassembles one
    request's spans across replicas into a Perfetto-loadable trace.

``GLT_TRACE_SAMPLE=0`` (the default) disables minting entirely:
`mint` returns None, every `span`/`resolve` on a None context is a
single falsy check, and the data plane is byte-identical.

Resolution is an idempotent MERGE: both the router (root span) and
the serving frontend (child spans) resolve the same trace_id — in an
in-process fleet they share this process-global tracer, so whichever
side resolves second appends its spans to the already-retained tree
instead of double-counting a retention.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

TRACE_SAMPLE_ENV = 'GLT_TRACE_SAMPLE'
TRACE_SLOW_MS_ENV = 'GLT_TRACE_SLOW_MS'
TRACE_BUFFER_ENV = 'GLT_TRACE_BUFFER'

#: retained-trace ring size (completed trees kept for /trace fetches)
DEFAULT_BUFFER = 256

#: unresolved-trace bound: a trace whose resolve never arrives (a
#: crashed router mid-request) must not pin spans forever
_MAX_PENDING = 1024

#: per-trace span bound — a runaway instrumentation loop must not
#: grow one tree without limit
_MAX_SPANS = 512


def _env_int(name: str, default: int) -> int:
  try:
    return int(float(os.environ.get(name, '') or default))
  except ValueError:
    return default


def _env_float(name: str) -> Optional[float]:
  raw = os.environ.get(name)
  if raw is None or raw == '':
    return None
  try:
    return float(raw)
  except ValueError:
    return None


def _new_id() -> str:
  return os.urandom(8).hex()


def child_ctx(ctx: Optional[dict], span_id: str) -> Optional[dict]:
  """A context whose spans parent under ``span_id`` (same trace, same
  sampled bit)."""
  if not ctx:
    return None
  return {'t': ctx['t'], 's': span_id, 'k': ctx.get('k', 0)}


def spans_to_events(spans: List[dict]) -> List[dict]:
  """Expand completed span records into paired ``span.begin`` /
  ``span.end`` events — the encoding `telemetry.export.to_chrome_trace`
  already pairs into balanced ``ph:'X'`` slices."""
  events: List[dict] = []
  for s in spans:
    dur = float(s.get('dur', 0.0))
    # spans from DIFFERENT processes share no monotonic origin — the
    # events carry only wall-clock ts so the exporter aligns every
    # process on the one comparable timebase
    meta = {k: v for k, v in s.items() if k not in ('dur', 'mono')}
    begin = dict(meta)
    begin['kind'] = 'span.begin'
    end = dict(meta)
    end.update(kind='span.end', dur=dur,
               ts=float(s.get('ts', 0.0)) + dur)
    events.append(begin)
    events.append(end)
  return events


class Tracer:
  """Bounded per-process trace store with tail-based retention.

  Args:
    sample: head-sampling period N (1-in-N minted traces carry the
      keep bit; 0 = tracing OFF).  None = ``GLT_TRACE_SAMPLE``.
    slow_ms: latency threshold above which a resolved trace is
      retained regardless of sampling.  None = ``GLT_TRACE_SLOW_MS``,
      falling back to the serving SLO p99 (``GLT_SERVING_SLO_P99_MS``).
    buffer: retained-trace ring size.  None = ``GLT_TRACE_BUFFER``.
  """

  def __init__(self, sample: Optional[int] = None,
               slow_ms: Optional[float] = None,
               buffer: Optional[int] = None):
    self._lock = threading.Lock()
    self._pending: 'collections.OrderedDict[str, List[dict]]' = \
        collections.OrderedDict()
    self._retained: 'collections.OrderedDict[str, dict]' = \
        collections.OrderedDict()
    self._minted = 0
    self.configure(sample=sample, slow_ms=slow_ms, buffer=buffer)

  def configure(self, sample: Optional[int] = None,
                slow_ms: Optional[float] = None,
                buffer: Optional[int] = None) -> None:
    """(Re)apply knobs; None re-reads the environment — tests and the
    bench driver flip sampling without rebuilding the global."""
    if sample is None:
      sample = _env_int(TRACE_SAMPLE_ENV, 0)
    if slow_ms is None:
      slow_ms = _env_float(TRACE_SLOW_MS_ENV)
      if slow_ms is None:
        from .slo import slo_p99_ms_from_env
        slow_ms = slo_p99_ms_from_env()
    if buffer is None:
      buffer = _env_int(TRACE_BUFFER_ENV, DEFAULT_BUFFER)
    self.sample = max(int(sample), 0)
    self.slow_ms = max(float(slow_ms), 0.0)
    self.buffer = max(int(buffer), 1)

  @property
  def enabled(self) -> bool:
    return self.sample > 0

  # -- recording -------------------------------------------------------------
  def mint(self) -> Optional[dict]:
    """New root context, or None when tracing is off.  The 1-in-N
    head-sample bit is decided HERE and rides the context — every
    process retains the same sampled traces."""
    if self.sample <= 0:
      return None
    with self._lock:
      self._minted += 1
      k = 1 if (self._minted - 1) % self.sample == 0 else 0
    tid = _new_id()
    return {'t': tid, 's': tid, 'k': k}

  def span(self, name: str, ctx: Optional[dict], *,
           span_id: Optional[str] = None,
           parent_id: Optional[str] = None,
           t0: Optional[float] = None, dur: float = 0.0,
           error: Optional[str] = None, **fields) -> Optional[str]:
    """Record one completed span under ``ctx``'s trace.  ``t0`` is the
    span's start on the monotonic clock (None = now - dur); wall-clock
    ``ts`` is derived from it so cross-process trees line up on the
    wall timebase.  Returns the span id (pre-mint one with
    ``span_id=`` to parent children under a span recorded later)."""
    if not ctx:
      return None
    now_mono = time.monotonic()
    if t0 is None:
      t0 = now_mono - dur
    sid = span_id or _new_id()
    parent = ctx['s'] if parent_id is None else parent_id
    if parent == sid:
      parent = None                  # self-parent = the trace root
    rec = {
        'kind': 'span', 'name': name, 'trace_id': ctx['t'],
        'span_id': sid, 'parent_id': parent,
        'pid': os.getpid(), 'tid': threading.get_ident(),
        # wall-clock START derived by rebasing the monotonic span
        # window — not a duration  # glint: disable=monotonic-clock
        'ts': time.time() - (now_mono - t0), 'mono': float(t0),
        'dur': max(float(dur), 0.0),
    }
    if error is not None:
      rec['error'] = str(error)
    for k, v in fields.items():
      if v is not None:
        rec.setdefault(k, v)
    with self._lock:
      tid = ctx['t']
      entry = self._retained.get(tid)
      if entry is not None:
        # late span on an already-retained trace (the rpc wrapper
        # closing after the frontend resolved) — merge directly
        if len(entry['spans']) < _MAX_SPANS:
          entry['spans'].append(rec)
        return rec['span_id']
      spans = self._pending.get(tid)
      if spans is None:
        while len(self._pending) >= _MAX_PENDING:
          self._pending.popitem(last=False)
        spans = self._pending[tid] = []
      if len(spans) < _MAX_SPANS:
        spans.append(rec)
    return rec['span_id']

  def resolve(self, ctx: Optional[dict], outcome: str = 'ok',
              latency_ms: float = 0.0) -> bool:
    """Apply the tail-retention verdict to a finished request's trace;
    returns whether the trace is (now) retained.  Idempotent merge:
    resolving a trace that is already retained folds any newly-pending
    spans into the kept tree."""
    if not ctx:
      return False
    tid = ctx['t'] if isinstance(ctx, dict) else str(ctx)
    sampled = bool(ctx.get('k')) if isinstance(ctx, dict) else False
    keep = (outcome != 'ok' or sampled
            or (self.slow_ms > 0
                and float(latency_ms) >= self.slow_ms))
    fresh = False
    with self._lock:
      spans = self._pending.pop(tid, [])
      entry = self._retained.get(tid)
      if entry is not None:
        room = _MAX_SPANS - len(entry['spans'])
        entry['spans'].extend(spans[:max(room, 0)])
        if outcome != 'ok' and entry['outcome'] == 'ok':
          entry['outcome'] = outcome
        entry['latency_ms'] = max(entry['latency_ms'],
                                  round(float(latency_ms), 3))
        return True
      if not keep:
        return False
      self._retained[tid] = {
          'trace_id': tid, 'outcome': outcome,
          'latency_ms': round(float(latency_ms), 3),
          'sampled': int(sampled), 'ts': round(time.time(), 3),
          'spans': spans,
      }
      while len(self._retained) > self.buffer:
        self._retained.popitem(last=False)
      fresh = True
    if fresh:
      from .live import live
      live.counter('serving.traces_retained_total').inc()
    return True

  # -- serving the buffer ----------------------------------------------------
  def traces(self) -> List[dict]:
    """Retained-trace index, newest first (span COUNTS, not bodies —
    the ``/traces`` listing)."""
    with self._lock:
      entries = list(self._retained.values())
    return [{'trace_id': e['trace_id'], 'outcome': e['outcome'],
             'latency_ms': e['latency_ms'], 'sampled': e['sampled'],
             'ts': e['ts'], 'spans': len(e['spans'])}
            for e in reversed(entries)]

  def spans_of(self, trace_id: str) -> List[dict]:
    """This process's retained spans for one trace (copies)."""
    with self._lock:
      entry = self._retained.get(trace_id)
      return [dict(s) for s in entry['spans']] if entry else []

  def events_of(self, trace_id: str) -> List[dict]:
    return spans_to_events(self.spans_of(trace_id))

  def stats(self) -> dict:
    with self._lock:
      return {'sample': self.sample, 'slow_ms': self.slow_ms,
              'buffer': self.buffer, 'minted': self._minted,
              'pending': len(self._pending),
              'retained': len(self._retained)}

  def clear(self) -> None:
    with self._lock:
      self._pending.clear()
      self._retained.clear()
      self._minted = 0


#: process-global tracer every serve-plane participant records into
#: (the one the ops endpoint serves at /traces)
tracer = Tracer()
