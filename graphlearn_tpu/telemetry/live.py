"""In-process live metrics registry — the pull side of the ops plane.

Everything the offline telemetry plane already measures ticks the
process-global `Metrics` counter store (`utils.profiling.metrics`):
cold-tier hit/miss, exchange padding counters, `_uncached_jit` compile
hit/miss, RPC retries, span histograms as flat ``span.<kind>.hist.*``
keys.  What was missing (ISSUE 12) is a *live surface* over that
store: a declared vocabulary, typed metric handles, gauges evaluated
at scrape time, and renderings an operator can pull DURING an
incident (`telemetry.opsserver` binds them to ``/metrics`` /
``/varz`` / ``/healthz``).

`LiveRegistry` deliberately does NOT invent a second counter store:

  * **counters** write through to the backing `Metrics` registry
    under their declared name (plus an optional ``{k=v}`` label
    suffix), so `gather_metrics`, the bench artifact and
    ``report --metrics-json`` consume them unchanged — one metrics
    vocabulary for the offline artifact, the regression gate and the
    fleet scrape.  Declaring an EXISTING key (``dist.feature.cache_hits``)
    simply exposes it on the scrape; the tick sites don't move.
  * **histograms** reuse the log2 bucket layout of
    `telemetry.histogram` (flat ``span.<name>.hist.*`` keys, recorded
    through ``Metrics.inc_many`` so a concurrent scrape can never see
    a torn bucket/count pair).
  * **gauges** are the one genuinely new kind: a stored float or a
    zero-argument callback evaluated at scrape time (queue depth,
    replay-cache occupancy, snapshot age) — point-in-time state that
    summing across restarts would corrupt, so it stays out of the
    counter store.

Every name registered here must appear in
``telemetry/schema.py::METRIC_NAMES`` with a ``'<type>: <doc>'``
value — enforced statically by the glint ``metric-name`` pass and at
runtime by strict registries (the process-global :data:`live`).

This module is import-light (no jax): the backing `Metrics` store is
bound lazily on first tick, so pure-client processes can import the
typed surface without pulling the device stack.
"""
from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import histogram as _hist
from .schema import METRIC_NAMES

#: declared-name shape: lowercase snake segments joined by dots (at
#: least two segments — a bare word collides with ad-hoc counter keys)
_NAME_RE = re.compile(r'^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$')

_KINDS = ('counter', 'gauge', 'histogram')


def flat_key(name: str, labels: Optional[Dict[str, object]] = None
             ) -> str:
  """The backing-store key of a (name, labels) metric instance:
  ``name`` or ``name{k=v,...}`` with sorted label keys — stable, so
  `gather_metrics` sums the same instance across hosts."""
  if not labels:
    return name
  inner = ','.join(f'{k}={labels[k]}' for k in sorted(labels))
  return f'{name}{{{inner}}}'


def prom_name(name: str) -> str:
  """Prometheus-legal metric family name (dots are not; the ``glt_``
  prefix namespaces the exporter)."""
  return 'glt_' + re.sub(r'[^a-zA-Z0-9_]', '_', name)


def _prom_labels(labels: Optional[Dict[str, object]],
                 extra: Optional[List[Tuple[str, str]]] = None) -> str:
  items: List[Tuple[str, str]] = []
  if labels:
    items.extend((k, str(labels[k])) for k in sorted(labels))
  if extra:
    items.extend(extra)
  if not items:
    return ''
  def esc(v: str) -> str:
    return v.replace('\\', r'\\').replace('"', r'\"').replace('\n', r'\n')
  return '{' + ','.join(f'{k}="{esc(v)}"' for k, v in items) + '}'


def _fmt(v: float) -> str:
  """Prometheus sample value: integers without a trailing .0 (half
  the consumers are humans reading curl output)."""
  f = float(v)
  return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Metric:
  __slots__ = ('registry', 'name', 'labels', 'key')

  def __init__(self, registry: 'LiveRegistry', name: str,
               labels: Optional[Dict[str, object]]):
    self.registry = registry
    self.name = name
    self.labels = dict(labels) if labels else None
    self.key = flat_key(name, labels)


class Counter(_Metric):
  """Monotone counter writing through to the backing `Metrics` store
  (so the offline aggregation/report stack sees it for free)."""

  def inc(self, value: float = 1.0) -> None:
    self.registry._backing().inc(self.key, value)

  def value(self) -> float:
    return float(self.registry._backing().snapshot().get(self.key, 0.0))


class Gauge(_Metric):
  """Point-in-time value: either ``set()`` explicitly or backed by a
  zero-argument callback evaluated at scrape time.  A callback that
  raises (or returns None) simply drops the sample from that scrape —
  a broken gauge must never break the scrape."""

  __slots__ = ('_value', '_fn')

  def __init__(self, registry, name, labels,
               fn: Optional[Callable[[], Optional[float]]] = None):
    super().__init__(registry, name, labels)
    self._value: Optional[float] = None
    self._fn = fn

  def set(self, value: float) -> None:
    self._value = float(value)

  def set_fn(self, fn: Callable[[], Optional[float]]) -> None:
    self._fn = fn

  def value(self) -> Optional[float]:
    if self._fn is not None:
      try:
        v = self._fn()
      except Exception:             # noqa: BLE001 — scrape must survive
        return None
      return None if v is None else float(v)
    return self._value


class LiveHistogram(_Metric):
  """Log2 latency histogram in the shared flat encoding
  (``span.<key>.hist.*`` in the backing store — the exact layout
  `gather_metrics` merges and ``report --metrics-json`` decodes)."""

  def observe(self, secs: float,
              exemplar: Optional[str] = None) -> None:
    """Record one sample; with ``exemplar`` (a trace_id), remember it
    as the landing bucket's last exemplar — ``/metrics`` renders it
    in OpenMetrics exemplar syntax, the sanctioned trace_id channel
    (a trace_id LABEL would mint unbounded series)."""
    _hist.record(self.key, secs, registry=self.registry._backing())
    if exemplar is not None:
      self.registry._note_exemplar(self.key, _hist.bucket_index(secs),
                                   exemplar, secs)


class LiveRegistry:
  """Thread-safe registry of declared live metrics + health providers.

  Args:
    store: backing `Metrics` counter store (None = the process-global
      one, bound lazily so importing this module stays jax-free).
    strict: validate registered names against
      ``schema.METRIC_NAMES`` (the process-global registry is strict;
      tests may build permissive private ones).

  Registration is idempotent per ``(kind, name, labels)``: the same
  call returns the same handle (a `gauge` re-registration with ``fn``
  replaces the callback — "latest instance wins" is the contract for
  per-object gauges like queue depth across frontend restarts).
  """

  def __init__(self, store=None, strict: bool = True):
    self._lock = threading.Lock()
    self._store = store
    self.strict = strict
    self._instances: Dict[Tuple[str, str], _Metric] = {}
    self._health: Dict[str, Callable[[], dict]] = {}
    #: (hist flat key, bucket index) -> (trace_id, value secs, ts) —
    #: last exemplar per bucket (bounded by buckets × instances)
    self._exemplars: Dict[Tuple[str, int],
                          Tuple[str, float, float]] = {}

  # -- backing store -------------------------------------------------------
  def _backing(self):
    if self._store is None:
      from ..utils.profiling import metrics
      self._store = metrics
    return self._store

  # -- registration --------------------------------------------------------
  def _check(self, kind: str, name: str) -> None:
    if not _NAME_RE.match(name):
      raise ValueError(
          f'live metric name {name!r} is not snake.dot '
          '(lowercase segments joined by dots)')
    if self.strict:
      doc = METRIC_NAMES.get(name)
      if doc is None:
        raise ValueError(
            f'live metric {name!r} is not declared in '
            'telemetry/schema.py::METRIC_NAMES — add it with a '
            "'<type>: <doc>' value (the glint metric-name pass "
            'enforces the same statically)')
      if not doc.startswith(f'{kind}:'):
        raise ValueError(
            f'live metric {name!r} is declared as '
            f'{doc.split(":", 1)[0]!r} but registered as {kind!r}')

  def _get(self, kind: str, name: str,
           labels: Optional[Dict[str, object]], factory) -> _Metric:
    self._check(kind, name)
    key = (kind, flat_key(name, labels))
    with self._lock:
      inst = self._instances.get(key)
      if inst is None:
        inst = self._instances[key] = factory()
      return inst

  def counter(self, name: str,
              labels: Optional[Dict[str, object]] = None) -> Counter:
    return self._get('counter', name, labels,
                     lambda: Counter(self, name, labels))

  def gauge(self, name: str,
            labels: Optional[Dict[str, object]] = None,
            fn: Optional[Callable[[], Optional[float]]] = None) -> Gauge:
    g = self._get('gauge', name, labels,
                  lambda: Gauge(self, name, labels, fn))
    if fn is not None:
      g.set_fn(fn)
    return g

  def histogram(self, name: str,
                labels: Optional[Dict[str, object]] = None
                ) -> LiveHistogram:
    return self._get('histogram', name, labels,
                     lambda: LiveHistogram(self, name, labels))

  def _note_exemplar(self, hist_key: str, bucket: int,
                     trace_id: str, value_secs: float) -> None:
    with self._lock:
      self._exemplars[(hist_key, bucket)] = (
          str(trace_id), float(value_secs), time.time())

  def exemplar_of(self, hist_key: str, bucket: int
                  ) -> Optional[Tuple[str, float, float]]:
    """The (trace_id, value_secs, ts) exemplar last recorded in one
    histogram bucket, if any — `report.py` uses it to jump from a
    p99 bucket to a captured trace."""
    with self._lock:
      return self._exemplars.get((hist_key, bucket))

  def unregister_gauge(self, name: str,
                       labels: Optional[Dict[str, object]] = None,
                       fn: Optional[Callable] = None) -> bool:
    """Drop a gauge instance so its callback stops pinning the object
    graph behind it (a shut-down frontend's admission queue, an SLO
    tracker's sample window).  With ``fn``, removes only if the
    instance still holds THAT callback — under "latest instance
    wins", a stale owner's unregister must not evict its
    replacement's gauge."""
    key = ('gauge', flat_key(name, labels))
    with self._lock:
      inst = self._instances.get(key)
      if inst is None:
        return False
      if fn is not None and inst._fn is not fn:   # type: ignore[attr-defined]
        return False
      del self._instances[key]
      return True

  # -- health providers ----------------------------------------------------
  def register_health(self, component: str,
                      fn: Callable[[], dict]) -> None:
    """Attach a health callback (dict-returning; an optional
    ``healthy`` key, default True, feeds the overall ``ok``).  Same
    name replaces — latest component instance wins."""
    with self._lock:
      self._health[component] = fn

  def unregister_health(self, component: str,
                        fn: Optional[Callable] = None) -> None:
    """Remove a health provider.  With ``fn``, removes only if the
    component still holds THAT callback — same "latest instance
    wins" guard as `unregister_gauge` (an old frontend's shutdown
    must not evict its replacement's provider)."""
    with self._lock:
      if fn is None or self._health.get(component) is fn:
        self._health.pop(component, None)

  def healthz(self) -> dict:
    """Liveness + per-component health: ``ok`` is the AND of every
    provider's ``healthy`` flag (a provider that raises reports
    unhealthy with the error, and cannot break the endpoint)."""
    with self._lock:
      providers = list(self._health.items())
    components: Dict[str, dict] = {}
    ok = True
    for name, fn in providers:
      try:
        block = dict(fn())
      except Exception as e:        # noqa: BLE001 — scrape must survive
        block = {'healthy': False, 'error': f'{type(e).__name__}: {e}'}
      healthy = bool(block.get('healthy', True))
      block['healthy'] = healthy
      ok = ok and healthy
      components[name] = block
    return {'ok': ok, 'pid': os.getpid(), 'ts': round(time.time(), 3),
            'components': components}

  def instruments(self) -> List[Tuple[str, _Metric]]:
    """``[(kind, metric), ...]`` snapshot of every registered
    instance — the declared sampling surface the time-series cadence
    loop walks (counters become rates, gauges are evaluated; see
    `telemetry.timeseries`)."""
    with self._lock:
      return [(kind, m) for (kind, _), m in self._instances.items()]

  # -- renderings ----------------------------------------------------------
  def _gauge_items(self) -> List[Tuple[Gauge, float]]:
    with self._lock:
      gauges = [m for (k, _), m in self._instances.items()
                if k == 'gauge']
    out = []
    for g in gauges:
      v = g.value()
      if v is not None:
        out.append((g, v))
    return out

  def snapshot(self) -> Dict[str, float]:
    """Flat ``{key: value}`` view: the full backing counter store
    (histograms stay in their flat encoding) plus every evaluated
    gauge — what ``/varz`` serves and the post-mortem bundle saves."""
    snap = dict(self._backing().snapshot())
    for g, v in self._gauge_items():
      snap[g.key] = v
    return snap

  def varz(self) -> dict:
    from .recorder import recorder
    snap = self.snapshot()
    return {'ts': round(time.time(), 3), 'pid': os.getpid(),
            'metrics': {k: snap[k] for k in sorted(snap)},
            'recorder': recorder.stats()}

  def prometheus_text(self) -> str:
    """Prometheus text exposition (format 0.0.4) of every DECLARED
    metric with at least one registered instance.  Counters/gauges
    render as single samples; histograms as cumulative ``le`` buckets
    in seconds plus ``_sum``/``_count`` (the standard layout, decoded
    from the shared flat encoding)."""
    snap = self._backing().snapshot()
    with self._lock:
      by_family: Dict[Tuple[str, str], List[_Metric]] = {}
      for (kind, _), m in self._instances.items():
        by_family.setdefault((m.name, kind), []).append(m)
      exemplars = dict(self._exemplars)
    lines: List[str] = []
    for (name, kind) in sorted(by_family):
      doc = METRIC_NAMES.get(name, '')
      doc = doc.split(':', 1)[1].strip() if ':' in doc else doc
      fam = prom_name(name)
      if doc:
        lines.append(f'# HELP {fam} '
                     + doc.replace('\\', r'\\').replace('\n', ' '))
      lines.append(f'# TYPE {fam} '
                   + ('untyped' if kind not in _KINDS else kind))
      for m in sorted(by_family[(name, kind)], key=lambda m: m.key):
        if kind == 'counter':
          lines.append(f'{fam}{_prom_labels(m.labels)} '
                       f'{_fmt(snap.get(m.key, 0.0))}')
        elif kind == 'gauge':
          v = m.value()               # type: ignore[attr-defined]
          if v is not None:
            lines.append(f'{fam}{_prom_labels(m.labels)} {_fmt(v)}')
        else:                         # histogram
          base = f'{_hist.KEY_PREFIX}{m.key}{_hist.HIST_SEP}'
          run = 0.0
          for i in range(_hist.NUM_BUCKETS):
            run += float(snap.get(f'{base}b{i:02d}', 0.0))
            le = _hist.bucket_upper_edge_secs(i)
            line = (f'{fam}_bucket'
                    f'{_prom_labels(m.labels, [("le", repr(le))])} '
                    f'{_fmt(run)}')
            ex = exemplars.get((m.key, i))
            if ex is not None:
              # OpenMetrics exemplar: the bucket's last trace_id —
              # absent entirely when tracing never attached one, so
              # GLT_TRACE_SAMPLE=0 output is byte-identical
              tid, val, ts = ex
              line += (f' # {{trace_id="{tid}"}} {_fmt(val)} '
                       f'{round(ts, 3)}')
            lines.append(line)
          lines.append(f'{fam}_bucket'
                       f'{_prom_labels(m.labels, [("le", "+Inf")])} '
                       f'{_fmt(snap.get(base + "count", 0.0))}')
          lines.append(f'{fam}_sum{_prom_labels(m.labels)} '
                       f'{_fmt(snap.get(base + "secs", 0.0))}')
          lines.append(f'{fam}_count{_prom_labels(m.labels)} '
                       f'{_fmt(snap.get(base + "count", 0.0))}')
    return '\n'.join(lines) + '\n'


#: sample-line shape of the text exposition (family + optional labels
#: + float), shared by the validating parser below
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})?\s+'
    r'([+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN))$')

#: OpenMetrics exemplar chunk (the part after ``# ``): a label set,
#: a value, an optional timestamp
_EXEMPLAR_RE = re.compile(
    r'^\{[^{}]*\}\s+[+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN)'
    r'(?:\s+[0-9]+(?:\.[0-9]+)?)?$')


def split_exemplar(line: str) -> Tuple[str, Optional[str]]:
  """``(sample_part, exemplar_or_None)`` for one exposition line.
  Only a WELL-FORMED OpenMetrics exemplar suffix
  (``... # {trace_id="…"} value [ts]``) is split off; anything else
  is returned untouched so the strict sample regex still rejects it
  loudly.  Shared by `parse_prometheus_text` and the federation
  strict parser — without this, every exemplar-emitting replica
  would be quarantined as malformed."""
  idx = line.find(' # {')
  if idx < 0:
    return line, None
  chunk = line[idx + 3:].strip()
  if _EXEMPLAR_RE.match(chunk):
    return line[:idx].rstrip(), chunk
  return line, None


def parse_prometheus_text(text: str) -> Dict[str, float]:
  """Strictly parse a Prometheus text exposition into
  ``{sample_name_with_labels: value}``; raises ``ValueError`` on the
  first malformed line.  The acceptance validator for the ops
  endpoint (and the bench's mid-run scrape check) — deliberately
  small, not a Prometheus client.  OpenMetrics exemplar suffixes on
  bucket samples are accepted (and dropped — exemplars are trace
  pointers, not sample values)."""
  out: Dict[str, float] = {}
  for n, raw in enumerate(text.splitlines(), 1):
    line = raw.strip()
    if not line:
      continue
    if line.startswith('#'):
      if not (line.startswith('# HELP ') or line.startswith('# TYPE ')):
        raise ValueError(f'line {n}: malformed comment {raw!r}')
      continue
    line, _ = split_exemplar(line)
    m = _SAMPLE_RE.match(line)
    if m is None:
      raise ValueError(f'line {n}: malformed sample {raw!r}')
    out[m.group(1) + (m.group(2) or '')] = float(m.group(3))
  return out


# -- default vocabulary wiring ----------------------------------------------
def _rate(snap: Dict[str, float], num_keys, den_keys
          ) -> Optional[float]:
  num = sum(v for k, v in snap.items()
            if any(k == b or k.startswith(b + '{') for b in num_keys))
  den = sum(v for k, v in snap.items()
            if any(k == b or k.startswith(b + '{') for b in den_keys))
  return round(num / den, 6) if den else None


def _wire_defaults(reg: LiveRegistry) -> None:
  """Declare the standard vocabulary: counters whose tick sites
  already exist across the data plane (declaring exposes them on the
  scrape — the tick sites don't move), and the derived gauges the
  acceptance scrape promises (hit rates, padding waste, shed rate).
  One literal call per name, so the glint ``metric-name`` pass can
  see every declaration has a registration site (and vice versa)."""
  reg.counter('dist.feature.lookups')
  reg.counter('dist.feature.cold_lookups')
  reg.counter('dist.feature.cold_misses')
  reg.counter('dist.feature.cache_hits')
  reg.counter('fused.compile.hits')
  reg.counter('fused.compile.misses')
  reg.counter('rpc.retries')
  reg.counter('producer.restarts_total')
  reg.counter('gns.bias_steps_total')
  reg.counter('gns.sketch_updates_total')
  reg.counter('snapshot.saves_total')
  reg.counter('snapshot.save_failures_total')
  reg.counter('postmortem.dumps_total')
  # cache.*_total register LABELED at their tick site
  # (data/cold_cache.py::emit_cache_events, per scope) — an
  # unlabeled twin here would render a permanently-zero sample
  # beside the real per-scope ones

  def _ring_dropped() -> float:
    from .recorder import recorder
    return float(recorder.stats()['ring_dropped'])

  def _cache_hit_rate() -> Optional[float]:
    snap = reg._backing().snapshot()
    return _rate(snap, ('cache.hits_total',),
                 ('cache.hits_total', 'cache.misses_total'))

  def _hbm_served_rate() -> Optional[float]:
    snap = reg._backing().snapshot()
    lookups = snap.get('dist.feature.lookups', 0.0)
    if not lookups:
      return None
    return round(
        1.0 - snap.get('dist.feature.cold_misses', 0.0) / lookups, 6)

  def _padding_waste() -> Optional[float]:
    snap = reg._backing().snapshot()
    slots = snap.get('dist.frontier.slots', 0.0)
    if not slots:
      return None
    sent = (snap.get('dist.frontier.offered', 0.0)
            - snap.get('dist.frontier.dropped', 0.0))
    return round(100.0 * (1.0 - sent / slots), 4)

  def _shed_rate() -> Optional[float]:
    snap = reg._backing().snapshot()
    return _rate(snap, ('serving.shed_total',),
                 ('serving.shed_total', 'serving.admitted_total'))

  reg.gauge('recorder.ring_dropped', fn=_ring_dropped)
  reg.gauge('cache.hit_rate', fn=_cache_hit_rate)
  reg.gauge('cache.hbm_served_rate', fn=_hbm_served_rate)
  reg.gauge('exchange.padding_waste_pct', fn=_padding_waste)
  reg.gauge('serving.shed_rate', fn=_shed_rate)


#: process-global live registry every subsystem registers with (the
#: one the ops endpoint serves); strict — names must be declared.
live = LiveRegistry(strict=True)
_wire_defaults(live)
