"""Registry of flight-recorder event kinds.

Exporters (`telemetry.export`), the report CLI, and external
dashboards key off event ``kind`` strings; an unregistered kind is a
consumer that silently sees nothing.  Every ``recorder.emit('<kind>',
...)`` call site must register its kind here — enforced statically by
``tests/test_event_schema.py``, which greps the package for emit call
sites and fails on any kind missing from :data:`EVENT_KINDS` (and on
stale registry entries with no remaining call site, so the table can't
rot in the other direction).

The value documents the emitter and the fields consumers may rely on.
"""
from __future__ import annotations

from typing import Dict

#: kind -> 'emitter: field summary' (the consumer contract)
EVENT_KINDS: Dict[str, str] = {
    'hop.padding':
        'DistNeighborLoader / fused epoch drivers: hop, nodes, '
        'capacity, fill (1 - fill = padding waste)',
    'channel.stall':
        'ChannelTelemetry._timed: op, secs, occupancy, channel',
    'slack.transition':
        'AdaptiveSlack: from_slack, to_slack, reason, drop_rate, '
        "pin_reason ('reversal' when this widen pins the ladder, "
        "else '')",
    'slack.pinned':
        'AdaptiveSlack: slack, drop_rate, pin_reason (why retuning '
        "stopped: 'reversal' = tighten->widen oscillation guard, "
        "'floor' = drop-free at the configured ladder floor)",
    'padding.truncate':
        'utils.padding.pad_1d: requested, size, dropped — a host-side '
        'pad silently cut non-fill entries (capacity bug surfacing; '
        'GLT_STRICT_PADDING=1 raises instead)',
    'dist.exchange':
        'ExchangeTelemetry drains: since-last-drain deltas of '
        'offered/dropped/slots per loss channel',
    'dist.cold_tier':
        'tiered DistFeature drains: lookups (all feature lookups), '
        'cold_lookups (past the hot tier — the cache denominator), '
        'misses (host-served), cache_hits, hit_rate',
    'cache.hit':
        'data.cold_cache consumers (scope=feature|dist|serving|'
        'hetero): count of cold lookups served from the HBM victim '
        'cache this overlay',
    'cache.miss':
        'data.cold_cache consumers: count of cold lookups that paid '
        'the host gather this overlay (admission candidates; '
        'scope=hetero has NO cache yet, so every cold lookup lands '
        'here — the live twin of cold_lookups == cold_misses)',
    'cache.admit':
        'data.cold_cache consumers: rows written into the HBM ring '
        'this overlay (frequency-ranked winners)',
    'cache.evict':
        'data.cold_cache consumers: residents displaced by this '
        "overlay's admissions (CLOCK second-chance victims)",
    'fused.compile':
        'loader.fused._uncached_jit: fn, secs, persistent_cache',
    'span.begin':
        'telemetry.spans: name, trace_id, span_id, parent_id, pid, '
        'tid (+caller fields)',
    'span.end':
        'telemetry.spans: same ids as span.begin plus dur '
        '(monotonic-clock seconds) and error',
    'fault.injected':
        'testing.chaos: site, action, nth, arrival (+op/worker/epoch '
        'filters, secs for delays) — one event per fired fault, so a '
        'chaos run reads out of the same stream as the retries and '
        'restarts it caused',
    'rpc.retry':
        'RpcClient.request: op, attempt, addr, error, backoff_secs — '
        'one transport fault absorbed by the resilience layer',
    'producer.restart':
        'MpSamplingProducer.supervise: worker, exitcode, replayed '
        '(unacked batches re-dispatched), restarts, budget',
    'peer.lost':
        'resilience layer (DistClient / DistLoader / supervise): '
        'peer, peer_kind (server|worker), degraded (True = epoch '
        'finished on survivors under GLT_DEGRADED_OK), lost_batches/'
        'outstanding, received, expected',
    'server.shutdown_timeout':
        'DistServer.wait_for_exit: rank, timeout_secs, '
        'clients_never_exited, clients_left, live_producers — a '
        'shutdown wait that expired instead of returning silently',
    'snapshot.save':
        'utils.checkpoint.SnapshotManager.save: index, ok, secs, dir, '
        'epoch, next_chunk (ok=False carries error — a failed '
        'snapshot write is absorbed, not fatal)',
    'snapshot.restore':
        'utils.checkpoint.SnapshotManager.restore_latest: index, '
        'secs, dir, epoch, next_chunk — one event per data-plane '
        'restore (resume and degraded rollback both land here)',
    'mesh.stall':
        'resilience.run_with_deadline: scope, deadline_secs, healthy '
        '(last-known-healthy process set) — a fused/mesh dispatch '
        'exceeded GLT_DISPATCH_DEADLINE and was converted into a '
        'typed MeshStallError instead of hanging the epoch',
    'serving.request':
        'serving.frontend executor, one per de-multiplexed request: '
        'seeds, bucket, coalesced (requests in the dispatch), ok, '
        'latency_ms (arrival -> resolve; the percentile-table and '
        'bench p50/p95/p99 source), error when ok=False',
    'serving.coalesce':
        'serving.frontend executor, one per coalesced dispatch: '
        'requests, seeds, bucket (chosen capacity), waited_ms since '
        "the run's first arrival (how much of GLT_SERVING_MAX_WAIT_MS "
        'actually bound)',
    'serving.admit':
        'serving.admission.AdmissionController.submit: seeds, '
        'queue_depth after admit, deadline_ms — one per admitted '
        'request',
    'gns.bias':
        'DistNeighborSampler.step_for_batch (GNS mode, build time): '
        'batch, boost, num_parts — one event per compiled GNS step, '
        'recording the cached-neighbor boost that step samples with',
    'gns.sketch_update':
        'DistNeighborSampler._gns_arrays: scope, residents, version, '
        'mask_bytes — one event per cached-set bitmask refresh (the '
        'sketch-selected cold-cache residents ∪ hot split became the '
        'new sampling-bias membership table)',
    'serving.shed':
        'serving.admission: reason (queue_full|deadline|too_large|'
        'draining|shutdown), seeds, queue_depth, limit / waited_ms / '
        'retry_after_ms — one per typed load-shed (the request '
        'future resolves with AdmissionRejected; nothing is silently '
        'dropped; draining sheds are intentional and burn no SLO '
        'budget)',
    'recorder.overflow':
        'telemetry.recorder, ONE-SHOT on the first in-memory ring '
        'drop: ring_capacity — from this point the flight recorder '
        'is a sliding window, not a full history (cumulative count: '
        'stats()["ring_dropped"] / the recorder.ring_dropped gauge)',
    'slo.burn':
        'telemetry.slo.SloTracker: window_secs, burn_rate, p99_ms, '
        'target_p99_ms, qps, count — a sliding window started '
        'consuming latency error budget faster than allotted '
        '(burn_rate crossed 1.0; re-arms when it recovers)',
    'postmortem.dump':
        'telemetry.postmortem.dump: reason, path, events, '
        'error — a post-mortem bundle (recorder ring + metrics '
        'snapshot + health) was written to GLT_POSTMORTEM_DIR',
    'serving.failover':
        'serving.router.FleetRouter: replica, event '
        '(evict|redrive|readmit|exhausted|quarantine|retire), '
        'redriven (in-flight '
        'requests moved to a survivor on evict), state — one event '
        'per fleet state transition / redrive wave, so a failover '
        'reads out of the same stream as the chaos faults that '
        'caused it',
    'serving.swap':
        'serving.swap.hot_swap: version, ok, rolled_back, '
        'parity_max_err, drained_ms — one event per hot model-swap '
        'attempt (ok=False carries error; a parity mismatch rolls '
        'back to the prior version with zero dropped requests; a '
        'never-quiesced executor aborts with rolled_back=False '
        'before any probe ran)',
    'aot.cache_hit':
        'serving.aot_cache.AotExecutableCache: program, bucket, key, '
        'secs — a warm executable deserialized from '
        'GLT_AOT_CACHE_DIR instead of recompiling',
    'aot.cache_miss':
        'serving.aot_cache.AotExecutableCache: program, bucket, key, '
        'reason (absent|stale|corrupt|unreadable|error) — this '
        'bucket paid a compile; corrupt/stale entries land here too '
        '(skip-to-recompile, never a crash or a wrong executable)',
    'ingest.wal_truncate':
        'streaming.wal.WriteAheadLog.open: path, offset, '
        'dropped_bytes, last_seqno — a torn tail (kill mid-append) '
        'was truncated back to the last whole record; replay lands '
        'exactly the whole-record prefix',
    'ingest.replay':
        'streaming.ingest.IngestPipeline.recover: restored (a '
        'compacted base was loaded), replayed_records/_events, '
        'skipped_records (<= the base watermark — the idempotence '
        'that makes a crash between snapshot and WAL reset safe), '
        'applied_seqno, secs — one event per recovery',
    'ingest.compact':
        'streaming.ingest.IngestPipeline.compact: ok, seqno '
        '(watermark baked into the snapshot), events, secs — ok='
        'False is an ABSORBED snapshot-write failure (the WAL keeps '
        'the full history; nothing lost)',
    'ingest.fault':
        'streaming.ingest.IngestPipeline: site (apply|compact|'
        'shard_refresh), '
        'error — an ingestion fault surfaced typed (and dumped a '
        'post-mortem bundle) instead of leaving a half-applied '
        'graph; the WAL replay makes the restart exactly-once',
    'partition.adopt':
        'failover.adopt_shard + the reader recovery seams: '
        'partition, survivor, version, secs (phase=recovered rows '
        'carry the classification→served-batch recovery clock)',
    'partition.book_version':
        'PartitionBook.adopt/.transfer: version, lost, survivor, '
        'num_lanes, planned (True = scheduled handoff cutover, not a '
        'crash adoption) — one per ownership transfer, the routing '
        'authority moving',
    'handoff.transfer':
        'parallel.handoff.handoff: partition, frm, to, phase '
        '(snapshot|transfer|fence|cutover|drain|rollback), version, '
        'secs, error (rollback cause / absorbed drain fault) — one '
        'event per seam of a planned ownership move, so a handoff '
        'reads out of the flight recorder end to end',
    'partition.relabel':
        'parallel.locality.locality_partition: partitioner, '
        'num_parts, num_nodes, seed, edge_cut_frac, max_part_frac, '
        'hotness_weighted — one event per locality relabel build '
        '(the placement decision a dataset was constructed under)',
    'partition.rebalance':
        'parallel.locality.execute_rebalance: partition, frm, to, '
        'demand, version, secs — one event per planned hot-range '
        'migration (each move is a fenced handoff.transfer ladder; '
        'this is the demand-driven WHY on top of it)',
    'exchange.retune':
        'parallel.dist_sampler.ExchangeTelemetry.capacity_retune: '
        'steps, frontier_dest_cap, frontier_traffic_cap, '
        'feature_dest_cap, feature_traffic_cap — the EWMA capacity '
        'model moved a quantized cap and the step cache was cleared '
        '(next dispatch compiles measured per-destination shares)',
    'scale.decision':
        'serving.autoscaler.ElasticController: dir (out|in), outcome '
        '(ok|rolled_back|held:cooldown|held:bounds|held:no_victim), '
        'replica, error, plus the signal snapshot that justified it '
        '(replicas, short_burn, long_burn, queue_frac, headroom_qps) '
        '— every considered scaling decision, acted or held',
    'pallas.dispatch':
        'r19 kernel gates (ops.pallas_sample.sample_one_hop_auto, '
        'data.cold_cache.make_pinned_cold_buffer, streaming.delta.'
        'StreamingGraph._merge_device): kernel (fused_sample|'
        'cold_gather|delta_merge) + per-kernel fields (mode/batch/k, '
        'rows/memory_kind, events/version) — one event per '
        'trace/build that took the Pallas path, so a perf run reads '
        'which arms actually ran the kernel out of the same stream '
        'as its step timings',
    'pallas.fallback':
        'r19 kernel gates (same three sites): kernel, reason '
        '(unsupported-shape strings or trace-error:<ExcType>) + the '
        'same per-kernel fields — the knob was ON but this call '
        'fell back to the XLA/host path at byte parity; contract '
        'errors (ValueError) re-raise instead of landing here',
}


#: span NAME vocabulary (the `name` field of span.begin/span.end —
#: the per-stage rows of the report CLI and the Perfetto slices).
#: Same contract as EVENT_KINDS: every ``span('<name>', ...)`` call
#: site registers here, enforced by the same static test.
SPAN_NAMES: Dict[str, str] = {
    'batch':
        'per-batch root span (mesh + host-runtime loaders)',
    'sample.exchange':
        'mesh samplers: the fused sample+exchange SPMD dispatch',
    'feature.lookup':
        'mesh samplers, TIERED stores only: the cold-tier overlay '
        '(the per-batch host sync worth attributing)',
    'stitch':
        'mesh loaders: Batch pytree assembly',
    'recv':
        'host-runtime DistLoader: channel dequeue',
    'collate':
        'host-runtime DistLoader: message -> static-shape Batch '
        '(carries producer_trace/producer_span link fields)',
    'producer.sample':
        'sampling worker subprocess: one sample+send',
    'server.fetch':
        'DistServer: one blocking buffer pull for a client',
    'client.fetch':
        'DistClient: one RPC fetch round trip',
    'fused.epoch':
        'fused epoch drivers: one whole run() call',
    'fused.dispatch':
        'fused epoch drivers: one chunk/program dispatch (tiered '
        "epochs tag phase='collect'|'train')",
    'feature.cold_overlay':
        'tiered fused epochs: the between-dispatch host cold service '
        'for one chunk (cache serve + host overlay + admissions; '
        'steps = batches corrected)',
    'fused.init_state':
        'FusedTreeEpoch.init_state: param init from the dummy batch',
    'exchange.layout':
        'mesh samplers, build time: one span per compiled SPMD step '
        'with the resolved exchange layout (dense/compact/hier/'
        'ragged), num_parts and slack',
    'exchange.stage':
        'parallel.exchange.capacity_spec, build time: hierarchical '
        'stage capacities (rows, cols, stage1_cap, stage2_cap) for '
        'one planned exchange',
    'serving.infer':
        'serving.frontend executor: one warm bucketed dispatch '
        '(device program + tiered host fill) — bucket, requests, '
        'seeds; queue wait is OUTSIDE this span (serving.request '
        'latency_ms minus this span = admission/coalescing wait)',
    'serving.route':
        'FleetRouter (request-trace root): one routed serve request '
        'submit→resolve, spanning the replica RPC + coalesced '
        'dispatch — replica, outcome; span_id == trace_id '
        '(recorded via telemetry.tracing, tail-retained)',
    'serving.rpc':
        'DistServer.serve_infer: one serve RPC on the server process '
        '(submit→future resolve) — the cross-process edge under '
        'serving.route (telemetry.tracing)',
    'serving.queue_wait':
        'serving frontend, per request: admission enqueue → '
        'coalesce pickup (the wait the coalescing executor imposed; '
        'also a live histogram under the same name)',
    'serving.dispatch_slice':
        'serving frontend, per request: this request\'s share of one '
        'coalesced dispatch (pickup → demux resolve) — bucket, '
        'requests riding the same dispatch (telemetry.tracing)',
    'serving.sample_collect':
        'serving engine, per dispatch: the neighbor-sampling collect '
        'program inside a tiered dispatch, parented under the '
        'dispatch slice — with serving.cold_fill it splits sampling '
        'cost from feature-fill cost (telemetry.tracing)',
    'serving.cold_fill':
        'serving engine, per dispatch: the tiered host cold-path '
        'feature fill inside the dispatch (cache serve + host '
        'gather), parented under the dispatch slice '
        '(telemetry.tracing)',
}


#: live-metric vocabulary (ISSUE 12): every counter/gauge/histogram
#: registered with the live ops registry (`telemetry.live`) must use a
#: ``snake.dot`` name from this table — enforced statically by the
#: glint ``metric-name`` pass, the metric twin of the event-schema
#: pass above.  The value is ``'<type>: <doc>'`` where type is one of
#: ``counter`` / ``gauge`` / ``histogram`` (the pass also checks the
#: registration call matches the declared type).  This table is the
#: ONE metrics vocabulary the offline artifact, the regression gate
#: and the fleet `/metrics` scrape share; an undeclared metric is a
#: dashboard panel nobody can discover.
METRIC_NAMES: Dict[str, str] = {
    'ops.scrapes_total':
        'counter: opsserver — HTTP requests answered by the ops '
        'endpoint (any of /metrics, /varz, /healthz)',
    'recorder.ring_dropped':
        'gauge: EventRecorder.stats()["ring_dropped"] — events lost '
        'to in-memory ring overflow (nonzero = the flight recorder '
        'is a sliding window, see the recorder.overflow event)',
    'serving.queue_depth':
        'gauge: AdmissionController.depth() at scrape time — '
        'requests waiting for the coalescing executor',
    'serving.in_flight':
        'gauge: requests inside the current coalesced dispatch '
        '(frontend executor state, read under its lock)',
    'serving.coalesce_fill_ratio':
        'gauge: seeds/bucket_capacity of the most recent coalesced '
        'dispatch — how much of the chosen bucket real traffic '
        'filled (low = padding-dominated dispatches)',
    'serving.requests_total':
        'counter: requests resolved OK by the serving executor',
    'serving.seeds_total':
        'counter: seeds served across all resolved requests',
    'serving.dispatches_total':
        'counter: coalesced device dispatches the executor ran',
    'serving.failed_total':
        'counter: requests resolved with an executor error '
        '(typed resolve — never a silent drop)',
    'serving.admitted_total':
        'counter: requests past admission into the bounded queue',
    'serving.shed_total':
        'counter: typed load-sheds, labeled by reason '
        '(queue_full|deadline|too_large|draining|shutdown)',
    'serving.shed_rate':
        'gauge: shed/(admitted+shed) over process lifetime — the '
        'overload signal the fleet scrape alarms on',
    'serving.request_latency':
        'histogram: end-to-end request latency (arrival→resolve, '
        'seconds; log2 buckets), labeled by serving bucket capacity',
    'serving.slo.p50_ms':
        'gauge: SloTracker short-window request latency p50 (ms)',
    'serving.slo.p99_ms':
        'gauge: SloTracker short-window request latency p99 (ms)',
    'serving.slo.qps':
        'gauge: SloTracker short-window completed-request rate',
    'serving.slo.qps_ratio':
        'gauge: short-window qps / GLT_SERVING_SLO_QPS (only '
        'exported when the target is configured)',
    'serving.slo.burn_rate':
        'gauge: latency-SLO error-budget burn rate per sliding '
        'window (violating_fraction / 1% budget vs '
        'GLT_SERVING_SLO_P99_MS; >1.0 = budget burning faster than '
        'allotted), labeled by window seconds',
    'cache.hits_total':
        'counter: cold-cache hits, labeled by scope '
        '(feature|dist|serving|hetero) — mirrors the cache.hit '
        'events (scope=hetero is pinned 0: no cache there yet, '
        'ROADMAP item 3 — visible live, not artifact-only)',
    'cache.misses_total':
        'counter: cold-cache misses (host-gather work), by scope',
    'cache.admits_total':
        'counter: rows admitted into the HBM victim ring, by scope',
    'cache.evicts_total':
        'counter: residents displaced by admissions, by scope',
    'cache.hit_rate':
        'gauge: hits/(hits+misses) summed across cache scopes — the '
        'live twin of the bench cache_hit_rate',
    'cache.hbm_served_rate':
        'gauge: 1 - cold_misses/lookups from the dist feature '
        'counters — total fraction of feature lookups served from '
        'HBM (hot tier + victim cache)',
    'dist.feature.lookups':
        'counter: all mesh feature lookups (the hbm_served_rate '
        'denominator; ticked by ExchangeTelemetry drains)',
    'dist.feature.cold_lookups':
        'counter: lookups past the hot tier (the cache_hit_rate '
        'denominator)',
    'dist.feature.cold_misses':
        'counter: cold lookups the host gather served',
    'dist.feature.cache_hits':
        'counter: cold lookups the HBM victim cache served',
    'exchange.padding_waste_pct':
        'gauge: 100*(1 - sent/slots) over the frontier exchange '
        'counters — the live padding-waste number the scale '
        'envelope tracks offline',
    'fused.compile.hits':
        'counter: _uncached_jit dispatches served by a warm '
        'in-memory executable',
    'fused.compile.misses':
        'counter: _uncached_jit dispatches that paid an XLA compile '
        '(nonzero after warmup = a shape escaped bucketing)',
    'gns.bias_steps_total':
        'counter: compiled GNS-biased sampler steps built '
        '(node + link modes)',
    'gns.sketch_updates_total':
        'counter: cached-set bitmask refreshes (cache-ring version '
        'bumps reaching the sampling bias)',
    'rpc.retries':
        'counter: transport faults absorbed by the RPC resilience '
        'layer (one per rpc.retry event)',
    'rpc.replay_cache_entries':
        'gauge: live entries across the RPC server replay cache '
        '(exactly-once occupancy; near the eviction caps = retries '
        'at risk of ReplayEvictedError)',
    'producer.restarts_total':
        'counter: sampling-worker restarts by the producer '
        'supervisor',
    'snapshot.saves_total':
        'counter: durable snapshot publishes (SnapshotManager.save '
        'ok=True)',
    'snapshot.save_failures_total':
        'counter: absorbed snapshot write failures (ok=False)',
    'snapshot.save_age_seconds':
        'gauge: seconds since the last successful snapshot save '
        '(absent until one lands; growing past the cadence = '
        'durability stalled)',
    'snapshot.restore_age_seconds':
        'gauge: seconds since the last snapshot restore (absent '
        'unless this process resumed/rolled back)',
    'postmortem.dumps_total':
        'counter: post-mortem bundles written to GLT_POSTMORTEM_DIR',
    'fleet.replicas':
        'gauge: FleetRouter replica count by state, labeled '
        'state=healthy|overloaded|draining|quarantined|dead '
        '(scrape-time evaluation off the replica table)',
    'fleet.redrives_total':
        'counter: in-flight requests redriven from a lost replica '
        'onto a survivor (each redriven at most once — the '
        'exactly-once failover ledger)',
    'fleet.evictions_total':
        'counter: replicas evicted from rotation after consecutive '
        'heartbeat misses (flapped replicas that return are '
        're-admitted and counted again on a later eviction)',
    'fleet.quarantines_total':
        'counter: replicas quarantined by the flap damper (≥3 '
        'dead→healthy readmits inside GLT_FLEET_FLAP_WINDOW_S) — '
        're-admission waits out an exponential backoff, doubling '
        'per quarantine of the same replica',
    'scale.replicas':
        'counter: ElasticController scaling actions executed, '
        'labeled dir=out|in (each tick = one replica admitted to / '
        'retired from rotation; rolled-back decisions do not tick)',
    'serving.swaps_total':
        'counter: hot model-swap attempts, labeled '
        'outcome=ok|rolled_back|aborted (rolled_back = '
        'offline_reference parity check refused the new version; '
        'aborted = executor never quiesced, probe never ran)',
    'aot.cache_hits_total':
        'counter: bucket executables restored from the persistent '
        'AOT cache (GLT_AOT_CACHE_DIR) instead of recompiling',
    'aot.cache_misses_total':
        'counter: bucket warmups that paid an XLA compile (absent/'
        'stale/corrupt cache entries all land here)',
    'ingest.events_total':
        'counter: edge-insert events applied to the delta-CSR by '
        'this process (WAL replays after a restart included — they '
        'are real applies this process performed)',
    'ingest.lag_events':
        'gauge: WAL events appended but not yet applied (the '
        'freshness debt; past GLT_INGEST_MAX_LAG the ingestion '
        'healthz component flips unhealthy)',
    'ingest.compactions_total':
        'counter: durable base compactions (snapshot published + '
        'WAL reset to the surviving suffix)',
    'graph.version':
        'gauge: the streaming graph\'s current published version — '
        'every reader dispatch pins exactly one of these; the value '
        'moving is ingest reaching the data plane',
    'partition.adoptions_total':
        'counter: partition-ownership transfers executed '
        '(failover.adopt_shard: durable shard loaded, book version '
        'bumped, survivor serving the orphaned range)',
    'partition.book_version':
        'gauge: the PartitionBook\'s current published version (0 = '
        'identity ownership; each adoption bumps it and every '
        'reader re-fences at its next dispatch seam)',
    'partition.recovery_secs':
        'gauge: classification→first-served-batch wall time of the '
        'most recent partition adoption (shard load + lane rebuild '
        '+ exchange-plan recompile)',
    'timeseries.samples_total':
        'counter: cadence-sampler sweeps completed by the '
        'TimeSeriesStore (one per GLT_TS_CADENCE_MS tick; a stalled '
        'counter here means the history rings have stopped filling)',
    'timeseries.series':
        'gauge: ring-buffered series currently held by the '
        'TimeSeriesStore (gauges plus counters-as-rates)',
    'fleet.scrapes_total':
        'counter: FleetScraper sweeps over the replica target set '
        '(one per GLT_FLEET_SCRAPE_MS tick or explicit scrape)',
    'fleet.scrape_errors_total':
        'counter: replica scrapes that failed (unreachable '
        'endpoint, malformed exposition), labeled by replica',
    'fleet.replicas_up':
        'gauge: replicas whose most recent scrape succeeded and '
        'whose /healthz rollup reported ok — the federation\'s own '
        'liveness view of the fleet',
    'gns.range_hotness':
        'gauge: decayed visit mass of one PartitionBook range from '
        'the GNS DecayedSketch top-K export, labeled by partition '
        '(only the K hottest ranges are exported)',
    'exchange.local_ids_total':
        'counter: exchange ids (frontier + feature) whose '
        'destination range was the requesting device\'s own — the '
        'attribution matrix diagonal, ticked at attribution drains',
    'exchange.cross_ids_total':
        'counter: exchange ids routed to a NON-self partition range '
        '(off-diagonal attribution mass — what locality-aware '
        'partitioning exists to shrink)',
    'partition.replicated_rows':
        'gauge: per-device rows of the read-only remote-row replica '
        'cache (`dist_data.build_replica_cache`) — the hot-row '
        'budget the masked gather serves locally instead of '
        'exchanging (0 = replication off)',
    'locality.edge_cut_frac':
        'gauge: fraction of edges crossing partitions under the '
        'most recent locality_partition run — the streaming '
        'partitioner\'s objective, measured on its own output',
    'serving.queue_wait':
        'histogram: per-request admission enqueue → coalesce pickup '
        'wait (seconds; log2 buckets) — overload diagnosis without '
        'inferring waits from shed diagnostics',
    'serving.traces_retained_total':
        'counter: request traces kept by the tail-retention verdict '
        '(slow/failed/sampled — telemetry.tracing; the /traces ring '
        'is bounded, this counts total captures)',
    'memory.tier_bytes':
        'gauge: bytes currently held by one memory tier, labeled '
        'tier=hot|cold_cache|streaming|gns|aot|wal|pinned_host '
        '(scrape-time callback from each owner — '
        'telemetry.memaccount)',
    'memory.tier_peak_bytes':
        'gauge: high-watermark of memory.tier_bytes since the '
        'owner registered (tracked at scrape time, by tier)',
    'fleet.headroom_qps':
        'gauge: sustainable request rate minus carried short-window '
        'QPS for this replica (traffic-weighted per-bucket EWMA '
        'serve-cost model — telemetry.memaccount.CapacityModel; '
        'the admission signal for SLO-driven autoscaling)',
}


#: closed label-key vocabulary of the live metric plane.  Every
#: ``labels={...}`` at a counter/gauge/histogram registration site
#: must draw its KEYS from this table (enforced statically by the
#: glint ``metric-label-cardinality`` pass) and each entry documents
#: the closed/bounded VALUE set — the property that keeps scrape
#: cardinality enumerable (a label whose values are unbounded is a
#: time-series leak: every new value mints a family member forever).
METRIC_LABELS: Dict[str, str] = {
    'scope':
        'cold-cache scope: feature|dist|serving|hetero (the four '
        'cache flavors — see cache.*_total)',
    'bucket':
        'serving bucket capacity: one of the GLT_SERVING_BUCKETS '
        'ladder seeds (default 1,2,4,8,16 — bounded by the ladder '
        'length)',
    'state':
        'FleetRouter replica state: healthy|overloaded|draining|'
        'quarantined|dead (fixed five-state machine)',
    'dir':
        'ElasticController scale direction: out|in (the two-way '
        'vocabulary of scale.replicas)',
    'reason':
        'admission shed reason: queue_full|deadline|too_large|'
        'draining|shutdown (the typed rejection vocabulary)',
    'outcome':
        'hot-swap outcome: ok|rolled_back|aborted (fixed three-way '
        'verdict of serving.swaps_total)',
    'window':
        'SLO sliding window: one of SloTracker.windows rendered as '
        '"<seconds>s" (default 60s|300s — bounded by the '
        'configured window tuple)',
    'replica':
        'fleet replica name: bounded by the fleet size (the '
        'FleetScraper target set / FleetRouter replica table)',
    'partition':
        'partition/range index: 0..P-1, bounded by the mesh '
        'num_parts (PartitionBook range ids)',
    'tier':
        'memory accounting tier: hot|cold_cache|streaming|gns|aot|'
        'wal|pinned_host (the closed memaccount.TIERS vocabulary — '
        'seven fixed byte-gauge families, never per-object)',
}


def registered(kind: str) -> bool:
  return kind in EVENT_KINDS
