"""Registry of flight-recorder event kinds.

Exporters (`telemetry.export`), the report CLI, and external
dashboards key off event ``kind`` strings; an unregistered kind is a
consumer that silently sees nothing.  Every ``recorder.emit('<kind>',
...)`` call site must register its kind here — enforced statically by
``tests/test_event_schema.py``, which greps the package for emit call
sites and fails on any kind missing from :data:`EVENT_KINDS` (and on
stale registry entries with no remaining call site, so the table can't
rot in the other direction).

The value documents the emitter and the fields consumers may rely on.
"""
from __future__ import annotations

from typing import Dict

#: kind -> 'emitter: field summary' (the consumer contract)
EVENT_KINDS: Dict[str, str] = {
    'hop.padding':
        'DistNeighborLoader / fused epoch drivers: hop, nodes, '
        'capacity, fill (1 - fill = padding waste)',
    'channel.stall':
        'ChannelTelemetry._timed: op, secs, occupancy, channel',
    'slack.transition':
        'AdaptiveSlack: from_slack, to_slack, reason, drop_rate, '
        "pin_reason ('reversal' when this widen pins the ladder, "
        "else '')",
    'slack.pinned':
        'AdaptiveSlack: slack, drop_rate, pin_reason (why retuning '
        "stopped: 'reversal' = tighten->widen oscillation guard, "
        "'floor' = drop-free at the configured ladder floor)",
    'padding.truncate':
        'utils.padding.pad_1d: requested, size, dropped — a host-side '
        'pad silently cut non-fill entries (capacity bug surfacing; '
        'GLT_STRICT_PADDING=1 raises instead)',
    'dist.exchange':
        'ExchangeTelemetry drains: since-last-drain deltas of '
        'offered/dropped/slots per loss channel',
    'dist.cold_tier':
        'tiered DistFeature drains: lookups (all feature lookups), '
        'cold_lookups (past the hot tier — the cache denominator), '
        'misses (host-served), cache_hits, hit_rate',
    'cache.hit':
        'data.cold_cache consumers (scope=feature|dist|serving): '
        'count of cold lookups served from the HBM victim cache this '
        'overlay',
    'cache.miss':
        'data.cold_cache consumers: count of cold lookups that paid '
        'the host gather this overlay (admission candidates)',
    'cache.admit':
        'data.cold_cache consumers: rows written into the HBM ring '
        'this overlay (frequency-ranked winners)',
    'cache.evict':
        'data.cold_cache consumers: residents displaced by this '
        "overlay's admissions (CLOCK second-chance victims)",
    'fused.compile':
        'loader.fused._uncached_jit: fn, secs, persistent_cache',
    'span.begin':
        'telemetry.spans: name, trace_id, span_id, parent_id, pid, '
        'tid (+caller fields)',
    'span.end':
        'telemetry.spans: same ids as span.begin plus dur '
        '(monotonic-clock seconds) and error',
    'fault.injected':
        'testing.chaos: site, action, nth, arrival (+op/worker/epoch '
        'filters, secs for delays) — one event per fired fault, so a '
        'chaos run reads out of the same stream as the retries and '
        'restarts it caused',
    'rpc.retry':
        'RpcClient.request: op, attempt, addr, error, backoff_secs — '
        'one transport fault absorbed by the resilience layer',
    'producer.restart':
        'MpSamplingProducer.supervise: worker, exitcode, replayed '
        '(unacked batches re-dispatched), restarts, budget',
    'peer.lost':
        'resilience layer (DistClient / DistLoader / supervise): '
        'peer, peer_kind (server|worker), degraded (True = epoch '
        'finished on survivors under GLT_DEGRADED_OK), lost_batches/'
        'outstanding, received, expected',
    'server.shutdown_timeout':
        'DistServer.wait_for_exit: rank, timeout_secs, '
        'clients_never_exited, clients_left, live_producers — a '
        'shutdown wait that expired instead of returning silently',
    'snapshot.save':
        'utils.checkpoint.SnapshotManager.save: index, ok, secs, dir, '
        'epoch, next_chunk (ok=False carries error — a failed '
        'snapshot write is absorbed, not fatal)',
    'snapshot.restore':
        'utils.checkpoint.SnapshotManager.restore_latest: index, '
        'secs, dir, epoch, next_chunk — one event per data-plane '
        'restore (resume and degraded rollback both land here)',
    'mesh.stall':
        'resilience.run_with_deadline: scope, deadline_secs, healthy '
        '(last-known-healthy process set) — a fused/mesh dispatch '
        'exceeded GLT_DISPATCH_DEADLINE and was converted into a '
        'typed MeshStallError instead of hanging the epoch',
    'serving.request':
        'serving.frontend executor, one per de-multiplexed request: '
        'seeds, bucket, coalesced (requests in the dispatch), ok, '
        'latency_ms (arrival -> resolve; the percentile-table and '
        'bench p50/p95/p99 source), error when ok=False',
    'serving.coalesce':
        'serving.frontend executor, one per coalesced dispatch: '
        'requests, seeds, bucket (chosen capacity), waited_ms since '
        "the run's first arrival (how much of GLT_SERVING_MAX_WAIT_MS "
        'actually bound)',
    'serving.admit':
        'serving.admission.AdmissionController.submit: seeds, '
        'queue_depth after admit, deadline_ms — one per admitted '
        'request',
    'gns.bias':
        'DistNeighborSampler.step_for_batch (GNS mode, build time): '
        'batch, boost, num_parts — one event per compiled GNS step, '
        'recording the cached-neighbor boost that step samples with',
    'gns.sketch_update':
        'DistNeighborSampler._gns_arrays: scope, residents, version, '
        'mask_bytes — one event per cached-set bitmask refresh (the '
        'sketch-selected cold-cache residents ∪ hot split became the '
        'new sampling-bias membership table)',
    'serving.shed':
        'serving.admission: reason (queue_full|deadline|too_large), '
        'seeds, queue_depth, limit / waited_ms — one per typed '
        'load-shed (the request future resolves with '
        'AdmissionRejected; nothing is silently dropped)',
}


#: span NAME vocabulary (the `name` field of span.begin/span.end —
#: the per-stage rows of the report CLI and the Perfetto slices).
#: Same contract as EVENT_KINDS: every ``span('<name>', ...)`` call
#: site registers here, enforced by the same static test.
SPAN_NAMES: Dict[str, str] = {
    'batch':
        'per-batch root span (mesh + host-runtime loaders)',
    'sample.exchange':
        'mesh samplers: the fused sample+exchange SPMD dispatch',
    'feature.lookup':
        'mesh samplers, TIERED stores only: the cold-tier overlay '
        '(the per-batch host sync worth attributing)',
    'stitch':
        'mesh loaders: Batch pytree assembly',
    'recv':
        'host-runtime DistLoader: channel dequeue',
    'collate':
        'host-runtime DistLoader: message -> static-shape Batch '
        '(carries producer_trace/producer_span link fields)',
    'producer.sample':
        'sampling worker subprocess: one sample+send',
    'server.fetch':
        'DistServer: one blocking buffer pull for a client',
    'client.fetch':
        'DistClient: one RPC fetch round trip',
    'fused.epoch':
        'fused epoch drivers: one whole run() call',
    'fused.dispatch':
        'fused epoch drivers: one chunk/program dispatch (tiered '
        "epochs tag phase='collect'|'train')",
    'feature.cold_overlay':
        'tiered fused epochs: the between-dispatch host cold service '
        'for one chunk (cache serve + host overlay + admissions; '
        'steps = batches corrected)',
    'fused.init_state':
        'FusedTreeEpoch.init_state: param init from the dummy batch',
    'exchange.layout':
        'mesh samplers, build time: one span per compiled SPMD step '
        'with the resolved exchange layout (dense/compact/hier/'
        'ragged), num_parts and slack',
    'exchange.stage':
        'parallel.exchange.capacity_spec, build time: hierarchical '
        'stage capacities (rows, cols, stage1_cap, stage2_cap) for '
        'one planned exchange',
    'serving.infer':
        'serving.frontend executor: one warm bucketed dispatch '
        '(device program + tiered host fill) — bucket, requests, '
        'seeds; queue wait is OUTSIDE this span (serving.request '
        'latency_ms minus this span = admission/coalescing wait)',
}


def registered(kind: str) -> bool:
  return kind in EVENT_KINDS
