"""Per-tier memory accounting + the serve-capacity headroom model.

ROADMAP item 3 (SLO-driven elastic autoscaling) needs two signals
that existed nowhere: **where the bytes are** and **how much traffic
this replica could still absorb**.  This module declares both on the
live registry so the fleet scraper federates them for free:

  * `register_tier` — each memory owner (hot feature shards, the
    cold-cache HBM ring, the streaming delta-CSR reserve, GNS bitmask
    replication, the AOT executable cache on disk, the ingestion WAL)
    registers a zero-argument byte callback under a fixed ``tier=``
    label.  Two gauges per tier: ``memory.tier_bytes`` (scrape-time
    occupancy) and ``memory.tier_peak_bytes`` (high-watermark since
    registration — watermarks are tracked at scrape, so an idle
    process pays nothing).  Re-registering a tier replaces the
    callback ("latest instance wins", the registry's gauge contract).
  * `CapacityModel` — a per-bucket EWMA of coalesced-dispatch service
    cost (seconds per request, fed by the serving frontend after
    every dispatch).  Traffic-weighting the per-bucket costs gives
    the replica's sustainable capacity for its CURRENT mix; minus the
    SLO tracker's observed short-window QPS that is the
    ``fleet.headroom_qps`` gauge — the admission signal an autoscaler
    (or the router's placement policy) consumes per replica.

Everything here is scrape-time pull: byte callbacks and the headroom
division run on the ops server's thread, never on the serve path.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

#: the closed tier vocabulary (the ``tier=`` label's value domain);
#: ``pinned_host`` (r19) is the zero-copy cold feature buffer
#: (`data.cold_cache.PinnedColdBuffer`) — host-side bytes, but
#: accelerator-visible and part of the feature plane's budget
TIERS = ('hot', 'cold_cache', 'streaming', 'gns', 'aot', 'wal',
         'pinned_host')

#: EWMA smoothing for per-bucket dispatch cost (≈ the last ~10
#: dispatches dominate — fast enough to track a mix shift, slow
#: enough to ride out one cold-fill outlier)
_ALPHA = 0.2


def register_tier(tier: str, fn: Callable[[], Optional[float]],
                  registry=None) -> Callable[[], None]:
  """Export ``fn()`` bytes as the ``tier=<tier>`` occupancy gauge
  (plus its peak twin); returns an unregister callable for the owner's
  close path.  ``fn`` returning None (owner mid-teardown) drops the
  sample from that scrape — and leaves the peak standing."""
  if tier not in TIERS:
    raise ValueError(
        f'unknown memory tier {tier!r} — the closed vocabulary is '
        f'{TIERS} (extend memaccount.TIERS and the schema label doc '
        'together)')
  if registry is None:
    from .live import live as registry
  state = {'peak': None}

  def current() -> Optional[float]:
    v = fn()
    if v is None:
      return None
    v = float(v)
    if state['peak'] is None or v > state['peak']:
      state['peak'] = v
    return v

  def peak() -> Optional[float]:
    current()
    return state['peak']

  registry.gauge('memory.tier_bytes', labels={'tier': tier},
                 fn=current)
  registry.gauge('memory.tier_peak_bytes', labels={'tier': tier},
                 fn=peak)

  def unregister() -> None:
    registry.unregister_gauge('memory.tier_bytes', {'tier': tier},
                              fn=current)
    registry.unregister_gauge('memory.tier_peak_bytes',
                              {'tier': tier}, fn=peak)
  return unregister


class CapacityModel:
  """Per-bucket EWMA serve-cost model → ``fleet.headroom_qps``.

  Args:
    slo: the frontend's `SloTracker` (its short-window QPS is the
      "traffic already carried" term; None = headroom equals raw
      capacity).
    registry: `LiveRegistry` to export on (None = the global one).

  The serving executor is serial, so with per-request service cost
  ``c_b`` for bucket ``b`` and observed request mix ``w_b``, the
  sustainable rate is ``1 / Σ (w_b/Σw) · c_b`` — capacity for the
  mix actually being served, not a best-case single-bucket number.
  """

  def __init__(self, slo=None, registry=None):
    if registry is None:
      from .live import live as registry
    self._registry = registry
    self._slo = slo
    self._lock = threading.Lock()
    self._cost: Dict[int, float] = {}     # bucket -> EWMA secs/request
    self._weight: Dict[int, float] = {}   # bucket -> requests seen
    # ONE bound-method object, pinned: the registry's fn-identity
    # unregister guard compares with `is`, and each `self._headroom`
    # access would mint a fresh bound method
    self._headroom_fn = self._headroom
    registry.gauge('fleet.headroom_qps', fn=self._headroom_fn)

  def observe(self, bucket: int, requests: int, secs: float) -> None:
    """Fold one coalesced dispatch (``requests`` riders served in
    ``secs`` of executor wall time) into the bucket's cost EWMA."""
    if requests <= 0 or secs < 0:
      return
    per_req = float(secs) / float(requests)
    with self._lock:
      prev = self._cost.get(bucket)
      self._cost[bucket] = (per_req if prev is None
                            else prev + _ALPHA * (per_req - prev))
      self._weight[bucket] = \
          self._weight.get(bucket, 0.0) + float(requests)

  def capacity_qps(self) -> Optional[float]:
    """Traffic-weighted sustainable request rate (None until the
    first dispatch lands)."""
    with self._lock:
      total_w = sum(self._weight.values())
      if not total_w:
        return None
      mean_cost = sum(self._weight[b] * self._cost[b]
                      for b in self._cost) / total_w
    if mean_cost <= 0:
      return None
    return 1.0 / mean_cost

  def _headroom(self) -> Optional[float]:
    cap = self.capacity_qps()
    if cap is None:
      return None
    carried = 0.0
    if self._slo is not None:
      st = self._slo._cached_stats(self._slo.windows[0])
      if st['count']:
        carried = float(st['qps'])
    return round(max(cap - carried, 0.0), 3)

  def snapshot(self) -> dict:
    with self._lock:
      return {'cost_secs_per_request': dict(self._cost),
              'requests_seen': dict(self._weight)}

  def close(self) -> None:
    """Unregister the headroom gauge (fn-identity guarded: a closed
    frontend must not evict its replacement's gauge)."""
    self._registry.unregister_gauge('fleet.headroom_qps',
                                    fn=self._headroom_fn)
