"""Post-mortem flight-recorder bundles — the data plane's black box.

A `MeshStallError`, an irrecoverable worker pool, a serving-executor
fault or a fatal signal today leaves NO artifact unless a bench
harness happened to be tee'ing the recorder to a file; the operator's
first question ("what was in flight?") is unanswerable after the
process dies.  With ``GLT_POSTMORTEM_DIR`` set, :func:`dump` writes
one self-contained timestamped JSON bundle at the moment of death:

  * the recorder's in-memory ring (the last ~4096 events — spans in
    flight, faults injected, retries, the final drain windows),
  * a full live-metrics snapshot (counters + evaluated gauges),
  * the ``/healthz`` view (per-component supervision state),
  * the error and caller-provided context.

``telemetry/report.py --postmortem <bundle>`` renders it: spans still
open at dump time, event counts over the final window, the resilience
and serving tables, supervision state.

Dumps are one-shot per ``(directory, reason)`` and capped per process
(a degraded-rollback loop that stalls three times produces one
``mesh.stall`` bundle, not three), written atomically (tmp + rename),
and NEVER raise into the dying code path — a failed post-mortem must
not mask the original error.  Everything is a no-op (one env read)
when ``GLT_POSTMORTEM_DIR`` is unset.
"""
from __future__ import annotations

import json
import os
import signal as _signal
import threading
import time
from typing import Any, Dict, Optional

POSTMORTEM_DIR_ENV = 'GLT_POSTMORTEM_DIR'

BUNDLE_SCHEMA = 'glt.postmortem.v1'

#: per-process cap across all reasons (a pathological fault storm must
#: not fill the disk with bundles)
_MAX_DUMPS = 16

_lock = threading.Lock()
_dumped: set = set()                 # {(directory, reason)}
_count = 0
_signals_installed = False


def postmortem_dir() -> Optional[str]:
  return os.environ.get(POSTMORTEM_DIR_ENV) or None


def enabled() -> bool:
  return postmortem_dir() is not None


def reset() -> None:
  """Forget one-shot state (tests re-point GLT_POSTMORTEM_DIR)."""
  global _count
  with _lock:
    _dumped.clear()
    _count = 0


def _error_block(error: BaseException) -> Dict[str, Any]:
  out: Dict[str, Any] = {'type': type(error).__name__,
                         'message': str(error)[:2000]}
  for attr in ('scope', 'healthy', 'deadline', 'peer', 'reason',
               'outstanding', 'received', 'expected'):
    v = getattr(error, attr, None)
    if v is not None:
      out[attr] = v if isinstance(v, (str, int, float, bool)) else repr(v)
  return out


def dump(reason: str, error: Optional[BaseException] = None,
         extra: Optional[dict] = None) -> Optional[str]:
  """Write one post-mortem bundle; returns its path (None when
  disabled, already dumped for this reason, or the write failed —
  never raises into the dying code path)."""
  directory = postmortem_dir()
  if directory is None:
    return None
  global _count
  with _lock:
    key = (directory, reason)
    if key in _dumped or _count >= _MAX_DUMPS:
      return None
    _dumped.add(key)
    _count += 1
  try:
    return _write_bundle(directory, reason, error, extra)
  except Exception:                 # noqa: BLE001 — a failed post-
    # mortem must never mask the original fault it documents
    return None


def _write_bundle(directory: str, reason: str,
                  error: Optional[BaseException],
                  extra: Optional[dict]) -> str:
  from .recorder import _safe_dumps, recorder
  # capture the ring BEFORE emitting postmortem.dump, so the bundle
  # holds only the history that led here (the dump event itself goes
  # to the live stream / any JSONL sink)
  events = recorder.events()
  rec_stats = recorder.stats()
  bundle: Dict[str, Any] = {
      'schema': BUNDLE_SCHEMA,
      'reason': reason,
      'ts': round(time.time(), 6),
      'mono': round(time.monotonic(), 6),
      'pid': os.getpid(),
  }
  if error is not None:
    bundle['error'] = _error_block(error)
  if extra:
    bundle['extra'] = extra
  try:
    from .live import live
    bundle['metrics'] = live.snapshot()
    bundle['health'] = live.healthz()
  except Exception as e:            # noqa: BLE001 — a broken gauge
    # callback must not cost the operator the event ring
    bundle['metrics_error'] = f'{type(e).__name__}: {e}'
  try:
    # the history rings: a crash dump shows burn-rate / queue depth /
    # ingest lag leading INTO the incident, not just the final sample
    from . import timeseries
    store = timeseries.global_store()
    if store is not None:
      bundle['timeseries'] = store.query()
  except Exception as e:            # noqa: BLE001 — same contract
    bundle['timeseries_error'] = f'{type(e).__name__}: {e}'
  bundle['recorder'] = rec_stats
  bundle['events'] = events
  os.makedirs(directory, exist_ok=True)
  stamp = time.strftime('%Y%m%dT%H%M%S', time.gmtime())
  name = (f'postmortem-{stamp}-{os.getpid()}-'
          f'{reason.replace(".", "_").replace("/", "_")}.json')
  path = os.path.join(directory, name)
  tmp = path + '.tmp'
  with open(tmp, 'w') as f:
    # event dicts already passed the recorder's jsonable coercion;
    # _safe_dumps degrades anything that still can't serialize
    f.write(_safe_dumps(bundle))
  os.replace(tmp, path)             # atomic publish: no torn bundles
  try:
    from ..utils.profiling import metrics
    metrics.inc('postmortem.dumps_total')
    recorder.emit('postmortem.dump', reason=reason, path=path,
                  events=len(events),
                  error=(f'{type(error).__name__}: {error}'[:200]
                         if error is not None else None))
  except Exception:                 # noqa: BLE001 — best-effort
    pass
  return path


def load_bundle(path: str) -> dict:
  """Read a bundle back (the report CLI's ``--postmortem`` input)."""
  with open(path) as f:
    obj = json.load(f)
  if obj.get('schema') != BUNDLE_SCHEMA:
    raise ValueError(
        f'{path} is not a post-mortem bundle (schema '
        f'{obj.get("schema")!r}, expected {BUNDLE_SCHEMA!r})')
  return obj


def install_signal_handlers(signums=(getattr(_signal, 'SIGTERM', None),)
                            ) -> bool:
  """Chain a dump-then-previous handler on fatal signals (the
  preemption path: SIGTERM from the scheduler).  Idempotent; only
  works from the main thread (callers off it get False, not a raise);
  a no-op unless ``GLT_POSTMORTEM_DIR`` is set."""
  global _signals_installed
  if not enabled():
    return False
  with _lock:
    if _signals_installed:
      return True
  handlers = {}

  def _make(prev, signum):
    def _handler(sig, frame):
      # dump on a HELPER thread with a bounded join, never inline:
      # the handler interrupts the main thread mid-bytecode, and if
      # that thread holds recorder._lock / Metrics._lock (emit runs
      # constantly), an inline dump would block on its own thread's
      # non-reentrant lock forever — the process would neither write
      # the bundle nor die.  Off-thread, a held lock merely costs
      # the bundle (join times out) and termination proceeds.
      reason = f'signal.{_signal.Signals(signum).name.lower()}'
      t = threading.Thread(target=dump, args=(reason,), daemon=True)
      t.start()
      t.join(10.0)
      if callable(prev):
        prev(sig, frame)
      elif prev is None or prev == _signal.SIG_DFL:
        # restore + re-raise so the process still dies with the
        # default disposition (exit code, core) the operator expects.
        # `None` = a handler installed OUTSIDE Python (embedded
        # interpreter / C launcher): we cannot chain to it, but
        # swallowing the signal would hang the preempted process —
        # default-and-die is the honest fallback.
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)
    return _handler

  try:
    for signum in signums:
      if signum is None:
        continue
      prev = _signal.getsignal(signum)
      handlers[signum] = prev
      _signal.signal(signum, _make(prev, signum))
  except ValueError:
    # not the main thread: signal.signal refuses before any handler
    # was replaced (it raises on the FIRST call), so nothing to undo
    return False
  with _lock:
    _signals_installed = True
  return True
