"""Serving SLO tracking: sliding-window percentiles + burn rate.

A latency SLO of the form "99% of requests under ``T`` ms" carries an
*error budget*: 1% of requests may exceed ``T``.  The operational
signal is not "is p99 over T right now" (too noisy at low traffic,
too slow at high) but the **burn rate** — how fast the window is
consuming that budget::

    burn = (violating_requests / requests) / 0.01

``burn == 1`` exactly spends the budget; ``burn == 50`` (half of all
requests violating) exhausts a month of budget in ~14 hours.  Tracking
it over TWO windows (default 60 s and 300 s) is the standard
multi-window alerting shape: the short window catches a fast burn
early, the long window filters blips.

`SloTracker` keeps a bounded deque of ``(mono, latency_ms, ok)``
samples, exports everything as live gauges (``serving.slo.*`` —
scrape-time evaluation, so an idle tier costs nothing), and emits a
one-shot ``slo.burn`` flight-recorder event when a window's burn rate
crosses 1.0 (re-arming when it recovers — each sustained incident
logs once, not once per request).

Targets come from ``GLT_SERVING_SLO_P99_MS`` (latency, 0/unset =
track percentiles but never burn) and ``GLT_SERVING_SLO_QPS``
(throughput floor, exported as the ``serving.slo.qps_ratio`` gauge —
deliberately NOT a burn trigger: an idle tier under-serves its QPS
target legitimately; the latency budget is the alarm).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, Optional, Tuple

SLO_P99_ENV = 'GLT_SERVING_SLO_P99_MS'
SLO_QPS_ENV = 'GLT_SERVING_SLO_QPS'

#: p99 SLO => 1% of requests may violate
DEFAULT_BUDGET = 0.01
DEFAULT_WINDOWS = (60.0, 300.0)

#: hard sample bound: 300 s at ~600 rps — past it the oldest samples
#: age out early (the burn rate stays right for the traffic it saw)
_MAX_SAMPLES = 200_000

#: re-evaluate burn on the observe path at most this often (scrapes
#: always evaluate fresh) — keeps the hot path at an append plus a
#: comparison, with the periodic eval a SORT-FREE single pass (full
#: percentile math runs only when a trip actually fires, and at
#: scrape time on the ops server's own thread)
_EVAL_INTERVAL_S = 1.0


def slo_p99_ms_from_env() -> float:
  try:
    return max(float(os.environ.get(SLO_P99_ENV, 0.0)), 0.0)
  except ValueError:
    return 0.0


def slo_qps_from_env() -> float:
  try:
    return max(float(os.environ.get(SLO_QPS_ENV, 0.0)), 0.0)
  except ValueError:
    return 0.0


class SloTracker:
  """Sliding-window latency/throughput SLO state for one serving tier.

  Args:
    p99_target_ms: latency SLO (None = ``GLT_SERVING_SLO_P99_MS``;
      0 = no latency SLO — percentiles/qps still tracked).
    qps_target: throughput floor (None = ``GLT_SERVING_SLO_QPS``).
    windows: (short, long) sliding windows in seconds.
    budget: allowed violating fraction (0.01 for a p99 SLO).
    registry: `LiveRegistry` to export gauges on (None = the global
      one; gauges evaluate lazily at scrape).
    clock: monotonic time source (tests inject a fake).
  """

  def __init__(self, p99_target_ms: Optional[float] = None,
               qps_target: Optional[float] = None,
               windows: Tuple[float, ...] = DEFAULT_WINDOWS,
               budget: float = DEFAULT_BUDGET,
               registry=None, clock=time.monotonic):
    self.p99_target_ms = (slo_p99_ms_from_env()
                          if p99_target_ms is None
                          else max(float(p99_target_ms), 0.0))
    self.qps_target = (slo_qps_from_env() if qps_target is None
                       else max(float(qps_target), 0.0))
    self.windows = tuple(sorted(float(w) for w in windows))
    self.budget = float(budget)
    self._clock = clock
    self._lock = threading.Lock()
    self._samples: 'collections.deque[Tuple[float, float, bool]]' = \
        collections.deque(maxlen=_MAX_SAMPLES)
    #: per-window memo of (now, stats) — one scrape reads up to six
    #: gauges, and each full evaluation copies + sorts the window;
    #: within one scrape burst they all share one computation
    self._stats_cache: Dict[float, Tuple[float, dict]] = {}
    self._started = clock()
    self._tripped: Dict[float, bool] = {w: False for w in self.windows}
    self._last_eval = -1e18
    if registry is None:
      from .live import live as registry
    self._registry = registry
    self._registered: list = []     # [(name, labels, fn)] for close()
    self._register_gauges(registry)

  def close(self) -> None:
    """Unregister this tracker's gauges (callback closures retain the
    sample window — a closed serving tier must not pin up to 200k
    samples for process lifetime).  Gauge instances a NEWER tracker
    already took over are left alone (fn-identity guarded)."""
    for name, labels, fn in self._registered:
      self._registry.unregister_gauge(name, labels, fn=fn)
    self._registered = []

  # -- feeding -------------------------------------------------------------
  def observe(self, latency_ms: float, ok: bool = True) -> None:
    """Record one resolved request (failed requests count against the
    budget regardless of latency).  O(1) amortized; burn evaluation
    is throttled to `_EVAL_INTERVAL_S`."""
    now = self._clock()
    with self._lock:
      self._samples.append((now, float(latency_ms), bool(ok)))
      horizon = now - self.windows[-1]
      while self._samples and self._samples[0][0] < horizon:
        self._samples.popleft()
      due = now - self._last_eval >= _EVAL_INTERVAL_S
      if due:
        self._last_eval = now
    if due and self.p99_target_ms > 0:
      self._evaluate_burn(now)

  # -- window math ---------------------------------------------------------
  def _window_samples(self, window: float, now: float):
    horizon = now - window
    with self._lock:
      return [s for s in self._samples if s[0] >= horizon]

  def window_stats(self, window: float,
                   now: Optional[float] = None) -> dict:
    """count / p50 / p99 (ms, over OK requests) / qps / violations /
    burn for one window.  ``qps`` divides by the elapsed time when the
    process is younger than the window (a fresh tier is not "under its
    QPS floor" for its first five minutes)."""
    now = self._clock() if now is None else now
    samples = self._window_samples(window, now)
    span = max(min(window, now - self._started), 1e-9)
    ok_lats = sorted(lat for _, lat, ok in samples if ok)
    violations = sum(1 for _, lat, ok in samples
                     if not ok or (self.p99_target_ms > 0
                                   and lat > self.p99_target_ms))
    count = len(samples)
    # empty/idle windows and zero-budget trackers read burn 0.0, never
    # NaN or a division error — the ElasticController's first
    # evaluation after admitting a fresh replica depends on it
    # (ISSUE 19: an idle replica must not look like it is burning)
    burn = ((violations / count) / self.budget
            if count and self.p99_target_ms > 0 and self.budget > 0
            else 0.0)

    def q(p: float) -> float:
      if not ok_lats:
        return 0.0
      i = min(int(p * (len(ok_lats) - 1) + 0.5), len(ok_lats) - 1)
      return ok_lats[i]

    return {'window_secs': window, 'count': count,
            'p50_ms': round(q(0.5), 3), 'p99_ms': round(q(0.99), 3),
            'qps': round(len(ok_lats) / span, 3),
            'violations': violations, 'burn_rate': round(burn, 4)}

  def _window_burn(self, window: float, now: float
                   ) -> Tuple[int, float]:
    """(count, burn) for one window in a single sort-free pass —
    the executor-thread evaluation must not pay the percentile sort
    (at 600 rps the 300 s window holds ~180k samples; sorting them
    every eval would inflate the very p99 being tracked)."""
    horizon = now - window
    count = violations = 0
    with self._lock:
      for t, lat, ok in reversed(self._samples):
        if t < horizon:
          break                      # deque is time-ordered
        count += 1
        if not ok or lat > self.p99_target_ms:
          violations += 1
    burn = ((violations / count) / self.budget
            if count and self.budget > 0 else 0.0)
    return count, burn

  def _evaluate_burn(self, now: float) -> None:
    from .recorder import recorder
    for w in self.windows:
      count, burn = self._window_burn(w, now)
      burning = count > 0 and burn > 1.0
      if burning and not self._tripped[w]:
        self._tripped[w] = True
        # full stats (percentile sort included) only here — once per
        # incident, not once per eval
        st = self.window_stats(w, now)
        recorder.emit('slo.burn', window_secs=w,
                      burn_rate=st['burn_rate'], p99_ms=st['p99_ms'],
                      target_p99_ms=self.p99_target_ms,
                      qps=st['qps'], count=st['count'])
      elif not burning and self._tripped[w]:
        self._tripped[w] = False     # re-arm: next incident logs again

  def _cached_stats(self, window: float) -> dict:
    """`window_stats` memoized across one scrape BURST: the
    scrape-time gauges (p50/p99/qps/qps_ratio off the short window,
    burn per window) render within ~a millisecond of each other, so
    a 20 ms memo collapses their six copy+sort evaluations into at
    most one per window — while staying far below any real scrape
    interval, so back-to-back scrapes (and asserts right after a
    traffic burst) always see fresh samples.

    Memo HITS are lock-free: the cache dict is only ever read/written
    whole-entry (CPython dict get/set are atomic), so the time-series
    cadence loop sampling these gauges at high rate never contends
    with `observe()` on the tracker lock — only the one fresh
    `window_stats` per 20 ms burst pays it (pinned by the
    lock-acquisition test in ``tests/test_timeseries.py``)."""
    now = self._clock()
    entry = self._stats_cache.get(window)
    # the entry is stale both past 20 ms AND when the clock moved
    # BACKWARDS (an injected test clock rewound, or a new tracker
    # reusing the memo after its predecessor): a frozen entry from
    # the future would otherwise be served forever
    if entry is not None and 0 <= now - entry[0] < 0.02:
      return entry[1]
    st = self.window_stats(window, now)
    self._stats_cache[window] = (now, st)
    return st

  # -- export --------------------------------------------------------------
  def snapshot(self) -> dict:
    """Per-window stats + targets (the heartbeat/post-mortem block).
    Reads through the scrape memo: heartbeat RPCs and /healthz polls
    must not pay (or serialize observe() behind) a fresh full-window
    copy+sort each."""
    return {'p99_target_ms': self.p99_target_ms,
            'qps_target': self.qps_target,
            'windows': [self._cached_stats(w) for w in self.windows]}

  def _register_gauges(self, registry) -> None:
    short = self.windows[0]

    # local `gauge` keeps registration call sites LITERAL (the glint
    # metric-name pass reads the first string arg of gauge(...) calls)
    # while also recording each (name, labels, fn) for close()
    def gauge(name, labels, fn):
      registry.gauge(name, labels=labels, fn=fn)
      self._registered.append((name, labels, fn))

    def stat(key: str):
      def read() -> Optional[float]:
        st = self._cached_stats(short)
        return float(st[key]) if st['count'] else None
      return read

    gauge('serving.slo.p50_ms', None, stat('p50_ms'))
    gauge('serving.slo.p99_ms', None, stat('p99_ms'))
    gauge('serving.slo.qps', None, stat('qps'))
    for w in self.windows:
      def burn(w=w) -> Optional[float]:
        st = self._cached_stats(w)
        if not st['count'] or self.p99_target_ms <= 0:
          return None
        return float(st['burn_rate'])
      gauge('serving.slo.burn_rate', {'window': f'{int(w)}s'}, burn)

    def qps_ratio() -> Optional[float]:
      if self.qps_target <= 0:
        return None
      st = self._cached_stats(short)
      return (round(st['qps'] / self.qps_target, 4)
              if st['count'] else None)
    gauge('serving.slo.qps_ratio', None, qps_ratio)
