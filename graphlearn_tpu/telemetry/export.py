"""Flight-recorder dump -> Chrome trace-event JSON (Perfetto-loadable).

A recorder JSONL (``GLT_TELEMETRY_JSONL`` or `EventRecorder.dump`) is
a flat event stream; this module turns it into the Chrome trace-event
format (the JSON array flavor) that https://ui.perfetto.dev and
``chrome://tracing`` open directly:

  * paired ``span.begin``/``span.end`` events (`telemetry.spans`)
    become COMPLETE ``"ph": "X"`` slices — name, ``ts``/``dur`` in
    microseconds on the monotonic timebase, ``pid``/``tid`` rows, and
    the span's trace/parent ids + extra fields under ``args`` (so
    Perfetto's query/flow UI can reconstruct the causal tree);
  * every other event kind becomes an INSTANT ``"ph": "i"`` marker on
    the same timeline (scope ``"t"``), so channel stalls and slack
    transitions line up against the spans that suffered them.

Unpaired begins (a crash mid-span, a recorder disable between begin
and end) are dropped rather than guessed at — the X-slice encoding
keeps every emitted slice begin/end balanced by construction.

The human-facing side of the same dump (per-stage latency tables,
trace diffs) lives in :mod:`.report`.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

SPAN_BEGIN = 'span.begin'
SPAN_END = 'span.end'


def load_events(path: str) -> List[Dict]:
  """Read a recorder JSONL dump; malformed lines (a kill mid-write on
  a shared file) are skipped, not fatal."""
  out = []
  with open(path) as f:
    for ln in f:
      ln = ln.strip()
      if not ln:
        continue
      try:
        out.append(json.loads(ln))
      except json.JSONDecodeError:
        continue
  return out


def _time_origins(events: List[Dict]):
  """Per-timebase zero points: `mono` events offset from the earliest
  mono, pre-`mono` events (an appended-to old dump) from the earliest
  wall ``ts`` — mixing the two bases against one origin would fling
  whichever group loses ~decades down the timeline."""
  monos = [float(e['mono']) for e in events if 'mono' in e]
  tss = [float(e['ts']) for e in events
         if 'mono' not in e and 'ts' in e]
  return (min(monos) if monos else 0.0, min(tss) if tss else 0.0)


def _event_us(ev: Dict, t0_mono: float, t0_ts: float) -> float:
  """Event time in microseconds on its own timebase's origin."""
  if 'mono' in ev:
    return (float(ev['mono']) - t0_mono) * 1e6
  return (float(ev.get('ts', 0.0)) - t0_ts) * 1e6


_META = ('kind', 'name', 'trace_id', 'span_id', 'parent_id', 'pid',
         'tid', 'ts', 'mono', 'dur')


def to_chrome_trace(events: List[Dict],
                    include_instants: bool = True) -> Dict:
  """Convert a recorder event list to a Chrome trace-event object
  (``{'traceEvents': [...], ...}``)."""
  if not events:
    return {'traceEvents': [], 'displayTimeUnit': 'ms'}
  t0_mono, t0_ts = _time_origins(events)
  begins: Dict[str, Dict] = {}
  out: List[Dict] = []
  for ev in events:
    kind = ev.get('kind')
    if kind == SPAN_BEGIN:
      sid = ev.get('span_id')
      if sid is not None:
        begins[sid] = ev
    elif kind == SPAN_END:
      b = begins.pop(ev.get('span_id'), None)
      if b is None:
        continue                      # unpaired end: drop
      dur_us = float(ev.get('dur', 0.0)) * 1e6
      args = {k: v for k, v in b.items() if k not in _META}
      args.update({k: v for k, v in ev.items() if k not in _META})
      args['trace_id'] = b.get('trace_id')
      args['parent_id'] = b.get('parent_id')
      args['span_id'] = b.get('span_id')
      out.append({
          'name': b.get('name', 'span'), 'ph': 'X', 'cat': 'span',
          'ts': round(_event_us(b, t0_mono, t0_ts), 3),
          'dur': round(max(dur_us, 0.0), 3),
          'pid': int(b.get('pid', 0)), 'tid': int(b.get('tid', 0)),
          'args': args,
      })
    elif include_instants:
      out.append({
          'name': kind or 'event', 'ph': 'i', 'cat': 'event', 's': 't',
          'ts': round(_event_us(ev, t0_mono, t0_ts), 3),
          'pid': int(ev.get('pid', 0)), 'tid': int(ev.get('tid', 0)),
          'args': {k: v for k, v in ev.items()
                   if k not in ('kind', 'ts', 'mono', 'pid', 'tid')},
      })
  # cross-process causality: a child slice whose parent slice lives
  # on a DIFFERENT pid gets a flow arrow (ph 's' at the parent, ph
  # 'f' binding to the end of the child's enclosing slice) — the RPC
  # edge Perfetto cannot infer from same-track nesting
  slices = {e['args'].get('span_id'): e for e in out
            if e.get('ph') == 'X' and e['args'].get('span_id')}
  flows: List[Dict] = []
  for sid, sl in slices.items():
    parent = slices.get(sl['args'].get('parent_id'))
    if parent is None or parent['pid'] == sl['pid']:
      continue
    flows.append({'name': 'rpc', 'ph': 's', 'cat': 'flow',
                  'id': str(sid), 'ts': parent['ts'],
                  'pid': parent['pid'], 'tid': parent['tid']})
    flows.append({'name': 'rpc', 'ph': 'f', 'bp': 'e', 'cat': 'flow',
                  'id': str(sid), 'ts': sl['ts'],
                  'pid': sl['pid'], 'tid': sl['tid']})
  out.extend(flows)
  out.sort(key=lambda e: e['ts'])
  return {'traceEvents': out, 'displayTimeUnit': 'ms'}


def write_chrome_trace(src_jsonl: str, dest_json: str,
                       include_instants: bool = True) -> int:
  """JSONL dump -> Chrome trace file; returns the trace-event count."""
  trace = to_chrome_trace(load_events(src_jsonl),
                          include_instants=include_instants)
  with open(dest_json, 'w') as f:
    json.dump(trace, f)
  return len(trace['traceEvents'])


def span_durations(events: List[Dict]) -> Dict[str, List[float]]:
  """Per-kind lists of span durations (seconds) from ``span.end``
  events — the raw material of the report tables."""
  out: Dict[str, List[float]] = {}
  for ev in events:
    if ev.get('kind') == SPAN_END and ev.get('dur') is not None:
      out.setdefault(ev.get('name', 'span'), []).append(
          float(ev['dur']))
  return out


def span_children(events: List[Dict]) -> Dict[Optional[str], List[str]]:
  """``{parent_span_id: [child_span_id, ...]}`` from begin events —
  the causal tree (roots under key None).  Begin lines missing a
  span_id (truncated shared-file writes) are skipped, matching
  `to_chrome_trace`."""
  out: Dict[Optional[str], List[str]] = {}
  for ev in events:
    if ev.get('kind') == SPAN_BEGIN and ev.get('span_id') is not None:
      out.setdefault(ev.get('parent_id'), []).append(ev['span_id'])
  return out
