"""Causal span layer over the flight recorder.

PR 1's recorder captures point events; answering "which stage of batch
1317 stalled — the exchange, the cold-tier drain, or the feature
gather?" needs *causally linked* spans with durations.  A span is a
``span.begin`` / ``span.end`` event pair sharing a ``span_id``, linked
into a tree by ``trace_id`` (the root's id) and ``parent_id``:

    {"kind": "span.begin", "name": "batch", "trace_id": "ab..",
     "span_id": "ab..", "parent_id": null, "pid": 71, "tid": 139.., ...}
    {"kind": "span.end",   "name": "batch", "span_id": "ab..",
     "dur": 0.0123, ...}

Durations come from the MONOTONIC clock (the recorder's ``mono``
field's timebase), so a wall-clock step/NTP slew mid-span cannot
produce negative or wild durations.  Each ``span.end`` also ticks the
per-kind log2 latency histogram (:mod:`.histogram`), which is what the
``telemetry.report`` CLI and the cross-host `gather_metrics` merge
read.

The ambient CURRENT span is a `contextvars.ContextVar`: ``span()``
blocks nest naturally per thread/task, and a fresh thread starts a
fresh trace (prefetch workers become their own roots).  For the
DISTRIBUTED pipeline the context crosses process boundaries as a tiny
uint8 tensor riding each `SampleMessage` under :data:`SPAN_KEY` — the
channels inject the sender's context on ``send`` and strip it on
``recv`` (`channel.base`), so a consumer can attribute its recv/collate
work to the producer's trace (``producer_trace`` / ``producer_span``
fields on the consumer's spans).

Cost when the recorder is OFF: one context-manager allocation and one
attribute check per ``span()`` block — safe for hot host paths.
"""
from __future__ import annotations

import contextvars
import json
import os
import time
from typing import NamedTuple, Optional

from .recorder import recorder

#: `SampleMessage` key carrying the serialized span context (uint8
#: JSON payload — every channel transport ships numpy arrays).
SPAN_KEY = '#SPAN'


class SpanContext(NamedTuple):
  """The propagated identity of an open span."""
  trace_id: str
  span_id: str


_CURRENT: contextvars.ContextVar[Optional[SpanContext]] = \
    contextvars.ContextVar('glt_span', default=None)


def _new_id() -> str:
  return os.urandom(8).hex()


def current() -> Optional[SpanContext]:
  """The ambient span context (None outside any span)."""
  return _CURRENT.get()


#: event fields the span machinery itself writes; a caller field with
#: one of these names is renamed ``<name>_`` instead of raising a
#: TypeError out of the hot path the moment telemetry gets enabled.
_RESERVED = frozenset(('kind', 'ts', 'mono', 'pid', 'tid', 'name',
                       'trace_id', 'span_id', 'parent_id', 'dur',
                       'error'))


class span:
  """Context manager / decorator: one timed, causally-linked span.

  >>> with span('batch', batch=7):
  ...   with span('sample.exchange'):    # child of 'batch'
  ...     dispatch()

  ``parent`` overrides the ambient parent (e.g. a `SpanContext`
  extracted from a channel message); extra keyword fields land on both
  the begin and end events (names colliding with the span machinery's
  own fields — `_RESERVED` — are suffixed with ``_``).  When the
  flight recorder is off the whole block is a no-op (one attribute
  check).  The yielded value is the span's `SpanContext` (None when
  disabled).
  """

  __slots__ = ('kind', 'fields', 'parent', 'ctx', '_token', '_t0')

  def __init__(self, kind: str, parent: Optional[SpanContext] = None,
               **fields):
    self.kind = kind
    self.fields = fields
    self.parent = parent
    self.ctx = None
    self._token = None
    self._t0 = 0.0

  def __enter__(self) -> Optional[SpanContext]:
    if self.ctx is not None:
      # re-entrant reuse of ONE instance would clobber _token and
      # leak the contextvar on exit, phantom-parenting every later
      # span on the thread; sequential reuse (ctx reset by __exit__)
      # stays fine
      raise RuntimeError(
          'span instance re-entered while open — construct a new '
          'span() for each nested block')
    if not recorder.enabled:
      return None
    # field normalization only on the enabled path — recorder-off cost
    # stays at the object allocation plus this one attribute check
    self.fields = {(k + '_' if k in _RESERVED else k): v
                   for k, v in self.fields.items()}
    parent = self.parent if self.parent is not None else _CURRENT.get()
    trace_id = parent.trace_id if parent else _new_id()
    sid = _new_id() if parent else trace_id   # root span id == trace id
    self.ctx = SpanContext(trace_id, sid)
    # pid/tid come from the recorder, which stamps them on EVERY event
    recorder.emit('span.begin', name=self.kind, trace_id=trace_id,
                  span_id=sid,
                  parent_id=parent.span_id if parent else None,
                  **self.fields)
    self._token = _CURRENT.set(self.ctx)
    # monotonic, not wall: durations must survive clock steps (the
    # recorder's `mono` field is the same timebase)
    self._t0 = time.monotonic()
    return self.ctx

  def __exit__(self, exc_type, exc, tb) -> bool:
    if self.ctx is None:
      return False
    dt = time.monotonic() - self._t0
    _CURRENT.reset(self._token)
    if recorder.enabled:
      # a disable() mid-span must keep the histogram and the trace's
      # span.end counts in agreement (both skip this span)
      from . import histogram
      histogram.record(self.kind, dt)
    recorder.emit('span.end', name=self.kind,
                  trace_id=self.ctx.trace_id, span_id=self.ctx.span_id,
                  parent_id=(self.parent.span_id if self.parent
                             else getattr(_CURRENT.get(), 'span_id',
                                          None)),
                  dur=round(dt, 6),
                  error=(exc_type.__name__ if exc_type else None),
                  **self.fields)
    self.ctx = None
    return False

  def __call__(self, fn):
    """Decorator form: ``@span('stage')``."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
      with type(self)(self.kind, parent=self.parent, **self.fields):
        return fn(*args, **kwargs)
    return wrapped


# -- cross-process propagation ---------------------------------------------

def inject(msg) -> None:
  """Attach the ambient span context to a `SampleMessage` in place
  (no-op when the recorder is off or no span is open).  The payload is
  a uint8 JSON tensor so every channel transport — pickle, shm
  tensor-map, socket RPC — carries it unchanged."""
  if not recorder.enabled:
    return
  ctx = _CURRENT.get()
  if ctx is None:
    return
  import numpy as np
  payload = json.dumps({'t': ctx.trace_id, 's': ctx.span_id})
  msg[SPAN_KEY] = np.frombuffer(payload.encode('utf-8'),
                                np.uint8).copy()


def extract(msg) -> Optional[SpanContext]:
  """Pop and decode the span context a producer injected into ``msg``
  (None when absent or malformed — a context must never break a
  batch)."""
  raw = msg.pop(SPAN_KEY, None) if hasattr(msg, 'pop') else None
  if raw is None:
    return None
  try:
    import numpy as np
    d = json.loads(bytes(bytearray(np.asarray(raw, np.uint8)))
                   .decode('utf-8'))
    return SpanContext(str(d['t']), str(d['s']))
  except Exception:             # noqa: BLE001 — degrade, never raise
    return None


def link_fields(ctx: Optional[SpanContext]) -> dict:
  """Cross-trace link fields for a span that CONSUMES another trace's
  message (the consumer's span stays in its own tree; the link records
  causality across the process boundary)."""
  if ctx is None:
    return {}
  return {'producer_trace': ctx.trace_id, 'producer_span': ctx.span_id}
