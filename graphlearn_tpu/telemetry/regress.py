"""Bench regression gate: artifact vs committed baseline.

The ROADMAP's "as fast as the hardware allows" has no automated guard:
nothing fails when a PR slows the hot path.  This module compares the
freshly written ``BENCH_ARTIFACT.json`` against a committed
``BENCH_BASELINE.json`` and FAILS (nonzero) with a per-metric report
when any headline metric regresses past a threshold (default 20%).
On the FIRST run — the bench trajectory starts empty — the artifact
itself becomes the baseline (verdict ``BASELINE_CREATED``), so the
gate bootstraps without manual setup; commit the baseline file to pin
it.

Deliberately import-light (json/os only), like `sink.py`: `bench.py`
loads it directly by file path so the driver process never pays the
package/jax import chain.  Also a CLI::

    python graphlearn_tpu/telemetry/regress.py ARTIFACT [BASELINE]
        [--threshold 0.2] [--update-baseline]

Env overrides: ``GLT_BENCH_BASELINE`` (baseline path),
``GLT_REGRESS_THRESHOLD`` (fractional slowdown tolerance).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

BASELINE_ENV = 'GLT_BENCH_BASELINE'
THRESHOLD_ENV = 'GLT_REGRESS_THRESHOLD'
DEFAULT_BASELINE = 'BENCH_BASELINE.json'
DEFAULT_THRESHOLD = 0.2

#: headline metrics the gate tracks: (dotted key, direction[, opts]).
#: 'lower' = smaller is better (times), 'higher' = bigger is better
#: (rates), 'present' = the key must exist as a number.  Keys absent
#: from either side are SKIPPED, not failed — phases degrade day to
#: day and a missing phase is not a regression.  The optional third
#: element is a per-metric options dict:
#:   'threshold'    — override the global slowdown tolerance
#:   'pin_baseline' — compare against a FIXED value instead of the
#:                    recorded baseline (absolute acceptance lines
#:                    that must not drift with the trajectory)
#:   'when'         — ('present' only) the guard applies only when
#:                    this other dotted key exists in the artifact
#:                    (i.e. the owning phase actually ran)
#:   'same'         — dotted context key (or tuple of keys) that must
#:                    hold the SAME value in artifact and baseline for
#:                    the comparison to apply; any mismatch SKIPS the
#:                    metric (e.g. rows measured under different
#:                    partitioners are not comparable — re-bootstrap
#:                    the baseline to re-arm the guard)
METRICS: Tuple[Tuple, ...] = (
    ('value', 'lower'),                       # the headline epoch time
    ('fused_epoch_secs', 'lower'),
    ('fused_epoch_secs_bf16', 'lower'),
    ('fused_hetero_epoch_secs', 'lower'),
    ('fused_subgraph_ms_per_step', 'lower'),
    ('train_step_mfu', 'higher'),
    ('dist.seeds_per_sec', 'higher'),
    ('dist.edges_per_sec_per_chip', 'higher'),
    # exchange-efficiency guard (ISSUE 3): the P=16 / P=64 rows of the
    # dist scale envelope — a PR that regresses padding waste or
    # throughput at scale fails the gate.  A 'pNN' path segment
    # selects the envelope row with num_parts == NN.
    ('dist.scale_envelope.p16.padding_waste_pct', 'lower'),
    ('dist.scale_envelope.p16.seeds_per_sec', 'higher'),
    ('dist.scale_envelope.p64.padding_waste_pct', 'lower'),
    ('dist.scale_envelope.p64.seeds_per_sec', 'higher'),
    # resilience guard (ISSUE 4): the host server->client loader path
    # WITH the retry/idempotency layer on, no faults injected — the
    # retry layer must not silently slow the fault-free hot path
    ('dist.chaos.fault_free_seeds_per_sec', 'higher'),
    # cold-cache guard (ISSUE 5): the tiered mesh-loader row — the
    # HBM victim cache + double-buffered cold overlay must keep the
    # tiered store's throughput and its on-device cache hit rate from
    # silently regressing back to the r5 static-split numbers
    ('dist.tiered.seeds_per_sec', 'higher'),
    ('dist.feature.cache_hit_rate', 'higher'),
    # cache-aware sampling guard (ISSUE 10): the GNS-on tiered row —
    # the sampler-side bias must keep beating the budget/universe
    # hit-rate ceiling AND hold the tiered throughput line (a PR that
    # silently un-biases the sampler or taxes the biased step fails
    # here, not in a notebook)
    ('dist.gns.cache_hit_rate', 'higher'),
    ('dist.gns.seeds_per_sec', 'higher'),
    # preemption-resume guard (ISSUE 6): restoring a mid-epoch
    # snapshot and re-entering the epoch must stay cheap — a resume
    # that re-executes half the epoch (replayed_batches creeping up)
    # or a restore path that grew a slow sync would erode exactly the
    # recovery-time story the snapshot layer exists for
    ('dist.resume.restore_secs', 'lower'),
    ('dist.resume.replayed_batches', 'lower'),
    # the snapshot-overhead acceptance line: snapshotting throughput
    # over the same run's no-snapshot line (~1.0 when saves are in
    # the noise).  Guarded as a positive RATIO, not the signed
    # overhead pct, whose healthy baseline straddles zero (the
    # cur/base slowdown math inverts on a negative baseline).
    ('dist.resume.snap_over_nosnap_ratio', 'higher'),
    # online-serving guard (ISSUE 9): the Zipf open-loop traffic row
    # (bench_serving.py) — tail latency and sustained completion rate
    # of the coalescing tier must not silently erode (shed_rate is
    # reported in the artifact; a healthy baseline of 0 makes it
    # ungateable by ratio, so the latency/throughput pair carries the
    # guard)
    ('dist.serving.p99_ms', 'lower'),
    ('dist.serving.qps', 'higher'),
    # fleet-failover guard (ISSUE 13): the kill-one-replica-mid-bench
    # acceptance run — sustained fleet completion rate must hold, and
    # failed/dropped requests must stay at the baseline (0: a zero
    # baseline skips here by the ratio rules, so the HARD zero-failure
    # gate is bench_serving's nonzero exit; this key catches drift
    # once any baseline records a nonzero count)
    ('dist.serving.fleet_qps', 'higher'),
    ('dist.serving.failover_failed_requests', 'lower'),
    # streaming-ingestion guard (ISSUE 14): the freshness-vs-
    # throughput open loop — sustained WAL->delta-CSR->publish
    # events/s must hold, and the serving p99 measured DURING
    # steady-state ingest must not erode (the zero-shed contract is
    # bench_ingest's nonzero exit, stamped into ingest_pin)
    ('dist.ingest.events_per_sec', 'higher'),
    ('dist.ingest.p99_during_ingest_ms', 'lower'),
    # elastic-failover guard (ISSUE 15): classification -> first
    # served batch must stay fast after a mid-epoch owner kill, and
    # the epoch must stay EXACTLY complete (1.0 — the hard
    # byte-identity/one-adoption gate is the worker's nonzero exit,
    # stamped into failover_pin)
    ('dist.failover.recovery_secs', 'lower'),
    ('dist.failover.completed_ratio', 'higher'),
    # traffic-attribution guard (ISSUE 16): the cross-partition byte
    # share at the P=16 envelope must not creep up (locality erosion
    # is invisible in throughput until it is not), and the top-K
    # hot-range coverage the GNS/exchange hotness export sees must
    # not collapse (a flat histogram means the sketch export lost the
    # skew signal the cold-tier placement feeds on)
    ('dist.attribution.cross_partition_bytes_frac', 'lower'),
    ('dist.attribution.hot_range_coverage', 'higher'),
    # locality co-design guard (ISSUE 20): the GLT_PARTITIONER=
    # locality envelope arm — the cross-partition byte share bought by
    # the relabel + replica set must not creep back toward random, and
    # the locality arm's throughput must hold its line.  Both guards
    # only compare rows measured under the SAME partitioner identity
    # ('same'): a baseline recorded under a different placement is
    # skipped, never silently ratcheted against
    ('dist.locality.cross_partition_bytes_frac', 'lower',
     {'same': 'dist.locality.partitioner'}),
    ('dist.locality.seeds_per_sec', 'higher',
     {'same': 'dist.locality.partitioner'}),
    # request-tracing guard (ISSUE 17): tracing-ON serve cost over
    # tracing-OFF on the same closed-loop schedule.  Pinned against a
    # FIXED 1.0 baseline with a 5% tolerance, so the gate reads
    # exactly "ratio <= 1.05" — a drifting recorded baseline must
    # never ratchet the acceptance line upward
    ('dist.serving.tracing_overhead_ratio', 'lower',
     {'threshold': 0.05, 'pin_baseline': 1.0}),
    # capacity-signal guard (ISSUE 17): whenever the fleet phase ran
    # at all, the replicas' EWMA capacity model must have exported a
    # live fleet.headroom_qps — the gauge's VALUE swings with load
    # (ungateable by ratio), but its absence means the autoscaler's
    # admission signal silently died
    ('dist.serving.fleet_headroom_qps', 'present',
     {'when': 'dist.serving.fleet_qps'}),
    # Pallas fused-pipeline guards (ISSUE 18, bench_pallas_sample.py):
    # the dispatcher-threaded FusedEpoch step with the kernels OFF —
    # the r19 threading (window-table staging, trace-time dispatch)
    # must not tax the default path
    ('pallas.fused_step_ms', 'lower'),
    # the pinned-host zero-copy cold gather at split<1, pinned against
    # the FIXED untiered XLA gather line (r18 roofline: 1.355 GB/s) —
    # the tiered store must not fall back behind the line the pinned
    # buffer exists to beat.  Hardware-only: the bench stamps the key
    # None on CPU, so the guard skips cleanly there
    ('pallas.feature_lookup_gbps', 'higher', {'pin_baseline': 1.355}),
    # the host delta-CSR merge rate (platform-independent; the device
    # kernel row is reported alongside, unguarded until a TPU baseline
    # lands)
    ('pallas.delta_merge_events_per_sec', 'higher'),
    # elastic-autoscaling guards (ISSUE 19, bench_autoscale.py): the
    # diurnal open loop's p99 with the ElasticController closing the
    # loop must not erode vs its own history (the hold-vs-static gate
    # is the worker's nonzero exit, stamped into autoscale_pin)
    ('dist.autoscale.p99_held_ms', 'lower'),
    # SLO burn outside the chaos incident window, pinned against the
    # FIXED burn budget of 1.0 with zero tolerance — the gate reads
    # exactly "burn_max < 1 outside the incident", never a drifting
    # recorded baseline
    ('dist.autoscale.burn_max', 'lower',
     {'threshold': 0.0, 'pin_baseline': 1.0}),
    # planned-handoff degraded window, pinned to ZERO: cur/0.5 - 1
    # > 0 the moment even one batch degrades across the cutover —
    # the whole point of fence-then-one-bump is that this is 0, not
    # merely small
    ('dist.autoscale.handoff_degraded_batches', 'lower',
     {'threshold': 0.0, 'pin_baseline': 0.5}),
)


def baseline_path(path: Optional[str] = None) -> str:
  return path or os.environ.get(BASELINE_ENV) or DEFAULT_BASELINE


def threshold_from_env(default: float = DEFAULT_THRESHOLD) -> float:
  try:
    return float(os.environ.get(THRESHOLD_ENV, default))
  except ValueError:
    return default


def _walk(obj: Dict, dotted: str):
  cur = obj
  for part in dotted.split('.'):
    if isinstance(cur, list):
      # 'pNN' selects the list element whose num_parts == NN (the
      # scale-envelope row addressing used by the exchange guard)
      if not (part.startswith('p') and part[1:].isdigit()):
        return None
      want = int(part[1:])
      cur = next((r for r in cur if isinstance(r, dict)
                  and r.get('num_parts') == want), None)
      continue
    if not isinstance(cur, dict):
      return None
    cur = cur.get(part)
  return cur


def _get(obj: Dict, dotted: str):
  cur = _walk(obj, dotted)
  return cur if isinstance(cur, (int, float)) else None


def _context(obj: Dict, dotted: str):
  """A 'same'-clause context value: any scalar (strings included —
  partitioner identities are the motivating case)."""
  cur = _walk(obj, dotted)
  return cur if isinstance(cur, (str, int, float, bool)) else None


def compare(artifact: Dict, baseline: Dict,
            threshold: float = DEFAULT_THRESHOLD) -> Dict:
  """Per-metric comparison.  Returns a verdict dict::

      {'status': 'PASS'|'FAIL', 'threshold': 0.2,
       'metrics': [{'key', 'direction', 'current', 'baseline',
                    'change_pct', 'status'}, ...],
       'regressed': ['fused_epoch_secs', ...]}

  ``change_pct`` is signed so that POSITIVE always means SLOWER
  (time up, or rate down), regardless of direction.
  """
  rows: List[Dict] = []
  regressed: List[str] = []
  for entry in METRICS:
    key, direction = entry[0], entry[1]
    opts = entry[2] if len(entry) > 2 else {}
    thr = opts.get('threshold', threshold)
    cur = _get(artifact, key)
    same = opts.get('same')
    if same is not None:
      same_keys = (same,) if isinstance(same, str) else tuple(same)
      if any(_context(artifact, k) != _context(baseline, k)
             for k in same_keys):
        rows.append({'key': key, 'direction': direction,
                     'current': cur, 'baseline': _get(baseline, key),
                     'change_pct': None, 'status': 'skipped'})
        continue
    if direction == 'present':
      gate = opts.get('when')
      if gate is not None and _get(artifact, gate) is None:
        rows.append({'key': key, 'direction': direction,
                     'current': cur, 'baseline': _get(baseline, key),
                     'change_pct': None, 'status': 'skipped'})
        continue
      status = 'ok' if cur is not None else 'regressed'
      if status == 'regressed':
        regressed.append(key)
      rows.append({'key': key, 'direction': direction, 'current': cur,
                   'baseline': _get(baseline, key),
                   'change_pct': 0.0 if cur is not None else 100.0,
                   'status': status})
      continue
    base = opts.get('pin_baseline')
    if base is None:
      base = _get(baseline, key)
    if cur is None or base is None or base == 0:
      rows.append({'key': key, 'direction': direction, 'current': cur,
                   'baseline': base, 'change_pct': None,
                   'status': 'skipped'})
      continue
    if direction == 'lower':
      slowdown = cur / base - 1.0
    else:
      # a rate collapsing to 0 is a total regression; the slowdown is
      # CLAMPED finite so the verdict stays strict-JSON (an Infinity
      # token in the artifact would make the whole file unparseable —
      # the exact failure mode the sink exists to prevent)
      slowdown = min(base / cur - 1.0 if cur else 1e4, 1e4)
    status = 'regressed' if slowdown > thr else 'ok'
    if status == 'regressed':
      regressed.append(key)
    rows.append({'key': key, 'direction': direction, 'current': cur,
                 'baseline': base,
                 'change_pct': round(100.0 * slowdown, 2),
                 'status': status})
  return {'status': 'FAIL' if regressed else 'PASS',
          'threshold': threshold, 'metrics': rows,
          'regressed': regressed}


def format_report(verdict: Dict) -> str:
  """Human-readable per-metric report (every line names its key, so a
  FAIL is actionable from the log alone)."""
  lines = [f"bench regression gate: {verdict['status']} "
           f"(threshold {verdict['threshold'] * 100:.0f}%)"]
  if verdict.get('baseline_created'):
    lines[0] = ('bench regression gate: BASELINE_CREATED '
                f"-> {verdict.get('baseline_path')} (first run; commit "
                'it to pin the trajectory)')
    if verdict.get('unguarded'):
      lines.append(
          '  WARNING: baseline lacks tracked metrics '
          f"{verdict['unguarded']} — these stay UNGUARDED until a "
          'complete run re-bootstraps (delete the baseline or pass '
          '--update-baseline after a full run)')
    return '\n'.join(lines)
  if verdict['status'] == 'ERROR':
    lines.append(f"  {verdict.get('error')}")
    return '\n'.join(lines)
  for m in verdict['metrics']:
    if m['status'] == 'skipped':
      lines.append(f"  [skip] {m['key']}: missing on one side "
                   f"(current={m['current']}, baseline={m['baseline']})")
      continue
    if m['direction'] == 'present':
      tag = 'FAIL' if m['status'] == 'regressed' else ' ok '
      state = ('MISSING (required while its phase ran)'
               if m['current'] is None else f"present ({m['current']})")
      lines.append(f"  [{tag}] {m['key']}: {state}")
      continue
    tag = 'FAIL' if m['status'] == 'regressed' else ' ok '
    lines.append(
        f"  [{tag}] {m['key']}: {m['current']} vs baseline "
        f"{m['baseline']} ({m['change_pct']:+.1f}% "
        f"{'slower' if m['change_pct'] >= 0 else 'faster'})")
  return '\n'.join(lines)


def summary(verdict: Dict) -> str:
  """Compact verdict for the artifact's bounded stdout summary line
  (`sink._SUMMARY_KEYS` carries it near the front)."""
  if verdict.get('baseline_created'):
    return 'BASELINE_CREATED'
  if verdict['status'] != 'FAIL':
    return verdict['status']
  worst = max((m for m in verdict['metrics']
               if m['status'] == 'regressed'),
              key=lambda m: m['change_pct'])
  return (f"FAIL {worst['key']} {worst['change_pct']:+.1f}%"
          + (f" (+{len(verdict['regressed']) - 1} more)"
             if len(verdict['regressed']) > 1 else ''))


def _write_json_atomic(path: str, obj: Dict) -> None:
  """tmp + rename, like the sink's artifact write: a kill mid-write
  must never leave a truncated baseline to poison every later gate."""
  import tempfile
  d = os.path.dirname(os.path.abspath(path))
  fd, tmp = tempfile.mkstemp(prefix='.bench_baseline.', dir=d)
  try:
    with os.fdopen(fd, 'w') as f:
      json.dump(obj, f, indent=1, sort_keys=True)
      f.write('\n')
    os.replace(tmp, path)
  except BaseException:
    try:
      os.unlink(tmp)
    except OSError:
      pass
    raise


def check(artifact, baseline: Optional[str] = None,
          threshold: Optional[float] = None,
          update_baseline: bool = False) -> Tuple[Dict, int]:
  """The gate: compare artifact vs baseline, return ``(verdict,
  exit_code)`` — 0 PASS / baseline bootstrapped, 1 regression, 2 the
  gate could not run.  ``artifact`` is the aggregate dict itself or a
  path to it (callers holding the fresh in-memory aggregate pass the
  dict, so a stale file on disk can never be gated by accident).

  A MISSING baseline is created from the artifact (first run — the
  intended bootstrap).  A CORRUPT baseline is rc 2, NOT recreated: a
  regressed run must never get to re-base the trajectory onto its own
  slow numbers through a conveniently broken file; fix or delete the
  baseline explicitly.  ``update_baseline`` rewrites it after a PASS
  (explicit re-basing)."""
  bp = baseline_path(baseline)
  thr = threshold_from_env() if threshold is None else float(threshold)
  if isinstance(artifact, dict):
    art = artifact
  else:
    with open(artifact) as f:
      art = json.load(f)
  if not os.path.exists(bp):
    _write_json_atomic(bp, art)
    # a partial first run (a crashed phase) pins a baseline with
    # holes, and compare() SKIPS keys missing from either side — name
    # the uncovered metrics loudly so the hole is a choice, not a
    # surprise (re-bootstrap from a complete run to close it)
    missing = [e[0] for e in METRICS if _get(art, e[0]) is None]
    return ({'status': 'PASS', 'baseline_created': True,
             'baseline_path': bp, 'threshold': thr, 'metrics': [],
             'regressed': [], 'unguarded': missing}, 0)
  try:
    with open(bp) as f:
      base = json.load(f)
  except (json.JSONDecodeError, ValueError) as e:
    return ({'status': 'ERROR', 'baseline_path': bp, 'threshold': thr,
             'metrics': [], 'regressed': [],
             'error': f'baseline is corrupt ({e}); fix or delete it '
                      'to re-bootstrap'}, 2)
  verdict = compare(art, base, thr)
  verdict['baseline_path'] = bp
  if update_baseline and verdict['status'] == 'PASS':
    _write_json_atomic(bp, art)
    verdict['baseline_updated'] = True
  return verdict, (1 if verdict['status'] == 'FAIL' else 0)


def main(argv: Optional[List[str]] = None) -> int:
  import argparse
  ap = argparse.ArgumentParser(
      description='Compare a bench artifact against the committed '
                  'baseline; exit 1 on regression.')
  ap.add_argument('artifact')
  ap.add_argument('baseline', nargs='?', default=None)
  ap.add_argument('--threshold', type=float, default=None,
                  help='fractional slowdown tolerance (default 0.2)')
  ap.add_argument('--update-baseline', action='store_true',
                  help='rewrite the baseline from this artifact after '
                       'a PASS')
  args = ap.parse_args(argv)
  try:
    verdict, rc = check(args.artifact, args.baseline,
                        threshold=args.threshold,
                        update_baseline=args.update_baseline)
  except (OSError, ValueError) as e:
    # infra failure (missing/corrupt artifact, unwritable baseline
    # dir) is rc 2, never rc 1 — a CI keying on the exit code must
    # not misread it as a perf regression
    print(f'bench regression gate: ERROR — could not run '
          f'({type(e).__name__}: {e})')
    return 2
  print(format_report(verdict))
  return rc


if __name__ == '__main__':
  import sys
  sys.exit(main())
