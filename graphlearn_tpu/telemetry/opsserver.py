"""Pull-based HTTP ops endpoint — one per process, three routes.

``/metrics``
    Prometheus text exposition (format 0.0.4) of every declared live
    metric — what a fleet scraper collects.
``/varz``
    Full JSON snapshot: every backing counter (flat histogram keys
    included), every evaluated gauge, recorder ring stats — the
    "give me everything" incident view.
``/healthz``
    Liveness + per-component health from the registered providers
    (producer/worker supervision state, per-bucket compile status,
    serving queue).  HTTP 200 when every component is healthy, 503
    otherwise — load-balancer-pollable.
``/timeseries``
    Windowed JSON history from the process `TimeSeriesStore` rings
    (``?names=a,b`` filters by key/prefix, ``?window_s=60`` bounds
    the lookback) — what a controller plots instead of point samples.
``/fleet``
    Federated exposition from an attached `FleetScraper` (per-replica
    ``replica=`` labels + ``glt_fleet_*`` aggregates); ``?format=json``
    returns the per-replica healthz rollup instead.  404 until a
    scraper is attached with `OpsServer.attach_fleet`.
``/traces``
    Index of tail-retained request traces from the process tracer
    (`telemetry.tracing` — slow/failed/sampled requests only).
``/trace?trace_id=``
    One trace's spans.  With a fleet scraper attached the spans are
    assembled across EVERY replica; ``?format=chrome`` renders the
    Perfetto-loadable Chrome trace-event object instead of raw spans.

Serving model: a `ThreadingHTTPServer` with daemon threads, so a
slow, stalled or chaos-delayed scrape occupies ITS OWN thread and can
never block the serving executor or a fused dispatch (pinned by the
``ops.scrape`` chaos site + test).  Scrapes read shared state only
through lock-guarded snapshots (`Metrics.snapshot`, gauge callbacks),
so they are consistent but never hold a hot-path lock across I/O.

Enable with ``GLT_OPS_PORT`` (**0 = disabled, the default** — the
data plane is byte-identical with the plane off).
`maybe_start_from_env` is called by `DistServer`, the
`ServingFrontend` and the bench drivers; the first caller binds, the
rest share the process singleton.
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

OPS_PORT_ENV = 'GLT_OPS_PORT'
OPS_HOST_ENV = 'GLT_OPS_HOST'
DEFAULT_HOST = '127.0.0.1'


def ops_port_from_env() -> int:
  try:
    return int(os.environ.get(OPS_PORT_ENV, '0'))
  except ValueError:
    return 0


def ops_host_from_env() -> str:
  return os.environ.get(OPS_HOST_ENV) or DEFAULT_HOST


class _OpsHandler(BaseHTTPRequestHandler):
  server_version = 'glt-ops/1'
  protocol_version = 'HTTP/1.1'

  def do_GET(self):                 # noqa: N802 — http.server API
    from ..testing import chaos
    registry = self.server.registry           # type: ignore[attr-defined]
    parsed = urlparse(self.path)
    path = parsed.path
    query = parse_qs(parsed.query)
    try:
      # chaos seam: a 'delay' stalls THIS handler thread (the
      # serving/fused hot paths must not notice), a 'drop' turns the
      # scrape into a 503 — the scraper's problem, nobody else's
      chaos.ops_scrape_check(path)
      self.server.scrapes.inc()               # type: ignore[attr-defined]
      if path == '/metrics':
        body = registry.prometheus_text().encode('utf-8')
        ctype = 'text/plain; version=0.0.4; charset=utf-8'
        status = 200
      elif path == '/varz':
        body = (json.dumps(registry.varz(), default=repr, indent=1)
                + '\n').encode('utf-8')
        ctype = 'application/json'
        status = 200
      elif path == '/healthz':
        health = registry.healthz()
        body = (json.dumps(health, default=repr, indent=1)
                + '\n').encode('utf-8')
        ctype = 'application/json'
        status = 200 if health.get('ok') else 503
      elif path == '/timeseries':
        from . import timeseries
        store = timeseries.global_store()
        if store is None:
          body = ('no time-series store in this process — set '
                  'GLT_OPS_PORT via maybe_start_from_env or call '
                  'timeseries.ensure_global()\n').encode('utf-8')
          ctype = 'text/plain'
          status = 404
        else:
          names = None
          if query.get('names'):
            names = [n for n in query['names'][0].split(',') if n]
          window_s = None
          if query.get('window_s'):
            try:
              window_s = float(query['window_s'][0])
            except ValueError:
              window_s = None
          body = (json.dumps(store.query(names=names,
                                         window_s=window_s),
                             indent=1) + '\n').encode('utf-8')
          ctype = 'application/json'
          status = 200
      elif path == '/fleet':
        fleet = getattr(self.server, 'fleet', None)
        if fleet is None:
          body = ('no fleet scraper attached — call '
                  'OpsServer.attach_fleet(FleetScraper(...))\n'
                  ).encode('utf-8')
          ctype = 'text/plain'
          status = 404
        elif query.get('format', ['prom'])[0] == 'json':
          rollup = fleet.fleet_json()
          body = (json.dumps(rollup, default=repr, indent=1)
                  + '\n').encode('utf-8')
          ctype = 'application/json'
          status = 200 if rollup.get('ok') else 503
        else:
          body = fleet.prometheus_text().encode('utf-8')
          ctype = 'text/plain; version=0.0.4; charset=utf-8'
          status = 200
      elif path == '/traces':
        from .tracing import tracer
        body = (json.dumps({'traces': tracer.traces(),
                            'stats': tracer.stats()},
                           indent=1) + '\n').encode('utf-8')
        ctype = 'application/json'
        status = 200
      elif path == '/trace':
        from .tracing import tracer
        tid = (query.get('trace_id') or [''])[0]
        fleet = getattr(self.server, 'fleet', None)
        if fleet is not None:
          spans = fleet.fetch_trace(tid)
        else:
          spans = tracer.spans_of(tid)
        if not tid or not spans:
          body = (f'no retained trace {tid!r} — see /traces for the '
                  'index (only slow/failed/sampled requests are '
                  'kept)\n').encode('utf-8')
          ctype = 'text/plain'
          status = 404
        elif query.get('format', ['json'])[0] == 'chrome':
          from . import export
          from .tracing import spans_to_events
          trace = export.to_chrome_trace(spans_to_events(spans))
          body = (json.dumps(trace) + '\n').encode('utf-8')
          ctype = 'application/json'
          status = 200
        else:
          body = (json.dumps({'trace_id': tid, 'spans': spans},
                             indent=1) + '\n').encode('utf-8')
          ctype = 'application/json'
          status = 200
      else:
        body = (f'no such route {path!r} — try /metrics, /varz, '
                '/healthz, /timeseries, /fleet, /traces, '
                '/trace?trace_id=\n').encode('utf-8')
        ctype = 'text/plain'
        status = 404
    except chaos.InjectedFault as e:
      body = f'{e}\n'.encode('utf-8')
      ctype = 'text/plain'
      status = 503
    except Exception as e:          # noqa: BLE001 — a broken render
      # must answer 500, not silently close the connection
      body = f'{type(e).__name__}: {e}\n'.encode('utf-8')
      ctype = 'text/plain'
      status = 500
    self.send_response(status)
    self.send_header('Content-Type', ctype)
    self.send_header('Content-Length', str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def log_message(self, fmt, *args):  # noqa: A003 — silence stderr
    del fmt, args


class OpsServer:
  """One process's ops endpoint.  ``port=0`` here means "pick an
  ephemeral port" (the env-var convention of 0 = disabled lives in
  `maybe_start_from_env`, not in this explicit constructor)."""

  def __init__(self, registry=None, port: int = 0,
               host: Optional[str] = None):
    if registry is None:
      from .live import live as registry
    self.registry = registry
    self._httpd = ThreadingHTTPServer(
        (host or ops_host_from_env(), max(int(port), 0)), _OpsHandler)
    self._httpd.daemon_threads = True
    self._httpd.registry = registry           # type: ignore[attr-defined]
    self._httpd.scrapes = registry.counter('ops.scrapes_total')  # type: ignore[attr-defined]
    self._httpd.fleet = None                  # type: ignore[attr-defined]
    self._thread = threading.Thread(
        target=self._httpd.serve_forever, daemon=True,
        name='glt-ops-server')
    self._thread.start()

  def attach_fleet(self, scraper) -> None:
    """Expose a `federation.FleetScraper` on the ``/fleet`` route
    (pass None to detach)."""
    self._httpd.fleet = scraper               # type: ignore[attr-defined]

  @property
  def fleet(self):
    return getattr(self._httpd, 'fleet', None)

  @property
  def port(self) -> int:
    return self._httpd.server_address[1]

  @property
  def url(self) -> str:
    host = self._httpd.server_address[0]
    return f'http://{host}:{self.port}'

  def close(self) -> None:
    self._httpd.shutdown()
    self._httpd.server_close()


# -- process singleton -------------------------------------------------------
_global: Optional[OpsServer] = None
_global_lock = threading.Lock()


def maybe_start_from_env() -> Optional[OpsServer]:
  """Start (or return) the process-global ops server per
  ``GLT_OPS_PORT``; None when disabled (0/unset — the default, under
  which the data plane is byte-identical to having no ops plane at
  all).  Called by every server/frontend/bench entry point;
  idempotent, first caller binds.  Also chains the post-mortem
  fatal-signal handler when ``GLT_POSTMORTEM_DIR`` is set — the two
  halves of "observable during the incident"."""
  from . import postmortem
  postmortem.install_signal_handlers()
  port = ops_port_from_env()
  if port <= 0:
    return None
  global _global
  with _global_lock:
    if _global is None:
      try:
        _global = OpsServer(port=port)
        # any process with an ops endpoint gets history for free —
        # the /timeseries route and postmortem rings read this store
        from . import timeseries
        timeseries.ensure_global()
      except OSError as e:
        # observability plumbing must never take the data plane down:
        # a bind failure (EADDRINUSE — two processes inheriting one
        # GLT_OPS_PORT on a host) degrades to no-ops-plane, loudly
        import sys
        print(f'glt-ops: could not bind GLT_OPS_PORT={port} ({e}) — '
              'continuing WITHOUT a live ops endpoint (give each '
              'process its own port, or 0 to silence)',
              file=sys.stderr)
        return None
    return _global


def global_server() -> Optional[OpsServer]:
  return _global


def stop_global() -> None:
  global _global
  with _global_lock:
    if _global is not None:
      _global.close()
      _global = None
      from . import timeseries
      timeseries.stop_global()
