"""Time-series history over the live metric plane (ISSUE 16 leg 1).

The live registry answers "what is the value NOW"; a controller (the
ROADMAP item-3 autoscaler) and an operator running a postmortem both
need "what was it over the last N minutes".  `TimeSeriesStore` is a
fixed-cadence sampler over every DECLARED live instrument:

  * **gauges** are evaluated exactly as a scrape would (a callback
    that raises or returns None drops that sample, never the sweep);
  * **counters** are converted to per-second RATES between
    consecutive sweeps (a cumulative total is useless to plot; a
    counter that rewinds — the fused rollback path — clamps to 0
    rather than recording a negative rate);
  * **histograms** are summarized per sweep as an observation rate
    (``<key>.hist:rate`` from the flat ``count`` key) — the full
    bucket vector stays a scrape-time artifact.

Samples land in bounded per-series ring buffers sized by
``retention / cadence`` (``GLT_TS_RETENTION_S`` / ``GLT_TS_CADENCE_MS``,
default 300 s at 1 s), so memory is fixed no matter how long the
process lives.  The store serves windowed queries (the `OpsServer`
``/timeseries`` JSON route) and attaches its rings to postmortem
bundles — a crash dump shows burn-rate / queue-depth / ingest-lag
leading INTO the incident, not just the final snapshot.

The sweep thread reads shared state only through the same surfaces a
scrape uses (`Metrics.snapshot`, gauge callbacks) — it must never
take a hot-path lock.  `SloTracker` gauges read through the tracker's
scrape memo and the admission queue-depth gauge is a lock-free
``len()`` read, so a 1 Hz (or much faster) cadence loop costs the
serving executor nothing (pinned by the concurrent observe+sample
test in ``tests/test_timeseries.py``).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

TS_CADENCE_ENV = 'GLT_TS_CADENCE_MS'
TS_RETENTION_ENV = 'GLT_TS_RETENTION_S'

DEFAULT_CADENCE_MS = 1000.0
DEFAULT_RETENTION_S = 300.0

QUERY_SCHEMA = 'glt.timeseries.v1'

#: flat-key suffix marking a counter-derived rate series
RATE_SUFFIX = ':rate'


def cadence_ms_from_env(default: float = DEFAULT_CADENCE_MS) -> float:
  try:
    return max(float(os.environ.get(TS_CADENCE_ENV, default)), 1.0)
  except ValueError:
    return default


def retention_s_from_env(default: float = DEFAULT_RETENTION_S) -> float:
  try:
    return max(float(os.environ.get(TS_RETENTION_ENV, default)), 1.0)
  except ValueError:
    return default


class _Ring:
  """One bounded series: parallel (ts, value) deques plus the raw
  cumulative count a rate series differentiates."""

  __slots__ = ('kind', 'points', 'last_raw')

  def __init__(self, kind: str, maxlen: int):
    self.kind = kind                  # 'gauge' | 'rate'
    self.points: 'collections.deque[Tuple[float, float]]' = \
        collections.deque(maxlen=maxlen)
    self.last_raw: Optional[float] = None


class TimeSeriesStore:
  """Fixed-cadence history sampler over one `LiveRegistry`.

  Args:
    registry: live registry to walk (None = the process-global one).
    cadence_ms: sweep period (None = ``GLT_TS_CADENCE_MS``).
    retention_s: ring span (None = ``GLT_TS_RETENTION_S``); ring
      length is ``ceil(retention / cadence)``.
    clock: wall-clock source stamped on samples (tests inject a fake
      and drive `sample_once` directly — the acceptance bundles need
      60 s of history without 60 s of wall time).
  """

  def __init__(self, registry=None, cadence_ms: Optional[float] = None,
               retention_s: Optional[float] = None, clock=time.time):
    if registry is None:
      from .live import live as registry
    self.registry = registry
    self.cadence_ms = (cadence_ms_from_env() if cadence_ms is None
                       else max(float(cadence_ms), 1.0))
    self.retention_s = (retention_s_from_env() if retention_s is None
                        else max(float(retention_s), 1.0))
    self._ring_len = max(
        2, int(-(-self.retention_s * 1000.0 // self.cadence_ms)))
    self._clock = clock
    self._lock = threading.Lock()
    self._rings: Dict[str, _Ring] = {}
    self._thread: Optional[threading.Thread] = None
    self._stop = threading.Event()
    self._m_samples = registry.counter('timeseries.samples_total')
    self._series_fn = self._series_count
    registry.gauge('timeseries.series', fn=self._series_fn)

  # -- sampling ------------------------------------------------------------
  def _series_count(self) -> float:
    with self._lock:
      return float(len(self._rings))

  def _ring(self, key: str, kind: str) -> _Ring:
    ring = self._rings.get(key)
    if ring is None:
      ring = self._rings[key] = _Ring(kind, self._ring_len)
    return ring

  def sample_once(self, now: Optional[float] = None) -> int:
    """One sweep over the registry's instruments; returns the number
    of points recorded.  Never raises: a broken gauge drops its own
    sample only (same contract as a scrape)."""
    from .histogram import HIST_SEP, KEY_PREFIX
    now = self._clock() if now is None else float(now)
    snap = self.registry._backing().snapshot()
    # gauges evaluate OUTSIDE the ring lock: a callback may read back
    # through the registry (the store's own series gauge does)
    entries: List[Tuple[str, str, float]] = []
    for kind, m in self.registry.instruments():
      if kind == 'counter':
        entries.append(('rate', m.key + RATE_SUFFIX,
                        float(snap.get(m.key, 0.0))))
      elif kind == 'gauge':
        v = m.value()
        if v is not None:
          entries.append(('gauge', m.key, float(v)))
      else:                           # histogram: observation rate
        entries.append(('rate', m.key + '.hist' + RATE_SUFFIX,
                        float(snap.get(
                            f'{KEY_PREFIX}{m.key}{HIST_SEP}count',
                            0.0))))
    recorded = 0
    with self._lock:
      for kind, key, v in entries:
        ring = self._ring(key, kind)
        if kind == 'rate':
          recorded += self._push_rate(ring, now, v)
        else:
          ring.points.append((now, v))
          recorded += 1
    self._m_samples.inc()
    return recorded

  @staticmethod
  def _push_rate(ring: _Ring, now: float, raw: float) -> int:
    prev = ring.last_raw
    prev_t = ring.points[-1][0] if ring.points else None
    ring.last_raw = raw
    if prev is None:
      # first observation anchors the delta; no rate yet
      ring.points.append((now, 0.0))
      return 0
    dt = now - (prev_t if prev_t is not None else now)
    rate = max(raw - prev, 0.0) / dt if dt > 0 else 0.0
    ring.points.append((now, round(rate, 6)))
    return 1

  # -- queries -------------------------------------------------------------
  def query(self, names: Optional[List[str]] = None,
            window_s: Optional[float] = None) -> dict:
    """Windowed JSON-able view: ``{schema, cadence_ms, retention_s,
    series: {key: {kind, points: [[ts, v], ...]}}}``.  ``names``
    filters by exact series key or dotted prefix — a counter NAME
    matches its derived ``:rate`` series, so callers ask for the
    instrument they know; ``window_s`` keeps only points newer than
    ``now - window_s``."""
    now = self._clock()
    horizon = None if window_s is None else now - float(window_s)
    with self._lock:
      items = [(k, r.kind, list(r.points))
               for k, r in sorted(self._rings.items())]
    series = {}
    for key, kind, points in items:
      if names is not None and not any(
          key == n or key.startswith(n + '.')
          or key.startswith(n + '{') or key.startswith(n + ':')
          for n in names):
        continue
      if horizon is not None:
        points = [p for p in points if p[0] >= horizon]
      if points:
        series[key] = {'kind': kind,
                       'points': [[round(t, 3), v] for t, v in points]}
    return {'schema': QUERY_SCHEMA, 'ts': round(now, 3),
            'cadence_ms': self.cadence_ms,
            'retention_s': self.retention_s, 'series': series}

  def span_s(self) -> float:
    """Seconds of history currently held (max over series)."""
    with self._lock:
      spans = [r.points[-1][0] - r.points[0][0]
               for r in self._rings.values() if len(r.points) >= 2]
    return max(spans) if spans else 0.0

  # -- lifecycle -----------------------------------------------------------
  def start(self) -> 'TimeSeriesStore':
    if self._thread is None:
      self._stop.clear()
      self._thread = threading.Thread(target=self._loop, daemon=True,
                                      name='glt-timeseries')
      self._thread.start()
    return self

  def _loop(self) -> None:
    period = self.cadence_ms / 1000.0
    while not self._stop.wait(period):
      try:
        self.sample_once()
      except Exception:               # noqa: BLE001 — the sweep must
        pass                          # outlive any one broken sweep

  def close(self) -> None:
    self._stop.set()
    t = self._thread
    if t is not None:
      t.join(2.0)
    self._thread = None
    self.registry.unregister_gauge('timeseries.series',
                                   fn=self._series_fn)


# -- process global ----------------------------------------------------------
_global: Optional[TimeSeriesStore] = None
_global_lock = threading.Lock()


def global_store() -> Optional[TimeSeriesStore]:
  return _global


def ensure_global(registry=None) -> TimeSeriesStore:
  """Start (or return) the process-global cadence sampler — called by
  `opsserver.maybe_start_from_env` so any process with an ops
  endpoint gets history for free, and by the postmortem path so a
  bundle can attach whatever rings exist."""
  global _global
  with _global_lock:
    if _global is None:
      _global = TimeSeriesStore(registry=registry).start()
    return _global


def stop_global() -> None:
  global _global
  with _global_lock:
    if _global is not None:
      _global.close()
      _global = None
