"""File-based bench artifact sink.

Round 5's evidence chain broke at the last hop: the aggregate JSON on
stdout outgrew the driver's 2000-char tail and `BENCH_r05.json` shipped
``"parsed": null``.  The permanent fix is structural: the FULL artifact
goes to a file (:func:`write_artifact`, atomic tmp+rename) and stdout
carries only a short summary line (:func:`summary_line`) that is
guaranteed to fit the tail — it degrades by dropping optional keys, and
always names the artifact file it summarizes.

Deliberately import-light (json/os/tempfile only) so it can be loaded
DIRECTLY by file path (`bench.py::_sink_module` does exactly that),
keeping the bench driver process free of the package import chain and
the device stack.  Importing it as `graphlearn_tpu.telemetry.sink`
still works but executes the package ``__init__`` (and thus jax) —
fine inside workers, wasteful in a json-only driver.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Dict, Optional

#: env override for the artifact file path.
ARTIFACT_ENV = 'GLT_BENCH_ARTIFACT'
DEFAULT_ARTIFACT = 'BENCH_ARTIFACT.json'

#: env override for the per-record JSONL sidecar the sweep benchmarks
#: append to (one line per configuration, across subprocesses).
RECORDS_ENV = 'GLT_BENCH_RECORDS'
DEFAULT_RECORDS = 'BENCH_ARTIFACT.jsonl'

#: the driver's stdout tail is 2000 chars; the summary stays well
#: under it so the trailing newline (and any wrapper prefix) can never
#: push the line's leading '{' out of the tail window.
SUMMARY_LIMIT = 1900

#: summary key order: earlier keys survive when the line must shrink.
#: 'regression' (the bench gate's compact verdict, telemetry.regress)
#: sits right behind the headline so a FAIL stays visible even when
#: the line degrades to its minimum.
_SUMMARY_KEYS = (
    'metric', 'value', 'unit', 'regression', 'vs_baseline', 'protocol',
    'fused_epoch_secs', 'fused_vs_baseline', 'fused_layout',
    'epoch_secs_min_med_max', 'epoch_floor_secs',
    'sampled_edges_per_sec_M_min_med_max', 'train_step_mfu',
    'fused_epoch_secs_bf16', 'fused_hetero_epoch_secs',
    'fused_compile_secs', 'fused_error', 'fused_suspect_elision',
    'achieved_hbm_frac', 'sessions', 'steps_per_epoch',
)
#: dist sub-keys lifted into the summary (the full dist dict can be
#: arbitrarily large — scale-envelope rows etc. live in the artifact).
_DIST_KEYS = ('padding_waste_pct', 'drop_rate_pct', 'seeds_per_sec',
              'edges_per_sec_per_chip', 'num_parts', 'error')


def artifact_path(path: Optional[str] = None) -> str:
  return path or os.environ.get(ARTIFACT_ENV) or DEFAULT_ARTIFACT


def records_path(path: Optional[str] = None) -> str:
  return path or os.environ.get(RECORDS_ENV) or DEFAULT_RECORDS


def write_artifact(obj: Dict, path: Optional[str] = None) -> str:
  """Write the full artifact JSON atomically; returns the path.  A
  reader never sees a half-written file (tmp + os.replace), and a kill
  between phases leaves the previous complete artifact in place."""
  dest = artifact_path(path)
  d = os.path.dirname(os.path.abspath(dest))
  fd, tmp = tempfile.mkstemp(prefix='.bench_artifact.', dir=d)
  try:
    with os.fdopen(fd, 'w') as f:
      json.dump(obj, f, indent=1, sort_keys=True)
      f.write('\n')
    os.replace(tmp, dest)
  except BaseException:
    try:
      os.unlink(tmp)
    except OSError:
      pass
    raise
  return dest


def append_record(rec: Dict, path: Optional[str] = None) -> str:
  """Append one JSON line to the records sidecar (the benchmarks/*
  sweep drivers' file artifact).  One write per line keeps concurrent
  sweep subprocesses line-atomic on POSIX."""
  dest = records_path(path)
  with open(dest, 'a') as f:
    f.write(json.dumps(rec) + '\n')
  return dest


def summary_line(art: Dict, artifact: Optional[str] = None,
                 limit: int = SUMMARY_LIMIT) -> str:
  """A one-line JSON summary of ``art`` guaranteed to be at most
  ``limit`` characters: headline keys in priority order, dropped from
  the tail until the line fits.  Always parseable; always carries
  ``artifact`` (the file holding the full JSON) when given."""
  picked = {}
  for k in _SUMMARY_KEYS:
    v = art.get(k)
    if v is not None:
      picked[k] = v
  dist = art.get('dist')
  if isinstance(dist, dict):
    dsum = {k: dist[k] for k in _DIST_KEYS if dist.get(k) is not None}
    if dsum:
      picked['dist'] = dsum
  if artifact is not None:
    picked['artifact'] = artifact
  line = json.dumps(picked)
  while len(line) > limit and picked:
    # drop the lowest-priority droppable key ('metric'/'value'/
    # 'regression'/'artifact' go last: they are the whole point of
    # the line — a regression FAIL must survive any degradation)
    order = [k for k in picked
             if k not in ('metric', 'value', 'regression', 'artifact')]
    victim = order[-1] if order else next(iter(picked))
    del picked[victim]
    line = json.dumps(picked)
  return line[:limit]
