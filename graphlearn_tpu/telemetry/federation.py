"""Fleet metrics federation (ISSUE 16 leg 2) — the scrape surface the
SLO autoscaler (ROADMAP item 3) closes its loop on.

One process's `OpsServer` answers for one replica.  `FleetScraper`
polls EVERY replica's exposition — remote ops endpoints over HTTP and
in-process `LocalReplica`s / private registries directly — and merges
the families into one federated view:

  * every replica's samples re-render under their original family
    names with a ``replica="<name>"`` label injected;
  * fleet-level aggregates ride beside them as ``glt_fleet_*``
    families: counters SUM across replicas, gauges take the fleet
    MAX (the alarming convention: the worst replica is the signal),
    and the log2 latency histograms QUANTILE-MERGE — bucket vectors
    sum across replicas (exactly how `gather_metrics` merges them
    mesh-wide) and the merged p50/p99 export as gauges;
  * ``/healthz`` rolls up per replica: the fleet is ok iff every
    scrapeable replica is ok, and unreachable replicas are reported
    (not silently dropped — a dead replica IS the signal).

The merged exposition is what the `OpsServer` ``/fleet`` route serves
(``?format=json`` for the health rollup), and it stays strictly
parseable by `live.parse_prometheus_text` — the acceptance check the
fleet bench runs mid-traffic.

Each replica's exposition is rendered from ONE snapshot on the
replica side, so per-replica histogram bucket/count pairs are
tear-free in the merged view; the merge itself only ever reads the
scraped text (no live locks held across replicas).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from .live import parse_prometheus_text, split_exemplar

FLEET_SCRAPE_ENV = 'GLT_FLEET_SCRAPE_MS'
DEFAULT_SCRAPE_MS = 1000.0

FLEET_PREFIX = 'glt_fleet_'

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_TYPE_RE = re.compile(r'^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$')
_HELP_RE = re.compile(r'^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$')


def scrape_ms_from_env(default: float = DEFAULT_SCRAPE_MS) -> float:
  try:
    return max(float(os.environ.get(FLEET_SCRAPE_ENV, default)), 10.0)
  except ValueError:
    return default


def _fmt(v: float) -> str:
  f = float(v)
  return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _render_labels(items: List[Tuple[str, str]]) -> str:
  if not items:
    return ''
  def esc(v: str) -> str:
    return v.replace('\\', r'\\').replace('"', r'\"').replace('\n', r'\n')
  return '{' + ','.join(f'{k}="{esc(v)}"' for k, v in items) + '}'


def parse_exposition(text: str) -> Dict[str, dict]:
  """Structured view of one strict text exposition:
  ``{family: {'type': t, 'help': h,
  'samples': [(sample_name, [(k, v), ...], value)]}}`` where
  ``sample_name`` keeps histogram suffixes (``_bucket``/``_sum``/
  ``_count``) and samples attach to the TYPE'd family they suffix.
  Validates with `parse_prometheus_text` first — malformed input
  raises before any partial structure escapes."""
  parse_prometheus_text(text)        # strict validation pass
  fams: Dict[str, dict] = {}
  order: List[str] = []

  def fam_for(sample_name: str) -> str:
    for suffix in ('_bucket', '_sum', '_count'):
      base = sample_name[:-len(suffix)] if sample_name.endswith(suffix) \
          else None
      if base and base in fams and fams[base]['type'] == 'histogram':
        return base
    return sample_name

  for raw in text.splitlines():
    line = raw.strip()
    if not line:
      continue
    th = _TYPE_RE.match(line)
    if th:
      fam = fams.setdefault(th.group(1),
                            {'type': 'untyped', 'help': '',
                             'samples': []})
      fam['type'] = th.group(2)
      if th.group(1) not in order:
        order.append(th.group(1))
      continue
    hh = _HELP_RE.match(line)
    if hh:
      fam = fams.setdefault(hh.group(1),
                            {'type': 'untyped', 'help': '',
                             'samples': []})
      fam['help'] = hh.group(2)
      if hh.group(1) not in order:
        order.append(hh.group(1))
      continue
    if line.startswith('#'):
      continue
    # an OpenMetrics exemplar suffix owns the line's LAST '}' — strip
    # it before the rpartition below, or the label body swallows it
    line, _ = split_exemplar(line)
    name, _, rest = line.partition('{') if '{' in line.split(' ', 1)[0] \
        else (line.split(' ', 1)[0], '', '')
    if rest:
      body, _, tail = rest.rpartition('}')
      labels = [(k, v) for k, v in _LABEL_RE.findall(body)]
      value = float(tail.strip())
    else:
      name, _, tail = line.partition(' ')
      labels = []
      value = float(tail.strip())
    base = fam_for(name)
    fam = fams.setdefault(base, {'type': 'untyped', 'help': '',
                                 'samples': []})
    if base not in order:
      order.append(base)
    fam['samples'].append((name, labels, value))
  return {k: fams[k] for k in order}


# -- replica targets ---------------------------------------------------------
class ReplicaTarget:
  """One scrapeable replica: ``scrape()`` returns
  ``(exposition_text, healthz_dict)`` or raises."""

  def __init__(self, name: str):
    self.name = name

  def scrape(self) -> Tuple[str, dict]:
    raise NotImplementedError


class RegistryTarget(ReplicaTarget):
  """In-process replica backed by a `LiveRegistry` (tests, and the
  scraping process's own registry federating as a member)."""

  def __init__(self, name: str, registry):
    super().__init__(name)
    self.registry = registry

  def scrape(self) -> Tuple[str, dict]:
    return self.registry.prometheus_text(), self.registry.healthz()


class HttpTarget(ReplicaTarget):
  """Remote replica scraped over its ops endpoint
  (``<url>/metrics`` + ``<url>/healthz``)."""

  def __init__(self, name: str, url: str, timeout_s: float = 2.0):
    super().__init__(name)
    self.url = url.rstrip('/')
    self.timeout_s = timeout_s

  def _get(self, route: str) -> Tuple[int, bytes]:
    req = urllib.request.Request(self.url + route)
    try:
      with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
        return resp.status, resp.read()
    except urllib.error.HTTPError as e:      # 503 healthz still has a body
      return e.code, e.read()

  def scrape(self) -> Tuple[str, dict]:
    status, body = self._get('/metrics')
    if status != 200:
      raise OSError(f'/metrics answered HTTP {status}')
    _, hbody = self._get('/healthz')
    try:
      health = json.loads(hbody.decode('utf-8'))
    except ValueError:
      health = {'ok': False, 'error': 'malformed /healthz body'}
    return body.decode('utf-8'), health


class LocalReplicaTarget(ReplicaTarget):
  """In-process `serving.router.LocalReplica`: its heartbeat's
  numeric leaves render as per-replica gauges (``glt_serving_*``
  families — the shared vocabulary, so they merge with remote
  replicas' real expositions)."""

  def __init__(self, name: str, replica):
    super().__init__(name)
    self.replica = replica

  def scrape(self) -> Tuple[str, dict]:
    hb = self.replica.heartbeat()    # raises when the replica is dead
    flat: Dict[str, float] = {}

    def walk(prefix: str, obj) -> None:
      if isinstance(obj, bool):
        return
      if isinstance(obj, (int, float)):
        flat[prefix] = float(obj)
      elif isinstance(obj, dict):
        for k in sorted(obj):
          walk(f'{prefix}_{k}' if prefix else str(k), obj[k])

    walk('', hb)
    lines = []
    for key in sorted(flat):
      fam = 'glt_' + re.sub(r'[^a-zA-Z0-9_]', '_', key)
      lines.append(f'# TYPE {fam} gauge')
      lines.append(f'{fam} {_fmt(flat[key])}')
    return ('\n'.join(lines) + '\n',
            {'ok': True, 'components': {'serving': {'healthy': True}}})


# -- histogram quantile merge ------------------------------------------------
def _merged_quantiles(bucket_groups: Dict[Tuple, Dict[float, float]]
                      ) -> List[Tuple[Tuple, float, float]]:
  """``[(labels_key, p50_secs, p99_secs)]`` from per-label-group
  cumulative ``le`` bucket vectors (already summed across replicas)."""
  out = []
  for labels_key, by_le in sorted(bucket_groups.items()):
    edges = sorted(le for le in by_le if le != float('inf'))
    total = max(by_le.values()) if by_le else 0.0
    if total <= 0:
      continue

    def q(p: float) -> float:
      rank = p * total
      for le in edges:
        if by_le[le] >= rank:
          return le
      return edges[-1] if edges else 0.0

    out.append((labels_key, q(0.5), q(0.99)))
  return out


class FleetScraper:
  """Polls a set of replica targets and serves the merged view.

  Args:
    targets: initial `ReplicaTarget`s (`add_registry` / `add_url` /
      `add_local_replica` append more).
    scrape_ms: poll cadence (None = ``GLT_FLEET_SCRAPE_MS``).
    registry: live registry for the scraper's own meta-metrics
      (None = the process-global one).
    clock: wall-clock for staleness stamps (tests inject).
  """

  def __init__(self, targets=(), scrape_ms: Optional[float] = None,
               registry=None, clock=time.time):
    if registry is None:
      from .live import live as registry
    self.registry = registry
    self.scrape_ms = (scrape_ms_from_env() if scrape_ms is None
                      else max(float(scrape_ms), 10.0))
    self._clock = clock
    self._lock = threading.Lock()
    self._targets: List[ReplicaTarget] = list(targets)
    #: name -> {'ok', 'text', 'health', 'error', 'ts'}
    self._last: Dict[str, dict] = {}
    self._thread: Optional[threading.Thread] = None
    self._stop = threading.Event()
    self._m_scrapes = registry.counter('fleet.scrapes_total')
    self._err_counters: Dict[str, object] = {}
    self._up_fn = self._replicas_up
    registry.gauge('fleet.replicas_up', fn=self._up_fn)

  # -- target management ---------------------------------------------------
  def add_target(self, target: ReplicaTarget) -> ReplicaTarget:
    with self._lock:
      self._targets.append(target)
    self._err_counters[target.name] = self.registry.counter(
        'fleet.scrape_errors_total', labels={'replica': target.name})
    return target

  def add_registry(self, name: str, registry) -> ReplicaTarget:
    return self.add_target(RegistryTarget(name, registry))

  def add_url(self, name: str, url: str,
              timeout_s: float = 2.0) -> ReplicaTarget:
    return self.add_target(HttpTarget(name, url, timeout_s))

  def add_local_replica(self, name: str, replica) -> ReplicaTarget:
    return self.add_target(LocalReplicaTarget(name, replica))

  # -- scraping ------------------------------------------------------------
  def _replicas_up(self) -> float:
    with self._lock:
      return float(sum(
          1 for st in self._last.values()
          if st['ok'] and st['health'].get('ok', False)))

  def scrape(self) -> Dict[str, dict]:
    """One sweep over every target; always completes (a failing
    replica records an error entry, never aborts the sweep)."""
    with self._lock:
      targets = list(self._targets)
    results: Dict[str, dict] = {}
    for t in targets:
      entry = {'ok': False, 'text': '', 'health': {},
               'error': None, 'ts': round(self._clock(), 3)}
      try:
        text, health = t.scrape()
        parse_prometheus_text(text)  # refuse malformed replicas loudly
        entry.update(ok=True, text=text, health=health)
      except Exception as e:          # noqa: BLE001 — a down replica
        entry['error'] = f'{type(e).__name__}: {e}'
        ctr = self._err_counters.get(t.name)
        if ctr is not None:
          ctr.inc()
      results[t.name] = entry
    with self._lock:
      self._last = results
    self._m_scrapes.inc()
    return results

  def _latest(self) -> Dict[str, dict]:
    with self._lock:
      last = dict(self._last)
    return last if last else self.scrape()

  # -- merged renderings ---------------------------------------------------
  def prometheus_text(self) -> str:
    """The federated exposition: per-replica samples under a
    ``replica=`` label plus ``glt_fleet_*`` aggregates."""
    last = self._latest()
    fam_meta: Dict[str, dict] = {}
    fam_order: List[str] = []
    #: family -> [(sample_name, labels, value, replica)]
    samples: Dict[str, List[Tuple[str, List, float, str]]] = {}
    for rname in sorted(last):
      st = last[rname]
      if not st['ok']:
        continue
      for fam, block in parse_exposition(st['text']).items():
        if fam not in fam_meta:
          fam_meta[fam] = {'type': block['type'], 'help': block['help']}
          fam_order.append(fam)
        for sname, labels, value in block['samples']:
          samples.setdefault(fam, []).append(
              (sname, labels, value, rname))

    lines: List[str] = []
    for fam in fam_order:
      meta = fam_meta[fam]
      if meta['help']:
        lines.append(f'# HELP {fam} {meta["help"]}')
      lines.append(f'# TYPE {fam} {meta["type"]}')
      for sname, labels, value, rname in samples.get(fam, ()):
        labeled = [(k, v) for k, v in labels] + [('replica', rname)]
        lines.append(f'{sname}{_render_labels(labeled)} {_fmt(value)}')
      lines.extend(self._aggregate_family(fam, meta,
                                          samples.get(fam, ())))
    return '\n'.join(lines) + '\n'

  def _aggregate_family(self, fam: str, meta: dict,
                        fam_samples) -> List[str]:
    agg_fam = FLEET_PREFIX + (fam[4:] if fam.startswith('glt_')
                              else fam)
    kind = meta['type']
    #: (sample_name, labels_key) -> merged value
    merged: Dict[Tuple[str, Tuple], float] = {}
    label_sets: Dict[Tuple[str, Tuple], List] = {}
    #: histogram quantile-merge state: labels_key -> {le: cum_count}
    buckets: Dict[Tuple, Dict[float, float]] = {}
    n_replicas = len({r for _, _, _, r in fam_samples})
    if not n_replicas:
      return []
    for sname, labels, value, _ in fam_samples:
      base_labels = [(k, v) for k, v in labels if k != 'replica']
      le = None
      if kind == 'histogram' and sname.endswith('_bucket'):
        le_items = [v for k, v in base_labels if k == 'le']
        base_labels = [(k, v) for k, v in base_labels if k != 'le']
        le = float(le_items[0]) if le_items else None
      lkey = tuple(base_labels)
      if le is not None:
        buckets.setdefault(lkey, {})
        buckets[lkey][le] = buckets[lkey].get(le, 0.0) + value
        skey = (sname, lkey + (('le', le_items[0]),))
        label_sets[skey] = base_labels + [('le', le_items[0])]
        merged[skey] = merged.get(skey, 0.0) + value
        continue
      skey = (sname, lkey)
      label_sets[skey] = base_labels
      if kind == 'gauge':
        merged[skey] = max(merged.get(skey, float('-inf')), value)
      else:                           # counter/untyped/_sum/_count: sum
        merged[skey] = merged.get(skey, 0.0) + value
    lines = [f'# HELP {agg_fam} fleet aggregate of {fam} over '
             f'{n_replicas} replicas '
             f'({"max" if kind == "gauge" else "sum"}'
             f'{"; quantile-merged" if kind == "histogram" else ""})',
             f'# TYPE {agg_fam} {kind}']
    for (sname, _), value in sorted(merged.items(),
                                    key=lambda kv: (kv[0][0],
                                                    str(kv[0][1]))):
      out_name = agg_fam + sname[len(fam):]
      labels = label_sets[(sname, _)]
      lines.append(f'{out_name}{_render_labels(labels)} {_fmt(value)}')
    if kind == 'histogram':
      for lkey, p50, p99 in _merged_quantiles(buckets):
        labels = list(lkey)
        lines.append(f'# TYPE {agg_fam}_p50_secs gauge')
        lines.append(f'{agg_fam}_p50_secs{_render_labels(labels)} '
                     f'{_fmt(p50)}')
        lines.append(f'# TYPE {agg_fam}_p99_secs gauge')
        lines.append(f'{agg_fam}_p99_secs{_render_labels(labels)} '
                     f'{_fmt(p99)}')
    return lines

  # -- fleet trace assembly -------------------------------------------------
  def fetch_trace(self, trace_id: str) -> List[dict]:
    """One request's spans from EVERY replica — the process-global
    tracer (in-process replicas all record there) plus each remote
    replica's ``/trace`` route — deduped by span_id and time-ordered.
    Unreachable replicas contribute nothing (the assembled tree is
    still served; a missing subtree IS the diagnostic)."""
    from .tracing import tracer
    spans: Dict[str, dict] = {
        s['span_id']: s for s in tracer.spans_of(trace_id)}
    with self._lock:
      targets = list(self._targets)
    for t in targets:
      if not isinstance(t, HttpTarget):
        continue                      # in-process: global tracer above
      try:
        status, body = t._get(f'/trace?trace_id={trace_id}')
        if status != 200:
          continue
        payload = json.loads(body.decode('utf-8'))
        for s in payload.get('spans', ()):
          if s.get('span_id'):
            spans.setdefault(s['span_id'], s)
      except Exception:               # noqa: BLE001 — a down replica
        continue
    return sorted(spans.values(),
                  key=lambda s: float(s.get('ts', 0.0)))

  def trace_chrome(self, trace_id: str) -> dict:
    """The assembled trace as a Chrome trace-event object (Perfetto-
    loadable; cross-process parent→child edges become flow arrows)."""
    from . import export
    from .tracing import spans_to_events
    return export.to_chrome_trace(
        spans_to_events(self.fetch_trace(trace_id)))

  def fleet_json(self) -> dict:
    """Healthz rollup: fleet ``ok`` is the AND over scrapeable
    replicas AND every replica being scrapeable."""
    last = self._latest()
    replicas = {}
    ok = bool(last)
    for name in sorted(last):
      st = last[name]
      r_ok = st['ok'] and bool(st['health'].get('ok', False))
      ok = ok and r_ok
      replicas[name] = {'ok': r_ok, 'error': st['error'],
                        'ts': st['ts'],
                        'health': st['health'] or None}
    return {'schema': 'glt.fleet.v1', 'ok': ok,
            'replicas_up': sum(1 for r in replicas.values() if r['ok']),
            'replicas': replicas,
            'scrape_ms': self.scrape_ms}

  # -- lifecycle -----------------------------------------------------------
  def start(self) -> 'FleetScraper':
    if self._thread is None:
      self._stop.clear()
      self._thread = threading.Thread(target=self._loop, daemon=True,
                                      name='glt-fleet-scraper')
      self._thread.start()
    return self

  def _loop(self) -> None:
    period = self.scrape_ms / 1000.0
    while not self._stop.wait(period):
      try:
        self.scrape()
      except Exception:               # noqa: BLE001 — keep polling
        pass

  def close(self) -> None:
    self._stop.set()
    t = self._thread
    if t is not None:
      t.join(2.0)
    self._thread = None
    self.registry.unregister_gauge('fleet.replicas_up', fn=self._up_fn)
