"""Mesh/cluster aggregation of host-local telemetry.

The device-side exchange counters of the mesh engines are already
cluster-global (each step's ``[P, 7]`` stats are summed over the sharded
axis before the host drains them), but everything the HOST ticks —
cold-tier lookups, loader batches, channel stalls, compile seconds — is
per-process.  :func:`gather_metrics` allgathers each host's `Metrics`
snapshot over the existing collective plane
(`jax.experimental.multihost_utils`, the same transport the cold-tier
capacity handshake rides) and sums them, so a multi-host job can report
cluster-wide numbers instead of host-0-only ones.

Single-controller processes (including the virtual CPU mesh the tests
and CI run) take the degenerate path: one host, aggregate == local.
Multi-process CPU meshes (the 2-process jax.distributed tests) have no
cross-process XLA collectives, so the transport falls back to the
jax.distributed COORDINATION service's key-value store
(`_kv_allgather_strings`) — same lockstep-call contract, same result.

The per-span-kind latency histograms (`telemetry.histogram`) ride this
exact machinery: they are flat ``span.<kind>.hist.*`` counter keys in
the registry, so ``gather_metrics(prefix='span.')`` IS the mesh-wide
histogram merge.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.profiling import Metrics, metrics


#: generation counter namespacing the KV-store allgather rounds —
#: correct as long as every process calls the collective helpers in
#: lockstep, which is already their contract (process_allgather is no
#: different).
_KV_GEN = [0]


def _kv_allgather_strings(payload: str) -> List[str]:
  """Allgather string payloads over the jax.distributed COORDINATION
  service (key-value store + barrier) instead of XLA collectives — the
  CPU backend has no cross-process collectives, but the coordinator is
  up whenever `jax.distributed.initialize` ran, so the virtual-mesh
  multi-process tests (and any CPU-mesh deployment) still aggregate.
  Each process publishes under a per-round key, reads every peer's,
  then deletes its own key after a barrier (no coordinator leak)."""
  import jax
  from jax._src import distributed
  client = distributed.global_state.client
  _KV_GEN[0] += 1
  gen = _KV_GEN[0]
  pid, n = jax.process_index(), jax.process_count()
  timeout_ms = 60_000
  client.key_value_set(f'glt/agg/{gen}/{pid}', payload)
  out = [client.blocking_key_value_get(f'glt/agg/{gen}/{i}',
                                       timeout_ms) for i in range(n)]
  client.wait_at_barrier(f'glt_agg_{gen}', timeout_ms)
  try:
    client.key_value_delete(f'glt/agg/{gen}/{pid}')
  except Exception:             # noqa: BLE001 — cleanup best-effort
    pass
  return out


def _allgather_strings(payload: str) -> List[str]:
  """One string payload per process.  XLA-collective transport
  (`process_allgather`, two rounds: length agreement then uint8-padded
  payloads) where the backend supports cross-process collectives; the
  coordination-service KV store on the CPU backend, which does not."""
  import jax
  if jax.process_count() == 1:
    return [payload]
  if jax.default_backend() == 'cpu':
    return _kv_allgather_strings(payload)
  from jax.experimental import multihost_utils
  raw = np.frombuffer(payload.encode('utf-8'), np.uint8)
  sizes = multihost_utils.process_allgather(
      np.asarray([raw.size], np.int64)).reshape(-1)
  cap = int(sizes.max())
  buf = np.zeros((max(cap, 1),), np.uint8)
  buf[:raw.size] = raw
  gathered = multihost_utils.process_allgather(buf)
  return [bytes(bytearray(gathered[i, :int(sizes[i])])).decode('utf-8')
          for i in range(gathered.shape[0])]


def _allgather_snapshots(snap: Dict[str, float]) -> List[Dict[str, float]]:
  """One snapshot per process — key sets may differ across hosts, so
  the payload is a JSON string, not a fixed vector."""
  return [json.loads(s) if s else {}
          for s in _allgather_strings(json.dumps(snap))]


def allgather_sum_int(vals) -> List[int]:
  """Element-wise SUM of an int vector across processes — the
  host-counter aggregation primitive (`cluster_exchange_stats` sums
  its cold-tier counters through this).  Single process: identity;
  CPU backend: the KV-store transport (same as `gather_metrics`)."""
  import jax
  if jax.process_count() == 1:
    return [int(v) for v in vals]
  if jax.default_backend() == 'cpu':
    rows = [json.loads(s)
            for s in _allgather_strings(json.dumps(
                [int(v) for v in vals]))]
    return [int(sum(col)) for col in zip(*rows)]
  from jax.experimental import multihost_utils
  return [int(x) for x in multihost_utils.process_allgather(
      np.asarray(vals, np.int64)).sum(axis=0)]


def gather_metrics(registry: Optional[Metrics] = None,
                   prefix: Optional[str] = None) -> Dict:
  """Cluster-wide view of a `Metrics` registry.

  Allgathers every process's ``registry.snapshot()`` and sums per key.
  ``prefix`` filters the snapshot before the exchange (smaller payload
  and a focused report, e.g. ``prefix='dist.'``).

  Returns ``{'num_hosts': H, 'aggregate': {key: summed}, 'per_host':
  [snapshot, ...]}`` — `per_host` preserves the raw inputs so callers
  can check the aggregate against the host-local numbers.
  """
  snap = (registry if registry is not None else metrics).snapshot()
  if prefix:
    snap = {k: v for k, v in snap.items() if k.startswith(prefix)}
  snaps = _allgather_snapshots(snap)
  agg: Dict[str, float] = {}
  for s in snaps:
    for k, v in s.items():
      agg[k] = agg.get(k, 0) + v
  return {'num_hosts': len(snaps), 'aggregate': agg, 'per_host': snaps}


def exchange_summary(stats: Dict[str, float]) -> Dict[str, float]:
  """Derived exchange health from a ``dist.*`` counter dict (the
  `exchange_stats` / `gather_metrics` key vocabulary): padding waste
  and drop rate per loss channel, the numbers the bench rounds track.
  """
  def g(k):
    return float(stats.get(k, 0))

  fr_off, fr_drop = g('dist.frontier.offered'), g('dist.frontier.dropped')
  fr_slots = g('dist.frontier.slots')
  ft_off, ft_drop = g('dist.feature.offered'), g('dist.feature.dropped')
  ft_slots = g('dist.feature.slots')
  sent_fr = fr_off - fr_drop
  sent_ft = ft_off - ft_drop
  out = {
      'frontier_padding_waste_pct': round(
          100.0 * (1 - sent_fr / fr_slots), 4) if fr_slots else None,
      'frontier_drop_rate_pct': round(
          100.0 * fr_drop / fr_off, 4) if fr_off else None,
      'feature_padding_waste_pct': round(
          100.0 * (1 - sent_ft / ft_slots), 4) if ft_slots else None,
      'feature_drop_rate_pct': round(
          100.0 * ft_drop / ft_off, 4) if ft_off else None,
      'negative_lost': g('dist.negative.lost'),
  }
  lookups = g('dist.feature.cold_lookups')
  if lookups:
    out['cold_hit_rate'] = round(
        1.0 - g('dist.feature.cold_misses') / lookups, 4)
  return out


def per_hop_padding(nsn, batch_size: int,
                    fanouts: Sequence[int]) -> List[Dict]:
  """Per-hop frontier sizes and padding-fill ratios from the sampler's
  ``num_sampled_nodes`` output.

  ``nsn`` is the per-hop NEW-node counts ``[H+1]`` (hop 0 = seeds), or
  any stacked/batched form of it — leading axes are summed and the
  capacities scaled by the collapsed multiplicity, so a ``[P, H+1]``
  mesh output or an epoch's ``[S, H+1]`` stack aggregates correctly.

  Hop ``h >= 1`` expands a frontier of capacity
  ``batch * prod(fanouts[:h-1])`` into a window of
  ``batch * prod(fanouts[:h])`` candidate slots; ``fill`` is the
  fraction of those slots that produced (new, for deduping samplers)
  nodes — ``1 - fill`` is that hop's padding waste.
  """
  arr = np.asarray(nsn, np.int64)
  mult = int(np.prod(arr.shape[:-1])) if arr.ndim > 1 else 1
  flat = arr.reshape(-1, arr.shape[-1]).sum(axis=0)
  caps = [batch_size]
  for k in fanouts:
    caps.append(caps[-1] * int(k))
  out = []
  for h in range(len(flat)):
    cap = caps[h] * mult if h < len(caps) else None
    row = {'hop': h, 'nodes': int(flat[h])}
    if cap:
      row['capacity'] = int(cap)
      row['fill'] = round(float(flat[h]) / cap, 6)
    out.append(row)
  return out
