"""Mesh/cluster aggregation of host-local telemetry.

The device-side exchange counters of the mesh engines are already
cluster-global (each step's ``[P, 7]`` stats are summed over the sharded
axis before the host drains them), but everything the HOST ticks —
cold-tier lookups, loader batches, channel stalls, compile seconds — is
per-process.  :func:`gather_metrics` allgathers each host's `Metrics`
snapshot over the existing collective plane
(`jax.experimental.multihost_utils`, the same transport the cold-tier
capacity handshake rides) and sums them, so a multi-host job can report
cluster-wide numbers instead of host-0-only ones.

Single-controller processes (including the virtual CPU mesh the tests
and CI run) take the degenerate path: one host, aggregate == local.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils.profiling import Metrics, metrics


def _allgather_snapshots(snap: Dict[str, float]) -> List[Dict[str, float]]:
  """One snapshot per process, via two `process_allgather` rounds
  (length agreement, then uint8-padded JSON payloads) — key sets may
  differ across hosts, so the payload is a string, not a vector."""
  import jax
  if jax.process_count() == 1:
    return [dict(snap)]
  from jax.experimental import multihost_utils
  payload = np.frombuffer(json.dumps(snap).encode('utf-8'), np.uint8)
  sizes = multihost_utils.process_allgather(
      np.asarray([payload.size], np.int64)).reshape(-1)
  cap = int(sizes.max())
  buf = np.zeros((max(cap, 1),), np.uint8)
  buf[:payload.size] = payload
  gathered = multihost_utils.process_allgather(buf)
  out = []
  for i in range(gathered.shape[0]):
    raw = bytes(bytearray(gathered[i, :int(sizes[i])]))
    out.append(json.loads(raw.decode('utf-8')) if raw else {})
  return out


def allgather_sum_int(vals) -> List[int]:
  """Element-wise SUM of an int vector across processes — the
  host-counter aggregation primitive (`cluster_exchange_stats` sums
  its cold-tier counters through this).  Single process: identity."""
  import jax
  if jax.process_count() == 1:
    return [int(v) for v in vals]
  from jax.experimental import multihost_utils
  return [int(x) for x in multihost_utils.process_allgather(
      np.asarray(vals, np.int64)).sum(axis=0)]


def gather_metrics(registry: Optional[Metrics] = None,
                   prefix: Optional[str] = None) -> Dict:
  """Cluster-wide view of a `Metrics` registry.

  Allgathers every process's ``registry.snapshot()`` and sums per key.
  ``prefix`` filters the snapshot before the exchange (smaller payload
  and a focused report, e.g. ``prefix='dist.'``).

  Returns ``{'num_hosts': H, 'aggregate': {key: summed}, 'per_host':
  [snapshot, ...]}`` — `per_host` preserves the raw inputs so callers
  can check the aggregate against the host-local numbers.
  """
  snap = (registry if registry is not None else metrics).snapshot()
  if prefix:
    snap = {k: v for k, v in snap.items() if k.startswith(prefix)}
  snaps = _allgather_snapshots(snap)
  agg: Dict[str, float] = {}
  for s in snaps:
    for k, v in s.items():
      agg[k] = agg.get(k, 0) + v
  return {'num_hosts': len(snaps), 'aggregate': agg, 'per_host': snaps}


def exchange_summary(stats: Dict[str, float]) -> Dict[str, float]:
  """Derived exchange health from a ``dist.*`` counter dict (the
  `exchange_stats` / `gather_metrics` key vocabulary): padding waste
  and drop rate per loss channel, the numbers the bench rounds track.
  """
  def g(k):
    return float(stats.get(k, 0))

  fr_off, fr_drop = g('dist.frontier.offered'), g('dist.frontier.dropped')
  fr_slots = g('dist.frontier.slots')
  ft_off, ft_drop = g('dist.feature.offered'), g('dist.feature.dropped')
  ft_slots = g('dist.feature.slots')
  sent_fr = fr_off - fr_drop
  sent_ft = ft_off - ft_drop
  out = {
      'frontier_padding_waste_pct': round(
          100.0 * (1 - sent_fr / fr_slots), 4) if fr_slots else None,
      'frontier_drop_rate_pct': round(
          100.0 * fr_drop / fr_off, 4) if fr_off else None,
      'feature_padding_waste_pct': round(
          100.0 * (1 - sent_ft / ft_slots), 4) if ft_slots else None,
      'feature_drop_rate_pct': round(
          100.0 * ft_drop / ft_off, 4) if ft_off else None,
      'negative_lost': g('dist.negative.lost'),
  }
  lookups = g('dist.feature.cold_lookups')
  if lookups:
    out['cold_hit_rate'] = round(
        1.0 - g('dist.feature.cold_misses') / lookups, 4)
  return out


def per_hop_padding(nsn, batch_size: int,
                    fanouts: Sequence[int]) -> List[Dict]:
  """Per-hop frontier sizes and padding-fill ratios from the sampler's
  ``num_sampled_nodes`` output.

  ``nsn`` is the per-hop NEW-node counts ``[H+1]`` (hop 0 = seeds), or
  any stacked/batched form of it — leading axes are summed and the
  capacities scaled by the collapsed multiplicity, so a ``[P, H+1]``
  mesh output or an epoch's ``[S, H+1]`` stack aggregates correctly.

  Hop ``h >= 1`` expands a frontier of capacity
  ``batch * prod(fanouts[:h-1])`` into a window of
  ``batch * prod(fanouts[:h])`` candidate slots; ``fill`` is the
  fraction of those slots that produced (new, for deduping samplers)
  nodes — ``1 - fill`` is that hop's padding waste.
  """
  arr = np.asarray(nsn, np.int64)
  mult = int(np.prod(arr.shape[:-1])) if arr.ndim > 1 else 1
  flat = arr.reshape(-1, arr.shape[-1]).sum(axis=0)
  caps = [batch_size]
  for k in fanouts:
    caps.append(caps[-1] * int(k))
  out = []
  for h in range(len(flat)):
    cap = caps[h] * mult if h < len(caps) else None
    row = {'hop': h, 'nodes': int(flat[h])}
    if cap:
      row['capacity'] = int(cap)
      row['fill'] = round(float(flat[h]) / cap, 6)
    out.append(row)
  return out
