"""Bounded, thread-safe JSON-lines flight recorder.

One `EventRecorder` holds a fixed-size in-memory ring of structured
events and (optionally) appends each event as one JSON line to a file.
Both sides are BOUNDED: the ring by ``max_events`` and the file by
``max_file_events`` — a runaway emitter can never eat the host's RAM or
disk (the "flight recorder" contract: keep the most recent window, drop
the oldest).

Producers call ``recorder.emit('hop.padding', hop=1, fill=0.42, ...)``
from any thread; when recording is off (the default) ``emit`` is a
single attribute check, so instrumentation can stay in hot host paths.

Event wire form (one JSON object per line)::

    {"ts": 1722700000.123, "mono": 12345.678901, "pid": 71,
     "tid": 1393..., "kind": "hop.padding", "hop": 1, ...}

``ts`` is ``time.time()`` at emit — human-readable, but steppable by
NTP; ``mono`` is ``time.monotonic()`` on the same event, the timebase
span durations and trace timelines are computed from (machine-wide on
Linux, so events from cooperating processes on one host order
correctly).  Every other field comes from the emitter.  Values are
coerced to JSON-serializable form; anything that still can't serialize
(bytes, enums, device arrays) degrades to ``repr`` — an event is never
lost to one bad field.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: env var: a path here enables the global recorder at import time.
TELEMETRY_ENV = 'GLT_TELEMETRY_JSONL'
#: env var: override the in-memory ring size of the global recorder.
TELEMETRY_EVENTS_ENV = 'GLT_TELEMETRY_EVENTS'

DEFAULT_MAX_EVENTS = 4096
DEFAULT_MAX_FILE_EVENTS = 200_000


def _jsonable(v: Any) -> Any:
  """Coerce numpy / jax scalars to plain python for json.dumps; values
  still unserializable after coercion (bytes, enums, device handles)
  degrade to ``repr`` — ``emit`` runs inside hot paths and must never
  raise out of them, and a degraded field beats a lost event."""
  if v is None or isinstance(v, (bool, int, float, str)):
    return v
  item = getattr(v, 'item', None)
  if item is not None and getattr(v, 'ndim', 0) == 0:
    try:
      return item()
    except Exception:             # noqa: BLE001 — best-effort coercion
      pass
  tolist = getattr(v, 'tolist', None)
  if tolist is not None and not isinstance(v, bytes):
    try:
      return tolist()
    except Exception:             # noqa: BLE001
      pass
  if isinstance(v, (list, tuple, dict)):
    # containers pass through; any unserializable LEAF degrades at
    # dump time (`_safe_dumps`)
    return v
  return repr(v)


def _safe_dumps(ev: Dict) -> str:
  """Serialize an event, degrading rather than raising: default=repr
  covers unserializable leaf VALUES, and the fallback re-dump covers
  what default can't (non-string dict KEYS, circular references) by
  repr-ing whole offending fields — the never-lose-the-event
  contract."""
  try:
    return json.dumps(ev, default=repr)
  except (TypeError, ValueError):
    return json.dumps(
        {k: (v if isinstance(v, (str, int, float, bool, type(None)))
             else repr(v)) for k, v in ev.items()})


class EventRecorder:
  """Bounded thread-safe event ring with an optional JSONL file sink.

  Args:
    path: JSONL file to append events to (None = ring only).
    max_events: in-memory ring capacity (oldest events drop first).
    max_file_events: hard cap on lines written to ``path`` per enable;
      past it the file stops growing (the ring keeps recording).
  """

  def __init__(self, path: Optional[str] = None,
               max_events: int = DEFAULT_MAX_EVENTS,
               max_file_events: int = DEFAULT_MAX_FILE_EVENTS):
    self._lock = threading.Lock()
    self._ring: collections.deque = collections.deque(
        maxlen=max(int(max_events), 1))
    self._path: Optional[str] = None
    self._file = None
    self._file_events = 0
    self._max_file_events = int(max_file_events)
    self._dropped_file_events = 0
    self._ring_dropped = 0
    self._overflow_emitted = False
    self.enabled = False
    if path:
      self.enable(path)

  # -- lifecycle ----------------------------------------------------------
  def enable(self, path: Optional[str] = None,
             max_events: Optional[int] = None,
             max_file_events: Optional[int] = None) -> 'EventRecorder':
    """Turn recording on (optionally into a JSONL file).  Idempotent;
    re-enabling with a different path closes the previous file."""
    with self._lock:
      if max_events is not None:
        self._ring = collections.deque(self._ring,
                                       maxlen=max(int(max_events), 1))
      if max_file_events is not None:
        self._max_file_events = int(max_file_events)
      # reopen on a NEW path, and also on the SAME path when the sink
      # was closed by an emit-time I/O failure (ENOSPC): a re-enable
      # after the operator frees space must resume the file, not
      # silently stay ring-only behind a stale `path` property
      if path is not None and (path != self._path
                               or self._file is None):
        self._close_file_locked()
        self._path = path
        # line-buffered append: each event is one write, so concurrent
        # processes sharing a path interleave at line granularity
        self._file = open(path, 'a', buffering=1)
        self._file_events = 0
      self.enabled = True
    return self

  def disable(self) -> None:
    with self._lock:
      self.enabled = False
      self._close_file_locked()
      self._path = None

  def _close_file_locked(self) -> None:
    if self._file is not None:
      try:
        self._file.close()
      except OSError:
        pass
      self._file = None

  @property
  def path(self) -> Optional[str]:
    return self._path

  # -- emit / read --------------------------------------------------------
  def emit(self, kind: str, **fields) -> None:
    """Record one event.  No-op (one attribute check) when disabled."""
    if not self.enabled:
      return
    # `mono` (time.monotonic) rides next to wall-clock `ts` so span
    # durations and cross-event timelines survive NTP steps/slews;
    # pid/tid put every event on its real process/thread row when
    # several processes append to one JSONL (the Chrome-trace rows)
    ev = {'ts': round(time.time(), 6),
          'mono': round(time.monotonic(), 6),
          'pid': os.getpid(), 'tid': threading.get_ident(),
          'kind': kind}
    for k, v in fields.items():
      ev[k] = _jsonable(v)
    overflow = False
    with self._lock:
      if not self.enabled:        # raced a disable()
        return
      if len(self._ring) == self._ring.maxlen:
        # the deque drops its oldest event on this append — count it
        # (the "did my window silently shrink" question an operator
        # asks an incident ring) and flag the FIRST drop for the
        # one-shot overflow event below
        self._ring_dropped += 1
        if not self._overflow_emitted:
          self._overflow_emitted = True
          overflow = True
      self._ring.append(ev)
      if self._file is not None:
        if self._file_events < self._max_file_events:
          # serialization can't raise (`_safe_dumps` degrades bad
          # fields in place); only a real I/O failure closes the sink
          try:
            self._file.write(_safe_dumps(ev) + '\n')
            self._file_events += 1
          except OSError:
            self._close_file_locked()
        else:
          self._dropped_file_events += 1
    if overflow:
      # one-shot, OUTSIDE the lock (this is a recursive emit; the
      # `_overflow_emitted` flag is already set, so it cannot loop):
      # the event marks WHEN the ring started losing history — the
      # cumulative count lives in `stats()['ring_dropped']` and the
      # `recorder.ring_dropped` live gauge
      self.emit('recorder.overflow', ring_capacity=self._ring.maxlen)

  @property
  def dropped_total(self) -> int:
    """Events lost to in-memory ring overflow since construction or
    the last `clear` (a cleared ring is a fresh window — stale drop
    counts would make a later post-mortem claim a partial window it
    never had)."""
    with self._lock:
      return self._ring_dropped

  def events(self, kind: Optional[str] = None) -> List[Dict]:
    """Snapshot of the in-memory ring (newest last), optionally
    filtered by ``kind``."""
    with self._lock:
      evs = list(self._ring)
    if kind is None:
      return evs
    return [e for e in evs if e['kind'] == kind]

  def clear(self) -> None:
    """Empty the ring and reset the overflow window: drop count and
    the one-shot `recorder.overflow` latch re-arm (the next trace's
    first drop gets its marker again)."""
    with self._lock:
      self._ring.clear()
      self._ring_dropped = 0
      self._overflow_emitted = False

  def dump(self, path: str) -> int:
    """Write the current ring snapshot as JSONL; returns event count."""
    evs = self.events()
    with open(path, 'w') as f:
      for e in evs:
        f.write(_safe_dumps(e) + '\n')
    return len(evs)

  def stats(self) -> Dict[str, int]:
    with self._lock:
      return {'ring_events': len(self._ring),
              'ring_capacity': self._ring.maxlen,
              'ring_dropped': self._ring_dropped,
              'file_events': self._file_events,
              'dropped_file_events': self._dropped_file_events}


def _from_env() -> EventRecorder:
  path = os.environ.get(TELEMETRY_ENV) or None
  try:
    cap = int(os.environ.get(TELEMETRY_EVENTS_ENV, DEFAULT_MAX_EVENTS))
  except ValueError:
    # this runs at package import: a malformed env var must degrade to
    # the default, not take down every `import graphlearn_tpu`
    cap = DEFAULT_MAX_EVENTS
  try:
    return EventRecorder(path=path, max_events=cap)
  except OSError:
    # unwritable JSONL path: degrade to RING-ONLY recording — the
    # user asked for telemetry, so the recorder must still be ON
    rec = EventRecorder(path=None, max_events=cap)
    return rec.enable() if path else rec


#: process-global flight recorder all library instrumentation emits to.
recorder = _from_env()
