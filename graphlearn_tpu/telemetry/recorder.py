"""Bounded, thread-safe JSON-lines flight recorder.

One `EventRecorder` holds a fixed-size in-memory ring of structured
events and (optionally) appends each event as one JSON line to a file.
Both sides are BOUNDED: the ring by ``max_events`` and the file by
``max_file_events`` — a runaway emitter can never eat the host's RAM or
disk (the "flight recorder" contract: keep the most recent window, drop
the oldest).

Producers call ``recorder.emit('hop.padding', hop=1, fill=0.42, ...)``
from any thread; when recording is off (the default) ``emit`` is a
single attribute check, so instrumentation can stay in hot host paths.

Event wire form (one JSON object per line)::

    {"ts": 1722700000.123, "kind": "hop.padding", "hop": 1, ...}

``ts`` is ``time.time()`` at emit; every other field comes from the
emitter.  Values must be JSON-serializable scalars/lists (numpy scalars
are coerced).
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: env var: a path here enables the global recorder at import time.
TELEMETRY_ENV = 'GLT_TELEMETRY_JSONL'
#: env var: override the in-memory ring size of the global recorder.
TELEMETRY_EVENTS_ENV = 'GLT_TELEMETRY_EVENTS'

DEFAULT_MAX_EVENTS = 4096
DEFAULT_MAX_FILE_EVENTS = 200_000


def _jsonable(v: Any) -> Any:
  """Coerce numpy / jax scalars to plain python for json.dumps."""
  item = getattr(v, 'item', None)
  if item is not None and getattr(v, 'ndim', 0) == 0:
    try:
      return item()
    except Exception:             # noqa: BLE001 — best-effort coercion
      pass
  tolist = getattr(v, 'tolist', None)
  if tolist is not None:
    try:
      return tolist()
    except Exception:             # noqa: BLE001
      pass
  return v


class EventRecorder:
  """Bounded thread-safe event ring with an optional JSONL file sink.

  Args:
    path: JSONL file to append events to (None = ring only).
    max_events: in-memory ring capacity (oldest events drop first).
    max_file_events: hard cap on lines written to ``path`` per enable;
      past it the file stops growing (the ring keeps recording).
  """

  def __init__(self, path: Optional[str] = None,
               max_events: int = DEFAULT_MAX_EVENTS,
               max_file_events: int = DEFAULT_MAX_FILE_EVENTS):
    self._lock = threading.Lock()
    self._ring: collections.deque = collections.deque(
        maxlen=max(int(max_events), 1))
    self._path: Optional[str] = None
    self._file = None
    self._file_events = 0
    self._max_file_events = int(max_file_events)
    self._dropped_file_events = 0
    self.enabled = False
    if path:
      self.enable(path)

  # -- lifecycle ----------------------------------------------------------
  def enable(self, path: Optional[str] = None,
             max_events: Optional[int] = None,
             max_file_events: Optional[int] = None) -> 'EventRecorder':
    """Turn recording on (optionally into a JSONL file).  Idempotent;
    re-enabling with a different path closes the previous file."""
    with self._lock:
      if max_events is not None:
        self._ring = collections.deque(self._ring,
                                       maxlen=max(int(max_events), 1))
      if max_file_events is not None:
        self._max_file_events = int(max_file_events)
      if path is not None and path != self._path:
        self._close_file_locked()
        self._path = path
        # line-buffered append: each event is one write, so concurrent
        # processes sharing a path interleave at line granularity
        self._file = open(path, 'a', buffering=1)
        self._file_events = 0
      self.enabled = True
    return self

  def disable(self) -> None:
    with self._lock:
      self.enabled = False
      self._close_file_locked()
      self._path = None

  def _close_file_locked(self) -> None:
    if self._file is not None:
      try:
        self._file.close()
      except OSError:
        pass
      self._file = None

  @property
  def path(self) -> Optional[str]:
    return self._path

  # -- emit / read --------------------------------------------------------
  def emit(self, kind: str, **fields) -> None:
    """Record one event.  No-op (one attribute check) when disabled."""
    if not self.enabled:
      return
    ev = {'ts': round(time.time(), 6), 'kind': kind}
    for k, v in fields.items():
      ev[k] = _jsonable(v)
    with self._lock:
      if not self.enabled:        # raced a disable()
        return
      self._ring.append(ev)
      if self._file is not None:
        if self._file_events < self._max_file_events:
          try:
            self._file.write(json.dumps(ev) + '\n')
            self._file_events += 1
          except (OSError, ValueError):
            self._close_file_locked()
        else:
          self._dropped_file_events += 1

  def events(self, kind: Optional[str] = None) -> List[Dict]:
    """Snapshot of the in-memory ring (newest last), optionally
    filtered by ``kind``."""
    with self._lock:
      evs = list(self._ring)
    if kind is None:
      return evs
    return [e for e in evs if e['kind'] == kind]

  def clear(self) -> None:
    with self._lock:
      self._ring.clear()

  def dump(self, path: str) -> int:
    """Write the current ring snapshot as JSONL; returns event count."""
    evs = self.events()
    with open(path, 'w') as f:
      for e in evs:
        f.write(json.dumps(e) + '\n')
    return len(evs)

  def stats(self) -> Dict[str, int]:
    with self._lock:
      return {'ring_events': len(self._ring),
              'ring_capacity': self._ring.maxlen,
              'file_events': self._file_events,
              'dropped_file_events': self._dropped_file_events}


def _from_env() -> EventRecorder:
  path = os.environ.get(TELEMETRY_ENV) or None
  try:
    cap = int(os.environ.get(TELEMETRY_EVENTS_ENV, DEFAULT_MAX_EVENTS))
  except ValueError:
    # this runs at package import: a malformed env var must degrade to
    # the default, not take down every `import graphlearn_tpu`
    cap = DEFAULT_MAX_EVENTS
  try:
    return EventRecorder(path=path, max_events=cap)
  except OSError:
    # unwritable JSONL path: record to the ring only
    return EventRecorder(path=None, max_events=cap)


#: process-global flight recorder all library instrumentation emits to.
recorder = _from_env()
