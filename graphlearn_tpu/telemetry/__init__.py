"""Structured telemetry plane: flight recorder, mesh aggregation, sinks.

The reference has NO tracing/profiling subsystem (SURVEY §5: wall-clock
prints in benchmarks only); `utils/profiling.py` grew the first counters
and xprof hooks, and this package grows them into a real layer with
three pieces:

  * :mod:`~graphlearn_tpu.telemetry.recorder` — a bounded, thread-safe
    JSON-lines "flight recorder" (`EventRecorder` / the global
    :data:`recorder`).  Samplers, loaders, channels and the fused
    epochs emit structured events into it: per-hop frontier sizes and
    padding-fill ratios, slack-cap drops and `AdaptiveSlack` ladder
    transitions, compile-cache hits/misses with `_uncached_jit` compile
    seconds, channel ring occupancy/stalls, and cold-tier hit/miss from
    tiered feature stores.  Recording is OFF by default (`emit` is a
    single attribute check); enable with
    ``recorder.enable('/path/flight.jsonl')`` or the
    ``GLT_TELEMETRY_JSONL`` env var.
  * :mod:`~graphlearn_tpu.telemetry.aggregate` —
    :func:`gather_metrics` allgathers per-host `Metrics` snapshots over
    the existing collective plane so the distributed engines report
    CLUSTER-wide padding-waste / drop-rate / throughput instead of
    host-0-only numbers (`DistNeighborSampler.cluster_exchange_stats`).
  * :mod:`~graphlearn_tpu.telemetry.sink` — the file-based bench
    artifact sink: the full artifact JSON goes to ``BENCH_ARTIFACT.json``
    (``GLT_BENCH_ARTIFACT`` overrides) and stdout carries only a short
    summary line, so a driver that tails the last 2000 characters can
    never truncate the artifact again (the `BENCH_r05.json`
    ``"parsed": null`` failure mode).

On top of the recorder sits the CAUSAL layer (this PR's tentpole):

  * :mod:`~graphlearn_tpu.telemetry.spans` — ``span()`` context
    manager emitting paired ``span.begin``/``span.end`` events with
    ``trace_id``/``span_id``/``parent_id`` and monotonic-clock
    durations; the pipeline (channels, mesh samplers, loaders, the
    server/client runtime, fused epochs) opens sample → exchange →
    feature-lookup → stitch → dispatch child spans, and the context
    crosses process boundaries inside each `SampleMessage`.
  * :mod:`~graphlearn_tpu.telemetry.histogram` — fixed-bucket log2
    latency histograms per span kind, encoded as flat metric keys so
    :func:`gather_metrics` merges them across hosts for free.
  * :mod:`~graphlearn_tpu.telemetry.export` /
    :mod:`~graphlearn_tpu.telemetry.report` — recorder dump → Chrome
    trace-event JSON (Perfetto-loadable), and the
    ``python -m graphlearn_tpu.telemetry.report`` per-stage latency
    table / trace-diff CLI.
  * :mod:`~graphlearn_tpu.telemetry.regress` — the bench regression
    gate (`bench.py --check-regression`): artifact vs committed
    ``BENCH_BASELINE.json``, nonzero exit + per-metric report on a
    threshold breach.
  * :mod:`~graphlearn_tpu.telemetry.schema` — the registry of event
    kinds and span names the static schema test enforces.

xprof integration: :func:`step_annotation` wraps
`jax.profiler.StepTraceAnnotation` so fused-epoch dispatches show up as
steps on the TensorBoard timeline; ``bench.py --trace-dir DIR`` captures
a trace around the fused session.

The LIVE ops plane (ISSUE 12) sits beside the offline stack:

  * :mod:`~graphlearn_tpu.telemetry.live` — the declared live-metric
    registry (`LiveRegistry` / the global :data:`live`): counters and
    log2 histograms writing through the shared `Metrics` store (one
    vocabulary with the offline artifact and `gather_metrics`), plus
    scrape-time gauges and health providers.
  * :mod:`~graphlearn_tpu.telemetry.opsserver` — the per-process HTTP
    ops endpoint (``/metrics`` Prometheus text, ``/varz`` JSON,
    ``/healthz``), bound via ``GLT_OPS_PORT`` (0 = disabled, default).
  * :mod:`~graphlearn_tpu.telemetry.slo` — serving SLO tracking:
    sliding-window percentiles and multi-window error-budget burn
    rate vs ``GLT_SERVING_SLO_P99_MS`` / ``GLT_SERVING_SLO_QPS``.
  * :mod:`~graphlearn_tpu.telemetry.postmortem` — the black box: on
    `MeshStallError` / irrecoverable peers / executor faults / fatal
    signals, one timestamped bundle (recorder ring + metrics snapshot
    + health + time-series rings) to ``GLT_POSTMORTEM_DIR``, rendered
    by ``report.py --postmortem``.

The fleet signal plane (ISSUE 16) completes the live stack:

  * :mod:`~graphlearn_tpu.telemetry.timeseries` — `TimeSeriesStore`:
    fixed-cadence samples of every live gauge/counter into bounded
    rings (counters become ``:rate`` series), served at
    ``/timeseries`` and attached to post-mortem bundles.
  * :mod:`~graphlearn_tpu.telemetry.federation` — `FleetScraper`:
    polls replica ops endpoints / in-process registries, re-labels
    each sample with ``replica=`` and merges ``glt_fleet_*``
    aggregates, served at ``/fleet``
    (``FleetRouter.make_scraper()`` wires a serving fleet up).

Request-scoped fleet tracing (ISSUE 17) rides the live stack:

  * :mod:`~graphlearn_tpu.telemetry.tracing` — the request
    `Tracer` (global :data:`tracer`): the router mints a trace
    context that rides the serve RPC, every hop records completed
    spans, and tail-based retention (slow / failed / 1-in-N) keeps
    the interesting traces in a bounded ring served at ``/traces``
    and ``/trace?trace_id=`` (``?format=chrome`` =
    Perfetto-loadable; `FleetScraper.fetch_trace` reassembles the
    cross-process tree first).  Live histograms attach the last
    trace id per bucket as an OpenMetrics EXEMPLAR on ``/metrics``.
  * :mod:`~graphlearn_tpu.telemetry.memaccount` — per-tier byte
    accounting (``memory.tier_bytes{tier=}`` + peaks over
    :data:`~graphlearn_tpu.telemetry.memaccount.TIERS`) and the
    `CapacityModel` EWMA cost model behind ``fleet.headroom_qps``.

The low-level counter/timer registry (`Metrics`, the global
:data:`metrics`, `trace`, `capture`) still lives in
:mod:`graphlearn_tpu.utils.profiling` and is re-exported here.
"""
from __future__ import annotations

from ..utils.profiling import (Metrics, capture, metrics, start_trace,
                               step_annotation, stop_trace, trace)
from .aggregate import exchange_summary, gather_metrics, per_hop_padding
from .federation import FleetScraper
from .histogram import Histogram, from_snapshot
from .live import (LiveRegistry, live, parse_prometheus_text,
                   split_exemplar)
from .memaccount import TIERS, CapacityModel, register_tier
from .opsserver import OpsServer, maybe_start_from_env
from .recorder import EventRecorder, recorder
from .sink import (artifact_path, append_record, summary_line,
                   write_artifact)
from .slo import SloTracker
from .spans import SpanContext, span
from .timeseries import TimeSeriesStore
from .tracing import Tracer, child_ctx, spans_to_events, tracer

__all__ = [
    'CapacityModel', 'EventRecorder', 'FleetScraper', 'Histogram',
    'LiveRegistry', 'Metrics', 'OpsServer', 'SloTracker',
    'SpanContext', 'TIERS', 'TimeSeriesStore', 'Tracer',
    'append_record', 'artifact_path', 'capture', 'child_ctx',
    'exchange_summary', 'from_snapshot', 'gather_metrics', 'live',
    'maybe_start_from_env', 'metrics', 'parse_prometheus_text',
    'per_hop_padding', 'recorder', 'register_tier', 'span',
    'spans_to_events', 'split_exemplar', 'start_trace',
    'step_annotation', 'stop_trace', 'summary_line', 'trace',
    'tracer', 'write_artifact',
]
