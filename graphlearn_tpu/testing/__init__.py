"""Deterministic failure tooling for the distributed runtime.

`graphlearn_tpu.testing.chaos` is the fault-injection harness the
resilience layer is proved against; it ships in the package (not under
tests/) because producer subprocesses and sampling servers must be
able to import it wherever they run.
"""
from .chaos import (ChaosPlan, Fault, FAULT_PLAN_ENV, WORKER_KILL_EXIT,
                    active, install, parse_plan, uninstall)

__all__ = [
    'ChaosPlan', 'Fault', 'FAULT_PLAN_ENV', 'WORKER_KILL_EXIT',
    'active', 'install', 'parse_plan', 'uninstall',
]
