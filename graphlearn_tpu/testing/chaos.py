"""Seeded, declarative fault injection for the distributed runtime.

The resilience layer (`distributed/resilience.py`) claims a flaky
peer degrades into a retry, not a hung TPU step; this harness makes
that claim testable.  A *fault plan* is a list of :class:`Fault`
records naming a **site** (an injection seam the runtime calls into),
an **action**, and *when* to fire (the ``nth`` matching arrival at
that seam, counted per fault — deterministic under a fixed plan, no
wall clocks involved).  Sites and actions:

  ``rpc.request``
      Seam inside `RpcClient.request`, once per attempt.  Actions:
      ``drop`` (sever the connection after the request is sent — the
      server may have executed it, exercising the replay cache),
      ``delay`` (sleep ``secs`` before sending), ``corrupt`` (scramble
      the reply payload so the client misparses — exercising the
      reset-on-partial-read path).  ``op`` filters by handler name.
  ``producer.worker``
      Seam at the top of a sampling worker's per-batch loop.  Action
      ``kill`` ( ``os._exit(WORKER_KILL_EXIT)`` — a hard crash, no
      cleanup, like the OOM killer).  ``worker`` / ``epoch`` filter by
      worker rank and epoch.
  ``checkpoint.io``
      Seam inside `utils.checkpoint.Checkpointer.save`.  Actions:
      ``fail`` (the write dies before any byte lands), ``truncate``
      (a PARTIAL tmp write then death before the atomic publish — the
      kill-mid-write scenario; the previous snapshot must stay the
      durable latest).
  ``fused.dispatch``
      Seam around each fused-epoch chunk dispatch (`loader.fused`,
      `parallel.fused`).  Actions: ``delay`` (sleep ``secs`` INSIDE
      the watchdog-timed region, so a configured
      ``GLT_DISPATCH_DEADLINE`` converts it into `MeshStallError` —
      the hung-collective simulation), ``kill`` (raise
      :class:`ChaosKilledError` — the in-process stand-in for a
      preemption; the producer-worker site keeps the real
      ``os._exit`` arm).  ``epoch`` filters by epoch.
  ``feature.cold_service``
      Seam at the top of the host cold-tier gather (single-chip
      `data.feature.Feature` mixed path and the mesh cold overlay).
      Action ``fail`` raises :class:`InjectedFault` — a host feature
      tier that died mid-epoch; the snapshot/resume layer is what
      turns it into a finished epoch.
  ``serving.request``
      Two seams in the online serving plane, distinguished by ``op``:
      ``op='serve_infer'`` fires inside the `DistServer.serve_infer`
      RPC handler (before admission), ``op='dispatch'`` inside the
      serving executor just before a coalesced dispatch.  Actions:
      ``delay`` (sleep ``secs`` — a slow executor; queued requests
      behind it expire and SHED typed, the SLO-gating under test),
      ``drop`` (raise :class:`InjectedFault` — the request/dispatch
      dies server-side; the client sees a typed error, and a
      transport-level retry of the same RPC is answered by the replay
      cache, never re-executed).
  ``ops.scrape``
      Seam at the top of the ops-endpoint HTTP handler
      (`telemetry.opsserver`), ``op`` = route path (``/metrics`` /
      ``/varz`` / ``/healthz``).  Actions: ``delay`` (a stalled
      scraper — must never block the serving executor or a fused
      dispatch), ``drop`` (raise :class:`InjectedFault`; the handler
      answers HTTP 503).
  ``serving.replica``
      Seam inside a fleet replica handle (`serving.router`), fired on
      ``op='submit'`` and ``op='heartbeat'`` arrivals; ``replica``
      filters by replica name.  Actions: ``kill`` (the replica dies
      for good — its executor stops cold, queued requests freeze, and
      the `FleetRouter` must evict it and REDRIVE its in-flight
      requests to a survivor), ``delay`` (a slow replica — heartbeats
      and submits stall ``secs``; the router keeps it at reduced
      weight instead of evicting, the overloaded-vs-dead
      discriminator under test), ``flap`` (unreachable for ``secs``
      then back — a network partition; shorter than the router's
      eviction threshold it costs nothing, longer it costs one
      eviction + redrive and a later re-admission).
  ``aot.cache``
      Seam inside the persistent AOT executable cache
      (`serving.aot_cache`), ``op`` = ``'save'`` / ``'load'``.
      Actions: ``fail`` (the write/read dies — absorbed: a cache
      fault must cost a recompile, never an unserved bucket),
      ``corrupt`` (the payload lands scrambled on disk — a later
      load must detect the bad checksum and fall back to recompile,
      never deserialize garbage into a wrong executable).
  ``ingest.wal``
      Seam inside `streaming.wal.WriteAheadLog.append`.  Actions:
      ``fail`` (the append dies before any byte lands — the caller
      sees a typed error and the log is unchanged), ``truncate``
      (a PARTIAL record is written and the process "dies" mid-append
      — the kill-mid-write scenario; the next open must detect the
      torn tail by checksum, truncate back to the last whole record,
      and replay must land exactly the whole-record prefix).
  ``ingest.apply``
      Seam inside `streaming.ingest.IngestPipeline` BETWEEN the
      durable WAL append and the in-memory delta-CSR commit.
      Actions: ``kill`` (raise :class:`ChaosKilledError` — the
      process dies with the event logged but not applied; a restart
      must replay it from the WAL exactly once), ``delay`` (a slow
      apply — the ``ingest.lag_events`` gauge grows and, past
      ``GLT_INGEST_MAX_LAG``, flips the ingestion healthz component).
  ``ingest.compact``
      Seam inside `streaming.ingest.IngestPipeline.compact`, fired
      BEFORE the compacted-base snapshot publishes.  Action ``kill``
      (raise :class:`ChaosKilledError` mid-compaction — the previous
      snapshot + the full WAL stay the durable truth; a restart
      replays to the identical graph).
  ``scale.spawn``
      Seam inside the ElasticController's scale-out path
      (`serving.autoscaler`), fired once per spawn attempt BEFORE the
      replica factory runs.  Actions: ``delay`` (a slow provision —
      sleeps in place, the evaluation loop stalls but nothing is
      admitted half-built), ``fail`` (raise :class:`InjectedFault` —
      provisioning died), ``kill`` (raise :class:`ChaosKilledError` —
      the spawn died mid-flight).  Either raise must roll the decision
      back typed (no partial replica in rotation) and re-arm: the
      cooldown is NOT spent on a failed decision.
  ``handoff.transfer``
      Seam inside the planned partition handoff (`parallel.handoff`),
      fired once per phase with ``op`` = the seam name (``snapshot`` /
      ``transfer`` / ``fence`` / ``cutover`` / ``drain``) and
      ``partition`` = the moving range.  Actions: ``delay`` (sleeps in
      place — the source keeps serving throughout, that is the zero-
      degraded-window contract), ``fail`` (raise
      :class:`InjectedFault`), ``kill`` (raise
      :class:`ChaosKilledError`).  A raise at any seam BEFORE
      ``cutover`` unwinds to clean source retention (book untouched,
      staged shard dropped, typed `HandoffAbortedError`); at ``drain``
      the cutover has already published, so the destination owns the
      range — never two owners either way.

Plans install three ways: programmatically (:func:`install`), from the
``GLT_FAULT_PLAN`` env var (inherited by producer subprocesses and
sampling servers — how cross-process chaos reaches them), or not at
all — every seam is a single module-attribute check when no plan is
active, so the harness costs nothing in production.

Plan syntax — JSON::

    {"seed": 7, "faults": [
      {"site": "rpc.request", "action": "drop", "nth": 3,
       "op": "fetch_one_sampled_message"},
      {"site": "producer.worker", "action": "kill", "nth": 2,
       "worker": 0}]}

or the compact form (``;``-separated, ``site:action:nth[:key=val...]``)::

    rpc.request:drop:3:op=fetch_one_sampled_message;producer.worker:kill:2:worker=0

Every fired fault emits a ``fault.injected`` flight-recorder event, so
a chaos run's injected faults and the retries/restarts they caused
read out of ONE event stream.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

FAULT_PLAN_ENV = 'GLT_FAULT_PLAN'

#: exit code of a chaos-killed sampling worker (distinctive in
#: ``dead_worker_exitcodes`` so tests can tell injected kills from
#: real crashes).
WORKER_KILL_EXIT = 173

_SITES = ('rpc.request', 'producer.worker', 'checkpoint.io',
          'fused.dispatch', 'feature.cold_service', 'serving.request',
          'ops.scrape', 'serving.replica', 'aot.cache', 'ingest.wal',
          'ingest.apply', 'ingest.compact', 'partition.owner',
          'scale.spawn', 'handoff.transfer')
_ACTIONS = ('drop', 'delay', 'corrupt', 'kill', 'fail', 'truncate',
            'flap')


class InjectedFault(RuntimeError):
  """A chaos 'fail' action fired: the real-world analog (disk error,
  host OOM, cold-tier service death) raised mid-operation."""


class ChaosKilledError(RuntimeError):
  """A planned ``fused.dispatch:kill`` fired — the in-process stand-in
  for a preemption (SIGKILL would also kill the test runner; the
  producer-worker site keeps the real ``os._exit`` arm).  Everything a
  real kill loses is lost here too: the test must resume from the
  DURABLE snapshot in a fresh driver, not from live state."""


@dataclass
class Fault:
  """One planned fault: fire ``count`` times starting at the ``nth``
  matching arrival (1-based) at ``site``."""
  site: str
  action: str
  nth: int = 1
  count: int = 1
  op: Optional[str] = None        # rpc.request: handler-name filter
  worker: Optional[int] = None    # producer.worker: rank filter
  epoch: Optional[int] = None     # producer.worker: epoch filter
  replica: Optional[str] = None   # serving.replica: replica-name filter
  #: partition.owner: the VICTIM partition (a kill here classifies
  #: that owner dead at the next dispatch seam); also filters when the
  #: seam names one
  partition: Optional[int] = None
  #: producer.worker: restart-generation filter — ``0`` targets only
  #: the ORIGINAL worker incarnation, so a deterministic kill cannot
  #: re-fire inside the supervisor's replacement (whose fresh process
  #: restarts the arrival counters)
  generation: Optional[int] = None
  secs: float = 0.1               # delay duration
  _seen: int = field(default=0, repr=False, compare=False)

  def __post_init__(self):
    if self.site not in _SITES:
      raise ValueError(f'unknown fault site {self.site!r} '
                       f'(expected one of {_SITES})')
    if self.action not in _ACTIONS:
      raise ValueError(f'unknown fault action {self.action!r} '
                       f'(expected one of {_ACTIONS})')

  def _matches(self, ctx: Dict[str, Any]) -> bool:
    if self.op is not None and ctx.get('op') != self.op:
      return False
    if self.worker is not None and ctx.get('worker') != self.worker:
      return False
    if self.epoch is not None and ctx.get('epoch') != self.epoch:
      return False
    if self.generation is not None and \
        ctx.get('generation') != self.generation:
      return False
    if self.replica is not None and ctx.get('replica') != self.replica:
      return False
    if (self.partition is not None and 'partition' in ctx
        and ctx.get('partition') != self.partition):
      return False
    return True


class ChaosPlan:
  """A set of faults plus the seeded RNG probabilistic faults draw
  from.  Arrival counting is per fault, under a lock — deterministic
  for single-threaded seams (the chaos tests run prefetch depth 1 so
  RPC arrivals are totally ordered)."""

  def __init__(self, faults: List[Fault], seed: int = 0):
    self.faults = list(faults)
    self.seed = int(seed)
    self.rng = random.Random(self.seed)
    self._lock = threading.Lock()
    self.fired: List[Dict[str, Any]] = []

  def on(self, site: str, **ctx) -> List[Fault]:
    """Record one arrival at ``site``; return the faults that fire."""
    fired = []
    with self._lock:
      for f in self.faults:
        if f.site != site or not f._matches(ctx):
          continue
        f._seen += 1
        if f.nth <= f._seen < f.nth + f.count:
          fired.append(f)
          rec = {'site': site, 'action': f.action, 'arrival': f._seen}
          rec.update({k: v for k, v in ctx.items()
                      if isinstance(v, (str, int, float))})
          self.fired.append(rec)
    for f in fired:
      _emit_injected(f, site, ctx)
    return fired

  def exhausted(self) -> bool:
    """Every planned fault has fired its full count."""
    with self._lock:
      return all(f._seen >= f.nth + f.count - 1 for f in self.faults)


def _emit_injected(f: Fault, site: str, ctx: Dict[str, Any]) -> None:
  from ..telemetry.recorder import recorder
  recorder.emit('fault.injected', site=site, action=f.action,
                nth=f.nth, arrival=f._seen,
                op=ctx.get('op'), worker=ctx.get('worker'),
                epoch=ctx.get('epoch'),
                secs=(f.secs if f.action == 'delay' else None))


def parse_plan(spec) -> ChaosPlan:
  """Parse a plan from a dict / list / JSON string / compact string."""
  if isinstance(spec, ChaosPlan):
    return spec
  seed = 0
  if isinstance(spec, str):
    s = spec.strip()
    if s.startswith('{') or s.startswith('['):
      spec = json.loads(s)
    else:
      return ChaosPlan([_parse_compact(part)
                        for part in s.split(';') if part.strip()])
  if isinstance(spec, dict):
    seed = int(spec.get('seed', 0))
    spec = spec.get('faults', [])
  faults = [f if isinstance(f, Fault) else Fault(**f) for f in spec]
  return ChaosPlan(faults, seed=seed)


def _parse_compact(part: str) -> Fault:
  toks = part.strip().split(':')
  if len(toks) < 2:
    raise ValueError(f'bad compact fault {part!r}: need site:action')
  kw: Dict[str, Any] = {'site': toks[0], 'action': toks[1]}
  if len(toks) > 2 and toks[2]:
    kw['nth'] = int(toks[2])
  for tok in toks[3:]:
    if '=' not in tok:
      raise ValueError(f'bad compact fault field {tok!r} in {part!r}')
    k, v = tok.split('=', 1)
    if k in ('nth', 'count', 'worker', 'epoch', 'generation',
             'partition'):
      kw[k] = int(v)
    elif k == 'secs':
      kw[k] = float(v)
    else:
      kw[k] = v
  return Fault(**kw)


# -- process-global plan ----------------------------------------------------
_plan: Optional[ChaosPlan] = None
_env_checked = False
_install_lock = threading.Lock()


def install(spec) -> ChaosPlan:
  """Install ``spec`` as the process's active plan (replacing any)."""
  global _plan, _env_checked
  with _install_lock:
    _plan = parse_plan(spec)
    _env_checked = True
  return _plan


def uninstall() -> None:
  """Deactivate chaos for this process (the env var stays untouched —
  subprocesses spawned later still inherit it)."""
  global _plan, _env_checked
  with _install_lock:
    _plan = None
    _env_checked = True


def active() -> Optional[ChaosPlan]:
  """The process's plan, lazily initialized from ``GLT_FAULT_PLAN``
  (how producer subprocesses and server processes pick chaos up)."""
  global _plan, _env_checked
  if _plan is None and not _env_checked:
    with _install_lock:
      if _plan is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get(FAULT_PLAN_ENV)
        if spec:
          _plan = parse_plan(spec)
  return _plan


# -- seams ------------------------------------------------------------------
def on(site: str, **ctx) -> List[Fault]:
  """The generic seam: no-op (one global read) without a plan."""
  p = active()
  return p.on(site, **ctx) if p is not None else []


def rpc_faults(op: str) -> List[Fault]:
  """`RpcClient.request` seam, called once per attempt.  The caller
  applies the returned actions (sleep for ``delay``, sever for
  ``drop``, scramble the reply for ``corrupt``)."""
  return on('rpc.request', op=op)


def maybe_delay(faults: List[Fault]) -> None:
  for f in faults:
    if f.action == 'delay':
      time.sleep(f.secs)


def corrupt_payload(payload: bytes) -> bytes:
  """Deterministically scramble a reply payload (bit-flip every 7th
  byte) — enough to break both pickle and tensor-map parsing."""
  buf = bytearray(payload)
  if not buf:
    return b'\xff\xff\xff\xff'
  buf[::7] = bytes((b ^ 0xFF) for b in buf[::7])
  return bytes(buf)


def worker_kill_check(rank: int, epoch: int, generation: int = 0,
                      flush=()) -> None:
  """Sampling-worker seam, called before each batch; a fired ``kill``
  hard-exits the process (no cleanup — a real crash).  ``generation``
  is the supervisor's restart count for this rank (0 = original).

  ``flush`` holds mp queues (the producer's progress-ack queue) whose
  feeder threads are joined BEFORE the exit.  The seam models a crash
  BETWEEN batches: every prior batch was already durably sent to the
  channel, and its ack merely sits in the mp.Queue feeder buffer — a
  plain ``os._exit`` raced that feeder, sometimes losing acks for
  batches the channel already holds, so the supervisor replayed the
  FULL assignment and the replacement re-fired the same deterministic
  ``nth`` kill until the restart budget died (the exact hazard
  `MpSamplingProducer._unacked` documents).  Joining the feeder keeps
  the simulation honest (a real crash that loses acks only replays
  already-delivered batches — harmless dedup — nondeterministically,
  not deterministically forever) and makes kill-fault replays exactly
  the unsent batches."""
  for f in on('producer.worker', worker=rank, epoch=epoch,
              generation=generation):
    if f.action == 'kill':
      for q in flush:
        try:
          q.close()
          q.join_thread()
        except Exception:           # noqa: BLE001 — best-effort flush
          pass
      os._exit(WORKER_KILL_EXIT)


def fused_dispatch_check(chunk: int = 0, epoch: int = 0,
                         phase: str = '') -> None:
  """Fused-chunk-dispatch seam (called INSIDE the watchdog-timed
  region): ``delay`` sleeps there so a configured dispatch deadline
  sees a hung collective; ``kill`` raises `ChaosKilledError` (the
  preemption stand-in)."""
  for f in on('fused.dispatch', chunk=chunk, epoch=epoch, op=phase or
              None):
    if f.action == 'delay':
      time.sleep(f.secs)
    elif f.action == 'kill':
      raise ChaosKilledError(
          f'injected fused.dispatch kill (epoch {epoch}, chunk '
          f'{chunk})')


def cold_service_check(scope: str = '') -> None:
  """Host cold-tier gather seam; ``fail`` raises `InjectedFault`."""
  for f in on('feature.cold_service', op=scope or None):
    if f.action == 'fail':
      raise InjectedFault(
          f'injected cold-tier service failure (scope {scope!r})')


def ops_scrape_check(path: str = '') -> None:
  """Ops-endpoint seam (`telemetry.opsserver`), once per HTTP request
  with ``op=<route path>``: ``delay`` stalls the scrape handler thread
  in place (the isolation under test — a wedged scraper must never
  block the serving executor or a fused dispatch), ``drop`` raises
  `InjectedFault` (the handler answers 503; the scraper's problem,
  nobody else's)."""
  for f in on('ops.scrape', op=path or None):
    if f.action == 'delay':
      time.sleep(f.secs)
    elif f.action == 'drop':
      raise InjectedFault(f'injected ops scrape drop (path {path!r})')


def partition_owner_check(step: int = 0) -> None:
  """Partition-owner seam (ISSUE 15), one arrival per mesh dispatch
  (called BEFORE the sampler's key stream advances, so a recovered
  dispatch replays byte-identically).  ``delay`` models a slow-but-
  alive owner (sleeps in place — the epoch slows, nothing is
  reclassified: the PR 13 overloaded-vs-dead discriminator); ``kill``
  classifies the fault's ``partition`` dead and raises the typed
  `PartitionLostError` the recovery ladder consumes (adopt →
  degraded → typed)."""
  fired = on('partition.owner', step=step)
  maybe_delay(fired)
  for f in fired:
    if f.action == 'kill':
      from ..parallel.failover import PartitionLostError
      p = int(f.partition or 0)
      raise PartitionLostError(
          f'injected partition.owner kill: partition {p} classified '
          f'dead at dispatch step {step}', partition=p)


def replica_faults(replica: str, op: str) -> List[Fault]:
  """Fleet-replica seam (`serving.router` handles), one arrival per
  ``submit`` / ``heartbeat``.  ``delay`` sleeps in place here (a slow
  replica — heartbeats stall, the router must classify it overloaded,
  not dead); ``kill`` and ``flap`` are returned for the HANDLE to
  apply (it owns the dead/flapping state the router then observes)."""
  fired = on('serving.replica', replica=replica, op=op)
  maybe_delay(fired)
  return fired


def aot_cache_faults(op: str) -> List[str]:
  """AOT-executable-cache seam (`serving.aot_cache`), ``op`` =
  ``'save'`` / ``'load'``.  ``fail`` raises `InjectedFault` (the
  caller absorbs it into a recompile); ``corrupt`` is returned so the
  writer scrambles the payload it is about to publish (the durable
  bad-entry scenario the checksum must catch on a later load)."""
  actions = [f.action for f in on('aot.cache', op=op)]
  if 'fail' in actions:
    raise InjectedFault(f'injected aot cache failure (op {op!r})')
  return actions


def ingest_wal_faults(op: str = 'append') -> List[str]:
  """WAL seam (`streaming.wal`), one arrival per append.  ``fail``
  raises `InjectedFault` BEFORE any byte is written (the log is
  unchanged — the caller's retry appends cleanly); ``truncate`` is
  returned so the WRITER lands a partial record and then raises (the
  kill-mid-append scenario the torn-tail recovery must absorb)."""
  actions = [f.action for f in on('ingest.wal', op=op)]
  if 'fail' in actions:
    raise InjectedFault(f'injected WAL append failure (op {op!r})')
  return actions


def ingest_apply_check(seqno: int = 0) -> None:
  """Delta-apply seam (`streaming.ingest`), fired between the durable
  WAL append and the in-memory commit: ``kill`` raises
  `ChaosKilledError` (the logged-but-unapplied crash the replay must
  make exactly-once), ``delay`` sleeps in place (lag grows)."""
  for f in on('ingest.apply', seqno=int(seqno)):
    if f.action == 'delay':
      time.sleep(f.secs)
    elif f.action == 'kill':
      raise ChaosKilledError(
          f'injected ingest apply kill (seqno {seqno})')


def ingest_compact_check(seqno: int = 0) -> None:
  """Compaction seam (`streaming.ingest.IngestPipeline.compact`),
  fired BEFORE the compacted-base snapshot publishes: ``kill`` raises
  `ChaosKilledError` mid-compaction — the previous snapshot plus the
  full WAL stay the durable truth."""
  for f in on('ingest.compact', seqno=int(seqno)):
    if f.action == 'kill':
      raise ChaosKilledError(
          f'injected ingest compaction kill (seqno {seqno})')


def scale_spawn_check(replica: str = '') -> None:
  """Elastic scale-out seam (`serving.autoscaler`), fired once per
  spawn attempt before the replica factory runs: ``delay`` sleeps in
  place (a slow provision), ``fail`` raises `InjectedFault`, ``kill``
  raises `ChaosKilledError` — both raises must surface as a typed
  rolled-back `scale.decision` that leaves the fleet unchanged and
  the cooldown unspent."""
  fired = on('scale.spawn', replica=replica or None)
  maybe_delay(fired)
  for f in fired:
    if f.action == 'fail':
      raise InjectedFault(
          f'injected scale.spawn provisioning failure '
          f'(replica {replica!r})')
    if f.action == 'kill':
      raise ChaosKilledError(
          f'injected scale.spawn kill (replica {replica!r})')


def handoff_transfer_check(seam: str, partition: int = 0) -> None:
  """Planned-handoff seam (`parallel.handoff`), fired once per phase
  with ``op`` = the seam name (snapshot/transfer/fence/cutover/drain)
  and ``partition`` = the moving range: ``delay`` sleeps in place (the
  source keeps serving — the handoff just takes longer), ``fail``
  raises `InjectedFault`, ``kill`` raises `ChaosKilledError`.  The
  caller's rollback ladder turns a pre-cutover raise into clean
  source retention and absorbs a post-cutover (drain) raise as a
  completed move — the single-owner invariant either way."""
  fired = on('handoff.transfer', op=seam, partition=int(partition))
  maybe_delay(fired)
  for f in fired:
    if f.action == 'fail':
      raise InjectedFault(
          f'injected handoff {seam} failure (partition {partition})')
    if f.action == 'kill':
      raise ChaosKilledError(
          f'injected handoff {seam} kill (partition {partition})')


def serving_request_check(op: str = '', replica: str = '') -> None:
  """Serving-plane seam (RPC handler: ``op='serve_infer'``; executor
  dispatch: ``op='dispatch'``): ``delay`` sleeps in place (driving
  deadline sheds behind it), ``drop`` raises `InjectedFault` (a typed
  server-side request loss — the replay cache still answers any
  transport retry of the same request id verbatim).  ``replica``
  carries the frontend's fleet name (when it has one), so a plan can
  stall ONE replica's dispatches — how the fleet bench backs its
  victim up with real in-flight requests before killing it."""
  for f in on('serving.request', op=op or None,
              replica=replica or None):
    if f.action == 'delay':
      time.sleep(f.secs)
    elif f.action == 'drop':
      raise InjectedFault(
          f'injected serving request drop (op {op!r})')
