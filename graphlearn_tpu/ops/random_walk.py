"""Uniform random walks over the device CSR.

Beyond-parity op: the reference reserves ``SamplingType.RANDOM_WALK``
(`sampler/base.py:325-331`) but never implements a walker; the
BASELINE north star names random-walk sampling as a first-class kernel
(DeepWalk/node2vec-style corpus generation).  TPU-native shape: one
`lax.scan` over walk steps, each step a fused (degree lookup, uniform
draw, neighbor gather) over the whole walk batch — static ``[B, L+1]``
output, INVALID_ID once a walk hits a dead end (matching the padding
convention everywhere else).

``restart_prob`` adds DeepWalk-with-restart semantics (walks jump back
to their start node with the given probability each step).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID


@functools.partial(jax.jit,
                   static_argnames=('walk_length', 'restart_prob'))
def random_walk(indptr: jax.Array, indices: jax.Array, starts: jax.Array,
                key: jax.Array, *, walk_length: int,
                restart_prob: float = 0.0) -> jax.Array:
  """``[B, walk_length + 1]`` node ids; column 0 = ``starts``.

  Invalid starts (< 0) and dead-end continuations emit INVALID_ID for
  the rest of the walk.  Each step draws uniformly from the current
  node's out-neighbors.
  """
  b = starts.shape[0]
  starts = starts.astype(jnp.int32)
  n = indptr.shape[0] - 1

  def step(cur, k):
    kk, kr = jax.random.split(k)
    valid = cur >= 0
    v = jnp.clip(cur, 0, n - 1)
    lo = indptr[v]
    deg = (indptr[v + 1] - lo).astype(jnp.int32)
    u = jax.random.randint(kk, (b,), 0, jnp.maximum(deg, 1))
    pos = jnp.clip(lo + u, 0, indices.shape[0] - 1)
    nxt = jnp.where(valid & (deg > 0), indices[pos].astype(jnp.int32),
                    INVALID_ID)
    if restart_prob > 0.0:
      jump = jax.random.uniform(kr, (b,)) < restart_prob
      nxt = jnp.where(jump & valid, starts, nxt)
    return nxt, nxt

  keys = jax.random.split(key, walk_length)
  _, path = jax.lax.scan(step, starts, keys)
  return jnp.concatenate([starts[None], path]).T


def walk_edges(walks: jax.Array, window: int = 1):
  """Skip-gram (src, dst) pairs from walks: every ordered pair within
  ``window`` hops on each walk — the corpus DeepWalk/node2vec trains
  on.  Returns ``(src, dst)`` of shape ``[B * L' ]`` with INVALID_ID
  where either endpoint is invalid."""
  b, l = walks.shape
  srcs, dsts = [], []
  for off in range(1, window + 1):
    srcs.append(walks[:, :l - off].reshape(-1))
    dsts.append(walks[:, off:].reshape(-1))
  src = jnp.concatenate(srcs)
  dst = jnp.concatenate(dsts)
  ok = (src >= 0) & (dst >= 0)
  return jnp.where(ok, src, INVALID_ID), jnp.where(ok, dst, INVALID_ID)
