"""Uniform random walks over the device CSR.

Beyond-parity op: the reference reserves ``SamplingType.RANDOM_WALK``
(`sampler/base.py:325-331`) but never implements a walker; the
BASELINE north star names random-walk sampling as a first-class kernel
(DeepWalk/node2vec-style corpus generation).  TPU-native shape: one
`lax.scan` over walk steps, each step a fused (degree lookup, uniform
draw, neighbor gather) over the whole walk batch — static ``[B, L+1]``
output, INVALID_ID once a walk hits a dead end (matching the padding
convention everywhere else).

``restart_prob`` adds DeepWalk-with-restart semantics (walks jump back
to their start node with the given probability each step).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID


@functools.partial(jax.jit,
                   static_argnames=('walk_length', 'restart_prob'))
def random_walk(indptr: jax.Array, indices: jax.Array, starts: jax.Array,
                key: jax.Array, *, walk_length: int,
                restart_prob: float = 0.0) -> jax.Array:
  """``[B, walk_length + 1]`` node ids; column 0 = ``starts``.

  Invalid starts (< 0) and dead-end continuations emit INVALID_ID for
  the rest of the walk.  Each step draws uniformly from the current
  node's out-neighbors.
  """
  b = starts.shape[0]
  starts = starts.astype(jnp.int32)
  n = indptr.shape[0] - 1
  if indices.shape[0] == 0:     # edgeless graph: keep gathers legal;
    indices = jnp.zeros((1,), indices.dtype)   # deg==0 masks every row

  def step(cur, k):
    kk, kr = jax.random.split(k)
    valid = cur >= 0
    v = jnp.clip(cur, 0, n - 1)
    lo = indptr[v]
    deg = (indptr[v + 1] - lo).astype(jnp.int32)
    u = jax.random.randint(kk, (b,), 0, jnp.maximum(deg, 1))
    pos = jnp.clip(lo + u, 0, indices.shape[0] - 1)
    nxt = jnp.where(valid & (deg > 0), indices[pos].astype(jnp.int32),
                    INVALID_ID)
    if restart_prob > 0.0:
      jump = jax.random.uniform(kr, (b,)) < restart_prob
      nxt = jnp.where(jump & valid, starts, nxt)
    return nxt, nxt

  keys = jax.random.split(key, walk_length)
  _, path = jax.lax.scan(step, starts, keys)
  return jnp.concatenate([starts[None], path]).T


@functools.partial(
    jax.jit, static_argnames=('walk_length', 'p', 'q', 'max_degree'))
def node2vec_walk(indptr: jax.Array, indices: jax.Array,
                  starts: jax.Array, key: jax.Array, *,
                  walk_length: int, p: float = 1.0, q: float = 1.0,
                  max_degree: int = 64) -> jax.Array:
  """Second-order (node2vec) biased walks, ``[B, walk_length + 1]``.

  Transition weights from ``cur`` given the previous node ``prev``:
  ``1/p`` back to ``prev``, ``1`` to common neighbors of ``prev``
  (distance 1), ``1/q`` otherwise (distance 2) — the node2vec
  search-bias scheme, computed per step over a static ``max_degree``
  candidate window with a Gumbel-max draw (no alias tables: the CSR
  binary search `edge_in_csr` answers the distance-1 test, so the
  whole walker stays allocation-free under jit).  Rows with more than
  ``max_degree`` out-edges draw from the first ``max_degree``
  candidates (choose >= the graph's max degree for exactness;
  ``CSRTopo.max_degree`` reports it).  Requires within-row-sorted
  columns (`CSRTopo` sorts).  The first step is uniform.
  """
  from .negative import edge_in_csr

  b = starts.shape[0]
  w = max(int(max_degree), 1)   # zero-size window would crash argmax;
                                # deg==0 rows are masked to INVALID
  starts = starts.astype(jnp.int32)
  n = indptr.shape[0] - 1
  if indices.shape[0] == 0:     # edgeless graph: keep gathers legal
    indices = jnp.zeros((1,), indices.dtype)
  num_edges = indices.shape[0]
  slot = jnp.arange(w, dtype=jnp.int32)

  def step(carry, k):
    cur, prev = carry
    valid = cur >= 0
    v = jnp.clip(cur, 0, n - 1)
    lo = indptr[v]
    deg = (indptr[v + 1] - lo).astype(jnp.int32)
    pos = jnp.clip(lo[:, None] + slot[None, :], 0, num_edges - 1)
    cand = indices[pos].astype(jnp.int32)            # [B, W]
    in_win = slot[None, :] < deg[:, None]
    prev_b = jnp.broadcast_to(prev[:, None], (b, w))
    is_back = cand == prev_b
    is_dist1 = edge_in_csr(indptr, indices,
                           jnp.where(prev_b >= 0, prev_b, 0
                                     ).reshape(-1),
                           cand.reshape(-1)).reshape(b, w)
    logw = jnp.where(
        is_back, -jnp.log(jnp.float32(p)),
        jnp.where(is_dist1, 0.0, -jnp.log(jnp.float32(q))))
    # first step (prev < 0) is uniform
    logw = jnp.where(prev[:, None] >= 0, logw, 0.0)
    g = -jnp.log(-jnp.log(
        jax.random.uniform(k, (b, w), minval=1e-20, maxval=1.0)))
    score = jnp.where(in_win, logw + g, -jnp.inf)
    pick = jnp.argmax(score, axis=1)
    nxt = jnp.where(valid & (deg > 0),
                    cand[jnp.arange(b), pick], INVALID_ID)
    return (nxt, cur), nxt

  keys = jax.random.split(key, walk_length)
  _, path = jax.lax.scan(
      step, (starts, jnp.full((b,), INVALID_ID, jnp.int32)), keys)
  return jnp.concatenate([starts[None], path]).T


def walk_edges(walks: jax.Array, window: int = 1):
  """Skip-gram (src, dst) pairs from walks: every ordered pair within
  ``window`` hops on each walk — the corpus DeepWalk/node2vec trains
  on.  Returns ``(src, dst)`` of shape ``[B * L' ]`` with INVALID_ID
  where either endpoint is invalid."""
  b, l = walks.shape
  srcs, dsts = [], []
  for off in range(1, window + 1):
    srcs.append(walks[:, :l - off].reshape(-1))
    dsts.append(walks[:, off:].reshape(-1))
  src = jnp.concatenate(srcs)
  dst = jnp.concatenate(dsts)
  ok = (src >= 0) & (dst >= 0)
  return jnp.where(ok, src, INVALID_ID), jnp.where(ok, dst, INVALID_ID)
