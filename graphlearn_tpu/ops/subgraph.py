"""Induced-subgraph extraction on device.

TPU-native replacement for the reference SubGraph op
(`csrc/cuda/subgraph_op.cu:38-124`, CPU twin `csrc/cpu/subgraph_op.cc`):
given a node set, emit all edges among those nodes with relabeled
endpoints.  The CUDA version builds a device hash table of the node set
and warp-scans each row; here membership is a sort + vectorized binary
search (no atomics) and each node contributes a static ``max_degree``
window of neighbor slots (capped, masked) instead of a ragged scan.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID


class SubGraphResult(NamedTuple):
  """Induced subgraph with static shapes.

  Attributes:
    nodes: ``[M]`` global node ids as given (padded with INVALID_ID).
    rows/cols: ``[M*D]`` local COO of induced edges (-1 when masked).
    eids: ``[M*D]`` global edge ids or None.
    edge_mask: ``[M*D]`` validity mask.
  """
  nodes: jax.Array
  rows: jax.Array
  cols: jax.Array
  eids: Optional[jax.Array]
  edge_mask: jax.Array


@functools.partial(
    jax.jit, static_argnames=('max_degree', 'with_edge_ids'))
def induced_subgraph(
    indptr: jax.Array,
    indices: jax.Array,
    nodes: jax.Array,
    *,
    max_degree: int,
    edge_ids: Optional[jax.Array] = None,
    with_edge_ids: bool = False,
) -> SubGraphResult:
  """Emit all edges among ``nodes`` (reference `SubGraphOp::NodeSubGraph`).

  Args:
    nodes: ``[M]`` unique global ids, INVALID_ID-padded.  Local index of
      ``nodes[i]`` is ``i`` (caller controls ordering, e.g. seeds first).
    max_degree: static per-node neighbor window; rows with more
      neighbors are truncated (choose >= graph max degree for exact
      results — `CSRTopo.max_degree` reports it).
  """
  num_edges = indices.shape[0]
  m = nodes.shape[0]
  d = max_degree

  valid_node = nodes >= 0
  n = jnp.where(valid_node, nodes, 0)
  start = indptr[n]
  deg = (indptr[n + 1] - start).astype(jnp.int32)
  deg = jnp.where(valid_node, deg, 0)

  wslot = jnp.arange(d, dtype=jnp.int32)
  in_deg = wslot[None, :] < deg[:, None]                 # [M, D]
  pos = jnp.clip(start[:, None] + wslot[None, :], 0, max(num_edges - 1, 0))
  win = jnp.where(in_deg, indices[pos].astype(jnp.int32), INVALID_ID)

  # Membership of each window neighbor in the node set: sort `nodes`
  # once, binary-search the window, map back to local ids via the sort
  # permutation (the no-atomics analog of the device hash table).
  big = jnp.iinfo(jnp.int32).max
  keyed = jnp.where(valid_node, n, big)
  order = jnp.argsort(keyed)
  sorted_nodes = keyed[order]
  loc = jnp.searchsorted(sorted_nodes, win.reshape(-1)).astype(jnp.int32)
  loc = jnp.clip(loc, 0, m - 1)
  hit = (sorted_nodes[loc] == win.reshape(-1)) & (win.reshape(-1) >= 0)
  col_local = jnp.where(hit, order[loc], INVALID_ID)     # [M*D]

  row_local = jnp.broadcast_to(
      jnp.arange(m, dtype=jnp.int32)[:, None], (m, d)).reshape(-1)
  edge_mask = hit & in_deg.reshape(-1)
  rows = jnp.where(edge_mask, row_local, INVALID_ID)
  cols = jnp.where(edge_mask, col_local, INVALID_ID)
  eids = None
  if with_edge_ids:
    flat_pos = pos.reshape(-1)
    if edge_ids is None:
      eids = jnp.where(edge_mask, flat_pos, INVALID_ID)
    else:
      eids = jnp.where(edge_mask, edge_ids[flat_pos], INVALID_ID)
  return SubGraphResult(nodes=nodes, rows=rows, cols=cols, eids=eids,
                        edge_mask=edge_mask)
