"""Pallas TPU gather kernel — the feature-store HBM row-gather primitive.

TPU-native counterpart of the reference's per-row warp gather
``GatherTensorKernel`` (`csrc/cuda/unified_tensor.cu:35-96`): on GPU one
32-lane warp copies one feature row from wherever it lives (HBM / peer
GPU / pinned host); on TPU the analog is a per-row **async DMA**
HBM→VMEM issued from a Pallas kernel, ``tile`` copies in flight per
grid step.  The table stays in HBM (``memory_space=ANY``), row ids are
scalar-prefetched into SMEM so the DMA addresses are known before the
body runs, and rows stream straight into the VMEM output block.

r5 ROOFLINE VERDICT (elision-proof protocol — AOT-compiled programs,
first-execution walls, value pulls; the earlier "~0.4 TB/s parity"
readings predate it and were tunnel artifacts): on v5e at
products-scale id sets (1M rows/call), the row gather is
DESCRIPTOR-BOUND at ~80-100M rows/s regardless of row width —
512 B rows: ~51 GB/s; 256 B (bf16): ~24 GB/s; 4 KB blocked rows:
~123 GB/s (30M rows/s); 16 KB: ~143 GB/s — while contiguous
streaming reads run 216-480 GB/s (day variance).  Consequences:
lane-padding D=100→128 and bf16 storage do NOT move the gather wall
(same rows/s), and THIS kernel's per-row DMA caps at ~26-33 GB/s
(tile 32→128 sweep; issue-cost-bound at ~15 ns/row).  A
streaming-select kernel (stream the covering range, extract wanted
rows in VMEM) is the only path past the bound, but Mosaic rejects
every extraction formulation tried: `jnp.take` on a VMEM block
(shape-mismatch on lowering), `take_along_axis` (internal compiler
error), per-row dynamic VMEM load/store in a fori_loop (internal
compiler error).  The XLA gather therefore stands at ~0.9-1.0 of the
measured achievable row rate, and `bench.py` reports
`gather_achieved_vs_achievable` against that basis.  The remote-chip
variant of the per-row DMA — owners pushing requested rows straight
into requester buffers via `make_async_remote_copy` — is implemented
and interpret-validated in `parallel/rdma_gather.py` (perf
qualification needs a >= 2-chip slice; the engines default to XLA
all_to_all).

Constraints discovered on real hardware (Mosaic tiling rules):
  * Row DMA slices must be lane-aligned: ``D % 128 == 0`` for f32/i32.
    Unaligned tables transparently fall back to the XLA gather (at
    parity perf, so no padding is forced on callers).
  * bf16 rows cannot be row-sliced at all (packed (16,128)(2,1)
    sublane tiling) — bf16 tables always take the XLA path.
  * 1-D arrays tile at 1024 elements, so *CSR neighbor-window* gathers
    at arbitrary ``indptr`` offsets are not DMA-able without a 4KB+
    aligned overfetch per seed.  MEASURED (r3, `ops/pallas_window.py`
    + `benchmarks/bench_pallas_window.py`, v5e, products-scale 61M-edge
    CSR, 8192 seeds x 128-wide windows, table repack hoisted out of
    the timed loop): the aligned-overfetch DMA kernel (two (8,128)
    units = 8 KB per seed, lane+sublane-rotate extraction, tile 16-32)
    reaches **~100-117 GB/s of useful window bytes** vs the XLA
    element gather's **~230-460 GB/s** across runs (tunnel-day
    variance) — XLA wins ~2.4-4x, consistent with the DMA path's
    16x inherent overfetch (8 KB moved per 512 B used) partially
    offset by its streaming efficiency.  The full `sample_one_hop`
    runs at ~430 M seeds/s (k=15) on the same input.  Sampling
    therefore stays on XLA as a measured decision, no longer a design
    assertion; a sub-4KB-aligned DMA primitive would be the thing to
    revisit.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.padding import round_up

# Rows gathered per grid step == async copies in flight.
_TILE = 32

#: Max ids the DMA kernel accepts: the id vector is SCALAR-PREFETCHED
#: into SMEM (1 MB on v5e), so ``4 * B`` bytes must fit with headroom
#: for the grid machinery — discovered the hard way at B=2^20 ids
#: ("Allocation (size=4194304) would exceed memory (size=1048576)",
#: space=smem).  Products-scale collation gathers ~938k ids, so ANY
#: lane-aligned table would have crashed here without this guard;
#: bigger gathers fall back to the XLA take (measured at parity for
#: large dense id sets anyway).
_MAX_DMA_IDS = 1 << 17


def pallas_enabled() -> bool:
  """Use the Pallas per-row DMA gather?  Default: NO since r5.

  The r5 elision-proof roofline (module docstring) put the per-row
  DMA at ~26-33 GB/s vs XLA's ~51 GB/s on the same sorted 1M-row
  pattern — the earlier "parity at 0.4 TB/s" reading that justified
  a TPU-on default was a tunnel timing artifact.  XLA is now the
  default everywhere; ``GLT_PALLAS=1`` opts the DMA kernel back in
  (on-TPU, or interpret-mode off-TPU for debugging).
  """
  return os.environ.get('GLT_PALLAS', '').strip().lower() in (
      '1', 'true', 'on', 'yes')


def _interpret_default() -> bool:
  return jax.default_backend() != 'tpu'


def _dma_supported(dtype) -> bool:
  """Row-sliceable dtypes: 32-bit (tiling (8,128), 1-row slices OK)."""
  return jnp.dtype(dtype).itemsize == 4


def gather_rows(table: jax.Array, idx: jax.Array, *,
                tile: int = _TILE,
                interpret: Optional[bool] = None) -> jax.Array:
  """Gather ``table[idx]`` rows via per-row async DMA.

  Callers use it unconditionally: it falls back to ``jnp.take`` when
  Pallas is disabled (:func:`pallas_enabled`), the table layout is
  not DMA-able (unaligned ``D``, sub-32-bit dtype), or the id vector
  exceeds the SMEM scalar-prefetch budget (`_MAX_DMA_IDS`).
  Out-of-range ids are clamped to the last row, matching
  ``jnp.take``'s TPU semantics.

  The env flag is re-read on every call (this plain wrapper dispatches
  to jitted implementations, so ``GLT_PALLAS=0`` works mid-process as
  the kill-switch it documents).

  Args:
    table: ``[N, D]`` HBM-resident array.
    idx: ``[B]`` int32 row ids (callers mask invalid rows after).
    tile: rows per grid step (DMAs in flight).
    interpret: force the kernel through the Pallas interpreter
      (tests); ``None`` = auto (off-TPU backends interpret).
  Returns:
    ``[B, D]`` gathered rows.
  """
  if interpret is None:
    if not pallas_enabled():
      return _xla_take(table, idx)
    interpret = _interpret_default()
  d = table.shape[1]
  if not interpret and (d % 128 != 0 or not _dma_supported(table.dtype)
                        or idx.shape[0] > _MAX_DMA_IDS):
    return _xla_take(table, idx)
  return _gather_rows_dma(table, idx, tile=tile, interpret=interpret)


@jax.jit
def _xla_take(table: jax.Array, idx: jax.Array) -> jax.Array:
  return jnp.take(table, idx.astype(jnp.int32), axis=0)


@functools.partial(jax.jit, static_argnames=('tile', 'interpret'))
def _gather_rows_dma(table: jax.Array, idx: jax.Array, *,
                     tile: int, interpret: bool) -> jax.Array:
  b = idx.shape[0]
  d = table.shape[1]
  bp = round_up(b, tile)
  idx_c = jnp.clip(idx.astype(jnp.int32), 0, table.shape[0] - 1)
  idx_p = jnp.zeros((bp,), jnp.int32).at[:b].set(idx_c)

  def kernel(idx_ref, table_ref, out_ref, sems):
    t = pl.program_id(0)
    for i in range(tile):
      r = idx_ref[t * tile + i]
      pltpu.make_async_copy(
          table_ref.at[r], out_ref.at[i], sems.at[i]).start()
    for i in range(tile):
      r = idx_ref[t * tile + i]
      pltpu.make_async_copy(
          table_ref.at[r], out_ref.at[i], sems.at[i]).wait()

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=1,
      grid=(bp // tile,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
      out_specs=pl.BlockSpec(
          (tile, d), lambda t, idx_ref: (t, 0), memory_space=pltpu.VMEM),
      scratch_shapes=[pltpu.SemaphoreType.DMA((tile,))],
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((bp, d), table.dtype),
      interpret=interpret,
  )(idx_p, table)
  return out[:b]
