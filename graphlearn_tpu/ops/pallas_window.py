"""Pallas CSR neighbor-window gather — the aligned-overfetch experiment.

The neighbor sampler's hot memory access is the ``[B, W]`` window
gather ``indices[indptr[seed] + 0..W)`` feeding Gumbel top-k
(`ops/neighbor.py` medium-degree path; the role of the reference's
reservoir read loop, `csrc/cuda/random_sampler.cu:58-108`).  XLA
lowers it to a general element gather.  Mosaic cannot DMA-slice a 1-D
array at arbitrary offsets, and HBM slices must respect the int32
(8, 128) tiling — so the DMA alternative is an ALIGNED OVERFETCH:
view ``indices`` as ``[R, 128]`` lanes, DMA the TWO 4 KB-aligned
(8, 128) units covering each seed's window into VMEM (8 KB per seed),
and cut the exact ``[w]`` slice with lane+sublane rotates (dynamic
slice does not lower in Mosaic; dynamic rotates do).

Measured on the real chip by ``benchmarks/bench_pallas_window.py``;
the verdict lives in `ops/pallas_gather.py`'s module notes.  The
sampler keeps whichever path that measurement favors.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: int32 HBM tiling unit: 8 sublanes x 128 lanes = 1024 elems = 4 KB.
UNIT = 1024
LANES = 128
SUBLANES = 8

_TILE = 16

#: max window width: a w <= 128 window spans <= 2 sublane rows, always
#: inside the two DMA'd units.
MAX_W = LANES


def prepare_window_table(indices: jax.Array) -> Tuple[jax.Array, int]:
  """One-time repack of a 1-D CSR column array into the ``[R, 128]``
  DMA-able layout (padded so the 2-unit window always fits).  Build it
  ONCE per graph: the repack touches all E elements and must never sit
  on the per-batch path (or in a kernel timing loop).
  Returns ``(ind2d, e)``."""
  e = indices.shape[0]
  rows = (-(-e // UNIT) + 2) * SUBLANES
  fill = indices[-1] if e else jnp.zeros((), indices.dtype)
  ind2d = jnp.concatenate(
      [indices, jnp.full((rows * LANES - e,), fill,
                         indices.dtype)]).reshape(rows, LANES)
  return ind2d, e


def csr_window_gather(indices: jax.Array, starts: jax.Array, w: int, *,
                      tile: int = _TILE,
                      interpret: Optional[bool] = None,
                      table: Optional[Tuple[jax.Array, int]] = None
                      ) -> jax.Array:
  """``out[i, j] = indices[starts[i] + j]`` for ``j < w`` via aligned
  unit DMA (positions past the array read the pad tail; callers mask
  by degree exactly like the XLA path).

  Args:
    indices: ``[E]`` int32 CSR column array.
    starts: ``[B]`` window start positions (``indptr[seeds]``).
    w: static window width, ``<= 128``.
    table: prebuilt `prepare_window_table` output — pass it on
      repeated calls so the O(E) repack is paid once per graph.
  """
  assert w <= MAX_W, (w, MAX_W)
  if interpret is None:
    interpret = jax.default_backend() != 'tpu'
  ind2d, e = table if table is not None else prepare_window_table(indices)
  starts = jnp.clip(starts.astype(jnp.int32), 0, max(e - 1, 0))
  return _window_dma(ind2d, starts, w=int(w), tile=int(tile),
                     interpret=bool(interpret))


@functools.partial(jax.jit, static_argnames=('w', 'tile', 'interpret'))
def _window_dma(ind2d: jax.Array, starts: jax.Array, *, w: int,
                tile: int, interpret: bool) -> jax.Array:
  b = starts.shape[0]
  bp = -(-b // tile) * tile
  starts_p = jnp.zeros((bp,), jnp.int32).at[:b].set(starts)
  unit_row = starts_p // UNIT * SUBLANES    # 8-aligned DMA start row
  offm = starts_p % UNIT                    # flat offset inside 2 units

  def kernel(row_ref, off_ref, tbl_ref, out_ref, scratch, sems):
    t = pl.program_id(0)
    for i in range(tile):
      r = row_ref[t * tile + i]
      pltpu.make_async_copy(tbl_ref.at[pl.ds(r, 2 * SUBLANES)],
                            scratch.at[i], sems.at[i]).start()
    for i in range(tile):
      r = row_ref[t * tile + i]
      pltpu.make_async_copy(tbl_ref.at[pl.ds(r, 2 * SUBLANES)],
                            scratch.at[i], sems.at[i]).wait()
      off = off_ref[t * tile + i]
      r0 = off // LANES
      c0 = off % LANES
      val = scratch[i]                       # [16, 128]
      rot = pltpu.roll(val, -c0, 1)          # lane rotate
      rot = pltpu.roll(rot, -r0, 0)          # sublane rotate
      # out[j] = val[r0 + (j >= 128 - c0), (c0 + j) % 128]
      lane = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
      take0 = lane < (LANES - c0)
      out_ref[pl.ds(i, 1), :] = jnp.where(take0, rot[0:1, :w],
                                          rot[1:2, :w])

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=2,
      grid=(bp // tile,),
      in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
      out_specs=pl.BlockSpec(
          (tile, w), lambda t, row_ref, off_ref: (t, 0),
          memory_space=pltpu.VMEM),
      scratch_shapes=[pltpu.VMEM((tile, 2 * SUBLANES, LANES),
                                 ind2d.dtype),
                      pltpu.SemaphoreType.DMA((tile,))],
  )
  out = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=jax.ShapeDtypeStruct((bp, w), ind2d.dtype),
      interpret=interpret,
  )(unit_row, offm, ind2d)
  return out[:b]


@functools.partial(jax.jit, static_argnames=('w',))
def xla_window_gather(indices: jax.Array, starts: jax.Array,
                      w: int) -> jax.Array:
  """The sampler's current window access, isolated for the bench."""
  e = indices.shape[0]
  pos = jnp.clip(starts[:, None].astype(jnp.int32)
                 + jnp.arange(w, dtype=jnp.int32)[None, :],
                 0, max(e - 1, 0))
  return indices[pos]
