"""Random negative edge sampling on device.

TPU-native replacement for the reference's curand negative sampler
(`csrc/cuda/random_negative_sampler.cu:37-120`, CPU twin
`csrc/cpu/random_negative_sampler.cc`).  The CUDA code draws (row, col)
pairs per thread, rejects existing edges via warp binary search in CSR,
retries up to ``trials_num`` times and compacts with thrust; here the
retry loop becomes a static ``[trials, R]`` batch of draws with a
vectorized branchless binary search, and compaction becomes a validity
mask (static shapes for XLA).

Requires within-row-sorted CSR columns (guaranteed by
`utils.topo.coo_to_csr`).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID


@jax.jit
def edge_in_csr(
    indptr: jax.Array,
    indices: jax.Array,
    rows: jax.Array,
    cols: jax.Array,
) -> jax.Array:
  """Vectorized membership test: is (rows[i], cols[i]) an edge?

  Counterpart of ``EdgeInCSR`` (`csrc/cuda/random_negative_sampler.cu:
  37-54`); the warp-cooperative binary search becomes a data-parallel
  fixed-depth (32-step) binary search over each row's sorted column
  slice.
  """
  num_edges = indices.shape[0]
  valid = rows >= 0
  r = jnp.where(valid, rows, 0)
  lo = indptr[r]
  hi = indptr[r + 1]
  hi0 = hi
  # ceil(log2(E+1)) static iterations; branchless lower_bound.  A slice
  # of length L needs bit_length(L) halvings to converge, and the
  # longest row can hold all E edges.
  for _ in range(max(num_edges, 1).bit_length()):
    active = lo < hi
    mid = (lo + hi) // 2
    v = indices[jnp.clip(mid, 0, max(num_edges - 1, 0))]
    go_right = v < cols
    lo = jnp.where(active & go_right, mid + 1, lo)
    hi = jnp.where(active & ~go_right, mid, hi)
  at = jnp.clip(lo, 0, max(num_edges - 1, 0))
  return valid & (lo < hi0) & (indices[at] == cols)


class NegativeSampleResult(NamedTuple):
  """``rows``/``cols``: ``[R]`` sampled pairs (INVALID_ID when masked);
  ``mask``: pair validity (always all-true when ``padding=True``)."""
  rows: jax.Array
  cols: jax.Array
  mask: jax.Array


@functools.partial(
    jax.jit,
    static_argnames=('req_num', 'trials', 'strict', 'padding', 'num_cols'))
def sample_negative(
    indptr: jax.Array,
    indices: jax.Array,
    req_num: int,
    key: jax.Array,
    *,
    trials: int = 5,
    strict: bool = True,
    padding: bool = True,
    num_cols: Optional[int] = None,
) -> NegativeSampleResult:
  """Draw ``req_num`` node pairs that are (in strict mode) non-edges.

  Mirrors the reference contract (`sampler/negative_sampler.py:21-51`):
  ``strict`` rejects existing edges with up to ``trials`` redraws per
  slot; ``padding`` falls back to the final (possibly invalid) draw so
  the output is always full.

  Args:
    num_cols: destination id space (bipartite graphs draw cols from
      the dst type's ``[0, num_cols)``); defaults to the row space.
  """
  num_nodes = indptr.shape[0] - 1
  kr, kc = jax.random.split(key)
  rows = jax.random.randint(kr, (trials, req_num), 0, num_nodes,
                            dtype=jnp.int32)
  cols = jax.random.randint(kc, (trials, req_num), 0,
                            num_cols if num_cols is not None else num_nodes,
                            dtype=jnp.int32)
  if not strict:
    return NegativeSampleResult(rows[0], cols[0],
                                jnp.ones((req_num,), bool))

  exists = edge_in_csr(indptr, indices, rows.reshape(-1),
                       cols.reshape(-1)).reshape(trials, req_num)
  ok = ~exists
  any_ok = jnp.any(ok, axis=0)
  first_ok = jnp.argmax(ok, axis=0)                  # first valid trial
  pick = jnp.where(any_ok, first_ok, trials - 1)     # padding fallback
  slot = jnp.arange(req_num)
  out_rows = rows[pick, slot]
  out_cols = cols[pick, slot]
  if padding:
    mask = jnp.ones((req_num,), bool)
  else:
    mask = any_ok
    out_rows = jnp.where(mask, out_rows, INVALID_ID)
    out_cols = jnp.where(mask, out_cols, INVALID_ID)
  return NegativeSampleResult(out_rows, out_cols, mask)
