"""Device ops: the TPU-native (XLA/Pallas) replacements for the
reference's C++/CUDA kernel layer (`graphlearn_torch/csrc/`)."""
from .neighbor import (OneHopResult, cal_nbr_prob, default_window,
                       lookup_degree, sample_one_hop)
from .gns import (DecayedSketch, bitmask_lookup, bits_table,
                  cached_set_bits, dedup_requester_bits,
                  fallback_req_index, gns_enabled, is_per_requester,
                  sample_one_hop_gns)
from .negative import NegativeSampleResult, edge_in_csr, sample_negative
from .pallas_gather import gather_rows, pallas_enabled
from .pallas_sample import (fused_sample_enabled, fused_sample_supported,
                            sample_one_hop_auto, sample_one_hop_fused)
from .pallas_delta import (DeltaMergeUnsupported, delta_merge_enabled,
                           merge_delta_csr_device)
from .random_walk import node2vec_walk, random_walk, walk_edges
from .subgraph import SubGraphResult, induced_subgraph
from .unique import (InducerState, UniqueResult, induce_next, init_node,
                     unique_stable)
