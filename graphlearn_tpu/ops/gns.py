"""Cache-aware Global Neighbor Sampling (GNS) — the sampler side of
the cold-tier story.

PR 5's honesty note measured the hard ceiling of pure cache-side
optimization: at ``split_ratio=0.3`` the in-degree sort already
hot-tiers the hubs, the residual cold traffic is near-uniform, and
``cache_hit_rate ≈ budget/universe`` (0.056) no matter the admission
policy.  Global Neighbor Sampling (PAPERS.md, arXiv 2106.06150) breaks
that ceiling from the *sampler* side: maintain an importance-sampled
set of frequently visited nodes, bias neighbor selection toward the
nodes the hardware can serve locally (HBM hot split ∪ the dynamic
cold-cache residents), and carry a per-edge ``1/q``
inclusion-probability correction so downstream aggregation stays
unbiased.  GNNSampler (arXiv 2108.11571) shows the same co-design —
the sampling algorithm shaped by what the memory hierarchy serves
cheaply — is where the real locality wins live.

Three pieces, shared by the mesh samplers, the cold cache and the
fused epoch drivers:

  * **`DecayedSketch`** — a fixed-size hashed visit-frequency sketch
    with exponential decay, maintained across batches.  It is the ONE
    notion of "hot" shared by cache admission (`data.cold_cache`
    ranks admission candidates by sketch score instead of the
    per-batch multiset) and the sampling bias (the cache residents it
    selects become members of the cached set below) — so the sampler
    and the cache agree on the working set instead of fighting over
    it.
  * **`cached_set_bits`** — a device-resident membership table over
    the global id space (bit-packed: 1 bit/node, so 100M nodes ride
    in 12.5 MB replicated), derived from the static hot split
    (``bounds`` + ``hot_counts``) ∪ the current `ClockShardCache`
    residents.  Refreshed only when the cache's ring actually changed
    (a version counter), never per step.
  * **`sample_one_hop_gns`** — the biased neighbor-selection kernel:
    a seeded, jit-compatible twin of `ops.neighbor.sample_one_hop`
    that samples cached neighbors with boosted probability and emits
    per-edge importance weights.  It composes with the same
    sort-based XLA machinery and the `plan_exchange` layouts (the
    weights ride the reply collective like the edge ids).

**Sampling distribution and the unbiasedness correction.**  Per seed
row with degree ``d`` (window ``W``, fanout ``k``):

  * ``d <= k`` — take all neighbors; weight 1 (the estimator is the
    exact neighbor mean, as in the uniform kernel).
  * ``k < d <= W`` — ``k`` INDEPENDENT draws from the boosted
    categorical ``q(v) ∝ 1 + boost·cached(v)`` over the gathered
    window (inverse-CDF over a cumulative-weight vector — no per-row
    control flow).  Each sampled edge carries
    ``w = p(v)/q(v) = (Σ_u w_u / d) / w_v`` so that the weighted
    masked mean ``Σ_j w_j f(v_j) / k`` is an unbiased estimator of
    the uniform neighbor mean for ANY membership mask and ANY boost —
    staleness of the cached set costs variance, never bias.
  * ``d > W`` — uniform with-replacement draws, weight 1 (unbiased
    as-is).  Deliberate: beyond-window rows are the extreme hubs the
    in-degree sort already hot-tiered, so the boost has nothing to
    win there and the window gather is the only cost.

Note the ``k < d <= W`` arm draws WITH replacement where the uniform
kernel's Gumbel top-k draws without: weighted without-replacement
inclusion probabilities have no closed form to correct by, and an
exact ``1/q`` beats an approximate one (GNS makes the same trade).
``GLT_GNS=0`` (the default) never reaches this module — the uniform
kernel runs untouched, byte-identical to HEAD.

Knobs: ``GLT_GNS`` (enable), ``GLT_GNS_BOOST`` (cached-neighbor
probability multiplier, default 16.0), ``GLT_GNS_DECAY`` (sketch
decay per update, default 0.95), ``GLT_GNS_SKETCH`` (sketch slots,
default 65536).
"""
from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.padding import INVALID_ID
from .neighbor import OneHopResult, default_window

GNS_ENV = 'GLT_GNS'
BOOST_ENV = 'GLT_GNS_BOOST'
DECAY_ENV = 'GLT_GNS_DECAY'
SKETCH_ENV = 'GLT_GNS_SKETCH'

#: default boost: a cached neighbor is 1 + boost = 17x as likely per
#: draw as an uncached one.  Tuned on the r05 tiered protocol (power-
#: law 50k graph, split 0.3, equal-HBM-budget cache): boost 8 -> 2.5x
#: the budget/universe hit-rate ceiling, 16 -> 3.5x, 32 -> 4.7x with
#: flat throughput — 16 clears the ISSUE-10 3x bar with margin while
#: keeping the importance weights O(d / (d + boost·n_cached)) bounded
#: for the corrected estimator.
DEFAULT_BOOST = 16.0

#: default sketch decay per update: ~20-batch memory half-life at one
#: update per batch, long enough to survive a shuffled epoch's gap
#: between repeats, short enough to track a drifting working set.
DEFAULT_DECAY = 0.95

#: default hashed-sketch slots (float32 scores -> 256 KB/shard).
DEFAULT_SKETCH_SLOTS = 1 << 16


def gns_enabled(spec=None) -> bool:
  """Resolve the GNS mode knob: an explicit kwarg wins, else
  ``GLT_GNS`` (off unless '1'/'true')."""
  if spec is not None:
    return bool(spec)
  return os.environ.get(GNS_ENV, '0').lower() in ('1', 'true')


def _env_float(env: str, default: float) -> float:
  try:
    return float(os.environ.get(env, default))
  except ValueError:
    return default


def resolve_boost(spec=None) -> float:
  if spec is not None:
    return float(spec)
  return _env_float(BOOST_ENV, DEFAULT_BOOST)


def resolve_decay(spec=None) -> float:
  if spec is not None:
    return float(spec)
  return min(max(_env_float(DECAY_ENV, DEFAULT_DECAY), 0.0), 1.0)


def resolve_sketch_slots(spec=None) -> int:
  if spec is not None:
    return max(int(spec), 1)
  try:
    return max(int(os.environ.get(SKETCH_ENV, DEFAULT_SKETCH_SLOTS)), 1)
  except ValueError:
    return DEFAULT_SKETCH_SLOTS


#: Fibonacci-hash multiplier (2^64 / phi): one wrapping multiply
#: decorrelates the slot assignment from the id structure — without
#: it, strided/structured id patterns alias systematically and a hot
#: id permanently inflates every ``id + k·slots`` alias.
_HASH_MULT = np.uint64(0x9E3779B97F4A7C15)


class DecayedSketch:
  """Hashed decayed visit-frequency sketch (host-side, bounded).

  ``scores[hash(id) % slots]`` approximates the exponentially-decayed
  visit count of ``id``; collisions over-score a few ids (count-min-
  style one-hash optimism), which costs an occasional wrong admission
  rank, never correctness.  Fixed memory regardless of graph size —
  the property that lets every `ClockShardCache` carry one without
  knowing its id universe.
  """

  def __init__(self, slots: Optional[int] = None,
               decay: Optional[float] = None, bounds=None):
    self.slots = resolve_sketch_slots(slots)
    self.decay = resolve_decay(decay)
    self.scores = np.zeros(self.slots, np.float32)
    # optional per-range attribution (ISSUE 16): with PartitionBook
    # bounds attached, every update also folds the batch into a
    # decayed per-RANGE visit histogram — exact (no hashing), P+1
    # floats — exported as the gns.range_hotness top-K gauges
    self.bounds = (None if bounds is None
                   else np.asarray(bounds, np.int64))
    self.range_mass = (None if bounds is None else
                       np.zeros(max(len(self.bounds) - 1, 1),
                                np.float32))

  def _slot(self, ids: np.ndarray) -> np.ndarray:
    mixed = ids.astype(np.uint64) * _HASH_MULT        # wraps mod 2^64
    return (mixed % np.uint64(self.slots)).astype(np.int64)

  def update(self, ids, counts=None) -> int:
    """Decay every score, then add this batch's visit multiplicities.
    Returns the number of valid ids folded in."""
    ids = np.asarray(ids, np.int64).reshape(-1)
    sel = ids >= 0
    ids = ids[sel]
    self.scores *= self.decay
    if self.range_mass is not None:
      self.range_mass *= self.decay
    if len(ids) == 0:
      return 0
    if counts is None:
      add = np.ones(len(ids), np.float32)
    else:
      add = np.asarray(counts, np.float32).reshape(-1)[sel]
    np.add.at(self.scores, self._slot(ids), add)
    if self.range_mass is not None:
      rng = np.clip(
          np.searchsorted(self.bounds, ids, side='right') - 1,
          0, len(self.range_mass) - 1)
      np.add.at(self.range_mass, rng, add)
    return len(ids)

  def hot_ranges(self, top_k: Optional[int] = None
                 ) -> List[Tuple[int, float]]:
    """``[(range_idx, share), ...]`` of the top-K ranges by decayed
    visit mass (share of total; empty when no bounds attached or no
    mass yet) — the hot-range table the locality-aware partitioner
    (ROADMAP item 4) ranks migration candidates from."""
    if self.range_mass is None:
      return []
    total = float(self.range_mass.sum())
    if total <= 0:
      return []
    p = len(self.range_mass)
    k = min(max(1, p // 4) if top_k is None else int(top_k), p)
    order = np.argsort(-self.range_mass, kind='stable')[:k]
    return [(int(r), float(self.range_mass[r] / total))
            for r in order]

  def score(self, ids) -> np.ndarray:
    ids = np.asarray(ids, np.int64).reshape(-1)
    out = self.scores[self._slot(np.clip(ids, 0, None))]
    return np.where(ids >= 0, out, 0.0).astype(np.float32)

  # -- DataPlaneState leaf (rides the owning ClockShardCache) -------------
  def state_dict(self) -> dict:
    out = {'scores': self.scores.copy(),
           'decay': np.float32(self.decay)}
    if self.range_mass is not None:
      out['range_mass'] = self.range_mass.copy()
    return out

  def load_state_dict(self, state: dict) -> None:
    scores = np.asarray(state['scores'], np.float32)
    if scores.shape[0] != self.slots:
      raise ValueError(
          f'visit-sketch snapshot has {scores.shape[0]} slots, this '
          f'sketch holds {self.slots}; resume with the same '
          f'{SKETCH_ENV} the snapshot was taken under')
    self.scores = scores.copy()
    self.decay = float(np.asarray(state['decay']))
    if self.range_mass is not None and 'range_mass' in state:
      rm = np.asarray(state['range_mass'], np.float32)
      if rm.shape == self.range_mass.shape:
        # older snapshots (or a repartitioned mesh) restart the range
        # histogram cold — residency/scores still restore
        self.range_mass = rm.copy()


def register_hotness_gauges(get_sketches, num_parts: int,
                            registry=None) -> list:
  """Register the ``gns.range_hotness{partition=p}`` fn-gauges: one
  per range, reading the decayed per-range visit mass aggregated over
  ``get_sketches()`` (a zero-arg callable — the cache's shard list).
  Only the top-K (``K = max(1, P // 4)``) hottest ranges sample a
  value at scrape time; the rest return None and drop, so /metrics
  carries exactly the hot-range table (bounded label cardinality:
  ``partition`` ranges over ``0..P-1``).  Returns the callbacks (for
  fn-guarded unregistration)."""
  if registry is None:
    from ..telemetry.live import live as registry

  def make(p: int):
    def read() -> Optional[float]:
      mass = None
      for sk in get_sketches():
        if sk.range_mass is None:
          continue
        mass = (sk.range_mass.copy() if mass is None
                else mass + sk.range_mass)
      if mass is None:
        return None
      total = float(mass.sum())
      if total <= 0:
        return None
      k = min(max(1, num_parts // 4), len(mass))
      hot = np.argsort(-mass, kind='stable')[:k]
      if p >= len(mass) or p not in hot:
        return None
      return round(float(mass[p] / total), 6)
    return read

  fns = []
  for p in range(int(num_parts)):
    fn = make(p)
    registry.gauge('gns.range_hotness', labels={'partition': str(p)},
                   fn=fn)
    fns.append(fn)
  return fns


def cached_set_bits(num_nodes: int, bounds: np.ndarray,
                    hot_counts: np.ndarray,
                    resident_ids: np.ndarray) -> np.ndarray:
  """Bit-packed membership table of the device-servable set: the
  static hot split (rows ``[bounds[p], bounds[p] + hot_counts[p])``
  per partition — the relabel sorts each partition hottest-first) ∪
  the current cold-cache residents.  ``uint8 [ceil(N/8)]``, little
  bit order (bit ``i`` of byte ``j`` = node ``8j + i``, matching
  `bitmask_lookup`)."""
  mask = np.zeros(int(num_nodes), bool)
  bounds = np.asarray(bounds, np.int64)
  hot_counts = np.asarray(hot_counts, np.int64)
  for p in range(len(hot_counts)):
    lo = int(bounds[p])
    mask[lo:lo + int(hot_counts[p])] = True
  res = np.asarray(resident_ids, np.int64).reshape(-1)
  res = res[(res >= 0) & (res < num_nodes)]
  mask[res] = True
  return np.packbits(mask, bitorder='little')


def set_resident_bits(base_bits: np.ndarray, resident_ids: np.ndarray,
                      num_nodes: int) -> np.ndarray:
  """OR resident membership into a copy of a (static) packed bitmask:
  O(bytes) copy + O(residents) scatter.  The refresh path caches the
  hot-split mask once (`cached_set_bits` with no residents) and pays
  only this per cache-version bump — the full O(num_nodes) bool
  rebuild would otherwise run on every admission wave, which in the
  near-uniform cold regime is nearly every batch."""
  bits = base_bits.copy()
  res = np.asarray(resident_ids, np.int64).reshape(-1)
  res = res[(res >= 0) & (res < num_nodes)]
  np.bitwise_or.at(bits, res >> 3,
                   (np.uint8(1) << (res & 7).astype(np.uint8)))
  return bits


def is_per_requester(bits) -> bool:
  """True when ``bits`` carries per-requester rows (the deduped
  ``(table, row_index)`` tuple or the legacy replicated 2-D stack)
  and therefore needs ``req`` at lookup time."""
  if isinstance(bits, tuple):
    return True
  return getattr(bits, 'ndim', 1) == 2


def fallback_req_index(bits) -> int:
  """The requester index whose mask is the conservative hot-split-
  only fallback (unattributable recv rows map here) — the LAST
  logical requester row under both bitmask encodings."""
  if isinstance(bits, tuple):
    return int(bits[1].shape[0] - 1)
  return int(bits.shape[0] - 1)


def bits_table(bits) -> jax.Array:
  """The physical ``[T, nbytes]`` byte table behind any bitmask
  encoding: the dedup tuple's table, a replicated 2-D stack as-is, a
  1-D shared mask viewed as one row.  (The Pallas fused kernel DMAs
  this block into VMEM whole — dedup is what keeps T at O(distinct
  caches) instead of O(P).)"""
  if isinstance(bits, tuple):
    return bits[0]
  if getattr(bits, 'ndim', 1) == 2:
    return bits
  return bits.reshape(1, -1)


def bitmask_lookup(bits, ids: jax.Array,
                   req: Optional[jax.Array] = None) -> jax.Array:
  """``[...]`` int ids -> uint8 membership (0/1); invalid ids (< 0)
  read 0.  Pure gathers + shifts — jit/vmap/shard_map friendly.

  ``bits`` may be 1-D (one shared mask), 2-D ``[R, nbytes]``
  per-requester masks (ISSUE 15), or the deduped ``(table
  [T, nbytes], row_index [R])`` tuple (ISSUE 18: T distinct mask
  CONTENTS, one small int row per requester — the P-fold replication
  collapses to O(distinct caches) bytes).  For the per-requester
  forms ``req`` (``[B]``, broadcast over the trailing dims of
  ``ids``) selects the mask per leading entry — each request is
  judged by what ITS requester serves locally, never by another
  device's cache ring."""
  valid = ids >= 0
  idc = jnp.where(valid, ids, 0).astype(jnp.int32)
  if is_per_requester(bits):
    if req is None:
      raise ValueError('per-requester bitmask (2-D bits) needs req')
    if isinstance(bits, tuple):
      table, row_index = bits
      row = jnp.clip(req, 0, row_index.shape[0] - 1).astype(jnp.int32)
      row = row_index[row].astype(jnp.int32)
    else:
      table = bits
      row = jnp.clip(req, 0, table.shape[0] - 1).astype(jnp.int32)
    row = row.reshape(row.shape + (1,) * (ids.ndim - row.ndim))
    byte = table[row, jnp.clip(idc >> 3, 0, table.shape[1] - 1)]
  else:
    byte = bits[jnp.clip(idc >> 3, 0, bits.shape[0] - 1)]
  bit = (byte >> (idc & 7).astype(jnp.uint8)) & jnp.uint8(1)
  return jnp.where(valid, bit, jnp.uint8(0))


def per_requester_bits(num_nodes: int, bounds: np.ndarray,
                       hot_counts: np.ndarray,
                       residents_by_device,
                       base_bits: Optional[np.ndarray] = None
                       ) -> np.ndarray:
  """``[R + 1, ceil(N/8)]`` stacked per-requester cached-set masks
  from `PartitionBook` placement (ISSUE 15): row ``d`` = static hot
  split ∪ device ``d``'s OWN cold-cache residents; the LAST row is
  the hot-split-only fallback (used for recv rows whose requester the
  exchange layout cannot attribute — conservative: a remote-only-
  resident row gets no boost, never an over-boost).

  ``residents_by_device`` maps device index -> resident-id array;
  devices absent from the map (e.g. other hosts' shards) get the
  fallback row — their residency is unknown, so no boost.
  ``base_bits`` lets a caller reuse an already-packed hot-split mask
  (the `_gns_hot_bits` cache) instead of repacking O(num_nodes)."""
  base = (base_bits if base_bits is not None
          else cached_set_bits(num_nodes, bounds, hot_counts,
                               np.empty(0, np.int64)))
  rows = []
  for d in range(len(hot_counts)):
    res = residents_by_device.get(d)
    if res is None or len(res) == 0:
      rows.append(base)
    else:
      rows.append(set_resident_bits(base, res, num_nodes))
  rows.append(base)
  return np.stack(rows)


def dedup_requester_bits(num_nodes: int, bounds: np.ndarray,
                         hot_counts: np.ndarray,
                         residents_by_device,
                         base_bits: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, np.ndarray]:
  """Deduped encoding of `per_requester_bits` (the PR 15 deferred
  item): ``(table [T, ceil(N/8)], row_index [R + 1])`` where
  ``row_index[r]`` names the table row holding requester ``r``'s
  mask and row ``row_index[-1]`` is the hot-split-only fallback.

  `per_requester_bits` replicates the base mask P+1 times even though
  most hosts contribute NO residents (other hosts' shards, cold
  start, single-shard meshes) — at 100M nodes and P=64 that is
  812 MB of identical bytes on every device.  Here the table holds
  each DISTINCT mask content once: row 0 is always the shared base;
  devices with residents get their own row; devices without (and the
  fallback) all point at row 0.  ``T <= 1 + #devices-with-residents``
  — the equivalence `bitmask_lookup(dedup) == bitmask_lookup(
  replicated)` and the T << R+1 memory drop are pinned in
  tests/test_pallas_sample.py."""
  base = (base_bits if base_bits is not None
          else cached_set_bits(num_nodes, bounds, hot_counts,
                               np.empty(0, np.int64)))
  rows = [base]
  row_index = np.zeros(len(hot_counts) + 1, np.int32)
  for d in range(len(hot_counts)):
    res = residents_by_device.get(d)
    if res is None or len(res) == 0:
      continue                     # shares row 0 (the base mask)
    row_index[d] = len(rows)
    rows.append(set_resident_bits(base, res, num_nodes))
  return np.stack(rows), row_index


@functools.partial(
    jax.jit, static_argnames=('k', 'boost', 'window', 'with_edge_ids',
                              'sort_locality'))
def sample_one_hop_gns(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    bits: jax.Array,
    boost: float,
    edge_ids: Optional[jax.Array] = None,
    *,
    req: Optional[jax.Array] = None,
    window: Optional[int] = None,
    with_edge_ids: bool = False,
    sort_locality: bool = True,
) -> OneHopResult:
  """Biased one-hop sampling with importance-weight correction.

  Same contract as `ops.neighbor.sample_one_hop` plus:

  Args:
    bits: bit-packed cached-set membership (`cached_set_bits`),
      indexed by GLOBAL neighbor id — or the 2-D per-requester stack
      (`per_requester_bits`), in which case ``req`` must give each
      seed row's requester index (ISSUE 15: boost only what THAT
      requester serves locally).
    boost: additive preference weight — a cached neighbor's draw
      weight is ``1 + boost`` vs 1 (static: part of the compile key).
    req: ``[B]`` requester index per seed row (2-D ``bits`` only).

  Returns an `OneHopResult` whose ``weights`` field (``[B, k]``
  float32) carries the per-edge ``p/q`` correction: the weighted
  masked mean ``sum(w·f·mask)/sum(mask)`` over each row's slots is an
  unbiased estimator of the row's uniform neighbor mean (module
  docstring).  Masked slots carry weight 0.
  """
  if sort_locality and seeds.shape[0] > 1:
    big = jnp.iinfo(seeds.dtype).max
    order = jnp.argsort(jnp.where(seeds >= 0, seeds, big))
    res = sample_one_hop_gns(indptr, indices, seeds[order], k, key,
                             bits, boost, edge_ids,
                             req=(req[order] if req is not None
                                  else None),
                             window=window,
                             with_edge_ids=with_edge_ids,
                             sort_locality=False)
    inv = jnp.argsort(order)
    return OneHopResult(
        nbrs=res.nbrs[inv], mask=res.mask[inv],
        eids=res.eids[inv] if res.eids is not None else None,
        weights=res.weights[inv])
  num_edges = indices.shape[0]
  b = seeds.shape[0]
  slot = jnp.arange(k, dtype=jnp.int32)

  valid_seed = seeds >= 0
  s = jnp.where(valid_seed, seeds, 0)
  start = indptr[s]
  deg = (indptr[s + 1] - start).astype(jnp.int32)
  deg = jnp.where(valid_seed, deg, 0)

  mask = slot[None, :] < jnp.minimum(deg, k)[:, None]

  k_rand, k_win = jax.random.split(key)
  # with-replacement uniform draws: the deg > W arm (weight 1)
  u = jax.random.uniform(k_rand, (b, k))
  rand_off = jnp.minimum((u * deg[:, None]).astype(jnp.int32),
                         jnp.maximum(deg - 1, 0)[:, None])

  # the boosted-categorical arm (k < deg <= W): gather the window,
  # read membership bits, inverse-CDF draw against the cumulative
  # boosted weights
  w = window if window is not None else default_window(k)
  wslot = jnp.arange(w, dtype=jnp.int32)
  in_deg = wslot[None, :] < deg[:, None]                  # [B, W]
  win_pos = jnp.clip(start[:, None] + wslot[None, :], 0,
                     max(num_edges - 1, 0))
  win_ids = indices[win_pos].astype(jnp.int32)            # [B, W]
  cached = bitmask_lookup(bits, jnp.where(in_deg, win_ids, -1),
                          req=req)
  wgt = jnp.where(in_deg,
                  1.0 + jnp.float32(boost) * cached.astype(jnp.float32),
                  0.0)                                    # [B, W]
  cum = jnp.cumsum(wgt, axis=1)                           # [B, W]
  total = cum[:, -1]                                      # = d + boost·n_c
  draws = jax.random.uniform(k_win, (b, k)) \
      * jnp.maximum(total, 1e-9)[:, None]
  biased_off = jax.vmap(
      lambda c, d: jnp.searchsorted(c, d, side='right'))(cum, draws)
  biased_off = jnp.minimum(biased_off.astype(jnp.int32),
                           jnp.maximum(deg - 1, 0)[:, None])
  # p/q = (total / deg) / w(v): weight of the drawn slot
  w_drawn = jnp.take_along_axis(wgt, biased_off, axis=1)
  iw = (total[:, None] / jnp.maximum(deg, 1)[:, None]) \
      / jnp.maximum(w_drawn, 1e-9)

  take_all = (deg <= k)[:, None]
  medium = ((deg > k) & (deg <= w))[:, None]
  off = jnp.where(take_all, slot[None, :],
                  jnp.where(medium, biased_off, rand_off))
  weights = jnp.where(mask,
                      jnp.where(medium, iw, 1.0).astype(jnp.float32),
                      0.0)

  pos = jnp.clip(start[:, None] + off, 0, max(num_edges - 1, 0))
  nbrs = jnp.where(mask, indices[pos].astype(jnp.int32), INVALID_ID)
  eids = None
  if with_edge_ids:
    if edge_ids is None:
      eids = jnp.where(mask, pos, INVALID_ID)
    else:
      eids = jnp.where(mask, edge_ids[pos], INVALID_ID)
  return OneHopResult(nbrs=nbrs, mask=mask, eids=eids, weights=weights)
