"""Capacity-bounded, order-preserving unique & relabel.

TPU-native replacement for the reference's GPU hash-table "inducer"
(`csrc/cuda/inducer.cu:94-141`, `csrc/cuda/hash_table.cu`,
`include/hash_table.cuh:24-150`): the CUDA code deduplicates node ids
and assigns local indices with atomicCAS open addressing.  TPUs have no
device-atomics idiom, so we use a sort-based unique instead — fully
static shapes, no data-dependent sizes, jit/vmap/shard_map friendly.

Semantics match the inducer contract: the *first occurrence order* of
ids is preserved (seeds keep local indices ``0..B-1``, newly discovered
nodes are appended in arrival order), which PyG-style batches rely on.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID


class UniqueResult(NamedTuple):
  """Result of a capacity-bounded unique.

  Attributes:
    values: ``[capacity]`` unique ids in first-occurrence order, padded
      with ``fill_value``.
    inverse: ``[n]`` local index of each input element in ``values``
      (-1 for invalid/padded inputs or overflow past capacity).
    count: scalar — number of valid unique ids (clamped to capacity).
  """
  values: jax.Array
  inverse: jax.Array
  count: jax.Array


@functools.partial(jax.jit, static_argnames=('capacity', 'fill_value'))
def unique_stable(
    x: jax.Array,
    capacity: int,
    fill_value: int = INVALID_ID,
    valid: Optional[jax.Array] = None,
) -> UniqueResult:
  """Order-preserving unique with a static output capacity.

  Algorithm (all O(n log n), static shapes):
    1. stable-sort ids (invalid ids mapped to a +inf sentinel) — within
       an equal-value segment the original positions stay ascending, so
       each segment HEAD already sits at its value's first occurrence
       (no segment-min scatters needed; they were the two hottest ops
       of the multihop program on v5e),
    2. rank segments in appearance order by sorting the heads' original
       positions,
    3. recover each element's appearance rank scatter-free: a running
       max propagates the segment head's sorted position, and argsort
       inverts the rank and sort permutations (TPU scatters measured
       ~3.5x the cost of sorts here).
  """
  n = x.shape[0]
  if n == 0:
    return UniqueResult(
        values=jnp.full((capacity,), fill_value, x.dtype),
        inverse=jnp.zeros((0,), jnp.int32),
        count=jnp.zeros((), jnp.int32))
  if valid is None:
    valid = x != fill_value
  else:
    valid = valid & (x != fill_value)
  big = jnp.iinfo(x.dtype).max
  xv = jnp.where(valid, x, big)

  order = jnp.argsort(xv, stable=True)          # positions sorted by value
  xs = xv[order]
  head = jnp.concatenate([jnp.ones((1,), bool), xs[1:] != xs[:-1]])
  head = head & (xs != big)
  # unique id (in sorted order) of each sorted element; invalids -> n.
  # Up to n distinct segments exist; overflow past `capacity` must drop
  # the *latest-appearing* ids (preserving earlier local indices), so
  # ranking happens over all n segments before truncation.
  uid = jnp.where(xs != big, jnp.cumsum(head) - 1, n)

  count = jnp.minimum(jnp.sum(head), capacity)

  # appearance order: stable sort -> the head of each segment carries
  # that value's first original position; sorting those positions gives
  # the appearance ranking directly.  Non-heads sink to the tail.
  first_pos = jnp.where(head, order, jnp.iinfo(jnp.int32).max)
  rank_to_sorted = jnp.argsort(first_pos)       # appearance rank -> sorted pos
  vals_by_rank = xs[rank_to_sorted]             # [n] value of rank j
  slot = jnp.arange(capacity)
  values = jnp.where(slot < count,
                     vals_by_rank[jnp.clip(slot, 0, n - 1)].astype(x.dtype),
                     fill_value)

  # Each element's appearance rank, scatter-free (TPU scatters measured
  # ~3.5x the cost of sorts in this program): a running max over the
  # sorted order gives every element its segment head's sorted
  # position (heads come first within a segment), and inverting the
  # rank permutation with argsort maps that head position to its rank.
  head_pos = jax.lax.cummax(
      jnp.where(head, jnp.arange(n, dtype=jnp.int32), -1))
  sorted_to_rank = jnp.argsort(rank_to_sorted)  # sorted pos -> rank
  inv_sorted = jnp.where(
      (uid < n) & (head_pos >= 0),
      sorted_to_rank[jnp.clip(head_pos, 0, n - 1)], -1)
  inv_sorted = jnp.where(inv_sorted < capacity, inv_sorted, -1)
  # inverse permutation of `order`, again via argsort instead of scatter
  inverse = inv_sorted[jnp.argsort(order)]
  return UniqueResult(values=values, inverse=inverse, count=count)


class InducerState(NamedTuple):
  """Functional inducer state: the node table accumulated across hops.

  Attributes:
    nodes: ``[capacity]`` global node ids in insertion order (padded).
    count: scalar number of valid entries.
  """
  nodes: jax.Array
  count: jax.Array


def init_node(seeds: jax.Array, capacity: int) -> Tuple[InducerState,
                                                        jax.Array]:
  """Seed the inducer table; counterpart of ``InitNode``
  (`csrc/cuda/inducer.cu:74`).  Seeds are deduplicated preserving order
  (reference seeds are assumed unique per batch; we dedup defensively).

  Returns the state and the seeds' local indices.
  """
  res = unique_stable(seeds, capacity)
  return InducerState(nodes=res.values, count=res.count), res.inverse


def induce_next(
    state: InducerState,
    src_local: jax.Array,
    nbrs: jax.Array,
    nbr_mask: jax.Array,
) -> Tuple[InducerState, jax.Array, jax.Array, jax.Array]:
  """Insert newly sampled neighbors; counterpart of ``InduceNext``
  (`csrc/cuda/inducer.cu:94-141`).

  Args:
    state: current node table.
    src_local: ``[B]`` local indices of the source nodes (-1 invalid).
    nbrs: ``[B, k]`` sampled neighbor global ids (-1 invalid).
    nbr_mask: ``[B, k]`` validity of each sampled neighbor.

  Returns:
    ``(new_state, rows, cols, frontier_start)`` where ``rows``/``cols``
    are the ``[B*k]`` local COO of the sampled edges — ``rows`` is the
    *neighbor* local index and ``cols`` the *source* local index,
    matching the reference's transposed emission for PyG message
    passing (`sampler/neighbor_sampler.py:159-166`) — and
    ``frontier_start`` is the previous node count (new frontier =
    ``state.nodes[frontier_start:new_count]``).
  """
  capacity = state.nodes.shape[0]
  b, k = nbrs.shape
  flat_nbrs = nbrs.reshape(-1)
  flat_mask = nbr_mask.reshape(-1)

  # Combined table: existing nodes first (so their indices are stable),
  # then the new candidates in arrival order.
  combined = jnp.concatenate([state.nodes, flat_nbrs])
  valid = jnp.concatenate(
      [jnp.arange(capacity) < state.count, flat_mask])
  res = unique_stable(combined, capacity, valid=valid)

  new_state = InducerState(nodes=res.values, count=res.count)
  nbr_local = res.inverse[capacity:]            # [B*k]
  src_flat = jnp.broadcast_to(src_local[:, None], (b, k)).reshape(-1)
  edge_valid = flat_mask & (src_flat >= 0) & (nbr_local >= 0)
  rows = jnp.where(edge_valid, nbr_local, -1)
  cols = jnp.where(edge_valid, src_flat, -1)
  return new_state, rows, cols, state.count
