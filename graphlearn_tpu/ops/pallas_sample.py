"""Pallas fused one-kernel sample+compact — the r19 sampling tentpole.

`ops/neighbor.py::sample_one_hop`'s medium-degree arm (``k < deg <=
W``) materializes a ``[B, W]`` gathered window, a ``[B, W]`` Gumbel
tensor and a full ``top_k`` sort network per hop; the GNS twin
(`ops/gns.py`) adds a ``[B, W]`` membership gather, a ``[B, W]``
cumulative-weight vector and a vmapped ``searchsorted`` on top.  In
the r5 FusedEpoch profile those intermediates are the bulk of the
~104 ms/step sort-based sampling cost.  This module fuses the whole
medium arm into ONE Pallas kernel:

  * the seed's CSR window arrives by aligned-overfetch DMA
    (`pallas_window.py` layout: two 4 KB units per seed, lane+sublane
    rotates cut the exact ``[w]`` slice — never a general gather);
  * the draw happens IN REGISTERS against the VMEM window — Gumbel
    rank-select for the uniform kernel, the GNS inverse-CDF biased
    draw ``q(v) ∝ 1 + boost·cached(v)`` with the per-requester
    bitmask lookup (the dedup table of `ops.gns.dedup_requester_bits`)
    read straight from a VMEM-resident bits block for the biased one;
  * compacted neighbor values, window offsets and (GNS) ``1/q``
    importance weights stream out in one pass — the ``[B, W]``
    window, sort and cumsum intermediates never reach HBM.

**Value parity is exact, not approximate.**  All randomness is drawn
OUTSIDE the kernel with the identical `jax.random` key discipline the
XLA kernels use (``k_rand, k_win = split(key)``; same shapes, same
order), so the fused kernel consumes the very same uniforms/Gumbels
and reproduces the XLA outputs bit-for-bit:

  * Gumbel top-k is computed as a rank-select (count of strictly
    greater entries with index tie-break) — the same total order
    `jax.lax.top_k` sorts by;
  * the inverse-CDF draw counts ``cum <= draw`` — exactly
    ``searchsorted(side='right')`` on a sorted vector;
  * the ``deg > W`` with-replacement arm and the ``deg <= k``
    take-all arm are selected from the same precomputed offsets the
    XLA path uses (the beyond-window gather stays an XLA gather: it
    is O(B·k), not O(B·W), and keeps the kernel's DMA footprint at
    two units per seed).

`tests/test_pallas_sample.py` pins nbrs/mask/eids/weights equality
against `sample_one_hop` / `sample_one_hop_gns` in interpret mode on
CPU tier-1 for every arm.

**Dispatch discipline** (the `pallas_gather.py` precedent): default
OFF; ``GLT_PALLAS_SAMPLE`` is re-read at every dispatch (kill
switch), `sample_one_hop_auto` falls back to the XLA kernels —
transparently and at value parity — whenever the shape, dtype or
backend disqualifies the kernel, and emits ``pallas.dispatch`` /
``pallas.fallback`` events at trace time so the chosen path is
visible in traces without taxing the steady state.

**Roofline note (r19).**  The medium arm moves ``8 KB`` of window
DMA + ``k`` compacted outputs per seed where the XLA path moves the
``[B, W]`` window plus the sort's O(W log W) compare network through
HBM; at the bench shapes (B=4096, k=8, W=64) that is ~6x less HBM
traffic on the draw path.  Like r5's window verdict, the win must be
re-measured on real hardware (`benchmarks/bench_pallas_sample.py`);
CPU tier-1 only pins correctness.  The beyond-window hub arm and the
O(E) `prepare_window_table` repack stay outside the kernel — pass a
prebuilt ``table`` on repeated calls (the `NeighborSampler` caches
one per graph version) or the repack lands on the per-call path.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.padding import INVALID_ID
from .neighbor import OneHopResult, default_window, sample_one_hop
from .gns import (bits_table, is_per_requester, resolve_boost,
                  sample_one_hop_gns)
from .pallas_window import (LANES, MAX_W, SUBLANES, UNIT, _TILE,
                            prepare_window_table)

SAMPLE_ENV = 'GLT_PALLAS_SAMPLE'

#: scalar-prefetch budget — same bound as `pallas_gather._MAX_DMA_IDS`.
_MAX_IDS = 1 << 17

#: VMEM budget for the replicated per-requester bits block (the dedup
#: table keeps this at O(distinct caches), not O(P)).
_MAX_BITS_BYTES = 4 << 20


def fused_sample_enabled() -> bool:
  """Re-read ``GLT_PALLAS_SAMPLE`` on every dispatch (kill switch —
  the `pallas_gather.pallas_enabled` discipline)."""
  return os.environ.get(SAMPLE_ENV, '').strip().lower() in (
      '1', 'true', 'on', 'yes')


def _interpret_default() -> bool:
  return jax.default_backend() != 'tpu'


def fused_sample_supported(b: int, k: int, window: Optional[int],
                           indices_dtype,
                           bits=None,
                           replace: bool = False,
                           num_edges: Optional[int] = None
                           ) -> Optional[str]:
  """None when the fused kernel can run this shape; else the
  fallback-reason string (stamped into the ``pallas.fallback``
  event)."""
  w = window if window is not None else default_window(k)
  if replace:
    return 'replace-arm'          # no window arm to fuse
  if b < 1 or k < 1 or num_edges == 0:
    return 'empty'
  if k > w:
    return 'k>window'
  if w > MAX_W:
    return f'window>{MAX_W}'      # two-unit overfetch no longer covers
  if b > _MAX_IDS:
    return 'batch>smem-budget'
  if jnp.dtype(indices_dtype) != jnp.int32:
    return 'indices-dtype'
  if bits is not None:
    tbl = bits_table(bits)
    if int(tbl.shape[0]) * int(tbl.shape[1]) > _MAX_BITS_BYTES:
      return 'bits>vmem-budget'
  return None


def _emit(kind: str, **fields) -> None:
  from ..telemetry.recorder import recorder
  if recorder.enabled:
    recorder.emit(kind, **fields)


def _make_kernel(*, k: int, w: int, tile: int, boost: float,
                 gns: bool, nbytes: int):
  """Kernel factory.  Scalar-prefetch refs: per-seed DMA row, intra-
  unit offset, degree, (GNS) bits-table row.  Tensor inputs: the
  ``[R, 128]`` window table (ANY -> manual DMA), the precomputed
  draws, the with-replacement offsets, the beyond-window values and
  (GNS) the bits table block."""

  def kernel(row_ref, off_ref, deg_ref, *rest):
    if gns:
      (req_ref, tbl_ref, draw_ref, rand_ref, large_ref, bits_ref,
       val_ref, out_off_ref, iw_ref, scratch, sems) = rest
    else:
      (tbl_ref, draw_ref, rand_ref, large_ref,
       val_ref, out_off_ref, scratch, sems) = rest
    t = pl.program_id(0)
    for i in range(tile):
      r = row_ref[t * tile + i]
      pltpu.make_async_copy(tbl_ref.at[pl.ds(r, 2 * SUBLANES)],
                            scratch.at[i], sems.at[i]).start()
    for i in range(tile):
      g = t * tile + i
      r = row_ref[g]
      pltpu.make_async_copy(tbl_ref.at[pl.ds(r, 2 * SUBLANES)],
                            scratch.at[i], sems.at[i]).wait()
      off = off_ref[g]
      r0 = off // LANES
      c0 = off % LANES
      val = scratch[i]                       # [16, 128]
      rot = pltpu.roll(val, -c0, 1)
      rot = pltpu.roll(rot, -r0, 0)
      lane = jax.lax.broadcasted_iota(jnp.int32, (1, w), 1)
      take0 = lane < (LANES - c0)
      win = jnp.where(take0, rot[0:1, :w], rot[1:2, :w])   # [1, w]

      deg_i = deg_ref[g]
      in_deg = lane < deg_i                                # [1, w]
      slot_k = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)
      ee = jax.lax.broadcasted_iota(jnp.int32, (k, w), 1)

      if gns:
        # membership bits for the window ids, straight from VMEM —
        # identical math to `bitmask_lookup` (invalid slots read
        # byte 0 and are zeroed by in_deg, exactly like the XLA
        # path's where(in_deg, win_ids, -1) masking)
        ids = jnp.where(in_deg, win, 0)
        rowv = jax.lax.dynamic_index_in_dim(
            bits_ref[...], req_ref[g], axis=0, keepdims=False)
        byte = jnp.take(rowv, jnp.clip(ids >> 3, 0, nbytes - 1)
                        .reshape(-1)).reshape(1, w)
        bit = (byte >> (ids & 7).astype(jnp.uint8)) & jnp.uint8(1)
        cached = jnp.where(in_deg, bit, jnp.uint8(0))
        wgt = jnp.where(
            in_deg,
            1.0 + jnp.float32(boost) * cached.astype(jnp.float32),
            0.0)                                           # [1, w]
        cum = jnp.cumsum(wgt, axis=1)
        total = cum[0, w - 1]
        draw = draw_ref[pl.ds(i, 1), :] * jnp.maximum(total, 1e-9)
        # searchsorted(side='right') == count of cum <= draw
        cmp = cum <= draw.reshape(k, 1)                    # [k, w]
        off_med = jnp.sum(cmp.astype(jnp.int32),
                          axis=1).reshape(1, k)
        off_med = jnp.minimum(off_med, jnp.maximum(deg_i - 1, 0))
        hot = ee == off_med.reshape(k, 1)                  # one-hot
        w_drawn = jnp.sum(jnp.where(hot, wgt, 0.0),
                          axis=1).reshape(1, k)
        iw = (total / jnp.maximum(deg_i, 1)) \
            / jnp.maximum(w_drawn, 1e-9)
        iw_ref[pl.ds(i, 1), :] = iw
      else:
        # Gumbel top-k as a rank select: rank(e) = #{f beating e}
        # under the (value desc, index asc) total order lax.top_k
        # sorts by — bit-identical winners, no sort network
        gmb = jnp.where(in_deg, draw_ref[pl.ds(i, 1), :], -jnp.inf)
        colv = gmb.reshape(w, 1)
        fidx = jax.lax.broadcasted_iota(jnp.int32, (w, w), 1)
        eidx = jax.lax.broadcasted_iota(jnp.int32, (w, w), 0)
        beats = (gmb > colv) | ((gmb == colv) & (fidx < eidx))
        rank = jnp.sum(beats.astype(jnp.int32),
                       axis=1).reshape(1, w)
        sel = rank == jax.lax.broadcasted_iota(jnp.int32, (k, w), 0)
        off_med = jnp.sum(
            jnp.where(sel,
                      jax.lax.broadcasted_iota(jnp.int32, (k, w), 1),
                      0), axis=1).reshape(1, k)

      take_all = deg_i <= k
      med = (deg_i > k) & (deg_i <= w)
      off_sel = jnp.where(take_all, slot_k,
                          jnp.where(med, off_med,
                                    rand_ref[pl.ds(i, 1), :]))
      # compact: value = window one-hot for in-window offsets, the
      # precomputed beyond-window gather for the hub arm
      onehot = ee == off_sel.reshape(k, 1)                 # [k, w]
      win_val = jnp.sum(jnp.where(onehot, win, 0),
                        axis=1).reshape(1, k)
      val_out = jnp.where(deg_i > w, large_ref[pl.ds(i, 1), :],
                          win_val)
      val_ref[pl.ds(i, 1), :] = val_out
      out_off_ref[pl.ds(i, 1), :] = off_sel

  return kernel


@functools.partial(
    jax.jit,
    static_argnames=('e', 'k', 'w', 'tile', 'boost', 'gns',
                     'interpret'))
def _fused_draw(ind2d, starts, deg, draws, rand_off, large_vals,
                reqrow, bits2d, *, e: int, k: int, w: int, tile: int,
                boost: float, gns: bool, interpret: bool):
  """Run the fused kernel over padded tiles; returns ``(val, off[,
  iw])`` each ``[b, k]``."""
  b = starts.shape[0]
  bp = -(-b // tile) * tile
  starts_p = jnp.zeros((bp,), jnp.int32).at[:b].set(
      jnp.clip(starts.astype(jnp.int32), 0, max(int(e) - 1, 0)))
  deg_p = jnp.zeros((bp,), jnp.int32).at[:b].set(deg)
  unit_row = starts_p // UNIT * SUBLANES
  offm = starts_p % UNIT

  def pad2(x, dtype):
    return jnp.zeros((bp, x.shape[1]), dtype).at[:b].set(
        x.astype(dtype))

  draws_p = pad2(draws, jnp.float32)
  rand_p = pad2(rand_off, jnp.int32)
  large_p = pad2(large_vals, jnp.int32)

  nbytes = int(bits2d.shape[1]) if gns else 0
  kernel = _make_kernel(k=k, w=w, tile=tile, boost=boost, gns=gns,
                        nbytes=nbytes)
  n_scalar = 4 if gns else 3
  dw = draws.shape[1]

  def blk(width):
    return pl.BlockSpec((tile, width),
                        lambda t, *refs: (t, 0),
                        memory_space=pltpu.VMEM)

  in_specs = [pl.BlockSpec(memory_space=pl.ANY),   # window table
              blk(dw), blk(k), blk(k)]
  inputs = [ind2d, draws_p, rand_p, large_p]
  out_shape = [jax.ShapeDtypeStruct((bp, k), jnp.int32),
               jax.ShapeDtypeStruct((bp, k), jnp.int32)]
  out_specs = [blk(k), blk(k)]
  scalars = [unit_row, offm, deg_p]
  if gns:
    scalars.append(jnp.zeros((bp,), jnp.int32).at[:b].set(reqrow))
    in_specs.append(pl.BlockSpec(bits2d.shape,
                                 lambda t, *refs: (0, 0),
                                 memory_space=pltpu.VMEM))
    inputs.append(bits2d)
    out_shape.append(jax.ShapeDtypeStruct((bp, k), jnp.float32))
    out_specs.append(blk(k))

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=n_scalar,
      grid=(bp // tile,),
      in_specs=in_specs,
      out_specs=out_specs,
      scratch_shapes=[pltpu.VMEM((tile, 2 * SUBLANES, LANES),
                                 ind2d.dtype),
                      pltpu.SemaphoreType.DMA((tile,))],
  )
  outs = pl.pallas_call(
      kernel,
      grid_spec=grid_spec,
      out_shape=tuple(out_shape),
      interpret=interpret,
  )(*scalars, *inputs)
  return tuple(o[:b] for o in outs)


def sample_one_hop_fused(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    edge_ids: Optional[jax.Array] = None,
    *,
    bits=None,
    boost: float = 0.0,
    req: Optional[jax.Array] = None,
    window: Optional[int] = None,
    with_edge_ids: bool = False,
    sort_locality: bool = True,
    interpret: Optional[bool] = None,
    tile: int = _TILE,
    table: Optional[Tuple[jax.Array, int]] = None,
) -> OneHopResult:
  """Fused-kernel twin of `sample_one_hop` (``bits=None``) /
  `sample_one_hop_gns` (``bits`` set) — same contract, bit-identical
  outputs.  Callers qualify the shape with `fused_sample_supported`
  first; this function assumes a qualified call.

  Args:
    table: prebuilt `prepare_window_table(indices)` — pass it on
      repeated calls so the O(E) repack is paid once per graph.
  """
  if interpret is None:
    interpret = _interpret_default()
  gns = bits is not None
  if sort_locality and seeds.shape[0] > 1:
    big = jnp.iinfo(seeds.dtype).max
    order = jnp.argsort(jnp.where(seeds >= 0, seeds, big))
    res = sample_one_hop_fused(
        indptr, indices, seeds[order], k, key, edge_ids, bits=bits,
        boost=boost,
        req=(req[order] if req is not None else None),
        window=window, with_edge_ids=with_edge_ids,
        sort_locality=False, interpret=interpret, tile=tile,
        table=table)
    inv = jnp.argsort(order)
    return OneHopResult(
        nbrs=res.nbrs[inv], mask=res.mask[inv],
        eids=res.eids[inv] if res.eids is not None else None,
        weights=(res.weights[inv] if res.weights is not None
                 else None))

  num_edges = indices.shape[0]
  b = seeds.shape[0]
  w = window if window is not None else default_window(k)
  slot = jnp.arange(k, dtype=jnp.int32)

  valid_seed = seeds >= 0
  s = jnp.where(valid_seed, seeds, 0)
  start = indptr[s]
  deg = (indptr[s + 1] - start).astype(jnp.int32)
  deg = jnp.where(valid_seed, deg, 0)
  mask = slot[None, :] < jnp.minimum(deg, k)[:, None]

  # identical key discipline to the XLA kernels: k_rand feeds the
  # with-replacement arm, k_win the window arm — same shapes, same
  # order, so the fused path consumes the very same draws
  k_rand, k_win = jax.random.split(key)
  u = jax.random.uniform(k_rand, (b, k))
  rand_off = jnp.minimum((u * deg[:, None]).astype(jnp.int32),
                         jnp.maximum(deg - 1, 0)[:, None])
  if gns:
    draws = jax.random.uniform(k_win, (b, k))
  else:
    draws = jax.random.gumbel(k_win, (b, w), dtype=jnp.float32)

  # the deg > W hub arm reads beyond the two DMA'd units; its O(B·k)
  # gather stays XLA (compacted positions, not the window)
  large_pos = jnp.clip(start[:, None] + rand_off, 0,
                       max(num_edges - 1, 0))
  large_vals = indices[large_pos].astype(jnp.int32)

  ind2d, e = table if table is not None else prepare_window_table(
      indices)
  if gns:
    tbl2d = bits_table(bits)
    if is_per_requester(bits):
      if req is None:
        raise ValueError('per-requester bitmask needs req')
      reqrow = _bits_row(bits, req)
    else:
      reqrow = jnp.zeros((b,), jnp.int32)
  else:
    tbl2d = jnp.zeros((1, 1), jnp.uint8)
    reqrow = jnp.zeros((b,), jnp.int32)

  outs = _fused_draw(ind2d, start, deg, draws, rand_off,
                     large_vals, reqrow, tbl2d, e=int(e), k=int(k),
                     w=int(w), tile=int(tile), boost=float(boost),
                     gns=gns, interpret=bool(interpret))
  if gns:
    val, off, iw = outs
  else:
    val, off = outs
    iw = None

  pos = jnp.clip(start[:, None] + off, 0, max(num_edges - 1, 0))
  nbrs = jnp.where(mask, val, INVALID_ID)
  eids = None
  if with_edge_ids:
    if edge_ids is None:
      eids = jnp.where(mask, pos, INVALID_ID)
    else:
      eids = jnp.where(mask, edge_ids[pos], INVALID_ID)
  weights = None
  if gns:
    medium = ((deg > k) & (deg <= w))[:, None]
    weights = jnp.where(mask,
                        jnp.where(medium, iw, 1.0).astype(jnp.float32),
                        0.0)
  return OneHopResult(nbrs=nbrs, mask=mask, eids=eids,
                      weights=weights)


def _bits_row(bits, req: jax.Array) -> jax.Array:
  """Resolve per-seed table rows for the kernel: the dedup tuple maps
  requester -> shared row; a replicated 2-D stack maps identically."""
  if isinstance(bits, tuple):
    tbl, row_index = bits
    row = jnp.clip(req, 0, row_index.shape[0] - 1).astype(jnp.int32)
    return row_index[row].astype(jnp.int32)
  return jnp.clip(req, 0, bits.shape[0] - 1).astype(jnp.int32)


def sample_one_hop_auto(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    edge_ids: Optional[jax.Array] = None,
    *,
    bits=None,
    boost: Optional[float] = None,
    req: Optional[jax.Array] = None,
    window: Optional[int] = None,
    with_edge_ids: bool = False,
    replace: bool = False,
    sort_locality: bool = True,
    table: Optional[Tuple[jax.Array, int]] = None,
    use_fused: Optional[bool] = None,
) -> OneHopResult:
  """THE sampling dispatcher: fused Pallas kernel when
  ``GLT_PALLAS_SAMPLE`` is on and the shape qualifies, else the XLA
  kernels — value-identical either way, so flipping the knob never
  changes results, only the lowering.  Dispatch resolves at trace
  time (jitted callers bake the choice per compile — the
  ``pallas.dispatch``/``pallas.fallback`` event marks which, once
  per compile, the `gns.bias` build-time-event precedent).

  ``bits=None`` selects the uniform kernel; otherwise the GNS-biased
  kernel with ``boost`` (env-resolved when None) and the optional
  per-requester ``req`` rows.
  """
  gns = bits is not None
  bst = resolve_boost(boost) if gns else 0.0
  fused = fused_sample_enabled() if use_fused is None else bool(
      use_fused)
  reason = None
  if fused:
    reason = fused_sample_supported(
        int(seeds.shape[0]), int(k), window, indices.dtype,
        bits=bits, replace=replace,
        num_edges=int(indices.shape[0]))
    if reason is None:
      try:
        out = sample_one_hop_fused(
            indptr, indices, seeds, k, key, edge_ids, bits=bits,
            boost=bst, req=req, window=window,
            with_edge_ids=with_edge_ids,
            sort_locality=sort_locality, table=table)
        _emit('pallas.dispatch', kernel='fused_sample',
              mode=('gns' if gns else 'uniform'),
              batch=int(seeds.shape[0]), k=int(k))
        return out
      except ValueError:
        raise                      # contract errors surface as-is
      except Exception as ex:      # pragma: no cover - lowering gap
        reason = f'trace-error:{type(ex).__name__}'
  if fused and reason is not None:
    _emit('pallas.fallback', kernel='fused_sample', reason=reason,
          batch=int(seeds.shape[0]), k=int(k))
  if gns:
    return sample_one_hop_gns(
        indptr, indices, seeds, k, key, bits, bst, edge_ids,
        req=req, window=window, with_edge_ids=with_edge_ids,
        sort_locality=sort_locality)
  return sample_one_hop(
      indptr, indices, seeds, k, key, edge_ids, window=window,
      with_edge_ids=with_edge_ids, replace=replace,
      sort_locality=sort_locality)
