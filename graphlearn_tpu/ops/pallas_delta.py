"""Pallas delta-CSR merge — dirty-row shift+insert on device (r19).

`streaming/delta.py::merge_delta_csr` keeps the clean bulk vectorized
but re-sorts every DIRTY row with a per-row python ``np.argsort``
loop; under steady-state ingest (ISSUE 14's freshness loop) that loop
is the merge's serial tail and it sits on the publish critical path.
This module replaces the loop with ONE kernel launch: a stable
MERGE-BY-RANK over all dirty rows at once.

Both inputs of a dirty row are already ordered — the base slice is
sorted CSR, the segment slice is event-ordered — so the stable sort
is really a two-way merge, and a merge needs no sort network: each
element's output position is its RANK,

  * base element ``i`` (column ``b_i``):   ``i + #{j: s_j <  b_i}``
  * seg  element ``j`` (column ``s_j``):   ``#{i: b_i <= s_j}
                                             + #{m < j: s_m <= s_j}
                                             + #{m > j: s_m <  s_j}``

which reproduces `coo_to_csr`'s stable lexsort tie-breaking exactly:
equal columns land base-first, then in event order (pinned
byte-identical in tests/test_pallas_sample.py).  Rows are padded to
the batch's max widths with an int32-max sentinel, so no per-row
control flow and no length scalars reach the kernel — sentinel
columns rank past every real column and fall off the cropped tail.

The host keeps what it is good at: the new ``indptr`` prefix sum and
the one-scatter clean-bulk shift (`merge_delta_csr`'s vectorized
half).  Dispatch discipline matches the other r19 kernels:
``GLT_PALLAS_DELTA`` (default OFF) is re-read per merge, any
disqualified shape raises `DeltaMergeUnsupported` and the caller
(`StreamingGraph.apply_events`) falls back to the host merge at byte
parity, stamping a ``pallas.fallback`` event.

Roofline note (r19): the rank kernel is compare-bound, O(L^2) per
row over VMEM-resident tiles vs the host loop's O(L log L) serial
passes + interpreter overhead per row; the win is batching every
dirty row into one launch, not asymptotics — re-measure on hardware
via `benchmarks/bench_pallas_sample.py` (delta-merge events/s row)
before defaulting it on.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import numpy as np

DELTA_ENV = 'GLT_PALLAS_DELTA'

#: per-row width cap (base or segment side): [L, L] compare tiles
#: must stay VMEM-plausible; wider rows fall back to the host merge.
_MAX_WIDTH = 2048

_TILE = 8


class DeltaMergeUnsupported(Exception):
  """Shape/dtype disqualifies the merge kernel; fall back to host."""


def delta_merge_enabled() -> bool:
  """Re-read ``GLT_PALLAS_DELTA`` on every merge (kill switch)."""
  return os.environ.get(DELTA_ENV, '').strip().lower() in (
      '1', 'true', 'on', 'yes')


def _rank_kernel(Lb: int, Ls: int, tile: int):
  import jax
  import jax.numpy as jnp
  from jax.experimental import pallas as pl

  def kernel(bc_ref, sc_ref, pb_ref, ps_ref):
    for i in range(tile):
      bc = bc_ref[pl.ds(i, 1), :]                       # [1, Lb]
      sc = sc_ref[pl.ds(i, 1), :]                       # [1, Ls]
      # base ranks: i + #{seg strictly below b_i}
      lt = sc < bc.reshape(Lb, 1)                       # [Lb, Ls]
      bi = jax.lax.broadcasted_iota(jnp.int32, (1, Lb), 1)
      pb_ref[pl.ds(i, 1), :] = bi + jnp.sum(
          lt.astype(jnp.int32), axis=1).reshape(1, Lb)
      # seg ranks: #{base <= s_j} + #{earlier seg <= s_j}
      #                           + #{later seg < s_j}
      le = bc <= sc.reshape(Ls, 1)                      # [Ls, Lb]
      c_base = jnp.sum(le.astype(jnp.int32), axis=1)
      mj = jax.lax.broadcasted_iota(jnp.int32, (Ls, Ls), 1)
      jj = jax.lax.broadcasted_iota(jnp.int32, (Ls, Ls), 0)
      scm = sc                                          # row of m
      scj = sc.reshape(Ls, 1)
      before = (scm < scj) | ((scm == scj) & (mj < jj))
      c_seg = jnp.sum(before.astype(jnp.int32), axis=1)
      ps_ref[pl.ds(i, 1), :] = (c_base + c_seg).reshape(1, Ls)

  return kernel


@functools.lru_cache(maxsize=32)
def _rank_call(Lb: int, Ls: int, rp: int, tile: int,
               interpret: bool):
  import jax
  from jax.experimental import pallas as pl
  import jax.numpy as jnp

  def blk(width):
    return pl.BlockSpec((tile, width), lambda t: (t, 0))

  return jax.jit(pl.pallas_call(
      _rank_kernel(Lb, Ls, tile),
      grid=(rp // tile,),
      in_specs=[blk(Lb), blk(Ls)],
      out_specs=[blk(Lb), blk(Ls)],
      out_shape=(jax.ShapeDtypeStruct((rp, Lb), jnp.int32),
                 jax.ShapeDtypeStruct((rp, Ls), jnp.int32)),
      interpret=interpret,
  ))


def merge_ranks(bc: np.ndarray, sc: np.ndarray, *,
                interpret: Optional[bool] = None,
                tile: int = _TILE
                ) -> Tuple[np.ndarray, np.ndarray]:
  """Stable two-way merge ranks for a batch of (base, seg) column
  rows, both ascending-sorted per row, int32-max sentinel padded.
  Returns ``(pos_b [R, Lb], pos_s [R, Ls])`` int32 output positions
  within each merged row."""
  import jax
  if interpret is None:
    interpret = jax.default_backend() != 'tpu'
  r, lb = bc.shape
  ls = sc.shape[1]
  rp = -(-r // tile) * tile
  sent = np.iinfo(np.int32).max
  if rp != r:
    pad = np.full((rp - r, lb), sent, np.int32)
    bc = np.concatenate([bc, pad])
    sc = np.concatenate([sc, np.full((rp - r, ls), sent, np.int32)])
  pos_b, pos_s = _rank_call(int(lb), int(ls), int(rp), int(tile),
                            bool(interpret))(bc, sc)
  return np.asarray(pos_b)[:r], np.asarray(pos_s)[:r]


def merge_delta_csr_device(indptr: np.ndarray, indices: np.ndarray,
                           eids: np.ndarray, seg,
                           *, interpret: Optional[bool] = None
                           ) -> Tuple[np.ndarray, np.ndarray,
                                      np.ndarray]:
  """Kernel-backed twin of `streaming.delta.merge_delta_csr` — same
  byte-identity contract (result equals ``coo_to_csr`` over the full
  event-ordered edge list).  Host does the indptr prefix sum and the
  clean-bulk shift; the dirty rows are merged by the rank kernel in
  one launch instead of the per-row python sort loop.

  Raises `DeltaMergeUnsupported` when the shape disqualifies the
  kernel (caller falls back to the host merge)."""
  from ..utils.topo import ptr2ind
  num_nodes = len(indptr) - 1
  src = np.asarray(seg.src, np.int64)
  if src.size and (src.min() < 0 or src.max() >= num_nodes):
    raise ValueError(
        f'delta source ids out of range for num_nodes={num_nodes}')
  sent = np.iinfo(np.int32).max
  if num_nodes >= sent:
    raise DeltaMergeUnsupported('num_nodes >= int32 sentinel')
  add = np.bincount(src, minlength=num_nodes).astype(np.int64)
  new_indptr = np.zeros(num_nodes + 1, np.int64)
  np.cumsum(np.diff(indptr) + add, out=new_indptr[1:])
  e_new = int(new_indptr[-1])
  new_indices = np.empty(e_new, indices.dtype)
  new_eids = np.empty(e_new, eids.dtype)
  if len(indices):
    rows_of = ptr2ind(indptr)
    pos = np.arange(len(indices)) + (new_indptr[:-1] - indptr[:-1]
                                     )[rows_of]
    new_indices[pos] = indices
    new_eids[pos] = eids
  dirty = np.unique(src)
  if dirty.size:
    dst = np.asarray(seg.dst)
    seg_eids = np.asarray(seg.eids)
    order = np.argsort(src, kind='stable')
    s_src = src[order]
    s_dst = dst[order]
    s_eids = seg_eids[order]
    seg_lo = np.searchsorted(s_src, dirty, side='left')
    seg_cnt = (np.searchsorted(s_src, dirty, side='right')
               - seg_lo).astype(np.int64)
    base_cnt = (indptr[dirty + 1] - indptr[dirty]).astype(np.int64)
    lb = max(1, int(base_cnt.max()))
    ls = max(1, int(seg_cnt.max()))
    if lb > _MAX_WIDTH or ls > _MAX_WIDTH:
      raise DeltaMergeUnsupported(f'dirty row wider than {_MAX_WIDTH}')
    rd = int(dirty.size)
    bi = np.arange(lb)
    bmask = bi[None, :] < base_cnt[:, None]
    bpos = np.asarray(indptr)[dirty][:, None] + bi     # base edge pos
    bc = np.full((rd, lb), sent, np.int32)
    bc[bmask] = np.asarray(indices)[bpos[bmask]].astype(np.int32)
    si = np.arange(ls)
    smask = si[None, :] < seg_cnt[:, None]
    spos = seg_lo[:, None] + si
    sc = np.full((rd, ls), sent, np.int32)
    sc[smask] = s_dst[spos[smask]].astype(np.int32)
    pos_b, pos_s = merge_ranks(bc, sc, interpret=interpret)
    tgt = (new_indptr[dirty][:, None] + pos_b)[bmask]
    srcpos = bpos[bmask]
    new_indices[tgt] = np.asarray(indices)[srcpos]
    new_eids[tgt] = np.asarray(eids)[srcpos]
    tgt = (new_indptr[dirty][:, None] + pos_s)[smask]
    sflat = spos[smask]
    new_indices[tgt] = s_dst[sflat].astype(new_indices.dtype)
    new_eids[tgt] = s_eids[sflat].astype(new_eids.dtype)
  return new_indptr, new_indices, new_eids
