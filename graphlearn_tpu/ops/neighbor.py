"""Uniform neighbor sampling on device (XLA).

TPU-native replacement for the reference's CUDA row-wise sampler
(`csrc/cuda/random_sampler.cu:39-108` — FillNbrsNum + reservoir
CSRRowWiseSampleKernel with curand Philox) and its CPU twin
(`csrc/cpu/random_sampler.cc:76-113`).

Design: the CUDA kernel emits *ragged* ``(nbrs, nbrs_num)``; XLA needs
static shapes, so we emit a dense ``[B, k]`` neighbor block plus a
validity mask.  Per-row strategy (fused into one vectorized program —
no per-row control flow):

  * ``deg <= k``       — take all neighbors (slots ``0..deg-1``).
  * ``k < deg <= W``   — exact uniform sampling *without* replacement
    via Gumbel top-k over a ``W``-wide gathered window (the TPU answer
    to reservoir sampling: no atomics, no sequential state).
  * ``deg > W``        — k independent uniform draws (*with*
    replacement).  With the default ``W = 8k`` the expected number of
    colliding slots is ``< k^2/2W = k/16``; duplicates are deduped by
    the inducer for the node table and are statistically harmless for
    GNN aggregation.

Randomness comes from `jax.random` (threefry), counter-based like
curand Philox, so sampling is reproducible and order-independent.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils.padding import INVALID_ID, round_up


class OneHopResult(NamedTuple):
  """Dense one-hop sample.

  Attributes:
    nbrs: ``[B, k]`` global neighbor ids (INVALID_ID where masked).
    mask: ``[B, k]`` slot validity (slot < min(deg, k)).
    eids: ``[B, k]`` global edge ids (INVALID_ID where masked) or None.
    weights: ``[B, k]`` per-edge importance weights (``p/q``
      inclusion-probability correction), or None.  Only the biased
      GNS kernel (`ops.gns.sample_one_hop_gns`) sets this; the
      uniform kernel's draws are already unbiased for the neighbor
      mean, so it leaves the field None and no consumer pays for it.
  """
  nbrs: jax.Array
  mask: jax.Array
  eids: Optional[jax.Array]
  weights: Optional[jax.Array] = None


def default_window(k: int) -> int:
  return round_up(max(8 * k, 64), 8)


@functools.partial(
    jax.jit, static_argnames=('k', 'window', 'with_edge_ids', 'replace',
                              'sort_locality'))
def sample_one_hop(
    indptr: jax.Array,
    indices: jax.Array,
    seeds: jax.Array,
    k: int,
    key: jax.Array,
    edge_ids: Optional[jax.Array] = None,
    *,
    window: Optional[int] = None,
    with_edge_ids: bool = False,
    replace: bool = False,
    sort_locality: bool = True,
) -> OneHopResult:
  """Sample up to ``k`` neighbors for each seed.

  Args:
    indptr: ``[N+1]`` CSR row pointers (device array).
    indices: ``[E]`` CSR column indices.
    seeds: ``[B]`` global seed ids; INVALID_ID entries produce empty
      rows (the masked analog of the reference's empty-fallback at
      `sampler/neighbor_sampler.py:118-136`).
    k: fanout (static).
    key: PRNG key.
    edge_ids: optional ``[E]`` global edge ids to emit alongside.
    window: static window size for the exact without-replacement path;
      defaults to ``8k``.
    with_edge_ids: emit ``eids`` (requires ``edge_ids``).
    replace: force with-replacement draws for every ``deg > k`` row
      (skips the window gather entirely — cheaper, more approximate).
    sort_locality: process seeds in sorted-id order internally (outputs
      restored to input order) — adjacent CSR rows share HBM pages, so
      the window gathers run ~25% faster on large graphs (measured on
      v5e at products scale).  Distribution-identical; per-seed draws
      differ from the unsorted order.
  """
  if sort_locality and seeds.shape[0] > 1:
    big = jnp.iinfo(seeds.dtype).max
    order = jnp.argsort(jnp.where(seeds >= 0, seeds, big))
    res = sample_one_hop(indptr, indices, seeds[order], k, key, edge_ids,
                         window=window, with_edge_ids=with_edge_ids,
                         replace=replace, sort_locality=False)
    # restore input order with plain gathers by the inverse permutation
    # (scatters would lower to XLA's collision-safe form — slower)
    inv = jnp.argsort(order)
    return OneHopResult(
        nbrs=res.nbrs[inv], mask=res.mask[inv],
        eids=res.eids[inv] if res.eids is not None else None)
  num_edges = indices.shape[0]
  b = seeds.shape[0]
  slot = jnp.arange(k, dtype=jnp.int32)

  valid_seed = seeds >= 0
  s = jnp.where(valid_seed, seeds, 0)
  # Edge positions keep indptr's dtype (int64-safe for >2^31 edges when
  # x64 is enabled); degrees always fit int32.
  start = indptr[s]
  deg = (indptr[s + 1] - start).astype(jnp.int32)
  deg = jnp.where(valid_seed, deg, 0)

  mask = slot[None, :] < jnp.minimum(deg, k)[:, None]

  k_rand, k_win = jax.random.split(key)
  # --- with-replacement draws (large-degree path / replace=True) -----------
  u = jax.random.uniform(k_rand, (b, k))
  rand_off = jnp.minimum((u * deg[:, None]).astype(jnp.int32),
                         jnp.maximum(deg - 1, 0)[:, None])

  if replace:
    off = jnp.where((deg <= k)[:, None], slot[None, :], rand_off)
  else:
    w = window if window is not None else default_window(k)
    wslot = jnp.arange(w, dtype=jnp.int32)
    in_deg = wslot[None, :] < deg[:, None]          # [B, W]
    g = jax.random.gumbel(k_win, (b, w), dtype=jnp.float32)
    g = jnp.where(in_deg, g, -jnp.inf)
    _, top_idx = jax.lax.top_k(g, k)                # [B, k] window slots
    medium = ((deg > k) & (deg <= w))[:, None]
    off = jnp.where((deg <= k)[:, None], slot[None, :],
                    jnp.where(medium, top_idx.astype(jnp.int32), rand_off))

  pos = jnp.clip(start[:, None] + off, 0, max(num_edges - 1, 0))
  nbrs = jnp.where(mask, indices[pos].astype(jnp.int32), INVALID_ID)
  eids = None
  if with_edge_ids:
    if edge_ids is None:
      eids = jnp.where(mask, pos, INVALID_ID)
    else:
      eids = jnp.where(mask, edge_ids[pos], INVALID_ID)
  return OneHopResult(nbrs=nbrs, mask=mask, eids=eids)


@jax.jit
def lookup_degree(indptr: jax.Array, nodes: jax.Array) -> jax.Array:
  """Degree lookup; counterpart of the ``LookupDegree`` kernel
  (`csrc/cuda/graph.cu:30-68`)."""
  valid = nodes >= 0
  n = jnp.where(valid, nodes, 0)
  deg = indptr[n + 1] - indptr[n]
  return jnp.where(valid, deg, 0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=('k',))
def cal_nbr_prob(
    indptr: jax.Array,
    indices: jax.Array,
    node_prob: jax.Array,
    k: int,
) -> jax.Array:
  """Propagate per-node sampling probability one hop.

  Counterpart of ``CalNbrProbKernel`` (`csrc/cuda/random_sampler.cu:
  166-208`), used by the frequency partitioner: each node ``u`` with
  hotness ``p_u`` contributes ``p_u * min(1, k/deg(u))`` to each of its
  neighbors.  Vectorized as a single edge-parallel scatter-add instead
  of a per-row kernel.
  """
  num_nodes = indptr.shape[0] - 1
  num_edges = indices.shape[0]
  edge_pos = jnp.arange(num_edges)
  rows = (jnp.searchsorted(indptr, edge_pos, side='right') - 1).astype(
      jnp.int32)
  deg = (indptr[rows + 1] - indptr[rows]).astype(node_prob.dtype)
  contrib = node_prob[rows] * jnp.minimum(1.0, k / jnp.maximum(deg, 1))
  return jax.ops.segment_sum(contrib, indices, num_segments=num_nodes)
