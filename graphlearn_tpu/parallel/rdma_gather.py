"""Remote-DMA feature exchange prototype (Pallas `make_async_remote_copy`).

The production mesh feature gather (`dist_sampler.dist_gather_multi`)
answers row requests with a dense reply `all_to_all`: every owner
first MATERIALIZES its reply rows into a local [P*C, D] buffer, then
XLA ships it.  This kernel instead pushes each requested row straight
from the owner's HBM table into the REQUESTER's receive buffer over
ICI — per-row RDMA, no owner-side reply materialization (one less
full-payload HBM round trip), fusing the reply exchange and the
stitch-source layout:

  requester r's receive buffer is ``[P, C, D]``; owner ``o`` writes
  row ``j`` of r's requests directly at ``recv[o, j]`` — exactly the
  layout the stitch gather reads.

Every (owner, slot) pair carries exactly one row copy (invalid slots
push row 0, masked later), so send/receive counts are static and the
completion waits are symmetric: each device starts P*C sends and waits
P*C receives of identical byte size.

Status: correctness-validated in Pallas interpret mode on the virtual
CPU mesh (`tests/test_rdma_gather.py`) and API-complete for real
slices; it CANNOT be performance-qualified in this environment (one
physical chip — ICI RDMA needs >= 2), so the production engines keep
the XLA `all_to_all` path.  On a real slice, drop this function in
place of `dist_gather` inside the shard_map body and race the two; the
bucketing, capacity and masking semantics are identical by
construction (shared `bucket_by_owner`).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .dist_sampler import bucket_by_owner
from .exchange import ExchangeSpec, MIN_EXCHANGE_CAP


def _dense_request_cap(exchange_capacity, num_parts: int):
  """Normalize an ``exchange_capacity`` (legacy int, None, or an
  `exchange.ExchangeSpec`) to the per-destination width of the DENSE
  request grid this kernel requires: every (owner, slot) pair maps to
  exactly one remote-DMA descriptor, so the request layout cannot be
  compacted or staged.

  A COMPACT spec flattens to ``base + pool`` per destination: the
  plan admits at most that many ids of any one owner (per-owner base
  prefix plus whatever the shared pool took, which is itself a
  prefix of the owner's overflow), so the dense grid keeps a strict
  superset of the ids the XLA compact path delivers.  A HIER spec
  has no cheap per-destination superset (its caps bound COLUMNS and
  ROWS, not owners) — it maps to a slots-equivalent dense cap, which
  can drop under skew the staged path absorbed; acceptable for this
  prototype, noted here so a real-slice integration revisits it."""
  if exchange_capacity is None or isinstance(exchange_capacity, int):
    return exchange_capacity
  if isinstance(exchange_capacity, ExchangeSpec):
    if exchange_capacity.layout == 'dense':
      return exchange_capacity.capacity
    if exchange_capacity.layout in ('compact', 'ragged'):
      return exchange_capacity.capacity + exchange_capacity.pool
    return max(MIN_EXCHANGE_CAP,
               -(-exchange_capacity.slots // num_parts))
  return int(exchange_capacity)


def _push_rows_kernel(num_parts: int, axis: str):
  """Kernel body: push each requested row to its requester's buffer."""

  def kernel(ids_ref, start_ref, shard_ref, out_ref, send_sem, recv_sem):
    my = jax.lax.axis_index(axis)
    rows_max = shard_ref.shape[0]
    c = ids_ref.shape[1]
    for r in range(num_parts):          # requester
      for j in range(c):                # its slot on me
        rid = ids_ref[r, j]
        local = jnp.clip(rid - start_ref[0], 0, rows_max - 1)
        pltpu.make_async_remote_copy(
            src_ref=shard_ref.at[local],
            dst_ref=out_ref.at[my, j],
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=r,
            device_id_type=pltpu.DeviceIdType.LOGICAL).start()
    # symmetric completion: P*C identical-size sends out, P*C in.
    # Any same-shape descriptor drains the matching semaphore bytes.
    for r in range(num_parts):
      for j in range(c):
        d = pltpu.make_async_remote_copy(
            src_ref=shard_ref.at[0], dst_ref=out_ref.at[r, j],
            send_sem=send_sem, recv_sem=recv_sem, device_id=r,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        d.wait_send()
        d.wait_recv()

  return kernel


def rdma_gather(shard_loc, bounds, ids, axis: str, num_parts: int,
                exchange_capacity: Optional[int] = None,
                interpret: Optional[bool] = None):
  """Distributed row gather with an RDMA reply path.

  Drop-in analog of `dist_sampler.dist_gather` (range-sharded tables):
  the request ids still travel by one small `all_to_all`; the reply
  rows travel by per-row remote DMA.  Call INSIDE shard_map over
  ``axis``.  Returns ``[len(ids), D]`` rows (zero rows for invalid /
  dropped ids).
  """
  if interpret is None:
    interpret = jax.default_backend() != 'tpu'
  if interpret is True:
    # 'on_wait' (the default) only executes a pending copy when a wait
    # matches it on the SENDING side; our completion waits are
    # byte-symmetric, not descriptor-matched, so force eager data
    # movement (hardware semaphores count bytes, matching the
    # symmetric waits natively)
    interpret = pltpu.InterpretParams(dma_execution_mode='eager')
  from .partition_book import range_of
  my_idx = jax.lax.axis_index(axis)
  my_start = bounds[my_idx]
  owner = range_of(bounds, ids)
  send, slot_p, slot_j = bucket_by_owner(
      ids, owner, num_parts, my_idx,
      _dense_request_cap(exchange_capacity, num_parts))
  c = send.shape[1]
  recv_ids = jax.lax.all_to_all(send, axis, 0, 0, tiled=True)  # [P, C]
  d = shard_loc.shape[1]

  recv = pl.pallas_call(
      _push_rows_kernel(num_parts, axis),
      in_specs=[
          pl.BlockSpec(memory_space=pltpu.SMEM),       # ids [P, C]
          pl.BlockSpec(memory_space=pltpu.SMEM),       # my_start [1]
          pl.BlockSpec(memory_space=pl.ANY),        # shard
      ],
      out_specs=pl.BlockSpec(memory_space=pl.ANY),  # recv [P, C, D]
      out_shape=jax.ShapeDtypeStruct((num_parts, c, d), shard_loc.dtype),
      scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
      compiler_params=pltpu.CompilerParams(has_side_effects=True),
      interpret=interpret,
  )(recv_ids.astype(jnp.int32), my_start[None].astype(jnp.int32),
    shard_loc)

  kept = slot_j >= 0
  out = recv[slot_p, jnp.where(kept, slot_j, 0)]
  ok = (ids >= 0) & kept
  return jnp.where(ok[:, None], out, 0)
