"""Planned `PartitionBook` handoff — move ownership, zero degraded window.

ISSUE 19.  The *scheduled* twin of crash adoption (`failover.py`):
load rebalancing and rolling maintenance move a range between live
devices through the SAME PartitionBook authority as failover, but
because nothing died, the move can be fenced — the source keeps
serving the range until the destination has durably acked the shard,
and the cutover is ONE RCU book bump.  No request is ever routed to a
device that does not hold the range's bytes, so the epoch completes
byte-identical to the no-handoff run (the PR 15 exact-completion
contract, now without a kill) with zero degraded batches and zero
lost/duplicated seeds.

The seam ladder (each phase is a ``handoff.transfer`` chaos seam with
``op`` = the seam name, and each emits one ``handoff.transfer``
flight-recorder event):

  1. **snapshot** — write the range's durable shard from the source's
     CURRENT stacks (`failover.shard_payload`, atomic publish);
  2. **transfer** — the destination loads the durable shard under the
     adoption deadline and validates it against the dataset's frozen
     shape (`failover.validate_shard_payload`);
  3. **fence** — the destination ack: the loaded payload must be
     byte-identical to what the source serves *right now*; only then
     is it STAGED on ``dataset.adopted_shards``.  The book — the
     routing authority — is still untouched: readers keep routing the
     range to the source;
  4. **cutover** — `PartitionBook.transfer`: one version bump,
     published RCU.  Readers fence at their next dispatch
     (``maybe_refresh_book``) and rebuild lane-stacked arrays that
     serve the staged shard from the destination;
  5. **drain** — the source's in-flight lane finishes naturally (its
     pinned pre-bump view stays valid for dispatches already cut);
     a fault HERE is post-cutover and is ABSORBED: the destination
     already owns the range.

A fault at any seam **before** cutover unwinds to clean source
retention: the staged shard is dropped, the book is untouched, and a
typed `HandoffAbortedError` names the seam — never two owners, never
a half-moved range.  The decision ledger of who-asked lives with the
caller (the ElasticController's `scale.decision` / an operator's
runbook); this module owns only the move.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from .failover import (NoDurableShardError, ShardStore, adopt_timeout_s,
                       shard_payload, shard_dir_from_env, dataset_meta,
                       validate_shard_payload, _load_with_deadline)
from .partition_book import AdoptionRefusedError, PartitionBook

#: the seam ladder, in execution order (chaos plans target these via
#: ``handoff.transfer:<action>:1:op=<seam>``)
SEAMS = ('snapshot', 'transfer', 'fence', 'cutover', 'drain')


class HandoffAbortedError(RuntimeError):
  """A planned handoff unwound before cutover: the source cleanly
  retains ownership (book untouched, staged shard dropped).  ``seam``
  names where the ladder stopped."""

  def __init__(self, msg: str, seam: Optional[str] = None,
               partition: Optional[int] = None):
    super().__init__(msg)
    self.seam = seam
    self.partition = partition


def _ack_payload(ds, rng: int, payload: Dict[str, np.ndarray]) -> None:
  """The fence's destination ack: every array in the transferred
  payload must be byte-identical to what the source serves from its
  live stacks RIGHT NOW — a stale or torn shard refuses here, before
  anything is staged."""
  live_now = shard_payload(ds, rng)
  for key, want in live_now.items():
    got = payload.get(key)
    if got is None or not np.array_equal(np.asarray(got),
                                         np.asarray(want)):
      raise HandoffAbortedError(
          f'destination ack failed for partition {int(rng)}: '
          f'transferred shard field {key!r} is not byte-identical to '
          'the live range (stale durable copy?)', seam='fence',
          partition=int(rng))


def handoff(ds, rng: int, to: int, store: Optional[ShardStore] = None,
            frm: Optional[int] = None) -> Dict:
  """Move range ``rng`` from its current owner to device ``to``
  through the fenced seam ladder.  Returns an info dict (``frm``,
  ``to``, ``version``, ``secs``, ``drain_fault``).  Raises typed —
  `HandoffAbortedError` / `AdoptionRefusedError` /
  `NoDurableShardError` — with the book untouched and nothing staged
  whenever the ladder stops before cutover."""
  from ..telemetry.recorder import recorder
  from ..testing import chaos
  book: PartitionBook = ds.partition_book
  rng, to = int(rng), int(to)
  if frm is None:
    frm = int(book.view().owners[rng])
  frm = int(frm)
  if store is None:
    d = shard_dir_from_env()
    if d is None:
      raise NoDurableShardError(
          'no shard store configured (GLT_SHARD_DIR unset) — a '
          'planned handoff needs the durable-shard transfer path')
    store = ShardStore(d)

  t0 = time.monotonic()
  staged = False
  seam = 'snapshot'

  def _emit(phase: str, **extra) -> None:
    recorder.emit('handoff.transfer', partition=rng, frm=frm, to=to,
                  phase=phase, version=book.version,
                  secs=round(time.monotonic() - t0, 6), **extra)

  try:
    # 1. snapshot — durable copy of the range from the source's stacks
    chaos.handoff_transfer_check('snapshot', partition=rng)
    store.save_shard(rng, shard_payload(ds, rng))
    store.save_meta(dataset_meta(ds))
    _emit('snapshot')

    # 2. transfer — destination loads the durable shard (deadline-
    # bounded: a wedged store aborts the handoff, not the epoch)
    seam = 'transfer'
    chaos.handoff_transfer_check('transfer', partition=rng)
    payload = _load_with_deadline(store, rng, adopt_timeout_s())
    payload = validate_shard_payload(ds, store, payload)
    _emit('transfer')

    # 3. fence — destination ack + staging; the book (and therefore
    # every router/reader) still points the range at the source
    seam = 'fence'
    chaos.handoff_transfer_check('fence', partition=rng)
    _ack_payload(ds, rng, payload)
    if not hasattr(ds, 'adopted_shards'):
      ds.adopted_shards = {}
    if rng in ds.adopted_shards:
      raise HandoffAbortedError(
          f'range {rng} already carries a staged/adopted shard — '
          'refusing to overwrite a prior ownership move',
          seam='fence', partition=rng)
    ds.adopted_shards[rng] = payload
    staged = True
    _emit('fence')

    # 4. cutover — ONE RCU bump; the only mutation of the routing
    # authority in the whole ladder (the chaos check sits BEFORE it,
    # so a cutover-seam kill still unwinds to source retention)
    seam = 'cutover'
    chaos.handoff_transfer_check('cutover', partition=rng)
    view = book.transfer(rng, frm, to)
    _emit('cutover')
  except BaseException as e:
    if staged:
      ds.adopted_shards.pop(rng, None)
    _emit('rollback', error=f'{type(e).__name__}: {e}', at_seam=seam)
    if isinstance(e, (AdoptionRefusedError, NoDurableShardError,
                      HandoffAbortedError)):
      raise
    raise HandoffAbortedError(
        f'handoff of partition {rng} to {to} aborted at the {seam} '
        f'seam ({type(e).__name__}: {e}) — source retains ownership',
        seam=seam, partition=rng) from e

  # 5. drain — post-cutover: the destination already owns the range,
  # so a fault here is ABSORBED (recorded, not raised) and the move
  # stands; the source's in-flight lane finishes on its pinned view
  drain_fault = None
  try:
    chaos.handoff_transfer_check('drain', partition=rng)
  except Exception as e:              # noqa: BLE001 — absorbed by design
    drain_fault = f'{type(e).__name__}: {e}'
  secs = time.monotonic() - t0
  _emit('drain', error=drain_fault)
  return {'partition': rng, 'frm': frm, 'to': to,
          'version': int(view.version), 'secs': secs,
          'drain_fault': drain_fault}
