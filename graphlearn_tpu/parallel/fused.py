"""Whole-epoch fused DISTRIBUTED training: one SPMD program per epoch.

The mesh twin of `loader.fused.FusedEpoch`: a `lax.scan` over the
epoch's ``[S, P, B]`` seed batches whose body is the full distributed
step — per-hop owner exchange (`all_to_all` over ICI), feature/label
collection, and the data-parallel optax update (`pmean` gradients) —
so the host enqueues ONE XLA program per epoch instead of S sampler
dispatches + S train dispatches.

The reference cannot express this at all: its distributed loader is an
asyncio RPC pipeline feeding a separate DDP step per batch
(`distributed/dist_loader.py`, `dist_neighbor_sampler.py`); fusing an
epoch into one compiled collective program is mesh-native territory.

Exchange telemetry is NOT lost: the scan stacks each step's device-side
counters and `run()` folds the epoch's totals back into the sampler's
accumulator, so `exchange_stats()` reads the same numbers the per-batch
path would produce.

Constraints (checked at construction):
  * static exchange slack — ``'adaptive'`` retunes between batches on
    the host, which a single fused program precludes by design
    (``'auto'`` resolves to the capacity default, as in the loaders).

TIERED stores (``split_ratio < 1``) run as **tiered fused epochs**
(ISSUE 5): the epoch splits into chunks of ``GLT_FUSED_COLD_CHUNK``
steps and each chunk runs THREE dispatches instead of one —

  1. a compiled sample+collect scan (the same SPMD step the per-batch
     sampler dispatches; cold rows come back zeroed past the owner's
     hot count);
  2. the host cold service BETWEEN dispatches: hits in the dynamic
     HBM victim cache (`data.cold_cache`) are overlaid by a local
     device gather, residual misses ride the bounded per-chunk host
     overlay (`overlay_cold_host` / `overlay_cold_owner`), and the
     corrected rows are admitted back into the cache;
  3. a compiled train scan over the chunk's corrected batches.

Batches are byte-identical to the per-batch tiered loader driven with
the same keys; the fused dispatch structure (O(S/chunk) programs, not
O(S) sampler+train dispatches) survives tiering.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..loader.fused import (_COMPILED_ATTRS, _SnapshotHooks,
                            _uncached_jit, driver_compile_count,
                            resolve_cold_chunk)
from ..models.train import TrainState
from .dist_data import DistDataset
from .dist_sampler import (DistLinkNeighborSampler, DistNeighborSampler,
                           link_step_metadata, pack_link_seeds_relabeled,
                           resolve_exchange_slack)
from .dp import (make_dp_eval_step, make_dp_supervised_step,
                 make_dp_unsupervised_step)


class _MeshEpochDriver(_SnapshotHooks):
  """Host-driver pieces shared by the three fused mesh classes, so
  the seed/key/device-put contracts cannot drift between them.

  Preemption tolerance (`_SnapshotHooks`): with a `SnapshotManager`
  attached, tiered epochs snapshot at every chunk boundary (the
  `GLT_FUSED_COLD_CHUNK` seams — the natural recovery points) and
  untiered epochs at epoch boundaries; `restore_from_snapshot` +
  `run()` then finish an interrupted epoch byte-identically.  Every
  dispatch additionally runs under the `GLT_DISPATCH_DEADLINE`
  watchdog: a collective hung by a dead mesh participant surfaces as
  a typed `MeshStallError` instead of wedging the epoch forever, and
  — with ``GLT_DEGRADED_OK=1`` and snapshots attached — the tiered
  driver rolls back to the last snapshot and finishes the epoch on
  the surviving hosts."""

  #: True = tiered store: run()/evaluate() take the chunked
  #: collect → cold-service → consume path (module docstring)
  _tiered = False

  def _chunk_arrs(self) -> dict:
    """The sampler's device arrays, plus — under cache-aware GNS —
    the freshly refreshed cached-set bitmask.  Called per dispatch so
    a chunk's sampling bias sees the admissions the previous chunk's
    cold service made (`ops.gns`: staleness costs placement, never
    estimator bias).

    Streaming ingestion (ISSUE 14) rides the same seam: `_arrays()`
    re-pins the newest published ``graph_version`` at each chunk
    boundary (`DistNeighborSampler.maybe_refresh_stream`), so a
    whole chunk's scan samples exactly one graph version and the
    GNS bitmask is invalidated with the graph it derives from.

    Partition failover (ISSUE 15) fences here too: owner supervision
    runs before the dispatch, and a book-version bump (adoption)
    rebuilds the lane-stacked arrays inside `_arrays()` and
    re-resolves the driver's captured dist step — the changed array
    shapes retrace the compiled scan against the new routing."""
    # the previous chunk's dispatch has been consumed by the time the
    # NEXT chunk asks for arrays — close a pending adoption's recovery
    # clock at this boundary (an adoption in the final chunk closes at
    # the next epoch's first boundary)
    self.sampler._complete_recovery()
    self.sampler._partition_supervision()
    arrs = self.sampler._arrays()
    ver = self.sampler._book_ver
    if getattr(self, '_driver_book_ver', 0) != ver:
      self._driver_book_ver = ver
      if hasattr(self, '_dist_step'):
        self._dist_step = self._resolve_dist_step()
      # the outer scan programs bake `book_spec` as a trace-time
      # closure constant, and jax.jit keys executables on avals only:
      # a bump that keeps every aval unchanged (a SECOND adoption at
      # the same lane count) would hit the stale in-memory executable
      # and route through the old owners — drop the program caches so
      # the next dispatch retraces against the new routing
      for name in _COMPILED_ATTRS:
        jitted = getattr(getattr(self, name, None), 'jitted', None)
        if jitted is not None and hasattr(jitted, 'clear_cache'):
          jitted.clear_cache()
    if getattr(self.sampler, 'gns', False):
      arrs = dict(arrs, gns=self.sampler._gns_arrays())
    return arrs

  def _resolve_dist_step(self):
    """Re-resolve the captured SPMD step after a book bump (the link
    driver overrides with its pair-step resolver)."""
    return self.sampler.step_for_batch(self.batch_size)

  # -- snapshot hooks (mesh-shaped overrides of _SnapshotHooks) -----------
  def data_plane_state(self) -> dict:
    return {'epoch_idx': self._epoch_idx,
            'batcher': self._batcher.state_dict(),
            'sampler': self.sampler.data_plane_state()}

  def load_data_plane_state(self, plane: dict) -> None:
    self._epoch_idx = int(np.asarray(plane['epoch_idx'])) - 1
    self._batcher.load_state_dict(plane['batcher'], mid_epoch=True)
    self.sampler.load_data_plane_state(plane['sampler'])

  def _state_to_device(self, train_host):
    from .dp import replicate
    return replicate(jax.tree_util.tree_map(np.asarray, train_host),
                     self.mesh)

  def _next_epoch_key(self):
    self._epoch_idx += 1
    return jax.random.fold_in(self._base_key, self._epoch_idx)

  def _eval_key(self):
    """Eval keys live in their own fold DOMAIN (base -> 0 -> 1);
    train keys are base -> epoch with epoch >= 1, so no epoch-counter
    value can alias a train sampling key (the loader.fused
    contract)."""
    return jax.random.fold_in(jax.random.fold_in(self._base_key, 0), 1)

  def _put_batches(self, arr: np.ndarray) -> jax.Array:
    """``[S, P, ...]`` host batches → device, sharded over the mesh
    axis on dim 1."""
    return jax.device_put(
        arr.astype(np.int32),
        NamedSharding(self.mesh, P(None, self.axis)))

  def _stack_eval_seeds(self, input_nodes, input_space: str):
    """Relabel + batch an eval split into ``[S, P, B]``."""
    from ..loader.node_loader import SeedBatcher
    ids = np.asarray(input_nodes).reshape(-1)
    if ids.dtype == np.bool_:
      ids = np.nonzero(ids)[0]
    if ids.size == 0:
      raise ValueError('evaluate() got an empty split')
    if input_space == 'old' and self.ds.old2new is not None:
      ids = self.ds.old2new[ids]
    ev = SeedBatcher(ids, self.batch_size * self.num_parts,
                     shuffle=False)
    return np.stack(list(ev)).reshape(-1, self.num_parts,
                                      self.batch_size)

  def run(self, state: TrainState) -> Tuple[TrainState, 'EpochStats']:
    """Run one epoch; ``state`` must be mesh-replicated and is
    DONATED — thread the returned state forward.  ``stats`` is LAZY
    (`loader.fused.EpochStats`)."""
    from ..distributed.resilience import run_with_deadline
    from ..loader.fused import EpochStats
    from ..telemetry.spans import span
    from ..testing import chaos
    from ..utils.profiling import step_annotation
    flat = np.stack(list(self._batcher))           # [S, P*B]
    seeds = flat.reshape(-1, self.num_parts, self.batch_size)
    s = seeds.shape[0]
    key = self._next_epoch_key()
    with span('fused.epoch', scope=type(self).__name__,
              epoch=self._epoch_idx, steps=seeds.shape[0],
              tiered=self._tiered):
      with step_annotation('fused_dist_epoch', self._epoch_idx):
        if self._tiered:
          state, losses, correct, valid, hops = self._run_tiered(
              state, seeds, key)
        else:
          # untiered = ONE program: snapshots land at epoch
          # boundaries only (there is no mid-epoch seam to save at)
          skip, l_saved, c_saved, v_saved, extra = self._take_resume(s)
          if skip >= s and l_saved:
            losses, correct, valid = l_saved[0], c_saved, v_saved
            hops = extra.get('hops')
          else:
            with span('fused.dispatch'):
              def _epoch_dispatch():
                chaos.fused_dispatch_check(chunk=0,
                                           epoch=self._epoch_idx)
                return self._compiled(state, self._put_batches(seeds),
                                      key, self._chunk_arrs())
              (state, losses, correct, valid, stats,
               hops) = run_with_deadline(_epoch_dispatch,
                                         scope='fused.dispatch')
            self.sampler._accumulate_stats(stats)
            self._save_chunk_snapshot(state, s, s, [losses], correct,
                                      valid, force=True, hops=hops)
      self._emit_hop_events(hops, seeds.shape[0])
    return state, EpochStats(losses, correct, valid)

  # -- tiered fused epochs (module docstring) -------------------------------

  def _chunk_key_stack(self, key, c0: int, n: int):
    """Per-step keys for one chunk, in the GLOBAL step index domain —
    the same ``fold_in(epoch_key, i)`` schedule the single-program
    scan uses, so tiered and untiered epochs draw identically."""
    return jnp.stack([jax.random.fold_in(key, i)
                      for i in range(c0, c0 + n)])

  def _cold_chunk_steps(self, total_steps: int) -> int:
    return resolve_cold_chunk(self._collect_step_bytes(), total_steps)

  def _tiered_chunks(self, stacked: np.ndarray, key, chunk: int):
    """Yield ``(c0, real_steps, [chunk, ...] piece, [chunk] keys)``:
    tail chunks pad with INVALID_ID seed rows (the loader twin's
    `_chunks` convention — every epoch length reuses ONE compile per
    collect/train/eval program; padded steps sample nothing and
    contribute no valid seeds).  Consumers must slice per-step
    outputs (losses, stats) to ``real_steps``."""
    s = stacked.shape[0]
    for c0 in range(0, s, chunk):
      part = stacked[c0:c0 + chunk]
      real = part.shape[0]
      if real < chunk:
        pad = np.full((chunk - real,) + stacked.shape[1:], -1,
                      stacked.dtype)
        part = np.concatenate([part, pad])
      yield c0, real, part, self._chunk_key_stack(key, c0, chunk)

  def _overlay_stacked(self, x_all, nodes_all):
    """Between-dispatch cold service for one chunk's stacked
    ``[c, ...]`` features/ids: per step, the sampler's cache-aware
    overlay (cache hits device-served, misses host-overlaid, corrected
    rows admitted)."""
    from ..telemetry.spans import span
    c = x_all.shape[0]
    with span('feature.cold_overlay', scope=type(self).__name__,
              steps=c):
      fixed = [self.sampler._overlay_cold_traced(x_all[i], nodes_all[i])
               for i in range(c)]
    return jnp.stack(fixed)

  def _run_tiered(self, state, seeds: np.ndarray, key):
    """Chunked collect → cold-service → train epoch (tiered stores).
    Returns ``(state, losses, correct, valid, hops)``.

    With snapshots attached, every chunk boundary is a durable
    recovery point, and a `MeshStallError` (hung collective under
    `GLT_DISPATCH_DEADLINE`) rolls back to the last snapshot and
    retries on the surviving hosts when ``GLT_DEGRADED_OK=1`` —
    instead of wedging or losing the epoch."""
    from ..distributed.resilience import MeshStallError, degraded_ok
    s = seeds.shape[0]
    chunk = self._cold_chunk_steps(s)
    skip, losses, correct, valid, extra = self._take_resume(chunk)
    hops = extra.get('hops')
    if 'sampler_stats' in extra:
      # a fresh-process resume continues the interrupted epoch's
      # cumulative exchange/cold telemetry, not a zeroed ledger
      self.sampler._load_stats_state(extra['sampler_stats'])
    stats_fn = lambda: {'sampler_stats': self.sampler._stats_state()}
    if self._snap is not None and skip == 0 and not losses:
      # epoch-entry save: the rollback target a chunk-0 stall needs
      self._save_chunk_snapshot(state, 0, chunk, losses, correct,
                                valid, force=True, extra_fn=stats_fn)
    parts = list(self._tiered_chunks(seeds, key, chunk))
    i = rollbacks = 0
    while i < len(parts):
      c0, real, part, keys = parts[i]
      if c0 < skip:
        i += 1
        continue
      try:
        state, ls, cor, val, hop = self._dispatch_tiered_chunk(
            state, part, keys, real, c0)
      except MeshStallError:
        if (not degraded_ok() or self._snap is None
            or rollbacks >= 3):
          raise
        rollback = self._rollback_to_snapshot(state)
        if rollback is None:
          raise     # nothing durable to roll back to: stay typed
        rollbacks += 1
        (state, skip, losses, correct, valid, hops) = rollback
        i = 0
        continue
      losses.append(ls[:real])
      correct = cor if correct is None else correct + cor
      valid = val if valid is None else valid + val
      hops = hop if hops is None else hops + hop
      self._save_chunk_snapshot(state, c0 + chunk, chunk, losses,
                                correct, valid, hops=hops,
                                extra_fn=stats_fn)
      i += 1
    return state, jnp.concatenate(losses), correct, valid, hops

  def _dispatch_tiered_chunk(self, state, part, keys, real: int,
                             c0: int):
    """One chunk's collect → overlay → train, every dispatch under
    the stall watchdog and the ``fused.dispatch`` chaos seam."""
    from ..distributed.resilience import run_with_deadline
    from ..telemetry.spans import span
    from ..testing import chaos
    with span('fused.dispatch', chunk=c0, phase='collect'):
      def _collect():
        chaos.fused_dispatch_check(chunk=int(c0),
                                   epoch=self._epoch_idx,
                                   phase='collect')
        return self._compiled_collect(self._put_batches(part), keys,
                                      self._chunk_arrs())
      data, stats = run_with_deadline(_collect, scope='fused.dispatch')
    # stats sliced to the real steps: padded tail steps still carry
    # static exchange SLOTS, which would inflate padding waste
    chunk_stats = jnp.sum(stats[:real], axis=0)
    data = self._overlay_chunk(data)
    with span('fused.dispatch', chunk=c0, phase='train'):
      out = run_with_deadline(self._train_chunk, state, data,
                              scope='fused.dispatch')
    # banked only after BOTH dispatches land: a train-phase stall
    # rolls back and re-runs the chunk, and stats accumulated at
    # collect time would then double-count
    self.sampler._accumulate_stats(chunk_stats)
    return out

  def _train_chunk(self, state, data):
    """Train dispatch for one tiered chunk -> ``(state, losses,
    correct, valid, hops)`` (the link driver overrides: no accuracy,
    no hop gauges)."""
    return self._compiled_train(state, data)

  def _rollback_to_snapshot(self, cur_state):
    """Degraded stall recovery: reload the last snapshot's train
    state + progress (NOT the full data plane — the epoch counters
    and batcher are live and correct mid-run) and hand back the
    accumulators to continue from.  ``None`` when no snapshot was
    ever published (every save failed): the caller re-raises the
    stall."""
    payload = self._snap.restore_latest()
    if payload is None:
      return None
    prog = payload['progress']
    train = payload.get('train')
    state = (self._state_to_device(train) if train is not None
             else cur_state)
    saved = np.asarray(prog['losses'])
    losses = [saved] if saved.size else []
    if 'sampler_stats' in prog:
      # re-dispatched chunks re-accumulate exchange/cold counters;
      # rewinding them to the snapshot keeps AdaptiveSlack and the
      # padding-waste metrics honest through a degraded recovery
      self.sampler._load_stats_state(prog['sampler_stats'])
    return (state, int(np.asarray(prog['next_chunk'])), losses,
            prog.get('correct'), prog.get('valid'), prog.get('hops'))

  def _emit_hop_events(self, hop_counts, steps: int) -> None:
    """Per-hop padding-fill flight-recorder events for one fused
    epoch.  ``hop_counts`` is the epoch's ``[H+1]`` per-hop node
    totals (summed over steps and devices inside the program — free
    in the scan); reading it is a device sync, so this only runs when
    the recorder is on (`EpochStats` laziness stays intact
    otherwise)."""
    from ..telemetry.recorder import recorder
    if not recorder.enabled:
      return
    from ..telemetry.aggregate import per_hop_padding
    fanouts = getattr(self, 'fanouts', None) or self.sampler.fanouts
    rows = per_hop_padding(
        np.asarray(hop_counts),
        self.batch_size * self.num_parts * max(int(steps), 1), fanouts)
    for row in rows:
      recorder.emit('hop.padding', scope=type(self).__name__,
                    epoch=self._epoch_idx, steps=int(steps), **row)

  def compile_count(self) -> int:
    """Total XLA compiles across this driver's `_uncached_jit`
    programs (`loader.fused.driver_compile_count`) — the mesh twin of
    the serving engine's zero-recompile pin.  A serving fleet that
    co-hosts training warms its epoch programs once and watches this
    stay flat, exactly like the bucket ladder."""
    return driver_compile_count(self)

  def cluster_exchange_stats(self) -> dict:
    """Cluster-wide padding-waste / drop-rate / cold-tier report for
    this epoch driver (delegates to the sampler's telemetry — see
    `ExchangeTelemetry.cluster_exchange_stats`)."""
    return self.sampler.cluster_exchange_stats()

  def evaluate(self, params, input_nodes,
               input_space: str = 'old') -> float:
    """Accuracy over ``input_nodes`` (e.g. the test split) as ONE
    SPMD scan program (VERDICT r4 #5) — or, for tiered stores, the
    chunked collect → cold-service → eval path."""
    seeds = self._stack_eval_seeds(input_nodes, input_space)
    if self._tiered:
      return self._evaluate_tiered(params, seeds)
    correct, total, stats = self._compiled_eval(
        params, self._put_batches(seeds), self._eval_key(),
        self._chunk_arrs())
    self.sampler._accumulate_stats(stats)
    return float(int(correct) / max(int(total), 1))

  def _evaluate_tiered(self, params, seeds: np.ndarray) -> float:
    key = self._eval_key()
    s = seeds.shape[0]
    chunk = self._cold_chunk_steps(s)
    correct = total = 0
    for c0, real, part, keys in self._tiered_chunks(seeds, key, chunk):
      data, stats = self._compiled_collect(
          self._put_batches(part), keys, self._chunk_arrs())
      self.sampler._accumulate_stats(jnp.sum(stats[:real], axis=0))
      data = self._overlay_chunk(data)
      c, t = self._compiled_eval_consume(params, data)
      correct += int(c)
      total += int(t)
    return correct / max(total, 1)


class FusedDistEpoch(_MeshEpochDriver):
  """One-program data-parallel training epochs on the mesh engine.

  Example::

      fused = FusedDistEpoch(dist_ds, [15, 10, 5], train_idx, apply_fn,
                             tx, batch_size=1024, mesh=mesh, seed=0)
      state = replicate(state, mesh)
      for epoch in range(10):
        state, stats = fused.run(state)

  Args:
    dataset: `DistDataset` (sharded layout).  Tiered stores
      (``split_ratio < 1``) run as chunked tiered fused epochs with
      the cold-cache service between dispatches (module docstring).
    num_neighbors: per-hop fanouts.
    input_nodes: global seed ids (``input_space`` semantics as in
      `DistNeighborLoader`).
    apply_fn / tx: model apply function and optax transformation.
    batch_size: PER-DEVICE seed batch size.
    mesh / axis: device mesh; its ``axis`` size must equal the
      partition count.
    shuffle / drop_last / seed: epoch iteration controls.
    exchange_slack: static capacity factor (``'auto'`` → the shuffled
      default; ``'adaptive'`` is rejected, see module docstring).
    remat: checkpoint the model forward (`jax.checkpoint`) — the fused
      program holds sampler buffers and training activations live
      together, and at large batch x fanout that joint peak can exceed
      per-chip HBM where the separate per-batch programs fit (see
      `loader.fused.FusedEpoch`).
    fast_compile: compile the epoch program with the expensive LLVM
      passes OFF (`loader.fused._FAST_COMPILE_OPTIONS`) — measured on
      the 8-device CPU mesh at the headline shape: ~38% off the scan
      compile wall for a modest runtime cost; for dev iteration and
      CPU-mesh validation.
  """

  def __init__(self, dataset: DistDataset, num_neighbors, input_nodes,
               apply_fn: Callable, tx: optax.GradientTransformation,
               batch_size: int, mesh: Optional[Mesh] = None,
               axis: str = 'data', shuffle: bool = True,
               drop_last: bool = False, seed: int = 0,
               input_space: str = 'old',
               exchange_slack='auto', exchange_layout=None,
               remat: bool = False,
               fast_compile: bool = False, gns=None):
    from ..loader.node_loader import SeedBatcher
    if dataset.node_features is None or dataset.node_labels is None:
      raise ValueError('FusedDistEpoch needs node features and labels')
    if exchange_slack == 'adaptive':
      raise ValueError(
          "exchange_slack='adaptive' retunes between batches on the "
          "host; FusedDistEpoch takes a static slack ('auto' or a "
          'number) — or use DistNeighborLoader for adaptive tuning')
    # 'adaptive' was rejected above, so the resolved slack is static
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistNeighborSampler(
        dataset, num_neighbors, mesh=mesh, axis=axis,
        collect_features=True, seed=seed, exchange_slack=slack,
        exchange_layout=exchange_layout, gns=gns)
    self.ds = dataset
    self.mesh = self.sampler.mesh
    self.axis = axis
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)

    seeds = np.asarray(input_nodes).reshape(-1)
    if input_space == 'old' and dataset.old2new is not None:
      seeds = dataset.old2new[seeds]
    self._batcher = SeedBatcher(seeds, self.batch_size * self.num_parts,
                                shuffle, drop_last, seed)
    self._base_key = jax.random.key(seed)
    self._epoch_idx = 0
    step_apply = jax.checkpoint(apply_fn) if remat else apply_fn
    self._dp_step = make_dp_supervised_step(step_apply, tx,
                                            self.batch_size, self.mesh,
                                            axis)
    # un-remat'd: evaluate() is forward-only
    self._dp_eval = make_dp_eval_step(apply_fn, self.batch_size,
                                      self.mesh, axis)
    self._dist_step = self.sampler.step_for_batch(self.batch_size)
    # _uncached_jit: never serve this program from the persistent
    # compilation cache — deserialized big scan programs crash the
    # tunneled TPU worker, and CPU AOT entries cross-loaded between
    # target-feature sets SIGILL (see loader.fused._fresh_compile)
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                                   fast_compile=fast_compile)
    self._compiled_eval = _uncached_jit(self._eval_fn,
                                        fast_compile=fast_compile)
    # tiered store: chunked collect → cold-service → train programs
    # (module docstring, "tiered fused epochs")
    self._tiered = dataset.node_features.is_tiered
    if self._tiered:
      self._compiled_collect = _uncached_jit(self._collect_fn,
                                             fast_compile=fast_compile)
      self._compiled_train = _uncached_jit(self._train_fn,
                                           donate_argnums=(0,),
                                           fast_compile=fast_compile)
      self._compiled_eval_consume = _uncached_jit(
          self._eval_consume_fn, fast_compile=fast_compile)

  def __len__(self) -> int:
    return len(self._batcher)

  # -- the one program ------------------------------------------------------

  def _collate(self, seeds: jax.Array, key_i: jax.Array, arrs: dict):
    """One fused distributed sample+collect: shared front half of the
    train and eval scan bodies (the same program `DistNeighborSampler`
    dispatches per batch).  Under GNS (``'gns'`` in ``arrs``) the step
    takes the cached-set bitmask and the per-edge importance weights
    land in the batch metadata."""
    from ..loader.transform import Batch
    extra = (arrs['gns'],) if 'gns' in arrs else ()
    outs = self._dist_step(
        arrs['indptr'], arrs['indices'], arrs['eids'], arrs['bounds'],
        seeds, arrs['fshards'], arrs['lshards'], arrs['cids'],
        arrs['crows'], arrs['efshards'], arrs['ebounds'],
        arrs['hcounts'], *extra, key_i)
    (nodes, _count, row, col, edge, seed_local, x, y, ef, nsn,
     stats) = outs[:11]
    md = {'seed_local': seed_local}
    if 'gns' in arrs:
      md['edge_weight'] = outs[11]
    batch = Batch(
        x=x, y=y, edge_index=jnp.stack([row, col], axis=1),
        edge_attr=ef, node=nodes, node_mask=nodes >= 0,
        edge_mask=row >= 0, edge=edge, batch=seeds,
        batch_size=self.batch_size,
        num_sampled_nodes=nsn, metadata=md)
    return batch, stats

  def _epoch_fn(self, state: TrainState, seeds_all: jax.Array,
                key: jax.Array, arrs: dict):
    """``[S, P, B]`` seed batches → S fused exchange+collect+train
    steps; outputs per-step losses, the summed telemetry and the
    per-hop new-node totals (for the padding-fill gauges)."""

    def body(state, xs):
      i, seeds = xs
      batch, stats = self._collate(seeds, jax.random.fold_in(key, i),
                                   arrs)
      state, loss, correct = self._dp_step(state, batch)
      # [P, H+1] new-node counts -> [H+1]: per-hop padding fill rides
      # the scan for free instead of a per-batch host sync
      hop = jnp.sum(batch.num_sampled_nodes, axis=0)
      return state, (loss, correct, jnp.sum(seeds >= 0), stats, hop)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    state, (losses, corrects, valids, stats, hops) = jax.lax.scan(
        body, state, (steps, seeds_all))
    return (state, losses, jnp.sum(corrects), jnp.sum(valids),
            jnp.sum(stats, axis=0), jnp.sum(hops, axis=0))

  def _eval_fn(self, params, seeds_all: jax.Array, key: jax.Array,
               arrs: dict):
    """Scan twin of an eval loop over ``[S, P, B]`` seeds — accuracy
    on the seed slots, psum'd over the mesh (`make_dp_eval_step`)."""

    def body(carry, xs):
      i, seeds = xs
      batch, stats = self._collate(seeds, jax.random.fold_in(key, i),
                                   arrs)
      correct, total = self._dp_eval(params, batch)
      return carry, (correct, total, stats)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    _, (correct, total, stats) = jax.lax.scan(
        body, 0, (steps, seeds_all))
    return jnp.sum(correct), jnp.sum(total), jnp.sum(stats, axis=0)

  # -- tiered fused epochs (chunked collect/train twins) --------------------

  def _collect_step_bytes(self) -> int:
    cap = self.sampler.node_capacity(self.batch_size)
    nf = self.ds.node_features
    return (self.num_parts * cap * nf.feature_dim
            * np.dtype(nf.shards.dtype).itemsize)

  def _collect_fn(self, seeds_all: jax.Array, keys: jax.Array,
                  arrs: dict):
    """``[c, P, B]`` seeds → the chunk's stacked sample+collect
    batches (cold rows zeroed, corrected between dispatches) + the
    stacked telemetry."""

    def body(_, xs):
      key_i, seeds = xs
      batch, stats = self._collate(seeds, key_i, arrs)
      return 0, (batch, stats)

    _, (batches, stats) = jax.lax.scan(body, 0, (keys, seeds_all))
    return batches, stats

  def _overlay_chunk(self, batches):
    batches.x = self._overlay_stacked(batches.x, batches.node)
    return batches

  def _train_fn(self, state: TrainState, batches):
    """Train scan over one chunk's corrected batches — the back half
    of the untiered `_epoch_fn` body."""

    def body(state, batch):
      state, loss, correct = self._dp_step(state, batch)
      hop = jnp.sum(batch.num_sampled_nodes, axis=0)
      return state, (loss, correct, jnp.sum(batch.batch >= 0), hop)

    state, (losses, corrects, valids, hops) = jax.lax.scan(
        body, state, batches)
    return (state, losses, jnp.sum(corrects), jnp.sum(valids),
            jnp.sum(hops, axis=0))

  def _eval_consume_fn(self, params, batches):
    def body(carry, batch):
      correct, total = self._dp_eval(params, batch)
      return carry, (correct, total)

    _, (c, t) = jax.lax.scan(body, 0, batches)
    return jnp.sum(c), jnp.sum(t)

  # run()/evaluate() come from `_MeshEpochDriver` — one host driver
  # for the supervised mesh twins (VERDICT r4 #5 wired there)


class FusedDistTreeEpoch(_MeshEpochDriver):
  """One-program TREE-LAYOUT data-parallel epochs over the mesh.

  The distributed twin of `loader.fused_tree.FusedTreeEpoch` — the
  flagship scatter-free/sort-free path running against a graph
  SHARDED over the devices: each hop exchanges the per-device level
  frontier to its owners (`_dist_one_hop` — windows come back in the
  tree layout, no dedup/induce step exists at all), all levels'
  features + the seed labels ride ONE capacity-capped
  `dist_gather_multi` exchange, `models.tree.TreeSAGE` aggregates by
  reshape + masked mean, and the optax update pmean-averages
  gradients — the whole epoch as one `lax.scan` SPMD program.

  Measured motivation (r5, single chip): the tree layout runs
  12.4x the subgraph fused step; this class carries the same design
  to the mesh, where the reference has no fused counterpart at all.

  Capacity semantics: level ids past the feature exchange's
  per-owner capacity return ZERO rows (counted in
  ``dist.feature.dropped``) while staying valid in the mean's count
  — the same explicit-overflow contract as the subgraph engines
  (`dist_gather_multi`); ``exchange_slack`` tunes it.

  Args:
    dataset: `DistDataset` (sharded; features + labels).  Tiered
      stores run as chunked tiered fused epochs (module docstring).
    num_neighbors: per-hop fanouts; ``len == model.num_layers``.
    input_nodes: global seed ids (``input_space`` as in the loaders).
    model: a `TreeSAGE`-shaped flax module.
    tx: optax transformation.
    batch_size: PER-DEVICE seed batch size.
    mesh / axis / shuffle / drop_last / seed / exchange_slack /
    remat / fast_compile: as `FusedDistEpoch`.
  """

  def __init__(self, dataset: DistDataset, num_neighbors, input_nodes,
               model, tx: optax.GradientTransformation,
               batch_size: int, mesh: Optional[Mesh] = None,
               axis: str = 'data', shuffle: bool = True,
               drop_last: bool = False, seed: int = 0,
               input_space: str = 'old', exchange_slack='auto',
               exchange_layout=None,
               remat: bool = False, fast_compile: bool = False,
               gns=None):
    from ..loader.node_loader import SeedBatcher
    if dataset.node_features is None or dataset.node_labels is None:
      raise ValueError('FusedDistTreeEpoch needs node features and '
                       'labels')
    if exchange_slack == 'adaptive':
      raise ValueError(
          "exchange_slack='adaptive' retunes on the host between "
          "batches; FusedDistTreeEpoch takes a static slack")
    self.fanouts = tuple(int(k) for k in num_neighbors)
    if getattr(model, 'num_layers', len(self.fanouts)) != \
        len(self.fanouts):
      raise ValueError(
          f'model.num_layers={model.num_layers} must equal '
          f'len(num_neighbors)={len(self.fanouts)}')
    # reuse the sampler scaffolding (mesh, device arrays, telemetry)
    # with no induce machinery — the DistRandomWalker pattern
    self.sampler = DistNeighborSampler(
        dataset, [], mesh=mesh, axis=axis, collect_features=True,
        seed=seed,
        exchange_slack=resolve_exchange_slack(exchange_slack, shuffle),
        exchange_layout=exchange_layout, gns=gns)
    self.ds = dataset
    self.model = model
    self.tx = tx
    self.mesh = self.sampler.mesh
    self.axis = axis
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    seeds = np.asarray(input_nodes).reshape(-1)
    if input_space == 'old' and dataset.old2new is not None:
      seeds = dataset.old2new[seeds]
    self._batcher = SeedBatcher(seeds, self.batch_size * self.num_parts,
                                shuffle, drop_last, seed)
    self._base_key = jax.random.key(seed)
    self._epoch_idx = 0
    apply = model.apply
    self._apply = jax.checkpoint(apply) if remat else apply
    self._eval_apply = apply
    self._sharded_step = self._make_sharded(train=True)
    self._sharded_eval = self._make_sharded(train=False)
    self._compiled = _uncached_jit(self._epoch_fn, donate_argnums=(0,),
                                   fast_compile=fast_compile)
    self._compiled_eval = _uncached_jit(self._eval_fn,
                                        fast_compile=fast_compile)
    self._tiered = dataset.node_features.is_tiered
    if self._tiered:
      self._sharded_collect = self._make_collect_sharded()
      self._sharded_consume = self._make_consume_sharded(train=True)
      self._sharded_consume_eval = self._make_consume_sharded(
          train=False)
      self._compiled_collect = _uncached_jit(self._collect_fn,
                                             fast_compile=fast_compile)
      self._compiled_train = _uncached_jit(self._train_fn,
                                           donate_argnums=(0,),
                                           fast_compile=fast_compile)
      self._compiled_eval_consume = _uncached_jit(
          self._eval_consume_fn, fast_compile=fast_compile)

  def __len__(self) -> int:
    return len(self._batcher)

  def init_state(self, rng) -> TrainState:
    from ..models.tree import tree_level_sizes
    d = self.ds.node_features.feature_dim
    sizes = tree_level_sizes(self.batch_size, self.fanouts)
    xs = [jnp.zeros((s, d), jnp.float32) for s in sizes]
    masks = [jnp.ones((s,), jnp.bool_) for s in sizes]
    params = self.model.init(rng, xs, masks)
    from .dp import replicate
    return replicate(
        TrainState(params, self.tx.init(params),
                   jnp.zeros((), jnp.int32)), self.mesh)

  # -- per-device body ------------------------------------------------------

  def _level_sizes(self):
    sizes = [self.batch_size]
    for k in self.fanouts:
      sizes.append(sizes[-1] * int(k))
    return sizes

  def _expand_collect(self, seeds, key, indptr_s, indices_s, bounds,
                      fshards_s, lshards_s, hcounts=None,
                      concat: bool = False, gns_bits=None):
    """Tree expansion + one fused feature/label exchange for one
    device's ``[B]`` seed slice.  Returns
    ``(xs, masks, y, stats7, hop_counts)`` — ``hop_counts[h]`` is the
    number of VALID ids in level ``h`` (the tree analog of the
    dedup path's per-hop new-node count, for the padding gauges).

    ``hcounts`` (tiered stores) zeroes feature rows past each owner's
    hot count — the caller overlays the cold tier; ``concat=True``
    returns ``(all_ids, feats, y, stats7, hop_counts)`` in the
    concatenated level layout instead of the split lists (the tiered
    collect phase's shape — the overlay machinery addresses one
    ``[L]`` id table, the consume phase re-splits).

    ``gns_bits`` (cache-aware GNS, tiered path only): hops sample
    through `ops.gns.sample_one_hop_gns` and a CUMULATIVE per-slot
    importance weight (the product of a slot's ancestor edge weights
    — the tree estimator's 1/q correction, GNS §3) rides back with
    the level layout; the consume phase multiplies each level's
    features by it so TreeSAGE's masked means stay unbiased."""
    from .dist_sampler import (_dist_one_hop, _slack_cap,
                               dist_gather_multi)
    from .exchange import dest_histogram
    from .partition_book import range_owner_fn
    slack = self.sampler.exchange_slack
    layout = self.sampler.exchange_layout
    gns = gns_bits is not None
    boost = self.sampler.gns_boost if gns else None
    levels, frontier = [seeds], seeds
    w_levels = [jnp.ones(seeds.shape, jnp.float32)]
    fstats = jnp.zeros((3,), jnp.int32)
    book_spec = self.sampler.book_spec   # trace-time routing constant
    # src->dst range attribution (ISSUE 16/20): the fused tree path
    # must tick the SAME [2P + 1] tail as the dedup sampler — this was
    # the dead feature counter (frontier_ids populated, feature_ids
    # all-zero) on every tiered envelope epoch
    attr_owner = range_owner_fn(bounds)
    attr_fr = jnp.zeros((self.num_parts,), jnp.int32)
    for h, k in enumerate(self.fanouts):
      attr_fr = attr_fr + dest_histogram(frontier, attr_owner,
                                         self.num_parts)
      nbrs, mask, _, hw, st = _dist_one_hop(
          indptr_s, indices_s, None, bounds, frontier, int(k),
          jax.random.fold_in(key, h), self.axis, self.num_parts,
          False, sort_locality=False,
          exchange_capacity=_slack_cap(frontier.shape[0],
                                       self.num_parts, slack, layout),
          gns_bits=gns_bits, gns_boost=boost, book_spec=book_spec)
      fstats = fstats + jnp.stack(st)
      nxt = jnp.where(mask, nbrs, -1).reshape(-1)
      levels.append(nxt)
      if gns:
        w_levels.append((w_levels[-1][:, None] * hw).reshape(-1))
      frontier = nxt
    all_ids = jnp.concatenate(levels)
    (feats, labels), gst = dist_gather_multi(
        (fshards_s, lshards_s), bounds, all_ids, self.axis,
        self.num_parts,
        exchange_capacity=_slack_cap(all_ids.shape[0], self.num_parts,
                                     slack, layout),
        hot_counts=hcounts, book_spec=book_spec)
    attr_ft = dest_histogram(all_ids, attr_owner, self.num_parts)
    stats7 = jnp.concatenate(
        [fstats, jnp.stack(gst), jnp.zeros((1,), jnp.int32),
         attr_fr, attr_ft, jnp.zeros((1,), jnp.int32)])
    hop_counts = jnp.stack(
        [jnp.sum((lvl >= 0).astype(jnp.int32)) for lvl in levels])
    y = labels[:self.batch_size]
    if concat:
      out = (all_ids, feats, y, stats7, hop_counts)
      return out + (jnp.concatenate(w_levels),) if gns else out
    sizes = [lvl.shape[0] for lvl in levels]
    xs, off = [], 0
    for s in sizes:
      xs.append(feats[off:off + s])
      off += s
    masks = [lvl >= 0 for lvl in levels]
    return xs, masks, y, stats7, hop_counts

  def _eval_tail(self, params, xs, masks, y, valid):
    axis = self.axis
    logits = self._eval_apply(params, xs, masks)
    correct = jax.lax.psum(
        jnp.sum((jnp.argmax(logits, -1) == y) & valid), axis)
    total = jax.lax.psum(jnp.sum(valid), axis)
    return correct, total

  def _train_tail(self, state, xs, masks, y, valid, hop_counts):
    """The DP update half of the tree step — shared by the fused
    single-program path and the tiered consume scan."""
    axis, b = self.axis, self.batch_size
    hop_g = jax.lax.psum(hop_counts, axis)         # global [H+1]

    def loss_fn(params):
      logits = self._apply(params, xs, masks)
      vf = valid.astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(
          logits, y.astype(jnp.int32))
      return (ce * vf).sum() / jnp.maximum(vf.sum(), 1.0), logits

    (loss, logits), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    updates, opt_state = self.tx.update(grads, state.opt_state,
                                        state.params)
    params = optax.apply_updates(state.params, updates)
    new_state = TrainState(params, opt_state, state.step + 1)
    any_valid = jax.lax.psum(jnp.sum(valid), axis) > 0
    state = jax.tree_util.tree_map(
        lambda new, old: jnp.where(any_valid, new, old),
        new_state, state)
    correct = jax.lax.psum(
        jnp.sum((jnp.argmax(logits[:b], -1) == y) & valid), axis)
    return (state, loss, correct, jax.lax.psum(jnp.sum(valid), axis),
            hop_g)

  def _make_sharded(self, train: bool):
    from .shard_map_compat import shard_map
    axis = self.axis

    def per_device(state_or_params, seeds_s, key, indptr_s, indices_s,
                   bounds, fshards_s, lshards_s):
      seeds = seeds_s[0]
      xs, masks, y, stats7, hop_counts = self._expand_collect(
          seeds, key, indptr_s[0], indices_s[0], bounds, fshards_s[0],
          lshards_s[0])
      valid = seeds >= 0
      if not train:
        correct, total = self._eval_tail(state_or_params, xs, masks, y,
                                         valid)
        return correct, total, stats7[None]
      state, loss, correct, n_valid, hop_g = self._train_tail(
          state_or_params, xs, masks, y, valid, hop_counts)
      return state, loss, correct, n_valid, stats7[None], hop_g

    ax = self.axis
    if train:
      out_specs = (P(), P(), P(), P(), P(ax), P())
    else:
      out_specs = (P(), P(), P(ax))
    return shard_map(
        per_device, mesh=self.mesh,
        in_specs=(P(), P(ax), P(), P(ax), P(ax), P(), P(ax), P(ax)),
        out_specs=out_specs)

  # -- tiered fused epochs: collect / consume twins -------------------------

  def _collect_step_bytes(self) -> int:
    nf = self.ds.node_features
    return (self.num_parts * sum(self._level_sizes()) * nf.feature_dim
            * np.dtype(nf.shards.dtype).itemsize)

  def _make_collect_sharded(self):
    """Per-device tree expansion + hot-masked feature/label exchange,
    returning the CONCATENATED level ids + features (the overlay
    machinery's addressing) instead of the split lists.  Under GNS the
    program takes the replicated cached-set bitmask and also returns
    the cumulative per-slot importance weights."""
    from .shard_map_compat import shard_map
    ax = self.axis
    gns = self.sampler.gns

    def per_device(seeds_s, key, indptr_s, indices_s, bounds,
                   fshards_s, lshards_s, hcounts, *rest):
      seeds = seeds_s[0]
      out = self._expand_collect(
          seeds, key, indptr_s[0], indices_s[0], bounds, fshards_s[0],
          lshards_s[0], hcounts=hcounts, concat=True,
          gns_bits=rest[0] if gns else None)
      all_ids, feats, y, stats7, hop_counts = out[:5]
      lead = (all_ids[None], feats[None], y[None], stats7[None],
              hop_counts[None])
      return lead + (out[5][None],) if gns else lead

    n_out = 6 if gns else 5
    return shard_map(
        per_device, mesh=self.mesh,
        in_specs=(P(ax), P(), P(ax), P(ax), P(), P(ax), P(ax), P())
        + ((P(),) if gns else ()),
        out_specs=tuple(P(ax) for _ in range(n_out)))

  def _make_consume_sharded(self, train: bool):
    """Per-device split of the corrected level features + the train or
    eval tail (the back half of `_make_sharded`'s per_device).  Under
    GNS each level's features are scaled by the cumulative importance
    weights BEFORE the model's masked means — the tree form of the
    1/q correction (weight 1 everywhere when the boost never bit)."""
    from .shard_map_compat import shard_map
    ax = self.axis
    sizes = self._level_sizes()
    gns = self.sampler.gns

    def per_device(state_or_params, seeds_s, ids_s, feats_s, y_s,
                   hop_s, *rest):
      seeds = seeds_s[0]
      ids, feats, y = ids_s[0], feats_s[0], y_s[0]
      w = rest[0][0] if gns else None
      xs, masks, off = [], [], 0
      for s in sizes:
        lvl = feats[off:off + s]
        if gns:
          lvl = lvl * w[off:off + s][:, None].astype(lvl.dtype)
        xs.append(lvl)
        masks.append(ids[off:off + s] >= 0)
        off += s
      valid = seeds >= 0
      if not train:
        correct, total = self._eval_tail(state_or_params, xs, masks, y,
                                         valid)
        return correct, total
      return self._train_tail(state_or_params, xs, masks, y, valid,
                              hop_s[0])

    if train:
      out_specs = (P(), P(), P(), P(), P())
    else:
      out_specs = (P(), P())
    return shard_map(
        per_device, mesh=self.mesh,
        in_specs=(P(), P(ax), P(ax), P(ax), P(ax), P(ax))
        + ((P(ax),) if gns else ()),
        out_specs=out_specs)

  def _collect_fn(self, seeds_all: jax.Array, keys: jax.Array,
                  arrs: dict):
    gns = 'gns' in arrs

    def body(_, xs):
      key_i, seeds = xs
      outs = self._sharded_collect(
          seeds, key_i, arrs['indptr'], arrs['indices'],
          arrs['bounds'], arrs['fshards'], arrs['lshards'],
          arrs['hcounts'], *((arrs['gns'],) if gns else ()))
      ids, feats, y, stats, hops = outs[:5]
      d = dict(seeds=seeds, ids=ids, feats=feats, y=y, hops=hops)
      if gns:
        d['w'] = outs[5]
      return 0, (d, stats)

    _, (data, stats) = jax.lax.scan(body, 0, (keys, seeds_all))
    return data, stats

  def _overlay_chunk(self, data):
    data['feats'] = self._overlay_stacked(data['feats'], data['ids'])
    return data

  def _consume_args(self, d):
    return ((d['w'],) if 'w' in d else ())

  def _train_fn(self, state: TrainState, data):
    def body(state, d):
      state, loss, correct, n_valid, hop_g = self._sharded_consume(
          state, d['seeds'], d['ids'], d['feats'], d['y'], d['hops'],
          *self._consume_args(d))
      return state, (loss, correct, n_valid, hop_g)

    state, (losses, corrects, valids, hops) = jax.lax.scan(
        body, state, data)
    return (state, losses, jnp.sum(corrects), jnp.sum(valids),
            jnp.sum(hops, axis=0))

  def _eval_consume_fn(self, params, data):
    def body(carry, d):
      correct, total = self._sharded_consume_eval(
          params, d['seeds'], d['ids'], d['feats'], d['y'], d['hops'],
          *self._consume_args(d))
      return carry, (correct, total)

    _, (c, t) = jax.lax.scan(body, 0, data)
    return jnp.sum(c), jnp.sum(t)

  # -- the one program ------------------------------------------------------

  def _epoch_fn(self, state: TrainState, seeds_all: jax.Array,
                key: jax.Array, arrs: dict):
    def body(state, xs_in):
      i, seeds = xs_in
      state, loss, correct, valid, stats, hop = self._sharded_step(
          state, seeds, jax.random.fold_in(key, i), arrs['indptr'],
          arrs['indices'], arrs['bounds'], arrs['fshards'],
          arrs['lshards'])
      return state, (loss, correct, valid, stats, hop)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    state, (losses, corrects, valids, stats, hops) = jax.lax.scan(
        body, state, (steps, seeds_all))
    return (state, losses, jnp.sum(corrects), jnp.sum(valids),
            jnp.sum(stats, axis=0), jnp.sum(hops, axis=0))

  def _eval_fn(self, params, seeds_all: jax.Array, key: jax.Array,
               arrs: dict):
    def body(carry, xs_in):
      i, seeds = xs_in
      correct, total, stats = self._sharded_eval(
          params, seeds, jax.random.fold_in(key, i), arrs['indptr'],
          arrs['indices'], arrs['bounds'], arrs['fshards'],
          arrs['lshards'])
      return carry, (correct, total, stats)

    steps = jnp.arange(seeds_all.shape[0], dtype=jnp.int32)
    _, (correct, total, stats) = jax.lax.scan(
        body, 0, (steps, seeds_all))
    return jnp.sum(correct), jnp.sum(total), jnp.sum(stats, axis=0)

  # run()/evaluate() come from `_MeshEpochDriver`


class FusedDistLinkEpoch(_MeshEpochDriver):
  """One-program data-parallel LINK-PREDICTION epochs on the mesh.

  The link member of the fused mesh family: the scan body runs the
  full distributed link step (per-device seed edges + collective
  strict negatives against the GLOBAL sharded graph + endpoint
  expansion + feature collection — the same program
  `DistLinkNeighborSampler` dispatches per batch) followed by the DP
  unsupervised update (`make_dp_unsupervised_step`: binary sigmoid or
  max-margin triplet link loss by the metadata keys, pmean gradients).

  Same constraints as `FusedDistEpoch`: a static exchange slack;
  tiered stores run as chunked tiered fused epochs (module
  docstring).

  Args:
    dataset: `DistDataset` (sharded layout).
    num_neighbors: per-hop fanouts for the endpoint expansion.
    edge_label_index: ``[2, E]`` (or ``(rows, cols)``) seed edges.
    apply_fn / tx: embedding model apply + optax transform.
    batch_size: PER-DEVICE seed-edge batch size.
    neg_sampling: ``'binary'`` / ``('triplet', amount)``.
    edge_label: optional labels (binary mode applies the reference's
      +1 shift via `pack_link_seeds`).
    remat: checkpoint the model forward (see `FusedDistEpoch`).
  """

  def __init__(self, dataset: DistDataset, num_neighbors,
               edge_label_index, apply_fn: Callable,
               tx: optax.GradientTransformation, batch_size: int,
               neg_sampling='binary', edge_label=None,
               mesh: Optional[Mesh] = None, axis: str = 'data',
               shuffle: bool = True, drop_last: bool = False,
               seed: int = 0, input_space: str = 'old',
               exchange_slack='auto', exchange_layout=None,
               remat: bool = False,
               fast_compile: bool = False, gns=None):
    from ..loader.node_loader import SeedBatcher
    if dataset.node_features is None:
      raise ValueError('FusedDistLinkEpoch needs node features')
    if exchange_slack == 'adaptive':
      raise ValueError(
          "exchange_slack='adaptive' retunes between batches on the "
          "host; FusedDistLinkEpoch takes a static slack ('auto' or "
          'a number) — or use DistLinkNeighborLoader')
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistLinkNeighborSampler(
        dataset, num_neighbors, neg_sampling=neg_sampling, mesh=mesh,
        axis=axis, collect_features=True, seed=seed,
        exchange_slack=slack, exchange_layout=exchange_layout, gns=gns)
    self.ds = dataset
    self.mesh = self.sampler.mesh
    self.axis = axis
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)

    self.pairs = pack_link_seeds_relabeled(        # [E, 2|3]
        edge_label_index, edge_label, self.sampler.neg_mode, dataset,
        input_space)
    self._batcher = SeedBatcher(self.pairs,
                                self.batch_size * self.num_parts,
                                shuffle, drop_last, seed)
    self._base_key = jax.random.key(seed)
    self._epoch_idx = 0
    step_apply = jax.checkpoint(apply_fn) if remat else apply_fn
    self._dp_step = make_dp_unsupervised_step(step_apply, tx, self.mesh,
                                              axis)
    self._dist_step = self.sampler.step_for_pairs(
        self.batch_size, self.pairs.shape[1])
    self._resolve_dist_step = lambda: self.sampler.step_for_pairs(
        self.batch_size, self.pairs.shape[1])
    self._apply = apply_fn            # un-remat'd: evaluate() is fwd-only
    self._compiled = _uncached_jit(       # see FusedDistEpoch note
        self._epoch_fn, donate_argnums=(0,), fast_compile=fast_compile)
    self._compiled_eval = _uncached_jit(self._auc_fn,
                                        fast_compile=fast_compile)
    self._tiered = dataset.node_features.is_tiered
    if self._tiered:
      self._compiled_collect = _uncached_jit(self._collect_fn,
                                             fast_compile=fast_compile)
      self._compiled_train = _uncached_jit(self._train_fn,
                                           donate_argnums=(0,),
                                           fast_compile=fast_compile)
      self._compiled_auc_consume = _uncached_jit(
          self._auc_consume_fn, fast_compile=fast_compile)

  def __len__(self) -> int:
    return len(self._batcher)

  # -- the one program ------------------------------------------------------

  def _epoch_fn(self, state: TrainState, pairs_all: jax.Array,
                key: jax.Array, arrs: dict):
    """``[S, P, B, 2|3]`` seed-edge batches → S fused
    negatives+exchange+collect+train steps."""

    def body(state, xs):
      i, pairs = xs
      batch, stats = self._link_batch(pairs, jax.random.fold_in(key, i),
                                      arrs)
      state, loss = self._dp_step(state, batch)
      valid = jnp.sum((pairs[:, :, 0] >= 0) & (pairs[:, :, 1] >= 0))
      return state, (loss, valid, stats)

    steps = jnp.arange(pairs_all.shape[0], dtype=jnp.int32)
    state, (losses, valids, stats) = jax.lax.scan(
        body, state, (steps, pairs_all))
    return state, losses, jnp.sum(valids), jnp.sum(stats, axis=0)

  def _link_batch(self, pairs: jax.Array, key_i: jax.Array, arrs: dict):
    """One fused distributed link sample+collect (negatives +
    endpoint expansion + features): shared front half of the train
    and eval scan bodies."""
    from ..loader.transform import Batch
    extra = (arrs['gns'],) if 'gns' in arrs else ()
    outs = self._dist_step(
        arrs['indptr'], arrs['indices'], arrs['eids'],
        arrs['bounds'], pairs, arrs['fshards'], arrs['lshards'],
        arrs['cids'], arrs['crows'], arrs['efshards'],
        arrs['ebounds'], arrs['hcounts'], *extra, key_i)
    (nodes, _count, row, col, edge, seed_local, x, y, ef, nsn,
     stats) = outs[:11]
    ew = outs[11] if 'gns' in arrs else None
    (eli, elab, elab_mask, src_idx, dst_pos, dst_neg) = \
        outs[12:] if 'gns' in arrs else outs[11:]
    md = link_step_metadata(self.sampler.neg_mode, seed_local, eli,
                            elab, elab_mask, src_idx, dst_pos, dst_neg)
    if ew is not None:
      md['edge_weight'] = ew
    batch = Batch(
        x=x, y=y, edge_index=jnp.stack([row, col], axis=1),
        edge_attr=ef, node=nodes, node_mask=nodes >= 0,
        edge_mask=row >= 0, edge=edge, batch=pairs[:, :, 0],
        batch_size=self.batch_size, num_sampled_nodes=nsn, metadata=md)
    return batch, stats

  # -- tiered fused epochs (chunked collect/train twins) --------------------

  def _collect_step_bytes(self) -> int:
    exp_seeds, _ = self.sampler._expansion_seeds(self.batch_size)
    cap = self.sampler.node_capacity(exp_seeds)
    nf = self.ds.node_features
    return (self.num_parts * cap * nf.feature_dim
            * np.dtype(nf.shards.dtype).itemsize)

  def _collect_fn(self, pairs_all: jax.Array, keys: jax.Array,
                  arrs: dict):
    def body(_, xs):
      key_i, pairs = xs
      batch, stats = self._link_batch(pairs, key_i, arrs)
      return 0, (batch, stats)

    _, (batches, stats) = jax.lax.scan(body, 0, (keys, pairs_all))
    return batches, stats

  def _overlay_chunk(self, batches):
    batches.x = self._overlay_stacked(batches.x, batches.node)
    return batches

  def _train_fn(self, state: TrainState, batches):
    def body(state, batch):
      state, loss = self._dp_step(state, batch)
      # SeedBatcher pads whole rows, so a valid src implies the pair
      return state, (loss, jnp.sum(batch.batch >= 0))

    state, (losses, valids) = jax.lax.scan(body, state, batches)
    return state, losses, jnp.sum(valids)

  def _auc_consume_fn(self, params, batches):
    auc_step = self._make_auc_step()

    def body(carry, batch):
      wins, total = auc_step(params, batch)
      return carry, (wins, total)

    _, (wins, totals) = jax.lax.scan(body, 0, batches)
    return jnp.sum(wins), jnp.sum(totals)

  def _make_auc_step(self):
    """Per-device embedding + pairwise (pos > neg) win counts, psum'd
    over the mesh — shared by the single-program `_auc_fn` and the
    tiered `_auc_consume_fn`."""
    from .shard_map_compat import shard_map
    b, axis = self.batch_size, self.axis

    def per_device(params, batch):
      batch = jax.tree_util.tree_map(lambda v: v[0], batch)
      emb = self._apply(params, batch.x, batch.edge_index,
                        batch.edge_mask)
      eli = batch.metadata['edge_label_index']      # [2, b + nn]
      mask = batch.metadata['edge_label_mask']
      score = (emb[eli[0]] * emb[eli[1]]).sum(-1)
      ps, ns = score[:b], score[b:]
      pv, nv = mask[:b], mask[b:]
      pair_ok = pv[:, None] & nv[None, :]
      # float32 accumulation: int32 pair counts overflow past ~2k
      # products-scale batches
      wins = (jnp.sum((ps[:, None] > ns[None, :]) & pair_ok,
                      dtype=jnp.float32)
              + 0.5 * jnp.sum((ps[:, None] == ns[None, :]) & pair_ok,
                              dtype=jnp.float32))
      wins = jax.lax.psum(wins, axis)
      total = jax.lax.psum(jnp.sum(pair_ok, dtype=jnp.float32), axis)
      return wins, total

    return shard_map(per_device, mesh=self.mesh,
                     in_specs=(P(), P(self.axis)),
                     out_specs=(P(), P()))

  def _auc_fn(self, params, pairs_all: jax.Array, key: jax.Array,
              arrs: dict):
    """Scan body of `evaluate`: per batch, the full distributed link
    step (fresh strict negatives), per-device embedding + pairwise
    (pos > neg) win counts, psum'd over the mesh — the SPMD twin of
    `loader.fused.FusedLinkEpoch._auc_fn` (batched rank-sum AUC,
    per-device positive/negative blocks)."""
    auc_step = self._make_auc_step()

    def body(carry, xs):
      i, pairs = xs
      batch, stats = self._link_batch(pairs, jax.random.fold_in(key, i),
                                      arrs)
      wins, total = auc_step(params, batch)
      return carry, (wins, total, stats)

    steps = jnp.arange(pairs_all.shape[0], dtype=jnp.int32)
    _, (wins, totals, stats) = jax.lax.scan(body, 0, (steps, pairs_all))
    return jnp.sum(wins), jnp.sum(totals), jnp.sum(stats, axis=0)

  def evaluate(self, params, edge_label_index,
               input_space: str = 'old') -> float:
    """Held-out link AUC over ``edge_label_index`` as ONE SPMD scan
    program — the mesh twin of `loader.fused.FusedLinkEpoch.evaluate`
    (VERDICT r4 #5).  Binary negative-sampling mode only (triplet
    mode's per-src negatives make precision@rank the right metric)."""
    from ..loader.node_loader import SeedBatcher
    if self.sampler.neg_mode != 'binary':
      raise ValueError('evaluate() needs binary negative sampling')
    pairs = pack_link_seeds_relabeled(edge_label_index, None, 'binary',
                                      self.ds, input_space)
    if pairs.shape[0] == 0:
      raise ValueError('evaluate() got an empty split')
    # eval batches must carry the SAME pair width the compiled dist
    # step was built for
    if pairs.shape[1] != self.pairs.shape[1]:
      pad = np.ones((pairs.shape[0],
                     self.pairs.shape[1] - pairs.shape[1]), np.int64)
      pairs = np.concatenate([pairs, pad], axis=1)
    ev = SeedBatcher(pairs, self.batch_size * self.num_parts,
                     shuffle=False)
    stacked = np.stack(list(ev)).reshape(-1, self.num_parts,
                                         self.batch_size,
                                         pairs.shape[1])
    if self._tiered:
      key = self._eval_key()
      s = stacked.shape[0]
      chunk = self._cold_chunk_steps(s)
      wins = total = 0.0
      for c0, real, part, keys in self._tiered_chunks(stacked, key,
                                                      chunk):
        batches, stats = self._compiled_collect(
            self._put_batches(part), keys, self._chunk_arrs())
        self.sampler._accumulate_stats(jnp.sum(stats[:real], axis=0))
        batches = self._overlay_chunk(batches)
        w, t = self._compiled_auc_consume(params, batches)
        wins += float(w)
        total += float(t)
      return wins / max(total, 1.0)
    wins, total, stats = self._compiled_eval(
        params, self._put_batches(stacked), self._eval_key(),
        self._chunk_arrs())
    self.sampler._accumulate_stats(stats)
    return float(wins) / max(float(total), 1.0)

  # -- host driver ----------------------------------------------------------

  def _train_chunk(self, state, data):
    # link train has no accuracy and no hop gauges — adapt to the
    # shared _run_tiered 5-tuple (None accumulators stay None)
    state, ls, val = self._compiled_train(state, data)
    return state, ls, None, val, None

  def run(self, state: TrainState) -> Tuple[TrainState, 'EpochStats']:
    """One epoch; ``state`` must be mesh-replicated and is DONATED.
    ``stats.seeds`` counts valid seed EDGES; accuracy reads 0 (the
    unsupervised objective has no accuracy)."""
    from ..loader.fused import EpochStats
    from ..utils.profiling import step_annotation
    flat = np.stack(list(self._batcher))           # [S, P*B, 2|3]
    pairs = flat.reshape(-1, self.num_parts, self.batch_size,
                         flat.shape[-1])
    key = self._next_epoch_key()
    with step_annotation('fused_dist_link_epoch', self._epoch_idx):
      if self._tiered:
        # the shared chunked driver: snapshot seams, stall watchdog
        # AND degraded rollback — the link driver must honor the same
        # preemption contract as the node twins (link stats carry
        # valid-pair counts; no accuracy, no hop gauges)
        state, losses, _corr, valid, _hops = self._run_tiered(
            state, pairs, key)
        return state, EpochStats(losses, jnp.zeros((), jnp.int32),
                                 valid)
      state, losses, valid, stats = self._compiled(
          state, self._put_batches(pairs), key, self._chunk_arrs())
    self.sampler._accumulate_stats(stats)
    return state, EpochStats(losses, jnp.zeros((), jnp.int32), valid)
