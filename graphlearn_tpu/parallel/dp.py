"""Data-parallel training over a device mesh.

TPU-native replacement for the reference's DP story — vanilla
`torch.nn.parallel.DistributedDataParallel` + NCCL allreduce in its
examples (`examples/multi_gpu/train_sage_ogbn_papers100m.py:33-41`,
SURVEY §2.3.1).  Instead of per-process replicas + NCCL, one SPMD
program over a `jax.sharding.Mesh`: params replicated, per-device batch
shards, gradients averaged with `psum` over the ``data`` axis riding
ICI.  The host side feeds stacked per-device batches (leading axis =
mesh size), the cross-device part is entirely XLA collectives.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.train import TrainState, supervised_loss


def make_mesh(n_devices: Optional[int] = None, axis: str = 'data') -> Mesh:
  """1-D device mesh over the first ``n_devices`` devices."""
  devs = jax.devices()[:n_devices] if n_devices else jax.devices()
  return Mesh(np.asarray(devs), (axis,))


def stack_batches(batches: Sequence[Any]):
  """Stack per-device Batch pytrees along a new leading device axis."""
  return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *batches)


def replicate(tree, mesh: Mesh):
  """Place a pytree fully replicated on the mesh (params / opt state)."""
  return jax.device_put(tree, NamedSharding(mesh, P()))


def local_batch_piece(batch, num_parts: int):
  """One device's slice of a ``[P, ...]``-stacked batch pytree — the
  single-device template `create_train_state` wants for param init
  under the mesh engines.  Reads only ADDRESSABLE shards, so it works
  on multi-host meshes where ``np.asarray(global_array)`` would not;
  leaves without the leading device axis pass through."""
  def pick(v):
    if (isinstance(v, jax.Array) and v.ndim
        and v.shape[0] == num_parts):
      return np.asarray(v.addressable_shards[0].data)[0]
    return v
  return jax.tree_util.tree_map(pick, batch)


def shard_stacked(tree, mesh: Mesh, axis: str = 'data'):
  """Place a stacked (leading device axis) pytree sharded over ``axis``."""
  return jax.device_put(tree, NamedSharding(mesh, P(axis)))


def make_dp_supervised_step(apply_fn: Callable,
                            tx: optax.GradientTransformation,
                            batch_size: int, mesh: Mesh,
                            axis: str = 'data'):
  """Build the SPMD data-parallel step.

  Returns ``step(state, stacked_batch) -> (state, mean_loss, correct)``
  where ``stacked_batch`` has a leading axis equal to the mesh size.
  Gradient averaging = ``jax.lax.pmean`` over the mesh axis — the XLA
  collective that replaces the reference's NCCL allreduce.
  """
  from .shard_map_compat import shard_map

  def per_device(state: TrainState, batch):
    # batch leaves carry a leading singleton shard axis; drop it.
    batch = jax.tree_util.tree_map(lambda x: x[0], batch)

    def loss_fn(params):
      from ..models.train import _apply_with_weights
      # the example SAGE path: GNS batches carry metadata
      # ['edge_weight'] (PR 10 1/q weights) — threaded into the
      # aggregation so GNS-on DP training is unbiased at the model
      logits = _apply_with_weights(apply_fn, params, batch)
      loss = supervised_loss(logits, batch.y, batch.batch, batch_size)
      return loss, logits

    (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params)
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    valid = batch.batch >= 0
    pred = jnp.argmax(logits[:batch_size], axis=-1)
    correct = jax.lax.psum(
        jnp.sum((pred == batch.y[:batch_size]) & valid), axis)
    return TrainState(params, opt_state, state.step + 1), loss, correct

  sharded = shard_map(
      per_device, mesh=mesh,
      in_specs=(P(), P(axis)),
      out_specs=(P(), P(), P()))

  @jax.jit
  def step(state, stacked_batch):
    new_state, loss, correct = sharded(state, stacked_batch)
    return new_state, loss, correct

  return step


def make_dp_eval_step(apply_fn: Callable, batch_size: int, mesh: Mesh,
                      axis: str = 'data'):
  """SPMD evaluation step: ``(params, stacked_batch) -> (correct,
  total)``, both psum-reduced over the mesh axis — the eval
  counterpart of `make_dp_supervised_step` (mirrors the single-chip
  `models.train.make_extracted_eval_step` contract)."""
  from .shard_map_compat import shard_map

  def per_device(params, batch):
    batch = jax.tree_util.tree_map(lambda x: x[0], batch)
    logits = apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)
    valid = batch.batch >= 0
    pred = jnp.argmax(logits[:batch_size], axis=-1)
    correct = jax.lax.psum(
        jnp.sum((pred == batch.y[:batch_size]) & valid), axis)
    total = jax.lax.psum(jnp.sum(valid), axis)
    return correct, total

  return shard_map(per_device, mesh=mesh, in_specs=(P(), P(axis)),
                   out_specs=(P(), P()))


def make_dp_unsupervised_step(apply_fn: Callable,
                              tx: optax.GradientTransformation,
                              mesh: Mesh, axis: str = 'data'):
  """SPMD data-parallel UNSUPERVISED (link-loss) step for stacked
  link batches (`DistLinkNeighborLoader` output): per-device link loss
  (binary sigmoid or max-margin triplet, picked by the batch's
  metadata keys) on its own positives/negatives, pmean-averaged
  gradients — the distributed form of the reference's unsupervised
  SAGE objective (`examples/graph_sage_unsup_ppi.py:41-45`)."""
  from ..models.train import link_loss_from_metadata
  from .shard_map_compat import shard_map

  def per_device(state: TrainState, batch):
    batch = jax.tree_util.tree_map(lambda x: x[0], batch)

    def loss_fn(params):
      emb = apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)
      return link_loss_from_metadata(emb, batch.metadata)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    grads = jax.lax.pmean(grads, axis)
    loss = jax.lax.pmean(loss, axis)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss

  sharded = shard_map(
      per_device, mesh=mesh,
      in_specs=(P(), P(axis)),
      out_specs=(P(), P()))

  @jax.jit
  def step(state, stacked_batch):
    return sharded(state, stacked_batch)

  return step


class DataParallelLoader:
  """Wraps a single-chip loader, emitting mesh-size stacks of batches.

  The host-side analog of the reference's per-rank seed splits
  (`dist_sampling_producer.py:249-260`): one host drives all local
  devices; each step consumes ``mesh_size`` consecutive batches.
  """

  def __init__(self, loader, mesh_size: int):
    self.loader = loader
    self.mesh_size = int(mesh_size)

  def __len__(self):
    return len(self.loader) // self.mesh_size

  def __iter__(self):
    it = iter(self.loader)
    while True:
      group = []
      try:
        for _ in range(self.mesh_size):
          group.append(next(it))
      except StopIteration:
        return
      yield stack_batches(group)
