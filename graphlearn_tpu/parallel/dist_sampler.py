"""Distributed neighbor sampling + feature collection over ICI.

TPU-native replacement for the reference's distributed engine
(`distributed/dist_neighbor_sampler.py:88-673` — asyncio RPC fan-out
per hop, `RpcSamplingCallee`, `stitch_sample_results`;
`distributed/dist_feature.py:134-269` — rpc feature fan-out + stitch).

The whole per-batch pipeline is ONE SPMD program under `shard_map`:

  hop:  owner = searchsorted(bounds, frontier)        (partition book)
        send buckets --all_to_all-->  peers           (seed exchange)
        local sample on owned CSR shard               (XLA, no host)
        results --all_to_all--> requesters            (reply)
        gather back to request order                  (the "stitch")
        dedup/relabel into the device's node table    (inducer)

  feat: same exchange pattern against feature shards.

The reference's pull-based variable-size RPC becomes fixed-capacity
collectives: each hop's exchange buffer is ``[P, F]`` where ``F`` is
that hop's static frontier capacity — padding waste instead of RPC
latency, the standard TPU trade.  Per-device batches make this data
parallel at the same time: device d samples ITS seed batch while
serving its partition to peers — what the reference needs a sampling
subprocess pool + event loop for (`dist_sampling_producer.py`).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..loader.prefetch import PrefetchingLoader
from ..ops.negative import edge_in_csr
from ..ops.neighbor import sample_one_hop
from ..ops.pallas_sample import sample_one_hop_auto
from ..ops.unique import init_node, induce_next
from ..utils.padding import INVALID_ID, max_sampled_nodes, round_up
from .dist_data import DistDataset
from .exchange import (MIN_EXCHANGE_CAP, capacity_spec, dest_histogram,
                       plan_exchange, resolve_layout)
from .partition_book import (book_owner_fn, edge_book_owner_fn,
                             edge_local_rows, edge_owner_fn,
                             hot_split_host, range_owner_fn)

#: default per-destination exchange capacity, as a multiple of the
#: balanced share (frontier / P).  2.0 tolerates 2x ownership skew
#: while shrinking every all_to_all buffer by P/2 — the right trade
#: for SHUFFLED seeds (near-balanced buckets); unshuffled loaders keep
#: exact (uncapped) exchanges since contiguous seed ranges can land
#: entirely on one owner.  See `bucket_by_owner` for drop semantics.
DEFAULT_EXCHANGE_SLACK = 2.0

#: layout of the per-step exchange-telemetry vector (stacked [P, 7]).
#: offered = valid ids entering an exchange; dropped = valid ids past
#: an owner's capacity (their neighbors/features are lost that hop);
#: slots = total send-buffer width (padding waste = 1 - offered/slots);
#: negative.lost = strict-negative slots whose every trial collided.
EXCHANGE_STAT_NAMES = (
    'frontier.offered', 'frontier.dropped', 'frontier.slots',
    'feature.offered', 'feature.dropped', 'feature.slots',
    'negative.lost')


def _exchange_stats(ids, slot_j, num_parts: int, cap: int):
  """(offered, dropped, slots) triple for one bucketed exchange —
  kept for direct `bucket_by_owner` users (the plan layouts in
  `parallel.exchange` carry their own triple)."""
  valid = ids >= 0
  offered = jnp.sum(valid.astype(jnp.int32))
  dropped = jnp.sum((valid & (slot_j < 0)).astype(jnp.int32))
  return offered, dropped, jnp.int32(num_parts * cap)


def bucket_by_owner(ids: jax.Array, owner: jax.Array, num_parts: int,
                    self_idx: jax.Array, capacity: Optional[int] = None):
  """Pack ids into per-owner rows of a ``[P, C]`` send buffer.

  Returns ``(send, slot_p, slot_j)``: ``send[p]`` holds the ids owned
  by partition ``p`` (-1 padded); original position ``i`` landed at
  ``send[slot_p[i], slot_j[i]]`` — the inverse map used to stitch
  replies back into request order (the collective-era
  `stitch_sample_results`, `csrc/cuda/stitch_sample_results.cu:27-100`).

  ``capacity`` bounds the per-destination row width ``C`` (default:
  the full frontier size ``F``).  With shuffled seeds each owner gets
  ~``F/P`` ids, so the uncapped buffer is ~``P``x padding — the
  SURVEY §7 "partition-aware capacity tuning" trade.  Ids past an
  owner's capacity are DROPPED: their ``slot_j`` is -1 and callers
  must mask their results invalid (a capped neighbor sample loses
  those neighbors — statistically a slight under-sample, never a
  wrong edge).
  """
  f = ids.shape[0]
  cap = f if capacity is None else min(int(capacity), f)
  valid = ids >= 0
  # invalid ids sort AFTER every real owner: they never consume a
  # capacity slot (parking them at self could evict valid self-owned
  # ids under a cap) and land in the dropped row of the scatter.
  owner = jnp.where(valid, owner, num_parts)
  perm = jnp.argsort(owner, stable=True)
  owner_s = owner[perm]
  ids_s = ids[perm]
  counts = jax.ops.segment_sum(jnp.ones((f,), jnp.int32), owner_s,
                               num_segments=num_parts + 1)
  offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                             jnp.cumsum(counts)[:-1]])
  rank = jnp.arange(f, dtype=jnp.int32) - offsets[owner_s]
  fits = (rank < cap) & (owner_s < num_parts)
  send = jnp.full((num_parts, cap), INVALID_ID, ids.dtype)
  # non-fitting entries scatter to row `num_parts`, dropped by XLA
  send = send.at[jnp.where(fits, owner_s, num_parts),
                 jnp.where(fits, rank, 0)].set(ids_s, mode='drop')
  slot_p = jnp.zeros((f,), jnp.int32).at[perm].set(
      jnp.where(owner_s < num_parts, owner_s, 0))
  slot_j = jnp.full((f,), -1, jnp.int32).at[perm].set(
      jnp.where(fits, rank, -1))
  return send, slot_p, slot_j


def bucket_with_payload(ids: jax.Array, payload: jax.Array,
                        owner: jax.Array, num_parts: int,
                        self_idx: jax.Array,
                        capacity: Optional[int] = None):
  """`bucket_by_owner` carrying a companion array: ``payload[i]`` lands
  in the same ``[p, j]`` slot as ``ids[i]`` (used to ship (row, col)
  pairs to the row's owner for distributed edge-existence tests)."""
  send, slot_p, slot_j = bucket_by_owner(ids, owner, num_parts, self_idx,
                                         capacity)
  cap = send.shape[1]
  kept = slot_j >= 0
  send_pl = jnp.full((num_parts, cap), INVALID_ID, payload.dtype)
  send_pl = send_pl.at[jnp.where(kept, slot_p, num_parts),
                       jnp.where(kept, slot_j, 0)].set(payload,
                                                       mode='drop')
  return send, send_pl, slot_p, slot_j


class _BookPlan:
  """Adopted-`PartitionBook` exchange: ids bucket to *(owner device,
  lane)* virtual destinations, ship as one ``[P, S*C]`` all_to_all,
  and each lane's receive buffer comes out laid exactly as the
  range's ORIGINAL owner would have seen it (per-range capacity,
  per-range positions) — the property that makes adopted epochs
  byte-identical to fault-free runs (`partition_book` module
  docstring).  Dense-style: post-adoption exchanges rebuild onto this
  plan whatever layout the identity book ran (documented in
  benchmarks/README "Elastic failover").
  """

  layout = 'book'

  def __init__(self, ids, bounds, spec, axis: str,
               capacity: Optional[int], payload=None,
               owner_mode: str = 'range'):
    from .exchange import ExchangeSpec, _bcast
    self._bcast = _bcast
    p, s = int(spec.num_parts), int(spec.num_lanes)
    f = ids.shape[0]
    if capacity is None:
      cap = f
    elif isinstance(capacity, ExchangeSpec):
      # per-RANGE capacity from the identity plan's slot budget: the
      # dense cap verbatim (the byte-identity arm — a range's lane
      # buffer must hold exactly what its original owner's dense row
      # held); compact/hier budgets flatten to slots/P rounded up,
      # floored like the dense rule
      if capacity.layout == 'dense':
        cap = min(int(capacity.capacity), f)
      else:
        cap = min(f, max(int(round_up(-(-capacity.slots // p), 8)),
                         MIN_EXCHANGE_CAP))
    else:
      cap = min(int(capacity), f)
    if owner_mode == 'mod':
      owner = edge_book_owner_fn(p, spec)(ids).astype(jnp.int32)
    else:
      owner = book_owner_fn(bounds, spec)(ids).astype(jnp.int32)
    self._p, self._s, self._cap, self._axis = p, s, cap, axis
    if payload is None:
      send, self.slot_p, self.slot_j = bucket_by_owner(
          ids, owner, p * s, None, cap)               # [P*S, cap]
      recv2 = jax.lax.all_to_all(send.reshape(p, s * cap), axis, 0, 0,
                                 tiled=True)          # [P_src, S*cap]
    else:
      send, send_pl, self.slot_p, self.slot_j = bucket_with_payload(
          ids, payload, owner, p * s, None, cap)
      both = jax.lax.all_to_all(
          jnp.concatenate([send.reshape(p, s * cap),
                           send_pl.reshape(p, s * cap)], axis=1),
          axis, 0, 0, tiled=True)
      recv2, recv_pl = both[:, :s * cap], both[:, s * cap:]
      self.recv_payload_lanes = recv_pl.reshape(p, s, cap).transpose(
          1, 0, 2).reshape(s, p * cap)
    #: lane j's receive buffer ``[P_src * cap]`` — bit-identical to
    #: the identity-book recv of the range assigned to (me, lane j)
    self.recv_lanes = recv2.reshape(p, s, cap).transpose(
        1, 0, 2).reshape(s, p * cap)
    self.kept = self.slot_j >= 0
    self.delivered = self.kept
    valid = ids >= 0
    offered = jnp.sum(valid.astype(jnp.int32))
    dropped = jnp.sum((valid & ~self.kept).astype(jnp.int32))
    self.stats = (offered, dropped, jnp.int32(p * s * cap))
    #: requester index per lane-recv row (the per-requester GNS mask
    #: needs the source device of every received frontier id)
    self.req_of_lane_recv = jnp.repeat(
        jnp.arange(p, dtype=jnp.int32), cap)

  def reply(self, values_lanes, fill=0):
    """``[S, P*cap, ...]`` per-lane owner-side values -> ``[F, ...]``
    in request order; un-kept positions get ``fill``."""
    p, s, cap = self._p, self._s, self._cap
    trail = values_lanes.shape[2:]
    v = values_lanes.reshape((s, p, cap) + trail)
    v = jnp.moveaxis(v, 0, 1).reshape((p, s * cap) + trail)
    back = jax.lax.all_to_all(v, self._axis, 0, 0, tiled=True)
    flat = back.reshape((p * s, cap) + trail)
    out = flat[self.slot_p, jnp.where(self.kept, self.slot_j, 0)]
    return jnp.where(self._bcast(self.kept, out), out,
                     jnp.asarray(fill, out.dtype))


def dist_edge_exists(indptr_loc, indices_loc, bounds, rows, cols,
                     axis: str, num_parts: int,
                     exchange_capacity: Optional[int] = None,
                     book_spec=None):
  """Distributed membership test over the range-sharded CSR: is
  ``(rows[i], cols[i])`` an edge of the global graph?

  Pairs travel to the row's owner (one all_to_all each way), which
  answers with its local `edge_in_csr` binary search — the collective
  analog of the reference's strict-rejection check
  (`csrc/cuda/random_negative_sampler.cu:37-54`) for graphs larger
  than one chip.  Pairs dropped by ``exchange_capacity`` overflow
  report True (conservatively "exists", so they are never used as
  strict negatives).
  """
  my_idx = jax.lax.axis_index(axis)
  if book_spec is not None:
    plan = _BookPlan(rows, bounds, book_spec, axis, exchange_capacity,
                     payload=cols)
    slot_ranges = jnp.asarray(book_spec.slot_ranges, jnp.int32)
    lanes_ex = []
    for j in range(book_spec.num_lanes):
      r_j = jnp.clip(slot_ranges[my_idx, j], 0, num_parts - 1)
      flat_r = plan.recv_lanes[j]
      local_r = jnp.where(flat_r >= 0, flat_r - bounds[r_j],
                          INVALID_ID).astype(jnp.int32)
      lanes_ex.append(edge_in_csr(
          indptr_loc[j], indices_loc[j], local_r,
          plan.recv_payload_lanes[j].astype(jnp.int32)))
    return plan.reply(jnp.stack(lanes_ex), fill=True)
  my_start = bounds[my_idx]
  owner_fn = range_owner_fn(bounds)
  plan = plan_exchange(rows, owner_fn, num_parts, axis,
                       exchange_capacity, payload=cols)
  flat_r = plan.recv
  local_r = jnp.where(flat_r >= 0, flat_r - my_start,
                      INVALID_ID).astype(jnp.int32)
  ex = edge_in_csr(indptr_loc, indices_loc, local_r,
                   plan.recv_payload.astype(jnp.int32))
  # undelivered pairs fill True ("exists", so never a strict negative)
  return plan.reply(ex, fill=True)


NEG_TRIALS = 5     # redraw attempts per strict-negative slot


def dist_sample_negative(indptr_loc, indices_loc, bounds,
                         num_rows: int, num_cols: int, req_num: int,
                         key, axis: str, num_parts: int,
                         trials: int = NEG_TRIALS,
                         exchange_capacity: Optional[int] = None,
                         rows_fixed: Optional[jax.Array] = None,
                         book_spec=None):
  """``req_num`` strict negative pairs over the sharded graph
  (collective analog of `ops.negative.sample_negative`): trials-stacked
  draws, ONE existence exchange for all trials, first-non-edge pick.
  Returns ``(rows, cols, ok)`` — ``ok`` False marks slots where every
  trial hit an existing edge (the padding fallback pair may be a REAL
  edge; consumers must mask it out of the negative label set).
  ``rows_fixed`` pins the row of each slot (triplet mode's per-source
  negatives)."""
  kr, kc = jax.random.split(key)
  if rows_fixed is None:
    rows = jax.random.randint(kr, (trials, req_num), 0, num_rows,
                              dtype=jnp.int32)
  else:
    rows = jnp.broadcast_to(rows_fixed[None, :], (trials, req_num))
  cols = jax.random.randint(kc, (trials, req_num), 0, num_cols,
                            dtype=jnp.int32)
  exists = dist_edge_exists(
      indptr_loc, indices_loc, bounds, rows.reshape(-1),
      cols.reshape(-1), axis, num_parts,
      exchange_capacity, book_spec=book_spec).reshape(trials, req_num)
  ok = ~exists
  any_ok = jnp.any(ok, axis=0)
  pick = jnp.where(any_ok, jnp.argmax(ok, axis=0), trials - 1)
  slot = jnp.arange(req_num)
  return rows[pick, slot], cols[pick, slot], any_ok


def _dist_one_hop_book(indptr_l, indices_l, eids_l, bounds, frontier,
                       k: int, key, axis: str, num_parts: int,
                       with_edge: bool, book_spec,
                       sort_locality: bool = True,
                       exchange_capacity: Optional[int] = None,
                       gns_bits=None,
                       gns_boost: Optional[float] = None):
  """Adopted-book hop: route per *(owner, lane)*, sample per RANGE.

  Each lane's receive buffer and sampling key are keyed by the range
  (``fold_in(key, range)``, not the device index), so an adopted
  shard's draws are bit-identical to what its original owner would
  have produced — the byte-identity half of the exact-completion
  contract.  Local arrays carry a leading lane axis (``[S, ...]``).
  """
  my_idx = jax.lax.axis_index(axis)
  plan = _BookPlan(frontier, bounds, book_spec, axis,
                   exchange_capacity)
  slot_ranges = jnp.asarray(book_spec.slot_ranges, jnp.int32)
  outs_n, outs_m, outs_e, outs_w = [], [], [], []
  for j in range(book_spec.num_lanes):
    r_j = jnp.clip(slot_ranges[my_idx, j], 0, num_parts - 1)
    flat = plan.recv_lanes[j]
    local = jnp.where(flat >= 0, flat - bounds[r_j],
                      INVALID_ID).astype(jnp.int32)
    lane_key = jax.random.fold_in(key, r_j)
    # sample_one_hop_auto resolves the GLT_PALLAS_SAMPLE dispatch at
    # trace time (value-identical either way — the gns.bias build-
    # time-event precedent); the dedup bits tuple flows as a pytree
    if gns_bits is not None:
      from ..ops.gns import is_per_requester
      res = sample_one_hop_auto(
          indptr_l[j], indices_l[j], local, k, lane_key,
          eids_l[j] if eids_l is not None else None,
          bits=gns_bits, boost=float(gns_boost),
          req=(plan.req_of_lane_recv if is_per_requester(gns_bits)
               else None),
          with_edge_ids=with_edge, sort_locality=sort_locality)
    else:
      res = sample_one_hop_auto(
          indptr_l[j], indices_l[j], local, k, lane_key,
          eids_l[j] if eids_l is not None else None,
          with_edge_ids=with_edge, sort_locality=sort_locality)
    outs_n.append(res.nbrs)
    outs_m.append(res.mask)
    if with_edge:
      outs_e.append(res.eids)
    if res.weights is not None:
      outs_w.append(res.weights)
  out_nbrs = plan.reply(jnp.stack(outs_n), fill=INVALID_ID)
  out_mask = plan.reply(jnp.stack(outs_m), fill=False)
  out_eids = (plan.reply(jnp.stack(outs_e), fill=INVALID_ID)
              if with_edge else None)
  out_w = (plan.reply(jnp.stack(outs_w), fill=0.0)
           if outs_w else None)
  return out_nbrs, out_mask, out_eids, out_w, plan.stats


def _dist_one_hop(indptr_loc, indices_loc, eids_loc, bounds, frontier,
                  k: int, key, axis: str, num_parts: int,
                  with_edge: bool, sort_locality: bool = True,
                  exchange_capacity: Optional[int] = None,
                  gns_bits=None, gns_boost: Optional[float] = None,
                  book_spec=None):
  """One distributed hop for this device's ``frontier`` ids.

  ``exchange_capacity`` caps the per-destination exchange width
  (default: the full frontier — ~P x padding with balanced buckets);
  overflowed frontier entries sample nothing this hop (masked).
  ``gns_bits`` (+ static ``gns_boost``) switches the owner-side
  kernel to cache-aware GNS sampling (`ops.gns.sample_one_hop_gns`):
  cached neighbors draw with boosted probability and per-edge
  importance weights ride the reply collective next to the ids.
  Returns ``(nbrs, mask, eids, weights, stats)`` — ``weights`` is
  None without GNS; ``stats`` is the (offered, dropped, slots)
  telemetry triple.
  """
  if book_spec is not None:
    return _dist_one_hop_book(
        indptr_loc, indices_loc, eids_loc, bounds, frontier, k, key,
        axis, num_parts, with_edge, book_spec,
        sort_locality=sort_locality,
        exchange_capacity=exchange_capacity, gns_bits=gns_bits,
        gns_boost=gns_boost)
  my_idx = jax.lax.axis_index(axis)
  my_start = bounds[my_idx]
  owner_fn = range_owner_fn(bounds)
  plan = plan_exchange(frontier, owner_fn, num_parts, axis,
                       exchange_capacity)
  flat = plan.recv
  local = jnp.where(flat >= 0, flat - my_start, INVALID_ID).astype(jnp.int32)
  if gns_bits is not None:
    from ..ops.gns import fallback_req_index, is_per_requester
    req = None
    if is_per_requester(gns_bits):
      # per-requester masks (ISSUE 15): the plan attributes each recv
      # row to its source device; layouts that cannot (hier's
      # two-stage re-bucketing) fall back to the hot-split-only row —
      # conservative (never over-boosts), still exactly corrected.
      # r19 carries the masks as the dedup (table, row_index) tuple —
      # O(distinct caches) VMEM instead of O(P) replication
      req = getattr(plan, 'requester_of_recv', None)
      if req is None:
        req = jnp.full(flat.shape, fallback_req_index(gns_bits),
                       jnp.int32)
    res = sample_one_hop_auto(indptr_loc, indices_loc, local, k,
                              jax.random.fold_in(key, my_idx),
                              eids_loc, bits=gns_bits,
                              boost=float(gns_boost), req=req,
                              with_edge_ids=with_edge,
                              sort_locality=sort_locality)
  else:
    res = sample_one_hop_auto(indptr_loc, indices_loc, local, k,
                              jax.random.fold_in(key, my_idx),
                              eids_loc, with_edge_ids=with_edge,
                              sort_locality=sort_locality)
  out_nbrs = plan.reply(res.nbrs, fill=INVALID_ID)
  out_mask = plan.reply(res.mask, fill=False)
  out_eids = plan.reply(res.eids, fill=INVALID_ID) if with_edge else None
  out_w = (plan.reply(res.weights, fill=0.0)
           if res.weights is not None else None)
  return out_nbrs, out_mask, out_eids, out_w, plan.stats


def _dist_gather_multi_book(shard_locs, bounds, ids, axis: str,
                            num_parts: int, book_spec,
                            exchange_capacity: Optional[int] = None,
                            shard_mode: str = 'range',
                            hot_counts: Optional[jax.Array] = None):
  """Adopted-book row gather: tables carry a leading lane axis
  (``[S, rows, ...]``); requests route per *(owner, lane)* and the
  hot-tier gate keys on the RANGE's hot count (placement is frozen;
  only the serving device moved)."""
  my_idx = jax.lax.axis_index(axis)
  plan = _BookPlan(ids, bounds, book_spec, axis, exchange_capacity,
                   owner_mode=shard_mode)
  slot_ranges = jnp.asarray(book_spec.slot_ranges, jnp.int32)
  ok = (ids >= 0) & plan.delivered
  outs = []
  for t, shard_l in enumerate(shard_locs):
    lane_rows = []
    for j in range(book_spec.num_lanes):
      flat = plan.recv_lanes[j]
      valid = flat >= 0
      r_j = jnp.clip(slot_ranges[my_idx, j], 0, num_parts - 1)
      if shard_mode == 'mod':
        local = jnp.where(valid, edge_local_rows(flat, num_parts), 0)
      else:
        local = jnp.where(valid, flat - bounds[r_j], 0)
      row_valid = valid
      if t == 0 and hot_counts is not None:
        row_valid = valid & (local < hot_counts[r_j])
      idx = jnp.clip(local, 0, shard_l.shape[1] - 1)
      rows = shard_l[j][idx]
      if rows.ndim == 1:
        rows = jnp.where(row_valid, rows, 0)
      else:
        rows = jnp.where(row_valid[:, None], rows, 0)
      lane_rows.append(rows)
    out = plan.reply(jnp.stack(lane_rows), fill=0)
    if out.ndim == 1:
      outs.append(jnp.where(ok, out, 0))
    else:
      outs.append(jnp.where(ok[:, None], out, 0))
  return tuple(outs), plan.stats


def dist_gather_multi(shard_locs, bounds, ids, axis: str, num_parts: int,
                      exchange_capacity: Optional[int] = None,
                      shard_mode: str = 'range',
                      hot_counts: Optional[jax.Array] = None,
                      book_spec=None):
  """Distributed row gather from several sharded tables that share an
  ownership scheme: ``out_t[i] = table_t[ids[i]]`` (the collective-era
  `DistFeature.async_get`, `distributed/dist_feature.py:134-269`).

  ``shard_mode='range'``: owner by ``searchsorted(bounds, id)`` (node
  tables); ``'mod'``: owner = ``id % P``, local row = ``id // P``
  (edge-feature tables, `build_dist_edge_feature` — strided so
  consecutive-id runs spread across owners under a capacity cap).

  The id bucketing and request all_to_all run ONCE for all tables —
  feature + label collection share a single exchange.  Invalid ids
  (-1) return zero rows; ids past ``exchange_capacity`` per owner
  return zero rows too (callers choosing a capacity accept that tail).
  ``hot_counts`` (``[P]``, tiered feature stores) marks the FIRST
  table HBM-partial: rows past the owner's hot count return zero and
  the caller overlays them from the host cold tier post-step.
  Returns ``(outs, stats)`` with the (offered, dropped, slots)
  telemetry triple.
  """
  if book_spec is not None:
    return _dist_gather_multi_book(
        shard_locs, bounds, ids, axis, num_parts, book_spec,
        exchange_capacity=exchange_capacity, shard_mode=shard_mode,
        hot_counts=hot_counts)
  my_idx = jax.lax.axis_index(axis)
  if shard_mode == 'mod':
    owner_fn = edge_owner_fn(num_parts)
  else:
    my_start = bounds[my_idx]
    owner_fn = range_owner_fn(bounds)
  plan = plan_exchange(ids, owner_fn, num_parts, axis,
                       exchange_capacity)
  flat = plan.recv
  valid = flat >= 0
  if shard_mode == 'mod':
    local = jnp.where(valid, edge_local_rows(flat, num_parts), 0)
  else:
    local = jnp.where(valid, flat - my_start, 0)
  ok = (ids >= 0) & plan.delivered
  outs = []
  for t, shard_loc in enumerate(shard_locs):
    row_valid = valid
    if t == 0 and hot_counts is not None:
      row_valid = valid & (local < hot_counts[my_idx])
    idx = jnp.clip(local, 0, shard_loc.shape[0] - 1)
    rows = shard_loc[idx]
    if rows.ndim == 1:
      rows = jnp.where(row_valid, rows, 0)
    else:
      rows = jnp.where(row_valid[:, None], rows, 0)
    out = plan.reply(rows, fill=0)
    if out.ndim == 1:
      outs.append(jnp.where(ok, out, 0))
    else:
      outs.append(jnp.where(ok[:, None], out, 0))
  return tuple(outs), plan.stats


def dist_gather(shard_loc, bounds, ids, axis: str, num_parts: int):
  """Single-table convenience wrapper over :func:`dist_gather_multi`."""
  (out,), _ = dist_gather_multi((shard_loc,), bounds, ids, axis,
                                num_parts)
  return out


def cache_overlay(gathered, ids, cache_ids_loc, cache_rows_loc):
  """Overlay this device's remote-hot CACHE rows on exchanged results
  — the collective-era `cat_feature_cache` trick
  (`distributed/dist_dataset.py:77-164`: cached remote rows count as
  local).

  In the RPC world a cache hit skips a network round-trip; under
  fixed-capacity collectives the all_to_all buffers do not shrink with
  the hit count, so the cache is applied as a post-exchange OVERLAY
  (identical bytes, ONE shared feature+label exchange) rather than a
  miss-only second exchange — its value here is serving hot rows from
  the freshest local copy and keeping the offline cache plan
  meaningful for RPC-backed deployments.

  ``cache_ids_loc``: ``[C]`` sorted ids (CACHE_PAD_ID padded);
  ``cache_rows_loc``: ``[C, D]``.
  """
  c = cache_ids_loc.shape[0]
  pos = jnp.clip(jnp.searchsorted(cache_ids_loc, ids), 0, c - 1)
  hit = (cache_ids_loc[pos] == ids) & (ids >= 0)
  cache_val = cache_rows_loc[pos]
  return jnp.where(hit[:, None], cache_val, gathered)


def resolve_exchange_slack(exchange_slack, shuffle: bool):
  """Resolve the loaders' ``'auto'`` default: capped at
  `DEFAULT_EXCHANGE_SLACK` for shuffled seeds (near-balanced owner
  buckets), exact for sequential seeds (contiguous ranges can land
  entirely on one owner and a cap would drop most of them).
  ``'adaptive'`` passes through — the loaders attach an
  `AdaptiveSlack` controller (shuffled seeds only)."""
  if isinstance(exchange_slack, str):
    if exchange_slack == 'adaptive':
      if not shuffle:
        raise ValueError(
            "exchange_slack='adaptive' needs shuffle=True: sequential "
            'seed ranges can land entirely on one owner, where any '
            'cap silently drops most of a batch')
      return 'adaptive'
    if exchange_slack != 'auto':
      raise ValueError(f'unknown exchange_slack {exchange_slack!r}')
    return DEFAULT_EXCHANGE_SLACK if shuffle else None
  return exchange_slack


#: `AdaptiveSlack` ladder, tightest first.  2.0 is the static default;
#: the controller walks DOWN when an epoch ends drop-free (less
#: padding = smaller exchanges) and UP on drops, pinning after the
#: first reversal so it never oscillates.  The sub-1.25 rungs only
#: bite under the compact/hier layouts (the dense layout's
#: `MIN_EXCHANGE_CAP` floor dominates their caps) — they are what
#: lets the ladder keep reclaiming padding on drop-free workloads
#: instead of pinning at 1.25 with 80%+ waste (the r5 envelope).
SLACK_LADDER = (0.75, 1.0, 1.25, 1.5, 2.0, 3.0, None)

#: tightest rung the ladder may reach by default (override per
#: controller or via ``GLT_SLACK_FLOOR``): the last step to 0.75
#: undercuts the BALANCED share and is opt-in.
DEFAULT_SLACK_FLOOR = 1.0

#: per-epoch frontier drop-rate above which the controller widens.
ADAPTIVE_DROP_TOLERANCE = 1e-3


class AdaptiveSlack:
  """Epoch-level exchange-capacity tuner (SURVEY §7 "partition-aware
  capacity tuning", made self-tuning).

  The static trade: a capacity of ``slack``x the balanced share
  shrinks every all_to_all by ``P/slack`` but drops frontier ids when
  ownership skews.  The right slack depends on the partition balance,
  which the telemetry measures per epoch — so the controller walks the
  `SLACK_LADDER` on epoch boundaries: drop-free epochs tighten one
  rung, a dropping epoch widens one rung, and the first tighten ->
  widen reversal PINS the setting (no oscillation).  Each change
  clears the sampler's step cache (one recompile, amortized over the
  remaining epochs).

  One slack value drives EVERY capacity knob of the selected exchange
  layout (`parallel.exchange.capacity_spec`): the dense per-
  destination cap, the compacted base width (its global overflow
  budget scales with the request width), and both hierarchical stage
  capacities — so the ladder tunes the new layouts with the same
  telemetry loop that tuned the dense cap.

  Args:
    floor: tightest slack the ladder may reach (default
      `DEFAULT_SLACK_FLOOR`, env ``GLT_SLACK_FLOOR``).  A drop-free
      epoch at the floor PINS there (``pin_reason='floor'``) — the
      controller is done, not stuck.
  """

  def __init__(self, sampler: 'DistNeighborSampler',
               start: float = DEFAULT_EXCHANGE_SLACK,
               floor: Optional[float] = None):
    import os
    self.sampler = sampler
    if floor is None:
      try:
        floor = float(os.environ.get('GLT_SLACK_FLOOR',
                                     DEFAULT_SLACK_FLOOR))
      except ValueError:
        floor = DEFAULT_SLACK_FLOOR
    finite = [s for s in SLACK_LADDER if s is not None]
    self._min_idx = min(
        (i for i, s in enumerate(SLACK_LADDER)
         if s is not None and s >= floor - 1e-9),
        default=len(finite) - 1)
    self.floor = SLACK_LADDER[self._min_idx]
    self._idx = SLACK_LADDER.index(start)
    self._pinned = False
    self._pin_reason = ''
    self._tightened_from = None
    self._last = {}
    sampler.exchange_slack = SLACK_LADDER[self._idx]

  @property
  def slack(self):
    return SLACK_LADDER[self._idx]

  def _set(self, idx: int, reason: str = '',
           drop_rate: float = 0.0, pin_reason: str = '') -> None:
    if idx == self._idx:
      return
    from ..telemetry.recorder import recorder
    from ..utils.profiling import metrics
    frm = SLACK_LADDER[self._idx]
    self._idx = idx
    self.sampler.exchange_slack = SLACK_LADDER[idx]
    self.sampler._steps.clear()       # new capacity = new program
    metrics.inc('dist.slack.transitions')
    recorder.emit('slack.transition', from_slack=frm,
                  to_slack=SLACK_LADDER[idx], reason=reason,
                  drop_rate=round(float(drop_rate), 6),
                  pin_reason=pin_reason)

  def _pin(self, reason: str, rate: float) -> None:
    self._pinned = True
    self._pin_reason = reason
    from ..telemetry.recorder import recorder
    recorder.emit('slack.pinned', slack=SLACK_LADDER[self._idx],
                  drop_rate=round(float(rate), 6), pin_reason=reason)

  #: ALL loss channels the shared slack caps gate — a clean frontier
  #: with skewed feature buckets must still read as "dropping"
  OFFER_KEYS = ('dist.frontier.offered', 'dist.feature.offered')
  DROP_KEYS = ('dist.frontier.dropped', 'dist.feature.dropped',
               'dist.negative.lost')

  def on_epoch_end(self) -> None:
    """Inspect the epoch's exchange telemetry and retune.  Ticks the
    metrics registry (a drain here must not swallow the epoch's
    residual delta from the global counters)."""
    st = self.sampler.exchange_stats()
    offered = sum(st[k] - self._last.get(k, 0) for k in self.OFFER_KEYS)
    dropped = sum(st[k] - self._last.get(k, 0) for k in self.DROP_KEYS)
    self._last = {k: st[k] for k in self.OFFER_KEYS + self.DROP_KEYS}
    if offered <= 0:
      return
    rate = dropped / offered
    # the hierarchical layout counts each id ONCE PER WIRE STAGE in
    # 'offered' (the per-wire fill contract), so its drop ratio reads
    # up to 2x low — compensate so the widen trigger fires at the
    # same per-id loss as the single-stage layouts
    tol = ADAPTIVE_DROP_TOLERANCE
    if resolve_layout(getattr(self.sampler, 'exchange_layout', None),
                      getattr(self.sampler, 'num_parts', 1)) == 'hier':
      tol = ADAPTIVE_DROP_TOLERANCE / 2
    if self._pinned and (self._pin_reason != 'floor'
                         or rate <= tol):
      # a reversal pin is final; a FLOOR pin only stops tightening —
      # drops at the floor must still get their capacity back
      return
    if rate > tol:
      # widen; if this reverses our own tighten, pin there
      wider = min(self._idx + 1, len(SLACK_LADDER) - 1)
      pin = (self._tightened_from is not None
             and wider >= self._tightened_from)
      self._set(wider, reason='drops', drop_rate=rate,
                pin_reason='reversal' if pin else '')
      if pin:
        self._pin('reversal', rate)
      else:
        self._pinned = False        # left the floor; resume tuning
    elif self._idx > self._min_idx:
      self._tightened_from = self._idx
      self._set(self._idx - 1, reason='drop_free', drop_rate=rate)
    elif not self._pinned:
      # drop-free AT the floor: the ladder is done tightening — pin
      # and say why, so 'slack_final == floor' is readable as
      # converged rather than stuck (the r5 envelope ambiguity)
      self._pin('floor', rate)

  # -- DataPlaneState (utils.checkpoint): the ladder's position -----------
  def state_dict(self) -> dict:
    """Rung index + pin state + the tighten-origin marker.  The
    telemetry baselines (``_last``) are NOT captured — they reference
    process-local cumulative counters that restart at zero in the
    resuming process; `load_state_dict` re-baselines against the live
    registry instead."""
    return {'idx': self._idx, 'pinned': int(self._pinned),
            'pin_reason': self._pin_reason,
            'tightened_from': (-1 if self._tightened_from is None
                               else int(self._tightened_from))}

  def load_state_dict(self, state: dict) -> None:
    idx = int(np.asarray(state['idx']))
    if idx != self._idx:
      self._set(idx, reason='restore')
    self._pinned = bool(int(np.asarray(state['pinned'])))
    self._pin_reason = str(np.asarray(state['pin_reason']))
    tf = int(np.asarray(state['tightened_from']))
    self._tightened_from = None if tf < 0 else tf
    st = self.sampler.exchange_stats()
    self._last = {k: st[k] for k in self.OFFER_KEYS + self.DROP_KEYS}


def _slack_cap(n: int, num_parts: int,
               exchange_slack: Optional[float],
               exchange_layout: Optional[str] = None, caps=None):
  """Capacity plan for one ``n``-id exchange: None = exact, else an
  `exchange.ExchangeSpec` under the sampler's layout (the dense spec
  reproduces the original ``max(ceil(n/P * slack), MIN_EXCHANGE_CAP)``
  rounded cap bit-for-bit).  ``caps``: the `EwmaCapacityModel`'s
  quantized ``(dest_cap, traffic_cap)`` for this channel (None keeps
  the uniform-share plan)."""
  d, t = caps if caps is not None else (None, None)
  return capacity_spec(n, num_parts, exchange_slack,
                       layout=exchange_layout, dest_cap=d,
                       traffic_cap=t)


def _expand_and_collect(indptr, indices, eids, bounds, seeds, key, *,
                        fanouts, node_cap, with_edge, collect_features,
                        collect_labels, with_cache, fshard, lshard,
                        cids, crows, axis, num_parts, exchange_slack,
                        exchange_layout=None,
                        collect_edge_features=False, efshard=None,
                        ebounds=None, ef_shard_mode='mod',
                        hot_counts=None, gns_bits=None,
                        gns_boost=None, book_spec=None,
                        cache_local=False, fr_caps=None, ft_caps=None):
  """Per-device multihop expansion + feature/label collection — the
  shared body of the node and link SPMD steps.  When
  ``collect_edge_features`` is set, every sampled edge's feature row is
  gathered by GLOBAL edge id through the same exchange machinery (the
  collective analog of the reference's efeats collation,
  `distributed/dist_neighbor_sampler.py:600-673`).  With ``gns_bits``
  set the hops sample cache-aware (GNS) and the per-edge importance
  weights come back aligned with the ``row``/``col`` edge list."""
  b = seeds.shape[0]
  state, seed_local = init_node(seeds, node_cap)
  f_cap = b
  slots = jnp.arange(f_cap, dtype=jnp.int32)
  fr_valid = slots < state.count
  frontier = jnp.where(
      fr_valid, state.nodes[jnp.clip(slots, 0, node_cap - 1)], INVALID_ID)
  frontier_local = jnp.where(fr_valid, slots, -1)

  rows_acc, cols_acc, eids_acc, ew_acc = [], [], [], []
  hop_counts = [state.count]
  fr_stats = jnp.zeros((3,), jnp.int32)
  ft_stats = jnp.zeros((3,), jnp.int32)
  # per-(src->dst)-RANGE traffic attribution (ISSUE 16): histogram the
  # ids each wire stage offers by their PartitionBook range owner —
  # this device's row of the fleet's P x P matrix.  Keyed by the RANGE
  # (identity book), so a row keeps meaning "ids in range r" even
  # after an adopted book remaps which physical device serves r.
  attr_owner = range_owner_fn(bounds)
  attr_fr = jnp.zeros((num_parts,), jnp.int32)
  attr_ft = jnp.zeros((num_parts,), jnp.int32)
  for h, k in enumerate(fanouts):
    hop_key = jax.random.fold_in(key, h)
    attr_fr = attr_fr + dest_histogram(frontier, attr_owner, num_parts)
    nbrs, mask, e, hw, hstats = _dist_one_hop(
        indptr, indices, eids, bounds, frontier, int(k), hop_key,
        axis, num_parts, with_edge,
        exchange_capacity=_slack_cap(frontier.shape[0], num_parts,
                                     exchange_slack, exchange_layout,
                                     caps=fr_caps),
        gns_bits=gns_bits, gns_boost=gns_boost, book_spec=book_spec)
    fr_stats = fr_stats + jnp.stack(hstats)
    state, rows, cols, prev_cnt = induce_next(
        state, frontier_local, nbrs, mask)
    rows_acc.append(rows)
    cols_acc.append(cols)
    if with_edge:
      eids_acc.append(jnp.where(rows >= 0, e.reshape(-1), INVALID_ID))
    if gns_bits is not None:
      # induce_next flattens [F, k] row-major, so the weight layout
      # matches the edge list's; masked/dropped edges carry 0
      ew_acc.append(jnp.where(rows >= 0, hw.reshape(-1), 0.0))
    hop_counts.append(state.count)
    f_cap = f_cap * int(k)
    slots = prev_cnt + jnp.arange(f_cap, dtype=jnp.int32)
    fr_valid = slots < state.count
    frontier = jnp.where(
        fr_valid, state.nodes[jnp.clip(slots, 0, node_cap - 1)],
        INVALID_ID)
    frontier_local = jnp.where(fr_valid, slots, -1)

  row = jnp.concatenate(rows_acc)
  col = jnp.concatenate(cols_acc)
  edge = jnp.concatenate(eids_acc) if with_edge else None
  ew = jnp.concatenate(ew_acc) if gns_bits is not None else None
  x = y = ef = None
  if collect_edge_features and edge is not None:
    (ef,), estats = dist_gather_multi(
        (efshard,), ebounds, edge, axis, num_parts,
        exchange_capacity=_slack_cap(edge.shape[0], num_parts,
                                     exchange_slack, exchange_layout,
                                     caps=ft_caps),
        shard_mode=ef_shard_mode, book_spec=book_spec)
    ft_stats = ft_stats + jnp.stack(estats)
    ef_owner = (edge_owner_fn(num_parts) if ef_shard_mode == 'mod'
                else range_owner_fn(ebounds))
    attr_ft = attr_ft + dest_histogram(edge, ef_owner, num_parts)
  tables = (((fshard,) if collect_features else ())
            + ((lshard,) if collect_labels else ()))
  replica_hits = jnp.zeros((1,), jnp.int32)
  if tables:
    node_valid = jnp.arange(node_cap, dtype=jnp.int32) < state.count
    gather_ids = state.nodes
    if with_cache and cache_local:
      # ISSUE 20 replica mode: rows replicated into this device's
      # cache are LOCAL — mask them out of the exchange request (the
      # overlay below fills them), and credit them to the attribution
      # diagonal via the dedicated stats slot.  This is what turns
      # hot-range coverage into avoided exchange bytes; the plain
      # offline cache plan (cache_local=False) keeps the byte-
      # identical post-exchange overlay.
      c = cids.shape[0]
      pos = jnp.clip(jnp.searchsorted(cids, state.nodes), 0, c - 1)
      local_hit = (cids[pos] == state.nodes) & (state.nodes >= 0) \
          & node_valid
      if hot_counts is None and book_spec is None:
        # owner bypass: rows THIS device already owns never need the
        # round trip either — serve them by a direct local-shard take
        # below.  With the diagonal off the wire, the EWMA capacity
        # model sizes the feature lanes from true REMOTE demand (the
        # diagonal otherwise pins `dest_cap`: locality partitioning
        # makes self-traffic the busiest cell).  Gated to the
        # full-resident store under the identity book — a tiered
        # shard holds only hot rows, and an adopted/remapped book
        # means the local shard no longer spans [bounds[p],
        # bounds[p+1]).
        my = jax.lax.axis_index(axis)
        lo = jnp.take(jnp.asarray(bounds), my)
        hi = jnp.take(jnp.asarray(bounds), my + 1)
        local_hit = local_hit | ((state.nodes >= lo)
                                 & (state.nodes < hi) & node_valid)
      gather_ids = jnp.where(local_hit, INVALID_ID, state.nodes)
      replica_hits = jnp.sum(local_hit.astype(jnp.int32))[None]
    got, gstats = dist_gather_multi(
        tables, bounds, gather_ids, axis, num_parts,
        exchange_capacity=_slack_cap(node_cap, num_parts,
                                     exchange_slack, exchange_layout,
                                     caps=ft_caps),
        hot_counts=hot_counts if collect_features else None,
        book_spec=book_spec)
    got = list(got)
    ft_stats = ft_stats + jnp.stack(gstats)
    attr_ft = attr_ft + dest_histogram(
        gather_ids, attr_owner, num_parts,
        valid=node_valid & (gather_ids >= 0))
    if collect_features:
      x = got.pop(0)
      if with_cache:
        # overlay local cache hits on the exchanged rows (see
        # `cache_overlay` for why this is an overlay, not a
        # miss-only exchange)
        x = cache_overlay(x, state.nodes, cids, crows)
        if cache_local and hot_counts is None and book_spec is None:
          # owner-bypass fill: the ids masked out above as self-owned
          # come straight from the resident shard
          my = jax.lax.axis_index(axis)
          lo = jnp.take(jnp.asarray(bounds), my)
          hi = jnp.take(jnp.asarray(bounds), my + 1)
          own = (state.nodes >= lo) & (state.nodes < hi) & node_valid
          rowsl = jnp.take(
              fshard, jnp.clip(state.nodes - lo, 0,
                               fshard.shape[0] - 1), axis=0)
          x = jnp.where(own[:, None], rowsl, x)
    if collect_labels:
      y = got.pop(0)
  cum = jnp.stack(hop_counts)
  nsn = jnp.concatenate([cum[:1], cum[1:] - cum[:-1]]).astype(jnp.int32)
  # stats layout: [7] scalar triple pairs + negative.lost slot, then
  # the [2P + 1] attribution tail (frontier dests, feature dests,
  # replica-hit count) — see `ExchangeTelemetry._accumulate_stats`
  # for the host-side split
  stats = jnp.concatenate([fr_stats, ft_stats, jnp.zeros((1,), jnp.int32),
                           attr_fr, attr_ft, replica_hits])
  return state, row, col, edge, seed_local, x, y, ef, nsn, stats, ew


def _make_dist_step(mesh: Mesh, num_parts: int, fanouts: Tuple[int, ...],
                    node_cap: int, with_edge: bool, collect_features: bool,
                    collect_labels: bool, axis: str = 'data',
                    with_cache: bool = False,
                    exchange_slack: Optional[float] = None,
                    exchange_layout: Optional[str] = None,
                    collect_edge_features: bool = False,
                    ef_shard_mode: str = 'mod', tiered: bool = False,
                    gns_boost: Optional[float] = None,
                    book_spec=None, cache_local: bool = False,
                    ewma_caps=None):
  """Build the jitted SPMD sample(+collect) step.

  ``exchange_slack``: per-destination exchange capacity as a multiple
  of the balanced share (``frontier/P``); None = uncapped (full
  frontier width, ~P x padding).  See `bucket_by_owner`.
  ``tiered``: the feature table is HBM-partial — owners zero rows past
  their hot count (``hcounts``) and the caller overlays the cold tier.
  ``gns_boost``: non-None builds the GNS variant — the step takes a
  replicated cached-set bitmask (``gns_bits``) before ``key`` and
  returns the per-edge importance weights as a 12th output; None
  builds EXACTLY the unbiased step (same signature, same program —
  the ``GLT_GNS=0`` byte-identity contract).
  """
  from .shard_map_compat import shard_map
  gns = gns_boost is not None

  def per_device(indptr_s, indices_s, eids_s, bounds, seeds_s, fshard_s,
                 lshard_s, cids_s, crows_s, efshard_s, ebounds, hcounts,
                 *rest):
    gns_bits = rest[0] if gns else None
    key = rest[-1]
    (state, row, col, edge, seed_local, x, y, ef, nsn, stats,
     ew) = _expand_and_collect(
        indptr_s[0], indices_s[0], eids_s[0] if with_edge else None,
        bounds, seeds_s[0], key,
        fanouts=fanouts, node_cap=node_cap, with_edge=with_edge,
        collect_features=collect_features, collect_labels=collect_labels,
        with_cache=with_cache,
        fshard=fshard_s[0] if collect_features else None,
        lshard=lshard_s[0] if collect_labels else None,
        cids=cids_s[0] if with_cache else None,
        crows=crows_s[0] if with_cache else None,
        axis=axis, num_parts=num_parts, exchange_slack=exchange_slack,
        exchange_layout=exchange_layout,
        collect_edge_features=collect_edge_features,
        efshard=efshard_s[0] if collect_edge_features else None,
        ebounds=ebounds, ef_shard_mode=ef_shard_mode,
        hot_counts=hcounts if tiered else None,
        gns_bits=gns_bits, gns_boost=gns_boost, book_spec=book_spec,
        cache_local=cache_local,
        fr_caps=ewma_caps.get('frontier') if ewma_caps else None,
        ft_caps=ewma_caps.get('feature') if ewma_caps else None)

    def lead(v):   # re-add the shard axis for stacked outputs
      return None if v is None else v[None]
    out = (lead(state.nodes), lead(state.count[None]), lead(row),
           lead(col), lead(edge), lead(seed_local), lead(x), lead(y),
           lead(ef), lead(nsn), lead(stats))
    return out + (lead(ew),) if gns else out

  specs_in = (P(axis), P(axis), P(axis), P(), P(axis), P(axis), P(axis),
              P(axis), P(axis), P(axis), P(), P()) \
      + ((P(),) if gns else ()) + (P(),)
  specs_out = tuple(P(axis) for _ in range(12 if gns else 11))
  sharded = shard_map(per_device, mesh=mesh, in_specs=specs_in,
                      out_specs=specs_out)

  @jax.jit
  def step(indptr_s, indices_s, eids_s, bounds, seeds_s, fshard_s,
           lshard_s, cids_s, crows_s, efshard_s, ebounds, hcounts,
           *rest):
    return sharded(indptr_s, indices_s, eids_s, bounds, seeds_s,
                   fshard_s, lshard_s, cids_s, crows_s, efshard_s,
                   ebounds, hcounts, *rest)

  return step


def _make_dist_link_step(mesh: Mesh, num_parts: int,
                         fanouts: Tuple[int, ...], node_cap: int,
                         batch: int, num_nodes: int,
                         neg_mode: Optional[str], num_neg: int,
                         neg_amount: float,
                         with_edge: bool, collect_features: bool,
                         collect_labels: bool, axis: str = 'data',
                         with_cache: bool = False,
                         exchange_slack: Optional[float] = None,
                         exchange_layout: Optional[str] = None,
                         collect_edge_features: bool = False,
                         ef_shard_mode: str = 'mod',
                         tiered: bool = False,
                         gns_boost: Optional[float] = None,
                         book_spec=None, cache_local: bool = False,
                         ewma_caps=None):
  """Build the jitted SPMD LINK sample step: per-device seed edges +
  collective strict negatives + the shared expansion body.

  The device analog of the reference's `_sample_from_edges`
  (`distributed/dist_neighbor_sampler.py:327-453`) — with the key
  difference that negatives are strict against the GLOBAL sharded
  graph (one `dist_edge_exists` exchange), where the reference settles
  for local-partition rejection.  ``gns_boost``: as `_make_dist_step`
  (non-None adds the bitmask input + the edge-weight output; the
  negative draws stay uniform — only the endpoint EXPANSION biases).
  """
  from .shard_map_compat import shard_map
  gns = gns_boost is not None

  def per_device(indptr_s, indices_s, eids_s, bounds, pairs_s, fshard_s,
                 lshard_s, cids_s, crows_s, efshard_s, ebounds, hcounts,
                 *rest):
    gns_bits = rest[0] if gns else None
    key = rest[-1]
    indptr = indptr_s[0]
    indices = indices_s[0]
    pairs = pairs_s[0]                       # [B, 2|3]
    src, dst = pairs[:, 0], pairs[:, 1]
    my_idx = jax.lax.axis_index(axis)
    neg_key = jax.random.fold_in(jax.random.fold_in(key, my_idx), 977)
    cap = _slack_cap(num_neg * NEG_TRIALS, num_parts,
                     exchange_slack, exchange_layout)
    neg_ok = None
    if neg_mode == 'binary':
      nrows, ncols, neg_ok = dist_sample_negative(
          indptr, indices, bounds, num_nodes, num_nodes, num_neg,
          neg_key, axis, num_parts, exchange_capacity=cap,
          book_spec=book_spec)
      seeds = jnp.concatenate([src, dst, nrows, ncols])
    elif neg_mode == 'triplet':
      amount = num_neg // batch
      srcs_rep = jnp.repeat(jnp.where(src >= 0, src, 0), amount)
      _, negs, neg_ok = dist_sample_negative(
          indptr, indices, bounds, num_nodes, num_nodes, num_neg,
          neg_key, axis, num_parts, exchange_capacity=cap,
          rows_fixed=srcs_rep.astype(jnp.int32),
          book_spec=book_spec)
      seeds = jnp.concatenate([src, dst, negs])
    else:
      seeds = jnp.concatenate([src, dst])
    seeds = jnp.where(seeds >= 0, seeds, INVALID_ID).astype(jnp.int32)

    (state, row, col, edge, seed_local, x, y, ef, nsn, stats,
     ew) = _expand_and_collect(
        indptr, indices, eids_s[0] if with_edge else None, bounds,
        seeds, key,
        fanouts=fanouts, node_cap=node_cap, with_edge=with_edge,
        collect_features=collect_features, collect_labels=collect_labels,
        with_cache=with_cache,
        fshard=fshard_s[0] if collect_features else None,
        lshard=lshard_s[0] if collect_labels else None,
        cids=cids_s[0] if with_cache else None,
        crows=crows_s[0] if with_cache else None,
        axis=axis, num_parts=num_parts, exchange_slack=exchange_slack,
        exchange_layout=exchange_layout,
        collect_edge_features=collect_edge_features,
        efshard=efshard_s[0] if collect_edge_features else None,
        ebounds=ebounds, ef_shard_mode=ef_shard_mode,
        hot_counts=hcounts if tiered else None,
        gns_bits=gns_bits, gns_boost=gns_boost, book_spec=book_spec,
        cache_local=cache_local,
        fr_caps=ewma_caps.get('frontier') if ewma_caps else None,
        ft_caps=ewma_caps.get('feature') if ewma_caps else None)

    b = batch
    sl = seed_local
    pair_valid = (src >= 0) & (dst >= 0)
    pos_label = jnp.where(
        pair_valid,
        pairs[:, 2] if pairs.shape[1] > 2 else jnp.ones((b,), jnp.int32),
        0)
    if neg_mode == 'binary':
      eli = jnp.stack([jnp.concatenate([sl[:b], sl[2 * b:2 * b + num_neg]]),
                       jnp.concatenate([sl[b:2 * b], sl[2 * b + num_neg:]])])
      elab = jnp.concatenate([pos_label,
                              jnp.zeros((num_neg,), jnp.int32)])
      # exhausted-trials slots may be REAL edges, and padded tail
      # batches keep the neg_amount-per-positive contract: negatives
      # beyond ceil(valid_pairs * amount) are masked out
      quota = jnp.ceil(jnp.sum(pair_valid)
                       * jnp.float32(neg_amount)).astype(jnp.int32)
      neg_keep = neg_ok & (jnp.arange(num_neg) < quota)
      emask_lab = jnp.concatenate([pair_valid, neg_keep])
      md = (eli, elab, emask_lab, jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b, 1), jnp.int32))
    elif neg_mode == 'triplet':
      amount = num_neg // batch
      dn = jnp.where(neg_ok, sl[2 * b:], -1).reshape(b, amount)
      md = (jnp.zeros((2, 1), jnp.int32), jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), bool), sl[:b], sl[b:2 * b], dn)
    else:
      eli = jnp.stack([sl[:b], sl[b:2 * b]])
      md = (eli, pos_label, pair_valid, jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b, 1), jnp.int32))

    if neg_ok is not None:
      stats = stats.at[6].add(
          jnp.sum((~neg_ok).astype(jnp.int32)))

    def lead(v):
      return None if v is None else v[None]
    out = ((lead(state.nodes), lead(state.count[None]), lead(row),
            lead(col), lead(edge), lead(seed_local), lead(x), lead(y),
            lead(ef), lead(nsn), lead(stats))
           + ((lead(ew),) if gns else ())
           + tuple(lead(m) for m in md))
    return out

  specs_in = (P(axis), P(axis), P(axis), P(), P(axis), P(axis), P(axis),
              P(axis), P(axis), P(axis), P(), P()) \
      + ((P(),) if gns else ()) + (P(),)
  specs_out = tuple(P(axis) for _ in range(18 if gns else 17))
  sharded = shard_map(per_device, mesh=mesh, in_specs=specs_in,
                      out_specs=specs_out)

  @jax.jit
  def step(indptr_s, indices_s, eids_s, bounds, pairs_s, fshard_s,
           lshard_s, cids_s, crows_s, efshard_s, ebounds, hcounts,
           *rest):
    return sharded(indptr_s, indices_s, eids_s, bounds, pairs_s,
                   fshard_s, lshard_s, cids_s, crows_s, efshard_s,
                   ebounds, hcounts, *rest)

  return step


def _make_dist_subgraph_step(mesh: Mesh, num_parts: int,
                             fanouts: Tuple[int, ...], node_cap: int,
                             max_degree: int, with_edge: bool,
                             collect_features: bool, collect_labels: bool,
                             axis: str = 'data',
                             with_cache: bool = False,
                             exchange_slack: Optional[float] = None,
                             exchange_layout: Optional[str] = None,
                             tiered: bool = False,
                             hop_chunk: Optional[int] = None,
                             book_spec=None):
  """Build the jitted SPMD INDUCED-SUBGRAPH step — the device-mesh
  analog of reference ``DistNeighborSampler._subgraph``
  (`distributed/dist_neighbor_sampler.py:456-516`).

  Per device: multihop closure over the sharded CSR (the shared
  expansion body), then ONE full-window distributed hop with
  ``k = max_degree`` — each owner returns every out-neighbor of the
  closure nodes it owns (no sampling: the Gumbel top-k window is exact
  when ``deg <= k``) — and a LOCAL sort-based membership test +
  relabel against this device's closure set.  The membership test runs
  at the requester, which owns its closure, so no closure-set
  all_gather is needed; edge (u, v) is emitted exactly once, by u's
  window, in natural (source, dest) direction like the single-chip
  `ops.subgraph.induced_subgraph`.

  ``hop_chunk`` bounds the full-window exchange: the node table is
  scanned in chunks of that many closure nodes, so every all_to_all
  buffer is ``[P, chunk]`` requests / ``[P, chunk, max_degree]``
  replies instead of ``[P, node_cap]`` — the SEAL-at-scale envelope
  (VERDICT r2 item 7): peak exchange width becomes
  ``chunk * P * max_degree`` regardless of closure size, at the cost
  of ``ceil(node_cap / chunk)`` serialized exchanges.  Results are
  EXACT either way (each chunk's window is still unsampled).
  """
  from .shard_map_compat import shard_map
  chunk = node_cap if hop_chunk is None else max(int(hop_chunk), 1)
  chunk = min(chunk, node_cap)
  n_chunks = -(-node_cap // chunk)
  pad_cap = n_chunks * chunk

  def per_device(indptr_s, indices_s, eids_s, bounds, seeds_s, fshard_s,
                 lshard_s, cids_s, crows_s, hcounts, key):
    (state, _row, _col, _edge, seed_local, x, y, _ef, nsn, stats,
     _ew) = _expand_and_collect(
        indptr_s[0], indices_s[0], None, bounds, seeds_s[0], key,
        fanouts=fanouts, node_cap=node_cap, with_edge=False,
        collect_features=collect_features, collect_labels=collect_labels,
        with_cache=with_cache,
        fshard=fshard_s[0] if collect_features else None,
        lshard=lshard_s[0] if collect_labels else None,
        cids=cids_s[0] if with_cache else None,
        crows=crows_s[0] if with_cache else None,
        axis=axis, num_parts=num_parts, exchange_slack=exchange_slack,
        exchange_layout=exchange_layout,
        hot_counts=hcounts if tiered else None, book_spec=book_spec)

    nodes = state.nodes                              # [node_cap]
    nodes_pad = jnp.concatenate(
        [nodes, jnp.full((pad_cap - node_cap,), INVALID_ID,
                         nodes.dtype)]) if pad_cap > node_cap else nodes
    nbrs_parts, mask_parts, eids_parts = [], [], []
    for ci in range(n_chunks):
      frontier_c = jax.lax.dynamic_slice_in_dim(nodes_pad, ci * chunk,
                                                chunk)
      nb, mk, ei, _w, hstats = _dist_one_hop(
          indptr_s[0], indices_s[0], eids_s[0] if with_edge else None,
          bounds, frontier_c, max_degree,
          # per-chunk fold: with a truncating max_degree the window
          # draws must stay independent across chunks
          jax.random.fold_in(key, ci), axis, num_parts,
          with_edge,
          exchange_capacity=_slack_cap(chunk, num_parts,
                                       exchange_slack,
                                       exchange_layout),
          book_spec=book_spec)
      stats = stats.at[:3].add(jnp.stack(hstats))
      # full-window hops are frontier traffic too: extend this
      # device's src->dst attribution row (stats[7:7+P])
      stats = stats.at[7:7 + num_parts].add(
          dest_histogram(frontier_c, range_owner_fn(bounds), num_parts))
      nbrs_parts.append(nb)
      mask_parts.append(mk)
      if with_edge:
        eids_parts.append(ei)
    nbrs = jnp.concatenate(nbrs_parts)[:node_cap]
    mask = jnp.concatenate(mask_parts)[:node_cap]
    eids = (jnp.concatenate(eids_parts)[:node_cap] if with_edge
            else None)
    big = jnp.iinfo(jnp.int32).max
    keyed = jnp.where(nodes >= 0, nodes, big)
    order = jnp.argsort(keyed)
    sorted_nodes = keyed[order]
    flat = nbrs.reshape(-1)
    loc = jnp.clip(jnp.searchsorted(sorted_nodes, flat), 0,
                   node_cap - 1).astype(jnp.int32)
    hit = (sorted_nodes[loc] == flat) & (flat >= 0) & mask.reshape(-1)
    col = jnp.where(hit, order[loc], INVALID_ID).astype(jnp.int32)
    row = jnp.where(
        hit,
        jnp.repeat(jnp.arange(node_cap, dtype=jnp.int32), max_degree),
        INVALID_ID)
    edge = (jnp.where(hit, eids.reshape(-1), INVALID_ID)
            if with_edge else None)

    def lead(v):
      return None if v is None else v[None]
    return (lead(nodes), lead(state.count[None]), lead(row), lead(col),
            lead(edge), lead(seed_local), lead(x), lead(y), lead(nsn),
            lead(stats))

  specs_in = (P(axis), P(axis), P(axis), P(), P(axis), P(axis), P(axis),
              P(axis), P(axis), P(), P())
  specs_out = tuple(P(axis) for _ in range(10))
  sharded = shard_map(per_device, mesh=mesh, in_specs=specs_in,
                      out_specs=specs_out)

  @jax.jit
  def step(indptr_s, indices_s, eids_s, bounds, seeds_s, fshard_s,
           lshard_s, cids_s, crows_s, hcounts, key):
    return sharded(indptr_s, indices_s, eids_s, bounds, seeds_s,
                   fshard_s, lshard_s, cids_s, crows_s, hcounts, key)

  return step


class ExchangeTelemetry:
  """Device-resident exchange-overflow telemetry shared by the mesh
  samplers: adding each step's stacked ``[P, 7]`` stats stays async
  (no per-batch host sync); `exchange_stats` materializes totals at
  epoch/bench boundaries and ticks the global metrics registry."""

  #: auto-drain interval: the device counter is int32 (x64 disabled)
  #: and the biggest per-step count (exchange SLOTS at the reference
  #: workload) is ~2e7, so 64 steps stay safely under 2^31.  Draining
  #: costs one [7]-scalar transfer at the tail of an already-dispatched
  #: chain — negligible against a training step.
  STATS_DRAIN_INTERVAL = 64

  def _init_stats(self) -> None:
    import threading
    # prefetch workers (`loader.prefetch`) call the sampler from a
    # second thread; the read-modify-write on the accumulators must
    # not interleave with an exchange_stats() drain
    self._stats_lock = threading.Lock()
    self._stats_acc = jnp.zeros((len(EXCHANGE_STAT_NAMES),), jnp.int32)
    self._stats_total = np.zeros(len(EXCHANGE_STAT_NAMES), np.int64)
    self._stats_pending = 0
    # per-(src device -> dst range) traffic attribution (ISSUE 16):
    # the step's stats vector carries [2P] histogram tails (frontier
    # dests, feature dests) per device; they accumulate UN-summed —
    # row = src device — into the device-resident [P, 2P] matrix
    self._attr_acc = None
    self._attr_total: Optional[np.ndarray] = None
    self._attr_reported = (0, 0)
    # host-side cold-tier counters (tiered feature stores only).
    # Definitions (benchmarks/README "Cold-tier metrics"):
    #   lookups      = valid node-table feature lookups;
    #   cold_lookups = lookups past the owner's hot count (the cold
    #                  tier's demand — the cache denominator);
    #   cold_misses  = cold lookups the HOST tier served (cache
    #                  misses; each one is host-gather work);
    #   cache_*      = dynamic HBM victim-cache traffic
    #                  (`data.cold_cache`).
    self._feat_lookups = 0
    self._cold_lookups = 0
    self._cold_misses = 0
    self._cache_hits = 0
    self._cache_admits = 0
    self._cache_evicts = 0
    self._cold_reported = (0,) * 6

  def _accumulate_stats(self, stats_stacked) -> None:
    n = len(EXCHANGE_STAT_NAMES)
    base = stats_stacked[:, :n]
    attr = stats_stacked[:, n:]
    with self._stats_lock:
      self._stats_acc = self._stats_acc + jnp.sum(base, axis=0)
      if attr.shape[1]:
        self._attr_acc = (attr if self._attr_acc is None
                          else self._attr_acc + attr)
      self._stats_pending += 1
      drain = self._stats_pending >= self.STATS_DRAIN_INTERVAL
    if drain:
      self.exchange_stats()

  def _stats_state(self) -> np.ndarray:
    """Cumulative counter snapshot (exchange totals + cold-tier host
    counters) as ONE int64 leaf — saved with each chunk snapshot so a
    degraded-mode rollback (`parallel.fused._rollback_to_snapshot`)
    can rewind the counters a re-dispatched chunk would otherwise
    double-count."""
    self.exchange_stats(tick_metrics=False)     # drain the device acc
    with self._stats_lock:
      cold = (self._feat_lookups, self._cold_lookups,
              self._cold_misses, self._cache_hits, self._cache_admits,
              self._cache_evicts)
      parts = [self._stats_total, np.asarray(cold, np.int64)]
      if self._attr_total is not None:
        # the [P, 2P] attribution matrix rides flattened at the tail;
        # shape reconstructs from the size (2P^2) alone
        parts.append(self._attr_total.reshape(-1))
      return np.concatenate(parts)

  def _load_stats_state(self, packed) -> None:
    arr = np.asarray(packed, np.int64)
    n = len(EXCHANGE_STAT_NAMES)
    with self._stats_lock:
      self._stats_acc = jnp.zeros_like(self._stats_acc)
      self._attr_acc = None
      self._stats_pending = 0
      self._stats_total = arr[:n].copy()
      (self._feat_lookups, self._cold_lookups, self._cold_misses,
       self._cache_hits, self._cache_admits,
       self._cache_evicts) = (int(v) for v in arr[n:n + 6])
      tail = arr[n + 6:]
      if tail.size:
        # rows = device count, cols = 2P+1 (frontier dests, feature
        # dests, replica-hit count — ISSUE 20) or 2P for pre-replica
        # snapshots; prefer the sampler's own num_parts (rows ==
        # cols/2 only when mesh size == P)
        p = getattr(self, 'num_parts',
                    int(round(np.sqrt(tail.size / 2))))
        cols = (2 * p + 1) if tail.size % (2 * p + 1) == 0 else 2 * p
        self._attr_total = tail.reshape(-1, cols).copy()
      else:
        # pre-attribution snapshot: counters restore, the matrix
        # restarts cold (documented fallback)
        self._attr_total = None
      # the registry watermark must never exceed the rewound counters
      # (a negative delta would tick the global metrics backwards)
      self._cold_reported = tuple(
          min(r, int(v)) for r, v in zip(self._cold_reported,
                                         arr[n:n + 6]))

  def exchange_stats(self, tick_metrics: bool = True):
    """Materialize cumulative exchange telemetry (one device sync).

    Returns ``{'dist.frontier.offered': n, ...}`` totals since
    construction; the delta since the previous call is also ticked
    into the global `utils.profiling.metrics` registry so overflow
    drops are never invisible.
    """
    # the WHOLE drain runs under the lock (a prefetch worker's
    # interval drain may race a caller's): totals and the reported-
    # watermark are read-modify-write shared state too.  Only the
    # registry ticks happen outside, on snapshot values.
    with self._stats_lock:
      acc = self._stats_acc
      self._stats_acc = jnp.zeros_like(acc)
      attr_acc = self._attr_acc
      self._attr_acc = None
      self._stats_pending = 0
      delta = np.asarray(jax.device_get(acc), np.int64)
      self._stats_total += delta
      if attr_acc is not None:
        a = np.asarray(jax.device_get(attr_acc), np.int64)
        if (self._attr_total is None
            or self._attr_total.shape != a.shape):
          self._attr_total = np.zeros_like(a)
        self._attr_total += a
      totals = self._stats_total.copy()
      cold_now = (self._feat_lookups, self._cold_lookups,
                  self._cold_misses, self._cache_hits,
                  self._cache_admits, self._cache_evicts)
      cold_delta = (0,) * 6
      if tick_metrics:
        cold_delta = tuple(n - p for n, p
                           in zip(cold_now, self._cold_reported))
        self._cold_reported = cold_now
    out = {f'dist.{n}': int(v)
           for n, v in zip(EXCHANGE_STAT_NAMES, totals)}
    lookups, cold_lookups, cold_misses, hits, admits, evicts = cold_now
    out['dist.feature.lookups'] = lookups
    out['dist.feature.cold_lookups'] = cold_lookups
    out['dist.feature.cold_misses'] = cold_misses
    out['dist.feature.cache_hits'] = hits
    out['dist.feature.cache_admits'] = admits
    out['dist.feature.cache_evicts'] = evicts
    # hot_hit_rate: fraction of feature lookups the HBM hot tier
    # served (what r5's "cold_hit_rate" actually measured);
    # cache/cold_hit_rate: fraction of COLD lookups served on-device
    # by the victim cache — each miss is host-gather work.  See
    # benchmarks/README "Cold-tier metrics".
    out['dist.feature.hot_hit_rate'] = (
        1.0 - cold_lookups / lookups if lookups else 1.0)
    out['dist.feature.cache_hit_rate'] = (
        1.0 - cold_misses / cold_lookups if cold_lookups else 0.0)
    out['dist.feature.cold_hit_rate'] = out[
        'dist.feature.cache_hit_rate']
    if tick_metrics:
      from ..telemetry.recorder import recorder
      from ..utils.profiling import metrics
      for n, d in zip(EXCHANGE_STAT_NAMES, delta):
        if d:
          metrics.inc(f'dist.{n}', float(d))
      for n, d in zip(('lookups', 'cold_lookups', 'cold_misses',
                       'cache_hits', 'cache_admits', 'cache_evicts'),
                      cold_delta):
        if d > 0:
          metrics.inc(f'dist.feature.{n}', float(d))
      if delta.any():
        # one flight-recorder event per drain window: the since-last
        # deltas, so a JSONL reader sees the exchange trajectory
        # without diffing cumulative totals
        recorder.emit(
            'dist.exchange',
            **{n.replace('.', '_'): int(d)
               for n, d in zip(EXCHANGE_STAT_NAMES, delta)})
      if cold_delta[1] > 0:
        recorder.emit('dist.cold_tier',
                      lookups=int(cold_delta[0]),
                      cold_lookups=int(cold_delta[1]),
                      misses=int(cold_delta[2]),
                      cache_hits=int(cold_delta[3]),
                      hit_rate=round(
                          1.0 - cold_delta[2] / cold_delta[1], 6))
    return out

  def attribution_matrices(self) -> Tuple[np.ndarray, np.ndarray]:
    """``(frontier, feature)`` — two ``[P, P]`` int64 id-count
    matrices, row = SRC device, column = DST range (`PartitionBook`
    identity ranges, so columns keep meaning "range r" under adopted
    books).  Drains the device accumulator (one sync)."""
    self.exchange_stats(tick_metrics=False)
    with self._stats_lock:
      tot = self._attr_total
      if tot is None:
        p = int(getattr(self, 'num_parts', 0) or 0)
        z = np.zeros((p, p), np.int64)
        return z, z.copy()
      # cols = 2P (pre-replica) or 2P+1 (trailing replica-hit count)
      p = tot.shape[1] // 2
      return tot[:, :p].copy(), tot[:, p:2 * p].copy()

  def replica_hits(self) -> int:
    """Cumulative feature lookups served WITHOUT riding the exchange
    (ISSUE 20b): replica-set hits plus the owner bypass's self-owned
    rows — everything the masked gather kept OFF the wire.  0 when
    the stats tail predates the replica slot or no replicas exist."""
    self.exchange_stats(tick_metrics=False)
    with self._stats_lock:
      tot = self._attr_total
      if tot is None or tot.shape[1] % 2 == 0:
        return 0
      return int(tot[:, -1].sum())

  def attribution_stats(self, top_k: Optional[int] = None,
                        feature_row_bytes: Optional[int] = None,
                        tick_metrics: bool = True) -> dict:
    """Traffic attribution rollup (`report.py --attribution` input).

    Bytes: frontier ids weigh 4 B (int32 on the wire), feature ids
    weigh one feature row (inferred from the node-feature store when
    not given).  ``hot_ranges`` prefers the GNS sketches' decayed
    range mass (the learned hotness); without an active sketch it
    falls back to the attribution matrix's column mass — measured
    demand per range (benchmarks/README "Fleet signal plane").
    """
    fr, ft = self.attribution_matrices()
    p = int(fr.shape[0])
    if feature_row_bytes is None:
      feature_row_bytes = 4
      try:
        sh = self.ds.node_features.shards
        feature_row_bytes = int(sh.shape[-1]) * int(
            np.dtype(sh.dtype).itemsize)
      except Exception:               # noqa: BLE001 — no feature
        pass                          # store on this sampler
    ids = fr + ft
    bytes_m = fr * 4 + ft * int(feature_row_bytes)
    # "local" is BOOK-OWNER-aware: cell (src device, dst range) costs
    # no wire bytes when the book routes range dst to device src —
    # under the identity book this is exactly the diagonal, and after
    # an adoption/rebalance the migrated range's column flips local on
    # its new owner's row (the matrices stay range-keyed).
    local_mask = np.eye(ids.shape[0], ids.shape[1], dtype=bool)
    book = getattr(self, 'book', None)
    if book is not None:
      try:
        owners = np.asarray(book.view().owners)
        if owners.shape[0] == ids.shape[1]:
          local_mask = (owners[None, :]
                        == np.arange(ids.shape[0])[:, None])
      except Exception:               # noqa: BLE001 — identity
        pass                          # fallback (no live view)
    # locally-served hits (ISSUE 20b) are feature rows the masked
    # gather served device-locally (replica copies + the owner
    # bypass's self-owned rows): they never reach the wire-truth
    # matrices, so credit them back as LOCAL demand.
    rep = self.replica_hits()
    total_ids = int(ids.sum()) + rep
    local_ids = int(ids[local_mask].sum()) + rep
    cross_ids = total_ids - local_ids
    rep_bytes = rep * int(feature_row_bytes)
    total_bytes = int(bytes_m.sum()) + rep_bytes
    cross_bytes = total_bytes - (int(bytes_m[local_mask].sum())
                                 + rep_bytes)

    mass = None
    source = 'exchange'
    cache = getattr(self, '_cold_cache', None)
    if cache is not None and getattr(cache, 'shards', None):
      ms = [sh.sketch.range_mass for sh in cache.shards
            if sh.sketch.range_mass is not None]
      if ms:
        agg = np.sum(ms, axis=0)
        if float(agg.sum()) > 0 and len(agg) == p:
          mass, source = agg.astype(np.float64), 'gns_sketch'
    if mass is None:
      mass = ids.sum(axis=0).astype(np.float64)   # demand per range
    total_mass = float(mass.sum())
    k = min(max(1, p // 4) if top_k is None else max(int(top_k), 1),
            max(p, 1))
    hot = []
    coverage = 0.0
    if p and total_mass > 0:
      order = np.argsort(-mass, kind='stable')[:k]
      hot = [{'partition': int(r),
              'share': round(float(mass[r] / total_mass), 6)}
             for r in order]
      coverage = round(float(mass[order].sum() / total_mass), 6)

    if tick_metrics:
      from ..telemetry.live import live
      d_local = max(local_ids - self._attr_reported[0], 0)
      d_cross = max(cross_ids - self._attr_reported[1], 0)
      self._attr_reported = (local_ids, cross_ids)
      if d_local:
        live.counter('exchange.local_ids_total').inc(d_local)
      if d_cross:
        live.counter('exchange.cross_ids_total').inc(d_cross)

    return {
        'num_parts': p,
        'feature_row_bytes': int(feature_row_bytes),
        'frontier_ids': fr.tolist(),
        'feature_ids': ft.tolist(),
        'bytes_matrix': bytes_m.tolist(),
        'local_ids': local_ids,
        'locally_served_ids': rep,
        'cross_ids': cross_ids,
        'cross_partition_ids_frac': (
            round(cross_ids / total_ids, 6) if total_ids else 0.0),
        'total_bytes': total_bytes,
        'cross_partition_bytes': cross_bytes,
        'cross_partition_bytes_frac': (
            round(cross_bytes / total_bytes, 6) if total_bytes
            else 0.0),
        'hotness_source': source,
        'top_k': k if p else 0,
        'hot_ranges': hot,
        'hot_range_coverage': coverage,
    }

  def _ewma_caps(self):
    """Per-channel ``(dest_cap, traffic_cap)`` dict for the step
    builders, or None when the EWMA model is off (the default — the
    compiled programs are then byte-identical to uniform shares)."""
    m = getattr(self, '_ewma_model', None)
    if m is None:
      return None
    caps = {c: m.caps(c) for c in m.CHANNELS}
    return caps if any(v != (None, None) for v in caps.values()) else None

  def capacity_retune(self) -> bool:
    """Epoch-end seam for the EWMA capacity co-design (ISSUE 20c):
    feed the attribution-matrix delta since the last retune into the
    `EwmaCapacityModel`; when a quantized cap moves, clear the step
    cache so the next dispatch compiles `capacity_spec(dest_cap=...)`
    sized to the OBSERVED per-destination traffic instead of uniform
    shares.  Returns True when the caps (and hence the programs)
    changed.  No-op unless GLT_EXCHANGE_EWMA is on."""
    m = getattr(self, '_ewma_model', None)
    if m is None:
      return False
    steps = int(self._step_cnt)
    d_steps = steps - self._ewma_last_steps
    if d_steps <= 0:
      return False
    fr, ft = self.attribution_matrices()
    last = self._ewma_last
    d_fr = fr - last[0] if last is not None else fr
    d_ft = ft - last[1] if last is not None else ft
    self._ewma_last = (fr, ft)
    self._ewma_last_steps = steps
    changed = m.observe('frontier', d_fr, d_steps)
    changed = m.observe('feature', d_ft, d_steps) or changed
    if changed:
      from ..telemetry.recorder import recorder
      caps = {c: m.caps(c) for c in m.CHANNELS}
      self._steps.clear()
      recorder.emit(
          'exchange.retune', steps=d_steps,
          frontier_dest_cap=caps['frontier'][0],
          frontier_traffic_cap=caps['frontier'][1],
          feature_dest_cap=caps['feature'][0],
          feature_traffic_cap=caps['feature'][1])
    return changed

  def cluster_exchange_stats(self) -> dict:
    """CLUSTER-wide exchange health: raw totals plus the derived
    padding-waste / drop-rate numbers the bench rounds track.

    The device-side counters are already global — each step's
    ``[P, 7]`` stats vector is summed over the sharded mesh axis
    before the host drains it, so every process reads the same
    cluster totals.  The HOST-side cold-tier counters are
    per-process; under multiple controllers they are summed over
    hosts via `telemetry.aggregate.allgather_sum_int`.  On a single
    controller (including the virtual CPU mesh) this is exactly
    `exchange_stats` plus the derived keys.
    """
    from ..telemetry.aggregate import allgather_sum_int, exchange_summary
    st = dict(self.exchange_stats())
    num_hosts = jax.process_count()
    if num_hosts > 1:
      keys = ('lookups', 'cold_lookups', 'cold_misses', 'cache_hits',
              'cache_admits', 'cache_evicts')
      summed = allgather_sum_int(
          [st[f'dist.feature.{k}'] for k in keys])
      for k, v in zip(keys, summed):
        st[f'dist.feature.{k}'] = v
      lookups, cold_lookups, cold_misses = summed[:3]
      st['dist.feature.hot_hit_rate'] = (
          1.0 - cold_lookups / lookups if lookups else 1.0)
      st['dist.feature.cache_hit_rate'] = (
          1.0 - cold_misses / cold_lookups if cold_lookups else 0.0)
      st['dist.feature.cold_hit_rate'] = st[
          'dist.feature.cache_hit_rate']
    st['num_hosts'] = num_hosts
    st.update(exchange_summary(st))
    return st


def put_stacked_host_local(mesh: Mesh, axis: str, num_parts: int,
                           host_parts, arr_local: np.ndarray) -> jax.Array:
  """Host-local put: this process holds only its partitions' slices
  (`DistDataset.host_parts`); assemble the GLOBAL ``[P, ...]`` array
  from per-device single-shard puts — no host ever materializes
  another host's tensors (the multi-host RAM story)."""
  from .multihost import host_partition_ids
  flat = mesh.devices.reshape(-1)
  mine = host_partition_ids(mesh).tolist()
  hp = list(np.asarray(host_parts))
  if mine != hp:
    raise ValueError(
        f'host_parts {hp} != this process\'s mesh positions {mine} '
        '— load with multihost.host_partition_ids(mesh)')
  assert arr_local.shape[0] == len(mine), (arr_local.shape, mine)
  shards = [jax.device_put(arr_local[j:j + 1], flat[i])
            for j, i in enumerate(mine)]
  return jax.make_array_from_single_device_arrays(
      (num_parts,) + tuple(arr_local.shape[1:]),
      NamedSharding(mesh, P(axis)), shards)


class DistNeighborSampler(ExchangeTelemetry):
  """Device-mesh distributed sampler (+ feature/label collection).

  The public analog of reference ``DistNeighborSampler``
  (`distributed/dist_neighbor_sampler.py:88-174`) — but synchronous
  SPMD: every call samples P per-device seed batches in one program.

  Args:
    dataset: `DistDataset` (sharded layout).
    num_neighbors: per-hop fanouts.
    mesh: mesh whose ``axis`` dimension matches the partition count.
  """

  def __init__(self, dataset: DistDataset, num_neighbors,
               mesh: Optional[Mesh] = None, axis: str = 'data',
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, exchange_slack: Optional[float] = None,
               exchange_layout: Optional[str] = None,
               cold_cache_rows='auto', gns=None):
    from .dp import make_mesh
    self.ds = dataset
    self.fanouts = tuple(int(k) for k in num_neighbors)
    self.num_parts = dataset.num_partitions
    self.mesh = mesh or make_mesh(self.num_parts, axis)
    self.axis = axis
    self.with_edge = with_edge
    self.collect_features = (collect_features
                             and dataset.node_features is not None)
    self.collect_labels = dataset.node_labels is not None
    # edge features need the sampled eids to gather by — implied
    # with_edge, like the reference's `with_edge=True` efeats contract
    self.collect_edge_features = (collect_features and with_edge
                                  and dataset.edge_features is not None)
    self._ef_shard_mode = (
        'mod' if (self.collect_edge_features
                  and dataset.edge_features.mod_sharded) else 'range')
    self.with_cache = (self.collect_features
                       and dataset.node_features.has_cache)
    # ISSUE 20 replica set (`from_full_graph(replica_frac=)`): the
    # cached rows are exact copies of remote rows, so the gather can
    # MASK them out of the exchange (served by the overlay) instead of
    # fetching them twice.  Offline cache plans (`cache_local=False`)
    # keep the historical overlay-after-gather semantics byte-for-byte.
    # Label collection shares the gathered id vector, so masking is
    # only sound when labels aren't gathered alongside.
    self.cache_local = bool(
        self.with_cache
        and getattr(dataset.node_features, 'cache_local', False)
        and self.collect_features and not self.collect_labels)
    # tiered store: HBM shards hold only each partition's hot rows;
    # cold rows live in host DRAM and are overlaid post-step
    # (`_maybe_overlay_cold`) — VERDICT r2 item 1 / reference
    # `data/feature.py:174-206` + `csrc/cuda/unified_tensor.cu:202+`.
    self.tiered = (self.collect_features
                   and dataset.node_features.is_tiered)
    # dynamic HBM victim cache over cold rows (`data.cold_cache`):
    # built lazily on the first cold overlay; 'auto' sizes it to
    # GLT_COLD_CACHE_ROWS or 15% of the largest partition's cold rows
    self._cold_cache_spec = cold_cache_rows
    self._cold_cache = None
    self._cold_cache_built = False
    # cache-aware Global Neighbor Sampling (ops.gns, r11): bias
    # neighbor selection toward the device-servable set (hot split ∪
    # cold-cache residents) with a 1/q unbiasedness correction.  Only
    # meaningful on tiered feature stores (a fully-HBM table has no
    # cold tier to steer away from); `GLT_GNS=1` / gns=True enables,
    # off is byte-identical to the unbiased sampler.
    from ..ops.gns import gns_enabled, resolve_boost
    self.gns = bool(gns_enabled(gns) and self.tiered
                    and self.collect_features)
    self.gns_boost = resolve_boost() if self.gns else None
    self._gns_bits = None
    self._gns_hot_bits = None
    self._gns_ver = -1
    # SURVEY §7 "partition-aware capacity tuning": e.g. 2.0 sends
    # 2x the balanced share per destination instead of the full
    # frontier (P/2 x fewer exchanged bytes); overflowed ids lose
    # their neighbors/features that hop (counted by the telemetry).
    # None = exact; the loaders resolve 'auto' to
    # DEFAULT_EXCHANGE_SLACK when shuffling, exact otherwise.
    self.exchange_slack = exchange_slack
    # exchange LAYOUT (parallel.exchange): None/'auto' keeps dense on
    # small meshes and compacts at P >= 16; 'dense'/'compact'/'hier'/
    # 'ragged' select explicitly (env GLT_EXCHANGE_LAYOUT overrides
    # 'auto' only).  Exact exchanges (slack None) always run dense.
    self.exchange_layout = exchange_layout or 'auto'
    # ISSUE 20 exchange co-design: per-destination capacity from an
    # EWMA of the attribution matrices (GLT_EXCHANGE_EWMA=1).  The
    # model observes matrix deltas at `capacity_retune()` (epoch end)
    # and its power-of-two caps feed `capacity_spec(dest_cap=...)`;
    # off (default) compiles exactly the uniform-share programs.
    from .exchange import EwmaCapacityModel, ewma_enabled
    self._ewma_model = (EwmaCapacityModel(self.num_parts)
                        if ewma_enabled() else None)
    self._ewma_last = None
    self._ewma_last_steps = 0
    self._base_key = jax.random.key(seed)
    self._step_cnt = 0
    self._steps = {}
    self._device_arrays = None
    #: ISSUE 15 — the single routing authority.  The sampler compiles
    #: its steps against one pinned `BookView` per dispatch and fences
    #: at the `_arrays()` seam: a version bump (adoption) rebuilds the
    #: device arrays lane-stacked and recompiles the exchange plans
    #: for the new routing.  The identity book (version 0) compiles
    #: EXACTLY the pre-book program.
    self.book = dataset.partition_book
    self._book_ver = self.book.version
    self._shard_store = None
    # degraded write-offs are DATASET state (the stacks are zeroed in
    # place): the set is shared so every sampler over this dataset
    # classifies the loss identically, and `maybe_refresh_book` fences
    # on its size so siblings rebuild from the emptied stacks instead
    # of serving a stale full view
    if not hasattr(dataset, 'degraded_partitions'):
      dataset.degraded_partitions = set()
    self._degraded_partitions = dataset.degraded_partitions
    self._degraded_seen = len(self._degraded_partitions)
    # the load-time durable copy: with GLT_SHARD_DIR set, the shards
    # are written NOW (idempotent across samplers over one dataset) —
    # an owner lost later adopts from this copy, and recovery never
    # pays (or depends on) a serialize of the dead owner's memory
    self._resolve_shard_store()
    #: streaming ingestion (ISSUE 14): last `graph_version` this
    #: sampler's stacks were (re)built from.  Seeded from the version
    #: `attach_stream` restacked ds.graph at, so the first dispatch
    #: doesn't repeat that restack on an identical graph (None =
    #: static dataset).
    self._stream_ver = getattr(dataset, 'stream_version', None)
    self._init_stats()

  def _put_stacked(self, arr_local: np.ndarray) -> jax.Array:
    return put_stacked_host_local(self.mesh, self.axis, self.num_parts,
                                  self.ds.host_parts, arr_local)

  def _put_shard(self, a: np.ndarray) -> jax.Array:
    """One ``[P, ...]`` stack onto the mesh — the same placement
    `_arrays` uses (host-local stacks on multi-host, a sharded
    `device_put` under a single controller)."""
    if getattr(self.ds, 'host_parts', None) is not None:
      return self._put_stacked(a)
    return jax.device_put(a, NamedSharding(self.mesh, P(self.axis)))

  def maybe_refresh_stream(self):
    """Version fence for streaming ingestion (ISSUE 14): when the
    dataset carries a `streaming.StreamingGraph` handle
    (`DistDataset.attach_stream`), re-pin the newest published view
    at this dispatch seam — restack the per-partition CSR by the
    FROZEN partition book (`restack_stream_view`) and RCU-swap the
    device-arrays dict, so the dispatch that called `_arrays()` works
    against exactly one ``graph_version`` end to end.  The cached-set
    bitmask is invalidated at the same seam (``_gns_ver`` reset):
    derived structures refresh with the graph they derive from.
    Returns the pinned version (None without a stream)."""
    stream = getattr(self.ds, 'stream', None)
    if stream is None:
      return None
    view = stream.pin()
    if view.version == self._stream_ver:
      return self._stream_ver
    from .dist_data import DistGraph, restack_stream_view
    g = self.ds.graph
    indptr_s, indices_s, eids_s = restack_stream_view(
        view, self.ds.old2new, g.bounds,
        min_edge_width=int(g.indices.shape[1]))
    # a degraded write-off stays written off: the restack rebuilds
    # every partition from the stream, which would resurrect the dead
    # owner's topology against its zeroed feature shard
    for p in self._degraded_partitions:
      indptr_s[p] = 0
      indices_s[p] = -1
      eids_s[p] = -1
    self.ds.graph = DistGraph(indptr_s, indices_s, eids_s, g.bounds)
    # adopted lanes track the restacked topology too: the stream owns
    # the full graph — the parked durable payload was only the
    # bootstrap copy (feature/label fields stay: topology-only stream)
    adopted = getattr(self.ds, 'adopted_shards', None)
    if adopted:
      for r in list(adopted):
        adopted[r] = dict(adopted[r], indptr=np.asarray(indptr_s[r]),
                          indices=np.asarray(indices_s[r]),
                          eids=np.asarray(eids_s[r]))
    if self._device_arrays is not None:
      if self.book.version or self._degraded_partitions:
        # lane-stacked arrays (post-adoption) — the in-place [P, W]
        # patch would drop the lane axis the compiled book steps
        # expect; rebuild at the seam instead
        self._device_arrays = None
        self._steps.clear()
      else:
        arrs = dict(self._device_arrays)  # RCU: in-flight dicts frozen
        arrs['indptr'] = self._put_shard(indptr_s)
        arrs['indices'] = self._put_shard(indices_s)
        arrs['eids'] = self._put_shard(eids_s)
        self._device_arrays = arrs
    self._gns_ver = -1                   # version-fenced invalidation
    self._stream_ver = view.version
    self.ds.stream_version = view.version  # later samplers seed here
    return self._stream_ver

  # -- elastic partition failover (ISSUE 15) -------------------------------
  def _resolve_shard_store(self):
    """The durable `failover.ShardStore` under ``GLT_SHARD_DIR``
    (None = failover off, degraded semantics unchanged).  First
    resolution WRITES the dataset's shards (the load-time durable
    copy the tentpole requires) unless the store already covers this
    partition count — single-controller only; host-local layouts
    would write other hosts' shards from placeholders."""
    if self._shard_store is not None:
      return self._shard_store
    from .failover import ShardStore, shard_dir_from_env
    d = shard_dir_from_env()
    if d is None or getattr(self.ds, 'host_parts', None) is not None:
      return None
    store = ShardStore(d)
    written = getattr(self.ds, '_shards_written', False)
    meta = store.meta()
    g = self.ds.graph
    # a stale store (different graph under the same dir) must be
    # overwritten, not trusted: shape alone can collide (a regenerated
    # same-config dataset), so the content fingerprint is checked too;
    # edge-width growth (streaming reserve) is allowed since adoption
    # pads narrower durable rows to the live width
    from .failover import dataset_fingerprint
    stale = (meta is None
             or meta.get('num_parts') != self.num_parts
             or meta.get('num_nodes') != int(g.num_nodes)
             or meta.get('node_width') != int(g.indptr.shape[1])
             or int(meta.get('edge_width', 0)) > int(g.indices.shape[1])
             or meta.get('fingerprint') not in
             (None, dataset_fingerprint(self.ds)))
    if not written and stale:
      store.write_dataset_shards(self.ds)
    self.ds._shards_written = True
    self._shard_store = store
    return store

  def _partition_supervision(self) -> None:
    """Chaos-seam owner supervision, run at every dispatch seam
    BEFORE the step counter advances: a planned ``partition.owner``
    kill classifies that owner dead (the in-process stand-in for the
    PR 13 heartbeat-miss discriminator; ``delay`` models a slow-but-
    alive owner and only costs wall clock) and recovery runs the
    documented ladder — adopt (durable shard present) → degraded
    (``GLT_DEGRADED_OK=1``) → typed `PartitionLostError`.  After a
    successful adoption the SAME dispatch proceeds: the key stream
    never advanced, so the recovered batch is byte-identical to the
    fault-free one."""
    from ..testing import chaos
    from .failover import PartitionLostError
    try:
      chaos.partition_owner_check(step=self._step_cnt + 1)
    except PartitionLostError as e:
      self._on_partition_lost(e)

  def _on_partition_lost(self, err) -> None:
    """One owner classified dead: run the fallback ladder."""
    import time as _time
    from ..distributed.resilience import degraded_ok
    from ..telemetry.recorder import recorder
    from .failover import NoDurableShardError, adopt_shard
    from .partition_book import AdoptionRefusedError
    p = int(err.partition or 0)
    if p in self._degraded_partitions:
      return                      # already written off (degraded)
    view = self.book.view()
    if int(view.owners[p]) != p:
      return                      # already adopted — reader just fences
    t0 = _time.monotonic()
    try:
      info = adopt_shard(self.ds, self._resolve_shard_store(), p)
    except (NoDurableShardError, AdoptionRefusedError) as e:
      # the documented ladder: adoption unavailable (no durable
      # shard, no eligible survivor, foreign store, adopt timeout) →
      # degraded when the operator opted in, typed otherwise
      if not degraded_ok():
        raise type(err)(
            f'partition {p} lost and adoption is unavailable '
            f'({e}); set GLT_SHARD_DIR for elastic failover or '
            f'GLT_DEGRADED_OK=1 for reduced completion',
            partition=p) from e
      self._enter_degraded(p)
      return
    self._adopt_pending_t0 = (t0, p, info['survivor'])
    recorder.emit('peer.lost', peer=p, peer_kind='partition',
                  degraded=False, adopted=True,
                  survivor=info['survivor'])

  def _enter_degraded(self, p: int) -> None:
    """Documented ``GLT_DEGRADED_OK`` fallback: the orphaned shard's
    nodes VANISH from the epoch (its CSR row and feature shard are
    emptied) — reduced data, exact accounting, flagged typed in the
    flight recorder, never a silent wrong answer."""
    from ..telemetry.recorder import recorder
    self._degraded_partitions.add(p)
    g = self.ds.graph
    g.indptr[p] = 0
    g.indices[p] = -1
    g.edge_ids[p] = -1
    nf = self.ds.node_features
    if nf is not None:
      nf.shards[p] = 0
      if nf.cold_host is not None:
        b = np.asarray(g.bounds, np.int64)
        nf.cold_host[b[p]:b[p + 1]] = 0
    self._device_arrays = None       # rebuild from the emptied stacks
    self._steps.clear()
    self._gns_ver = -1
    self._degraded_seen = len(self._degraded_partitions)
    recorder.emit('peer.lost', peer=p, peer_kind='partition',
                  degraded=True, adopted=False)

  def _complete_recovery(self) -> None:
    """First successful dispatch after an adoption: close the
    recovery clock (classification → served batch) into the
    ``partition.recovery_secs`` gauge."""
    pending = getattr(self, '_adopt_pending_t0', None)
    if pending is None:
      return
    import time as _time
    from ..telemetry.live import live
    from ..telemetry.recorder import recorder
    t0, p, survivor = pending
    self._adopt_pending_t0 = None
    secs = _time.monotonic() - t0
    live.gauge('partition.recovery_secs').set(float(secs))
    recorder.emit('partition.adopt', partition=p, survivor=survivor,
                  version=self.book.version, phase='recovered',
                  secs=round(secs, 6))

  def maybe_refresh_book(self):
    """Version fence for partition ownership (ISSUE 15) — the same
    RCU discipline as `maybe_refresh_stream`: when the shared
    `PartitionBook` published a newer view (an adoption), rebuild the
    owner-side device arrays LANE-STACKED for the new routing, clear
    the step cache (the `BookSpec` is a trace-time constant — new
    routing = new exchange plans and capacity specs) and invalidate
    the GNS bitmask (derived structures refresh with the placement
    they derive from).  Readers hold one view per dispatch; a bump
    mid-dispatch swaps the attribute, never the arrays in flight."""
    ver = self.book.version
    ndeg = len(self._degraded_partitions)
    if ver == self._book_ver and ndeg == self._degraded_seen:
      return ver
    self._book_ver = ver
    self._book_view = self.book.view()
    self._degraded_seen = ndeg
    self._device_arrays = None
    self._steps.clear()
    self._gns_ver = -1
    return ver

  @property
  def book_spec(self):
    """Hashable static routing tables of the PINNED view (None =
    identity book: every step compiles the pre-book program)."""
    view = getattr(self, '_book_view', None)
    if view is None or view.version != self._book_ver:
      self._book_view = view = self.book.view()
    return view.spec()

  def _lane_source(self, r: int) -> dict:
    """Shard payload serving range ``r``: the durably re-loaded copy
    for adopted ranges (`failover.adopt_shard` parked it), the live
    stacks otherwise."""
    adopted = getattr(self.ds, 'adopted_shards', {})
    if r in adopted:
      return adopted[r]
    g = self.ds.graph
    out = {'indptr': g.indptr[r], 'indices': g.indices[r],
           'eids': g.edge_ids[r]}
    nf = self.ds.node_features
    if self.collect_features and nf is not None:
      out['fshard'] = nf.shards[r]
    if self.collect_labels and self.ds.node_labels is not None:
      out['lshard'] = np.asarray(self.ds.node_labels)[r]
    if self.collect_edge_features:
      out['efshard'] = self.ds.edge_features.shards[r]
    return out

  def _lane_stacked(self, key: str, template: np.ndarray, fill):
    """``[P, ...]`` owner-side stack → ``[P, S, ...]`` lane stack:
    device ``d``'s lane ``j`` holds the shard of range
    ``slot_ranges[d, j]`` (unassigned lanes hold ``fill``)."""
    view = self._book_view
    p, s = view.num_partitions, int(view.num_lanes)
    out = np.full((p, s) + tuple(template.shape[1:]), fill,
                  template.dtype)
    for d in range(p):
      for j in range(s):
        r = int(view.slot_ranges[d, j])
        if r < 0:
          continue
        src = self._lane_source(r).get(key)
        if src is None:
          continue
        src = np.asarray(src, template.dtype)
        sl = tuple(slice(0, n) for n in src.shape)
        out[(d, j) + sl] = src
    return out

  def _arrays(self):
    # book fence FIRST: a version bump (adoption) drops the cached
    # dict and the compiled steps, so this dispatch rebuilds against
    # exactly one pinned BookView (`maybe_refresh_book`)
    self.maybe_refresh_book()
    if self._device_arrays is None:
      shard = NamedSharding(self.mesh, P(self.axis))
      repl = NamedSharding(self.mesh, P())
      g = self.ds.graph
      put = jax.device_put
      fshards = (self.ds.node_features.shards if self.collect_features
                 else np.zeros((self.num_parts, 1, 1), np.float32))
      lshards = (self.ds.node_labels if self.collect_labels
                 else np.zeros((self.num_parts, 1), np.int32))
      if self.with_cache:
        cids = self.ds.node_features.cache_ids
        crows = self.ds.node_features.cache_rows
      else:
        from .dist_data import CACHE_PAD_ID
        cids = np.full((self.num_parts, 1), CACHE_PAD_ID, np.int32)
        crows = np.zeros((self.num_parts, 1, 1), np.float32)
      if self.collect_edge_features:
        efshards = self.ds.edge_features.shards
        ebounds = self.ds.edge_features.bounds
      else:
        efshards = np.zeros((self.num_parts, 1, 1), np.float32)
        ebounds = np.zeros(self.num_parts + 1, np.int64)
      hcounts = (self.ds.node_features.hot_counts
                 if self.collect_features
                 else np.zeros(self.num_parts, np.int32))
      if getattr(self.ds, 'host_parts', None) is not None:
        # stacked arrays hold ONLY this host's partitions: assemble
        # the global sharded arrays shard-by-shard.  Placeholder
        # tables must match the LOCAL stack height.
        pl = len(self.ds.host_parts)
        if not self.collect_features:
          fshards = np.zeros((pl, 1, 1), np.float32)
        if not self.collect_labels:
          lshards = np.zeros((pl, 1), np.int32)
        if not self.with_cache:
          cids = cids[:pl]
          crows = crows[:pl]
        if not self.collect_edge_features:
          efshards = efshards[:pl]
        putS = self._put_stacked
      else:
        putS = lambda a: put(a, shard)       # noqa: E731
      spec = self.book_spec
      if spec is None:
        # identity book: EXACTLY the pre-book arrays (the fault-free
        # byte-identity contract — failover compiled in costs nothing)
        self._device_arrays = dict(
            indptr=putS(g.indptr), indices=putS(g.indices),
            eids=putS(g.edge_ids), bounds=put(g.bounds, repl),
            fshards=putS(np.asarray(fshards)),
            lshards=putS(np.asarray(lshards)),
            cids=putS(cids), crows=putS(crows),
            efshards=putS(efshards), ebounds=put(ebounds, repl),
            hcounts=put(np.asarray(hcounts, np.int32), repl))
      else:
        # adopted book: owner-side stacks grow a lane axis — device
        # ``d`` lane ``j`` serves range ``slot_ranges[d, j]``, adopted
        # lanes built from the DURABLE shard payload.  Requester-side
        # arrays (the offline remote-hot cache) keep their shape.
        self._device_arrays = dict(
            indptr=putS(self._lane_stacked('indptr', g.indptr, 0)),
            indices=putS(self._lane_stacked('indices', g.indices, -1)),
            eids=putS(self._lane_stacked('eids', g.edge_ids, -1)),
            bounds=put(g.bounds, repl),
            fshards=putS(self._lane_stacked('fshard',
                                            np.asarray(fshards), 0)),
            lshards=putS(self._lane_stacked('lshard',
                                            np.asarray(lshards), 0)),
            cids=putS(cids), crows=putS(crows),
            efshards=putS(self._lane_stacked('efshard', efshards, 0)),
            ebounds=put(ebounds, repl),
            hcounts=put(np.asarray(hcounts, np.int32), repl))
    # streaming fence: re-pin the newest published graph version at
    # the dispatch seam (no-op for static datasets).  Callers hold
    # the RETURNED dict for the whole dispatch — a publish landing
    # mid-dispatch swaps the attribute, never the dict in flight.
    self.maybe_refresh_stream()
    return self._device_arrays

  def node_capacity(self, batch_size: int) -> int:
    cap = max_sampled_nodes(batch_size, self.fanouts)
    cap = min(cap, batch_size + self.ds.graph.num_nodes)
    return round_up(cap, 8)

  def step_for_batch(self, batch_size: int):
    """The compiled SPMD step for per-device batches of ``batch_size``
    (built once per size).  Signature: ``step(indptr, indices, eids,
    bounds, seeds, fshards, lshards, cids, crows, efshards, ebounds,
    hcounts, key)`` — also the scan body of `FusedDistEpoch`."""
    cfg = (int(batch_size),)
    if cfg not in self._steps:
      with self._layout_span(batch=int(batch_size)):
        self._steps[cfg] = _make_dist_step(
            self.mesh, self.num_parts, self.fanouts,
            self.node_capacity(int(batch_size)),
            self.with_edge, self.collect_features, self.collect_labels,
            self.axis, with_cache=self.with_cache,
            exchange_slack=self.exchange_slack,
            exchange_layout=self.exchange_layout,
            collect_edge_features=self.collect_edge_features,
            ef_shard_mode=self._ef_shard_mode, tiered=self.tiered,
            gns_boost=self.gns_boost, book_spec=self.book_spec,
            cache_local=self.cache_local, ewma_caps=self._ewma_caps())
      if self.gns:
        from ..telemetry.recorder import recorder
        from ..utils.profiling import metrics
        metrics.inc('gns.bias_steps_total')
        recorder.emit('gns.bias', batch=int(batch_size),
                      boost=float(self.gns_boost),
                      num_parts=self.num_parts)
    return self._steps[cfg]

  def _layout_span(self, **fields):
    """Build-time `exchange.layout` span around step construction: the
    resolved layout + slack land in the flight recorder once per
    compiled program (the runtime path stays span-free)."""
    from ..telemetry.spans import span
    return span('exchange.layout',
                layout=resolve_layout(self.exchange_layout,
                                      self.num_parts),
                num_parts=self.num_parts,
                slack=self.exchange_slack, **fields)

  def sample_from_nodes(self, seeds_stacked: np.ndarray, key=None):
    """``seeds_stacked``: ``[P, B]`` per-device seed batches (relabeled
    id space, -1 padded).  Returns stacked pytree pieces.  ``key``
    overrides the internal key stream (the fused-vs-per-batch parity
    tests drive both engines with identical keys)."""
    return self._finish_nodes(self._dispatch_nodes(seeds_stacked, key))

  def _dispatch_nodes(self, seeds_stacked: np.ndarray, key=None):
    """Dispatch the SPMD sample+collect step WITHOUT the cold-tier
    finish: the returned dict's arrays are in flight on device.  With
    `_finish_nodes` this is the loaders' double-buffered cold
    pipeline — batch k+1's sampling runs on device while batch k's
    cold overlay does its host work (`PrefetchingLoader._pipelined`).
    """
    from ..telemetry.spans import span
    b = seeds_stacked.shape[1]
    # supervision + fence BEFORE step resolution: an adoption here
    # clears the step cache and the step must compile for the new
    # routing, with the key stream still un-advanced (byte-identity)
    self._partition_supervision()
    arrs = self._arrays()
    step = self.step_for_batch(b)
    self._step_cnt += 1
    if key is None:
      key = jax.random.fold_in(self._base_key, self._step_cnt)
    # 'sample.exchange': the fused sample+exchange SPMD dispatch —
    # async, so its duration is dispatch latency; sync time (the
    # stage-attribution signal) lands in the feature.lookup child
    # whenever a cold overlay forces the host to wait
    with span('sample.exchange', step=self._step_cnt, batch=b):
      seeds_dev = jax.device_put(
          np.asarray(seeds_stacked, dtype=np.int32),
          NamedSharding(self.mesh, P(self.axis)))
      extra = (self._gns_arrays(),) if self.gns else ()
      outs = step(arrs['indptr'], arrs['indices'], arrs['eids'],
                  arrs['bounds'], seeds_dev, arrs['fshards'],
                  arrs['lshards'], arrs['cids'], arrs['crows'],
                  arrs['efshards'], arrs['ebounds'],
                  arrs['hcounts'], *extra, key)
      (nodes, count, row, col, edge, seed_local, x, y, ef, nsn,
       stats) = outs[:11]
      ew = outs[11] if self.gns else None
    # outside the span: the every-64th-call drain blocks on the
    # device, and that sync must not masquerade as dispatch latency
    self._complete_recovery()
    self._accumulate_stats(stats)
    out = dict(node=nodes, node_count=count[..., 0], row=row, col=col,
               edge=edge, seed_local=seed_local, x=x, y=y, ef=ef,
               num_sampled_nodes=nsn, batch=seeds_dev,
               overlay_step=self._step_cnt)
    if ew is not None:
      out['edge_weight'] = ew
    return out

  def _finish_nodes(self, out: dict) -> dict:
    """The host half of a dispatched step: the cold-tier overlay
    (no-op for untiered stores).  ``overlay_step`` pins the span to
    the step that DISPATCHED this batch — under the cold pipeline
    batch k+1's dispatch has already advanced ``_step_cnt`` by the
    time batch k's overlay runs."""
    out['x'] = self._maybe_overlay_cold(out['x'], out['node'],
                                        step=out.pop('overlay_step',
                                                     None))
    return out

  def _maybe_overlay_cold(self, x, nodes, step=None):
    """Overlay host-DRAM cold-tier rows onto the exchanged features
    (requester-side `overlay_cold_host` for single-controller
    ``cold_host`` tables; owner-served `overlay_cold_owner` for
    host-local ``cold_local`` stacks) and tick the cold telemetry."""
    if not self.tiered or x is None:
      return x
    from ..telemetry.spans import span
    with span('feature.lookup',
              step=self._step_cnt if step is None else step):
      return self._overlay_cold_traced(x, nodes)

  def _ensure_cold_cache(self):
    """Build the `MeshColdCache` on first use (the budget needs the
    feature dim and the partitions' cold-row counts, both known only
    for tiered stores)."""
    if self._cold_cache_built:
      return self._cold_cache
    self._cold_cache_built = True
    if not self.tiered:
      return None
    from ..data.cold_cache import MeshColdCache, resolve_cache_rows
    nf = self.ds.node_features
    counts = np.diff(self.ds.graph.bounds)
    cold_rows = int(np.maximum(counts - nf.hot_counts, 0).max(
        initial=0))
    cap = resolve_cache_rows(self._cold_cache_spec, cold_rows)
    if cap > 0:
      num_local = (len(self.ds.host_parts)
                   if self.ds.host_parts is not None
                   else self.num_parts)
      shard = NamedSharding(self.mesh, P(self.axis))
      putS = (self._put_stacked
              if self.ds.host_parts is not None
              else (lambda a: jax.device_put(a, shard)))
      self._cold_cache = MeshColdCache(
          cap, nf.shards.shape[-1], nf.shards.dtype, num_local,
          self.mesh, self.axis, putS, bounds=self.ds.graph.bounds)
    return self._cold_cache

  def _gns_arrays(self) -> jax.Array:
    """The replicated cached-set bitmask (`ops.gns.cached_set_bits`)
    for the GNS step's ``gns_bits`` input, rebuilt ONLY when the cold
    cache's residency actually changed (its version counter) — the
    refresh is one N/8-byte host build + replicated transfer, paid
    per admission wave, never per step.

    Staleness is harmless by construction: the importance weights
    correct ANY membership mask exactly, so a mask lagging one batch
    behind the ring costs a little bias-placement efficiency, zero
    estimator bias (`ops.gns` module docstring).
    """
    cache = self._ensure_cold_cache()
    ver = cache.version if cache is not None else 0
    if self._gns_bits is None or ver != self._gns_ver:
      from ..ops.gns import cached_set_bits, dedup_requester_bits
      n = self.ds.graph.num_nodes
      if self._gns_hot_bits is None:
        # the static half, packed once: refreshes pay O(bytes) copy
        # + O(residents), not the O(num_nodes) bool rebuild
        self._gns_hot_bits = cached_set_bits(
            n, self.ds.graph.bounds,
            self.ds.node_features.hot_counts, np.empty(0, np.int64))
      # PER-REQUESTER masks (ISSUE 15, the PR 10 known-limit fix):
      # row d = hot split ∪ device d's OWN cache residents, last row
      # = hot-only fallback for unattributable recv rows.  The union
      # mask over-boosted rows resident only on another device's ring
      # — a remote-only resident now gets no boost locally.  Devices
      # outside this host (host_parts) stay hot-only: unknown
      # residency must never over-boost (weights keep ANY mask
      # unbiased; a conservative mask costs placement, not bias).
      residents_by_dev = {}
      n_res = 0
      if cache is not None:
        hp = (self.ds.host_parts if self.ds.host_parts is not None
              else np.arange(self.num_parts))
        for j, sh in enumerate(cache.shards):
          res = sh.resident_ids()
          residents_by_dev[int(hp[j])] = res
          n_res += len(res)
      # r19 dedup: devices sharing a mask row (no residents of their
      # own, plus the fallback) point at ONE shared row through the
      # int32 indirection map — [T, N/8] + [R+1] instead of the
      # [R+1, N/8] replication, consumed identically by the XLA and
      # Pallas bias paths (equivalence pinned in
      # tests/test_pallas_sample.py)
      table, row_index = dedup_requester_bits(
          n, self.ds.graph.bounds,
          self.ds.node_features.hot_counts, residents_by_dev,
          base_bits=self._gns_hot_bits)
      repl = NamedSharding(self.mesh, P())
      self._gns_bits = (jax.device_put(table, repl),
                        jax.device_put(row_index, repl))
      self._gns_ver = ver
      mask_bytes = int(table.nbytes) + int(row_index.nbytes)
      # memory accounting (ISSUE 17): the replicated bitmask is the
      # GNS tier's whole bill; re-registered on each rebuild so the
      # gauge tracks the live arrays
      from ..telemetry.memaccount import register_tier
      register_tier(
          'gns', lambda b=self._gns_bits: sum(
              int(getattr(a, 'nbytes', 0)) for a in b))
      from ..utils.profiling import metrics
      metrics.inc('gns.sketch_updates_total')
      from ..telemetry.recorder import recorder
      if recorder.enabled:
        recorder.emit('gns.sketch_update', scope='dist',
                      residents=int(n_res), version=int(ver),
                      mask_bytes=mask_bytes)
    return self._gns_bits

  def _overlay_cold_traced(self, x, nodes):
    """The overlay body, under `_maybe_overlay_cold`'s span — the
    span exists only for tiered stores, where this is the per-batch
    host sync worth attributing.

    Order of service per batch: (1) hits in the dynamic HBM victim
    cache are overlaid by a purely local device gather (no host
    bytes); (2) residual misses ride the host cold tier
    (requester-side `overlay_cold_host` or owner-served
    `overlay_cold_owner`); (3) the now-corrected miss rows are
    admitted into the cache (device→device `at[].set`), so the next
    batch's repeats hit — the cross-batch cold-id dedup.
    """
    from ..data.cold_cache import emit_cache_events
    from ..testing import chaos
    # chaos seam: the host cold tier can die mid-epoch; a planned
    # 'fail' surfaces here, before any host gather
    chaos.cold_service_check('dist')
    nf = self.ds.node_features
    g = self.ds.graph
    cache = self._ensure_cold_cache()
    hits = admits = evicts = 0
    if nf.cold_host is not None:
      # single-controller table: every shard addressable
      nodes_l = np.asarray(jax.device_get(nodes)).astype(np.int64)
      valid = nodes_l >= 0
      # placement reads through the book's frozen-range rule (ISSUE
      # 15): the hot/cold split keys on the RANGE — adoption moves the
      # serving device, never a row's tier
      _rng, local, cold = hot_split_host(g.bounds, nf.hot_counts,
                                         nodes_l, valid)
      lookups, cold_n = int(valid.sum()), int(cold.sum())
      miss = cold
      if cache is not None:
        hit, slot = cache.lookup(nodes_l, cold)
        hits = int(hit.sum())
        x = cache.serve(x, hit, slot)
        miss = cold & ~hit
      x, _, served = overlay_cold_host(
          x, nodes, g.bounds, nf.hot_counts, nf.cold_host, self.mesh,
          self.axis, self.num_parts, nodes_host=nodes_l,
          cold_mask=miss)
      if cache is not None and miss.any():
        plans = cache.plan_admissions(nodes_l, miss)
        admits, evicts = cache.commit_admissions(
            x, plans, cache.admit_width(plans))
    else:
      hp = (self.ds.host_parts if self.ds.host_parts is not None
            else np.arange(self.num_parts))
      plan = plan_cold_requests(nodes, g.bounds, nf.hot_counts, hp,
                                cache_ids=nf.cache_ids)
      hp_, nodes_l, valid, owner, cold, counts, lookups = plan
      cold_n = int(cold.sum())
      if cache is not None:
        hit, slot = cache.lookup(nodes_l, cold)
        hits = int(hit.sum())
        # serve runs UNCONDITIONALLY under multiple controllers: every
        # process must dispatch the same programs on the global arrays
        x = cache.serve(x, hit, slot)
        miss = cold & ~hit
        counts = np.zeros_like(counts)
        sel_j, sel_pos = np.nonzero(miss)
        if len(sel_j):
          np.add.at(counts, (sel_j, owner[sel_j, sel_pos]), 1)
        plan = (hp_, nodes_l, valid, owner, miss, counts, lookups)
        adm_plans = cache.plan_admissions(nodes_l, miss)
        # ONE handshake agrees on both per-batch program widths
        caps = _global_max_vec([int(counts.max(initial=0)),
                                cache.admit_width(adm_plans)])
        x, _, served = overlay_cold_owner(
            x, nodes, g.bounds, nf.hot_counts, nf.cold_local,
            self.mesh, self.axis, self.num_parts, hp, plan_=plan,
            agreed_capacity=caps[0])
        admits, evicts = cache.commit_admissions(x, adm_plans,
                                                 caps[1])
      else:
        x, _, served = overlay_cold_owner(
            x, nodes, g.bounds, nf.hot_counts, nf.cold_local,
            self.mesh, self.axis, self.num_parts, hp, plan_=plan)
    with self._stats_lock:
      self._feat_lookups += lookups
      self._cold_lookups += cold_n
      self._cold_misses += served
      self._cache_hits += hits
      self._cache_admits += admits
      self._cache_evicts += evicts
    if cache is not None:
      # cache-off runs (GLT_COLD_CACHE_ROWS=0, the static-split bench
      # baseline) must not record phantom cache.miss traffic — cold
      # service without a cache is already visible as cold_misses
      emit_cache_events('dist', hits, served, admits, evicts)
    return x

  # -- DataPlaneState (utils.checkpoint) ----------------------------------
  def data_plane_state(self) -> dict:
    """Key-stream cursor + cold-cache rings.  ``step_cnt`` positions
    the per-batch sampling keys (``fold_in(base_key, step_cnt)``) —
    restoring it is what makes resumed batches byte-identical."""
    state = {'step_cnt': self._step_cnt}
    cache = self._ensure_cold_cache()
    if cache is not None:
      state['cache'] = cache.state_dict()
    return state

  def load_data_plane_state(self, state: dict) -> None:
    self._step_cnt = int(np.asarray(state['step_cnt']))
    if 'cache' in state:
      cache = self._ensure_cold_cache()
      if cache is not None:
        cache.load_state_dict(state['cache'])


@jax.jit
def _overlay_cold_rows(x, mask, rank, compact):
  """``x[p, i] = compact[rank[p, i]] where mask`` — the device half of
  the cold-tier overlay (`overlay_cold_host`)."""
  return jnp.where(mask[..., None], compact[rank], x)


def overlay_cold_host(x, nodes, bounds, hot_counts, cold_host, mesh,
                      axis: str, num_parts: int, nodes_host=None,
                      cold_mask=None):
  """Serve cold-tier rows (host DRAM) for node-table entries the HBM
  exchange zeroed — shared by the homo and hetero mesh engines.

  Tiered stores serve only HBM-hot rows through the all_to_all
  (owners zero rows past their hot count); the cold remainder is
  host-gathered into a COMPACT replicated buffer and expanded on
  device by a rank map — the same compact-transfer trade as the
  single-chip mixed path (`data/feature.py.__getitem__`), stacked.
  The explicit, per-batch analog of the reference's UVA reads
  (`csrc/cuda/unified_tensor.cu:202+`).  Costs one device sync for
  the node table — the honest price of exceeding HBM.

  Returns ``(x', lookups, misses)`` for the caller's telemetry.
  ``nodes_host`` skips the device_get when the caller already fetched
  the table (the hetero engine batches ONE sync over all node types).
  ``cold_mask`` overrides the cold-row predicate with a precomputed
  mask (the cache-aware caller passes ``cold & ~cache_hit`` so served
  rows skip the host gather).
  """
  from ..utils.padding import next_power_of_two
  nodes_h = np.asarray(nodes_host if nodes_host is not None
                       else jax.device_get(nodes)).astype(np.int64)
  valid = nodes_h >= 0
  if cold_mask is not None:
    cold = cold_mask
  else:
    _rng, _local, cold = hot_split_host(bounds, hot_counts, nodes_h,
                                        valid)
  lookups = int(valid.sum())
  n_cold = int(cold.sum())
  if n_cold == 0:
    return x, lookups, 0
  cold_pad = next_power_of_two(n_cold)
  compact = np.zeros((cold_pad, cold_host.shape[1]), cold_host.dtype)
  compact[:n_cold] = cold_host[nodes_h[cold]]
  flat = cold.reshape(-1)
  rank = np.where(flat, np.cumsum(flat) - 1,
                  0).astype(np.int32).reshape(cold.shape)
  shard = NamedSharding(mesh, P(axis))
  repl = NamedSharding(mesh, P())
  out = _overlay_cold_rows(x, jax.device_put(cold, shard),
                           jax.device_put(rank, shard),
                           jax.device_put(compact, repl))
  return out, lookups, n_cold


def _local_shards_stacked(arr, host_parts) -> np.ndarray:
  """This process's shards of a dim-0-sharded global array, stacked
  ``[len(host_parts), ...]`` in ``host_parts`` order — the read half
  of `put_stacked_host_local` (multi-host safe: only addressable
  shards are touched)."""
  by_part = {}
  for s in arr.addressable_shards:
    by_part[int(s.index[0].start or 0)] = np.asarray(s.data)[0]
  return np.stack([by_part[int(p)] for p in host_parts])


def _global_max_int(v: int) -> int:
  """Agree on ``max(v)`` across processes — the request-capacity
  handshake of the owner-served cold overlay (every process must
  compile/run identical [P, P, C] programs or the collectives
  deadlock).  Single-process: the local value."""
  return _global_max_vec([v])[0]


def _global_max_vec(vs) -> list:
  """Vector form of `_global_max_int`: ONE allgather agrees on the
  element-wise max of a whole list — hetero batches with many tiered
  node types pay one DCN round trip instead of one per type
  (ADVICE r4: the per-(type, batch) handshake can dominate batch time
  at large P)."""
  if jax.process_count() == 1:
    return [int(v) for v in vs]
  from jax.experimental import multihost_utils
  return [int(x) for x in multihost_utils.process_allgather(
      np.asarray(vs, np.int64)).max(axis=0)]


@functools.lru_cache(maxsize=None)
def _cold_overlay_programs(mesh: Mesh, axis: str, num_parts: int):
  """The two tiny collectives of the owner-served cold overlay
  (`overlay_cold_owner`), cached per mesh: request-id all_to_all and
  reply all_to_all + scatter."""
  from .shard_map_compat import shard_map
  s3 = P(axis, None, None)
  s2 = P(axis, None)
  s4 = P(axis, None, None, None)

  def _exch(req):                                  # [1, P, C]
    return jax.lax.all_to_all(req[0], axis, 0, 0, tiled=True)[None]

  exchange_requests = jax.jit(shard_map(
      _exch, mesh=mesh, in_specs=(s3,), out_specs=s3))

  def _scatter(x, replies, mask, owner_idx, slot_idx):
    rep = jax.lax.all_to_all(replies[0], axis, 0, 0,
                             tiled=True)           # [P, C, D] by owner
    rows = rep[owner_idx[0], slot_idx[0]]          # [cap, D]
    return jnp.where(mask[0][:, None], rows, x[0])[None]

  scatter_replies = jax.jit(shard_map(
      _scatter, mesh=mesh, in_specs=(s3, s4, s2, s2, s2),
      out_specs=s3))
  return exchange_requests, scatter_replies


def plan_cold_requests(nodes, bounds, hot_counts, host_parts,
                       cache_ids=None, nodes_host=None):
  """Requester-side analysis half of `overlay_cold_owner`: which
  sampled rows are cold, who owns them, and the per-owner counts.
  Callers overlaying SEVERAL tiered stores in one batch (the hetero
  engine) run this per store, agree on all capacities in ONE
  `_global_max_vec` handshake, then execute each overlay with
  ``agreed_capacity`` — one DCN round trip per batch instead of one
  per store (ADVICE r4)."""
  hp = [int(p) for p in host_parts]
  num_parts = len(hot_counts)
  nodes_l = (nodes_host if nodes_host is not None
             else _local_shards_stacked(nodes, hp)).astype(np.int64)
  valid = nodes_l >= 0
  owner, local, cold = hot_split_host(bounds, hot_counts, nodes_l,
                                      valid)
  if cache_ids is not None:
    # cache-served rows already carry correct values — skip them
    for j in range(nodes_l.shape[0]):
      cid = np.asarray(cache_ids[j])
      pos = np.clip(np.searchsorted(cid, nodes_l[j]), 0, len(cid) - 1)
      cold[j] &= ~((cid[pos] == nodes_l[j]) & valid[j])
  counts = np.zeros((nodes_l.shape[0], num_parts), np.int64)
  if cold.any():
    sel_j, sel_pos = np.nonzero(cold)
    np.add.at(counts, (sel_j, owner[sel_j, sel_pos]), 1)
  return (hp, nodes_l, valid, owner, cold, counts, int(valid.sum()))


def overlay_cold_owner(x, nodes, bounds, hot_counts, cold_local, mesh,
                       axis: str, num_parts: int, host_parts,
                       cache_ids=None, nodes_host=None, plan_=None,
                       agreed_capacity=None):
  """OWNER-served cold-tier overlay — the multi-host form
  (`DistFeature.cold_local`): each host holds only its own
  partitions' cold rows, so a requester cannot gather them locally
  (the `overlay_cold_host` path needs the full ``[N, D]`` table).
  Instead the cold rows ride a second per-batch gather, the
  collective analog of the reference's RPC feature fan-out against
  per-host UVA tables (`distributed/dist_feature.py:134-269` +
  `data/feature.py:174-206`):

    1. each process reads ITS devices' sampled-node shards and marks
       rows the HBM exchange zeroed (past the owner's hot count and
       not served by the local remote-hot cache);
    2. processes agree on a power-of-two request capacity ``C``
       (`_global_max_int` — all processes must run identical
       programs);
    3. one all_to_all ships the ``[P, P, C]`` request ids to owners;
    4. each owner host gathers the requested rows from its DRAM stack
       (this is THE host round trip — the honest price of exceeding
       HBM, same as the requester-side path);
    5. one all_to_all ships replies back; a scatter overlays them.

  Works identically under a single controller (every partition is
  addressable) — the virtual-mesh tests drive the same code path the
  multi-host deployment runs.  Returns ``(x', lookups, misses)``.
  """
  plan = (plan_ if plan_ is not None
          else plan_cold_requests(nodes, bounds, hot_counts, host_parts,
                                  cache_ids=cache_ids,
                                  nodes_host=nodes_host))
  hp, nodes_l, valid, owner, cold, counts, lookups = plan
  pl, cap = nodes_l.shape
  from ..utils.padding import next_power_of_two
  c_req = (agreed_capacity if agreed_capacity is not None
           else _global_max_int(int(counts.max(initial=0))))
  if c_req == 0:
    return x, lookups, 0
  n_cold = int(cold.sum())
  c_pad = next_power_of_two(c_req)
  # vectorized (requester, owner) bucketing (ADVICE r4: the nested
  # pl x P python loops were per-batch host work): stable-sort the
  # cold rows by their (j, owner) group; slot-in-group = rank minus
  # the group's first rank
  req = np.full((pl, num_parts, c_pad), -1, np.int32)
  owner_idx = np.zeros((pl, cap), np.int32)
  slot_idx = np.zeros((pl, cap), np.int32)
  sel_j, sel_pos = np.nonzero(cold)
  if len(sel_j):
    own = owner[sel_j, sel_pos]
    ids = nodes_l[sel_j, sel_pos]
    gkey = sel_j * num_parts + own
    order = np.argsort(gkey, kind='stable')
    ks = gkey[order]
    starts = np.r_[0, np.nonzero(np.diff(ks))[0] + 1]
    sizes = np.diff(np.r_[starts, len(ks)])
    slots = (np.arange(len(ks))
             - np.repeat(starts, sizes)).astype(np.int32)
    req[sel_j[order], own[order], slots] = ids[order]
    owner_idx[sel_j, sel_pos] = own
    slot_idx[sel_j[order], sel_pos[order]] = slots

  exchange_requests, scatter_replies = _cold_overlay_programs(
      mesh, axis, num_parts)
  putS = functools.partial(put_stacked_host_local, mesh, axis,
                           num_parts, hp)
  req_at_owner = exchange_requests(putS(req))
  ro = _local_shards_stacked(req_at_owner, hp)     # [pl, P, C]
  d = cold_local.shape[-1]
  replies = np.zeros((pl, num_parts, c_pad, d), cold_local.dtype)
  for j, p in enumerate(hp):
    ids = ro[j].astype(np.int64)
    loc = np.where(ids >= 0, ids - bounds[p], 0)
    loc = np.clip(loc, 0, cold_local.shape[1] - 1)
    replies[j] = np.where((ids >= 0)[..., None], cold_local[j][loc], 0)
  x2 = scatter_replies(x, putS(replies), putS(cold),
                       putS(owner_idx), putS(slot_idx))
  return x2, lookups, n_cold


def _make_dist_walk_step(mesh: Mesh, num_parts: int, walk_length: int,
                         axis: str = 'data',
                         exchange_slack: Optional[float] = None,
                         exchange_layout: Optional[str] = None,
                         book_spec=None):
  """Jitted SPMD uniform random walk over the sharded CSR: each step
  is one `_dist_one_hop` with fanout 1 (a uniform neighbor draw
  through the owner exchange) — the distributed arm of
  `ops.random_walk` (beyond reference parity; the reference only
  reserves ``SamplingType.RANDOM_WALK``)."""
  from .shard_map_compat import shard_map

  def per_device(indptr_s, indices_s, bounds, starts_s, key):
    cur = starts_s[0].astype(jnp.int32)
    path = [cur]
    stats = jnp.zeros((3,), jnp.int32)
    attr_owner = range_owner_fn(bounds)
    attr_fr = jnp.zeros((num_parts,), jnp.int32)
    for h in range(walk_length):
      attr_fr = attr_fr + dest_histogram(cur, attr_owner, num_parts)
      nbrs, mask, _, _w, hstats = _dist_one_hop(
          indptr_s[0], indices_s[0], None, bounds, cur, 1,
          jax.random.fold_in(key, h), axis, num_parts, False,
          exchange_capacity=_slack_cap(cur.shape[0], num_parts,
                                       exchange_slack,
                                       exchange_layout),
          book_spec=book_spec)
      stats = stats + jnp.stack(hstats)
      cur = jnp.where(mask[:, 0], nbrs[:, 0], INVALID_ID).astype(
          jnp.int32)
      path.append(cur)
    walks = jnp.stack(path, axis=1)             # [B, L+1]
    full = jnp.concatenate(
        [stats, jnp.zeros((4,), jnp.int32), attr_fr,
         jnp.zeros((num_parts + 1,), jnp.int32)])
    return walks[None], full[None]

  specs_in = (P(axis), P(axis), P(), P(axis), P())
  sharded = shard_map(per_device, mesh=mesh, in_specs=specs_in,
                      out_specs=(P(axis), P(axis)))
  return jax.jit(sharded)


#: `hop_chunk='auto'` engages chunking once one full-window reply
#: buffer (``node_cap * max_degree`` int32 per destination device)
#: would exceed this many elements — 16M = 64 MB, comfortably inside
#: HBM while keeping the all_to_all rendezvous bounded at any P.
SUBGRAPH_WINDOW_BUDGET = 1 << 24


def resolve_hop_chunk(hop_chunk, node_cap: int,
                      max_degree: int) -> Optional[int]:
  """Resolve the subgraph samplers' ``'auto'``: chunk only when the
  full-window exchange would exceed `SUBGRAPH_WINDOW_BUDGET` elements
  (results are EXACT either way; chunking costs serialized exchanges,
  so small configs keep the single wide one)."""
  if isinstance(hop_chunk, str):
    if hop_chunk != 'auto':
      raise ValueError(f'unknown hop_chunk {hop_chunk!r}')
    if node_cap * max_degree <= SUBGRAPH_WINDOW_BUDGET:
      return None
    # round DOWN so chunk * max_degree never exceeds the budget (the
    # MIN_EXCHANGE_CAP floor may for degenerate max_degree — a floor,
    # not a violation of intent)
    return max(SUBGRAPH_WINDOW_BUDGET // max_degree // 8 * 8,
               MIN_EXCHANGE_CAP)
  return hop_chunk


class DistSubGraphSampler(DistNeighborSampler):
  """Device-mesh induced-subgraph sampler: multihop closure + one
  full-window distributed hop + local membership/relabel (SEAL at pod
  scale; reference `distributed/dist_neighbor_sampler.py:456-516`).

  Args:
    max_degree: static per-node neighbor window for the induced scan;
      None = the sharded graph's true max degree (exact results).
    hop_chunk: closure nodes per full-window exchange — bounds the
      all_to_all to ``[P, chunk, max_degree]`` (SEAL-at-scale
      envelope; see `_make_dist_subgraph_step`).  ``'auto'`` (default)
      chunks only past `SUBGRAPH_WINDOW_BUDGET`; None = always one
      node_cap-wide exchange.
  """

  def __init__(self, dataset: DistDataset, num_neighbors,
               max_degree: Optional[int] = None,
               hop_chunk='auto', **kwargs):
    super().__init__(dataset, num_neighbors, **kwargs)
    # induced subgraphs are EXACT by contract (a biased closure
    # corrupts SEAL/DRNL labels the way a capacity drop would), so a
    # global GLT_GNS=1 must not flip this sampler's flag: the step
    # never biases, and the flag must not report otherwise
    self.gns = False
    self.gns_boost = None
    if max_degree is None:
      g = dataset.graph
      max_degree = int(np.diff(g.indptr, axis=1).max())
    self.max_degree = max(int(max_degree), 1)
    self.hop_chunk = hop_chunk

  def sample_subgraph(self, seeds_stacked: np.ndarray):
    """``seeds_stacked``: ``[P, B]`` per-device seed batches (relabeled
    space, -1 padded).  Returns the induced-subgraph pieces; edges in
    natural (source, dest) direction; ``seed_local`` doubles as the
    reference's ``mapping`` metadata."""
    b = seeds_stacked.shape[1]
    node_cap = self.node_capacity(b)
    self._partition_supervision()
    arrs = self._arrays()
    cfg = ('subgraph', b)
    if cfg not in self._steps:
      with self._layout_span(batch=b, mode='subgraph'):
        self._steps[cfg] = _make_dist_subgraph_step(
            self.mesh, self.num_parts, self.fanouts, node_cap,
            self.max_degree, self.with_edge, self.collect_features,
            self.collect_labels, self.axis, with_cache=self.with_cache,
            exchange_slack=self.exchange_slack,
            exchange_layout=self.exchange_layout, tiered=self.tiered,
            hop_chunk=resolve_hop_chunk(self.hop_chunk, node_cap,
                                        self.max_degree),
            book_spec=self.book_spec)
    from ..telemetry.spans import span
    self._step_cnt += 1
    key = jax.random.fold_in(self._base_key, self._step_cnt)
    with span('sample.exchange', step=self._step_cnt,
              mode='subgraph'):
      seeds_dev = jax.device_put(
          np.asarray(seeds_stacked, dtype=np.int32),
          NamedSharding(self.mesh, P(self.axis)))
      (nodes, count, row, col, edge, seed_local, x, y, nsn, stats) = \
          self._steps[cfg](arrs['indptr'], arrs['indices'],
                           arrs['eids'], arrs['bounds'], seeds_dev,
                           arrs['fshards'], arrs['lshards'],
                           arrs['cids'], arrs['crows'],
                           arrs['hcounts'], key)
    self._complete_recovery()
    self._accumulate_stats(stats)
    x = self._maybe_overlay_cold(x, nodes)
    return dict(node=nodes, node_count=count[..., 0], row=row, col=col,
                edge=edge, seed_local=seed_local, x=x, y=y,
                num_sampled_nodes=nsn, batch=seeds_dev)


class DistRandomWalker(DistNeighborSampler):
  """Device-mesh uniform random walks (DeepWalk-corpus generation over
  a graph larger than one chip) — see `_make_dist_walk_step`.
  Subclasses `DistNeighborSampler` for the shared scaffolding (mesh,
  key stream, device-array cache, step cache, telemetry).

  Args:
    dataset: `DistDataset`.
    walk_length: steps per walk (output is ``[P, B, L+1]``).
    exchange_slack: default EXACT — a dropped frontier id does not
      under-sample one hop here, it truncates the walk's whole
      remainder, and walk frontiers are degree-biased (hotness
      partitioners concentrate them on few owners), so the loaders'
      capped default would silently empty the corpus.  Pass a float to
      opt in where partition balance is known.
  """

  def __init__(self, dataset: DistDataset, walk_length: int,
               exchange_slack=None, **kwargs):
    if exchange_slack == 'adaptive':
      raise ValueError(
          "exchange_slack='adaptive' is not supported for random "
          'walks: a dropped frontier id truncates the whole walk '
          'remainder, so the walker stays exact (pass a float to opt '
          'into a cap where partition balance is known)')
    super().__init__(
        dataset, [], collect_features=False, with_edge=False,
        # 'auto' resolves to exact here (see class docstring)
        exchange_slack=resolve_exchange_slack(exchange_slack, False),
        **kwargs)
    self.walk_length = int(walk_length)

  def walk(self, starts_stacked: np.ndarray) -> jax.Array:
    """``starts_stacked``: ``[P, B]`` per-device start nodes (relabeled
    space, -1 padded).  Returns ``[P, B, walk_length + 1]``."""
    b = starts_stacked.shape[1]
    self._partition_supervision()
    arrs = self._arrays()
    cfg = ('walk', b)
    if cfg not in self._steps:
      with self._layout_span(batch=b, mode='walk'):
        self._steps[cfg] = _make_dist_walk_step(
            self.mesh, self.num_parts, self.walk_length, self.axis,
            self.exchange_slack, self.exchange_layout,
            book_spec=self.book_spec)
    self._step_cnt += 1
    key = jax.random.fold_in(self._base_key, self._step_cnt)
    starts = jax.device_put(
        np.asarray(starts_stacked, np.int32),
        NamedSharding(self.mesh, P(self.axis)))
    walks, stats = self._steps[cfg](arrs['indptr'], arrs['indices'],
                                    arrs['bounds'], starts, key)
    self._complete_recovery()
    self._accumulate_stats(stats)
    return walks


class DistSubGraphLoader(PrefetchingLoader):
  """Distributed induced-subgraph loader over the device mesh — the
  mesh-engine arm of reference ``DistSubGraphLoader``
  (`distributed/dist_subgraph_loader.py:28-89`); the host-runtime arm
  lives in `graphlearn_tpu.distributed`.  Yields stacked `Batch`
  pytrees with ``metadata['mapping']`` locating each seed in the node
  table (the SEAL contract, `loader/subgraph_loader.py:88-97`).
  """

  def __init__(self, dataset: DistDataset, num_neighbors, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, mesh: Optional[Mesh] = None,
               with_edge: bool = False, collect_features: bool = True,
               max_degree: Optional[int] = None, seed: int = 0,
               input_space: str = 'old', exchange_slack='auto',
               exchange_layout: Optional[str] = None,
               hop_chunk='auto', prefetch: int = 0):
    from ..loader.node_loader import SeedBatcher
    self.prefetch = int(prefetch)
    # 'auto' resolves to EXACT here, shuffled or not: a dropped
    # closure node under a capacity cap loses its whole neighbor
    # window, making the "induced subgraph" silently wrong (for
    # neighbor sampling a drop is a statistical under-sample; for
    # SEAL/DRNL it corrupts labels).  An explicit float still opts in.
    # `hop_chunk` is the scale lever that keeps exact affordable: it
    # bounds every full-window exchange to [P, chunk, max_degree].
    if exchange_slack == 'adaptive':
      raise ValueError(
          "exchange_slack='adaptive' is not supported for induced "
          'subgraphs: any capacity drop corrupts SEAL/DRNL labels, so '
          'the loader stays exact (hop_chunk bounds the exchange '
          'instead)')
    if exchange_slack == 'auto':
      exchange_slack = None
    self.sampler = DistSubGraphSampler(
        dataset, num_neighbors, max_degree=max_degree, mesh=mesh,
        with_edge=with_edge, collect_features=collect_features,
        seed=seed,
        exchange_slack=resolve_exchange_slack(exchange_slack, shuffle),
        exchange_layout=exchange_layout,
        hop_chunk=hop_chunk)
    self.ds = dataset
    seeds = np.asarray(input_nodes).reshape(-1)
    if input_space == 'old' and dataset.old2new is not None:
      seeds = dataset.old2new[seeds]
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    self._batcher = SeedBatcher(seeds, batch_size * self.num_parts,
                                shuffle, drop_last, seed)

  def __len__(self):
    return len(self._batcher)

  def _produce(self, seed_iter):
    from ..loader.transform import Batch
    from ..telemetry.spans import span
    flat = next(seed_iter)
    with span('batch', scope='DistSubGraphLoader'):
      seeds = flat.reshape(self.num_parts, self.batch_size)
      out = self.sampler.sample_subgraph(seeds)
      with span('stitch'):
        edge_index = jnp.stack([out['row'], out['col']], axis=1)
        return Batch(
            x=out['x'], y=out['y'], edge_index=edge_index,
            node=out['node'], node_mask=out['node'] >= 0,
            edge_mask=out['row'] >= 0, edge=out['edge'],
            batch=out['batch'], batch_size=self.batch_size,
            num_sampled_nodes=out['num_sampled_nodes'],
            metadata={'seed_local': out['seed_local'],
                      'mapping': out['seed_local']})


class _ResumableEpochMixin:
  """Mid-epoch snapshot/resume for the mesh loaders (the
  `utils.checkpoint` DataPlaneState protocol, loader-shaped).

  ``state_dict()`` captures the epoch cursor: the batcher's RNG (the
  interrupted epoch's permutation is RE-DRAWN on resume, not stored),
  the number of batches already handed out, the sampler key-stream
  position those batches consumed, and the cold-cache rings.
  ``load_state_dict()`` + ``resume_epoch()`` then continue the epoch
  in a fresh loader with byte-identical remaining batches: same
  permutation, same per-batch sampling keys (``step_cnt`` excludes
  any lost dispatch-ahead overshoot — the in-flight batch k+1 a kill
  destroys is re-dispatched with the same key).
  """

  def _start_epoch(self, seed_iter):
    self._epoch_start_steps = self.sampler._step_cnt
    self._consumed = 0
    return super()._start_epoch(seed_iter)

  def state_dict(self) -> dict:
    if getattr(self, '_active_prefetch', None) is not None:
      # the worker thread runs _produce ahead of the consumer, so
      # `_consumed` counts batches the trainer may never have seen —
      # a snapshot here would skip them on resume (silent batch loss)
      raise ValueError(
          'mid-epoch snapshots need a synchronous epoch (prefetch=0): '
          'a prefetch worker produces ahead of the trainer, so the '
          'durable cursor would overcount delivered batches')
    c = int(getattr(self, '_consumed', 0))
    start = getattr(self, '_epoch_start_steps',
                    self.sampler._step_cnt)
    sampler_state = self.sampler.data_plane_state()
    # the CONSUMED-batch key position, not the live counter: under the
    # dispatch-ahead overlay batch k+1's dispatch has already advanced
    # the counter while batch k is the newest durable batch
    sampler_state['step_cnt'] = start + c
    out = {'batcher': self._batcher.state_dict(), 'consumed': c,
           'epoch_count': int(getattr(self, '_epoch_count', 0)),
           'sampler': sampler_state}
    ctl = getattr(self, '_adaptive', None)
    if ctl is not None:
      out['slack'] = ctl.state_dict()
    return out

  def load_state_dict(self, state: dict) -> None:
    self._batcher.load_state_dict(state['batcher'], mid_epoch=True)
    self.sampler.load_data_plane_state(state['sampler'])
    # the ladder's rung/pin survive the restart (ISSUE 6: AdaptiveSlack
    # is one of the stateful components a restart would silently reset)
    ctl = getattr(self, '_adaptive', None)
    if ctl is not None and 'slack' in state:
      ctl.load_state_dict(state['slack'])
    self._epoch_count = int(np.asarray(state.get('epoch_count', 0)))
    self._resume_consumed = int(np.asarray(state['consumed']))

  def resume_epoch(self):
    """Iterator over the interrupted epoch's REMAINING batches (call
    after `load_state_dict`); `iter(loader)` afterwards starts the
    next epoch exactly where an uninterrupted run would."""
    consumed = getattr(self, '_resume_consumed', None)
    if consumed is None:
      raise ValueError('resume_epoch() needs load_state_dict() first')
    self._resume_consumed = None
    it = iter(self._batcher)       # re-draws the interrupted epoch's perm
    for _ in range(consumed):
      next(it)                     # skip what the trainer already has
    ep = PrefetchingLoader._start_epoch(self, it)
    self._consumed = consumed
    self._epoch_start_steps = self.sampler._step_cnt - consumed
    return ep


class DistNeighborLoader(_ResumableEpochMixin, PrefetchingLoader):
  """Distributed loader facade (reference ``DistNeighborLoader``,
  `distributed/dist_neighbor_loader.py:27-94`).

  Splits the (relabeled) seed set across the mesh, yields stacked
  `Batch` pytrees ready for the DP train step: leading axis = device.
  ``prefetch=N`` runs the host side of the NEXT batch (seed prep, the
  collective dispatch, the tiered store's cold overlay) on a worker
  thread while the current step trains — the overlap tiered stores
  need, since their overlay syncs on the node table per batch.
  """

  def __init__(self, dataset: DistDataset, num_neighbors, input_nodes,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, mesh: Optional[Mesh] = None,
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, input_space: str = 'old',
               exchange_slack='auto',
               exchange_layout: Optional[str] = None,
               prefetch: int = 0, cold_cache_rows='auto', gns=None):
    from ..loader.node_loader import SeedBatcher
    self.prefetch = int(prefetch)
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistNeighborSampler(
        dataset, num_neighbors, mesh=mesh, with_edge=with_edge,
        collect_features=collect_features, seed=seed,
        exchange_slack=(DEFAULT_EXCHANGE_SLACK if slack == 'adaptive'
                        else slack),
        exchange_layout=exchange_layout,
        cold_cache_rows=cold_cache_rows, gns=gns)
    self._adaptive = (AdaptiveSlack(self.sampler)
                      if slack == 'adaptive' else None)
    self._epoch_count = 0
    import os
    # tiered stores default to the double-buffered cold overlay
    # (GLT_COLD_PREFETCH=0 opts out; batches are byte-identical)
    self._cold_pipeline = (self.sampler.tiered
                           and os.environ.get('GLT_COLD_PREFETCH',
                                              '1') != '0')
    self.ds = dataset
    seeds = np.asarray(input_nodes).reshape(-1)
    if input_space == 'old' and dataset.old2new is not None:
      seeds = dataset.old2new[seeds]
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    # one batcher per device slice, all consuming a common shuffled pool
    self._batcher = SeedBatcher(seeds, batch_size * self.num_parts,
                                shuffle, drop_last, seed)

  def __len__(self):
    return len(self._batcher)

  def _maybe_emit_hop_events(self, nsn) -> None:
    """Per-hop frontier-size / padding-fill flight-recorder events for
    one batch.  Only when the recorder is on: reading the stacked
    ``num_sampled_nodes`` is a device sync, which the hot path must
    never pay by default."""
    from ..telemetry.recorder import recorder
    if not recorder.enabled:
      return
    from ..telemetry.aggregate import per_hop_padding
    self._batch_idx = getattr(self, '_batch_idx', 0) + 1
    if getattr(nsn, 'is_fully_addressable', True):
      arr = np.asarray(nsn)
    else:
      # multi-controller mesh: only this host's shards are readable —
      # emit the HOST-LOCAL per-hop fill (capacities scale by the
      # local shard count inside per_hop_padding), instead of
      # crashing the job the recorder is meant to diagnose
      arr = np.concatenate(
          [np.asarray(s.data) for s in nsn.addressable_shards])
    rows = per_hop_padding(arr, self.batch_size, self.sampler.fanouts)
    for row in rows:
      recorder.emit('hop.padding', scope='dist_loader',
                    batch=self._batch_idx, **row)

  def _dispatch_flat(self, flat):
    seeds = flat.reshape(self.num_parts, self.batch_size)  # [P * B]
    return self.sampler._dispatch_nodes(seeds)

  def _produce(self, seed_iter):
    from ..loader.transform import Batch
    from ..telemetry.spans import span
    # acquire BEFORE the span: epoch end (StopIteration) must not
    # emit an empty `batch` root span
    if self._cold_pipeline:
      acquired = self._pipeline_acquire(seed_iter)
    else:
      flat = next(seed_iter)                       # [P * B]
    # 'batch' is the per-batch ROOT span; the sampler's
    # sample.exchange / feature.lookup spans nest under it, and
    # 'stitch' covers the Batch assembly — the causal tree stage
    # attribution reads
    with span('batch', scope='DistNeighborLoader',
              batch=getattr(self, '_batch_idx', 0) + 1):
      if self._cold_pipeline:
        # tiered stores: double-buffered cold overlay — batch k+1's
        # sampling is dispatched before batch k's overlay syncs
        # (`PrefetchingLoader._pipelined`; GLT_COLD_PREFETCH=0 off)
        out = self._pipelined(acquired, seed_iter,
                              self._dispatch_flat,
                              self.sampler._finish_nodes)
      else:
        seeds = flat.reshape(self.num_parts, self.batch_size)
        out = self.sampler.sample_from_nodes(seeds)
      self._maybe_emit_hop_events(out['num_sampled_nodes'])
      with span('stitch'):
        edge_index = jnp.stack([out['row'], out['col']],
                               axis=1)             # [P, 2, E]
        md = {'seed_local': out['seed_local']}
        if 'edge_weight' in out:
          # GNS importance weights, aligned with the [P, E] edge list
          # — consumers weight aggregation by them to stay unbiased
          md['edge_weight'] = out['edge_weight']
        batch = Batch(
            x=out['x'], y=out['y'], edge_index=edge_index,
            edge_attr=out['ef'],
            node=out['node'], node_mask=out['node'] >= 0,
            edge_mask=out['row'] >= 0, edge=out['edge'],
            batch=out['batch'], batch_size=self.batch_size,
            num_sampled_nodes=out['num_sampled_nodes'],
            metadata=md)
      self._consumed = getattr(self, '_consumed', 0) + 1
      return batch


def pack_link_seeds(edge_label_index, edge_label,
                    neg_mode: Optional[str]):
  """Pack seed edges (+optional integer labels, binary +1-shifted) into
  the ``[E, 2|3]`` tensor both mesh link loaders batch over — ONE
  definition of the label contract (`link_loader.py:146-186`)."""
  if isinstance(edge_label_index, (tuple, list)):
    rows, cols = edge_label_index
  else:
    ei = np.asarray(edge_label_index)
    rows, cols = ei[0], ei[1]
  rows = np.asarray(rows, np.int64)
  cols = np.asarray(cols, np.int64)
  colsarr = [rows, cols]
  if edge_label is not None:
    lab = np.asarray(edge_label)
    if not np.issubdtype(lab.dtype, np.integer):
      raise ValueError(
          'mesh link loaders carry integer edge labels in their packed '
          'seed tensor; for float labels use the host-runtime '
          'DistLinkNeighborLoader (graphlearn_tpu.distributed)')
    lab = lab.astype(np.int64)
    if neg_mode == 'binary':
      lab = lab + 1     # reference +1 shift (`link_loader.py:146-186`)
    colsarr.append(lab)
  return rows, cols, colsarr


def pack_link_seeds_relabeled(edge_label_index, edge_label,
                              neg_mode: Optional[str], dataset,
                              input_space: str) -> np.ndarray:
  """`pack_link_seeds` + the ``input_space`` old→new endpoint remap —
  the one constructor-side contract shared by `DistLinkNeighborLoader`
  and `FusedDistLinkEpoch`.  Returns the packed ``[E, 2|3]`` pairs."""
  rows, cols, colsarr = pack_link_seeds(edge_label_index, edge_label,
                                        neg_mode)
  if input_space == 'old' and dataset.old2new is not None:
    colsarr[0] = dataset.old2new[rows]
    colsarr[1] = dataset.old2new[cols]
  return np.stack(colsarr, axis=1)


def link_step_metadata(neg_mode: Optional[str], seed_local, eli, elab,
                       elab_mask, src_idx, dst_pos, dst_neg) -> dict:
  """Map a link step's label outputs to the metadata dict
  `link_loss_from_metadata` dispatches on — ONE definition for the
  per-batch sampler and the fused epoch twin."""
  md = {'seed_local': seed_local}
  if neg_mode == 'triplet':
    md.update(src_index=src_idx, dst_pos_index=dst_pos,
              dst_neg_index=dst_neg, pair_mask=src_idx >= 0)
  else:
    md.update(edge_label_index=eli, edge_label=elab,
              edge_label_mask=elab_mask)
  return md


class DistLinkNeighborSampler(DistNeighborSampler):
  """Device-mesh LINK sampler: per-device seed edges + collective
  strict negatives + endpoint expansion — the SPMD analog of the
  reference's link path (`distributed/dist_neighbor_sampler.py:
  327-453`), with negatives strict against the GLOBAL sharded graph
  via `dist_edge_exists` (the reference rejects only locally).

  Args:
    neg_sampling: ``None`` / ``'binary'`` / ``('triplet', amount)``.
  """

  def __init__(self, dataset: DistDataset, num_neighbors,
               neg_sampling=None, **kwargs):
    super().__init__(dataset, num_neighbors, **kwargs)
    from ..sampler.base import NegativeSampling
    ns = (NegativeSampling.cast(neg_sampling)
          if neg_sampling is not None else None)
    # NegativeSampling validates the mode/amount; unknown strings raise
    # instead of silently sampling no negatives
    self.neg_mode = ns.mode if ns is not None else None
    self.neg_amount = float(ns.amount) if ns is not None else 1.0

  def _expansion_seeds(self, b: int) -> Tuple[int, int]:
    """(total expansion seeds, negative count) per device batch —
    negative counts come from the ONE shared definition
    (`distributed.dist_options.binary_num_negatives`)."""
    from ..distributed.dist_options import binary_num_negatives
    if self.neg_mode == 'binary':
      nn = binary_num_negatives(b, self.neg_amount)
      return 2 * b + 2 * nn, nn
    if self.neg_mode == 'triplet':
      amount = int(np.ceil(self.neg_amount))
      return 2 * b + b * amount, b * amount
    return 2 * b, 0

  def step_for_pairs(self, batch_size: int, width: int):
    """The compiled SPMD link step for ``[P, batch_size, width]`` seed
    edges (built once per (batch, width)) — also the scan body of
    `FusedDistLinkEpoch`."""
    b = int(batch_size)
    exp_seeds, num_neg = self._expansion_seeds(b)
    cfg = ('link', b, int(width))
    if cfg not in self._steps:
      with self._layout_span(batch=b, mode='link'):
        self._steps[cfg] = _make_dist_link_step(
            self.mesh, self.num_parts, self.fanouts,
            self.node_capacity(exp_seeds), b,
            self.ds.graph.num_nodes, self.neg_mode, num_neg,
            self.neg_amount,
            self.with_edge, self.collect_features, self.collect_labels,
            self.axis, with_cache=self.with_cache,
            exchange_slack=self.exchange_slack,
            exchange_layout=self.exchange_layout,
            collect_edge_features=self.collect_edge_features,
            ef_shard_mode=self._ef_shard_mode, tiered=self.tiered,
            gns_boost=self.gns_boost, book_spec=self.book_spec,
            cache_local=self.cache_local, ewma_caps=self._ewma_caps())
      if self.gns:
        from ..telemetry.recorder import recorder
        from ..utils.profiling import metrics
        metrics.inc('gns.bias_steps_total')
        recorder.emit('gns.bias', batch=b, mode='link',
                      boost=float(self.gns_boost),
                      num_parts=self.num_parts)
    return self._steps[cfg]

  def sample_from_edges(self, pairs_stacked: np.ndarray, key=None):
    """``pairs_stacked``: ``[P, B, 2|3]`` per-device (src, dst[, label])
    seed edges in the relabeled id space, -1 padded."""
    return self._finish_edges(self._dispatch_edges(pairs_stacked, key))

  def _dispatch_edges(self, pairs_stacked: np.ndarray, key=None):
    """Link twin of `_dispatch_nodes` (the cold pipeline's dispatch
    half)."""
    from ..telemetry.spans import span
    p, b = pairs_stacked.shape[:2]
    self._partition_supervision()
    arrs = self._arrays()
    step = self.step_for_pairs(b, pairs_stacked.shape[2])
    self._step_cnt += 1
    if key is None:
      key = jax.random.fold_in(self._base_key, self._step_cnt)
    with span('sample.exchange', step=self._step_cnt, batch=b,
              mode='link'):
      pairs_dev = jax.device_put(
          np.asarray(pairs_stacked, dtype=np.int32),
          NamedSharding(self.mesh, P(self.axis)))
      extra = (self._gns_arrays(),) if self.gns else ()
      outs = step(arrs['indptr'], arrs['indices'], arrs['eids'],
                  arrs['bounds'], pairs_dev, arrs['fshards'],
                  arrs['lshards'], arrs['cids'], arrs['crows'],
                  arrs['efshards'], arrs['ebounds'],
                  arrs['hcounts'], *extra, key)
      (nodes, count, row, col, edge, seed_local, x, y, ef, nsn,
       stats) = outs[:11]
      ew = outs[11] if self.gns else None
      (eli, elab, elab_mask, src_idx, dst_pos, dst_neg) = \
          outs[12:] if self.gns else outs[11:]
    self._complete_recovery()
    self._accumulate_stats(stats)
    md = link_step_metadata(self.neg_mode, seed_local, eli, elab,
                            elab_mask, src_idx, dst_pos, dst_neg)
    if ew is not None:
      md['edge_weight'] = ew
    return dict(node=nodes, node_count=count[..., 0], row=row, col=col,
                edge=edge, x=x, y=y, ef=ef, num_sampled_nodes=nsn,
                batch=pairs_dev[:, :, 0], metadata=md,
                overlay_step=self._step_cnt)

  def _finish_edges(self, out: dict) -> dict:
    out['x'] = self._maybe_overlay_cold(out['x'], out['node'],
                                        step=out.pop('overlay_step',
                                                     None))
    return out


class DistLinkNeighborLoader(_ResumableEpochMixin, PrefetchingLoader):
  """Distributed link-prediction loader over the device mesh
  (reference ``DistLinkNeighborLoader``,
  `distributed/dist_link_neighbor_loader.py:30-153`): seed edges split
  across devices, negatives drawn collectively, stacked `Batch`
  pytrees with link-label metadata ready for the DP unsupervised step.

  Args:
    edge_label_index: ``[2, E]`` (or ``(rows, cols)``) seed edges.
    edge_label: optional labels (binary mode applies the reference's
      +1 shift).
    neg_sampling: ``'binary'`` / ``('triplet', amount)`` / None.
    input_space: ``'old'`` runs seeds through ``dataset.old2new``.
  """

  def __init__(self, dataset: DistDataset, num_neighbors,
               edge_label_index, edge_label=None, neg_sampling=None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, mesh: Optional[Mesh] = None,
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, input_space: str = 'old',
               exchange_slack='auto',
               exchange_layout: Optional[str] = None,
               prefetch: int = 0, cold_cache_rows='auto', gns=None):
    from ..loader.node_loader import SeedBatcher
    self.prefetch = int(prefetch)
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistLinkNeighborSampler(
        dataset, num_neighbors, neg_sampling=neg_sampling, mesh=mesh,
        with_edge=with_edge, collect_features=collect_features,
        seed=seed,
        exchange_slack=(DEFAULT_EXCHANGE_SLACK if slack == 'adaptive'
                        else slack),
        exchange_layout=exchange_layout,
        cold_cache_rows=cold_cache_rows, gns=gns)
    self._adaptive = (AdaptiveSlack(self.sampler)
                      if slack == 'adaptive' else None)
    self._epoch_count = 0
    import os
    self._cold_pipeline = (self.sampler.tiered
                           and os.environ.get('GLT_COLD_PREFETCH',
                                              '1') != '0')
    self.pairs = pack_link_seeds_relabeled(
        edge_label_index, edge_label, self.sampler.neg_mode, dataset,
        input_space)
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    self._batcher = SeedBatcher(self.pairs,
                                batch_size * self.num_parts, shuffle,
                                drop_last, seed)

  def __len__(self):
    return len(self._batcher)

  def _dispatch_flat(self, flat):
    pairs = flat.reshape(self.num_parts, self.batch_size, -1)
    return self.sampler._dispatch_edges(pairs)

  def _produce(self, seed_iter):
    from ..loader.transform import Batch
    from ..telemetry.spans import span
    # acquire BEFORE the span (see DistNeighborLoader._produce)
    if self._cold_pipeline:
      acquired = self._pipeline_acquire(seed_iter)
    else:
      flat = next(seed_iter)                       # [P * B, 2|3]
    with span('batch', scope='DistLinkNeighborLoader'):
      if self._cold_pipeline:
        out = self._pipelined(acquired, seed_iter,
                              self._dispatch_flat,
                              self.sampler._finish_edges)
      else:
        pairs = flat.reshape(self.num_parts, self.batch_size, -1)
        out = self.sampler.sample_from_edges(pairs)
      with span('stitch'):
        edge_index = jnp.stack([out['row'], out['col']], axis=1)
        batch = Batch(
            x=out['x'], y=out['y'], edge_index=edge_index,
            edge_attr=out['ef'],
            node=out['node'], node_mask=out['node'] >= 0,
            edge_mask=out['row'] >= 0, edge=out['edge'],
            batch=out['batch'], batch_size=self.batch_size,
            num_sampled_nodes=out['num_sampled_nodes'],
            metadata=out['metadata'])
      self._consumed = getattr(self, '_consumed', 0) + 1
      return batch
