from .dp import (DataParallelLoader, make_dp_supervised_step, make_mesh,
                 replicate, shard_stacked, stack_batches)
