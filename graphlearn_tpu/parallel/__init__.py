from .dp import (DataParallelLoader, local_batch_piece,
                 make_dp_supervised_step,
                 make_dp_unsupervised_step, make_mesh,
                 replicate, shard_stacked, stack_batches)
from .dist_data import (DistDataset, DistFeature, DistGraph,
                        build_dist_edge_feature, build_dist_feature,
                        build_dist_graph)
from . import multihost
from .dist_hetero import (DistHeteroDataset, DistHeteroLinkNeighborLoader,
                          DistHeteroNeighborLoader,
                          DistHeteroNeighborSampler)
from .fused import (FusedDistEpoch, FusedDistLinkEpoch,
                    FusedDistTreeEpoch)
from .dist_sampler import (DistLinkNeighborLoader, DistLinkNeighborSampler,
                           DistNeighborLoader, DistNeighborSampler,
                           DistRandomWalker,
                           DistSubGraphLoader, DistSubGraphSampler,
                           bucket_by_owner, dist_edge_exists, dist_gather,
                           dist_sample_negative)
from .exchange import (ExchangeSpec, HAVE_RAGGED, capacity_spec,
                       mesh_factors, plan_exchange, resolve_layout,
                       simulate_assignment)
