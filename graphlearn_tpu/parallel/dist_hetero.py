"""Heterogeneous distributed sampling over the device mesh.

The hetero counterpart of `parallel/dist_sampler.py` — and the engine
behind IGBH-scale distributed RGNN (reference `examples/igbh/
dist_train_rgnn.py` + `distributed/dist_neighbor_sampler.py`'s hetero
branch, `:255-324`): every node type is range-sharded with its own
bounds, every edge type's local CSR lives on its source type's owner
device, and each hop's cross-partition neighbor exchange rides
`all_to_all` per edge type inside ONE SPMD program.

Layout (`DistHeteroDataset`):
  * per node type: contiguous relabel by its partition book →
    ``bounds[nt]`` (`RangePartitionBook` form), feature/label shards
    ``[P, rows_max_nt, D]``;
  * per edge type ``(s, rel, d)``: edges owned by the SRC node's
    partition; stacked local CSRs ``[P, ...]`` with local rows in
    ``s``-space and GLOBAL (relabeled) ``d``-space columns, so sampled
    neighbors enter ``d``'s tables with no translation.

Engine (`DistHeteroNeighborSampler`): the hetero multihop loop of
`sampler/hetero_neighbor_sampler.py` with every one-hop replaced by
the collective exchange of `dist_sampler._dist_one_hop` (bucket by
``searchsorted(bounds[s], frontier)`` → all_to_all → local sample →
all_to_all back → stitch), and per-type feature collection via
`dist_gather_multi` against that type's shards.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.cold_cache import emit_cache_events
from ..loader.prefetch import PrefetchingLoader
from ..ops.unique import init_node, induce_next
from ..sampler.hetero_neighbor_sampler import (_plan_capacities,
                                               normalize_fanouts)
from ..typing import EdgeType, NodeType, reverse_edge_type
from ..utils.padding import INVALID_ID
from .dist_data import build_dist_edge_feature, build_dist_feature
from .dist_sampler import (ExchangeTelemetry, NEG_TRIALS, _dist_one_hop,
                           _slack_cap, dist_gather_multi,
                           dist_sample_negative, overlay_cold_host,
                           resolve_exchange_slack)


class DistHeteroDataset:
  """Per-type sharded hetero layout.

  Attributes:
    graphs: ``{EdgeType: DistGraph}`` (bounds of the SRC type).
    bounds: ``{NodeType: [P+1]}`` ownership ranges.
    node_features: ``{NodeType: DistFeature}``.
    node_labels: ``{NodeType: [P, rows_max]}``.
    edge_features: ``{EdgeType: DistFeature}`` MOD-sharded over that
      type's GLOBAL edge ids (owner = eid % P,
      `build_dist_edge_feature`).
    old2new / new2old: ``{NodeType: [N_nt]}`` id-space maps.
  """

  def __init__(self, graphs, bounds, node_features=None, node_labels=None,
               old2new=None, edge_features=None, host_parts=None):
    self.graphs = dict(graphs)
    self.bounds = {nt: np.asarray(b, np.int64) for nt, b in bounds.items()}
    self.node_features = dict(node_features or {})
    self.node_labels = dict(node_labels or {})
    self.edge_features = {tuple(et): f
                          for et, f in (edge_features or {}).items()}
    self.old2new = dict(old2new or {})
    self.new2old = {nt: np.argsort(m) for nt, m in self.old2new.items()}
    #: multi-host: partition indices THIS process materialized (see
    #: `DistDataset.host_parts`).  None = all partitions.
    self.host_parts = (np.asarray(host_parts, np.int64)
                       if host_parts is not None else None)

  @property
  def num_partitions(self) -> int:
    return len(next(iter(self.bounds.values()))) - 1

  @property
  def etypes(self) -> Tuple[EdgeType, ...]:
    return tuple(sorted(self.graphs.keys()))

  @property
  def ntypes(self) -> Tuple[NodeType, ...]:
    return tuple(sorted(self.bounds.keys()))

  def num_nodes_dict(self) -> Dict[NodeType, int]:
    return {nt: int(b[-1]) for nt, b in self.bounds.items()}

  @classmethod
  def from_full_graph(cls, num_parts: int, edge_index_dict,
                      node_feat_dict=None, node_label_dict=None,
                      num_nodes_dict=None, node_pb_dict=None,
                      seed: int = 0, edge_feat_dict=None,
                      edge_ids_dict=None,
                      split_ratio: float = 1.0,
                      partitioner=None) -> 'DistHeteroDataset':
    """In-memory partition + shard (testing & single-host path) — the
    hetero analog of `DistDataset.from_full_graph`.  ``edge_ids_dict``
    preserves caller-global edge ids (``edge_feat_dict`` rows index by
    them); defaults to input order per etype.  ``split_ratio < 1``
    tiers every node-type feature store (HBM hot / host-DRAM cold,
    hotness = cross-etype in-degree) — the IGBH-scale lever
    (`build_dist_feature`).

    ``partitioner`` (or ``GLT_PARTITIONER``): ``'locality'`` runs the
    ISSUE 20 streaming partitioner over the DISJOINT UNION of all node
    types (one joint stream, so an etype's endpoints co-locate across
    types) and splits the joint assignment back per type; the balance
    bound then holds on the union, not per type.  Unset/'range' keeps
    the historical seeded round-robin byte-for-byte.  An explicit
    ``node_pb_dict`` entry always wins for its type."""
    node_feat_dict = node_feat_dict or {}
    node_label_dict = node_label_dict or {}
    num_nodes_dict = dict(num_nodes_dict or {})
    ntypes = sorted({t for (s, _, d) in edge_index_dict for t in (s, d)}
                    | set(node_feat_dict) | set(num_nodes_dict))
    for (s, _, d), (rows, cols) in edge_index_dict.items():
      num_nodes_dict[s] = max(num_nodes_dict.get(s, 0),
                              int(np.max(rows, initial=-1)) + 1)
      num_nodes_dict[d] = max(num_nodes_dict.get(d, 0),
                              int(np.max(cols, initial=-1)) + 1)
    for nt, f in node_feat_dict.items():
      num_nodes_dict[nt] = max(num_nodes_dict.get(nt, 0), len(f))

    hotness = {}
    if split_ratio < 1.0:
      # hotness = in-degree summed over every etype landing on nt
      hotness = {nt: np.zeros(num_nodes_dict[nt], np.int64)
                 for nt in ntypes}
      for (s, _, d), (rows, cols) in edge_index_dict.items():
        hotness[d] += np.bincount(np.asarray(cols),
                                  minlength=num_nodes_dict[d])

    rng = np.random.default_rng(seed)
    node_pb_dict = dict(node_pb_dict or {})
    from .locality import locality_partition, resolve_partitioner
    part_kind = resolve_partitioner(partitioner)
    missing = [nt for nt in ntypes if nt not in node_pb_dict]
    if missing and isinstance(part_kind, str) and part_kind == 'locality':
      # joint stream over the disjoint union: offset each type's id
      # space, partition once, split the assignment back per type
      off, tot = {}, 0
      for nt in ntypes:
        off[nt] = tot
        tot += num_nodes_dict[nt]
      g_rows = [off[s] + np.asarray(r, np.int64)
                for (s, _, d), (r, c) in edge_index_dict.items()]
      g_cols = [off[d] + np.asarray(c, np.int64)
                for (s, _, d), (r, c) in edge_index_dict.items()]
      pb_joint, _ = locality_partition(
          np.concatenate(g_rows) if g_rows else np.empty(0, np.int64),
          np.concatenate(g_cols) if g_cols else np.empty(0, np.int64),
          tot, num_parts, seed=seed)
      for nt in missing:
        node_pb_dict[nt] = pb_joint[off[nt]:off[nt]
                                    + num_nodes_dict[nt]].copy()
    old2new, bounds = {}, {}
    for nt in ntypes:
      n = num_nodes_dict[nt]
      pb = node_pb_dict.get(nt)
      if pb is None:
        pb = np.empty(n, dtype=np.int32)
        perm = rng.permutation(n)
        for p in range(num_parts):
          pb[perm[p::num_parts]] = p
        node_pb_dict[nt] = pb
      if nt in hotness:
        order = np.lexsort((np.arange(n), -hotness[nt], pb))
      else:
        order = np.argsort(pb, kind='stable')
      m = np.empty(n, dtype=np.int64)
      m[order] = np.arange(n)
      old2new[nt] = m
      counts = np.bincount(pb, minlength=num_parts)
      bounds[nt] = np.concatenate([[0], np.cumsum(counts)])

    graphs = {}
    for et, (rows, cols) in edge_index_dict.items():
      s, _, d = et
      graphs[et] = _build_etype_graph(
          old2new[s][np.asarray(rows)], old2new[d][np.asarray(cols)],
          bounds[s], num_parts,
          edge_ids=(edge_ids_dict or {}).get(et))

    feats = {nt: build_dist_feature(f, old2new[nt], bounds[nt],
                                    split_ratio=split_ratio)
             for nt, f in node_feat_dict.items()}
    labels = {}
    for nt, lab in node_label_dict.items():
      labels[nt] = build_dist_feature(
          np.asarray(lab), old2new[nt], bounds[nt]).shards[..., 0]
    efeats = {tuple(et): build_dist_edge_feature(f, num_parts)
              for et, f in (edge_feat_dict or {}).items()}
    return cls(graphs, bounds, feats, labels, old2new,
               edge_features=efeats)

  @classmethod
  def from_partition_dir(cls, root, num_parts: Optional[int] = None,
                         split_ratio: float = 1.0,
                         host_parts=None) -> 'DistHeteroDataset':
    """Assemble from the offline partitioner's hetero layout
    (`partition/base.py` hetero branch; reference `DistDataset.load`).
    ``split_ratio < 1`` tiers every node-type feature store.
    ``host_parts`` materializes only this process's partitions (see
    `DistDataset.from_partition_dir`) and serves the full composition:
    tiered stores (owner-served cold tiers, `overlay_cold_owner`),
    per-etype edge features, and ``by_dst`` layouts."""
    if host_parts is not None:
      return _hetero_host_local(cls, root, num_parts, split_ratio,
                                host_parts)
    from ..partition import load_partition
    p0 = load_partition(root, 0)
    meta = p0['meta']
    assert meta['hetero'], 'homogeneous layout: use DistDataset'
    num_parts = num_parts or meta['num_parts']
    parts = [p0] + [load_partition(root, i) for i in range(1, num_parts)]

    edge_index_dict, node_pb_dict, edge_ids_dict = {}, {}, {}
    for nt in meta['node_types']:
      node_pb_dict[nt] = np.asarray(parts[0]['node_pb'][nt].table)
    for et in parts[0]['graph']:
      rows = np.concatenate([p['graph'][et].edge_index[0] for p in parts])
      cols = np.concatenate([p['graph'][et].edge_index[1] for p in parts])
      edge_index_dict[et] = (rows, cols)
      # keep the partitioner's GLOBAL edge ids: edge features (and any
      # user-side eid provenance) index by them, not by concat order
      edge_ids_dict[et] = np.concatenate(
          [p['graph'][et].eids for p in parts])
    node_feat_dict = {}
    for nt in meta['node_types']:
      fparts = [p['node_feat'].get(nt) for p in parts]
      if any(f is not None for f in fparts):
        n = int(meta['num_nodes'][nt])
        d = next(f for f in fparts if f is not None).feats.shape[1]
        feats = np.zeros((n, d), next(f for f in fparts
                                      if f is not None).feats.dtype)
        for f in fparts:
          if f is not None:
            feats[f.ids] = f.feats
        node_feat_dict[nt] = feats
    node_label_dict = {}
    for nt in meta['node_types']:
      lparts = [p['node_label'].get(nt) for p in parts]
      if any(l is not None for l in lparts):
        n = int(meta['num_nodes'][nt])
        lab0 = next(l for l in lparts if l is not None)[0]
        labels = np.zeros((n,), lab0.dtype)
        for l in lparts:
          if l is not None:
            labels[l[1]] = l[0]
        node_label_dict[nt] = labels
    edge_feat_dict = {}
    from ..typing import as_str
    for et in edge_index_dict:
      fparts = [(p.get('edge_feat') or {}).get(et) for p in parts]
      if any(f is not None for f in fparts):
        e = int(meta.get('num_edges', {}).get(
            as_str(et), len(edge_index_dict[et][0])))
        f0 = next(f for f in fparts if f is not None)
        efeats = np.zeros((e, f0.feats.shape[1]), f0.feats.dtype)
        for f in fparts:
          if f is not None:
            efeats[f.ids] = f.feats
        edge_feat_dict[et] = efeats
    return cls.from_full_graph(
        num_parts, edge_index_dict, node_feat_dict, node_label_dict,
        num_nodes_dict={nt: int(meta['num_nodes'][nt])
                        for nt in meta['node_types']},
        node_pb_dict=node_pb_dict, edge_feat_dict=edge_feat_dict,
        edge_ids_dict=edge_ids_dict, split_ratio=split_ratio)


def _hetero_host_local(cls, root, num_parts, split_ratio, host_parts):
  """Host-local arm of `DistHeteroDataset.from_partition_dir`:
  materialize only ``host_parts`` — global relabels/bounds/padding/
  hotness from per-type ``node_pb_*`` files, chunked mmap scans, and
  mmap'd array shapes; local CSR/feature/label/edge-feature stacks
  from per-partition files.  Tiered stores get per-type owner-served
  cold stacks (`DistFeature.cold_local`); ``by_dst`` layouts are
  re-bucketed by src owner with chunked scans."""
  import json as _json
  from pathlib import Path
  from ..typing import as_str, edge_type_from_str
  from .dist_data import (DistFeature, DistGraph, partition_in_degree,
                          relabel_by_partition, scatter_partition_rows,
                          stack_mod_edge_features, stack_partition_csr,
                          stack_partition_csr_rebucket,
                          tiered_local_feature)
  root = Path(root)
  with open(root / 'META.json') as f:
    meta = _json.load(f)
  assert meta['hetero'], 'homogeneous layout: use DistDataset'
  by_src = meta.get('edge_assign', 'by_src') == 'by_src'
  num_parts = num_parts or meta['num_parts']
  host_parts = np.asarray(host_parts, np.int64)
  etypes = [edge_type_from_str(ets) for ets in meta['edge_types']]

  # hotness per node type = in-degree summed over etypes landing on it
  # (the from_full_graph tiering policy, chunked) — MUST match the
  # single-controller relabel of the same (layout, split_ratio)
  hotness = {}
  if split_ratio < 1.0:
    hotness = {nt: np.zeros(int(meta['num_nodes'][nt]), np.int64)
               for nt in meta['node_types']}
    for et in etypes:
      hotness[et[2]] += partition_in_degree(
          root, f'graph/{as_str(et)}', int(meta['num_nodes'][et[2]]),
          num_parts)

  node_pbs, old2new, bounds, counts = {}, {}, {}, {}
  for nt in meta['node_types']:
    node_pbs[nt] = np.load(root / f'node_pb_{nt}.npy')
    old2new[nt], counts[nt], bounds[nt] = relabel_by_partition(
        node_pbs[nt], num_parts, hotness.get(nt))

  graphs = {}
  for et in etypes:
    s, _, d = et
    if by_src:
      indptr_s, indices_s, eids_s = stack_partition_csr(
          root, host_parts, f'graph/{as_str(et)}', old2new[s],
          old2new[d], bounds[s], counts[s], num_parts)
    else:
      indptr_s, indices_s, eids_s = stack_partition_csr_rebucket(
          root, host_parts, f'graph/{as_str(et)}', node_pbs[s],
          old2new[s], old2new[d], bounds[s], counts[s], num_parts)
    graphs[et] = DistGraph(indptr_s, indices_s, eids_s, bounds[s])

  feats, labels = {}, {}
  for nt in meta['node_types']:
    max_nodes = int(counts[nt].max())
    fs = scatter_partition_rows(root, host_parts, f'node_feat/{nt}',
                                'feats', old2new[nt], bounds[nt],
                                max_nodes)
    ls = scatter_partition_rows(root, host_parts, f'node_label/{nt}',
                                'labels', old2new[nt], bounds[nt],
                                max_nodes)
    if fs is not None:
      if split_ratio < 1.0:
        feats[nt] = tiered_local_feature(fs, counts[nt], split_ratio,
                                         host_parts, bounds[nt])
      else:
        feats[nt] = DistFeature(fs, bounds[nt])
    if ls is not None:
      labels[nt] = ls

  efeats = {}
  for et in etypes:
    ef = stack_mod_edge_features(
        root, host_parts, f'edge_feat/{as_str(et)}', num_parts,
        int(meta.get('num_edges', {}).get(as_str(et), 0)))
    if ef is not None:
      efeats[et] = ef
  return cls(graphs, bounds, feats, labels, old2new,
             edge_features=efeats, host_parts=host_parts)


def _build_etype_graph(rows_new: np.ndarray, cols_new: np.ndarray,
                       bounds_s: np.ndarray, num_parts: int,
                       edge_ids: Optional[np.ndarray] = None):
  """Stacked per-partition local CSRs for one edge type.

  ``rows_new`` are RELABELED src-type ids (sharded by ``bounds_s``),
  ``cols_new`` RELABELED dst-type ids kept global — the hetero twist
  `build_dist_graph` can't express (its single relabel map would be
  applied to both endpoint spaces).  ``edge_ids`` preserves the
  caller's GLOBAL edge ids (edge features index by them); defaults to
  input order.
  """
  from .dist_data import DistGraph
  from .partition_book import range_of_host
  from ..utils.topo import coo_to_csr
  counts = np.diff(bounds_s)
  owner = range_of_host(bounds_s, rows_new, num_parts=num_parts)
  if edge_ids is None:
    edge_ids = np.arange(len(rows_new), dtype=np.int64)
  else:
    edge_ids = np.asarray(edge_ids, np.int64)
  max_nodes = int(counts.max()) if num_parts else 0
  max_edges = max(int(np.bincount(owner, minlength=num_parts).max()), 1)
  indptr_s = np.zeros((num_parts, max_nodes + 1), dtype=np.int64)
  indices_s = np.full((num_parts, max_edges), -1, dtype=np.int32)
  eids_s = np.full((num_parts, max_edges), -1, dtype=np.int64)
  for p in range(num_parts):
    sel = owner == p
    local_rows = rows_new[sel] - bounds_s[p]
    iptr, idx, eid = coo_to_csr(local_rows, cols_new[sel],
                                int(counts[p]), edge_ids[sel])
    indptr_s[p, :len(iptr)] = iptr
    indptr_s[p, len(iptr):] = iptr[-1]
    indices_s[p, :len(idx)] = idx
    eids_s[p, :len(eid)] = eid
  return DistGraph(indptr_s, indices_s, eids_s, bounds_s)


class DistHeteroNeighborSampler(ExchangeTelemetry):
  """SPMD hetero multihop sampler (+ per-type feature collection).

  Args:
    dataset: `DistHeteroDataset`.
    num_neighbors: per-hop fanouts — list (all etypes) or per-etype
      dict.
    mesh: mesh whose ``axis`` size == partition count.
    exchange_slack: per-destination exchange capacity as a multiple of
      the balanced share (see `dist_sampler.DistNeighborSampler`);
      None = exact.
  """

  def __init__(self, dataset: DistHeteroDataset, num_neighbors,
               mesh: Optional[Mesh] = None, axis: str = 'data',
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, exchange_slack: Optional[float] = None,
               exchange_layout: Optional[str] = None):
    from .dp import make_mesh
    self.ds = dataset
    self.etypes, self.fanouts, self.num_hops = normalize_fanouts(
        dataset.etypes, num_neighbors)
    self.num_parts = dataset.num_partitions
    self.mesh = mesh or make_mesh(self.num_parts, axis)
    self.axis = axis
    self.with_edge = with_edge
    self.collect_features = collect_features
    self.exchange_slack = exchange_slack
    # see DistNeighborSampler: dense/compact/hier/ragged per-etype
    # exchange layout; every per-type hop and gather below shares it
    self.exchange_layout = exchange_layout or 'auto'
    self._base_key = jax.random.key(seed)
    self._step_cnt = 0
    self._steps = {}
    self._device_arrays = None
    self._init_stats()

  def _arrays(self):
    if self._device_arrays is None:
      from .dist_sampler import put_stacked_host_local
      shard = NamedSharding(self.mesh, P(self.axis))
      repl = NamedSharding(self.mesh, P())
      put = jax.device_put
      if getattr(self.ds, 'host_parts', None) is not None:
        putS = lambda a: put_stacked_host_local(    # noqa: E731
            self.mesh, self.axis, self.num_parts, self.ds.host_parts,
            np.asarray(a))
      else:
        putS = lambda a: put(np.asarray(a), shard)  # noqa: E731
      arrs = {'graphs': {}, 'bounds': {}, 'feats': {}, 'labels': {},
              'efeats': {}, 'hcounts': {}}
      for et in self.etypes:
        g = self.ds.graphs[et]
        arrs['graphs'][et] = (putS(g.indptr), putS(g.indices),
                              putS(g.edge_ids))
      for nt, b in self.ds.bounds.items():
        arrs['bounds'][nt] = put(b, repl)
      if self.collect_features:
        for nt, f in self.ds.node_features.items():
          arrs['feats'][nt] = putS(f.shards)
          arrs['hcounts'][nt] = put(
              np.asarray(f.hot_counts, np.int32), repl)
        if self.with_edge:
          # only fanout-selected etypes sample edges; features of
          # unselected etypes would never be gathered (and their
          # eids_acc keys don't exist in the step)
          for et, f in self.ds.edge_features.items():
            if et in self.etypes:
              arrs['efeats'][et] = (putS(f.shards),
                                    put(f.bounds, repl))
      for nt, l in self.ds.node_labels.items():
        arrs['labels'][nt] = putS(l)
      self._device_arrays = arrs
    return self._device_arrays

  def _make_step(self, input_sizes: Dict[NodeType, int],
                 link: Optional[dict] = None):
    from .shard_map_compat import shard_map
    ntypes, table_cap, frontier_caps, _ = _plan_capacities(
        self.etypes, self.fanouts, input_sizes, self.num_hops,
        self.ds.num_nodes_dict())
    num_nodes = self.ds.num_nodes_dict()
    seed_types = tuple(sorted(input_sizes))
    etypes = self.etypes
    fanouts = self.fanouts
    num_parts = self.num_parts
    axis = self.axis
    with_edge = self.with_edge
    arrs = self._arrays()
    feat_nts = tuple(sorted(arrs['feats'])) if self.collect_features else ()
    label_nts = tuple(sorted(arrs['labels']))
    efeat_ets = tuple(sorted(arrs['efeats']))
    tiered_nts = {nt: self.ds.node_features[nt].is_tiered
                  for nt in feat_nts}
    # per-TABLE ownership scheme: a mixed mod/range edge_features dict
    # must not collapse to one global mode (wrong-owner gathers return
    # silent zeros)
    ef_modes = {et: ('mod' if self.ds.edge_features[et].mod_sharded
                     else 'range') for et in efeat_ets}
    num_hops = self.num_hops
    exchange_slack = self.exchange_slack
    exchange_layout = self.exchange_layout

    def per_device(graphs_t, bounds_t, feats_t, labels_t, efeats_t,
                   ebounds_t, hcounts_t, seeds_s, key):
      graphs = {et: tuple(a[0] for a in g)
                for et, g in zip(etypes, graphs_t)}
      bounds = dict(zip(ntypes, bounds_t))
      fshards = {nt: f[0] for nt, f in zip(feat_nts, feats_t)}
      lshards = {nt: l[0] for nt, l in zip(label_nts, labels_t)}
      efshards = {et: f[0] for et, f in zip(efeat_ets, efeats_t)}
      ebounds = dict(zip(efeat_ets, ebounds_t))
      hcounts = dict(zip(feat_nts, hcounts_t))
      seeds = seeds_s[0]

      neg_ok = None
      if link is None:
        seed_sets = {seed_types[0]: seeds}
      else:
        # link mode: endpoints + collective strict negatives on the
        # seed edge type's sharded CSR (the hetero arm of
        # `dist_sampler._make_dist_link_step`)
        let = link['etype']
        s_t, _, d_t = let
        pairs = seeds
        src, dst = pairs[:, 0], pairs[:, 1]
        li, lx, _ = graphs[let]
        my_idx = jax.lax.axis_index(axis)
        neg_key = jax.random.fold_in(jax.random.fold_in(key, my_idx), 977)
        neg_cap = _slack_cap(link['num_neg'] * NEG_TRIALS, num_parts,
                             exchange_slack, exchange_layout)
        if link['mode'] == 'binary':
          nrows, ncols, neg_ok = dist_sample_negative(
              li, lx, bounds[s_t], num_nodes[s_t], num_nodes[d_t],
              link['num_neg'], neg_key, axis, num_parts,
              exchange_capacity=neg_cap)
          src_seeds = jnp.concatenate([src, nrows])
          dst_seeds = jnp.concatenate([dst, ncols])
        elif link['mode'] == 'triplet':
          amount = link['num_neg'] // link['batch']
          srcs_rep = jnp.repeat(jnp.where(src >= 0, src, 0), amount)
          _, negs, neg_ok = dist_sample_negative(
              li, lx, bounds[s_t], num_nodes[s_t], num_nodes[d_t],
              link['num_neg'], neg_key, axis, num_parts,
              exchange_capacity=neg_cap,
              rows_fixed=srcs_rep.astype(jnp.int32))
          src_seeds = src
          dst_seeds = jnp.concatenate([dst, negs])
        else:
          src_seeds, dst_seeds = src, dst
        clean = lambda v: jnp.where(v >= 0, v, INVALID_ID).astype(
            jnp.int32)
        if s_t == d_t:
          seed_sets = {s_t: clean(jnp.concatenate([src_seeds,
                                                   dst_seeds]))}
        else:
          seed_sets = {s_t: clean(src_seeds), d_t: clean(dst_seeds)}

      states, seed_locals = {}, {}
      for nt in ntypes:
        if nt in seed_sets:
          states[nt], seed_locals[nt] = init_node(seed_sets[nt],
                                                  table_cap[nt])
        else:
          states[nt] = init_node(
              jnp.full((1,), INVALID_ID, jnp.int32), table_cap[nt])[0]
      fr_start = {nt: jnp.zeros((), jnp.int32) for nt in ntypes}
      rows_acc = {et: [] for et in etypes}
      cols_acc = {et: [] for et in etypes}
      eids_acc = {et: [] for et in etypes}
      nsn = {nt: [states[nt].count] for nt in ntypes}
      fr_stats = jnp.zeros((3,), jnp.int32)
      ft_stats = jnp.zeros((3,), jnp.int32)

      for h in range(num_hops):
        hop_start = {nt: states[nt].count for nt in ntypes}
        frontiers = {}
        for nt in ntypes:
          fcap = frontier_caps[h].get(nt, 0)
          if fcap <= 0:
            frontiers[nt] = None
            continue
          slots = fr_start[nt] + jnp.arange(fcap, dtype=jnp.int32)
          valid = slots < hop_start[nt]
          nodes = states[nt].nodes[
              jnp.clip(slots, 0, table_cap[nt] - 1)]
          frontiers[nt] = (jnp.where(valid, nodes, INVALID_ID),
                           jnp.where(valid, slots, -1))
        for ei_i, et in enumerate(etypes):
          s, _, d = et
          k = fanouts[et][h] if h < len(fanouts[et]) else 0
          if k <= 0 or frontiers.get(s) is None:
            continue
          fr_nodes, fr_local = frontiers[s]
          indptr, indices, eids = graphs[et]
          hop_key = jax.random.fold_in(jax.random.fold_in(key, h), ei_i)
          nbrs, mask, e, _w, hstats = _dist_one_hop(
              indptr, indices, eids if with_edge else None, bounds[s],
              fr_nodes, int(k), hop_key, axis, num_parts, with_edge,
              exchange_capacity=_slack_cap(fr_nodes.shape[0], num_parts,
                                           exchange_slack,
                                           exchange_layout))
          fr_stats = fr_stats + jnp.stack(hstats)
          states[d], rows, cols, _ = induce_next(
              states[d], fr_local, nbrs, mask)
          rows_acc[et].append(rows)
          cols_acc[et].append(cols)
          if with_edge:
            eids_acc[et].append(
                jnp.where(rows >= 0, e.reshape(-1), INVALID_ID))
        for nt in ntypes:
          fr_start[nt] = hop_start[nt]
          nsn[nt].append(states[nt].count)

      x = {}
      for nt in feat_nts:
        (x[nt],), gstats = dist_gather_multi(
            (fshards[nt],), bounds[nt], states[nt].nodes, axis,
            num_parts,
            exchange_capacity=_slack_cap(table_cap[nt], num_parts,
                                         exchange_slack,
                                         exchange_layout),
            hot_counts=hcounts[nt] if tiered_nts[nt] else None)
        ft_stats = ft_stats + jnp.stack(gstats)
      y = {}
      for nt in label_nts:
        (y[nt],), gstats = dist_gather_multi(
            (lshards[nt],), bounds[nt], states[nt].nodes, axis,
            num_parts,
            exchange_capacity=_slack_cap(table_cap[nt], num_parts,
                                         exchange_slack,
                                         exchange_layout))
        ft_stats = ft_stats + jnp.stack(gstats)

      ef = {}
      for et in efeat_ets:
        if not eids_acc.get(et):
          continue
        all_eids = jnp.concatenate(eids_acc[et])
        (ef[et],), gstats = dist_gather_multi(
            (efshards[et],), ebounds[et], all_eids, axis, num_parts,
            exchange_capacity=_slack_cap(all_eids.shape[0], num_parts,
                                         exchange_slack,
                                         exchange_layout),
            shard_mode=ef_modes[et])
        ft_stats = ft_stats + jnp.stack(gstats)

      neg_lost = (jnp.sum((~neg_ok).astype(jnp.int32))
                  if neg_ok is not None else jnp.int32(0))
      stats = jnp.concatenate([fr_stats, ft_stats, neg_lost[None]])
      if neg_ok is None:
        neg_ok = jnp.ones((1,), bool)

      def lead(v):
        return None if v is None else v[None]
      node_t = tuple(lead(states[nt].nodes) for nt in ntypes)
      cnt_t = tuple(lead(states[nt].count[None]) for nt in ntypes)
      row_t = tuple(
          lead(jnp.concatenate(rows_acc[et])) if rows_acc[et] else None
          for et in etypes)
      col_t = tuple(
          lead(jnp.concatenate(cols_acc[et])) if cols_acc[et] else None
          for et in etypes)
      eid_t = tuple(
          lead(jnp.concatenate(eids_acc[et]))
          if (with_edge and eids_acc[et]) else None
          for et in etypes)
      x_t = tuple(lead(x[nt]) for nt in feat_nts)
      y_t = tuple(lead(y[nt]) for nt in label_nts)
      nsn_t = tuple(
          lead(jnp.concatenate(
              [jnp.stack(nsn[nt])[:1],
               jnp.stack(nsn[nt])[1:] - jnp.stack(nsn[nt])[:-1]]))
          for nt in ntypes)
      sl_t = tuple(lead(seed_locals[nt]) for nt in seed_types)
      ef_t = tuple(lead(ef[et]) if et in ef else None
                   for et in efeat_ets)
      return (node_t, cnt_t, row_t, col_t, eid_t, sl_t,
              x_t, y_t, ef_t, nsn_t, lead(neg_ok), lead(stats))

    sh = P(axis)
    rp = P()
    in_specs = (
        tuple((sh, sh, sh) for _ in etypes),      # graphs
        tuple(rp for _ in ntypes),                # bounds
        tuple(sh for _ in feat_nts),              # feature shards
        tuple(sh for _ in label_nts),             # label shards
        tuple(sh for _ in efeat_ets),             # edge-feature shards
        tuple(rp for _ in efeat_ets),             # edge-feature bounds
        tuple(rp for _ in feat_nts),              # feature hot counts
        sh,                                       # seeds
        rp,                                       # key
    )
    out_specs = (
        tuple(sh for _ in ntypes), tuple(sh for _ in ntypes),
        tuple(sh for _ in etypes), tuple(sh for _ in etypes),
        tuple(sh for _ in etypes), tuple(sh for _ in seed_types),
        tuple(sh for _ in feat_nts), tuple(sh for _ in label_nts),
        tuple(sh for _ in efeat_ets),
        tuple(sh for _ in ntypes), sh, sh,
    )
    sharded = shard_map(per_device, mesh=self.mesh, in_specs=in_specs,
                        out_specs=out_specs)
    meta = dict(ntypes=ntypes, feat_nts=feat_nts, label_nts=label_nts,
                seed_types=seed_types, efeat_ets=efeat_ets)
    return jax.jit(sharded), meta

  def _overlay_cold_types(self, feat_nts, ntypes, x_t, node_t):
    """Per-node-type cold-tier overlay (+ telemetry) for tiered
    feature stores — the hetero arm of
    `dist_sampler.overlay_cold_host` / `overlay_cold_owner`.  All
    requester-side (``cold_host``) node tables come down in ONE
    device_get (one sync per batch, like the homo path); owner-served
    (``cold_local``, host-local layouts) types run the second-gather
    protocol, which reads only this process's addressable shards."""
    from .dist_sampler import overlay_cold_owner
    tiered = [(i, nt) for i, (nt, x) in enumerate(zip(feat_nts, x_t))
              if x is not None and self.ds.node_features[nt].is_tiered]
    if not tiered:
      return x_t
    host_side = [(i, nt) for i, nt in tiered
                 if self.ds.node_features[nt].cold_host is not None]
    fetched = (jax.device_get([node_t[ntypes.index(nt)]
                               for _, nt in host_side])
               if host_side else [])
    out = list(x_t)
    for (i, nt), nodes_h in zip(host_side, fetched):
      nf = self.ds.node_features[nt]
      out[i], lookups, misses = overlay_cold_host(
          out[i], node_t[ntypes.index(nt)], self.ds.bounds[nt],
          nf.hot_counts, nf.cold_host, self.mesh, self.axis,
          self.num_parts, nodes_host=nodes_h)
      with self._stats_lock:
        # hetero engine: no dynamic cache yet — every cold request is
        # host-served (cold_lookups == cold_misses)
        self._feat_lookups += lookups
        self._cold_lookups += misses
        self._cold_misses += misses
      # surface the no-cache economics LIVE (ISSUE 14 satellite):
      # cache.misses_total{scope=hetero} ticks with hits pinned at 0,
      # so `cold_lookups == cold_misses` (ROADMAP item 3's hetero
      # cold-cache gap) reads off /metrics instead of artifact-only
      emit_cache_events('hetero', 0, int(misses), 0, 0)
    hp = (self.ds.host_parts if self.ds.host_parts is not None
          else np.arange(self.num_parts))
    # ONE capacity handshake for every owner-served type (ADVICE r4:
    # a per-(type, batch) allgather dominates at large P x many
    # types): plan all types first, agree on all capacities in a
    # single `_global_max_vec`, then execute each overlay
    from .dist_sampler import _global_max_vec, plan_cold_requests
    owner_served = [(i, nt) for i, nt in tiered
                    if self.ds.node_features[nt].cold_host is None]
    plans = []
    for i, nt in owner_served:
      nf = self.ds.node_features[nt]
      plans.append(plan_cold_requests(
          node_t[ntypes.index(nt)], self.ds.bounds[nt], nf.hot_counts,
          hp, cache_ids=nf.cache_ids))
    agreed = _global_max_vec(
        [int(p[5].max(initial=0)) for p in plans]) if plans else []
    for (i, nt), plan, cap in zip(owner_served, plans, agreed):
      nf = self.ds.node_features[nt]
      out[i], lookups, misses = overlay_cold_owner(
          out[i], node_t[ntypes.index(nt)], self.ds.bounds[nt],
          nf.hot_counts, nf.cold_local, self.mesh, self.axis,
          self.num_parts, hp, cache_ids=nf.cache_ids, plan_=plan,
          agreed_capacity=cap)
      with self._stats_lock:
        # hetero engine: no dynamic cache yet — every cold request is
        # host-served (cold_lookups == cold_misses)
        self._feat_lookups += lookups
        self._cold_lookups += misses
        self._cold_misses += misses
      # same live accounting for the owner-served arm (see above)
      emit_cache_events('hetero', 0, int(misses), 0, 0)
    return tuple(out)

  def sample_from_nodes(self, input_type: NodeType,
                        seeds_stacked: np.ndarray):
    """``seeds_stacked``: ``[P, B]`` per-device seeds of ``input_type``
    in that type's RELABELED id space (-1 padded).  Returns a dict of
    per-type stacked pieces."""
    b = int(seeds_stacked.shape[1])
    cfg = (input_type, b)
    if cfg not in self._steps:
      self._steps[cfg] = self._make_step({input_type: b})
    step, meta = self._steps[cfg]
    arrs = self._arrays()
    self._step_cnt += 1
    key = jax.random.fold_in(self._base_key, self._step_cnt)
    seeds_dev = jax.device_put(
        np.asarray(seeds_stacked, dtype=np.int32),
        NamedSharding(self.mesh, P(self.axis)))
    graphs_t = tuple(arrs['graphs'][et] for et in self.etypes)
    bounds_t = tuple(arrs['bounds'][nt] for nt in meta['ntypes'])
    feats_t = tuple(arrs['feats'][nt] for nt in meta['feat_nts'])
    labels_t = tuple(arrs['labels'][nt] for nt in meta['label_nts'])
    efeats_t = tuple(arrs['efeats'][et][0] for et in meta['efeat_ets'])
    ebounds_t = tuple(arrs['efeats'][et][1] for et in meta['efeat_ets'])
    hcounts_t = tuple(arrs['hcounts'][nt] for nt in meta['feat_nts'])
    (node_t, cnt_t, row_t, col_t, eid_t, sl_t, x_t, y_t, ef_t,
     nsn_t, _, stats) = step(graphs_t, bounds_t, feats_t, labels_t,
                             efeats_t, ebounds_t, hcounts_t, seeds_dev,
                             key)
    self._accumulate_stats(stats)
    x_t = self._overlay_cold_types(meta['feat_nts'], meta['ntypes'],
                                   x_t, node_t)
    seed_local = sl_t[meta['seed_types'].index(input_type)]
    ntypes = meta['ntypes']
    out = dict(
        node=dict(zip(ntypes, node_t)),
        node_count={nt: c[..., 0] for nt, c in zip(ntypes, cnt_t)},
        row={reverse_edge_type(et): r
             for et, r in zip(self.etypes, row_t) if r is not None},
        col={reverse_edge_type(et): c
             for et, c in zip(self.etypes, col_t) if c is not None},
        edge={reverse_edge_type(et): e
              for et, e in zip(self.etypes, eid_t) if e is not None},
        seed_local=seed_local,
        x=dict(zip(meta['feat_nts'], x_t)),
        y=dict(zip(meta['label_nts'], y_t)),
        ef={reverse_edge_type(et): e
            for et, e in zip(meta['efeat_ets'], ef_t) if e is not None},
        num_sampled_nodes=dict(zip(ntypes, nsn_t)),
        batch=seeds_dev, input_type=input_type)
    return out

  def _link_input_sizes(self, etype, mode, amount, b):
    """Per-type seed counts for link expansion — negative counts from
    the ONE shared definition (`distributed.dist_options.
    binary_num_negatives`)."""
    from ..distributed.dist_options import binary_num_negatives
    s_t, _, d_t = etype
    if mode == 'binary':
      nn = binary_num_negatives(b, amount)
      src_n = dst_n = b + nn
    elif mode == 'triplet':
      nn = b * int(np.ceil(amount))
      src_n, dst_n = b, b + nn
    else:
      nn = 0
      src_n = dst_n = b
    if s_t == d_t:
      return {s_t: src_n + dst_n}, nn
    return {s_t: src_n, d_t: dst_n}, nn

  def sample_from_edges(self, input_type: EdgeType,
                        pairs_stacked: np.ndarray,
                        neg_sampling=None):
    """``pairs_stacked``: ``[P, B, 2|3]`` per-device (src, dst[,
    label]) seed edges of edge type ``input_type``, each endpoint in
    its node type's RELABELED id space.  Negatives are strict against
    the global sharded etype CSR (collective `dist_edge_exists`)."""
    from ..sampler.base import NegativeSampling
    et = tuple(input_type)
    s_t, _, d_t = et
    ns = (NegativeSampling.cast(neg_sampling)
          if neg_sampling is not None else None)
    mode = ns.mode if ns is not None else None
    amount = float(ns.amount) if ns is not None else 1.0
    b = int(pairs_stacked.shape[1])
    input_sizes, num_neg = self._link_input_sizes(et, mode, amount, b)
    cfg = ('link', et, mode, amount, b, pairs_stacked.shape[2])
    if cfg not in self._steps:
      self._steps[cfg] = self._make_step(
          input_sizes, link=dict(etype=et, mode=mode,
                                 num_neg=num_neg, batch=b))
    step, meta = self._steps[cfg]
    arrs = self._arrays()
    self._step_cnt += 1
    key = jax.random.fold_in(self._base_key, self._step_cnt)
    pairs_dev = jax.device_put(
        np.asarray(pairs_stacked, dtype=np.int32),
        NamedSharding(self.mesh, P(self.axis)))
    graphs_t = tuple(arrs['graphs'][e] for e in self.etypes)
    bounds_t = tuple(arrs['bounds'][nt] for nt in meta['ntypes'])
    feats_t = tuple(arrs['feats'][nt] for nt in meta['feat_nts'])
    labels_t = tuple(arrs['labels'][nt] for nt in meta['label_nts'])
    efeats_t = tuple(arrs['efeats'][e][0] for e in meta['efeat_ets'])
    ebounds_t = tuple(arrs['efeats'][e][1] for e in meta['efeat_ets'])
    hcounts_t = tuple(arrs['hcounts'][nt] for nt in meta['feat_nts'])
    (node_t, cnt_t, row_t, col_t, eid_t, sl_t, x_t, y_t, ef_t, nsn_t,
     neg_ok, stats) = step(graphs_t, bounds_t, feats_t, labels_t,
                           efeats_t, ebounds_t, hcounts_t, pairs_dev,
                           key)
    self._accumulate_stats(stats)
    x_t = self._overlay_cold_types(meta['feat_nts'], meta['ntypes'],
                                   x_t, node_t)
    ntypes = meta['ntypes']
    seed_types = meta['seed_types']
    sl = dict(zip(seed_types, sl_t))
    if s_t == d_t:
      all_sl = sl[s_t]
      if mode == 'triplet':
        n_src = b
      elif mode == 'binary':
        n_src = b + num_neg
      else:
        n_src = b
      sl_s, sl_d = all_sl[:, :n_src], all_sl[:, n_src:]
    else:
      sl_s, sl_d = sl[s_t], sl[d_t]
    pair_valid = (pairs_dev[:, :, 0] >= 0) & (pairs_dev[:, :, 1] >= 0)
    pos_label = jnp.where(
        pair_valid,
        pairs_dev[:, :, 2] if pairs_stacked.shape[2] > 2
        else jnp.ones_like(pair_valid, jnp.int32), 0)
    md = {'seed_local': sl}
    if mode == 'binary':
      # sl_s/sl_d are already laid out positives-then-negatives
      eli = jnp.stack([sl_s, sl_d], axis=1)
      quota = jnp.ceil(jnp.sum(pair_valid, axis=1, keepdims=True)
                       * jnp.float32(amount)).astype(jnp.int32)
      neg_keep = neg_ok & (jnp.arange(num_neg)[None, :] < quota)
      md.update(
          edge_label_index=eli,
          edge_label=jnp.concatenate(
              [pos_label, jnp.zeros((pos_label.shape[0], num_neg),
                                    jnp.int32)], axis=1),
          edge_label_mask=jnp.concatenate([pair_valid, neg_keep],
                                          axis=1))
    elif mode == 'triplet':
      amount_i = num_neg // b
      dn = jnp.where(neg_ok, sl_d[:, b:], -1).reshape(
          sl_d.shape[0], b, amount_i)
      md.update(src_index=sl_s[:, :b], dst_pos_index=sl_d[:, :b],
                dst_neg_index=dn, pair_mask=sl_s[:, :b] >= 0)
    else:
      md.update(edge_label_index=jnp.stack([sl_s, sl_d], axis=1),
                edge_label=pos_label, edge_label_mask=pair_valid)
    return dict(
        node=dict(zip(ntypes, node_t)),
        node_count={nt: c[..., 0] for nt, c in zip(ntypes, cnt_t)},
        row={reverse_edge_type(e): r
             for e, r in zip(self.etypes, row_t) if r is not None},
        col={reverse_edge_type(e): c
             for e, c in zip(self.etypes, col_t) if c is not None},
        edge={reverse_edge_type(e): v
              for e, v in zip(self.etypes, eid_t) if v is not None},
        x=dict(zip(meta['feat_nts'], x_t)),
        y=dict(zip(meta['label_nts'], y_t)),
        ef={reverse_edge_type(e): v
            for e, v in zip(meta['efeat_ets'], ef_t) if v is not None},
        num_sampled_nodes=dict(zip(ntypes, nsn_t)),
        batch=pairs_dev[:, :, 0], metadata=md, input_type=et)


class DistHeteroNeighborLoader(PrefetchingLoader):
  """Distributed hetero loader: stacked `HeteroBatch`-shaped pytrees
  (leading axis = device), ready for a DP hetero train step.

  The facade reference users reach via ``DistNeighborLoader`` on a
  hetero `DistDataset` (`distributed/dist_neighbor_loader.py:27-94`).
  ``prefetch=N`` overlaps the next batch's host work (incl. tiered
  cold overlays) with the current device step.
  """

  def __init__(self, dataset: DistHeteroDataset, num_neighbors,
               input_nodes, batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, mesh: Optional[Mesh] = None,
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, input_space: str = 'old',
               exchange_slack='auto',
               exchange_layout: Optional[str] = None,
               prefetch: int = 0):
    from ..loader.node_loader import SeedBatcher
    from .dist_sampler import DEFAULT_EXCHANGE_SLACK, AdaptiveSlack
    self.prefetch = int(prefetch)
    input_type, seeds = input_nodes
    self.input_type = input_type
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistHeteroNeighborSampler(
        dataset, num_neighbors, mesh=mesh, with_edge=with_edge,
        collect_features=collect_features, seed=seed,
        exchange_slack=(DEFAULT_EXCHANGE_SLACK if slack == 'adaptive'
                        else slack),
        exchange_layout=exchange_layout)
    self._adaptive = (AdaptiveSlack(self.sampler)
                      if slack == 'adaptive' else None)
    self._epoch_count = 0
    self.ds = dataset
    seeds = np.asarray(seeds).reshape(-1)
    if input_space == 'old' and input_type in dataset.old2new:
      seeds = dataset.old2new[input_type][seeds]
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    self._batcher = SeedBatcher(seeds, batch_size * self.num_parts,
                                shuffle, drop_last, seed)

  def __len__(self):
    return len(self._batcher)

  def _produce(self, seed_iter):
    from ..loader.transform import HeteroBatch
    flat = next(seed_iter)
    seeds = flat.reshape(self.num_parts, self.batch_size)
    out = self.sampler.sample_from_nodes(self.input_type, seeds)
    ei = {et: jnp.stack([out['row'][et], out['col'][et]], axis=1)
          for et in out['row']}
    em = {et: out['row'][et] >= 0 for et in out['row']}
    md = {'seed_local': out['seed_local'],
          'input_type': self.input_type}
    if out['edge']:
      # global eids per reversed etype — same key the host runtime
      # collates (`distributed/dist_loader.py::_collate_hetero`)
      md['edge_dict'] = out['edge']
    return HeteroBatch(
        x_dict=out['x'], y_dict=out['y'], edge_index_dict=ei,
        edge_attr_dict=dict(out.get('ef') or {}), node_dict=out['node'],
        node_mask_dict={nt: v >= 0 for nt, v in out['node'].items()},
        edge_mask_dict=em,
        batch_dict={self.input_type: out['batch']},
        batch_size=self.batch_size,
        metadata=md)


class DistHeteroLinkNeighborLoader(PrefetchingLoader):
  """Distributed hetero link-prediction loader over the device mesh
  (the hetero arm of `dist_sampler.DistLinkNeighborLoader`; reference
  users reach it via ``DistLinkNeighborLoader`` on a hetero dataset,
  `distributed/dist_link_neighbor_loader.py:30-153`).

  Args:
    edge_label_index: ``(edge_type, (rows, cols))`` seed edges, each
      endpoint in its node type's id space.
    edge_label: optional integer labels (binary mode applies the
      reference's +1 shift).
    neg_sampling: ``'binary'`` / ``('triplet', amount)`` / None.
  """

  def __init__(self, dataset: DistHeteroDataset, num_neighbors,
               edge_label_index, edge_label=None, neg_sampling=None,
               batch_size: int = 1, shuffle: bool = False,
               drop_last: bool = False, mesh: Optional[Mesh] = None,
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0, input_space: str = 'old',
               exchange_slack='auto',
               exchange_layout: Optional[str] = None,
               prefetch: int = 0):
    from ..loader.node_loader import SeedBatcher
    from ..sampler.base import NegativeSampling
    self.prefetch = int(prefetch)
    from .dist_sampler import pack_link_seeds
    input_type, pairs = edge_label_index
    self.input_type = tuple(input_type)
    # cast ONCE at construction: validates the mode up front and keeps
    # the +1 label shift in lockstep with the sampler's parsing
    ns = (NegativeSampling.cast(neg_sampling)
          if neg_sampling is not None else None)
    self.neg_sampling = ns
    from .dist_sampler import DEFAULT_EXCHANGE_SLACK, AdaptiveSlack
    slack = resolve_exchange_slack(exchange_slack, shuffle)
    self.sampler = DistHeteroNeighborSampler(
        dataset, num_neighbors, mesh=mesh, with_edge=with_edge,
        collect_features=collect_features, seed=seed,
        exchange_slack=(DEFAULT_EXCHANGE_SLACK if slack == 'adaptive'
                        else slack),
        exchange_layout=exchange_layout)
    self._adaptive = (AdaptiveSlack(self.sampler)
                      if slack == 'adaptive' else None)
    rows, cols, colsarr = pack_link_seeds(
        pairs, edge_label, ns.mode if ns is not None else None)
    s_t, _, d_t = self.input_type
    if input_space == 'old':
      if s_t in dataset.old2new:
        colsarr[0] = dataset.old2new[s_t][rows]
      if d_t in dataset.old2new:
        colsarr[1] = dataset.old2new[d_t][cols]
    self.num_parts = dataset.num_partitions
    self.batch_size = int(batch_size)
    self._batcher = SeedBatcher(np.stack(colsarr, axis=1),
                                batch_size * self.num_parts, shuffle,
                                drop_last, seed)

  def __len__(self):
    return len(self._batcher)

  def _produce(self, seed_iter):
    from ..loader.transform import HeteroBatch
    flat = next(seed_iter)
    pairs = flat.reshape(self.num_parts, self.batch_size, -1)
    out = self.sampler.sample_from_edges(self.input_type, pairs,
                                         neg_sampling=self.neg_sampling)
    ei = {et: jnp.stack([out['row'][et], out['col'][et]], axis=1)
          for et in out['row']}
    em = {et: out['row'][et] >= 0 for et in out['row']}
    md = dict(out['metadata'])
    md['input_type'] = self.input_type
    if out['edge']:
      md['edge_dict'] = out['edge']
    return HeteroBatch(
        x_dict=out['x'], y_dict=out['y'], edge_index_dict=ei,
        edge_attr_dict=dict(out.get('ef') or {}), node_dict=out['node'],
        node_mask_dict={nt: v >= 0 for nt, v in out['node'].items()},
        edge_mask_dict=em,
        batch_dict={self.input_type[0]: out['batch']},
        batch_size=self.batch_size, metadata=md)
