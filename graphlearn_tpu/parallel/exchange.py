"""Pluggable all-to-all exchange layouts for the ICI data plane.

Every distributed engine in this package moves ids (and feature/reply
payloads) through the same request/reply pattern: bucket ids by owner
partition, ship buckets to owners, compute locally, ship replies back,
stitch into request order.  The r5 scale envelope showed the naive
uniform ``[P, C]`` bucketing blowing up at scale: the per-destination
capacity ``C`` is floor-bounded (`MIN_EXCHANGE_CAP`, worst-case skew),
so send slots grow as ``P * C`` while the real traffic stays ~the
frontier size — 81.5% padding waste at P=16 and 96.9% at P=64.

This module makes the layout a pluggable choice behind one API
(`capacity_spec` + `plan_exchange`), with three selectable layouts:

``dense``
    The original layout: ``[P, C]`` send buffer, one
    ``jax.lax.all_to_all`` each way, per-destination capacity
    ``max(ceil(n/P * slack), MIN_EXCHANGE_CAP)``.  Zero-risk default
    for small meshes; the floor is paid P times.

``compact``
    Tight per-destination base (``ceil(n/P * slack)``, NO floor) plus
    one lane-aligned globally-shared overflow pool: ids past their
    owner's base capacity ride a compact ``[V]`` buffer that is
    all-gathered, so skew headroom is paid ONCE per exchange instead
    of once per destination.  When the balanced share is tiny
    (``n/P * slack < POOL_ONLY_MAX_SHARE``) the base collapses to the
    pool alone — for frontiers much smaller than the mesh,
    replicating the whole (tiny) request vector costs less than any
    per-destination layout.  This is the GNNSampler / PyTorch-Direct
    lesson applied to the ICI plane: align layout to the transfer
    granularity of the hardware, not to per-logical-bucket bounds.

``hier``
    Two-stage hierarchical routing over a ``[rows, cols]`` factoring
    of the mesh (``rows * cols == P``, both ~sqrt(P)): stage 1 routes
    each id to its owner's COLUMN (an all_to_all within each mesh
    row), stage 2 routes within the column to the owner's row.  The
    per-destination floor is paid ``rows + cols`` ~ ``2 * sqrt(P)``
    times instead of ``P`` times, and every collective has ~sqrt(P)
    participants (bounded rendezvous at large P).  Stage-2 drops are
    shipped back to the requester as a delivered bit so capacity
    overflow is never silent.

``ragged``
    ``jax.lax.ragged_all_to_all`` (newer JAX, TPU): per-destination
    send sizes are runtime values, so there is no capacity waste at
    all.  Version-gated at import time (`HAVE_RAGGED`); on jax 0.4.37
    or CPU `resolve_layout` falls back to ``compact``.

Selection: pass ``exchange_layout=`` to the samplers/loaders, or set
``GLT_EXCHANGE_LAYOUT`` (wins over the built-in ``'auto'`` rule, loses
to an explicit per-sampler layout).  ``'auto'`` keeps ``dense`` below
`AUTO_COMPACT_MIN_PARTS` devices (bit-identical with the pre-layout
engines) and switches to ``compact`` at P >= 16 where the floor waste
dominates.

Capacity knobs, all tuned by `dist_sampler.AdaptiveSlack` through the
single slack ladder: the per-destination base multiplier (``slack``),
the global overflow budget (``POOL_FRAC`` of the request width, env
``GLT_EXCHANGE_POOL_FRAC``), and the per-stage capacities of the
hierarchical layout (slack times the per-stage balanced share, floored
at `MIN_STAGE_CAP`).

Accounting contract (the telemetry triple every plan exposes):
``offered`` counts valid ids entering each wire stage (an id crossing
both hierarchical stages counts twice — the triple measures per-wire
fill, i.e. the fraction of exchanged slots carrying payload);
``dropped`` counts valid ids that lost their slot; ``slots`` is the
static send-buffer footprint.  Invariant: ``offered - dropped <=
slots`` (what was actually sent fits in the slots).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.padding import INVALID_ID, round_up

#: per-destination capacity floor of the DENSE layout: exchanges this
#: small gain nothing from capping (the buffer is a few KB) but would
#: drop ids on ANY ownership skew, so they stay exact.  This floor —
#: paid per destination, P times — is exactly the waste the compacted
#: and hierarchical layouts exist to reclaim.
MIN_EXCHANGE_CAP = 64

#: hierarchical per-STAGE bucket floor (paid ~2*sqrt(P) times).
MIN_STAGE_CAP = 16

#: minimum compacted overflow-pool width (absolute skew headroom that
#: the tight per-destination base no longer carries).
MIN_POOL = 32

#: compacted overflow pool as a fraction of the request width — the
#: GLOBAL skew budget, paid once per exchange instead of once per
#: destination.  Default; ``GLT_EXCHANGE_POOL_FRAC`` overrides at
#: capacity-planning time (read per call, like the layout env knob,
#: so late exports and monkeypatched tests take effect).
POOL_FRAC = 0.25


def _pool_frac() -> float:
  try:
    return float(os.environ.get('GLT_EXCHANGE_POOL_FRAC', POOL_FRAC))
  except ValueError:
    return POOL_FRAC

#: below this per-destination share (``n/P * slack``) the compacted
#: base is dropped entirely and the whole request rides the pool: a
#: frontier much smaller than the mesh is cheaper to replicate than to
#: bucket (the all_gather is ~n elements; any per-destination layout
#: pays >= P slots).
POOL_ONLY_MAX_SHARE = 2.0

#: ``'auto'`` switches dense -> compact at this mesh size: below it
#: the dense floor waste is bounded (P * MIN_EXCHANGE_CAP is small)
#: and bit-compatibility with the original engines wins.
AUTO_COMPACT_MIN_PARTS = 16

#: hierarchical needs a non-trivial factoring.
HIER_MIN_PARTS = 4

LAYOUTS = ('dense', 'compact', 'hier', 'ragged')

#: import-time version gate for the ragged backend (jax >= 0.5-era on
#: TPU).  jax 0.4.37 / CPU: False, and 'ragged' resolves to 'compact'.
HAVE_RAGGED = hasattr(jax.lax, 'ragged_all_to_all')

_ENV_LAYOUT = 'GLT_EXCHANGE_LAYOUT'


def resolve_layout(layout: Optional[str], num_parts: int) -> str:
  """Resolve a requested layout name to the one that will run.

  ``None``/``'auto'`` consults ``GLT_EXCHANGE_LAYOUT`` then the
  built-in rule (dense below `AUTO_COMPACT_MIN_PARTS`, compact at or
  above).  ``'ragged'`` falls back to ``'compact'`` when this jax has
  no `ragged_all_to_all` (the import-time gate); ``'hier'`` falls back
  to ``'dense'`` when the mesh is too small to factor.
  """
  name = layout or 'auto'
  if name == 'auto':
    name = os.environ.get(_ENV_LAYOUT, '') or 'auto'
  if name == 'auto':
    name = ('compact' if num_parts >= AUTO_COMPACT_MIN_PARTS
            else 'dense')
  if name not in LAYOUTS:
    raise ValueError(
        f'unknown exchange layout {name!r}; expected one of '
        f"{LAYOUTS + ('auto',)}")
  if name == 'ragged' and not HAVE_RAGGED:
    name = 'compact'
  if name == 'hier':
    if num_parts < HIER_MIN_PARTS:
      name = 'dense'
    elif mesh_factors(num_parts)[1] < 2:
      name = 'compact'            # prime P: no useful factoring
  return name


def mesh_factors(num_parts: int) -> Tuple[int, int]:
  """``(rows, cols)`` with ``rows * cols == num_parts``, both as close
  to sqrt(P) as the factorization allows (rows >= cols)."""
  c = max(int(np.floor(np.sqrt(num_parts))), 1)
  while num_parts % c:
    c -= 1
  return num_parts // c, c


@dataclasses.dataclass(frozen=True)
class ExchangeSpec:
  """Static capacity plan for one bucketed exchange (trace-time
  constant — part of the compiled program's shape)."""
  layout: str
  num_parts: int
  #: per-destination width: dense cap / compacted base (0 = pool-only).
  capacity: int = 0
  #: compacted global overflow budget (send-slot width of the pool).
  pool: int = 0
  #: hierarchical mesh factoring and per-stage bucket widths.
  rows: int = 0
  cols: int = 0
  stage_caps: Tuple[int, int] = (0, 0)

  @property
  def slots(self) -> int:
    """Static send-buffer footprint (the ``slots`` telemetry term)."""
    if self.layout == 'hier':
      return self.cols * self.stage_caps[0] + self.rows * self.stage_caps[1]
    if self.layout == 'compact':
      return self.num_parts * self.capacity + self.pool
    return self.num_parts * self.capacity


def capacity_spec(n: int, num_parts: int, slack: Optional[float],
                  layout: Optional[str] = None,
                  floor: int = MIN_EXCHANGE_CAP,
                  dest_cap: Optional[int] = None,
                  traffic_cap: Optional[int] = None
                  ) -> Optional[ExchangeSpec]:
  """Plan the static capacities of one ``n``-id exchange.

  ``slack`` is the per-destination capacity multiplier over the
  balanced share ``n / P`` (the `AdaptiveSlack` ladder value); None
  means EXACT — per-destination width ``n`` under the dense layout,
  which can never drop an id (callers needing exactness — walkers,
  induced subgraphs — rely on this returning None unchanged).

  ``dest_cap`` / ``traffic_cap`` (ISSUE 20, exchange co-design): the
  `EwmaCapacityModel`'s measured per-step demand — ``dest_cap``
  replaces the UNIFORM balanced share ``n / P`` with the measured
  busiest-destination id count, and ``traffic_cap`` bounds the total
  per-step wire traffic so the compact layout's overflow pool shrinks
  when locality/replication skews traffic local.  Both are quantized
  by the model (powers of two) so recompiles stay logarithmic.  None
  keeps the uniform plan bit-for-bit.  The hierarchical layout keeps
  uniform stage shares (its buckets aggregate destinations, so a
  per-destination measurement does not map onto its caps).
  """
  if slack is None:
    return None
  n = int(n)
  num_parts = int(num_parts)
  name = resolve_layout(layout, num_parts)
  lam = n / num_parts * float(slack)
  if dest_cap is not None and name != 'hier':
    lam = min(n, int(dest_cap)) * float(slack)
  if name == 'hier':
    rows, cols = mesh_factors(num_parts)
    # per-stage caps: slack times the stage's balanced share PLUS an
    # additive fluctuation margin (max of the stage floor and 25% of
    # the share) — a pure multiplier leaves no absolute headroom at
    # small shares, where Poisson noise routinely exceeds slack * lam
    lam1 = n / cols
    lam2 = n / rows
    c1 = int(np.ceil(lam1 * float(slack))) + max(
        MIN_STAGE_CAP, int(np.ceil(lam1 / 4)))
    # stage-2 buckets are single partitions (full ownership skew where
    # stage 1 averaged over a column) — extra 1.5x skew headroom
    c2 = int(np.ceil(lam2 * float(slack) * 1.5)) + max(
        MIN_STAGE_CAP, int(np.ceil(lam2 / 4)))
    c1 = int(round_up(min(c1, n), 4))
    c2 = int(round_up(min(c2, n), 4))
    from ..telemetry.spans import span
    with span('exchange.stage', layout='hier', rows=rows, cols=cols,
              stage1_cap=c1, stage2_cap=c2, n=n):
      pass          # build-time marker: one per compiled stage pair
    return ExchangeSpec('hier', num_parts, rows=rows, cols=cols,
                        stage_caps=(c1, c2))
  dense = ExchangeSpec(
      'dense', num_parts,
      capacity=int(round_up(min(n, max(int(np.ceil(lam)),
                                       int(floor))), 8)))
  if name in ('compact', 'ragged'):
    # ('ragged' resolved but unsupported specs never reach here: the
    # resolve above already mapped it to 'compact' when gated)
    if name == 'ragged':
      budget = int(round_up(max(n, 1), 8))
      return ExchangeSpec('ragged', num_parts, capacity=budget,
                          pool=2 * budget)
    if lam < POOL_ONLY_MAX_SHARE:
      # pool-only: the whole request vector is the pool — exact (every
      # id fits by construction), slots == round_up(n, 8)
      return ExchangeSpec('compact', num_parts, capacity=0,
                          pool=int(round_up(max(n, 1), 8)))
    base = int(np.ceil(lam))
    # the pool absorbs GLOBAL skew: its budget scales with the ids
    # that actually ride the wire per step (measured `traffic_cap`
    # when the EWMA model supplies one) rather than the request width
    wire = n if traffic_cap is None else min(n, int(traffic_cap))
    pool = int(round_up(
        min(n, max(MIN_POOL, int(np.ceil(wire * _pool_frac())))), 8))
    compact = ExchangeSpec('compact', num_parts,
                           capacity=min(base, n), pool=pool)
    # compact's whole win is reclaiming the dense FLOOR padding; when
    # the share is large enough that the floor never bound, the tight
    # base equals the dense cap and the pool is pure overhead — keep
    # the dense program (also skew-safer: floor >= base + pool/P)
    return compact if compact.slots < dense.slots else dense
  return dense


def dest_histogram(ids: jax.Array, owner_fn: Callable,
                   num_parts: int, valid=None) -> jax.Array:
  """[P] int32 count of valid ids per destination partition — the
  attribution row one device contributes to the fleet's P×P src→dst
  traffic matrix (`ExchangeTelemetry.attribution_matrices`).

  Keyed by ``owner_fn`` — callers pass the `PartitionBook` RANGE owner
  (`partition_book.range_owner_fn`), so a row means "ids in range r"
  even after an adopted book remaps which physical device serves r.
  Traceable (runs inside the compiled step); invalid ids route to a
  dropped overflow bin, never a partition.
  """
  if valid is None:
    valid = ids >= 0
  owner = jnp.where(valid, owner_fn(ids).astype(jnp.int32),
                    jnp.int32(num_parts))
  owner = jnp.clip(owner, 0, num_parts)
  return jax.ops.segment_sum(
      jnp.ones(ids.shape, jnp.int32), owner,
      num_segments=num_parts + 1)[:num_parts]


_ENV_EWMA = 'GLT_EXCHANGE_EWMA'


def ewma_enabled(flag=None) -> bool:
  """``GLT_EXCHANGE_EWMA=1`` turns on measured (EWMA) capacity sizing;
  default OFF — the uniform-share plans stay byte-identical."""
  if flag is not None:
    return bool(flag)
  return os.environ.get(_ENV_EWMA, '').lower() in ('1', 'true', 'on')


def _quantize_pow2(x: float) -> int:
  """Next power of two >= x (>= 1): the capacity ladder that bounds
  recompiles to log2 steps over any traffic trajectory."""
  v = max(int(np.ceil(x)), 1)
  return int(1 << (v - 1).bit_length())


class EwmaCapacityModel:
  """EWMA of measured exchange demand -> quantized capacity caps
  (ISSUE 20 exchange co-design).

  Fed per-channel (``'frontier'`` / ``'feature'``) attribution-matrix
  DELTAS at epoch boundaries: the busiest (src, dst) cell per step
  becomes the per-destination demand (replacing the uniform ``n / P``
  share in `capacity_spec`), and the busiest src row per step bounds
  total wire traffic (shrinking the compact pool).  Both are EWMA'd
  (``GLT_EXCHANGE_EWMA_ALPHA``), padded by a headroom multiplier
  (``GLT_EXCHANGE_EWMA_HEADROOM``) and quantized to powers of two so a
  capacity change — and therefore a recompile — happens at most
  logarithmically often.  `AdaptiveSlack` keeps guarding drops on top:
  an under-measured epoch that drops ids widens the slack rung the
  usual way.
  """

  CHANNELS = ('frontier', 'feature')

  def __init__(self, num_parts: int, alpha: Optional[float] = None,
               headroom: Optional[float] = None):
    def _f(env: str, default: float) -> float:
      try:
        return float(os.environ.get(env, default))
      except ValueError:
        return default
    self.num_parts = int(num_parts)
    self.alpha = (_f('GLT_EXCHANGE_EWMA_ALPHA', 0.5)
                  if alpha is None else float(alpha))
    self.headroom = (_f('GLT_EXCHANGE_EWMA_HEADROOM', 1.3)
                     if headroom is None else float(headroom))
    self._dest: dict = {}
    self._traffic: dict = {}
    self._caps: dict = {}

  def observe(self, channel: str, matrix_delta, steps: int) -> bool:
    """Fold one epoch's [P, P] id-count matrix delta (``steps`` step
    dispatches) into the model.  Returns True when the QUANTIZED caps
    moved — the caller must recompile (clear its step cache)."""
    if steps <= 0:
      return False
    m = np.asarray(matrix_delta, np.float64)
    if m.size == 0 or m.sum() <= 0:
      return False
    dest = float(m.max()) / steps
    traffic = float(m.sum(axis=1).max()) / steps
    a = self.alpha
    self._dest[channel] = (a * dest + (1 - a) * self._dest[channel]
                           if channel in self._dest else dest)
    self._traffic[channel] = (
        a * traffic + (1 - a) * self._traffic[channel]
        if channel in self._traffic else traffic)
    caps = (_quantize_pow2(self._dest[channel] * self.headroom),
            _quantize_pow2(self._traffic[channel] * self.headroom))
    changed = self._caps.get(channel) != caps
    self._caps[channel] = caps
    return changed

  def caps(self, channel: str):
    """``(dest_cap, traffic_cap)`` for `capacity_spec`, or
    ``(None, None)`` before the first observation (uniform plan)."""
    return self._caps.get(channel, (None, None))

  def state_dict(self) -> dict:
    return {f'{c}_{k}': float(d[c])
            for k, d in (('dest', self._dest), ('traffic', self._traffic))
            for c in d}

  def load_state_dict(self, state: dict) -> None:
    for key, val in state.items():
      c, k = key.rsplit('_', 1)
      (self._dest if k == 'dest' else self._traffic)[c] = float(
          np.asarray(val))
    for c in set(self._dest) & set(self._traffic):
      self._caps[c] = (
          _quantize_pow2(self._dest[c] * self.headroom),
          _quantize_pow2(self._traffic[c] * self.headroom))


def _bcast(mask: jax.Array, values: jax.Array) -> jax.Array:
  """Broadcast a [F] mask over the trailing dims of [F, ...]."""
  return mask.reshape(mask.shape + (1,) * (values.ndim - 1))


def _row_groups(rows: int, cols: int):
  return [[r * cols + c for c in range(cols)] for r in range(rows)]


def _col_groups(rows: int, cols: int):
  return [[r * cols + c for r in range(rows)] for c in range(cols)]


class _SubExchange:
  """One bucketed all_to_all over ``nbuckets`` destinations — the
  shared machinery of the dense layout and each hierarchical stage
  (``groups`` routes the collective within mesh sub-groups)."""

  def __init__(self, ids, owner, nbuckets: int, axis: str,
               capacity: Optional[int], groups=None, payload=None):
    from .dist_sampler import bucket_by_owner, bucket_with_payload
    self.axis = axis
    self.nbuckets = nbuckets
    self.groups = groups
    if payload is None:
      send, self.slot_p, self.slot_j = bucket_by_owner(
          ids, owner, nbuckets, None, capacity)
      recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=True,
                                axis_index_groups=groups)
    else:
      send, send_pl, self.slot_p, self.slot_j = bucket_with_payload(
          ids, payload, owner, nbuckets, None, capacity)
      c = send.shape[1]
      # ONE fused [G, 2C] exchange for ids + payload (these buffers
      # are small and latency-bound on ICI)
      both = jax.lax.all_to_all(
          jnp.concatenate([send, send_pl], axis=1), axis, 0, 0,
          tiled=True, axis_index_groups=groups)
      recv, recv_pl = both[:, :c], both[:, c:]
      self.recv_payload = recv_pl.reshape(-1)
    self.cap = send.shape[1]
    self.recv = recv.reshape(-1)                  # [nbuckets * cap]
    self.kept = self.slot_j >= 0
    valid = ids >= 0
    self.offered = jnp.sum(valid.astype(jnp.int32))
    self.dropped = jnp.sum((valid & ~self.kept).astype(jnp.int32))

  def reply(self, values, fill):
    """[nbuckets * cap, ...] owner-side values -> [F, ...] in request
    order; un-kept positions get ``fill``."""
    v = values.reshape((self.nbuckets, self.cap) + values.shape[1:])
    back = jax.lax.all_to_all(v, self.axis, 0, 0, tiled=True,
                              axis_index_groups=self.groups)
    out = back[self.slot_p, jnp.where(self.kept, self.slot_j, 0)]
    return jnp.where(_bcast(self.kept, out), out,
                     jnp.asarray(fill, out.dtype))


class _DensePlan:
  """The original ``[P, C]`` layout behind the plan API."""

  layout = 'dense'

  def __init__(self, ids, owner_fn, num_parts: int, axis: str,
               capacity: Optional[int], payload=None):
    owner = owner_fn(ids).astype(jnp.int32)
    self._sub = _SubExchange(ids, owner, num_parts, axis, capacity,
                             payload=payload)
    self.recv = self._sub.recv
    if payload is not None:
      self.recv_payload = self._sub.recv_payload
    self.kept = self._sub.kept
    self.delivered = self._sub.kept
    #: source device of each recv row (``recv`` is the flattened
    #: [P_src, cap] buffer) — the per-requester GNS mask attribution
    #: (ISSUE 15): owners bias each request by what ITS requester can
    #: serve locally, not by the union of every device's cache
    self.requester_of_recv = jnp.repeat(
        jnp.arange(num_parts, dtype=jnp.int32), self._sub.cap)
    self.stats = (self._sub.offered, self._sub.dropped,
                  jnp.int32(num_parts * self._sub.cap))

  def reply(self, values, fill=0):
    return self._sub.reply(values, fill)


class _CompactPlan:
  """Tight per-destination base + globally-shared overflow pool.

  Base: ``[P, cap]`` all_to_all (cap may be 0 — pool-only mode).
  Pool: ``[V]`` all_gather — every owner sees every device's overflow
  ids, answers the ones it owns; replies ride a ``[P, V]`` all_to_all
  and the requester selects each id's reply row by its owner.  The
  pool is the skew budget paid ONCE per exchange.
  """

  layout = 'compact'

  def __init__(self, ids, owner_fn, num_parts: int, axis: str,
               spec: ExchangeSpec, payload=None):
    f = ids.shape[0]
    p = num_parts
    cap = int(spec.capacity)
    v = int(spec.pool)
    self._p, self._cap, self._pool, self._axis = p, cap, v, axis
    valid = ids >= 0
    owner = jnp.where(valid, owner_fn(ids).astype(jnp.int32), p)
    perm = jnp.argsort(owner, stable=True)
    owner_s = owner[perm]
    ids_s = ids[perm]
    counts = jax.ops.segment_sum(jnp.ones((f,), jnp.int32), owner_s,
                                 num_segments=p + 1)
    offsets = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(f, dtype=jnp.int32) - offsets[owner_s]
    real = owner_s < p
    in_base = real & (rank < cap)
    want_pool = real & ~in_base
    pool_rank = jnp.cumsum(want_pool.astype(jnp.int32)) - 1
    in_pool = want_pool & (pool_rank < v)

    def scatter_pool(vals, dtype):
      buf = jnp.full((v,), INVALID_ID, dtype)
      return buf.at[jnp.where(in_pool, pool_rank, v)].set(vals,
                                                          mode='drop')

    def scatter_base(vals, dtype):
      buf = jnp.full((p, max(cap, 1)), INVALID_ID, dtype)
      return buf.at[jnp.where(in_base, owner_s, p),
                    jnp.where(in_base, rank, 0)].set(vals, mode='drop')

    pool_send = scatter_pool(ids_s, ids.dtype)
    sends = [pool_send]
    if payload is not None:
      payload_s = payload[perm]
      sends.append(scatter_pool(payload_s, payload.dtype))
    pool_all = jax.lax.all_gather(
        jnp.stack(sends) if len(sends) > 1 else sends[0][None],
        axis, tiled=False)                        # [P, 1|2, V]
    if cap > 0:
      base_send = scatter_base(ids_s, ids.dtype)
      if payload is not None:
        base_pl = scatter_base(payload_s, payload.dtype)
        both = jax.lax.all_to_all(
            jnp.concatenate([base_send, base_pl], axis=1), axis, 0, 0,
            tiled=True)
        base_recv, base_recv_pl = both[:, :cap], both[:, cap:]
      else:
        base_recv = jax.lax.all_to_all(base_send, axis, 0, 0,
                                       tiled=True)
      self.recv = jnp.concatenate([base_recv.reshape(-1),
                                   pool_all[:, 0].reshape(-1)])
      if payload is not None:
        self.recv_payload = jnp.concatenate(
            [base_recv_pl.reshape(-1), pool_all[:, 1].reshape(-1)])
    else:
      self.recv = pool_all[:, 0].reshape(-1)      # [P * V]
      if payload is not None:
        self.recv_payload = pool_all[:, 1].reshape(-1)

    # requester attribution (per-requester GNS masks, ISSUE 15): base
    # recv is the flattened [P_src, cap] buffer; the pool is an
    # all_gather whose row p holds device p's overflow ids verbatim
    src = jnp.arange(p, dtype=jnp.int32)
    if cap > 0:
      self.requester_of_recv = jnp.concatenate(
          [jnp.repeat(src, cap), jnp.repeat(src, v)])
    else:
      self.requester_of_recv = jnp.repeat(src, v)

    # inverse maps back to request order
    inv = lambda x, fill: jnp.full((f,), fill, jnp.int32).at[perm].set(x)
    self._owner = inv(jnp.where(real, owner_s, 0), 0)
    self._slot_j = inv(jnp.where(in_base, rank, -1), -1)
    self._pool_slot = inv(jnp.where(in_pool, pool_rank, -1), -1)
    self.kept = (self._slot_j >= 0) | (self._pool_slot >= 0)
    self.delivered = self.kept
    offered = jnp.sum(valid.astype(jnp.int32))
    dropped = jnp.sum((valid & ~self.kept).astype(jnp.int32))
    self.stats = (offered, dropped, jnp.int32(p * cap + v))

  def reply(self, values, fill=0):
    p, cap, v = self._p, self._cap, self._pool
    base_n = p * cap
    pool_part = values[base_n:].reshape((p, v) + values.shape[1:])
    # row o of the replied stack = owner o's answers for MY pool ids
    pool_back = jax.lax.all_to_all(pool_part, self._axis, 0, 0,
                                   tiled=True)
    out_pool = pool_back[self._owner,
                         jnp.where(self._pool_slot >= 0,
                                   self._pool_slot, 0)]
    fillv = jnp.asarray(fill, out_pool.dtype)
    out = jnp.where(_bcast(self._pool_slot >= 0, out_pool), out_pool,
                    fillv)
    if cap > 0:
      base_part = values[:base_n].reshape((p, cap) + values.shape[1:])
      base_back = jax.lax.all_to_all(base_part, self._axis, 0, 0,
                                     tiled=True)
      out_base = base_back[self._owner,
                           jnp.where(self._slot_j >= 0,
                                     self._slot_j, 0)]
      out = jnp.where(_bcast(self._slot_j >= 0, out_base), out_base,
                      out)
    return out


class _HierPlan:
  """Two-stage hierarchical exchange over a [rows, cols] mesh
  factoring: stage 1 within mesh rows (bucket by owner COLUMN), stage
  2 within mesh columns (bucket by owner ROW).  Owners are recomputed
  from the ids at the intermediate device, so no routing metadata
  travels.  Stage-2 drops are shipped back as a delivered bit (one
  int8 reply through stage 1) — multi-stage overflow is never silent.
  """

  layout = 'hier'

  def __init__(self, ids, owner_fn, num_parts: int, axis: str,
               spec: ExchangeSpec, payload=None):
    rows, cols = spec.rows, spec.cols
    c1, c2 = spec.stage_caps
    self._owner_fn = owner_fn
    owner = owner_fn(ids).astype(jnp.int32)
    st1 = _SubExchange(ids, owner % cols, cols, axis, c1,
                       groups=_row_groups(rows, cols), payload=payload)
    ids1 = st1.recv                                  # [cols * c1]
    owner1 = owner_fn(ids1).astype(jnp.int32)
    st2 = _SubExchange(ids1, owner1 // cols, rows, axis, c2,
                       groups=_col_groups(rows, cols),
                       payload=(st1.recv_payload
                                if payload is not None else None))
    self.recv = st2.recv                             # [rows * c2]
    if payload is not None:
      self.recv_payload = st2.recv_payload
    self._st1, self._st2 = st1, st2
    self.kept = st1.kept
    # a kept id may still have been dropped at stage 2 — reply the
    # intermediate's kept bits back through stage 1 (one int8 [cols,
    # c1] exchange) so the requester can mask undelivered results
    bits = st1.reply(st2.kept.astype(jnp.int8), fill=0)
    self.delivered = st1.kept & (bits > 0)
    offered = st1.offered + st2.offered
    dropped = st1.dropped + st2.dropped
    self.stats = (offered, dropped,
                  jnp.int32(cols * c1 + rows * c2))

  def reply(self, values, fill=0):
    mid = self._st2.reply(values, fill)              # [cols * c1, ...]
    out = self._st1.reply(mid, fill)                 # [F, ...]
    return jnp.where(_bcast(self.delivered, out), out,
                     jnp.asarray(fill, out.dtype))


class _RaggedPlan:  # pragma: no cover — needs jax.lax.ragged_all_to_all
  """`jax.lax.ragged_all_to_all` backend: runtime per-destination send
  sizes, no capacity waste.  Reachable only when `HAVE_RAGGED` (newer
  JAX on TPU) — on jax 0.4.37/CPU `resolve_layout` already fell back
  to 'compact', so this class is validated on real slices only.

  KNOWN LIMIT (pre-hardware-validation): the receive buffer is a
  static 2x the send budget, but total arrivals at one device are
  bounded only by ``P * n`` — extreme ownership skew can exceed the
  buffer, and `ragged_all_to_all`'s behavior past it is undefined
  while ``stats`` still reads dropped=0.  Before promoting this
  backend on a real slice, gate it on measured skew (or clamp
  ``recv_sizes`` against remaining space and count the clamp as
  drops); the dense-family layouts bound this by construction.
  """

  layout = 'ragged'

  def __init__(self, ids, owner_fn, num_parts: int, axis: str,
               spec: ExchangeSpec, payload=None):
    if payload is not None:
      raise NotImplementedError(
          'ragged exchange does not carry forward payloads yet; use '
          'compact/dense for paired exchanges')
    f = ids.shape[0]
    p = num_parts
    budget = int(spec.capacity)            # compacted send budget
    out_budget = int(spec.pool)            # receive budget (2x send)
    valid = ids >= 0
    owner = jnp.where(valid, owner_fn(ids).astype(jnp.int32), p)
    perm = jnp.argsort(owner, stable=True)
    owner_s = owner[perm]
    ids_s = jnp.where(owner_s < p, ids[perm], INVALID_ID)
    counts = jax.ops.segment_sum(jnp.ones((f,), jnp.int32), owner_s,
                                 num_segments=p + 1)[:p]
    send_sizes = counts
    input_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    recv_sizes = jax.lax.all_to_all(send_sizes[:, None], axis, 0, 0,
                                    tiled=True)[:, 0]
    output_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(recv_sizes)[:-1]])
    operand = jnp.full((budget,), INVALID_ID, ids.dtype)
    operand = operand.at[jnp.arange(f)].set(ids_s, mode='drop')
    out_buf = jnp.full((out_budget,), INVALID_ID, ids.dtype)
    self.recv = jax.lax.ragged_all_to_all(
        operand, out_buf, input_offsets, send_sizes,
        output_offsets, recv_sizes, axis_name=axis)
    self._perm = perm
    self._axis = axis
    self._io = (input_offsets, send_sizes, output_offsets, recv_sizes)
    self._rank = jnp.arange(f, dtype=jnp.int32) - input_offsets[
        jnp.clip(owner_s, 0, p - 1)]
    self.kept = valid
    self.delivered = valid
    self.stats = (jnp.sum(valid.astype(jnp.int32)), jnp.int32(0),
                  jnp.int32(budget))

  def reply(self, values, fill=0):
    input_offsets, send_sizes, output_offsets, recv_sizes = self._io
    out = jnp.full(self._perm.shape + values.shape[1:],
                   jnp.asarray(fill, values.dtype), values.dtype)
    # roles swap: the owner's received layout becomes the send layout
    back = jax.lax.ragged_all_to_all(
        values, out, output_offsets, recv_sizes, input_offsets,
        send_sizes, axis_name=self._axis)
    # back is in compacted (sorted-by-owner) order; undo the sort
    inv = jnp.zeros_like(self._perm).at[self._perm].set(
        jnp.arange(self._perm.shape[0]))
    return back[inv]


def plan_exchange(ids: jax.Array, owner_fn: Callable, num_parts: int,
                  axis: str, spec=None, payload=None):
  """Build the exchange plan for one request vector.

  Args:
    ids: [F] int ids (-1 padded invalid).
    owner_fn: maps an id array to owner partition indices (the range
      ``searchsorted`` or the mod rule) — called again at the
      hierarchical intermediate, so it must be position-independent.
    spec: None (exact dense), a legacy int per-destination cap, or an
      `ExchangeSpec` from `capacity_spec`.
    payload: optional [F] companion array delivered alongside each id
      (the (row, col) pair shipping of the distributed edge test).

  Returns a plan with ``recv`` (flat ids this device must answer),
  ``recv_payload`` (when ``payload`` given), ``kept``/``delivered``
  [F] masks, ``stats`` (offered, dropped, slots) and
  ``reply(values, fill)`` mapping owner-side [R, ...] results back to
  request order.
  """
  if spec is None or isinstance(spec, (int, np.integer)):
    return _DensePlan(ids, owner_fn, num_parts, axis,
                      None if spec is None else int(spec),
                      payload=payload)
  if spec.layout == 'dense':
    return _DensePlan(ids, owner_fn, num_parts, axis, spec.capacity,
                      payload=payload)
  if spec.layout == 'compact':
    return _CompactPlan(ids, owner_fn, num_parts, axis, spec,
                        payload=payload)
  if spec.layout == 'hier':
    return _HierPlan(ids, owner_fn, num_parts, axis, spec,
                     payload=payload)
  if spec.layout == 'ragged':  # pragma: no cover — gated, TPU-only
    if payload is not None:
      # the ragged backend has no forward-payload support yet: paired
      # exchanges (edge-existence tests shipping (row, col)) degrade
      # to the exact pool-only compact plan instead of crashing the
      # step trace — same spirit as the import-time gate
      fb = ExchangeSpec('compact', num_parts, capacity=0,
                        pool=int(round_up(max(ids.shape[0], 1), 8)))
      return _CompactPlan(ids, owner_fn, num_parts, axis, fb,
                          payload=payload)
    return _RaggedPlan(ids, owner_fn, num_parts, axis, spec)
  raise ValueError(f'unknown layout {spec.layout!r}')


# ---------------------------------------------------------------------------
# host-side simulation (property tests at any P without a device mesh)

def simulate_assignment(ids: np.ndarray, owner: np.ndarray,
                        spec) -> dict:
  """Pure-numpy twin of the plan slot assignment: which ids keep a
  slot under ``spec``, and the (offered, dropped, slots) triple.
  Mirrors the traced bucketing exactly (stable sort by owner, rank
  against base capacity, overflow pool, per-stage hierarchical caps)
  so capacity properties can be tested at P=64 without 64 devices.
  """
  ids = np.asarray(ids)
  owner = np.asarray(owner)
  valid = ids >= 0
  offered = int(valid.sum())

  def bucket_kept(own, nbuckets, cap):
    own = np.where(valid_cur, own, nbuckets)
    order = np.argsort(own, kind='stable')
    own_s = own[order]
    rank = np.zeros(len(own), np.int64)
    counts = {}
    for pos, o in zip(order, own_s):
      rank[pos] = counts.get(o, 0)
      counts[o] = counts.get(o, 0) + 1
    return (own < nbuckets) & (rank < cap), rank

  if spec is None:
    return {'kept': valid.copy(), 'offered': offered, 'dropped': 0,
            'slots': len(ids) * int(owner.max(initial=0) + 1)}
  if isinstance(spec, (int, np.integer)):
    num_parts = int(owner.max(initial=0) + 1)
    spec = ExchangeSpec('dense', num_parts, capacity=int(spec))
  p = spec.num_parts
  valid_cur = valid
  if spec.layout == 'dense':
    kept, _ = bucket_kept(owner, p, spec.capacity)
    kept &= valid
  elif spec.layout == 'compact':
    in_base, _ = bucket_kept(owner, p, spec.capacity)
    in_base &= valid
    want_pool = valid & ~in_base
    pool_rank = np.cumsum(want_pool) - 1
    kept = in_base | (want_pool & (pool_rank < spec.pool))
  elif spec.layout == 'hier':
    rows, cols = spec.rows, spec.cols
    c1, c2 = spec.stage_caps
    kept1, _ = bucket_kept(owner % cols, cols, c1)
    kept1 &= valid
    # stage 2 runs at the intermediate on the arrived ids; worst-case
    # host model: all of THIS device's kept ids land on one
    # intermediate with nothing else — per-row rank against c2
    valid_cur = kept1
    kept2, _ = bucket_kept(owner // cols, rows, c2)
    kept = kept1 & kept2
  else:
    kept = valid.copy()
  dropped = int((valid & ~kept).sum())
  return {'kept': kept, 'offered': offered, 'dropped': dropped,
          'slots': int(spec.slots)}
