"""Locality-aware mesh partitioning (ISSUE 20).

The mesh data plane's default placement is random: `from_full_graph`
deals nodes round-robin over a seeded permutation, so at P partitions
every hop pays the near-worst-case ``1 - 1/P`` cross-partition
exchange fraction (the PR 16 attribution plane measures 0.937 at
P=16).  This module closes the loop the repo already half-owns — the
signals (DecayedSketch hotness, per-(src,dst) attribution matrices)
and the actuator (PR 19 planned handoff) exist; what was missing is
the partitioner between them:

  * :func:`locality_partition` — a deterministic, seeded streaming
    partitioner (LDG/Fennel-style greedy: maximize same-partition
    neighbor affinity, discounted by a balance penalty, under a hard
    ``(1 + eps) * N / P`` capacity) emitting a ``node_pb`` that
    `build_dist_graph` relabels into contiguous ranges.  PartitionBook
    ranges stay FROZEN by contract — locality is achieved entirely by
    relabeling at dataset build, and the in-degree ordering WITHIN
    each range is preserved by `relabel_by_partition(hotness=...)`, so
    `hot_split_host` tiering composes unchanged.
  * :func:`rebalance_plan` / :func:`execute_rebalance` — the online
    arm: rank ranges by measured demand (sketch ``range_mass`` when
    supplied, else the attribution matrix's column mass), and migrate
    the hottest ranges of overloaded owners onto their top REQUESTER
    when that device is underloaded — each move a PR 19 fenced
    handoff, so the epoch completes with zero degraded batches.

Selection is env-gated (``GLT_PARTITIONER=range|locality``): unset or
``range`` keeps the historical placement byte-for-byte.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

#: partitioner identities the env knob / `from_full_graph` accept.
PARTITIONERS = ('range', 'locality')


def resolve_partitioner(partitioner=None) -> Union[str, np.ndarray,
                                                   Callable]:
  """Resolve the active partitioner identity: an explicit argument
  (name, precomputed ``node_pb`` array, or a callable
  ``(rows, cols, num_nodes, num_parts) -> node_pb``) wins; otherwise
  the ``GLT_PARTITIONER`` env knob; default ``'range'`` — the
  historical random-round-robin placement, byte-identical to HEAD."""
  if partitioner is None:
    partitioner = os.environ.get('GLT_PARTITIONER', 'range') or 'range'
  if isinstance(partitioner, str):
    if partitioner not in PARTITIONERS:
      raise ValueError(
          f'unknown partitioner {partitioner!r}: expected one of '
          f'{PARTITIONERS}, a node_pb array, or a callable')
    return partitioner
  if callable(partitioner):
    return partitioner
  return np.asarray(partitioner)


def _env_float(name: str, default: float) -> float:
  try:
    return float(os.environ.get(name, default))
  except ValueError:
    return default


def _env_int(name: str, default: int) -> int:
  try:
    return int(os.environ.get(name, default))
  except ValueError:
    return default


def edge_cut_frac(rows, cols, node_pb) -> float:
  """Fraction of edges whose endpoints live on different partitions —
  the quantity the streaming passes greedily minimize."""
  rows = np.asarray(rows)
  if not len(rows):
    return 0.0
  node_pb = np.asarray(node_pb)
  return float(np.mean(node_pb[rows] != node_pb[np.asarray(cols)]))


def _adjacency_csr(rows: np.ndarray, cols: np.ndarray,
                   num_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
  """Undirected adjacency CSR (both edge directions, self-loops
  dropped): the affinity structure the greedy stream scores against."""
  u = np.concatenate([rows, cols])
  v = np.concatenate([cols, rows])
  keep = u != v
  u, v = u[keep], v[keep]
  order = np.argsort(u, kind='stable')
  u, v = u[order], v[order]
  indptr = np.zeros(num_nodes + 1, np.int64)
  np.cumsum(np.bincount(u, minlength=num_nodes), out=indptr[1:])
  return indptr, v.astype(np.int64)


def locality_partition(rows, cols, num_nodes: int, num_parts: int, *,
                       seed: int = 0,
                       hotness: Optional[np.ndarray] = None,
                       balance_eps: Optional[float] = None,
                       passes: Optional[int] = None
                       ) -> Tuple[np.ndarray, Dict]:
  """Deterministic seeded streaming partition of a COO graph.

  LDG/Fennel-style greedy: nodes stream in a seeded random order; each
  is assigned to the eligible partition maximizing
  ``affinity(v, p) * (1 - size[p] / cap)`` where affinity is the
  (hotness-weighted) count of v's already-placed neighbors on ``p``
  and ``cap = ceil((1 + eps) * N / P)`` is a HARD bound — the balance
  guarantee `max range size <= (1 + eps) * N / P` holds by
  construction.  ``passes`` additional refinement sweeps re-stream
  every node and keep any capacity-respecting move that strictly
  improves its affinity score.

  ``hotness``: optional per-node mass (a `DecayedSketch` works too —
  anything with ``.score(ids)``); cutting a hot node's edges costs
  proportionally more, so hot neighborhoods co-locate first.

  Returns ``(node_pb [N] int32, stats)`` with
  ``stats = {'edge_cut_frac', 'max_part_frac', 'cap', 'passes',
  'seed'}``.  Same inputs + same seed => identical ``node_pb``.
  """
  rows = np.asarray(rows, np.int64)
  cols = np.asarray(cols, np.int64)
  num_nodes = int(num_nodes)
  num_parts = int(num_parts)
  if balance_eps is None:
    balance_eps = _env_float('GLT_LOCALITY_EPS', 0.05)
  if passes is None:
    passes = _env_int('GLT_LOCALITY_PASSES', 1)
  if hotness is not None and hasattr(hotness, 'score'):
    hotness = hotness.score(np.arange(num_nodes))
  cap = int(np.ceil((1.0 + float(balance_eps)) * num_nodes
                    / max(num_parts, 1)))
  cap = max(cap, 1)
  indptr, nbrs = _adjacency_csr(rows, cols, num_nodes)
  if hotness is not None:
    hot = np.asarray(hotness, np.float64)
    scale = hot.mean() or 1.0
    w = 1.0 + hot / scale           # neighbor weight: hot edges cost more
  else:
    w = np.ones(num_nodes, np.float64)

  rng = np.random.default_rng(seed)
  order = rng.permutation(num_nodes)
  part = np.full(num_nodes, -1, np.int64)
  sizes = np.zeros(num_parts, np.int64)
  # the tiny load term breaks affinity ties toward the emptiest
  # partition (and places isolated nodes round-robin-ish) without ever
  # outweighing one real neighbor
  tie = 1.0 / (cap * max(num_parts, 1) * 4.0)

  def _best(v: int, current: int = -1) -> int:
    nb = nbrs[indptr[v]:indptr[v + 1]]
    pnb = part[nb]
    placed = pnb >= 0
    if placed.any():
      aff = np.bincount(pnb[placed], weights=w[nb[placed]],
                        minlength=num_parts)
    else:
      aff = np.zeros(num_parts, np.float64)
    score = aff * (1.0 - sizes / cap) - sizes * tie
    score[sizes >= cap] = -np.inf
    if current >= 0:
      score[current] = aff[current] * (1.0 - (sizes[current] - 1) / cap) \
          - (sizes[current] - 1) * tie
    return int(np.argmax(score))

  for v in order:
    p = _best(int(v))
    part[v] = p
    sizes[p] += 1

  for _ in range(max(int(passes), 0)):
    moved = 0
    for v in order:
      v = int(v)
      cur = int(part[v])
      p = _best(v, current=cur)
      if p != cur and sizes[p] < cap:
        sizes[cur] -= 1
        sizes[p] += 1
        part[v] = p
        moved += 1
    if not moved:
      break

  cut = edge_cut_frac(rows, cols, part)
  stats = {
      'edge_cut_frac': cut,
      'max_part_frac': float(sizes.max(initial=0) * num_parts
                             / max(num_nodes, 1)),
      'cap': cap,
      'passes': int(passes),
      'seed': int(seed),
  }
  from ..telemetry.live import live
  from ..telemetry.recorder import recorder
  live.gauge('locality.edge_cut_frac').set(cut)
  recorder.emit('partition.relabel', partitioner='locality',
                num_parts=num_parts, num_nodes=num_nodes,
                seed=int(seed), edge_cut_frac=round(cut, 6),
                max_part_frac=round(stats['max_part_frac'], 6),
                hotness_weighted=hotness is not None)
  return part.astype(np.int32), stats


# -- online rebalance: measured demand -> planned handoffs -------------------

def _demand_per_range(attribution: Dict,
                      sketch=None) -> Optional[np.ndarray]:
  """Per-range demand mass [P]: the sketch's exact decayed per-range
  histogram when attached, else the attribution bytes matrix's column
  mass (bytes requested OF each range, all requesters summed)."""
  if sketch is not None:
    mass = getattr(sketch, 'range_mass', None)
    if mass is not None and np.asarray(mass).sum() > 0:
      return np.asarray(mass, np.float64)
  m = attribution.get('bytes_matrix') if attribution else None
  if m is None:
    return None
  return np.asarray(m, np.float64).sum(axis=0)


def rebalance_plan(attribution: Dict, sketch=None, book=None, *,
                   max_moves: Optional[int] = None,
                   overload_factor: Optional[float] = None
                   ) -> List[Dict]:
  """Plan hot-range migrations from measured traffic.

  ``attribution``: `DistNeighborSampler.attribution_stats()` output
  (its ``bytes_matrix`` is [src device, dst range]); ``sketch``: an
  optional `ops.gns.DecayedSketch` whose ``range_mass`` supersedes the
  matrix for demand ranking; ``book``: the dataset's `PartitionBook`
  (constrains which moves its v1 `transfer` will accept).

  A range moves when (a) its serving device is loaded above
  ``overload_factor`` x the mean demand, (b) its top off-owner
  REQUESTER (bytes-matrix column argmax) is loaded below the mean, and
  (c) the book can take the move: the range still sits at its identity
  owner, the destination is alive, carries no extra lane, and is used
  by at most one move in the plan.  Returns an ordered move list
  ``[{'range', 'frm', 'to', 'demand'}, ...]`` (hottest first) for
  :func:`execute_rebalance`.
  """
  if overload_factor is None:
    overload_factor = _env_float('GLT_REBALANCE_OVERLOAD', 1.1)
  demand = _demand_per_range(attribution, sketch)
  if demand is None or not len(demand) or demand.sum() <= 0:
    return []
  num_parts = len(demand)
  m = np.asarray(attribution.get('bytes_matrix',
                                 np.zeros((num_parts, num_parts))),
                 np.float64)
  owners = (np.asarray(book.view().owners) if book is not None
            else np.arange(num_parts))
  dead = set(np.flatnonzero(owners != np.arange(num_parts)).tolist())
  # device load = demand of every range it currently serves
  load = np.zeros(num_parts, np.float64)
  for r in range(num_parts):
    load[int(owners[r])] += demand[r]
  mean = load.sum() / max(num_parts, 1)
  busy_dest = set(int(owners[r]) for r in range(num_parts)
                  if int(owners[r]) != r)
  plan: List[Dict] = []
  for r in np.argsort(-demand):
    r = int(r)
    if max_moves is not None and len(plan) >= max_moves:
      break
    frm = int(owners[r])
    if frm != r:
      continue                    # already off-owner: immovable in v1
    if load[frm] <= overload_factor * mean:
      continue
    col = m[:, r].copy()
    col[r] = -1.0                 # the owner itself is not a move target
    for d in np.argsort(-col):
      d = int(d)
      if col[d] <= 0:
        break
      if (d == r or d in dead or d in busy_dest
          or load[d] >= mean):
        continue
      plan.append({'range': r, 'frm': frm, 'to': d,
                   'demand': float(demand[r])})
      busy_dest.add(d)
      load[frm] -= demand[r]
      load[d] += demand[r]
      break
  return plan


def execute_rebalance(ds, plan: Sequence[Dict], store=None) -> List[Dict]:
  """Run a :func:`rebalance_plan` through the PR 19 fenced handoff
  ladder, one move at a time — each move is snapshot -> transfer ->
  fence -> one RCU book bump -> drain, so readers never route a range
  to a device that does not hold its bytes and the epoch completes
  with zero degraded batches.  Emits one ``partition.rebalance`` event
  per move; a move refused by the book or aborted pre-cutover stops
  the remaining plan (the measured state it was computed from no
  longer holds).  Returns the per-move handoff info dicts."""
  from ..telemetry.recorder import recorder
  from .handoff import handoff
  infos: List[Dict] = []
  for mv in plan:
    info = handoff(ds, int(mv['range']), int(mv['to']), store=store)
    recorder.emit('partition.rebalance', partition=int(mv['range']),
                  frm=int(mv['frm']), to=int(mv['to']),
                  demand=float(mv.get('demand', 0.0)),
                  version=info['version'],
                  secs=round(float(info['secs']), 6))
    infos.append(info)
  return infos
