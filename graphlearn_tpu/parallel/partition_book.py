"""Versioned, transferable partition ownership — THE routing authority.

ISSUE 15 (ROADMAP item 4's named refactor unlock): until now four
independent conventions answered "who owns id ``g``?" — the inline
``searchsorted(bounds, g)`` lambdas of the hop exchanges, the
``eid % P`` rule of the mod-sharded edge-feature tables, the cold
overlay's host-side owner recompute, and the GNS bitmask's implicit
"owner == device" assumption.  All four were frozen at load time, so
a dead partition owner could only mean reduced data (degraded
completion) or a rollback — the orphaned shard's nodes vanished from
the epoch.

`PartitionBook` makes ownership a first-class, monotone-versioned,
RCU-published mapping:

  * **ranges stay frozen** — the contiguous relabel (``bounds``) is
    the id space every feature shard, seed split and hot/cold
    placement was built against, and never moves;
  * **owners move** — ``owners[r]`` names the mesh position serving
    range ``r``.  At version 0 the book is the identity
    (``owners[r] == r``) and every consumer compiles EXACTLY the
    pre-book program (the fault-free byte-identity contract);
  * **adoption** (`adopt`) reassigns an orphaned range to a survivor,
    bumps the version, and publishes a new immutable `BookView`.
    Readers pin one view per dispatch (the same RCU discipline as the
    streaming `GraphView`, ISSUE 14) and fence at their existing
    ``_arrays()`` / ``_chunk_arrs`` seams — a bump mid-dispatch never
    tears a compiled program.

The four consumers all read ownership through this module:
hop routing (`range_owner_fn` / `book_owner_fn` + the lane plan in
`dist_sampler._BookPlan`), feature hot/cold placement
(`hot_split_host`), cold-cache admission (the overlay planners feed
admission from the same split), and the GNS cached-set bitmask
(`ops.gns.per_requester_bits` builds one mask row per requesting
device from the same placement).  The mod-sharded
edge-feature rule lives here too (`edge_owner_*` / `edge_local_*`) so
no ``% P`` routing convention remains outside this module — enforced
by a grep test in ``tests/test_partition_failover.py``.

**Lanes.**  After adoption one device serves several ranges.  The
compiled SPMD steps route by *(device, lane)*: range ``r`` maps to
virtual destination ``owners[r] * num_lanes + lane_of_range[r]``, and
each device's local arrays grow a leading lane axis holding one
shard per lane.  Because requests are bucketed per RANGE (capacity,
positions and the sampling key all keyed by the range, not the
device), a lane's receive buffer is bit-identical to what the range's
original owner would have received — which is what makes an adopted
epoch's batches byte-identical to the fault-free run.  The identity
book has one lane and compiles the original program.
"""
from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np


class AdoptionRefusedError(RuntimeError):
  """A partition-adoption request that must not proceed: the range is
  already adopted (double adoption would fork the routing authority),
  the survivor is itself dead, or the survivor already carries an
  adopted lane (v1 supports one adopted shard per survivor)."""


class BookView(NamedTuple):
  """One immutable published snapshot of the book (RCU: readers pin a
  view per dispatch; `PartitionBook.adopt` publishes a new one)."""
  version: int
  bounds: np.ndarray          # [P+1] frozen ownership ranges
  owners: np.ndarray          # [P] mesh position serving each range
  lane_of_range: np.ndarray   # [P] lane index of each range at owner
  slot_ranges: np.ndarray     # [P, S] range served by (device, lane),
                              # -1 = unassigned
  num_lanes: int

  @property
  def num_partitions(self) -> int:
    return len(self.bounds) - 1

  @property
  def is_identity(self) -> bool:
    return self.version == 0

  def spec(self) -> Optional['BookSpec']:
    """Hashable static form for compile keys — None when identity, so
    the identity book's steps compile exactly the pre-book program."""
    if self.is_identity:
      return None
    return BookSpec(
        version=int(self.version),
        num_parts=self.num_partitions,
        num_lanes=int(self.num_lanes),
        owners=tuple(int(o) for o in self.owners),
        lane_of_range=tuple(int(x) for x in self.lane_of_range),
        slot_ranges=tuple(tuple(int(x) for x in row)
                          for row in self.slot_ranges))


class BookSpec(NamedTuple):
  """Static (trace-time constant) routing tables baked into compiled
  steps — part of the step-cache key, so a version bump recompiles
  the exchange plans for the new routing."""
  version: int
  num_parts: int
  num_lanes: int
  owners: Tuple[int, ...]
  lane_of_range: Tuple[int, ...]
  slot_ranges: Tuple[Tuple[int, ...], ...]


class PartitionBook:
  """Monotone-versioned node→owner table over frozen contiguous
  ranges (`dist_data.relabel_by_partition`).

  Thread-safe: mutations happen under ``_lock`` and publish a fresh
  immutable `BookView`; readers call `view()` (one atomic attribute
  read) and never observe a torn table.
  """

  def __init__(self, bounds: np.ndarray):
    bounds = np.asarray(bounds, np.int64)
    assert bounds.ndim == 1 and len(bounds) >= 2
    p = len(bounds) - 1
    self._lock = threading.Lock()
    #: the version table — guarded-by: self._lock
    self._version = 0
    #: range -> serving mesh position — guarded-by: self._lock
    self._owners = np.arange(p, dtype=np.int32)
    #: the adoption ledger (one record per ownership transfer) —
    #: guarded-by: self._lock
    self._adoptions: List[Dict] = []
    #: the planned-handoff ledger (ISSUE 19) — separate from
    #: ``_adoptions`` so the crash-adoption record shape stays frozen —
    #: guarded-by: self._lock
    self._transfers: List[Dict] = []
    self._bounds = bounds
    self._published = self._build_view_locked()

  # -- publication ---------------------------------------------------------
  def _build_view_locked(self) -> BookView:
    """Assemble the immutable view from the guarded tables (call with
    ``_lock`` held — or from ``__init__`` before the book escapes)."""
    p = len(self._bounds) - 1
    per_dev: List[List[int]] = [[] for _ in range(p)]
    lane = np.zeros(p, np.int32)
    # own range first (lane 0 == the device's own shard, so every
    # non-survivor keeps exactly its identity layout), adopted ranges
    # in range order after it
    for r in range(p):
      if int(self._owners[r]) == r:
        lane[r] = len(per_dev[r])
        per_dev[r].append(r)
    for r in range(p):
      o = int(self._owners[r])
      if o != r:
        lane[r] = len(per_dev[o])
        per_dev[o].append(r)
    s = max((len(d) for d in per_dev), default=1) or 1
    slots = np.full((p, s), -1, np.int32)
    for d in range(p):
      for j, r in enumerate(per_dev[d]):
        slots[d, j] = r
    return BookView(version=self._version, bounds=self._bounds,
                    owners=self._owners.copy(), lane_of_range=lane,
                    slot_ranges=slots, num_lanes=s)

  def view(self) -> BookView:
    """Pin the current published view (lock-free read)."""
    return self._published

  @property
  def version(self) -> int:
    return self._published.version

  @property
  def bounds(self) -> np.ndarray:
    return self._bounds

  @property
  def num_partitions(self) -> int:
    return len(self._bounds) - 1

  def adoptions(self) -> List[Dict]:
    with self._lock:
      return [dict(a) for a in self._adoptions]

  def transfers(self) -> List[Dict]:
    """The planned-handoff ledger (one record per `transfer` cutover)."""
    with self._lock:
      return [dict(t) for t in self._transfers]

  # -- ownership transfer --------------------------------------------------
  def adopt(self, lost: int, survivor: int) -> BookView:
    """Transfer range ``lost`` to mesh position ``survivor``; bump the
    version and publish.  Typed refusals (`AdoptionRefusedError`)
    never mutate the book."""
    p = self.num_partitions
    lost, survivor = int(lost), int(survivor)
    if not 0 <= lost < p or not 0 <= survivor < p:
      raise AdoptionRefusedError(
          f'partition out of range: lost={lost} survivor={survivor} '
          f'(P={p})')
    if lost == survivor:
      raise AdoptionRefusedError(
          f'partition {lost} cannot adopt itself')
    with self._lock:
      if int(self._owners[lost]) != lost:
        raise AdoptionRefusedError(
            f'partition {lost} is already adopted (owner '
            f'{int(self._owners[lost])}, version {self._version}) — '
            'a second adoption would fork the routing authority')
      if int(self._owners[survivor]) != survivor:
        raise AdoptionRefusedError(
            f'survivor {survivor} is itself dead (owned by '
            f'{int(self._owners[survivor])})')
      if int(np.sum(self._owners == survivor)) > 1:
        raise AdoptionRefusedError(
            f'survivor {survivor} already carries an adopted shard '
            '(one adopted lane per survivor in v1) — pick another')
      self._owners[lost] = survivor
      self._version += 1
      self._adoptions.append({'lost': lost, 'survivor': survivor,
                              'version': self._version})
      self._published = self._build_view_locked()
      view = self._published
    from ..telemetry.live import live
    from ..telemetry.recorder import recorder
    live.gauge('partition.book_version').set(float(view.version))
    recorder.emit('partition.book_version', version=view.version,
                  lost=lost, survivor=survivor,
                  num_lanes=view.num_lanes)
    return view

  def transfer(self, rng: int, frm: int, to: int) -> BookView:
    """Planned ownership handoff (ISSUE 19): move range ``rng`` from
    its current owner ``frm`` to ``to`` in ONE version bump — the
    cutover step of `parallel.handoff.handoff`.  Shares `adopt`'s v1
    lane constraints (the destination must serve its own range and
    carry no extra lane) but records into the SEPARATE ``_transfers``
    ledger, leaving the crash-adoption ledger shape untouched.  Typed
    refusals (`AdoptionRefusedError`) never mutate the book."""
    p = self.num_partitions
    rng, frm, to = int(rng), int(frm), int(to)
    if not 0 <= rng < p or not 0 <= to < p:
      raise AdoptionRefusedError(
          f'partition out of range: rng={rng} to={to} (P={p})')
    if to == frm:
      raise AdoptionRefusedError(
          f'handoff of partition {rng} from {frm} to itself')
    with self._lock:
      if int(self._owners[rng]) != frm:
        raise AdoptionRefusedError(
            f'stale handoff source: range {rng} is owned by '
            f'{int(self._owners[rng])}, not {frm} (version '
            f'{self._version}) — refusing a cutover that would fork '
            'the routing authority')
      if int(self._owners[rng]) != rng:
        raise AdoptionRefusedError(
            f'range {rng} is already served off-owner (by {frm}) — '
            'one moved lane per range in v1; restore identity first')
      if int(self._owners[to]) != to:
        raise AdoptionRefusedError(
            f'destination {to} is itself dead (owned by '
            f'{int(self._owners[to])})')
      if int(np.sum(self._owners == to)) > 1:
        raise AdoptionRefusedError(
            f'destination {to} already carries an extra lane '
            '(one moved shard per device in v1) — pick another')
      self._owners[rng] = to
      self._version += 1
      self._transfers.append({'range': rng, 'frm': frm, 'to': to,
                              'version': self._version})
      self._published = self._build_view_locked()
      view = self._published
    from ..telemetry.live import live
    from ..telemetry.recorder import recorder
    live.gauge('partition.book_version').set(float(view.version))
    recorder.emit('partition.book_version', version=view.version,
                  lost=rng, survivor=to, planned=True,
                  num_lanes=view.num_lanes)
    return view

  def live_partitions(self) -> np.ndarray:
    """Mesh positions still serving their own range (adoption-eligible
    survivors)."""
    v = self.view()
    p = v.num_partitions
    own = np.asarray([int(v.owners[r]) == r for r in range(p)])
    return np.nonzero(own)[0]

  def pick_survivor(self, lost: int) -> int:
    """Deterministic survivor choice: the lowest-indexed live device
    serving only its own shard (fewest lanes first, then index)."""
    v = self.view()
    counts = np.bincount(np.asarray(v.owners),
                         minlength=v.num_partitions)
    for d in sorted(range(v.num_partitions),
                    key=lambda d: (int(counts[d]), d)):
      if d == int(lost):
        continue
      if int(v.owners[d]) == d and int(counts[d]) == 1:
        return d
    raise AdoptionRefusedError(
        f'no eligible survivor for partition {lost}: every live '
        'device already carries an adopted shard')


# -- ownership arithmetic (device + host forms) -----------------------------
#
# These small functions are the ONLY place the two ownership rules
# (range searchsorted, mod-strided edge ids) are written down; every
# routing site in parallel/ calls through them.

def range_of(bounds, ids):
  """Device form: id -> frozen range index (``searchsorted`` rule)."""
  import jax.numpy as jnp
  return (jnp.searchsorted(bounds, ids, side='right') - 1).astype(
      jnp.int32)


def range_of_host(bounds, ids, num_parts: Optional[int] = None):
  """Host form of `range_of`, clipped to valid ranges."""
  p = (int(num_parts) if num_parts is not None else len(bounds) - 1)
  return np.clip(
      np.searchsorted(bounds, np.asarray(ids), side='right') - 1,
      0, p - 1).astype(np.int32)


def range_owner_fn(bounds):
  """The identity-book owner function of the hop/gather exchanges —
  owner == range.  Byte-identical to the pre-book inline lambdas."""
  def owner_fn(v):
    return range_of(bounds, v)
  return owner_fn


def book_owner_fn(bounds, spec: BookSpec):
  """Adopted-book VIRTUAL owner function: range ``r`` routes to
  destination-lane ``owners[r] * S + lane_of_range[r]``."""
  import jax.numpy as jnp
  owners = jnp.asarray(spec.owners, jnp.int32)
  lanes = jnp.asarray(spec.lane_of_range, jnp.int32)
  s = int(spec.num_lanes)

  def owner_fn(v):
    r = jnp.clip(range_of(bounds, v), 0, spec.num_parts - 1)
    return owners[r] * s + lanes[r]
  return owner_fn


def edge_owner_fn(num_parts: int):
  """Device owner function of MOD-sharded (strided) edge-feature
  tables: owner = ``eid mod P`` (`build_dist_edge_feature`)."""
  import jax.numpy as jnp

  def owner_fn(v):
    return (v % num_parts).astype(jnp.int32)
  return owner_fn


def edge_book_owner_fn(num_parts: int, spec: BookSpec):
  """Adopted-book virtual owner function for mod-sharded tables."""
  import jax.numpy as jnp
  owners = jnp.asarray(spec.owners, jnp.int32)
  lanes = jnp.asarray(spec.lane_of_range, jnp.int32)
  s = int(spec.num_lanes)

  def owner_fn(v):
    r = (v % num_parts).astype(jnp.int32)
    return owners[r] * s + lanes[r]
  return owner_fn


def edge_local_rows(ids, num_parts: int):
  """Device local-row rule of mod-sharded tables (eid -> shard row)."""
  return ids // num_parts


def edge_owner_host(ids, num_parts: int) -> np.ndarray:
  return (np.asarray(ids) % int(num_parts)).astype(np.int32)


def edge_local_rows_host(ids, num_parts: int) -> np.ndarray:
  return np.asarray(ids) // int(num_parts)


def hot_split_host(bounds, hot_counts, ids, valid=None):
  """THE host-side hot/cold placement read (feature store + cold-cache
  admission): returns ``(rng, local, cold)`` where ``rng`` is the
  frozen range of each id, ``local`` its row within the range, and
  ``cold`` marks rows past the range's hot count (host-tier service).
  Placement keys on the RANGE, never the serving device — adoption
  moves the server, not the split."""
  ids = np.asarray(ids)
  if valid is None:
    valid = ids >= 0
  hot_counts = np.asarray(hot_counts)
  rng = range_of_host(bounds, ids, num_parts=len(hot_counts))
  local = np.where(valid, ids - np.asarray(bounds)[rng], 0)
  cold = valid & (local >= hot_counts[rng])
  return rng, local, cold
