"""Elastic partition failover: durable shards + ownership adoption.

ISSUE 15.  The data-plane half of the `PartitionBook` story: each
partition's CSR + feature shard is durably re-loadable (`ShardStore`,
atomic tmp→rename publishes — the PR 6 `SnapshotManager` discipline —
written at load time and refreshed at ingest-compaction seams), and
when supervision classifies an owner dead (the chaos
``partition.owner`` site in-process; `PeerLostError` / heartbeat
misses through the PR 13 overloaded-vs-dead discriminator in the
server world) a designated survivor **adopts** the orphaned shard:

  1. `adopt_shard` loads the durable shard (missing →
     `NoDurableShardError`, the caller falls back to the documented
     ``GLT_DEGRADED_OK`` path), validates it against the dataset's
     frozen widths, and parks it on ``dataset.adopted_shards``;
  2. the book version bumps (`PartitionBook.adopt` — double adoption
     refused typed);
  3. readers fence at their ``_arrays()`` / ``_chunk_arrs`` seams:
     the sampler rebuilds its device arrays lane-stacked, exchange
     plans and capacity specs recompile for the new routing, and the
     epoch resumes with the **exact-completion contract** — every
     expected seed served, batches byte-identical to the fault-free
     run where the schedule is deterministic.

Knobs: ``GLT_SHARD_DIR`` (durable shard directory; unset = no
failover, degraded semantics unchanged), ``GLT_ADOPT_TIMEOUT_S``
(budget for the shard load + rebuild; a hung disk surfaces typed
instead of wedging recovery).
"""
from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from .partition_book import AdoptionRefusedError, PartitionBook

SHARD_DIR_ENV = 'GLT_SHARD_DIR'
ADOPT_TIMEOUT_ENV = 'GLT_ADOPT_TIMEOUT_S'

#: default adoption budget (seconds): shard load + array rebuild for
#: one partition — generous for host-DRAM-sized shards, small enough
#: that a wedged disk fails the adoption instead of the epoch SLO.
DEFAULT_ADOPT_TIMEOUT_S = 120.0


class PartitionLostError(RuntimeError):
  """A partition owner was classified dead mid-epoch (chaos
  ``partition.owner`` kill in-process; heartbeat-miss /
  `PeerLostError` classification in the server world)."""

  def __init__(self, msg: str, partition: Optional[int] = None):
    super().__init__(msg)
    self.partition = partition


class NoDurableShardError(RuntimeError):
  """Adoption was requested but the shard store holds no durable copy
  of the orphaned partition — the documented fallback ladder applies
  (adopt → rollback → degraded, ``GLT_DEGRADED_OK``)."""


def dataset_fingerprint(ds) -> int:
  """Cheap content fingerprint of a `DistDataset` (strided CRC over
  the topology + per-partition edge counts + bounds): a regenerated
  SAME-SHAPED dataset reusing ``GLT_SHARD_DIR`` must not be served
  another graph's durable shards — shape metadata alone collides.
  Strided (≤64K sampled indices) so load-time validation stays O(1)
  in graph size; a collision needs identical shape AND an identical
  sample, which regeneration does not produce in practice."""
  import zlib
  g = ds.graph
  idx = np.ascontiguousarray(np.asarray(g.indices).ravel())
  stride = max(1, idx.size // 65536)
  h = zlib.crc32(np.ascontiguousarray(idx[::stride]).tobytes())
  h = zlib.crc32(np.ascontiguousarray(
      np.asarray(g.indptr)[:, -1]).tobytes(), h)
  h = zlib.crc32(np.ascontiguousarray(
      np.asarray(g.bounds, np.int64)).tobytes(), h)
  return int(h)


def adopt_timeout_s() -> float:
  try:
    return float(os.environ.get(ADOPT_TIMEOUT_ENV,
                                DEFAULT_ADOPT_TIMEOUT_S))
  except ValueError:
    return DEFAULT_ADOPT_TIMEOUT_S


def shard_dir_from_env() -> Optional[str]:
  return os.environ.get(SHARD_DIR_ENV) or None


class ShardStore:
  """Durable per-partition shard snapshots.

  One ``shard{p}.npz`` per partition plus a ``SHARDS.json`` meta
  (partition count, array widths — the adoption-time validation
  fingerprint).  Every publish is atomic (tmp → rename, the
  `SnapshotManager` discipline): a kill mid-write leaves the previous
  durable shard as the latest, never a torn file.
  """

  def __init__(self, root):
    self.root = Path(root)
    self.root.mkdir(parents=True, exist_ok=True)

  def _shard_path(self, p: int) -> Path:
    return self.root / f'shard{int(p)}.npz'

  def _meta_path(self) -> Path:
    return self.root / 'SHARDS.json'

  def _publish(self, path: Path, write_fn) -> None:
    tmp = path.with_name(path.name + '.tmp')
    with open(tmp, 'wb') as f:
      write_fn(f)
      f.flush()
      os.fsync(f.fileno())
    os.replace(tmp, path)

  def save_shard(self, p: int, payload: Dict[str, np.ndarray]) -> None:
    arrays = {k: np.asarray(v) for k, v in payload.items()
              if v is not None}
    self._publish(self._shard_path(p),
                  lambda f: np.savez(f, **arrays))

  def save_meta(self, meta: Dict) -> None:
    data = json.dumps(meta, sort_keys=True).encode()
    self._publish(self._meta_path(), lambda f: f.write(data))

  def meta(self) -> Optional[Dict]:
    try:
      with open(self._meta_path()) as f:
        return json.load(f)
    except (OSError, ValueError):
      return None

  def has_shard(self, p: int) -> bool:
    return self._shard_path(p).exists()

  def load_shard(self, p: int) -> Dict[str, np.ndarray]:
    path = self._shard_path(p)
    if not path.exists():
      raise NoDurableShardError(
          f'no durable shard for partition {int(p)} under '
          f'{self.root} — adoption unavailable; the documented '
          f'fallback is GLT_DEGRADED_OK=1 (reduced completion)')
    with np.load(path, allow_pickle=False) as z:
      return {k: z[k] for k in z.files}

  def partitions(self):
    return sorted(int(f.stem[len('shard'):])
                  for f in self.root.glob('shard*.npz'))

  # -- dataset integration -------------------------------------------------
  def write_dataset_shards(self, ds) -> int:
    """Write one durable shard per partition of a `DistDataset` —
    called at load time (and re-called at ingest-compaction seams via
    `refresh_cb`, so a streamed topology's durable copy tracks the
    compacted base).  Returns the number of shards written."""
    p = ds.graph.num_partitions
    for r in range(p):
      self.save_shard(r, shard_payload(ds, r))
    self.save_meta(dataset_meta(ds))
    return p

  def refresh_cb(self, ds):
    """Compaction-seam refresh hook for `streaming.IngestPipeline`
    (``shard_refresh=store.refresh_cb(ds)``): after each durable base
    compaction the shard snapshots are rewritten from the dataset's
    CURRENT stacks, so an adoption after a long ingest run loads the
    streamed topology, not the load-time one."""
    def _refresh() -> None:
      self.write_dataset_shards(ds)
    return _refresh


def shard_payload(ds, r: int) -> Dict[str, np.ndarray]:
  """One partition's durable payload, built from the dataset's
  CURRENT stacks — shared by the load-time/compaction-seam bulk write
  (`ShardStore.write_dataset_shards`) and the planned handoff's
  snapshot phase (`parallel.handoff`), so both sides serialize the
  identical shard shape."""
  g = ds.graph
  r = int(r)
  nf = ds.node_features
  bounds = np.asarray(g.bounds, np.int64)
  payload = {
      'indptr': g.indptr[r], 'indices': g.indices[r],
      'eids': g.edge_ids[r],
  }
  if nf is not None:
    payload['fshard'] = nf.shards[r]
    payload['hot_count'] = np.asarray([nf.hot_counts[r]], np.int64)
    if nf.cold_host is not None:
      payload['cold'] = nf.cold_host[bounds[r]:bounds[r + 1]]
  if ds.node_labels is not None:
    payload['lshard'] = np.asarray(ds.node_labels)[r]
  if ds.edge_features is not None:
    payload['efshard'] = ds.edge_features.shards[r]
  return payload


def dataset_meta(ds) -> Dict:
  """The `ShardStore` meta record for a dataset — the adoption-time
  validation fingerprint (`validate_shard_payload` checks against
  it)."""
  g = ds.graph
  return {
      'num_parts': int(g.num_partitions),
      'num_nodes': int(g.num_nodes),
      'node_width': int(g.indptr.shape[1]),
      'edge_width': int(g.indices.shape[1]),
      'fingerprint': dataset_fingerprint(ds),
  }


def validate_shard_payload(ds, store: 'ShardStore',
                           payload: Dict[str, np.ndarray],
                           ) -> Dict[str, np.ndarray]:
  """The shared load-side validation ladder (crash adoption AND
  planned handoff): check the store meta against the dataset's frozen
  shape, then widen the CSR rows to the dataset's current stack
  widths.  Typed `AdoptionRefusedError` on any mismatch; returns the
  padded payload."""
  book: PartitionBook = ds.partition_book
  meta = store.meta() or {}
  if meta.get('num_parts') not in (None, book.num_partitions):
    raise AdoptionRefusedError(
        f"shard store {store.root} was written for "
        f"{meta.get('num_parts')} partitions, this dataset has "
        f'{book.num_partitions}')
  g = ds.graph
  # the durable copy must be THIS graph's: num_parts can collide
  # across graphs, so the frozen shape fingerprint is checked too —
  # a mismatched store adopted silently would serve another graph's
  # topology/features for the orphaned range
  if meta.get('num_nodes') not in (None, int(g.num_nodes)):
    raise AdoptionRefusedError(
        f"shard store {store.root} was written for "
        f"{meta.get('num_nodes')} nodes, this dataset has "
        f'{int(g.num_nodes)}')
  if meta.get('node_width') not in (None, int(g.indptr.shape[1])):
    raise AdoptionRefusedError(
        f"shard store {store.root} node width "
        f"{meta.get('node_width')} != dataset {int(g.indptr.shape[1])}"
        f' (different bounds — not this graph)')
  if int(meta.get('edge_width') or 0) > int(g.indices.shape[1]):
    raise AdoptionRefusedError(
        f"shard store {store.root} edge width "
        f"{meta.get('edge_width')} exceeds the dataset's "
        f'{int(g.indices.shape[1])} — truncation would corrupt the '
        f'adopted CSR')
  payload['indptr'] = _pad_to(
      np.asarray(payload['indptr']), g.indptr.shape[1],
      int(np.asarray(payload['indptr'])[-1]))
  payload['indices'] = _pad_to(np.asarray(payload['indices']),
                               g.indices.shape[1], -1)
  payload['eids'] = _pad_to(np.asarray(payload['eids']),
                            g.edge_ids.shape[1], -1)
  return payload


def _load_with_deadline(store: 'ShardStore', lost: int,
                        timeout_s: float) -> Dict[str, np.ndarray]:
  """`load_shard` in a worker thread under the adoption budget: a
  WEDGED store (hung NFS read) fails the adoption typed instead of
  wedging recovery — the stuck daemon thread is abandoned and the
  caller proceeds down the fallback ladder."""
  import threading
  box: Dict = {}

  def _run():
    try:
      box['payload'] = store.load_shard(lost)
    except BaseException as e:        # noqa: BLE001 — re-raised below
      box['err'] = e

  t = threading.Thread(target=_run, daemon=True,
                       name=f'glt-adopt-load-p{int(lost)}')
  t.start()
  t.join(max(timeout_s, 0.001))
  if t.is_alive():
    raise AdoptionRefusedError(
        f'adoption of partition {int(lost)} exceeded '
        f'GLT_ADOPT_TIMEOUT_S={adopt_timeout_s():g}s loading the '
        f'durable shard (wedged store?)')
  if 'err' in box:
    raise box['err']
  return box['payload']


def _pad_to(arr: np.ndarray, width: int, fill) -> np.ndarray:
  """Widen a loaded shard row to the dataset's current stack width
  (streaming `reserve_edges` may have grown the stacks after the
  durable copy was written)."""
  if arr.shape[0] >= width:
    return arr[:width] if arr.shape[0] > width else arr
  out = np.full((width,) + arr.shape[1:], fill, arr.dtype)
  out[:arr.shape[0]] = arr
  return out


def adopt_shard(ds, store: Optional[ShardStore], lost: int,
                survivor: Optional[int] = None) -> Dict:
  """Execute one ownership transfer: load the durable shard, validate,
  park it on the dataset, bump the book.  Returns an info dict
  (``survivor``, ``version``, ``load_secs``).  Raises
  `NoDurableShardError` (no durable copy — fall back to degraded) or
  `AdoptionRefusedError` (double adoption / no survivor) without
  mutating anything."""
  from ..telemetry.live import live
  from ..telemetry.recorder import recorder
  if store is None:
    d = shard_dir_from_env()
    if d is None:
      raise NoDurableShardError(
          'no shard store configured (GLT_SHARD_DIR unset) — '
          'adoption unavailable; GLT_DEGRADED_OK=1 is the documented '
          'fallback')
    store = ShardStore(d)
  book: PartitionBook = ds.partition_book
  lost = int(lost)
  t0 = time.monotonic()
  deadline = t0 + adopt_timeout_s()
  if survivor is None:
    survivor = book.pick_survivor(lost)
  payload = _load_with_deadline(store, lost,
                                deadline - time.monotonic())
  payload = validate_shard_payload(ds, store, payload)
  if time.monotonic() > deadline:
    raise AdoptionRefusedError(
        f'adoption of partition {lost} exceeded GLT_ADOPT_TIMEOUT_S='
        f'{adopt_timeout_s():g}s while loading the durable shard')
  if not hasattr(ds, 'adopted_shards'):
    ds.adopted_shards = {}
  view = book.adopt(lost, int(survivor))  # typed refusals raise here
  ds.adopted_shards[lost] = payload
  secs = time.monotonic() - t0
  live.counter('partition.adoptions_total').inc()
  recorder.emit('partition.adopt', partition=lost,
                survivor=int(survivor), version=view.version,
                secs=round(secs, 6))
  return {'survivor': int(survivor), 'version': view.version,
          'load_secs': secs}
