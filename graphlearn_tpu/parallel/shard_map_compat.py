"""shard_map API compatibility (jax>=0.8 moved it out of experimental
and renamed check_rep -> check_vma)."""
from __future__ import annotations

try:
  from jax import shard_map as _shard_map

  def shard_map(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
except ImportError:  # older jax
  from jax.experimental.shard_map import shard_map as _shard_map

  def shard_map(f, mesh, in_specs, out_specs):
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
