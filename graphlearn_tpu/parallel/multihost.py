"""Multi-host (pod / multi-slice) launch helpers.

The reference scales out by launching one process group per machine
with torch RPC worlds knitted over TCP/RDMA (`distributed/rpc.py:
236-292`, `run_dist_bench.py` ssh fan-out).  JAX is single-controller
per host: every host runs the SAME program, `jax.distributed`
initializes the cross-host runtime, and the mesh spans all hosts'
devices — collectives ride ICI within a slice and DCN across slices
automatically.  What the framework must add is exactly two things:

  * a mesh over ALL devices with the partition axis aligned to the
    global device order (`global_mesh`);
  * deterministic per-host seed sharding so every host feeds its own
    devices' seed batches without coordination (`host_seed_shard`) —
    the multi-host analog of the reference's per-worker `randperm`
    splits (`dist_sampling_producer.py:249-260`): same epoch
    permutation everywhere (shared seed), disjoint slices by host.

Typical launch (same script on every host)::

    from graphlearn_tpu.parallel import multihost
    multihost.initialize()                  # env-driven on TPU pods
    mesh = multihost.global_mesh()
    ds = DistDataset.from_partition_dir(
        root, mesh.devices.size,
        # each host materializes ONLY its partitions' tensors
        # (per-host RAM = 1/num_hosts of the dataset)
        host_parts=multihost.host_partition_ids(mesh))
    seeds = multihost.host_seed_shard(all_seeds, epoch=e, seed=0)
    loader = DistNeighborLoader(ds, fanouts, seeds, mesh=mesh, ...)
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def _is_initialized() -> bool:
  """Has the cross-host runtime come up?  `jax.distributed.
  is_initialized` only exists on newer jax; older releases expose the
  client on the private global state — check both without touching
  the XLA backend."""
  probe = getattr(jax.distributed, 'is_initialized', None)
  if probe is not None:
    return bool(probe())
  try:
    from jax._src import distributed
    return distributed.global_state.client is not None
  except Exception:             # noqa: BLE001 — can't tell: assume no
    return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
  """Bring up the cross-host runtime (no-op if already initialized).

  On TPU pods all three arguments resolve from the environment; set
  them explicitly for CPU/GPU multi-process testing.
  """
  # NOTE: nothing here may touch the XLA backend (jax.devices(),
  # jax.process_count(), ...) before initialize() — backend init makes
  # distributed init impossible, and that failure must stay LOUD.
  if _is_initialized():
    return
  try:
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id)
  except ValueError:
    # Swallow ONLY the fully-implicit case (no cluster environment
    # detected, nothing requested): single-process tests.  Any
    # explicitly-requested multi-process setup must fail loudly.
    if (coordinator_address is not None or num_processes is not None
        or process_id is not None):
      raise


def global_mesh(axis: str = 'data') -> Mesh:
  """One partition-axis mesh over every device of every host."""
  return Mesh(np.asarray(jax.devices()), (axis,))


def host_device_slice(num_parts: Optional[int] = None) -> slice:
  """This host's contiguous slice of the mesh partition axis."""
  num_parts = num_parts or len(jax.devices())
  per_host = num_parts // jax.process_count()
  lo = jax.process_index() * per_host
  return slice(lo, lo + per_host)


def host_partition_ids(mesh: Mesh) -> np.ndarray:
  """The partition indices whose devices live on THIS process, in mesh
  order — feed `DistDataset.from_partition_dir(host_parts=...)` so
  each host materializes only the shards its devices will hold."""
  flat = mesh.devices.reshape(-1)
  return np.asarray([i for i, d in enumerate(flat)
                     if d.process_index == jax.process_index()],
                    np.int64)


def global_max(value: int, mesh: Mesh) -> int:
  """Max of a per-process host scalar across every process of the mesh
  — e.g. the class count over host-local label shards (each host sees
  only its partitions; model widths must agree globally).  Works
  unchanged single-process."""
  import jax.numpy as jnp
  from jax.sharding import NamedSharding, PartitionSpec
  axis = mesh.axis_names[0]
  flat = mesh.devices.reshape(-1)
  shards = [jax.device_put(np.asarray([value], np.int64), flat[i])
            for i in host_partition_ids(mesh)]
  g = jax.make_array_from_single_device_arrays(
      (flat.size,), NamedSharding(mesh, PartitionSpec(axis)), shards)
  out = jax.jit(jnp.max,
                out_shardings=NamedSharding(mesh, PartitionSpec()))(g)
  return int(out)


def host_seed_shard(seeds: np.ndarray, epoch: int = 0, seed: int = 0,
                    shuffle: bool = True) -> np.ndarray:
  """This host's disjoint slice of the (globally shuffled) seed set.

  Every host computes the SAME permutation from ``(seed, epoch)`` and
  takes its process-index slice — globally consistent epoch shuffling
  with zero cross-host coordination.  Shards are wrap-around padded to
  EQUAL length (torch DistributedSampler semantics): unequal shards
  would run different step counts and desynchronize the SPMD
  collectives at epoch end.
  """
  seeds = np.asarray(seeds)
  if shuffle:
    rng = np.random.default_rng((int(seed), int(epoch)))
    seeds = seeds[rng.permutation(len(seeds))]
  n_hosts = jax.process_count()
  per = -(-len(seeds) // n_hosts)
  if per * n_hosts > len(seeds) and len(seeds):
    # wrap-around pad to exactly per * n_hosts even when the pad
    # exceeds the seed count (tiny seed sets on many hosts)
    seeds = np.resize(seeds, (per * n_hosts,) + seeds.shape[1:])
  lo = jax.process_index() * per
  return seeds[lo:lo + per]
