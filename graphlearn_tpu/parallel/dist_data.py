"""Distributed (sharded) graph + feature layout.

TPU-native replacement for the reference's per-process partition world
(`distributed/dist_dataset.py`, `dist_graph.py`, `dist_feature.py`):
instead of one dataset object per RPC worker, ONE host builds a
device-sharded layout over a `jax.sharding.Mesh`:

  * nodes are **relabeled to contiguous ownership ranges** so the
    partition book collapses to a `RangePartitionBook` (``bounds``
    [P+1]) — owner lookup is a `searchsorted`, O(P) memory, jittable
    (vs the reference's N-entry dense book, `typing.py:77`);
  * each device holds a **local CSR** of its owned nodes' out-edges
    (rows local, columns GLOBAL ids so sampled neighbors need no
    translation), padded to the max partition size and stacked
    ``[P, ...]`` for `shard_map`;
  * each device holds its **feature/label shard** ``[rows_max, D]``.

The reference's load path (`DistDataset.load` -> `load_partition` +
`cat_feature_cache`) maps to :meth:`DistDataset.from_partition_dir`.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ..typing import RangePartitionBook
from ..utils.topo import coo_to_csr, ptr2ind


class DistGraph:
  """Stacked per-partition local CSRs + ownership bounds.

  Attributes:
    indptr: ``[P, max_local_nodes + 1]``.
    indices: ``[P, max_local_edges]`` (GLOBAL neighbor ids, -1 pad).
    edge_ids: ``[P, max_local_edges]`` global edge ids (-1 pad).
    bounds: ``[P + 1]`` ownership ranges (RangePartitionBook).
  """

  def __init__(self, indptr, indices, edge_ids, bounds):
    self.indptr = np.asarray(indptr)
    self.indices = np.asarray(indices)
    self.edge_ids = np.asarray(edge_ids)
    self.bounds = np.asarray(bounds, dtype=np.int64)

  @property
  def num_partitions(self) -> int:
    return len(self.bounds) - 1

  @property
  def num_nodes(self) -> int:
    return int(self.bounds[-1])

  @property
  def node_pb(self) -> RangePartitionBook:
    return RangePartitionBook(self.bounds)

  @property
  def max_local_nodes(self) -> int:
    return self.indptr.shape[1] - 1


def relabel_by_partition(node_pb: np.ndarray, num_parts: int,
                         hotness: Optional[np.ndarray] = None):
  """THE contiguous-ownership relabel — single definition shared by
  every loader path (a host-local and a single-controller load of the
  same layout must agree on the id space, or precomputed seeds/splits
  mis-address every row).  Sort nodes by (partition[, -hotness],
  old id); returns ``(old2new, counts, bounds)``."""
  node_pb = np.asarray(node_pb)
  num_nodes = len(node_pb)
  if hotness is not None:
    hot = np.asarray(hotness)
    if hot.dtype.kind == 'u':
      hot = hot.astype(np.int64)   # unsigned negation would wrap
    order = np.lexsort((np.arange(num_nodes), -hot,
                        node_pb))                    # new id -> old id
  else:
    order = np.argsort(node_pb, kind='stable')       # new id -> old id
  old2new = np.empty(num_nodes, dtype=np.int64)
  old2new[order] = np.arange(num_nodes)
  counts = np.bincount(node_pb, minlength=num_parts)
  bounds = np.concatenate([[0], np.cumsum(counts)])
  return old2new, counts, bounds


def stack_partition_csr(root, host_parts, subpath: str,
                        old2new_src, old2new_dst, bounds_src, counts_src,
                        num_parts: int):
  """Shared host-local CSR stacking (homo + hetero loaders): pad
  widths from mmap'd shapes over ALL partitions, materialize only
  ``host_parts`` — one definition so the two loaders cannot drift.

  ``subpath``: dir under ``part{i}/`` holding rows/cols/eids
  (``'graph'`` or ``'graph/<etype>'``).  Returns
  ``(indptr_s, indices_s, eids_s)`` stacked ``[len(host_parts), ...]``.
  """
  from pathlib import Path
  from ..utils.topo import coo_to_csr
  root = Path(root)
  edge_counts = [
      np.load(root / f'part{i}' / subpath / 'rows.npy',
              mmap_mode='r').shape[0] for i in range(num_parts)]
  max_edges = max(max(edge_counts), 1)
  max_nodes = int(counts_src.max()) if num_parts else 0
  pl = len(host_parts)
  indptr_s = np.zeros((pl, max_nodes + 1), np.int64)
  indices_s = np.full((pl, max_edges), -1, np.int32)
  eids_s = np.full((pl, max_edges), -1, np.int64)
  for j, p in enumerate(host_parts):
    gdir = root / f'part{p}' / subpath
    rows = np.load(gdir / 'rows.npy')
    cols = np.load(gdir / 'cols.npy')
    eids = np.load(gdir / 'eids.npy')
    local_rows = old2new_src[rows] - bounds_src[p]
    if len(local_rows) and (local_rows.min() < 0
                            or local_rows.max() >= counts_src[p]):
      raise ValueError(
          f'partition {p} ({subpath}) holds edges whose src it does '
          'not own (corrupt or non-by_src layout)')
    iptr, idx, eid = coo_to_csr(local_rows, old2new_dst[cols],
                                int(counts_src[p]), eids)
    indptr_s[j, :len(iptr)] = iptr
    indptr_s[j, len(iptr):] = iptr[-1]
    indices_s[j, :len(idx)] = idx
    eids_s[j, :len(eid)] = eid
  return indptr_s, indices_s, eids_s


def scatter_partition_rows(root, host_parts, subpath: str, fname: str,
                           old2new, bounds, max_nodes: int):
  """Shared host-local row scatter (features ``fname='feats'`` or
  labels ``fname='labels'``): stack ``[len(host_parts), max_nodes
  (, D)]`` with each partition's owned rows placed at their local
  offsets; None when the files do not exist."""
  from pathlib import Path
  root = Path(root)
  out = None
  for j, p in enumerate(host_parts):
    d = root / f'part{p}' / subpath
    if not (d / f'{fname}.npy').exists():
      continue
    vals = np.load(d / f'{fname}.npy')
    ids = np.load(d / 'ids.npy')
    if out is None:
      out = np.zeros((len(host_parts), max_nodes) + vals.shape[1:],
                     vals.dtype)
    out[j, old2new[ids] - bounds[p]] = vals
  return out


def hot_count(counts, split_ratio: float) -> np.ndarray:
  """THE hot-row arithmetic of the tiered store: how many of each
  partition's ``counts`` rows are HBM-served at ``split_ratio``.
  ONE definition shared by every site that tiers or addresses a
  tiered layout (`build_dist_feature`, `tiered_local_feature`, and
  any loader-side HBM-served predicate): the ceil-vs-round rounding
  must agree everywhere, or the builder and the lookup path silently
  disagree on which rows are hot and mis-tier the boundary row of
  every partition."""
  return np.ceil(np.asarray(counts) * float(split_ratio)).astype(
      np.int64)


_SCAN_CHUNK = 1 << 22


def partition_in_degree(root, subpath: str, num_nodes: int,
                        num_parts: int) -> np.ndarray:
  """Chunked in-degree (OLD id space) over every partition dir's
  ``cols.npy`` — the host-local twin of the single-controller
  ``np.bincount(concat(cols))`` hotness (`from_partition_dir`), so a
  host-local and a single-controller load of the same tiered layout
  produce THE SAME relabel.  mmap + fixed chunks keep RAM at
  O(num_nodes) counts, never O(E) edges."""
  from pathlib import Path
  root = Path(root)
  deg = np.zeros(num_nodes, np.int64)
  for i in range(num_parts):
    cols = np.load(root / f'part{i}' / subpath / 'cols.npy',
                   mmap_mode='r')
    for s in range(0, len(cols), _SCAN_CHUNK):
      deg += np.bincount(np.asarray(cols[s:s + _SCAN_CHUNK]),
                         minlength=num_nodes)
  return deg


def stack_partition_csr_rebucket(root, host_parts, subpath: str,
                                 node_pb, old2new_src, old2new_dst,
                                 bounds_src, counts_src, num_parts: int):
  """Host-local CSR stacking for ``by_dst`` layouts: partition dirs
  bucket edges by DST owner, so one src's out-edges are scattered
  across ALL dirs — re-bucket them by SRC owner with chunked mmap
  scans (the host-local twin of the reference's chunked re-bucketing,
  `partition/base.py:218-290`).  Pass 1 counts edges per src
  partition for the global pad width; pass 2 materializes only
  ``host_parts``.  RAM stays O(this host's edges), never O(E)."""
  from pathlib import Path
  from ..utils.topo import coo_to_csr
  root = Path(root)
  node_pb = np.asarray(node_pb)
  # pass 1 — per-src-partition edge counts over every dir
  counts_e = np.zeros(num_parts, np.int64)
  for i in range(num_parts):
    rows_f = np.load(root / f'part{i}' / subpath / 'rows.npy',
                     mmap_mode='r')
    for s in range(0, len(rows_f), _SCAN_CHUNK):
      chunk = np.asarray(rows_f[s:s + _SCAN_CHUNK])
      counts_e += np.bincount(node_pb[chunk], minlength=num_parts)
  max_edges = max(int(counts_e.max()), 1)
  max_nodes = int(counts_src.max()) if num_parts else 0
  pl = len(host_parts)
  # pass 2 — ONE more scan over the files, each chunk bucketed into
  # per-host-part accumulators (not one full scan per part: at IGBH
  # scale with P=64 that multiplies tens of GB of reads by P)
  part_of = {int(p): j for j, p in enumerate(host_parts)}
  acc = [([], [], []) for _ in range(pl)]
  for i in range(num_parts):
    gdir = root / f'part{i}' / subpath
    rows_f = np.load(gdir / 'rows.npy', mmap_mode='r')
    cols_f = np.load(gdir / 'cols.npy', mmap_mode='r')
    eids_f = np.load(gdir / 'eids.npy', mmap_mode='r')
    for s in range(0, len(rows_f), _SCAN_CHUNK):
      chunk = np.asarray(rows_f[s:s + _SCAN_CHUNK])
      owner_c = node_pb[chunk]
      cchunk = echunk = None
      for p, j in part_of.items():
        sel = owner_c == p
        if sel.any():
          if cchunk is None:
            cchunk = np.asarray(cols_f[s:s + _SCAN_CHUNK])
            echunk = np.asarray(eids_f[s:s + _SCAN_CHUNK])
          acc[j][0].append(chunk[sel])
          acc[j][1].append(cchunk[sel])
          acc[j][2].append(echunk[sel])
  indptr_s = np.zeros((pl, max_nodes + 1), np.int64)
  indices_s = np.full((pl, max_edges), -1, np.int32)
  eids_s = np.full((pl, max_edges), -1, np.int64)
  for j, p in enumerate(host_parts):
    rs, cs, es = acc[j]
    rows = np.concatenate(rs) if rs else np.empty(0, np.int64)
    cols = np.concatenate(cs) if cs else np.empty(0, np.int64)
    eids = np.concatenate(es) if es else np.empty(0, np.int64)
    local_rows = old2new_src[rows] - bounds_src[p]
    iptr, idx, eid = coo_to_csr(local_rows, old2new_dst[cols],
                                int(counts_src[p]), eids)
    indptr_s[j, :len(iptr)] = iptr
    indptr_s[j, len(iptr):] = iptr[-1]
    indices_s[j, :len(idx)] = idx
    eids_s[j, :len(eid)] = eid
  return indptr_s, indices_s, eids_s


def stack_mod_edge_features(root, host_parts, subpath: str,
                            num_parts: int, num_edges: int):
  """Host-local MOD-sharded edge-feature stacking: shard ``p`` row
  ``r`` holds edge ``r * P + p`` (`build_dist_edge_feature`
  semantics), built by scanning every partition dir's
  ``edge_feat/{feats,ids}.npy`` and materializing only the rows whose
  ``eid % P`` lands in ``host_parts`` — RAM is 1/num_hosts of the
  table while file reads stay global (the layout lives on shared
  storage, exactly like the reference's per-process `load_partition`
  reads).  Returns a `DistFeature` or None."""
  from pathlib import Path
  root = Path(root)
  part_set = {int(p): j for j, p in enumerate(host_parts)}
  pl = len(host_parts)
  rows_max = max(-(-num_edges // num_parts), 1)
  shards = None
  for i in range(num_parts):
    d = root / f'part{i}' / subpath
    if not (d / 'feats.npy').exists():
      continue
    ids = np.load(d / 'ids.npy')
    feats = np.load(d / 'feats.npy', mmap_mode='r')
    if shards is None:
      de = feats.shape[1] if feats.ndim > 1 else 1
      shards = np.zeros((pl, rows_max, de), feats.dtype)
    from .partition_book import edge_local_rows_host, edge_owner_host
    owner = edge_owner_host(ids, num_parts)
    for p, j in part_set.items():
      sel = owner == p
      if sel.any():
        vals = np.asarray(feats[sel])
        shards[j, edge_local_rows_host(ids[sel], num_parts)] = (
            vals if vals.ndim > 1 else vals[:, None])
  if shards is None:
    return None
  return DistFeature(shards, np.arange(num_parts + 1, dtype=np.int64),
                     mod_sharded=True)


def tiered_local_feature(fs: np.ndarray, counts: np.ndarray,
                         split_ratio: float, host_parts,
                         bounds) -> 'DistFeature':
  """Tier a host-local feature stack: slice each partition's hot rows
  (hottest-first after the hotness relabel) into the HBM shard and
  keep the FULL local stack as this host's cold tier.  ONE definition
  shared by the homo and hetero host-local loaders — the rounding and
  clamp must stay bit-identical to `build_dist_feature` or the
  host-local/single-controller relabel parity breaks."""
  hot_counts = hot_count(counts, split_ratio)
  hot_max = max(int(hot_counts.max()), 1)
  shards = np.zeros((len(host_parts), hot_max, fs.shape[-1]), fs.dtype)
  for j, p in enumerate(host_parts):
    shards[j, :hot_counts[p]] = fs[j, :hot_counts[p]]
  return DistFeature(shards, bounds, hot_counts=hot_counts,
                     cold_local=fs)


def stack_partition_cache(root, host_parts, subpath: str, old2new,
                          num_parts: int):
  """Host-local offline-cache-plan stacking: every partition's cache
  file is self-contained (its own REMOTE-hot rows), so each host reads
  only its partitions' files; the pad width ``C`` comes from mmap'd
  SHAPES across all partitions (the stacked arrays must agree
  globally).  Returns ``(cache_ids [pl, C], cache_rows [pl, C, D])``
  sorted by relabeled id, or ``(None, None)``."""
  from pathlib import Path
  root = Path(root)
  sizes = []
  for i in range(num_parts):
    f = root / f'part{i}' / subpath / 'cache_ids.npy'
    sizes.append(np.load(f, mmap_mode='r').shape[0] if f.exists() else 0)
  cmax = max(sizes, default=0)
  if cmax == 0:
    return None, None
  pl = len(host_parts)
  ids_out = np.full((pl, cmax), CACHE_PAD_ID, np.int32)
  rows_out = None
  for j, p in enumerate(host_parts):
    d = root / f'part{p}' / subpath
    if not (d / 'cache_ids.npy').exists():
      continue
    cid = np.load(d / 'cache_ids.npy')
    cfeat = np.load(d / 'cache_feats.npy')
    if rows_out is None:
      rows_out = np.zeros((pl, cmax, cfeat.shape[1]), cfeat.dtype)
    new = old2new[cid].astype(np.int32)
    order = np.argsort(new)
    ids_out[j, :len(cid)] = new[order]
    rows_out[j, :len(cid)] = cfeat[order]
  if rows_out is None:
    return None, None
  return ids_out, rows_out


def build_dist_graph(rows: np.ndarray, cols: np.ndarray,
                     node_pb: np.ndarray, num_nodes: int,
                     edge_ids: Optional[np.ndarray] = None,
                     num_parts: Optional[int] = None,
                     hotness: Optional[np.ndarray] = None
                     ) -> Tuple[DistGraph, np.ndarray]:
  """Relabel + shard a COO graph by a node partition book.

  Returns ``(dist_graph, old2new)`` — feed seeds/features through
  ``old2new`` to enter the relabeled id space.  Pass ``num_parts``
  explicitly when trailing partitions may be empty (the book's max
  value alone would under-count them).

  ``hotness`` (optional ``[N]``) orders rows WITHIN each partition
  hottest-first, so a tiered feature store's ``split_ratio`` keeps the
  hottest rows in HBM — the sharded analog of `sort_by_in_degree`
  (reference `data/reorder.py:19-31`).
  """
  node_pb = np.asarray(node_pb)
  if num_parts is None:
    num_parts = int(node_pb.max()) + 1 if node_pb.size else 1
  old2new, counts, bounds = relabel_by_partition(node_pb, num_parts,
                                                 hotness)

  rows_n = old2new[np.asarray(rows)]
  cols_n = old2new[np.asarray(cols)]
  if edge_ids is None:
    edge_ids = np.arange(len(rows_n), dtype=np.int64)

  # per-partition local CSR (rows local, cols global).
  max_nodes = int(counts.max()) if num_parts else 0
  owner = node_pb[np.asarray(rows)]
  max_edges = max(int(np.bincount(owner, minlength=num_parts).max()), 1)
  indptr_s = np.zeros((num_parts, max_nodes + 1), dtype=np.int64)
  indices_s = np.full((num_parts, max_edges), -1, dtype=np.int32)
  eids_s = np.full((num_parts, max_edges), -1, dtype=np.int64)
  for p in range(num_parts):
    sel = owner == p
    local_rows = rows_n[sel] - bounds[p]
    iptr, idx, eid = coo_to_csr(local_rows, cols_n[sel],
                                int(counts[p]), edge_ids[sel])
    # pad indptr by repeating the terminal value so padded local rows
    # have degree zero.
    indptr_s[p, :len(iptr)] = iptr
    indptr_s[p, len(iptr):] = iptr[-1]
    indices_s[p, :len(idx)] = idx
    eids_s[p, :len(eid)] = eid
  return DistGraph(indptr_s, indices_s, eids_s, bounds), old2new


def restack_stream_view(view, old2new: np.ndarray, bounds: np.ndarray,
                        min_edge_width: int = 0):
  """Re-shard one published streaming `GraphView` by an EXISTING
  partition book (ISSUE 14: the mesh arm of version fencing).

  The view lives in the original (old) id space; ``old2new`` and
  ``bounds`` are the dataset's frozen relabel + ownership — features,
  labels, caches and the GNS hot split are all built against them, so
  a streamed topology refresh must never move a node.  Edges are
  recovered in EVENT order (``argsort(edge_ids)`` — edge ids are the
  global event positions) and pushed through the exact
  `build_dist_graph` per-partition ``coo_to_csr`` path, so a quiesced
  streamed mesh graph is byte-identical to `DistDataset.from_full_graph`
  over the same event sequence (pinned by tests).

  ``min_edge_width`` floors the stacked indices width (the previous
  stack's width): shapes only GROW, and only to the next power of two
  — a compiled mesh step recompiles logarithmically over any growth,
  never per publish.
  """
  from ..utils.padding import next_power_of_two
  bounds = np.asarray(bounds, np.int64)
  num_parts = len(bounds) - 1
  counts = np.diff(bounds)
  max_nodes = int(counts.max()) if num_parts else 0
  order = np.argsort(np.asarray(view.edge_ids), kind='stable')
  rows_old = ptr2ind(np.asarray(view.indptr))[order]
  cols_old = np.asarray(view.indices)[order]
  eids = np.asarray(view.edge_ids)[order]
  rows_n = np.asarray(old2new)[rows_old]
  cols_n = np.asarray(old2new)[cols_old]
  from .partition_book import range_of_host
  owner = range_of_host(bounds, rows_n)
  per_part = np.bincount(owner, minlength=num_parts)
  width = max(next_power_of_two(max(int(per_part.max(initial=0)), 1)),
              int(min_edge_width))
  indptr_s = np.zeros((num_parts, max_nodes + 1), dtype=np.int64)
  indices_s = np.full((num_parts, width), -1, dtype=np.int32)
  eids_s = np.full((num_parts, width), -1, dtype=np.int64)
  for p in range(num_parts):
    sel = owner == p
    local_rows = rows_n[sel] - bounds[p]
    iptr, idx, eid = coo_to_csr(local_rows, cols_n[sel],
                                int(counts[p]), eids[sel])
    indptr_s[p, :len(iptr)] = iptr
    indptr_s[p, len(iptr):] = iptr[-1]
    indices_s[p, :len(idx)] = idx
    eids_s[p, :len(eid)] = eid
  return indptr_s, indices_s, eids_s


CACHE_PAD_ID = np.iinfo(np.int32).max  # sorts AFTER every real id


class DistFeature:
  """Stacked per-partition feature shards + optional remote-hot cache
  + optional host-DRAM cold tier.

  Attributes:
    shards: ``[P, hot_max, D]`` HBM-bound hot rows (zero where padded).
      When untier'd (``split_ratio=1``), ``hot_max = rows_max`` and the
      table is fully device-resident.
    bounds: ``[P + 1]`` — row ``r`` of shard ``p`` holds global id
      ``bounds[p] + r``.
    hot_counts: ``[P]`` hot rows per partition: id ``g`` is HBM-served
      iff ``g - bounds[owner] < hot_counts[owner]``.
    cold_host: optional ``[N, D]`` host-DRAM table addressed by
      relabeled global id — the TPU-VM analog of the reference's
      pinned-CPU UVA chunk (`csrc/cuda/unified_tensor.cu:202+`,
      `data/feature.py:174-206`): cold misses are host-gathered per
      batch and overlaid post-exchange (`DistNeighborSampler.
      _overlay_cold`).  None = fully HBM-resident.
    cold_local: optional ``[len(host_parts), max_nodes, D]`` host-DRAM
      stack holding only THIS HOST'S partitions' rows (local offsets)
      — the multi-host form of the cold tier: each host keeps
      1/num_hosts of the cold bytes and serves them at the OWNER via
      the second-gather overlay (`dist_sampler.overlay_cold_owner`).
      Mutually exclusive with ``cold_host``.
    cache_ids: optional ``[P, C]`` SORTED (relabeled) ids of remote
      rows partition ``p`` caches locally, ``CACHE_PAD_ID``-padded —
      the collective-era `cat_feature_cache`
      (`partition/base.py:606-647`): lookups hit the cache first and
      only misses ride the all_to_all.
    cache_rows: optional ``[P, C, D]`` the cached rows.
  """

  def __init__(self, shards, bounds, cache_ids=None, cache_rows=None,
               mod_sharded: bool = False, hot_counts=None,
               cold_host=None, cold_local=None,
               cache_local: bool = False):
    self.shards = np.asarray(shards)
    self.bounds = np.asarray(bounds, dtype=np.int64)
    self.hot_counts = (np.asarray(hot_counts, np.int32)
                       if hot_counts is not None
                       else np.diff(self.bounds).astype(np.int32))
    self.cold_host = (np.asarray(cold_host)
                      if cold_host is not None else None)
    self.cold_local = (np.asarray(cold_local)
                       if cold_local is not None else None)
    assert self.cold_host is None or self.cold_local is None
    self.cache_ids = (np.asarray(cache_ids, np.int32)
                      if cache_ids is not None else None)
    self.cache_rows = (np.asarray(cache_rows)
                       if cache_rows is not None else None)
    #: True = strided ownership (owner = id % P, row = id // P) —
    #: `build_dist_edge_feature`; False = range ownership by `bounds`.
    self.mod_sharded = mod_sharded
    #: True = the cache is the ISSUE 20 read-only replica set: the
    #: sampler's feature lookup treats cached rows as LOCAL (they are
    #: masked out of the exchange request and overlaid from the
    #: replica, and the attribution credits them to the diagonal).
    #: False (offline cache plans) keeps the post-exchange-overlay
    #: semantics — identical exchanged bytes.
    self.cache_local = cache_local

  @property
  def feature_dim(self) -> int:
    return self.shards.shape[-1]

  @property
  def has_cache(self) -> bool:
    return self.cache_ids is not None and self.cache_ids.shape[1] > 0

  @property
  def is_tiered(self) -> bool:
    return self.cold_host is not None or self.cold_local is not None


def build_feature_cache(cache_ids_old, cache_feats, old2new, num_parts):
  """Assemble per-partition sorted cache arrays from the offline
  layout's ``cache_ids/cache_feats`` (old id space)."""
  cmax = max((len(c) for c in cache_ids_old), default=0)
  if cmax == 0:
    return None, None
  d = next(f.shape[1] for f in cache_feats if f is not None and len(f))
  dtype = next(f.dtype for f in cache_feats if f is not None and len(f))
  ids = np.full((num_parts, cmax), CACHE_PAD_ID, np.int32)
  rows = np.zeros((num_parts, cmax, d), dtype)
  for p in range(num_parts):
    cid = np.asarray(cache_ids_old[p], np.int64)
    if not len(cid):
      continue
    new = old2new[cid].astype(np.int32)
    order = np.argsort(new)
    ids[p, :len(cid)] = new[order]
    rows[p, :len(cid)] = np.asarray(cache_feats[p])[order]
  return ids, rows


def build_replica_cache(feats_new: np.ndarray, bounds: np.ndarray,
                        hotness_new: np.ndarray, frac: float):
  """Mesh-plane `cat_feature_cache` analog (ISSUE 20): replicate the
  globally hottest rows read-only into every partition's cache so the
  PartitionBook-routed feature lookup can serve them locally.

  Each partition caches the top ``ceil(frac * N)`` hottest rows it
  does NOT own (its own rows are already local); ``hotness_new`` ranks
  in the RELABELED id space (a `DecayedSketch` export or in-degree).
  Returns ``(cache_ids [P, C] sorted CACHE_PAD_ID-padded,
  cache_rows [P, C, D])`` or ``(None, None)`` at a zero budget.
  """
  bounds = np.asarray(bounds, np.int64)
  num_parts = len(bounds) - 1
  n = int(bounds[-1])
  c = int(np.ceil(float(frac) * n))
  if c <= 0 or n == 0:
    return None, None
  feats_new = np.asarray(feats_new)
  if feats_new.ndim == 1:
    feats_new = feats_new[:, None]
  hot = np.asarray(hotness_new, np.float64)
  order = np.argsort(-hot, kind='stable')        # hottest first, stable
  ids = np.full((num_parts, c), CACHE_PAD_ID, np.int32)
  rows = np.zeros((num_parts, c, feats_new.shape[1]), feats_new.dtype)
  for p in range(num_parts):
    remote = order[(order < bounds[p]) | (order >= bounds[p + 1])][:c]
    remote = np.sort(remote)
    ids[p, :len(remote)] = remote
    rows[p, :len(remote)] = feats_new[remote]
  from ..telemetry.live import live
  live.gauge('partition.replicated_rows').set(float(c))
  return ids, rows


def replica_budget_frac(replica_frac=None) -> float:
  """Resolve the replication budget: argument wins, else the
  ``GLT_LOCALITY_REPLICA_FRAC`` knob (fraction of ALL nodes each
  device replicates; 0 = no replica cache, the default)."""
  import os
  if replica_frac is not None:
    return float(replica_frac)
  try:
    return float(os.environ.get('GLT_LOCALITY_REPLICA_FRAC', 0.0))
  except ValueError:
    return 0.0


def build_dist_feature(feats: np.ndarray, old2new: np.ndarray,
                       bounds: np.ndarray,
                       split_ratio: float = 1.0) -> DistFeature:
  """Shard a feature table by the relabeled ownership ranges.

  ``split_ratio < 1`` builds the TIERED store (VERDICT r2 item 1 /
  reference `data/feature.py:174-206` + `unified_tensor.cu:202+`):
  only the first ``ceil(split_ratio * rows)`` rows of each partition —
  the hottest, when the relabel was built with ``hotness`` — go to the
  HBM shard; the full table stays in host DRAM as the cold tier, so
  the distributed store serves tables larger than aggregate HBM.
  """
  feats = np.asarray(feats)
  if feats.ndim == 1:
    feats = feats[:, None]
  num_parts = len(bounds) - 1
  counts = np.diff(bounds)
  split_ratio = float(split_ratio)
  if not 0.0 <= split_ratio <= 1.0:
    raise ValueError(f'split_ratio must be in [0, 1], got {split_ratio}')
  tiered = split_ratio < 1.0
  hot_counts = (hot_count(counts, split_ratio)
                if tiered else counts.astype(np.int64))
  hot_max = int(hot_counts.max()) if num_parts else 0
  if tiered:
    hot_max = max(hot_max, 1)   # keep the gather shape non-degenerate
                                # at split_ratio=0 (rows stay masked)
  shards = np.zeros((num_parts, hot_max, feats.shape[1]), feats.dtype)
  reordered = np.empty_like(feats)
  reordered[old2new] = feats          # new id -> features
  for p in range(num_parts):
    shards[p, :hot_counts[p]] = (
        reordered[bounds[p]:bounds[p] + hot_counts[p]])
  return DistFeature(shards, bounds, hot_counts=hot_counts,
                     cold_host=reordered if tiered else None)


def build_dist_edge_feature(efeats: np.ndarray,
                            num_parts: int) -> DistFeature:
  """MOD-shard an edge-feature table ``[E, De]`` (indexed by GLOBAL
  edge id): shard ``p`` row ``r`` holds edge ``r * P + p``.

  Edge ids are stable through the node relabel (`build_dist_graph`
  keeps the input edge order), so no id map is needed — the collective
  analog of the reference's separate ``edge_feat_pb``
  (`distributed/dist_dataset.py:183-193`).  Mod (strided) assignment,
  not ranges, on purpose: a node's out-edges have CONSECUTIVE ids in
  the usual COO order, so range sharding would send one seed's whole
  edge set to a single owner and systematically overflow the
  capacity-bounded gather; mod sharding spreads every consecutive run
  evenly, making the balanced-share capacity assumption hold by
  construction.
  """
  efeats = np.asarray(efeats)
  if efeats.ndim == 1:
    efeats = efeats[:, None]
  e = efeats.shape[0]
  rows_max = max(-(-e // num_parts), 1)
  shards = np.zeros((num_parts, rows_max, efeats.shape[1]), efeats.dtype)
  for p in range(num_parts):
    own = efeats[p::num_parts]
    shards[p, :len(own)] = own
  return DistFeature(shards, np.arange(num_parts + 1, dtype=np.int64),
                     mod_sharded=True)


class DistDataset:
  """Sharded dataset: graph + features + labels in the relabeled space.

  Attributes:
    graph: `DistGraph`.
    node_features: `DistFeature` or None.
    node_labels: ``[P, rows_max]`` stacked label shards or None.
    edge_features: `DistFeature` MOD-sharded over GLOBAL edge ids
      (owner = eid % P; see `build_dist_edge_feature`) or None.
    old2new / new2old: id-space maps.
  """

  def __init__(self, graph: DistGraph, node_features=None, node_labels=None,
               old2new: Optional[np.ndarray] = None, edge_features=None,
               host_parts: Optional[np.ndarray] = None):
    self.graph = graph
    self.node_features = node_features
    self.node_labels = node_labels
    self.edge_features = edge_features
    self.old2new = old2new
    self.new2old = (np.argsort(old2new) if old2new is not None else None)
    #: multi-host: the partition indices THIS process materialized
    #: (stacked arrays then hold only these, in this order) — see
    #: `from_partition_dir(host_parts=...)`.  None = all partitions.
    self.host_parts = (np.asarray(host_parts, np.int64)
                       if host_parts is not None else None)
    #: placement identity ('range' | 'locality' | 'custom' |
    #: 'explicit') — benchmark artifacts record it so regression
    #: baselines never compare rows across partitioner changes.
    self.partitioner = 'explicit'
    self._partition_book = None
    #: ISSUE 15: durably re-loaded shards parked by `failover.
    #: adopt_shard`, keyed by the ORPHANED partition index.  Samplers
    #: build the adopted lane's device arrays from these payloads (the
    #: bytes that survived, not the dead owner's live memory).
    self.adopted_shards = {}

  @property
  def num_partitions(self) -> int:
    return self.graph.num_partitions

  @property
  def partition_book(self):
    """THE routing authority (ISSUE 15): one `PartitionBook` per
    dataset, shared by every sampler/loader/driver built over it so an
    adoption observed by one reader is observed by all at their next
    fence.  Version 0 (identity) compiles the pre-book programs."""
    if self._partition_book is None:
      from .partition_book import PartitionBook
      self._partition_book = PartitionBook(self.graph.bounds)
    return self._partition_book

  def attach_stream(self, stream) -> 'DistDataset':
    """Back this dataset's topology with a streaming graph (ISSUE
    14).  The stream lives in the ORIGINAL (old) id space; the
    dataset's relabel/ownership stay frozen (features, caches and the
    GNS hot split are built against them) and only the per-partition
    CSR stacks refresh.  Samplers pick the handle up at their
    dispatch/chunk seams (`DistNeighborSampler.maybe_refresh_stream`)
    — one published ``graph_version`` per dispatch, never a torn
    stack.  Single-controller only: the multi-host restack (each host
    re-sharding its own partitions) is follow-on work."""
    if self.host_parts is not None:
      raise NotImplementedError(
          'streaming refresh of a multi-host (host_parts) layout is '
          'not supported yet — each host would need to restack its '
          'own partitions from the stream')
    if self.edge_features is not None:
      raise NotImplementedError(
          'attach_stream on a dataset with edge features is not '
          'supported yet — streamed edges get eids past the frozen '
          'edge-feature shards, so collect_edge_features would '
          'gather wrong rows (growable edge-feature tiers are '
          'follow-on work)')
    if self.old2new is None:
      raise ValueError('attach_stream needs a dataset with an '
                       'old2new relabel (from_full_graph-style)')
    self.stream = stream
    view = stream.pin()
    g = self.graph
    indptr_s, indices_s, eids_s = restack_stream_view(
        view, self.old2new, g.bounds,
        min_edge_width=int(g.indices.shape[1]))
    self.graph = DistGraph(indptr_s, indices_s, eids_s, g.bounds)
    #: the version self.graph's stacks were built from — samplers
    #: seed their seam fence here so the first dispatch skips a
    #: redundant restack of the identical graph
    self.stream_version = view.version
    return self

  @classmethod
  def from_full_graph(cls, num_parts: int, rows, cols, node_feat=None,
                      node_label=None, num_nodes: Optional[int] = None,
                      node_pb: Optional[np.ndarray] = None,
                      seed: int = 0, edge_feat=None,
                      split_ratio: float = 1.0,
                      hotness: Optional[np.ndarray] = None,
                      partitioner=None,
                      replica_frac: Optional[float] = None
                      ) -> 'DistDataset':
    """In-memory partition + shard (testing & single-host path).

    ``split_ratio < 1`` tiers the node-feature store (HBM hot /
    host-DRAM cold, see `build_dist_feature`); ``hotness`` defaults to
    in-degree so the HBM tier keeps the most-gathered rows
    (`sort_by_in_degree` policy, reference `data/reorder.py:19-31`).

    ``partitioner`` (ISSUE 20) selects node placement when ``node_pb``
    is not given: ``'range'`` (default / ``GLT_PARTITIONER`` unset) is
    the historical seeded random round-robin, byte-identical to the
    pre-locality path; ``'locality'`` runs the
    `locality.locality_partition` streaming edge-cut minimizer
    (hotness-weighted when ``hotness`` — an array or a `DecayedSketch`
    — is supplied); an array is taken as a precomputed ``node_pb``
    (e.g. the offline `FrequencyPartitioner` output); a callable is
    invoked as ``partitioner(rows, cols, num_nodes, num_parts)``.
    Every mode relabels through the same `build_dist_graph` path and
    the dataset carries ``old2new``/``new2old`` so batches, labels and
    served predictions surface original ids.

    ``replica_frac > 0`` (or ``GLT_LOCALITY_REPLICA_FRAC``) builds the
    read-only replica cache (`build_replica_cache`): each device
    additionally holds the top ``ceil(frac * N)`` hottest REMOTE
    feature rows and the sampler serves them as local.
    """
    from .locality import resolve_partitioner
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    n = int(num_nodes if num_nodes is not None
            else max(rows.max(initial=-1), cols.max(initial=-1)) + 1)
    if hotness is not None and hasattr(hotness, 'score'):
      hotness = hotness.score(np.arange(n))    # DecayedSketch export
    part_identity = 'explicit'
    if node_pb is None:
      part = resolve_partitioner(partitioner)
      if isinstance(part, str) and part == 'range':
        part_identity = 'range'
        rng = np.random.default_rng(seed)
        node_pb = np.empty(n, dtype=np.int32)
        perm = rng.permutation(n)
        for p in range(num_parts):
          node_pb[perm[p::num_parts]] = p
      elif isinstance(part, str):              # 'locality'
        from .locality import locality_partition
        part_identity = 'locality'
        if hotness is None:
          hotness = np.bincount(cols, minlength=n)   # in-degree
        node_pb, _ = locality_partition(rows, cols, n, num_parts,
                                        seed=seed, hotness=hotness)
      elif callable(part):
        part_identity = 'custom'
        node_pb = np.asarray(part(rows, cols, n, num_parts))
      else:
        part_identity = 'custom'
        node_pb = part
    if split_ratio < 1.0 and hotness is None:
      hotness = np.bincount(cols, minlength=n)       # in-degree
    g, old2new = build_dist_graph(rows, cols, node_pb, n,
                                  num_parts=num_parts, hotness=hotness)
    nf = (build_dist_feature(node_feat, old2new, g.bounds,
                             split_ratio=split_ratio)
          if node_feat is not None else None)
    rep = replica_budget_frac(replica_frac)
    if nf is not None and rep > 0:
      feats = np.asarray(node_feat)
      if feats.ndim == 1:
        feats = feats[:, None]
      feats_new = np.empty_like(feats)
      feats_new[old2new] = feats
      rank = (np.asarray(hotness) if hotness is not None
              else np.bincount(cols, minlength=n))
      rank_new = np.empty(n, np.float64)
      rank_new[old2new] = rank
      cids, crows = build_replica_cache(feats_new, g.bounds, rank_new,
                                        rep)
      if cids is not None:
        nf.cache_ids, nf.cache_rows = cids, crows
        nf.cache_local = True
    nl = None
    if node_label is not None:
      # build_dist_feature preserves dtype — no float round-trip.
      lab = np.asarray(node_label)
      nl = build_dist_feature(lab, old2new, g.bounds).shards[..., 0]
    ef = (build_dist_edge_feature(edge_feat, num_parts)
          if edge_feat is not None else None)
    ds = cls(g, nf, nl, old2new, edge_features=ef)
    ds.partitioner = part_identity
    return ds

  @classmethod
  def from_partition_dir(cls, root, num_parts: Optional[int] = None,
                         split_ratio: float = 1.0,
                         host_parts=None) -> 'DistDataset':
    """Assemble from the offline partitioner's layout
    (reference `DistDataset.load`, `distributed/dist_dataset.py:77-164`).
    ``split_ratio < 1`` tiers the node-feature store (HBM hot /
    host-DRAM cold; hotness = in-degree).

    ``host_parts`` (multi-host): materialize ONLY those partitions'
    graph/feature/label tensors on this process — the others live on
    their own hosts and enter the mesh via
    `jax.make_array_from_single_device_arrays` (the sampler's
    host-local put).  At IGBH scale this is what keeps per-host RAM
    at ``1/num_hosts`` of the dataset instead of all of it.  Pass
    `multihost.host_partition_ids(mesh)`.  The host-local arm serves
    the FULL composition (reference parity `data/feature.py:174-206`
    + `partition/base.py:502-647`): tiered stores (``split_ratio <
    1`` keeps only hot rows in HBM; each host's cold rows stay in its
    own DRAM and are owner-served per batch,
    `dist_sampler.overlay_cold_owner`), edge features (mod-sharded,
    built host-locally), the offline cache plan, and ``by_dst``
    layouts (chunked re-bucketing).
    """
    if host_parts is not None:
      return cls._from_partition_dir_host_local(
          root, num_parts, split_ratio, host_parts)
    from ..partition import load_partition
    parts = []
    p0 = load_partition(root, 0)
    meta = p0['meta']
    num_parts = num_parts or meta['num_parts']
    parts = [p0] + [load_partition(root, i) for i in range(1, num_parts)]
    assert not meta['hetero'], (
        'hetero layout: use DistHeteroDataset.from_partition_dir')
    node_pb = parts[0]['node_pb'].table
    n = len(node_pb)
    rows = np.concatenate([p['graph'].edge_index[0] for p in parts])
    cols = np.concatenate([p['graph'].edge_index[1] for p in parts])
    eids = np.concatenate([p['graph'].eids for p in parts])
    hotness = (np.bincount(cols, minlength=n) if split_ratio < 1.0
               else None)
    g, old2new = build_dist_graph(rows, cols, node_pb, n, edge_ids=eids,
                                  num_parts=num_parts, hotness=hotness)
    nf = None
    if parts[0]['node_feat'] is not None:
      d = parts[0]['node_feat'].feats.shape[1]
      feats = np.zeros((n, d), parts[0]['node_feat'].feats.dtype)
      for p in parts:
        feats[p['node_feat'].ids] = p['node_feat'].feats
      nf = build_dist_feature(feats, old2new, g.bounds,
                              split_ratio=split_ratio)
      # remote-hot cache planned by the partitioner (cache_ratio /
      # FrequencyPartitioner): served locally, misses ride all_to_all.
      cache_ids = [p['node_feat'].cache_ids
                   if p['node_feat'].cache_ids is not None else []
                   for p in parts]
      cache_feats = [p['node_feat'].cache_feats for p in parts]
      cids, crows = build_feature_cache(cache_ids, cache_feats, old2new,
                                        num_parts)
      nf.cache_ids, nf.cache_rows = cids, crows
    nl = None
    if parts[0]['node_label'] is not None:
      lab0, ids0 = parts[0]['node_label']
      labels = np.zeros((n,), lab0.dtype)
      for p in parts:
        lab, ids = p['node_label']
        labels[ids] = lab
      nl = build_dist_feature(labels, old2new, g.bounds).shards[..., 0]
    ef = None
    if parts[0].get('edge_feat') is not None:
      e = len(rows)
      d = parts[0]['edge_feat'].feats.shape[1]
      efeats = np.zeros((e, d), parts[0]['edge_feat'].feats.dtype)
      for p in parts:
        efeats[p['edge_feat'].ids] = p['edge_feat'].feats
      ef = build_dist_edge_feature(efeats, num_parts)
    return cls(g, nf, nl, old2new, edge_features=ef)

  @classmethod
  def _from_partition_dir_host_local(cls, root, num_parts, split_ratio,
                                     host_parts) -> 'DistDataset':
    """Materialize only ``host_parts`` (see `from_partition_dir`).

    Global quantities (relabel, bounds, padding widths, hotness) come
    from the tiny per-layout metadata — ``node_pb.npy``, chunked mmap
    scans, and mmap'd array SHAPES — never from other hosts' tensors.
    """
    import json as _json
    from pathlib import Path
    root = Path(root)
    with open(root / 'META.json') as f:
      meta = _json.load(f)
    if meta['hetero']:
      raise ValueError(
          'hetero layout: use DistHeteroDataset.from_partition_dir')
    num_parts = num_parts or meta['num_parts']
    host_parts = np.asarray(host_parts, np.int64)
    node_pb = np.load(root / 'node_pb.npy')
    # the relabel must MATCH a single-controller load of the same
    # (layout, split_ratio): tiered loads order rows within each
    # partition by in-degree hotness, computed here by chunked scan
    hotness = (partition_in_degree(root, 'graph', len(node_pb),
                                   num_parts)
               if split_ratio < 1.0 else None)
    old2new, counts, bounds = relabel_by_partition(node_pb, num_parts,
                                                   hotness)
    max_nodes = int(counts.max()) if num_parts else 0
    if meta.get('edge_assign', 'by_src') == 'by_src':
      indptr_s, indices_s, eids_s = stack_partition_csr(
          root, host_parts, 'graph', old2new, old2new, bounds, counts,
          num_parts)
    else:
      indptr_s, indices_s, eids_s = stack_partition_csr_rebucket(
          root, host_parts, 'graph', node_pb, old2new, old2new, bounds,
          counts, num_parts)
    feats_s = scatter_partition_rows(root, host_parts, 'node_feat',
                                     'feats', old2new, bounds,
                                     max_nodes)
    labels_s = scatter_partition_rows(root, host_parts, 'node_label',
                                      'labels', old2new, bounds,
                                      max_nodes)
    g = DistGraph(indptr_s, indices_s, eids_s, bounds)
    nf = None
    if feats_s is not None:
      if split_ratio < 1.0:
        nf = tiered_local_feature(feats_s, counts, split_ratio,
                                  host_parts, bounds)
      else:
        nf = DistFeature(feats_s, bounds)
      cids, crows = stack_partition_cache(root, host_parts, 'node_feat',
                                          old2new, num_parts)
      nf.cache_ids, nf.cache_rows = cids, crows
    ef = stack_mod_edge_features(root, host_parts, 'edge_feat',
                                 num_parts, int(meta['num_edges']))
    return cls(g, nf, labels_s, old2new, edge_features=ef,
               host_parts=host_parts)
