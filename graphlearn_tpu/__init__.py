"""graphlearn_tpu: a TPU-native GNN data-plane framework.

Brand-new JAX/XLA/Pallas re-design with the capability set of
graphlearn-for-pytorch (reference mounted at /root/reference): device
graph sampling, tiered feature storage, PyG-vocabulary loaders, and a
distributed (ICI-collective) runtime — built for TPU from the ground
up: static shapes + masks, counter-based PRNG, pjit/shard_map
parallelism instead of RPC.
"""
from . import data, loader, ops, sampler, telemetry, utils
from .typing import (EdgeType, NodeType, RangePartitionBook, Split,
                     TablePartitionBook, as_str, reverse_edge_type)

__version__ = '0.1.0'
