"""Heterogeneous GNN models over `HeteroBatch` pytrees.

TPU counterparts of the PyG models the reference's hetero examples
train: R-GCN/RGAT/RSAGE (`examples/igbh/rgnn.py`) and HGT
(`examples/hetero/train_hgt_mag.py`).  Convention matches the hetero
batch emission: ``edge_index_dict[(a, rel, b)][0]`` indexes type-``a``
nodes (message sources), ``[1]`` indexes type-``b`` nodes (targets).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..typing import EdgeType, NodeType, as_str
from .conv import SAGEConv, segment_mean


class _NamedConv(nn.Module):
  """Binds a factory-made conv under an explicit etype-keyed scope, so
  params never depend on positional auto-naming (which shifts when a
  batch lacks some edge type)."""
  factory: Callable[[], nn.Module]

  @nn.compact
  def __call__(self, x, edge_index, edge_mask):
    return self.factory()(x, edge_index, edge_mask)


class HeteroConv(nn.Module):
  """Applies a per-edge-type conv and aggregates per target type.

  Two modes (reference analog: PyG's ``HeteroConv`` the examples wrap,
  `examples/igbh/rgnn.py`):

    * default (``make_conv=None``): per-etype linear message +
      mean-aggregation, plus a per-type self term — the RGCN flavor;
    * ``make_conv`` given: each edge type gets a fresh conv from the
      factory (e.g. ``lambda: GATConv(d, heads=h)`` for RGAT), run
      bipartite via source-offset concatenation; no extra self term
      (the conv's own self path applies, PyG semantics).

  Args:
    etypes: edge types to convolve.
    out_features: per-type output width (factory convs must produce
      this width too — e.g. ``GATConv(d // heads, heads=heads)``).
    aggr: cross-etype aggregation into a target type ('sum'/'mean').
    make_conv: optional factory of homogeneous convs with signature
      ``conv(x, edge_index, edge_mask)``.
  """
  etypes: Tuple[EdgeType, ...]
  out_features: int
  aggr: str = 'sum'
  make_conv: Optional[Callable[[], nn.Module]] = None
  dtype: Optional[jnp.dtype] = None   # compute dtype; params stay f32

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict=None):
    if self.make_conv is not None and self.dtype is not None:
      # the factory owns its convs' compute dtype; accepting both
      # would leave the dominant per-etype matmuls silently f32
      raise ValueError(
          'HeteroConv(make_conv=..., dtype=...): set the compute dtype '
          'inside the factory instead, e.g. '
          'lambda: SAGEConv(d, dtype=jnp.bfloat16)')
    out: Dict[NodeType, Any] = {}
    counts: Dict[NodeType, int] = {}
    for et in self.etypes:
      a, _, b = et
      if a not in x_dict or b not in x_dict:
        continue
      if et in edge_index_dict:
        ei = edge_index_dict[et]
        em = (edge_mask_dict or {}).get(et)
      else:
        # etype configured but absent from this batch: run the conv on
        # an empty edge set so the param structure stays a function of
        # `self.etypes`, never of batch content (otherwise a batch
        # missing one etype would init/apply a different pytree).
        ei = jnp.zeros((2, 0), jnp.int32)
        em = jnp.zeros((0,), jnp.bool_)
      na, nb = x_dict[a].shape[0], x_dict[b].shape[0]
      src, dst = ei[0], ei[1]
      if self.make_conv is not None:
        conv = _NamedConv(self.make_conv, name=f'conv_{as_str(et)}')
        if a == b:
          # self-relation: the conv runs directly — no concat, no
          # doubled node dimension for the usually-largest relation.
          agg = conv(x_dict[a], ei, em)
        else:
          # bipartite via concatenation: [x_b; x_a] so dst ids are
          # unchanged and src ids shift by nb; any homogeneous conv
          # then runs unmodified, and rows [0, nb) are the dst output.
          xa, xb = x_dict[a], x_dict[b]
          if xa.shape[-1] != xb.shape[-1]:
            raise ValueError(
                f'HeteroConv(make_conv=...) needs equal feature widths '
                f'for {et}: {xa.shape[-1]} vs {xb.shape[-1]} — project '
                f'per-type inputs first (e.g. a Dense per node type)')
          xcat = jnp.concatenate([xb, xa], axis=0)
          src2 = jnp.clip(src, 0, na - 1) + nb
          ei2 = jnp.stack([src2, dst])
          agg = conv(xcat, ei2, em)[:nb]
      else:
        msg = nn.Dense(self.out_features, use_bias=False,
                       dtype=self.dtype, name=f'lin_{as_str(et)}')(
                           x_dict[a][jnp.clip(src, 0, na - 1)])
        agg = segment_mean(msg, dst, nb, em)
      out[b] = out.get(b, 0) + agg
      counts[b] = counts.get(b, 0) + 1
    res = {}
    for nt, x in x_dict.items():
      if self.make_conv is not None:
        # factory mode: conv output only; untouched types pass through
        # a projection so widths stay consistent across layers.
        if nt in out:
          h = out[nt]
          if self.aggr == 'mean':
            h = h / counts[nt]
          res[nt] = h
        else:
          res[nt] = nn.Dense(self.out_features, dtype=self.dtype,
                             name=f'lin_self_{nt}')(x)
        continue
      self_term = nn.Dense(self.out_features, dtype=self.dtype,
                           name=f'lin_self_{nt}')(x)
      if nt in out:
        h = out[nt]
        if self.aggr == 'mean':
          h = h / counts[nt]
        res[nt] = self_term + h
      else:
        res[nt] = self_term
    return res


class RGCN(nn.Module):
  """Relational GCN stack — the reference's hetero workhorse
  (`examples/igbh/rgnn.py` RGCN/RSAGE flavor)."""
  etypes: Tuple[EdgeType, ...]
  hidden_features: int
  out_features: int
  num_layers: int = 2
  dropout: float = 0.0
  target_ntype: Optional[NodeType] = None
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict=None, *,
               train: bool = False):
    h = x_dict
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      feats = self.out_features if last else self.hidden_features
      h = HeteroConv(self.etypes, feats, dtype=self.dtype,
                     name=f'conv{i}')(h, edge_index_dict, edge_mask_dict)
      if not last:
        h = {nt: nn.relu(v) for nt, v in h.items()}
        if self.dropout > 0:
          h = {nt: nn.Dropout(self.dropout, deterministic=not train)(v)
               for nt, v in h.items()}
    if self.dtype is not None:
      h = {nt: v.astype(jnp.float32) for nt, v in h.items()}
    if self.target_ntype is not None:
      return h[self.target_ntype]
    return h


class HGTConv(nn.Module):
  """Heterogeneous Graph Transformer convolution.

  Type-specific Q/K/V projections + per-edge-type relation transforms
  and priors, masked segment-softmax attention per target node — the
  model of reference `examples/hetero/train_hgt_mag.py:102-121`
  (there via PyG's HGTConv; re-designed here for padded batches).
  """
  ntypes: Tuple[NodeType, ...]
  etypes: Tuple[EdgeType, ...]
  out_features: int
  heads: int = 2
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict=None):
    h, f = self.heads, self.out_features // self.heads
    assert self.out_features % self.heads == 0
    q_dict, k_dict, v_dict = {}, {}, {}
    for nt in self.ntypes:
      if nt not in x_dict:
        continue
      n = x_dict[nt].shape[0]
      q_dict[nt] = nn.Dense(h * f, dtype=self.dtype,
                           name=f'q_{nt}')(x_dict[nt]).reshape(
          n, h, f)
      k_dict[nt] = nn.Dense(h * f, dtype=self.dtype,
                           name=f'k_{nt}')(x_dict[nt]).reshape(
          n, h, f)
      v_dict[nt] = nn.Dense(h * f, dtype=self.dtype,
                           name=f'v_{nt}')(x_dict[nt]).reshape(
          n, h, f)

    # accumulate per-target-type attention numerators/denominators
    agg = {nt: 0.0 for nt in q_dict}
    den = {nt: 0.0 for nt in q_dict}
    for et in self.etypes:
      if et not in edge_index_dict:
        continue
      a, _, b = et
      if a not in k_dict or b not in q_dict:
        continue
      ei = edge_index_dict[et]
      em = (edge_mask_dict or {}).get(et)
      na, nb = k_dict[a].shape[0], q_dict[b].shape[0]
      src = jnp.clip(ei[0], 0, na - 1)
      dst = ei[1]
      valid = em if em is not None else (dst >= 0)
      dsafe = jnp.where(valid, dst, nb)
      w_att = self.param(f'w_att_{as_str(et)}',
                         nn.initializers.glorot_uniform(), (h, f, f))
      w_msg = self.param(f'w_msg_{as_str(et)}',
                         nn.initializers.glorot_uniform(), (h, f, f))
      prior = self.param(f'prior_{as_str(et)}', nn.initializers.ones, (h,))
      k = jnp.einsum('ehf,hfg->ehg', k_dict[a][src],
                     w_att.astype(k_dict[a].dtype))
      v = jnp.einsum('ehf,hfg->ehg', v_dict[a][src],
                     w_msg.astype(v_dict[a].dtype))
      q = q_dict[b][jnp.clip(dst, 0, nb - 1)]
      score = ((q * k).sum(-1).astype(jnp.float32)
               * prior[None, :] / jnp.sqrt(f))         # [E, h]
      score = jnp.where(valid[:, None], score, -jnp.inf)
      smax = jax.ops.segment_max(score, dsafe, num_segments=nb)
      smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
      ex = jnp.where(valid[:, None],
                     jnp.exp(score - smax[jnp.clip(dst, 0, nb - 1)]), 0.0)
      num = jax.ops.segment_sum(
          (ex.astype(v.dtype)[:, :, None] * v).reshape(-1, h * f), dsafe,
          num_segments=nb).reshape(nb, h, f)
      agg[b] = agg[b] + num
      den[b] = den[b] + jax.ops.segment_sum(ex, dsafe, num_segments=nb)

    out = {}
    for nt in q_dict:
      n = x_dict[nt].shape[0]
      if isinstance(agg[nt], float):
        out[nt] = nn.Dense(self.out_features, dtype=self.dtype,
                           name=f'skip_{nt}')(x_dict[nt])
        continue
      att = agg[nt] / jnp.maximum(den[nt], 1e-16)[:, :, None]
      att = att.reshape(n, h * f)
      out[nt] = (nn.Dense(self.out_features, dtype=self.dtype,
                          name=f'out_{nt}')(nn.gelu(att))
          + nn.Dense(self.out_features, dtype=self.dtype,
                     name=f'skip_{nt}')(x_dict[nt]))
    return out


class HGT(nn.Module):
  """HGT stack with a final target-type head."""
  ntypes: Tuple[NodeType, ...]
  etypes: Tuple[EdgeType, ...]
  hidden_features: int
  out_features: int
  num_layers: int = 2
  heads: int = 2
  target_ntype: Optional[NodeType] = None
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x_dict, edge_index_dict, edge_mask_dict=None, *,
               train: bool = False):
    h = {nt: nn.Dense(self.hidden_features, dtype=self.dtype,
                      name=f'in_{nt}')(x)
         for nt, x in x_dict.items()}
    for i in range(self.num_layers):
      h = HGTConv(self.ntypes, self.etypes, self.hidden_features,
                  self.heads, dtype=self.dtype, name=f'conv{i}')(
                      h, edge_index_dict, edge_mask_dict)
      h = {nt: nn.relu(v) for nt, v in h.items()}
    if self.target_ntype is not None:
      out = nn.Dense(self.out_features, dtype=self.dtype,
                     name='head')(h[self.target_ntype])
      return (out.astype(jnp.float32) if self.dtype is not None else out)
    out = {nt: nn.Dense(self.out_features, dtype=self.dtype,
                        name=f'head_{nt}')(v)
           for nt, v in h.items()}
    if self.dtype is not None:
      out = {nt: v.astype(jnp.float32) for nt, v in out.items()}
    return out
