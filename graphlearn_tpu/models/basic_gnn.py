"""Stacked GNN models over padded batches.

The TPU counterparts of the PyG models the reference's examples train
(GraphSAGE: `examples/train_sage_ogbn_products.py`; GAT/GCN variants in
`examples/`).  Each model is a flax module whose ``__call__`` takes
``(x, edge_index, edge_mask)`` — the `Batch` pytree fields — and
returns per-node embeddings/logits over the static node table.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .conv import GINConv, GATConv, GCNConv, SAGEConv


class BasicGNN(nn.Module):
  """L-layer stack: conv → relu → dropout, last layer linear."""
  hidden_features: int
  out_features: int
  num_layers: int = 2
  dropout: float = 0.0
  aggr: str = 'mean'
  dtype: Optional[jnp.dtype] = None   # compute dtype (bfloat16 puts
                                      # the matmuls on the MXU at half
                                      # width; params/outputs stay f32)

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    raise NotImplementedError

  @nn.compact
  def __call__(self, x, edge_index, edge_mask=None, *,
               edge_weight=None, train: bool = False):
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      out = self.out_features if last else self.hidden_features
      conv = self.make_conv(out, i)
      if edge_weight is not None:
        # GNS 1/q importance weights (Batch.metadata['edge_weight']):
        # only convs that define an unbiased weighted aggregation
        # accept them (SAGEConv) — passing to others raises loudly
        # rather than silently dropping the correction
        x = conv(x, edge_index, edge_mask, edge_weight=edge_weight)
      else:
        x = conv(x, edge_index, edge_mask)
      if not last:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x.astype(jnp.float32) if self.dtype is not None else x


class GraphSAGE(BasicGNN):
  """The flagship model (reference flagship example
  `examples/train_sage_ogbn_products.py`: 3 layers, hidden 256)."""

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    return SAGEConv(out_features, aggr=self.aggr, dtype=self.dtype,
                    name=f'conv{idx}')


class GCN(BasicGNN):

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    return GCNConv(out_features, dtype=self.dtype, name=f'conv{idx}')


class GIN(BasicGNN):
  """GIN stack (sum aggregator + per-layer MLP) — the
  expressiveness-maximal member of the standard zoo."""

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    return GINConv(out_features, hidden_features=self.hidden_features,
                   train_eps=True, dtype=self.dtype, name=f'conv{idx}')


class GAT(BasicGNN):
  heads: int = 4

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    last = idx == self.num_layers - 1
    return GATConv(out_features if last else out_features // self.heads,
                   heads=self.heads, concat=not last, dtype=self.dtype,
                   name=f'conv{idx}')


class DGCNN(nn.Module):
  """Deep Graph CNN: sort-pooling + 1-D convolutions.

  The classifier the reference's SEAL example trains (its
  `examples/seal_link_pred.py` uses PyG's DGCNN: stacked tanh-GCN
  layers, concatenate all layer outputs, SortPool the top ``k`` nodes
  by the last 1-wide layer's value, then Conv1d -> MLP).  TPU
  re-design: the pool is a masked top-k (static ``k``) instead of a
  dynamic-size sort, the "kernel = total-width, stride = total-width"
  Conv1d of the paper is the equivalent per-node width-1 convolution
  over the ``[k, D]`` sequence, and everything keeps static shapes.

  Call with node features (or label embeddings), padded local COO and
  masks; returns ``[out_features]`` graph-level logits.
  """
  hidden_features: int = 32
  out_features: int = 2
  num_layers: int = 3
  k: int = 30
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x, edge_index, edge_mask=None, node_mask=None):
    if node_mask is None:
      node_mask = jnp.ones((x.shape[0],), bool)
    hs = []
    h = x
    for i in range(self.num_layers):
      h = jnp.tanh(GCNConv(self.hidden_features, dtype=self.dtype,
                           name=f'conv{i}')(h, edge_index, edge_mask))
      hs.append(h)
    # final 1-wide layer provides the canonical sort key
    h = jnp.tanh(GCNConv(1, dtype=self.dtype,
                         name=f'conv{self.num_layers}')(
                             h, edge_index, edge_mask))
    hs.append(h)
    hcat = jnp.concatenate(hs, axis=-1)                   # [n, D]
    sort_key = jnp.where(node_mask, h[:, 0], -jnp.inf)
    top = jax.lax.top_k(sort_key, min(self.k, x.shape[0]))[1]
    valid = sort_key[top] > -jnp.inf
    pooled = jnp.where(valid[:, None], hcat[top], 0.0)    # [k, D]
    if pooled.shape[0] < self.k:                          # tiny graphs
      pooled = jnp.concatenate(
          [pooled, jnp.zeros((self.k - pooled.shape[0], pooled.shape[1]),
                             pooled.dtype)])
    seq = pooled[None]                                    # [1, k, D]
    z = nn.relu(nn.Conv(16, kernel_size=(1,), dtype=self.dtype,
                        name='conv1d_a')(seq))
    if z.shape[1] >= 2:
      z = nn.max_pool(z, window_shape=(2,), strides=(2,))
    # kernel clamps for small k so the VALID conv never emits length 0
    z = nn.relu(nn.Conv(32, kernel_size=(min(5, z.shape[1]),),
                        padding='VALID', dtype=self.dtype,
                        name='conv1d_b')(z))
    z = z.reshape(1, -1)
    z = nn.relu(nn.Dense(128, dtype=self.dtype)(z))
    out = nn.Dense(self.out_features, dtype=self.dtype)(z)[0]
    return out.astype(jnp.float32) if self.dtype is not None else out
