"""Stacked GNN models over padded batches.

The TPU counterparts of the PyG models the reference's examples train
(GraphSAGE: `examples/train_sage_ogbn_products.py`; GAT/GCN variants in
`examples/`).  Each model is a flax module whose ``__call__`` takes
``(x, edge_index, edge_mask)`` — the `Batch` pytree fields — and
returns per-node embeddings/logits over the static node table.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from .conv import GATConv, GCNConv, SAGEConv


class BasicGNN(nn.Module):
  """L-layer stack: conv → relu → dropout, last layer linear."""
  hidden_features: int
  out_features: int
  num_layers: int = 2
  dropout: float = 0.0
  aggr: str = 'mean'
  dtype: Optional[jnp.dtype] = None   # compute dtype (bfloat16 puts
                                      # the matmuls on the MXU at half
                                      # width; params/outputs stay f32)

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    raise NotImplementedError

  @nn.compact
  def __call__(self, x, edge_index, edge_mask=None, *, train: bool = False):
    for i in range(self.num_layers):
      last = i == self.num_layers - 1
      out = self.out_features if last else self.hidden_features
      x = self.make_conv(out, i)(x, edge_index, edge_mask)
      if not last:
        x = nn.relu(x)
        if self.dropout > 0:
          x = nn.Dropout(self.dropout, deterministic=not train)(x)
    return x.astype(jnp.float32) if self.dtype is not None else x


class GraphSAGE(BasicGNN):
  """The flagship model (reference flagship example
  `examples/train_sage_ogbn_products.py`: 3 layers, hidden 256)."""

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    return SAGEConv(out_features, aggr=self.aggr, dtype=self.dtype,
                    name=f'conv{idx}')


class GCN(BasicGNN):

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    return GCNConv(out_features, dtype=self.dtype, name=f'conv{idx}')


class GAT(BasicGNN):
  heads: int = 4

  def make_conv(self, out_features: int, idx: int) -> nn.Module:
    last = idx == self.num_layers - 1
    return GATConv(out_features if last else out_features // self.heads,
                   heads=self.heads, concat=not last, dtype=self.dtype,
                   name=f'conv{idx}')
