"""Message-passing convolutions on padded COO batches (flax).

The reference deliberately leaves model compute to PyG
(`README.md` "Architecture Overview"); its examples train PyG's
``SAGEConv``/``GATConv``/HGT on the batches GLT loads.  A standalone
TPU framework has no PyG to lean on, so the model family lives here —
designed for the padding contract: edges are ``[2, E]`` local COO with
-1 masked slots, aggregation is `segment_sum` over static-size node
tables (XLA lowers this to fused one-hot matmuls / scatter on the MXU;
no atomics, no dynamic shapes).

Edge direction follows the loader's transposed emission
(reference `sampler/neighbor_sampler.py:159-166`): ``edge_index[0]`` is
the message *source* (sampled neighbor), ``edge_index[1]`` the
*target* (seed side) — i.e. messages flow src→dst like PyG.
"""
from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def segment_mean(data: jax.Array, segment_ids: jax.Array,
                 num_segments: int, mask: Optional[jax.Array] = None,
                 weights: Optional[jax.Array] = None) -> jax.Array:
  """Masked mean-aggregation of edge messages into node slots.

  Invalid edges (mask False or negative target) are routed to segment
  ``num_segments`` which is out of range and therefore dropped by XLA's
  segment_sum — the standard static-shape trick.

  ``weights`` (``[E]``, the GNS 1/q importance weights from
  ``Batch.metadata['edge_weight']``) scale the NUMERATOR only while
  the denominator stays the valid-edge count: the estimator is
  ``Σ_j w_j·x_j / k``, exactly the form `ops.gns` proves unbiased for
  the uniform neighbor mean under ANY sampling bias (the weights
  average to 1 in expectation).  ``weights=None`` is bit-identical to
  the unweighted path.
  """
  if mask is not None:
    segment_ids = jnp.where(mask, segment_ids, num_segments)
  else:
    segment_ids = jnp.where(segment_ids >= 0, segment_ids, num_segments)
  if weights is not None:
    data = data * weights.astype(data.dtype)[:, None]
  tot = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
  # count in f32: low-precision ones (bf16) saturate near 256 under
  # scatter-add, corrupting hub-node means
  cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), jnp.float32),
                            segment_ids, num_segments=num_segments)
  mean = tot.astype(jnp.float32) / jnp.maximum(cnt, 1.0)[:, None]
  return mean.astype(data.dtype)


def segment_max(data: jax.Array, segment_ids: jax.Array,
                num_segments: int, mask: Optional[jax.Array] = None
                ) -> jax.Array:
  if mask is not None:
    segment_ids = jnp.where(mask, segment_ids, num_segments)
  out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
  return jnp.where(jnp.isfinite(out), out, 0.0)


def segment_softmax(e: jax.Array, dst: jax.Array, num_segments: int,
                    valid: jax.Array) -> jax.Array:
  """Masked per-target softmax over edge scores ``e`` ``[E, h]`` —
  THE attention normalizer (GAT/GATv2 share it): route invalid edges
  out of range, subtract the per-target max, exp, normalize."""
  dsafe = jnp.where(valid, dst, num_segments)
  dc = jnp.clip(dst, 0, num_segments - 1)
  e = jnp.where(valid[:, None], e, -jnp.inf)
  emax = jax.ops.segment_max(e, dsafe, num_segments=num_segments)
  emax = jnp.where(jnp.isfinite(emax), emax, 0.0)
  ex = jnp.where(valid[:, None], jnp.exp(e - emax[dc]), 0.0)
  denom = jax.ops.segment_sum(ex, dsafe, num_segments=num_segments)
  return ex / jnp.maximum(denom[dc], 1e-16)


def _attention_aggregate(z_src_sel: jax.Array, w: jax.Array,
                         dst: jax.Array, valid: jax.Array, n: int,
                         heads: int, features: int,
                         concat: bool) -> jax.Array:
  """Shared GAT/GATv2 tail: weight edge messages by the softmaxed
  scores, scatter into node slots, merge heads."""
  dsafe = jnp.where(valid, dst, n)
  msg = z_src_sel * w.astype(z_src_sel.dtype)[:, :, None]  # [E, h, f]
  agg = jax.ops.segment_sum(msg.reshape(-1, heads * features), dsafe,
                            num_segments=n).reshape(n, heads, features)
  if concat:
    return agg.reshape(n, heads * features)
  return agg.mean(axis=1)


class SAGEConv(nn.Module):
  """GraphSAGE convolution (mean aggregator).

  ``out[v] = W_l · x[v] + W_r · mean_{u→v} x[u]`` — the layer the
  reference's flagship examples use via PyG
  (`examples/train_sage_ogbn_products.py`).

  ``edge_weight`` threads the GNS per-edge 1/q importance weights
  (``Batch.metadata['edge_weight']``, PR 10) into the aggregation so
  cache-biased sampling stays unbiased END TO END at the model, not
  just the estimator (mean: weighted numerator over valid-count
  denominator; sum: weighted sum).  None = the unweighted path,
  bit-identical to before.
  """
  out_features: int
  use_bias: bool = True
  aggr: str = 'mean'
  dtype: Optional[jnp.dtype] = None   # compute dtype (e.g. bfloat16
                                      # for the MXU); params stay f32

  @nn.compact
  def __call__(self, x: jax.Array, edge_index: jax.Array,
               edge_mask: Optional[jax.Array] = None,
               edge_weight: Optional[jax.Array] = None) -> jax.Array:
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    src, dst = edge_index[0], edge_index[1]
    msg = x[jnp.clip(src, 0, n - 1)]
    if self.aggr == 'mean':
      agg = segment_mean(msg, dst, n, edge_mask, weights=edge_weight)
    elif self.aggr == 'max':
      if edge_weight is not None:
        raise ValueError('edge_weight has no unbiased meaning under '
                         "max aggregation — use aggr='mean'/'sum' "
                         'with GNS importance weights')
      agg = segment_max(msg, dst, n, edge_mask)
    elif self.aggr == 'sum':
      if edge_weight is not None:
        msg = msg * edge_weight.astype(msg.dtype)[:, None]
      seg = jnp.where(edge_mask, dst, n) if edge_mask is not None else dst
      agg = jax.ops.segment_sum(msg, seg, num_segments=n)
    else:
      raise ValueError(f'Unknown aggr {self.aggr!r}')
    out = (nn.Dense(self.out_features, use_bias=self.use_bias,
                    dtype=self.dtype, name='lin_self')(x)
           + nn.Dense(self.out_features, use_bias=False,
                      dtype=self.dtype, name='lin_neigh')(agg))
    return out


class GCNConv(nn.Module):
  """Graph convolution with symmetric degree normalization (masked)."""
  out_features: int
  use_bias: bool = True
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x: jax.Array, edge_index: jax.Array,
               edge_mask: Optional[jax.Array] = None) -> jax.Array:
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    src, dst = edge_index[0], edge_index[1]
    valid = edge_mask if edge_mask is not None else (dst >= 0)
    ssafe = jnp.where(valid, src, n)
    dsafe = jnp.where(valid, dst, n)
    # degrees count in f32 (bf16 scatter-add saturates near 256)
    ones = valid.astype(jnp.float32)
    deg_in = jax.ops.segment_sum(ones, dsafe, num_segments=n) + 1.0
    deg_out = jax.ops.segment_sum(ones, ssafe, num_segments=n) + 1.0
    w = (jax.lax.rsqrt(deg_out)[jnp.clip(src, 0, n - 1)]
         * jax.lax.rsqrt(deg_in)[jnp.clip(dst, 0, n - 1)])
    h = nn.Dense(self.out_features, use_bias=self.use_bias,
                 dtype=self.dtype)(x)
    msg = h[jnp.clip(src, 0, n - 1)] * w.astype(h.dtype)[:, None]
    agg = jax.ops.segment_sum(msg, dsafe, num_segments=n)
    # self loop with 1/deg normalization
    self_w = jax.lax.rsqrt(deg_in) * jax.lax.rsqrt(deg_out)
    return agg + h * self_w.astype(h.dtype)[:, None]


class GINConv(nn.Module):
  """Graph isomorphism convolution (sum aggregator + MLP).

  ``out[v] = MLP((1 + eps) * x[v] + sum_{u→v} x[u])`` — the
  expressiveness-maximal aggregator of the standard zoo (Xu et al.);
  masked edges route to the out-of-range segment like every conv
  here.  ``train_eps`` learns the self-weight; otherwise eps stays a
  constant.
  """
  out_features: int
  hidden_features: Optional[int] = None
  eps: float = 0.0
  train_eps: bool = False
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x: jax.Array, edge_index: jax.Array,
               edge_mask: Optional[jax.Array] = None) -> jax.Array:
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    src, dst = edge_index[0], edge_index[1]
    valid = edge_mask if edge_mask is not None else (dst >= 0)
    dsafe = jnp.where(valid, dst, n)
    msg = x[jnp.clip(src, 0, n - 1)]
    agg = jax.ops.segment_sum(msg, dsafe, num_segments=n)
    if self.train_eps:
      eps = self.param('eps', nn.initializers.constant(self.eps),
                       ()).astype(x.dtype)
    else:
      eps = self.eps
    h = (1.0 + eps) * x + agg
    hidden = self.hidden_features or self.out_features
    h = nn.Dense(hidden, dtype=self.dtype, name='mlp_0')(h)
    h = nn.relu(h)
    return nn.Dense(self.out_features, dtype=self.dtype, name='mlp_1')(h)


class GATConv(nn.Module):
  """Graph attention convolution (masked softmax over incoming edges)."""
  out_features: int
  heads: int = 1
  concat: bool = True
  negative_slope: float = 0.2
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x: jax.Array, edge_index: jax.Array,
               edge_mask: Optional[jax.Array] = None) -> jax.Array:
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    h, f = self.heads, self.out_features
    src, dst = edge_index[0], edge_index[1]
    valid = edge_mask if edge_mask is not None else (dst >= 0)
    z = nn.Dense(h * f, use_bias=False,
                 dtype=self.dtype)(x).reshape(n, h, f)
    a_src = self.param('att_src', nn.initializers.glorot_uniform(),
                       (h, f))
    a_dst = self.param('att_dst', nn.initializers.glorot_uniform(),
                       (h, f))
    a_src = a_src.astype(z.dtype)
    a_dst = a_dst.astype(z.dtype)
    alpha_src = (z * a_src[None]).sum(-1).astype(jnp.float32)  # [n, h]
    alpha_dst = (z * a_dst[None]).sum(-1).astype(jnp.float32)
    sc = jnp.clip(src, 0, n - 1)
    e = nn.leaky_relu(alpha_src[sc] + alpha_dst[jnp.clip(dst, 0, n - 1)],
                      self.negative_slope)          # [E, h]
    w = segment_softmax(e, dst, n, valid)
    return _attention_aggregate(z[sc], w, dst, valid, n, h, f,
                                self.concat)


class GATv2Conv(nn.Module):
  """GATv2 attention (Brody et al.): the score applies the nonlinearity
  BEFORE the attention vector — ``e(u, v) = a^T leaky_relu(W_s x[u] +
  W_d x[v])`` — fixing GAT's static-attention limitation.  Same masked
  segment-softmax machinery as `GATConv`."""
  out_features: int
  heads: int = 1
  concat: bool = True
  negative_slope: float = 0.2
  dtype: Optional[jnp.dtype] = None

  @nn.compact
  def __call__(self, x: jax.Array, edge_index: jax.Array,
               edge_mask: Optional[jax.Array] = None) -> jax.Array:
    if self.dtype is not None:
      x = x.astype(self.dtype)
    n = x.shape[0]
    h, f = self.heads, self.out_features
    src, dst = edge_index[0], edge_index[1]
    valid = edge_mask if edge_mask is not None else (dst >= 0)
    sc = jnp.clip(src, 0, n - 1)
    dc = jnp.clip(dst, 0, n - 1)
    z_src = nn.Dense(h * f, use_bias=False, dtype=self.dtype,
                     name='lin_src')(x).reshape(n, h, f)
    z_dst = nn.Dense(h * f, use_bias=False, dtype=self.dtype,
                     name='lin_dst')(x).reshape(n, h, f)
    att = self.param('att', nn.initializers.glorot_uniform(), (h, f))
    pre = nn.leaky_relu(z_src[sc] + z_dst[dc],
                        self.negative_slope)         # [E, h, f]
    e = (pre * att[None].astype(pre.dtype)).sum(-1).astype(jnp.float32)
    w = segment_softmax(e, dst, n, valid)
    return _attention_aggregate(z_src[sc], w, dst, valid, n, h, f,
                                self.concat)
