from .conv import (GATConv, GATv2Conv, GCNConv, GINConv, SAGEConv,
                   segment_mean, segment_max)
from .basic_gnn import DGCNN, GAT, GCN, GIN, BasicGNN, GraphSAGE
from .tree import TreeSAGE, tree_level_sizes
from .hetero import HGT, HGTConv, HeteroConv, RGCN
from .train import (TrainState, create_train_state, make_eval_step,
                    make_supervised_step, make_unsupervised_step,
                    link_loss_from_metadata, supervised_loss,
                    triplet_link_loss, unsupervised_link_loss)
