"""Tree-layout GraphSAGE: scatter-free message passing on the
sampler's native window structure.

The subgraph path (`models.conv.SAGEConv` on a deduped node table)
matches the reference's PyG consumption model
(`examples/train_sage_ogbn_products.py` via PyG ``SAGEConv``), but its
aggregation is a `segment_sum` — an XLA scatter, measured at ~2/3 of
the whole train step on v5e at products scale (r5 decomposition:
205 ms of a ~440 ms fused step was the model, dominated by
scatter-add over ~938k edge slots, fwd AND bwd).

TPUs want streams, not scatters.  Multi-hop sampling already produces
a STATIC tree: level ``t`` holds ``B * k_1 * ... * k_t`` slots, and
each parent owns a contiguous ``k_{t+1}``-slot window of children.  On
that layout mean-aggregation is a reshape + masked mean — pure VPU
streaming — and the backward is a broadcast.  No scatter exists
anywhere in the program (the only gathers are the per-level feature
lookups).

Estimator note: the tree does NOT dedup repeated nodes.  A node drawn
twice gets two independently-sampled expansions (the original
GraphSAGE formulation); the deduped subgraph path expands each unique
node once and re-drawn nodes alias one expansion (the reference's
estimator, `csrc/cpu/inducer.cc`).  Both are unbiased neighborhood
estimators; padded compute volume is IDENTICAL (level sizes equal the
subgraph path's per-hop capacity blocks), so the tree layout is a
strict compute-shape win on TPU.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def tree_level_sizes(batch_size: int, fanouts: Sequence[int]
                     ) -> Tuple[int, ...]:
  """Slot count per tree level: ``[B, B*k1, B*k1*k2, ...]``."""
  sizes = [batch_size]
  for k in fanouts:
    sizes.append(sizes[-1] * int(k))
  return tuple(sizes)


class TreeSAGE(nn.Module):
  """GraphSAGE (mean aggregator) over tree-layout level tensors.

  ``__call__(xs, masks)`` where ``xs[t]`` is the ``[F_t, D]`` feature
  tensor of level ``t`` (``F_t = B * k_1 * ... * k_t``) and
  ``masks[t]`` its ``[F_t]`` validity — the output is the seed level's
  ``[B, out_features]`` logits.  Layer ``l`` applies ONE weight pair
  (self + neighbor) across all levels that still matter, exactly like
  the subgraph ``SAGEConv`` stack shares weights across the node
  table.

  ``len(xs)`` must be ``num_layers + 1``.
  """
  hidden_features: int
  out_features: int
  num_layers: int = 2
  dtype: Optional[jnp.dtype] = None   # compute dtype (bf16 → MXU);
                                      # params stay f32

  @nn.compact
  def __call__(self, xs: Sequence[jax.Array],
               masks: Sequence[jax.Array]) -> jax.Array:
    if len(xs) != self.num_layers + 1:
      raise ValueError(
          f'TreeSAGE(num_layers={self.num_layers}) needs '
          f'{self.num_layers + 1} levels, got {len(xs)}')
    hs = [x.astype(self.dtype) if self.dtype is not None else x
          for x in xs]
    # zero out invalid slots once: they then contribute nothing as
    # self terms of masked-out rows or as masked children
    hs = [h * m[:, None].astype(h.dtype) for h, m in zip(hs, masks)]
    for layer in range(self.num_layers):
      out = (self.hidden_features if layer < self.num_layers - 1
             else self.out_features)
      lin_self = nn.Dense(out, dtype=self.dtype,
                          name=f'layer{layer}_self')
      lin_neigh = nn.Dense(out, use_bias=False, dtype=self.dtype,
                           name=f'layer{layer}_neigh')
      new_hs = []
      for t in range(self.num_layers - layer):
        parent, child = hs[t], hs[t + 1]
        k = child.shape[0] // parent.shape[0]
        cm = masks[t + 1].reshape(parent.shape[0], k)
        cd = child.reshape(parent.shape[0], k, child.shape[1])
        # masked mean over the static child window — the whole
        # aggregation.  The mask must gate the SUM too: past layer 0
        # an invalid slot's activation is relu(bias) != 0 (the input
        # zeroing above only cleans the leaves), and an unmasked sum
        # would leak it into every window with degree < fanout.
        cnt = jnp.maximum(cm.sum(axis=1, dtype=jnp.float32), 1.0)
        mean = ((cd * cm[..., None].astype(cd.dtype)).sum(axis=1)
                / cnt[:, None].astype(cd.dtype))
        h = lin_self(parent) + lin_neigh(mean)
        if layer < self.num_layers - 1:
          h = nn.relu(h)
        new_hs.append(h)
      hs = new_hs
    return hs[0].astype(jnp.float32)
