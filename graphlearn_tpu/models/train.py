"""Supervised / unsupervised training steps on Batch pytrees.

The reference leaves the training loop to user code + DDP
(`examples/train_sage_ogbn_products.py:90-130`); here the loop is a
jitted optax step.  Loss is computed on the **seed slots** only (table
positions ``[0, batch_size)``), masked by seed validity — padded seeds
contribute zero, so the tail batch trains correctly with one compiled
program.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class TrainState(NamedTuple):
  params: Any
  opt_state: Any
  step: jax.Array


def create_train_state(model, rng, sample_batch, tx: optax.GradientTransformation
                       ) -> Tuple[TrainState, Callable]:
  """Init params from a sample batch; returns (state, apply_fn)."""
  params = model.init(rng, sample_batch.x, sample_batch.edge_index,
                      sample_batch.edge_mask)
  return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32)), \
      model.apply


def supervised_loss(logits: jax.Array, y: jax.Array, batch_seeds: jax.Array,
                    batch_size: int) -> jax.Array:
  """Masked softmax CE over seed slots [0, batch_size)."""
  seed_logits = logits[:batch_size]
  seed_y = y[:batch_size]
  valid = (batch_seeds >= 0).astype(seed_logits.dtype)
  ce = optax.softmax_cross_entropy_with_integer_labels(
      seed_logits, seed_y.astype(jnp.int32))
  return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)


def make_extracted_supervised_step(extract: Callable,
                                   tx: optax.GradientTransformation,
                                   batch_size: int):
  """Build ``(state, batch) -> (state, loss, correct)`` from an
  ``extract(params, batch) -> (logits, y, seeds)`` adapter — ONE
  update body (masked seed-slot CE, optax update, masked correct
  count) shared by the homogeneous and hetero step builders and the
  fused epoch runners."""

  def step(state: TrainState, batch):
    def loss_fn(params):
      logits, y, seeds = extract(params, batch)
      loss = supervised_loss(logits, y, seeds, batch_size)
      return loss, (logits, y, seeds)

    (loss, (logits, y, seeds)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    valid = seeds >= 0
    pred = jnp.argmax(logits[:batch_size], axis=-1)
    correct = jnp.sum((pred == y[:batch_size]) & valid)
    return TrainState(params, opt_state, state.step + 1), loss, correct

  return step


def _apply_with_weights(apply_fn, params, batch):
  """One definition of "apply the model to a Batch": when the sampler
  attached GNS 1/q importance weights (``metadata['edge_weight']``,
  PR 10), thread them into the aggregation so biased sampling stays
  unbiased at the model (the presence check is static per pytree
  structure — no retrace churn)."""
  md = getattr(batch, 'metadata', None) or {}
  ew = md.get('edge_weight') if isinstance(md, dict) else None
  if ew is not None:
    return apply_fn(params, batch.x, batch.edge_index, batch.edge_mask,
                    edge_weight=ew)
  return apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)


def make_supervised_step(apply_fn, tx: optax.GradientTransformation,
                         batch_size: int):
  """Build a jitted ``(state, batch) -> (state, loss, correct)`` step."""

  def extract(params, batch):
    logits = _apply_with_weights(apply_fn, params, batch)
    return logits, batch.y, batch.batch

  return jax.jit(make_extracted_supervised_step(extract, tx, batch_size))


def make_extracted_eval_step(extract: Callable, batch_size: int):
  """``(params, batch) -> (correct, total)`` from the same extract
  adapter `make_extracted_supervised_step` takes — ONE definition of
  the masked seed-slot accuracy."""

  def step(params, batch):
    logits, y, seeds = extract(params, batch)
    valid = seeds >= 0
    pred = jnp.argmax(logits[:batch_size], axis=-1)
    correct = jnp.sum((pred == y[:batch_size]) & valid)
    return correct, jnp.sum(valid)

  return step


def make_eval_step(apply_fn, batch_size: int):

  def extract(params, batch):
    logits = _apply_with_weights(apply_fn, params, batch)
    return logits, batch.y, batch.batch

  return jax.jit(make_extracted_eval_step(extract, batch_size))


def unsupervised_link_loss(emb: jax.Array, metadata: dict) -> jax.Array:
  """Binary link-prediction loss from sampler metadata
  (``edge_label_index`` / ``edge_label`` / ``edge_label_mask``), the
  objective of the reference's unsupervised SAGE example
  (`examples/graph_sage_unsup_ppi.py:41-45`)."""
  eli = metadata['edge_label_index']
  label = metadata['edge_label'].astype(emb.dtype)
  mask = metadata.get('edge_label_mask')
  n = emb.shape[0]
  src = emb[jnp.clip(eli[0], 0, n - 1)]
  dst = emb[jnp.clip(eli[1], 0, n - 1)]
  logit = jnp.sum(src * dst, axis=-1)
  ls = optax.sigmoid_binary_cross_entropy(logit, jnp.minimum(label, 1.0))
  if mask is not None:
    valid = mask & (eli[0] >= 0) & (eli[1] >= 0)
  else:
    valid = (eli[0] >= 0) & (eli[1] >= 0)
  v = valid.astype(emb.dtype)
  return (ls * v).sum() / jnp.maximum(v.sum(), 1.0)


def triplet_link_loss(emb: jax.Array, metadata: dict,
                      margin: float = 1.0) -> jax.Array:
  """Max-margin triplet loss from sampler metadata (``src_index`` /
  ``dst_pos_index`` / ``dst_neg_index`` with -1 invalid slots) — the
  triplet-mode counterpart of :func:`unsupervised_link_loss`."""
  si = metadata['src_index']
  dp = metadata['dst_pos_index']
  dn = metadata['dst_neg_index']
  n = emb.shape[0]
  es = emb[jnp.clip(si, 0, n - 1)]
  ep = emb[jnp.clip(dp, 0, n - 1)]
  en = emb[jnp.clip(dn, 0, n - 1)]                  # [B, A, D]
  pos = jnp.sum(es * ep, axis=-1)                   # [B]
  neg = jnp.sum(es[:, None, :] * en, axis=-1)       # [B, A]
  ls = jnp.maximum(0.0, margin - pos[:, None] + neg)
  valid = ((si >= 0) & (dp >= 0))[:, None] & (dn >= 0)
  v = valid.astype(emb.dtype)
  return (ls * v).sum() / jnp.maximum(v.sum(), 1.0)


def link_loss_from_metadata(emb: jax.Array, metadata: dict) -> jax.Array:
  """Dispatch binary vs triplet link loss by the (static) metadata
  keys a link batch carries."""
  if 'edge_label_index' in metadata:
    return unsupervised_link_loss(emb, metadata)
  if 'src_index' in metadata:
    return triplet_link_loss(emb, metadata)
  raise KeyError('batch metadata carries neither edge_label_index '
                 '(binary) nor src_index (triplet) link labels')


def make_unsupervised_step(apply_fn, tx: optax.GradientTransformation):
  """Build a jitted link-loss step.  The loss dispatches binary vs
  triplet by the batch's (static) metadata keys
  (`link_loss_from_metadata`), so one builder serves both the
  per-batch loaders and `loader.fused.FusedLinkEpoch`."""

  @jax.jit
  def step(state: TrainState, batch):
    def loss_fn(params):
      emb = apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)
      return link_loss_from_metadata(emb, batch.metadata)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss

  return step
