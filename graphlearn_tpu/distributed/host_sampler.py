"""Host (CPU) multi-hop neighbor sampling -> flat SampleMessage.

The engine that runs inside sampling subprocesses — the role the
reference's `DistNeighborSampler._sample_from_nodes` + `_colloate_fn`
play in its sampling workers (`distributed/dist_neighbor_sampler.py:
255-324,600-673`), built on the native CPU ops instead of CUDA.
Feature/label collation happens here, in the producer, so the trainer
process only deserializes and `device_put`s.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from .. import native
from ..channel.base import SampleMessage
from ..typing import as_str, reverse_edge_type
from .host_dataset import HostDataset, HostHeteroDataset


def shard_out_edges(ds, nodes: np.ndarray, with_edge: bool):
  """ALL out-edges of ``nodes`` on a host CSR in one vectorized pass (a
  per-node loop would dominate the producer hot path at SEAL closure
  sizes): returns ``(src_pos, nbrs, eids | None)``, ``src_pos``
  indexing into ``nodes``."""
  starts = ds.indptr[nodes]
  degs = ds.indptr[nodes + 1] - starts
  total = int(degs.sum())
  # flat positions of every node's out-edges in `indices`
  off = np.repeat(np.cumsum(degs) - degs, degs)
  flat = (np.arange(total) - off
          + np.repeat(starts, degs)) if total else np.empty(0, np.int64)
  src_pos = np.repeat(np.arange(len(nodes), dtype=np.int64), degs)
  eids = (ds.edge_ids[flat] if (with_edge and ds.edge_ids is not None)
          else None)
  return src_pos, ds.indices[flat], eids


def sorted_cols(indptr: np.ndarray, indices: np.ndarray) -> np.ndarray:
  """Within-row-sorted column view of an (unsorted) CSR, enabling
  vectorized membership tests."""
  rows = np.repeat(np.arange(len(indptr) - 1), np.diff(indptr))
  order = np.lexsort((indices, rows))
  return indices[order]


def edges_exist(indptr: np.ndarray, sindices: np.ndarray,
                rows: np.ndarray, cols: np.ndarray) -> np.ndarray:
  """Vectorized (row, col) membership via per-row binary search on the
  sorted view — one pass, no per-source Python loops."""
  e = len(sindices)
  if e == 0:
    return np.zeros(len(rows), bool)
  lo = indptr[rows].copy()
  hi0 = indptr[rows + 1]
  hi = hi0.copy()
  for _ in range(max(int(e), 1).bit_length()):
    active = lo < hi
    mid = (lo + hi) // 2
    v = sindices[np.clip(mid, 0, max(e - 1, 0))]
    go = v < cols
    lo = np.where(active & go, mid + 1, lo)
    hi = np.where(active & ~go, mid, hi)
  at = np.clip(lo, 0, e - 1)
  return (lo < hi0) & (sindices[at] == cols)


def strict_negative_pairs(indptr, sindices, num_src: int, num_dst: int,
                          count: int, seed: int, trials: int = 5):
  """``count`` (row, col) pairs avoiding existing edges — the
  reference's strict+padding negative sampler
  (`random_negative_sampler.cu:96-120`) as trials-stacked draws with
  batched rejection; slots where every trial collides keep the last
  draw (non-strict padding).  Bipartite-aware: rows from ``num_src``,
  cols from ``num_dst``.  Returns ``(rows, cols, ok)`` — ``ok`` False
  marks exhausted-trials slots whose pair may be a REAL edge; callers
  must mask those out of the negative label set (mirroring the mesh
  engine's ``neg_ok`` contract in `parallel.dist_sampler.
  dist_sample_negative`)."""
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, num_src, (trials, count))
  cols = rng.integers(0, num_dst, (trials, count))
  exists = edges_exist(indptr, sindices, rows.reshape(-1),
                       cols.reshape(-1)).reshape(trials, count)
  ok = ~exists
  any_ok = ok.any(axis=0)
  pick = np.where(any_ok, np.argmax(ok, axis=0), trials - 1)
  ar = np.arange(count)
  return rows[pick, ar], cols[pick, ar], any_ok


def strict_negative_dsts(indptr, sindices, src: np.ndarray, num_dst: int,
                         amount: int, seed: int, trials: int = 5):
  """Per-source strict negative destinations ``[len(src), amount]``
  (triplet mode), vectorized like :func:`strict_negative_pairs`.
  Returns ``(dsts, ok)`` — ``ok[i, j]`` False marks exhausted-trials
  slots (possible real edges) the caller must invalidate."""
  rng = np.random.default_rng(seed)
  m = len(src) * amount
  cand = rng.integers(0, num_dst, (trials, m))
  srcr = np.tile(np.repeat(src, amount), (trials, 1))
  exists = edges_exist(indptr, sindices, srcr.reshape(-1),
                       cand.reshape(-1)).reshape(trials, m)
  ok = ~exists
  any_ok = ok.any(axis=0)
  pick = np.where(any_ok, np.argmax(ok, axis=0), trials - 1)
  return (cand[pick, np.arange(m)].reshape(len(src), amount),
          any_ok.reshape(len(src), amount))


class HostNeighborSampler:
  """Multi-hop uniform sampler over a `HostDataset`.

  Args:
    dataset: host CSR + features.
    num_neighbors: per-hop fanouts.
    with_edge: emit global edge ids.
    collect_features: gather ``nfeats``/``nlabels`` rows into messages.
    seed: base PRNG seed (per-batch streams derive from it).
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0):
    if getattr(dataset, 'node_pb', None) is not None and \
        type(self) is HostNeighborSampler:
      raise ValueError(
          'HostDataset is a partition shard (node_pb is set): a '
          'local-only sampler would silently under-sample remote '
          'neighborhoods and zero-fill remote features.  Use '
          'HostDistNeighborSampler (graphlearn_tpu.distributed.'
          'host_dist_sampler) with peer partition services, the mesh '
          'engine (graphlearn_tpu.parallel), or load the FULL graph '
          'via HostDataset.from_dataset.')
    self.ds = dataset
    self.fanouts = [int(k) for k in num_neighbors]
    self.with_edge = with_edge
    self.collect_features = collect_features
    self._seed = int(seed)
    self._batch_idx = 0

  def _next_batch_seed(self, batch_seed: Optional[int]) -> int:
    if batch_seed is None:
      batch_seed = self._seed + self._batch_idx
      self._batch_idx += 1
    return batch_seed

  # -- overridable data-access hooks (the partition-aware subclass in
  # `host_dist_sampler.py` reroutes these through peer RPC fan-out) ------
  def _begin_batch(self) -> None:
    """Per-batch reset hook (dist subclass clears its eid caches)."""

  def _one_hop(self, frontier: np.ndarray, k: int, hop_seed: int):
    """Sample ``k`` neighbors of each frontier id; returns
    ``(nbrs [n,k], mask [n,k], eids [n,k] | None)``."""
    return native.sample_one_hop(
        self.ds.indptr, self.ds.indices, frontier, k, seed=hop_seed,
        edge_ids=self.ds.edge_ids, with_edge_ids=self.with_edge)

  def _gather_node_features(self, ids: np.ndarray) -> np.ndarray:
    return self.ds.node_features[ids]

  def _gather_node_labels(self, ids: np.ndarray) -> np.ndarray:
    return self.ds.node_labels[ids]

  def _gather_edge_features(self, eids: np.ndarray) -> np.ndarray:
    return self.ds.edge_features[eids]

  def _closure_out_edges(self, nodes: np.ndarray):
    """ALL out-edges of ``nodes`` (the induced-subgraph scan source);
    see :func:`shard_out_edges`."""
    return shard_out_edges(self.ds, nodes, self.with_edge)

  @property
  def _has_node_features(self) -> bool:
    return self.ds.node_features is not None

  @property
  def _has_node_labels(self) -> bool:
    return self.ds.node_labels is not None

  @property
  def _has_edge_features(self) -> bool:
    return self.ds.edge_features is not None

  def _expand(self, seeds: np.ndarray, batch_seed: int):
    """Multi-hop expansion shared by node/link/subgraph modes; returns
    ``(inducer, seed_local, rows, cols, eids, num_sampled)``."""
    self._begin_batch()
    ind = native.CpuInducer(capacity_hint=max(len(seeds) * 4, 64))
    seed_local = ind.init_nodes(seeds)
    frontier = ind.all_nodes()
    rows_acc, cols_acc, eids_acc = [], [], []
    num_sampled = [ind.num_nodes]
    for h, k in enumerate(self.fanouts):
      nbrs, mask, eids = self._one_hop(frontier, k,
                                       batch_seed * 1000003 + h)
      before = ind.num_nodes
      new_nodes, rl, cl = ind.induce_next(frontier, nbrs, mask)
      keep = rl.reshape(-1) >= 0
      rows_acc.append(rl.reshape(-1)[keep])
      cols_acc.append(cl.reshape(-1)[keep])
      if self.with_edge:
        eids_acc.append(eids.reshape(-1)[keep])
      num_sampled.append(ind.num_nodes - before)
      frontier = new_nodes
      if len(frontier) == 0:
        break
    rows = (np.concatenate(rows_acc) if rows_acc else np.empty(0, np.int32))
    cols = (np.concatenate(cols_acc) if cols_acc else np.empty(0, np.int32))
    eids = (np.concatenate(eids_acc) if (self.with_edge and eids_acc)
            else None)
    return ind, seed_local, rows, cols, eids, num_sampled

  def _finish(self, seeds, ind, seed_local, rows, cols, eids,
              num_sampled) -> SampleMessage:
    nodes = ind.all_nodes()
    msg: SampleMessage = {
        '#IS_HETERO': np.uint8(0),
        'ids': nodes,
        'rows': rows,
        'cols': cols,
        'batch': np.ascontiguousarray(seeds, np.int64),
        'seed_local': seed_local,
        'num_sampled_nodes': np.asarray(num_sampled, np.int32),
    }
    if eids is not None:
      msg['eids'] = eids
      if self.collect_features and self._has_edge_features:
        # per-edge feature rows by global eid — the reference's efeats
        # collation (`dist_neighbor_sampler.py:600-673`)
        msg['efeats'] = np.ascontiguousarray(
            self._gather_edge_features(eids))
    if self.collect_features and self._has_node_features:
      msg['nfeats'] = np.ascontiguousarray(
          self._gather_node_features(nodes))
    if self._has_node_labels:
      msg['nlabels'] = np.ascontiguousarray(
          self._gather_node_labels(nodes))
    return msg

  def sample_from_nodes(self, seeds: np.ndarray,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """One ragged mini-batch message for ``seeds``."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    batch_seed = self._next_batch_seed(batch_seed)
    out = self._expand(seeds, batch_seed)
    return self._finish(seeds, *out)

  # -- link mode (reference `DistNeighborSampler._sample_from_edges`,
  # `dist_neighbor_sampler.py:327-453`) -----------------------------------
  def sample_from_edges(self, src: np.ndarray, dst: np.ndarray,
                        label: Optional[np.ndarray] = None,
                        neg_mode: Optional[str] = None,
                        neg_amount: float = 1.0,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """Link-prediction message: endpoints + negatives expanded, with
    PyG link-label metadata under ``#META.*`` keys."""
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    b = len(src)
    batch_seed = self._next_batch_seed(batch_seed)
    neg_ok = None
    if neg_mode == 'binary':
      from .dist_options import binary_num_negatives
      num_neg = binary_num_negatives(b, neg_amount)
      # padding=False: the native sampler returns only strict-verified
      # pairs; self-pad the exhausted slots with masked dummies so the
      # batch keeps its static width while possible real edges stay
      # out of the negative label set (the mesh engine's neg_ok
      # contract)
      srows, scols = native.negative_sample(
          self.ds.indptr, self.ds.indices, num_neg, strict=True,
          padding=False, seed=batch_seed * 31 + 7)
      cnt = len(srows)
      nrows = np.zeros(num_neg, np.int64)
      ncols = np.zeros(num_neg, np.int64)
      nrows[:cnt] = srows
      ncols[:cnt] = scols
      neg_ok = np.arange(num_neg) < cnt
      seeds = np.concatenate([src, dst, nrows, ncols])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      neg_dst, neg_ok = self._triplet_neg(src, amount, batch_seed)
      seeds = np.concatenate([src, dst, neg_dst.reshape(-1)])
    else:
      seeds = np.concatenate([src, dst])
    msg = self._finish(seeds, *self._expand(seeds, batch_seed))
    sl = msg['seed_local']
    pos_label = (np.ascontiguousarray(label, np.int64)
                 if label is not None else np.ones(b, np.int64))
    if neg_mode == 'binary':
      msg['#META.edge_label_index'] = np.stack([
          np.concatenate([sl[:b], sl[2 * b:2 * b + num_neg]]),
          np.concatenate([sl[b:2 * b], sl[2 * b + num_neg:]]),
      ]).astype(np.int64)
      msg['#META.edge_label'] = np.concatenate(
          [pos_label, np.zeros(num_neg, np.int64)])
      msg['#META.edge_label_mask'] = np.concatenate(
          [np.ones(b, bool), neg_ok])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      msg['#META.src_index'] = sl[:b]
      msg['#META.dst_pos_index'] = sl[b:2 * b]
      msg['#META.dst_neg_index'] = np.where(
          neg_ok, sl[2 * b:].reshape(b, amount), -1)
    else:
      msg['#META.edge_label_index'] = np.stack(
          [sl[:b], sl[b:2 * b]]).astype(np.int64)
      msg['#META.edge_label'] = pos_label
    return msg

  def _sorted_csr(self):
    """Lazily cached within-row-sorted column view (the native CSR is
    unsorted)."""
    if not hasattr(self, '_sorted_indices'):
      self._sorted_indices = sorted_cols(self.ds.indptr, self.ds.indices)
    return self._sorted_indices

  def _triplet_neg(self, src: np.ndarray, amount: int, batch_seed: int,
                   trials: int = 5) -> Tuple[np.ndarray, np.ndarray]:
    return strict_negative_dsts(self.ds.indptr, self._sorted_csr(), src,
                                self.ds.num_nodes, amount, batch_seed,
                                trials)

  # -- subgraph mode (reference `DistNeighborSampler._subgraph`,
  # `dist_neighbor_sampler.py:456-516`) -----------------------------------
  def sample_subgraph(self, seeds: np.ndarray,
                      batch_seed: Optional[int] = None) -> SampleMessage:
    """Multi-hop closure, then ALL edges among the collected nodes
    (relabeled local COO) — the SEAL enclosing-subgraph message."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    batch_seed = self._next_batch_seed(batch_seed)
    ind, seed_local, _r, _c, _e, num_sampled = self._expand(
        seeds, batch_seed)
    nodes = ind.all_nodes()
    # membership + relabel over the closure set, one vectorized pass
    order = np.argsort(nodes)
    snodes = nodes[order]
    src_l, nb, flat_eids = self._closure_out_edges(nodes)
    pos = np.clip(np.searchsorted(snodes, nb), 0, max(len(snodes) - 1, 0))
    keep = (snodes[pos] == nb) if len(snodes) else np.zeros(0, bool)
    rows = src_l[keep]
    cols = order[pos[keep]]
    eids = flat_eids[keep] if flat_eids is not None else None
    msg = self._finish(seeds, ind, seed_local, rows, cols, eids,
                       num_sampled)
    msg['#META.mapping'] = seed_local
    return msg


class HostHeteroNeighborSampler:
  """Heterogeneous multi-hop sampler over a `HostHeteroDataset`.

  The host-runtime twin of the device hetero engine
  (`graphlearn_tpu/sampler/hetero_neighbor_sampler.py`) and the role
  the reference's hetero `DistNeighborSampler` path plays inside
  sampling workers (`distributed/dist_neighbor_sampler.py:192-253` +
  hetero `_colloate_fn` keys `f'{type}.x'`, `:600-673`).  Semantics
  match the device engine: per-node-type dedup tables, per-edge-type
  per-hop fanouts, edges emitted under the REVERSED edge type with
  transposed (neighbor -> seed) direction.

  Message layout (flat, shm-serializable): ``'#IS_HETERO'=1``;
  per node type ``'{nt}.ids' / '{nt}.nfeats' / '{nt}.nlabels' /
  '{nt}.num_sampled' / '{nt}.seed_local'`` (seeded types only); per
  emitted reversed edge type ``'{as_str(et)}.rows' / '.cols' /
  '.eids'``; plus ``'batch'`` and link-label ``'#META.*'`` keys.
  """

  def __init__(self, dataset: HostHeteroDataset, num_neighbors,
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0):
    if getattr(dataset, 'node_pb', None) is not None:
      raise ValueError(
          'HostHeteroDataset is a partition shard (node_pb is set): a '
          'local-only sampler would silently under-sample remote '
          'neighborhoods.  Use the mesh engine '
          '(graphlearn_tpu.parallel.DistHeteroNeighborSampler) or load '
          'the FULL graph via HostHeteroDataset.from_dataset.')
    from ..sampler.hetero_neighbor_sampler import normalize_fanouts
    self.ds = dataset
    self.etypes, self.fanouts, self.num_hops = normalize_fanouts(
        dataset.edge_types, num_neighbors)
    self.with_edge = with_edge
    self.collect_features = collect_features
    self._seed = int(seed)
    self._batch_idx = 0
    self._sorted = {}        # etype -> within-row-sorted column view

  def _next_batch_seed(self, batch_seed: Optional[int]) -> int:
    if batch_seed is None:
      batch_seed = self._seed + self._batch_idx
      self._batch_idx += 1
    return batch_seed

  def _sorted_for(self, etype):
    if etype not in self._sorted:
      indptr, indices, _ = self.ds.csr[etype]
      self._sorted[etype] = sorted_cols(indptr, indices)
    return self._sorted[etype]

  def _expand(self, seeds_by_type, batch_seed: int):
    """Per-type multi-hop expansion; returns
    ``(states, seed_locals, rows/cols/eids per etype, num_sampled)``."""
    ntypes = self.ds.node_types
    states = {nt: native.CpuInducer(
        capacity_hint=max(sum(len(v) for v in seeds_by_type.values()) * 4,
                          64)) for nt in ntypes}
    seed_locals = {}
    frontier = {}
    for nt, g in seeds_by_type.items():
      seed_locals[nt] = states[nt].init_nodes(g)
      n = states[nt].num_nodes
      frontier[nt] = (states[nt].all_nodes(),
                      np.arange(n, dtype=np.int32))
    num_sampled = {nt: [states[nt].num_nodes] for nt in ntypes}
    rows_acc = {et: [] for et in self.etypes}
    cols_acc = {et: [] for et in self.etypes}
    eids_acc = {et: [] for et in self.etypes}
    for h in range(self.num_hops):
      start = {nt: states[nt].num_nodes for nt in ntypes}
      for ei, et in enumerate(self.etypes):
        s, _, d = et
        fan = self.fanouts[et]
        k = fan[h] if h < len(fan) else 0
        fr = frontier.get(s)
        if k <= 0 or fr is None or len(fr[0]) == 0:
          continue
        indptr, indices, edge_ids = self.ds.csr[et]
        nbrs, mask, eids = native.sample_one_hop(
            indptr, indices, fr[0], int(k),
            seed=(batch_seed * 1000003 + h) * 131 + ei,
            edge_ids=edge_ids, with_edge_ids=self.with_edge)
        _, rl, cl = states[d].induce_from(fr[1], nbrs, mask)
        keep = rl.reshape(-1) >= 0
        rows_acc[et].append(rl.reshape(-1)[keep])
        cols_acc[et].append(cl.reshape(-1)[keep])
        if self.with_edge:
          eids_acc[et].append(eids.reshape(-1)[keep])
      # hop-h frontier of each type = nodes first discovered this hop,
      # deduplicated across ALL edge types by the shared table
      frontier = {}
      for nt in ntypes:
        end = states[nt].num_nodes
        num_sampled[nt].append(end - start[nt])
        if end > start[nt]:
          frontier[nt] = (states[nt].nodes_since(start[nt]),
                          np.arange(start[nt], end, dtype=np.int32))
    return states, seed_locals, rows_acc, cols_acc, eids_acc, num_sampled

  def _finish(self, states, seed_locals, rows_acc, cols_acc, eids_acc,
              num_sampled) -> SampleMessage:
    msg: SampleMessage = {'#IS_HETERO': np.uint8(1)}
    for nt in self.ds.node_types:
      ids = states[nt].all_nodes()
      msg[f'{nt}.ids'] = ids
      msg[f'{nt}.num_sampled'] = np.asarray(num_sampled[nt], np.int32)
      if nt in seed_locals:
        msg[f'{nt}.seed_local'] = seed_locals[nt]
      if self.collect_features and nt in self.ds.node_features:
        msg[f'{nt}.nfeats'] = np.ascontiguousarray(
            self.ds.node_features[nt][ids])
      if nt in self.ds.node_labels:
        msg[f'{nt}.nlabels'] = np.ascontiguousarray(
            self.ds.node_labels[nt][ids])
    for et in self.etypes:
      if not rows_acc[et]:
        continue
      key = as_str(reverse_edge_type(et))
      msg[f'{key}.rows'] = np.concatenate(rows_acc[et])
      msg[f'{key}.cols'] = np.concatenate(cols_acc[et])
      if self.with_edge and eids_acc[et]:
        eids = np.concatenate(eids_acc[et])
        msg[f'{key}.eids'] = eids
        if (self.collect_features
            and tuple(et) in self.ds.edge_features):
          msg[f'{key}.efeats'] = np.ascontiguousarray(
              self.ds.edge_features[tuple(et)][eids])
    return msg

  def sample_from_nodes(self, input_type: str, seeds: np.ndarray,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """One ragged hetero mini-batch message for ``input_type`` seeds."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    batch_seed = self._next_batch_seed(batch_seed)
    msg = self._finish(*self._expand({input_type: seeds}, batch_seed))
    msg['batch'] = seeds
    return msg

  def sample_from_edges(self, input_type, src: np.ndarray,
                        dst: np.ndarray,
                        label: Optional[np.ndarray] = None,
                        neg_mode: Optional[str] = None,
                        neg_amount: float = 1.0,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """Hetero link-prediction message: ``input_type`` is the seed edge
    type; endpoints + negatives expand from their own node types."""
    s, _, d = tuple(input_type)
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    b = len(src)
    batch_seed = self._next_batch_seed(batch_seed)
    neg_ok = None
    if neg_mode == 'binary':
      from .dist_options import binary_num_negatives
      indptr, _, _ = self.ds.csr[tuple(input_type)]
      sind = self._sorted_for(tuple(input_type))
      num_neg = binary_num_negatives(b, neg_amount)
      nrows, ncols, neg_ok = strict_negative_pairs(
          indptr, sind, self.ds.num_nodes[s], self.ds.num_nodes[d],
          num_neg, seed=batch_seed * 31 + 7)
      src_seeds = np.concatenate([src, nrows])
      dst_seeds = np.concatenate([dst, ncols])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      indptr, _, _ = self.ds.csr[tuple(input_type)]
      sind = self._sorted_for(tuple(input_type))
      negs, neg_ok = strict_negative_dsts(indptr, sind, src,
                                          self.ds.num_nodes[d], amount,
                                          seed=batch_seed * 31 + 7)
      src_seeds = src
      dst_seeds = np.concatenate([dst, negs.reshape(-1)])
    else:
      src_seeds, dst_seeds = src, dst
    if s == d:
      seeds_by_type = {s: np.concatenate([src_seeds, dst_seeds])}
    else:
      seeds_by_type = {s: src_seeds, d: dst_seeds}
    out = self._expand(seeds_by_type, batch_seed)
    msg = self._finish(*out)
    seed_locals = out[1]
    if s == d:
      all_local = seed_locals[s]
      sl_s = all_local[:len(src_seeds)]
      sl_d = all_local[len(src_seeds):]
    else:
      sl_s, sl_d = seed_locals[s], seed_locals[d]
    msg['batch'] = src
    pos_label = (np.ascontiguousarray(label, np.int64)
                 if label is not None else np.ones(b, np.int64))
    if neg_mode == 'binary':
      msg['#META.edge_label_index'] = np.stack(
          [sl_s, sl_d]).astype(np.int64)
      msg['#META.edge_label'] = np.concatenate(
          [pos_label, np.zeros(len(sl_s) - b, np.int64)])
      msg['#META.edge_label_mask'] = np.concatenate(
          [np.ones(b, bool), neg_ok])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      msg['#META.src_index'] = sl_s[:b]
      msg['#META.dst_pos_index'] = sl_d[:b]
      msg['#META.dst_neg_index'] = np.where(
          neg_ok, sl_d[b:].reshape(b, amount), -1)
    else:
      msg['#META.edge_label_index'] = np.stack(
          [sl_s, sl_d]).astype(np.int64)
      msg['#META.edge_label'] = pos_label
    return msg
