"""Host (CPU) multi-hop neighbor sampling -> flat SampleMessage.

The engine that runs inside sampling subprocesses — the role the
reference's `DistNeighborSampler._sample_from_nodes` + `_colloate_fn`
play in its sampling workers (`distributed/dist_neighbor_sampler.py:
255-324,600-673`), built on the native CPU ops instead of CUDA.
Feature/label collation happens here, in the producer, so the trainer
process only deserializes and `device_put`s.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .. import native
from ..channel.base import SampleMessage
from .host_dataset import HostDataset


class HostNeighborSampler:
  """Multi-hop uniform sampler over a `HostDataset`.

  Args:
    dataset: host CSR + features.
    num_neighbors: per-hop fanouts.
    with_edge: emit global edge ids.
    collect_features: gather ``nfeats``/``nlabels`` rows into messages.
    seed: base PRNG seed (per-batch streams derive from it).
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0):
    self.ds = dataset
    self.fanouts = [int(k) for k in num_neighbors]
    self.with_edge = with_edge
    self.collect_features = collect_features
    self._seed = int(seed)
    self._batch_idx = 0

  def _next_batch_seed(self, batch_seed: Optional[int]) -> int:
    if batch_seed is None:
      batch_seed = self._seed + self._batch_idx
      self._batch_idx += 1
    return batch_seed

  def _expand(self, seeds: np.ndarray, batch_seed: int):
    """Multi-hop expansion shared by node/link/subgraph modes; returns
    ``(inducer, seed_local, rows, cols, eids, num_sampled)``."""
    ind = native.CpuInducer(capacity_hint=max(len(seeds) * 4, 64))
    seed_local = ind.init_nodes(seeds)
    frontier = ind.all_nodes()
    rows_acc, cols_acc, eids_acc = [], [], []
    num_sampled = [ind.num_nodes]
    for h, k in enumerate(self.fanouts):
      nbrs, mask, eids = native.sample_one_hop(
          self.ds.indptr, self.ds.indices, frontier, k,
          seed=batch_seed * 1000003 + h, edge_ids=self.ds.edge_ids,
          with_edge_ids=self.with_edge)
      before = ind.num_nodes
      new_nodes, rl, cl = ind.induce_next(frontier, nbrs, mask)
      keep = rl.reshape(-1) >= 0
      rows_acc.append(rl.reshape(-1)[keep])
      cols_acc.append(cl.reshape(-1)[keep])
      if self.with_edge:
        eids_acc.append(eids.reshape(-1)[keep])
      num_sampled.append(ind.num_nodes - before)
      frontier = new_nodes
      if len(frontier) == 0:
        break
    rows = (np.concatenate(rows_acc) if rows_acc else np.empty(0, np.int32))
    cols = (np.concatenate(cols_acc) if cols_acc else np.empty(0, np.int32))
    eids = (np.concatenate(eids_acc) if (self.with_edge and eids_acc)
            else None)
    return ind, seed_local, rows, cols, eids, num_sampled

  def _finish(self, seeds, ind, seed_local, rows, cols, eids,
              num_sampled) -> SampleMessage:
    nodes = ind.all_nodes()
    msg: SampleMessage = {
        '#IS_HETERO': np.uint8(0),
        'ids': nodes,
        'rows': rows,
        'cols': cols,
        'batch': np.ascontiguousarray(seeds, np.int64),
        'seed_local': seed_local,
        'num_sampled_nodes': np.asarray(num_sampled, np.int32),
    }
    if eids is not None:
      msg['eids'] = eids
    if self.collect_features and self.ds.node_features is not None:
      msg['nfeats'] = np.ascontiguousarray(self.ds.node_features[nodes])
    if self.ds.node_labels is not None:
      msg['nlabels'] = np.ascontiguousarray(self.ds.node_labels[nodes])
    return msg

  def sample_from_nodes(self, seeds: np.ndarray,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """One ragged mini-batch message for ``seeds``."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    batch_seed = self._next_batch_seed(batch_seed)
    out = self._expand(seeds, batch_seed)
    return self._finish(seeds, *out)

  # -- link mode (reference `DistNeighborSampler._sample_from_edges`,
  # `dist_neighbor_sampler.py:327-453`) -----------------------------------
  def sample_from_edges(self, src: np.ndarray, dst: np.ndarray,
                        label: Optional[np.ndarray] = None,
                        neg_mode: Optional[str] = None,
                        neg_amount: float = 1.0,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """Link-prediction message: endpoints + negatives expanded, with
    PyG link-label metadata under ``#META.*`` keys."""
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    b = len(src)
    batch_seed = self._next_batch_seed(batch_seed)
    if neg_mode == 'binary':
      from .dist_options import binary_num_negatives
      num_neg = binary_num_negatives(b, neg_amount)
      nrows, ncols = native.negative_sample(
          self.ds.indptr, self.ds.indices, num_neg, strict=True,
          padding=True, seed=batch_seed * 31 + 7)
      seeds = np.concatenate([src, dst, nrows, ncols])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      neg_dst = self._triplet_neg(src, amount, batch_seed)
      seeds = np.concatenate([src, dst, neg_dst.reshape(-1)])
    else:
      seeds = np.concatenate([src, dst])
    msg = self._finish(seeds, *self._expand(seeds, batch_seed))
    sl = msg['seed_local']
    pos_label = (np.ascontiguousarray(label, np.int64)
                 if label is not None else np.ones(b, np.int64))
    if neg_mode == 'binary':
      msg['#META.edge_label_index'] = np.stack([
          np.concatenate([sl[:b], sl[2 * b:2 * b + num_neg]]),
          np.concatenate([sl[b:2 * b], sl[2 * b + num_neg:]]),
      ]).astype(np.int64)
      msg['#META.edge_label'] = np.concatenate(
          [pos_label, np.zeros(num_neg, np.int64)])
    elif neg_mode == 'triplet':
      amount = int(np.ceil(neg_amount))
      msg['#META.src_index'] = sl[:b]
      msg['#META.dst_pos_index'] = sl[b:2 * b]
      msg['#META.dst_neg_index'] = sl[2 * b:].reshape(b, amount)
    else:
      msg['#META.edge_label_index'] = np.stack(
          [sl[:b], sl[b:2 * b]]).astype(np.int64)
      msg['#META.edge_label'] = pos_label
    return msg

  def _sorted_csr(self):
    """Lazily cached within-row-sorted column view (the native CSR is
    unsorted) enabling vectorized membership tests."""
    if not hasattr(self, '_sorted_indices'):
      indptr, indices = self.ds.indptr, self.ds.indices
      rows = np.repeat(np.arange(len(indptr) - 1),
                       np.diff(indptr))
      order = np.lexsort((indices, rows))
      self._sorted_indices = indices[order]
    return self._sorted_indices

  def _edge_exists(self, rows: np.ndarray, cols: np.ndarray) -> np.ndarray:
    """Vectorized (row, col) membership via per-row binary search on
    the sorted view — one pass, no per-source Python loops."""
    indptr = self.ds.indptr
    sindices = self._sorted_csr()
    e = len(sindices)
    if e == 0:
      return np.zeros(len(rows), bool)
    lo = indptr[rows].copy()
    hi0 = indptr[rows + 1]
    hi = hi0.copy()
    for _ in range(max(int(e), 1).bit_length()):
      active = lo < hi
      mid = (lo + hi) // 2
      v = sindices[np.clip(mid, 0, max(e - 1, 0))]
      go = v < cols
      lo = np.where(active & go, mid + 1, lo)
      hi = np.where(active & ~go, mid, hi)
    at = np.clip(lo, 0, e - 1)
    return (lo < hi0) & (sindices[at] == cols)

  def _triplet_neg(self, src: np.ndarray, amount: int,
                   batch_seed: int, trials: int = 5) -> np.ndarray:
    """Per-source strict negative destinations, fully vectorized
    (the reference's curand retry loop, `random_negative_sampler.cu:
    56-94`, as trials-stacked draws + batched rejection)."""
    rng = np.random.default_rng(batch_seed)
    n = self.ds.num_nodes
    m = len(src) * amount
    cand = rng.integers(0, n, (trials, m))
    srcr = np.tile(np.repeat(src, amount), (trials, 1))
    exists = self._edge_exists(srcr.reshape(-1),
                               cand.reshape(-1)).reshape(trials, m)
    ok = ~exists
    pick = np.where(ok.any(axis=0), np.argmax(ok, axis=0), trials - 1)
    return cand[pick, np.arange(m)].reshape(len(src), amount)

  # -- subgraph mode (reference `DistNeighborSampler._subgraph`,
  # `dist_neighbor_sampler.py:456-516`) -----------------------------------
  def sample_subgraph(self, seeds: np.ndarray,
                      batch_seed: Optional[int] = None) -> SampleMessage:
    """Multi-hop closure, then ALL edges among the collected nodes
    (relabeled local COO) — the SEAL enclosing-subgraph message."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    batch_seed = self._next_batch_seed(batch_seed)
    ind, seed_local, _r, _c, _e, num_sampled = self._expand(
        seeds, batch_seed)
    nodes = ind.all_nodes()
    # membership + relabel over the closure set: one vectorized pass
    # (a per-node loop here would dominate the producer hot path at
    # SEAL closure sizes)
    order = np.argsort(nodes)
    snodes = nodes[order]
    indptr, indices = self.ds.indptr, self.ds.indices
    starts = indptr[nodes]
    degs = indptr[nodes + 1] - starts
    total = int(degs.sum())
    # flat positions of every closure node's out-edges in `indices`
    off = np.repeat(np.cumsum(degs) - degs, degs)
    flat = (np.arange(total) - off
            + np.repeat(starts, degs)) if total else np.empty(0, np.int64)
    src_l = np.repeat(np.arange(len(nodes), dtype=np.int64), degs)
    nb = indices[flat]
    pos = np.clip(np.searchsorted(snodes, nb), 0, max(len(snodes) - 1, 0))
    keep = (snodes[pos] == nb) if len(snodes) else np.zeros(0, bool)
    rows = src_l[keep]
    cols = order[pos[keep]]
    eids = (self.ds.edge_ids[flat][keep]
            if (self.with_edge and self.ds.edge_ids is not None)
            else None)
    msg = self._finish(seeds, ind, seed_local, rows, cols, eids,
                       num_sampled)
    msg['#META.mapping'] = seed_local
    return msg
