"""Host (CPU) multi-hop neighbor sampling -> flat SampleMessage.

The engine that runs inside sampling subprocesses — the role the
reference's `DistNeighborSampler._sample_from_nodes` + `_colloate_fn`
play in its sampling workers (`distributed/dist_neighbor_sampler.py:
255-324,600-673`), built on the native CPU ops instead of CUDA.
Feature/label collation happens here, in the producer, so the trainer
process only deserializes and `device_put`s.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .. import native
from ..channel.base import SampleMessage
from .host_dataset import HostDataset


class HostNeighborSampler:
  """Multi-hop uniform sampler over a `HostDataset`.

  Args:
    dataset: host CSR + features.
    num_neighbors: per-hop fanouts.
    with_edge: emit global edge ids.
    collect_features: gather ``nfeats``/``nlabels`` rows into messages.
    seed: base PRNG seed (per-batch streams derive from it).
  """

  def __init__(self, dataset: HostDataset, num_neighbors: Sequence[int],
               with_edge: bool = False, collect_features: bool = True,
               seed: int = 0):
    self.ds = dataset
    self.fanouts = [int(k) for k in num_neighbors]
    self.with_edge = with_edge
    self.collect_features = collect_features
    self._seed = int(seed)
    self._batch_idx = 0

  def sample_from_nodes(self, seeds: np.ndarray,
                        batch_seed: Optional[int] = None) -> SampleMessage:
    """One ragged mini-batch message for ``seeds``."""
    seeds = np.ascontiguousarray(seeds, np.int64)
    if batch_seed is None:
      batch_seed = self._seed + self._batch_idx
      self._batch_idx += 1
    ind = native.CpuInducer(capacity_hint=max(len(seeds) * 4, 64))
    seed_local = ind.init_nodes(seeds)
    frontier = ind.all_nodes()
    rows_acc, cols_acc, eids_acc = [], [], []
    num_sampled = [ind.num_nodes]
    for h, k in enumerate(self.fanouts):
      nbrs, mask, eids = native.sample_one_hop(
          self.ds.indptr, self.ds.indices, frontier, k,
          seed=batch_seed * 1000003 + h, edge_ids=self.ds.edge_ids,
          with_edge_ids=self.with_edge)
      before = ind.num_nodes
      new_nodes, rl, cl = ind.induce_next(frontier, nbrs, mask)
      keep = rl.reshape(-1) >= 0
      rows_acc.append(rl.reshape(-1)[keep])
      cols_acc.append(cl.reshape(-1)[keep])
      if self.with_edge:
        eids_acc.append(eids.reshape(-1)[keep])
      num_sampled.append(ind.num_nodes - before)
      frontier = new_nodes
      if len(frontier) == 0:
        break
    nodes = ind.all_nodes()
    msg: SampleMessage = {
        '#IS_HETERO': np.uint8(0),
        'ids': nodes,
        'rows': np.concatenate(rows_acc) if rows_acc else
                np.empty(0, np.int32),
        'cols': np.concatenate(cols_acc) if cols_acc else
                np.empty(0, np.int32),
        'batch': seeds,
        'seed_local': seed_local,
        'num_sampled_nodes': np.asarray(num_sampled, np.int32),
    }
    if self.with_edge:
      msg['eids'] = (np.concatenate(eids_acc) if eids_acc else
                     np.empty(0, np.int64))
    if self.collect_features and self.ds.node_features is not None:
      msg['nfeats'] = np.ascontiguousarray(self.ds.node_features[nodes])
    if self.ds.node_labels is not None:
      msg['nlabels'] = np.ascontiguousarray(self.ds.node_labels[nodes])
    return msg
