"""Socket RPC substrate for the server-client deployment mode.

The reference rides torch.distributed.rpc/TensorPipe (ibv RDMA + uv
TCP, `distributed/rpc.py:236-292`).  A TPU-VM sampling tier has no
torch runtime to lean on, and the *data* plane between hosts is DCN
TCP anyway — so the control plane here is a deliberately small
threaded socket RPC:

  * frames: ``[u32 kind][u64 len][payload]`` — kind 0 = pickled
    control object, kind 1 = tensor-map bytes (`csrc/tensor_map.cc`
    serialization, no pickle on the sample-message path);
  * server: one daemon thread per connection, handlers looked up in a
    registry (the reference's `RpcCalleeBase`/`rpc_register`,
    `rpc.py:364-443`);
  * client: a connection pool so concurrent prefetch threads each own
    a socket.

Trusted-cluster assumption (same as TensorPipe): control frames use
pickle, so only run between your own hosts.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ..native import parse_tensor_map, serialize_tensor_map

_HDR = struct.Struct('<IQ')
KIND_PICKLE = 0
KIND_TENSOR_MAP = 1


def _send_frame(sock: socket.socket, kind: int, payload: bytes) -> None:
  sock.sendall(_HDR.pack(kind, len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
  buf = bytearray()
  while len(buf) < n:
    chunk = sock.recv(min(n - len(buf), 1 << 20))
    if not chunk:
      raise ConnectionError('peer closed')
    buf += chunk
  return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
  kind, ln = _HDR.unpack(_recv_exact(sock, _HDR.size))
  return kind, _recv_exact(sock, ln)


def send_obj(sock: socket.socket, obj: Any) -> None:
  """Send one value; dict-of-ndarray goes through the tensor-map path."""
  if isinstance(obj, RawTensorMap):
    _send_frame(sock, KIND_TENSOR_MAP, bytes(obj))
  elif (isinstance(obj, dict) and obj
      and all(isinstance(k, str) for k in obj)
      and all(isinstance(v, (np.ndarray, np.generic))
              for v in obj.values())):
    _send_frame(sock, KIND_TENSOR_MAP, serialize_tensor_map(obj))
  else:
    _send_frame(sock, KIND_PICKLE, pickle.dumps(obj, protocol=5))


def recv_obj(sock: socket.socket) -> Any:
  kind, payload = _recv_frame(sock)
  if kind == KIND_TENSOR_MAP:
    return parse_tensor_map(payload)
  return pickle.loads(payload)


class RawTensorMap(bytes):
  """Already-serialized tensor-map payload: `send_obj` frames it
  directly (no parse/re-serialize on the server's fetch hot path) and
  the receiving side parses it into the usual dict."""


class RpcError(RuntimeError):
  pass


class _RemoteError:
  def __init__(self, msg: str):
    self.msg = msg


class RpcServer:
  """Threaded request server with a name->handler registry."""

  def __init__(self, host: str = '0.0.0.0', port: int = 0):
    registry: Dict[str, Callable] = {}
    self._registry = registry
    active: set = set()
    closed = [False]
    alock = threading.Lock()
    self._active, self._alock, self._closed = active, alock, closed

    class Handler(socketserver.BaseRequestHandler):
      def handle(self):
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        with alock:
          if closed[0]:
            # accepted just as shutdown() snapshotted the set: self-
            # close instead of serving a "dead" server's connection
            try:
              sock.close()
            except OSError:
              pass
            return
          active.add(sock)
        try:
          while True:
            name, args, kwargs = recv_obj(sock)
            fn = registry.get(name)
            try:
              if fn is None:
                raise RpcError(f'no handler registered for {name!r}')
              result = fn(*args, **kwargs)
            except Exception as exc:  # ship the error to the caller
              send_obj(sock, _RemoteError(f'{type(exc).__name__}: {exc}'))
              continue
            send_obj(sock, result)
        except (ConnectionError, EOFError, OSError):
          return
        finally:
          with alock:
            active.discard(sock)

    class Server(socketserver.ThreadingTCPServer):
      daemon_threads = True
      allow_reuse_address = True

    self._server = Server((host, port), Handler)
    self.host, self.port = self._server.server_address
    self._thread = threading.Thread(target=self._server.serve_forever,
                                    daemon=True)

  def register(self, name: str, fn: Callable) -> None:
    """Reference `rpc_register` (`distributed/rpc.py:401-420`)."""
    self._registry[name] = fn

  def start(self) -> None:
    self._thread.start()

  def shutdown(self) -> None:
    """Stop accepting AND sever live connections: handler threads are
    daemons blocked in recv, so without the severing a "shut down"
    server keeps answering pooled peers indefinitely — callers (and
    failure tests) must see a dead peer as ConnectionError, not as a
    healthy endpoint."""
    self._server.shutdown()
    self._server.server_close()
    with self._alock:
      self._closed[0] = True
      conns = list(self._active)
    for s in conns:
      try:
        s.shutdown(socket.SHUT_RDWR)
      except OSError:
        pass
      try:
        s.close()
      except OSError:
        pass


class RpcClient:
  """Per-thread pooled connections to one server address."""

  def __init__(self, host: str, port: int):
    self.addr = (host, port)
    self._local = threading.local()
    self._all: list = []
    self._lock = threading.Lock()

  def _sock(self) -> socket.socket:
    s = getattr(self._local, 'sock', None)
    if s is None:
      s = socket.create_connection(self.addr, timeout=120)
      s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
      self._local.sock = s
      with self._lock:
        self._all.append(s)
    return s

  def request(self, name: str, *args, **kwargs) -> Any:
    """Synchronous call (reference `request_server`,
    `dist_client.py:79-98`); safe from multiple threads."""
    sock = self._sock()
    send_obj(sock, (name, args, kwargs))
    out = recv_obj(sock)
    if isinstance(out, _RemoteError):
      raise RpcError(out.msg)
    return out

  def close(self) -> None:
    with self._lock:
      for s in self._all:
        try:
          s.close()
        except OSError:
          pass
      self._all.clear()
